"""Benchmark: fused GPT training-step throughput on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = measured MFU / 0.40 (the BASELINE.md north-star MFU target;
the reference publishes no absolute numbers — BASELINE.md).

Robustness contract (VERDICT r1 item 1c): the measurement runs in a child
process; if the ambient backend (e.g. a TPU tunnel) fails to initialize, the
parent retries once, then falls back to a forced-CPU run, and ALWAYS emits the
JSON line — with an "error" field if every attempt died.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# rough peak bf16 FLOPs/s per chip by device kind
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "cpu": 1e11,
}

_MARK = "BENCH_JSON:"


def measure() -> dict:
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )

    dev = jax.devices()[0]
    on_tpu = "tpu" in dev.platform.lower() or "TPU" in getattr(dev, "device_kind", "")
    kind = getattr(dev, "device_kind", dev.platform)
    peak = next((v for k, v in PEAK_FLOPS.items() if k.lower() in kind.lower()),
                197e12 if on_tpu else 1e11)

    if on_tpu:
        batch, seq, preset, dtype, steps = 8, 1024, "gpt-125m", "bfloat16", 10
    else:  # CPU fallback so the bench runs anywhere
        batch, seq, preset, dtype, steps = 2, 128, "gpt-test", "float32", 3

    # BENCH_FUSED_CE=<chunk>: A/B the chunked fused linear+CE loss path
    # (logits never materialized) against the standard criterion
    fused_chunk = int(os.environ.get("BENCH_FUSED_CE", "0"))
    cfg = gpt_presets(preset, max_position_embeddings=seq, dtype=dtype,
                      fused_loss_chunk=fused_chunk)
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    if fused_chunk > 0:
        step = TrainStep(model, lambda loss: loss, optim)
    else:
        step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)

    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")
    labels = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                              dtype="int64")

    def one_step():
        if fused_chunk > 0:
            return step(inputs=(ids, None, labels), labels=())
        return step(inputs=(ids,), labels=(labels,))

    # warmup / compile (sync before starting the clock)
    for _ in range(3):
        loss = one_step()
        _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    _ = float(loss)  # sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    n_params = v * h + seq * h + L * 12 * h * h + 2 * h
    # fwd+bwd FLOPs/token: 6*N for matmuls + 6*L*s*h causal attention
    flops_per_token = 6 * n_params + 6 * L * seq * h
    mfu = tokens_per_sec * flops_per_token / peak

    print(f"# device={kind} loss={float(loss):.4f} mfu={mfu:.3f} "
          f"step_ms={1000 * dt / steps:.1f}", file=sys.stderr)
    return {
        "metric": f"gpt_{preset.split('-')[1]}_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def _child_main():
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        # the env var alone can be overridden by a TPU-tunnel site shim;
        # the config update cannot
        jax.config.update("jax_platforms", "cpu")
    result = measure()
    print(_MARK + json.dumps(result))


def _run_child(env: dict, timeout: float) -> dict | None:
    code = (
        f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r}); "
        "import bench; bench._child_main()"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print("# bench child timed out", file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr[-4000:])
    for line in proc.stdout.splitlines():
        if line.startswith(_MARK):
            return json.loads(line[len(_MARK):])
    return None


def main():
    if os.environ.get("_GRAFT_BENCH_CHILD") == "1":
        _child_main()
        return

    base = dict(os.environ)
    base["_GRAFT_BENCH_CHILD"] = "1"
    cpu_env = dict(base)
    cpu_env["JAX_PLATFORMS"] = "cpu"
    # a WEDGED tunnel hangs rather than erroring, so the retry gets a short
    # leash and the CPU fallback still runs within the driver's budget
    attempts = [(base, 1200.0), (base, 300.0), (cpu_env, 600.0)]

    errors = []
    for i, (env, budget) in enumerate(attempts):
        plat = env.get("JAX_PLATFORMS", "<default>")
        result = _run_child(env, timeout=budget)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"attempt {i} (JAX_PLATFORMS={plat}) failed")
        print(f"# {errors[-1]}", file=sys.stderr)

    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "error": "; ".join(errors),
    }))


if __name__ == "__main__":
    main()

"""Benchmark: training-step throughput on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = measured MFU / 0.40 (the BASELINE.md north-star MFU target;
the reference publishes no absolute numbers — BASELINE.md). On a non-TPU
run the line carries "fallback": "cpu" and vs_baseline: null — a CPU
number says nothing about TPU perf and must not be read as one.

The driver metric (default) is the fused GPT train step. `BENCH_MODE`
selects the other BASELINE.md configs (run by tools/tpu_perf_sprint.py):
    gpt (default) | resnet50 | bert | widedeep | eager

Robustness contract (VERDICT r1 item 1c): the measurement runs in a child
process; if the ambient backend (e.g. a TPU tunnel) fails to initialize, the
parent retries once, then falls back to a forced-CPU run, and ALWAYS emits the
JSON line — with an "error" field if every attempt died.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# rough peak bf16 FLOPs/s per chip by device kind
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "cpu": 1e11,
}

_MARK = "BENCH_JSON:"


def _device_info():
    import jax

    dev = jax.devices()[0]
    on_tpu = "tpu" in dev.platform.lower() or "TPU" in getattr(dev, "device_kind", "")
    kind = getattr(dev, "device_kind", dev.platform)
    peak = next((v for k, v in PEAK_FLOPS.items() if k.lower() in kind.lower()),
                197e12 if on_tpu else 1e11)
    return on_tpu, kind, peak


MODES = ("gpt", "resnet50", "bert", "widedeep", "eager")


def measure() -> dict:
    mode = os.environ.get("BENCH_MODE", "gpt")
    if mode not in MODES:
        raise SystemExit(f"unknown BENCH_MODE={mode!r}; one of {MODES}")
    result = {
        "gpt": measure_gpt,
        "resnet50": measure_resnet50,
        "bert": measure_bert,
        "widedeep": measure_widedeep,
        "eager": measure_eager,
    }[mode]()
    on_tpu, kind, _ = _device_info()
    result["device_kind"] = kind
    if not on_tpu:
        # A CPU run measures nothing about TPU perf: MFU against a CPU
        # "peak" is fiction, so make the fallback explicit and the
        # comparison null. Exception: widedeep's vs_baseline is held-out
        # AUC (the BASELINE row asks for AUC parity), which is
        # device-independent and stays meaningful.
        result["fallback"] = "cpu"
        if mode != "widedeep":
            result["vs_baseline"] = None
        # attach the most recent MEASURED on-chip record for this mode
        # (artifacts/TPU_RESULTS.json, written by the measurement
        # sprints) so a wedged-tunnel round still carries the TPU
        # number — clearly labeled, never merged into `value`
        try:
            banked = json.load(open(os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "artifacts", "TPU_RESULTS.json")))
            key = "baseline" if mode == "gpt" else mode
            rec = banked.get(key)
            if rec and "cpu" not in str(rec.get("device_kind", "")).lower():
                result["last_measured_tpu"] = rec
        except (FileNotFoundError, json.JSONDecodeError):
            pass
        if mode == "gpt":
            # a wedged tunnel blocks execution but not the TPU COMPILER:
            # AOT-compile the real TPU bench config (GPT-125M b=8 s=1024
            # bf16) for one v5e chip and attach its clearly-labeled
            # estimate so even a wedged round records TPU-backend
            # evidence (fields are est_* — compiler/roofline, not a
            # measurement; never merged into `value`)
            result["tpu_aot_estimate"] = _gpt_tpu_aot_estimate()
    return result


def _gpt_tpu_aot_estimate() -> dict | None:
    """Best-effort AOT estimate of the TPU bench config; None on any
    failure (no libtpu, lockfile contention, version drift)."""
    code = r"""
import json, sys
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
from paddle_tpu.jit.aot import topology_mesh, estimate_step_seconds
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models import gpt_presets
from paddle_tpu.models.gpt import gpt_hbm_estimate

batch, seq = 8, 1024
# no single-chip topology exists (v5e:1x1 is rejected), so compile pure
# DP x8 with per-chip batch 8: the per-chip program matches the
# single-chip bench shape plus a grad all-reduce (compute-dominated at
# this size, so the estimate is a close upper bound)
mesh = topology_mesh("v5e:2x4", {"data": 8})
est = gpt_hbm_estimate(
    gpt_presets("gpt-125m", max_position_embeddings=seq, dtype="bfloat16",
                recompute=False, use_flash_attention=True),
    mesh, global_batch=batch * 8, seq=seq)
sec = estimate_step_seconds(est)
out = {"per_chip_batch": batch, "seq": seq,
       "config": "gpt-125m bf16 flash, DPx8 proxy for single chip",
       "note": "roofline = LOWER bound on step time (upper bound on "
               "tok/s); round-2 MEASURED 103025 tok/s/chip on this shape"}
if sec:
    out["est_step_seconds"] = round(sec["seconds"], 6)
    out["est_signal"] = sec["signal"]
    out["est_tokens_per_sec_chip"] = round(batch * seq / sec["seconds"], 1)
out["peak_hbm_bytes"] = est.get("peak_hbm_bytes")
print("AOT_JSON:" + json.dumps(out))
""" % (os.path.dirname(os.path.abspath(__file__)),)
    try:
        # must fit INSIDE the CPU-fallback child's 900s budget alongside
        # the ~2-3 min CPU measurement (the estimate is a bonus, never
        # worth losing the measured fallback over). 420s covers the clean
        # ~45s compile with generous room for host contention (this host
        # has recorded ~280s AOT compiles under parallel-suite load)
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=420)
    except subprocess.TimeoutExpired:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("AOT_JSON:"):
            return json.loads(line[len("AOT_JSON:"):])
    return None


def measure_gpt() -> dict:
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )

    on_tpu, kind, peak = _device_info()

    if on_tpu:
        batch, seq, preset, dtype, steps = 8, 1024, "gpt-125m", "bfloat16", 10
    else:  # CPU fallback so the bench runs anywhere
        batch, seq, preset, dtype, steps = 2, 128, "gpt-test", "float32", 3
    # variant knobs (A/B'd by the measurement sprints): b16+remat fits at
    # 6.36 GiB by the compiler (b12 without remat would NOT at 18 GiB)
    batch = int(os.environ.get("BENCH_GPT_BATCH", batch))
    remat = os.environ.get("BENCH_GPT_REMAT", "0") == "1"

    # BENCH_FUSED_CE=<chunk>: A/B the chunked fused linear+CE loss path
    # (logits never materialized) against the standard criterion
    fused_chunk = int(os.environ.get("BENCH_FUSED_CE", "0"))
    cfg = gpt_presets(preset, max_position_embeddings=seq, dtype=dtype,
                      fused_loss_chunk=fused_chunk, recompute=remat)
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    if fused_chunk > 0:
        step = TrainStep(model, lambda loss: loss, optim)
    else:
        step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)

    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)), dtype="int64")
    labels = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (batch, seq)),
                              dtype="int64")

    def one_step():
        if fused_chunk > 0:
            return step(inputs=(ids, None, labels), labels=())
        return step(inputs=(ids,), labels=(labels,))

    # warmup / compile (sync before starting the clock)
    for _ in range(3):
        loss = one_step()
        _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    _ = float(loss)  # sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    n_params = v * h + seq * h + L * 12 * h * h + 2 * h
    # fwd+bwd FLOPs/token: 6*N for matmuls + 6*L*s*h causal attention
    flops_per_token = 6 * n_params + 6 * L * seq * h
    mfu = tokens_per_sec * flops_per_token / peak

    print(f"# device={kind} loss={float(loss):.4f} mfu={mfu:.3f} "
          f"step_ms={1000 * dt / steps:.1f}", file=sys.stderr)
    result = {
        "metric": f"gpt_{preset.split('-')[1]}_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }
    result.update(_grad_comm_fields(model))
    result.update(_metrics_fields(model))
    result.update(_memory_fields(step))
    result.update(_kernel_fields(model, optim, cfg, batch, seq))
    result.update(_serve_fields())
    result.update(_pipeline_fields())
    result.update(_ps_fields())
    return result


def _ps_fields() -> dict:
    """ISSUE 20 parameter-server smoke: the quick tools/ps_bench.py run
    (compiled Wide&Deep step under the double-buffered sharded-embedding
    pipeline vs the eager per-step lookup baseline). `ps_examples_per_s`
    and `ps_exposed_pull_ms` are gated by tools/bench_gate.py; the
    nested record keeps the speedup and wire/cache detail for the
    trajectory."""
    import importlib.util

    try:
        spec = importlib.util.spec_from_file_location(
            "ps_bench", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tools", "ps_bench.py"))
        pb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pb)
        out = pb.main(["--quick", "--out", os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "artifacts",
            "ps_bench_quick.json")])
        return {
            "ps_examples_per_s": out["ps_examples_per_s"],
            "ps_exposed_pull_ms": out["ps_exposed_pull_ms"],
            "ps": {
                "speedup_vs_eager": out["speedup_vs_eager"],
                "step_ms": out["pipeline"]["step_ms"],
                "codec": {c: r.get("wire_ratio_vs_fp32")
                          for c, r in out["codec"].items()},
                "cache_hit_rate": {a: r["hit_rate"]
                                   for a, r in out["cache"].items()},
            },
        }
    except Exception as e:  # accounting must never sink the measurement
        print(f"# ps smoke unavailable: {e}", file=sys.stderr)
        return {}


def _pipeline_fields() -> dict:
    """ISSUE 15 pipeline-training smoke: the composed gpt-test
    PipelineTrainStep (1F1B loss+grad engine inside one compiled step)
    vs the unpipelined step at equal global batch, in a subprocess with
    virtual pipe devices (the bench child itself may own a single
    device). `pipeline_bubble_pct` (analytic (P-1)/(M+P-1)) and
    `pipeline_watermark_bytes` (XLA temp bytes of the composed step —
    the activation watermark the schedule bounds by depth) are gated by
    tools/bench_gate.py."""
    try:
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2")
        env.setdefault("JAX_PLATFORMS", "cpu")
        # ISSUE 19: a persistent compilation cache shared into a process
        # with a DIFFERENT forced device count aborted glibc (PR-15's
        # workaround stripped the cache wholesale). The root fix keys the
        # cache directory by (device_kind, world) exactly like artifact-
        # cache entries — the child gets its own `cpu-w2` subdirectory
        # under the SAME base, so cross-world entries are unreachable and
        # the child still keeps its compile cache across retries.
        from paddle_tpu.jit.artifact_cache import compilation_cache_subdir

        cache_base = env.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        env["JAX_COMPILATION_CACHE_DIR"] = compilation_cache_subdir(
            cache_base, world=2, device_kind="cpu")
        tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "pipeline_throughput.py")
        rec, last = None, ""
        for _attempt in range(2):     # one retry: the abort is sporadic
            r = subprocess.run([sys.executable, tool, "--composed"],
                               env=env, timeout=600, capture_output=True,
                               text=True)
            for line in reversed(r.stdout.splitlines()):
                if line.strip().startswith("{"):
                    rec = json.loads(line)
                    break
            if rec is not None:
                break
            last = f"rc={r.returncode}: {r.stderr[-300:]}"
        if rec is None:
            raise RuntimeError(
                f"composed bench produced no JSON ({last})")
        fields = {
            "pipeline_bubble_pct": rec["pipeline_bubble_pct"],
            "pipeline": {
                "microbatches": rec["config"]["microbatches"],
                "pipe": rec["config"]["pipe"],
                "stash_slots": rec["stash_slots"],
                "tokens_per_s": rec["tokens_per_s"],
                "watermark_bytes_at_4x_microbatches":
                    rec["watermark_bytes_at_4x_microbatches"],
            },
        }
        if rec.get("pipeline_watermark_bytes"):
            fields["pipeline_watermark_bytes"] = \
                rec["pipeline_watermark_bytes"]
        return fields
    except Exception as e:  # accounting must never sink the measurement
        print(f"# pipeline smoke unavailable: {e}", file=sys.stderr)
        return {}


def _serve_fields() -> dict:
    """ISSUE 14 serving-runtime smoke: a small open-loop run of the
    continuous-batching ReplicaSet on gpt-test (always gpt-test — the
    serve smoke must stay seconds even when the train bench is a big
    preset). `serve_tokens_per_s` (generated tokens/s at 2x the
    sequential baseline's saturation rate) and `serve_p99_ms` are gated
    by tools/bench_gate.py, as are the ISSUE 16 additions
    `serve_cache_hit_tokens_per_s` (prefix-cache hit-token throughput on
    a Zipfian mix) and `serve_spec_tokens_per_step` (mean committed
    tokens per speculative decode step, 1-layer self-draft), and the
    ISSUE 19 boot numbers `replica_boot_warm_ms` /
    `ttft_after_eviction_ms` (zero-cold-start plane)."""
    import importlib.util

    try:
        spec = importlib.util.spec_from_file_location(
            "serve_bench", os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tools", "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        dm = sb.build_decode_model("gpt-test")
        specs = sb.make_workload(10, dm.vocab_size, seed=0)
        base = sb.run_sequential_baseline(dm, specs)
        point = sb.run_open_loop(
            dm, specs, qps=2.0 * base["requests_per_s"])
        # ISSUE 16 smokes, sized for seconds: Zipfian prefix-cache hit
        # throughput (hit-token counter delta over the cached drive) and
        # speculative committed-tokens-per-step (1-layer self-draft)
        from paddle_tpu.serving.engine import _m_prefix_hit

        zipf = sb.make_zipf_workload(8, dm.vocab_size, n_sys=2,
                                     sys_len=48, max_new=4, seed=1)
        sb._drive_engine(dm, zipf[:4], prefix_cache=True)  # warm jit
        hit0 = _m_prefix_hit.get()
        _, zwall, _ = sb._drive_engine(dm, zipf, prefix_cache=True)
        cache_hit_tps = round((_m_prefix_hit.get() - hit0) / zwall, 1)
        dspecs = sb.make_workload(6, dm.vocab_size, seed=2,
                                  prompt_lo=6, prompt_hi=10,
                                  new_lo=16, new_hi=20)
        _, _, seng = sb._drive_engine(dm, dspecs, prefix_cache=False,
                                      draft_model=dm.truncated(1),
                                      spec_k=4)
        spec_tps = round(seng.spec_emitted / max(1, seng.spec_steps), 3)
        # ISSUE 19 boot smoke: cold (fresh jit wrappers) vs warm replica
        # boot and TTFT across a warm-handoff eviction — both gated
        boot_specs = sb.make_workload(8, dm.vocab_size, seed=3,
                                      new_lo=12, new_hi=20)
        boot = sb.run_boot_phase(dm, boot_specs)
        return {
            "serve_tokens_per_s": point["tokens_per_s"],
            "serve_p99_ms": point["p99_ms"],
            "serve_cache_hit_tokens_per_s": cache_hit_tps,
            "serve_spec_tokens_per_step": spec_tps,
            "replica_boot_warm_ms": boot["replica_boot_warm_ms"],
            "replica_boot_cold_ms": boot["replica_boot_cold_ms"],
            "ttft_after_eviction_ms": boot["ttft_after_eviction_ms"],
            "serve": {
                "baseline_tokens_per_s": base["tokens_per_s"],
                "speedup": round(point["tokens_per_s"]
                                 / base["tokens_per_s"], 3),
                "mean_batch_occupancy": point["mean_batch_occupancy"],
                "completed": point["accepted"] - point["rejected"],
                "boot": {k: boot[k] for k in
                         ("buckets_warmed", "boot_speedup",
                          "redispatched", "lost", "ok")},
            },
        }
    except Exception as e:  # accounting must never sink the measurement
        print(f"# serve smoke unavailable: {e}", file=sys.stderr)
        return {}


def _kernel_fields(model, optim, cfg, batch, seq) -> dict:
    """ISSUE 13 kernel-layer fields: `fused_update_ms` — wall time of one
    fused flat-bucket optimizer update over this model's buckets (the
    compiled inner loop the pallas dequant+update kernel owns on TPU;
    the jnp composition under the default flag-off dispatch) — and
    `flash_block`, the block shape flash-attention dispatch would run
    for this bench config (tuned/default/fallback source included, so
    the trajectory records WHICH tiles produced the number)."""
    import jax
    import jax.numpy as jnp

    try:
        from paddle_tpu.optimizer.fused import FusedFlatUpdater
        from paddle_tpu.ops.flash_attention import flash_block_choice

        fields = {}
        fused = FusedFlatUpdater(optim, model.parameters())
        lr = jnp.asarray(optim.get_lr(), jnp.float32)
        rs = np.random.RandomState(0)
        work = []  # [fn, p, g, slots] per bucket, compiled via _bucket_fn
        for b in fused.buckets:
            p = fused._flat_params(b)
            g = jnp.asarray(rs.randn(b.size), jnp.float32).astype(p.dtype)
            work.append([fused._bucket_fn(b), p, g,
                         fused._init_flat_slots(b)])

        def one_pass():
            outs = []
            for item in work:
                fn, p, g, slots = item
                new_p, new_s = fn(p, g, slots, lr)
                item[3] = new_s     # slots are donated in, fresh out
                outs.append(new_p)
            jax.block_until_ready(outs)

        one_pass()  # warmup / compile outside the clock
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            one_pass()
            times.append(time.perf_counter() - t0)
        fields["fused_update_ms"] = round(sorted(times)[2] * 1e3, 3)
        heads = getattr(cfg, "num_heads",
                        getattr(cfg, "num_attention_heads", None))
        if heads:
            d = cfg.hidden_size // heads
            fields["flash_block"] = flash_block_choice(
                (batch, seq, heads, d),
                dtype=getattr(cfg, "dtype", "float32"))
        return fields
    except Exception as e:  # accounting must never sink the measurement
        print(f"# kernel fields unavailable: {e}", file=sys.stderr)
        return {}


def _memory_fields(step) -> dict:
    """Measured peak-HBM accounting for the bench step (ISSUE 6), next to
    the roofline estimate the record already carries
    (tpu_aot_estimate.peak_hbm_bytes): the PJRT allocator's
    peak_bytes_in_use where the backend reports it (TPU), else XLA's
    memory_analysis of the exact compiled train step
    (TrainStep.memory_analysis — argument+temp+output-alias). Also records
    the live-tensor byte count so the eager working set is on the record."""
    try:
        from paddle_tpu.observability import memory as obs_mem

        fields = {}
        stats = obs_mem.device_memory_stats()
        analysis = step.memory_analysis()
        if stats and stats.get("peak_bytes_in_use"):
            fields["peak_hbm_bytes_measured"] = int(stats["peak_bytes_in_use"])
            fields["peak_hbm_source"] = "device_memory_stats"
        elif analysis is not None:
            fields["peak_hbm_bytes_measured"] = int(
                analysis["peak_hbm_bytes"])
            fields["peak_hbm_source"] = "xla_memory_analysis"
        if analysis is not None:
            fields["train_step_memory"] = {
                k: analysis[k] for k in ("argument_bytes", "temp_bytes",
                                         "output_bytes", "alias_bytes",
                                         "peak_hbm_bytes")}
        live = obs_mem.live_tensor_bytes()
        if live is not None:
            fields["live_tensor_bytes"] = int(live)
        return fields
    except Exception as e:  # accounting must never sink the measurement
        print(f"# memory accounting unavailable: {e}", file=sys.stderr)
        return {}


def _metrics_fields(model) -> dict:
    """Observability snapshot for the bench record (ISSUE 3): trace-cache
    hit rate over this run's eager dispatches, plus a checkpoint
    save-duration histogram measured by one real atomic commit of the bench
    model's weights — so every BENCH_* file carries compile-cache and
    checkpoint telemetry next to the wall-clock number."""
    try:
        import shutil
        import tempfile

        from paddle_tpu.observability import get_registry
        from paddle_tpu.robustness.checkpoint import CheckpointManager

        d = tempfile.mkdtemp(prefix="bench_ckpt_")
        try:
            mgr = CheckpointManager(d, keep_last_n=1)
            mgr.save(model.state_dict(), 0)
            mgr.close()
        finally:
            shutil.rmtree(d, ignore_errors=True)
        snap = get_registry().snapshot()
        hits = snap.get("trace_cache_hits_total", 0)
        misses = snap.get("trace_cache_misses_total", 0)
        keep = {
            k: v for k, v in snap.items()
            if k.startswith(("trace_cache_", "eager_dispatch",
                             "grad_comm_", "checkpoint_save",
                             "collectives_total"))
        }
        keep["trace_cache_hit_rate"] = (
            round(hits / (hits + misses), 4) if (hits + misses) else None)
        return {"metrics": keep}
    except Exception as e:  # telemetry must never sink the measurement
        print(f"# metrics snapshot unavailable: {e}", file=sys.stderr)
        return {}


def _grad_comm_fields(model) -> dict:
    """DP gradient-traffic plan for this model under the default grad_comm
    settings: codec name + bytes/collectives per step, so the trajectory
    records the bucketing/quantization win next to the throughput number."""
    try:
        from paddle_tpu.distributed import grad_comm, overlap

        plan = grad_comm.comm_plan(model.parameters(),
                                   grad_comm.GradCommConfig())
        fields = {
            "grad_codec": plan["codec"],
            "comm_bytes_per_step": plan["comm_bytes_per_step"],
            "comm_collectives_per_step": plan["collectives_per_step"],
            "per_param_comm_bytes": plan["per_param_comm_bytes"],
            # ISSUE 8: the COMPILED step's wire bytes under the default
            # codec — sync_async / TrainStep(grad_comm=) now apply the
            # codec in-trace, so the compiled path moves the plan's bytes
            # instead of raw fp32 (tools/grad_comm_bench.py's traced_*
            # columns measure the same number from a compiled shard_map
            # sync; tests pin their agreement)
            "comm_bytes_per_step_traced": plan["comm_bytes_per_step"],
        }
        # bucket-ready overlapped sync (ISSUE 5): measured on detached
        # fakes of this model's param shapes — how much of the comm work
        # hides under an emulated backward window vs the serial sync. The
        # small caps split this model into several buckets so the pipeline
        # has stages (the default 25MB cap is one bucket for small nets —
        # nothing to overlap); same config as tools/overlap_bench.py.
        rep = overlap.overlap_report(
            model.parameters(),
            grad_comm.GradCommConfig(comm_buffer_size=0.05,
                                     last_comm_buffer_size=0.01),
            world=2, compute_s=0.04)
        fields["overlap_efficiency"] = rep["overlap_efficiency"]
        fields["exposed_comm_ms"] = {
            "serial": rep["serial_exposed_comm_ms"],
            "overlapped": rep["overlapped_exposed_comm_ms"],
        }
        # ZeRO-3 parameter direction (ISSUE 9): exposed gather ms with the
        # layer-ahead prefetch + per-rank resident param bytes at rest,
        # measured on detached fakes of this model's param shapes
        # (distributed/sharding/stage3.py); tools/bench_gate.py gates both
        from paddle_tpu.distributed.sharding.stage3 import (
            zero3_gather_report,
        )

        z3 = zero3_gather_report(
            model.parameters(),
            grad_comm.GradCommConfig(comm_buffer_size=0.05,
                                     last_comm_buffer_size=0.01),
            world=2, compute_s=0.04)
        fields["zero3_exposed_gather_ms"] = z3["prefetch_exposed_gather_ms"]
        fields["zero3_param_bytes_per_rank"] = \
            z3["zero3_param_bytes_per_rank"]
        fields["zero3_gather"] = {
            "sync_exposed_ms": z3["sync_exposed_gather_ms"],
            "prefetched_exposed_ms": z3["prefetch_exposed_gather_ms"],
            "n_buckets": z3["n_buckets"],
            "param_bytes_full": z3["param_bytes_full"],
        }
        # elastic resharding + preemption (ISSUE 10): the N=4→M=2 shard
        # geometry transform on this model's shapes (host cost — the
        # transform IS host-side), bit-identity asserted in passing, and
        # one emergency preemption checkpoint commit of this model's
        # state — both gated by tools/bench_gate.py against the grace
        # window budget
        fields.update(_reshard_fields(model))
        return fields
    except Exception as e:  # accounting must never sink the measurement
        print(f"# grad_comm plan unavailable: {e}", file=sys.stderr)
        return {}


def _reshard_fields(model) -> dict:
    """reshard_ms (N=4→M=2 zero3 transform on this model's shapes) and
    emergency_save_ms (one tagged preemption checkpoint commit)."""
    import shutil
    import tempfile

    try:
        from paddle_tpu.distributed import grad_comm
        from paddle_tpu.distributed.sharding.reshard import reshard_report
        from paddle_tpu.robustness.checkpoint import CheckpointManager
        from paddle_tpu.robustness.preemption import timed_emergency_save

        rep = reshard_report(
            model.parameters(),
            grad_comm.GradCommConfig(comm_buffer_size=0.05,
                                     last_comm_buffer_size=0.01),
            old_world=4, new_world=2)
        fields = {
            "reshard_ms": rep["reshard_ms"],
            "reshard": {k: rep[k] for k in
                        ("from_world", "to_world", "n_buckets",
                         "param_bytes_full", "bit_identical")},
        }
        d = tempfile.mkdtemp(prefix="bench_emergency_")
        try:
            mgr = CheckpointManager(d, keep_last_n=1)
            ms = timed_emergency_save(mgr, {"model": model.state_dict()}, 0)
            mgr.close()
        finally:
            shutil.rmtree(d, ignore_errors=True)
        fields["emergency_save_ms"] = round(ms, 3)
        return fields
    except Exception as e:  # accounting must never sink the measurement
        print(f"# reshard/emergency fields unavailable: {e}",
              file=sys.stderr)
        return {}


def measure_resnet50() -> dict:
    """BASELINE.md config 2: ResNet-50 train step, samples/s/chip + MFU."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    on_tpu, kind, peak = _device_info()
    if on_tpu:
        # batch 256: the TPU compiler ranks it well ahead of 64/128
        # (artifacts/resnet_aot_probe.json: est 2127 vs 1321 samples/s,
        # 9.5 GiB HBM — fits v5e's 16) and conv efficiency rises with
        # batch; round-5 measured 1758 at batch 64
        batch, img, steps = 256, 224, 8
    else:
        batch, img, steps = 2, 64, 2

    model = resnet50(num_classes=1000)
    optim = opt.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=model.parameters())
    step = TrainStep(model, lambda logits, y: F.cross_entropy(logits, y),
                     optim)

    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(batch, 3, img, img).astype("float32"))
    y = paddle.to_tensor(rs.randint(0, 1000, (batch,)), dtype="int64")

    from paddle_tpu.amp import auto_cast

    def one_step():
        with auto_cast(enable=on_tpu, level="O2", dtype="bfloat16"):
            return step(inputs=(x,), labels=(y,))

    for _ in range(3):
        loss = one_step()
        _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    _ = float(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = batch * steps / dt
    # fwd FLOPs ~4.09 GF at 224^2 (conv-dominated -> scales with area);
    # train step ~= 3x fwd
    flops_per_sample = 3 * 4.09e9 * (img * img) / (224 * 224)
    mfu = samples_per_sec * flops_per_sample / peak
    print(f"# device={kind} loss={float(loss):.4f} mfu={mfu:.3f} "
          f"step_ms={1000 * dt / steps:.1f}", file=sys.stderr)
    return {
        "metric": "resnet50_train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def measure_bert() -> dict:
    """BASELINE.md config 3: BERT pretraining (MLM+NSP), samples/s/chip + MFU."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import BertForPretraining, bert_presets

    on_tpu, kind, peak = _device_info()
    fused_chunk = int(os.environ.get("BENCH_FUSED_CE", "0"))
    if on_tpu:
        batch, seq, preset, steps = 16, 512, "bert-base", 10
    else:
        batch, seq, preset, steps = 2, 64, "bert-test", 2

    cfg = bert_presets(preset, fused_loss_chunk=fused_chunk)
    model = BertForPretraining(cfg)
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    # loss = MLM loss (model computes it over masked positions) + NSP CE
    step = TrainStep(
        model,
        lambda mlm_loss, nsp_logits, nsp_lbl:
            mlm_loss + F.cross_entropy(nsp_logits, nsp_lbl),
        optim)

    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (batch, seq))
    masked = rs.rand(batch, seq) < 0.15
    mlm = np.where(masked, ids, -1)
    ids_t = paddle.to_tensor(ids, dtype="int64")
    mlm_t = paddle.to_tensor(mlm, dtype="int64")
    nsp_t = paddle.to_tensor(rs.randint(0, 2, (batch,)), dtype="int64")

    from paddle_tpu.amp import auto_cast

    def one_step():
        with auto_cast(enable=on_tpu, level="O2", dtype="bfloat16"):
            return step(inputs=(ids_t, None, None, None, mlm_t),
                        labels=(nsp_t,))

    for _ in range(3):
        loss = one_step()
        _ = float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = one_step()
    _ = float(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = batch * steps / dt
    h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    n_params = v * h + seq * h + 2 * h + L * 12 * h * h + 2 * h * h
    # bidirectional attention: 12*L*s*h per token fwd+bwd (no causal halving)
    flops_per_token = 6 * n_params + 12 * L * seq * h
    mfu = samples_per_sec * seq * flops_per_token / peak
    print(f"# device={kind} loss={float(loss):.4f} mfu={mfu:.3f} "
          f"step_ms={1000 * dt / steps:.1f}", file=sys.stderr)
    return {
        "metric": "bert_train_samples_per_sec",  # same name as the failure
        "value": round(samples_per_sec, 2),      # fallback, for aggregation
        "unit": "samples/s/chip",
        "vs_baseline": round(mfu / 0.40, 4),
    }


def measure_widedeep() -> dict:
    """BASELINE.md config 5: Wide&Deep over the PS, examples/s + AUC.

    vs_baseline here is the held-out AUC (the BASELINE row asks for AUC
    parity, not an MFU); the throughput is the headline value.
    """
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.ps import (
        LocalPs, TheOnePSRuntime, distributed_lookup_table,
    )
    from paddle_tpu.distributed.ps.communicator import AsyncCommunicator
    from paddle_tpu.metric import Auc

    on_tpu, kind, _ = _device_info()
    batch, slots, steps, vocab = ((512, 16, 60, 10000) if on_tpu
                                  else (128, 8, 30, 2000))

    runtime = TheOnePSRuntime()
    ps = LocalPs()
    ps.create_table(0, dim=8, init_range=0.01, lr=0.1, optimizer="adagrad")
    runtime.client = ps
    runtime.communicator = AsyncCommunicator(ps)
    runtime.communicator.start()

    deep = paddle.nn.Sequential(
        paddle.nn.Linear(8 * slots, 64), paddle.nn.ReLU(),
        paddle.nn.Linear(64, 1))
    optim = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=deep.parameters())
    rs = np.random.RandomState(0)
    true_w = rs.randn(vocab)

    def make_batch(n):
        ids = rs.randint(0, vocab, (n, slots))
        labels = (true_w[ids].sum(1) > 0).astype("float32")
        return ids, labels

    # the heter pass path (PSGPUTrainer analog): the pass working set
    # lives on device, ONE compiled program per step (gather + dense
    # fwd/bwd + Adam + grad accumulation), merged PS push per pass —
    # vs the eager per-step lookup/push path this avoids the per-batch
    # host<->device row round-trip that dominates behind a TPU tunnel
    from paddle_tpu.distributed.ps.heter_cache import DevicePassCache
    from paddle_tpu.distributed.ps.heter_trainer import CompiledPassStep

    cache = DevicePassCache(ps, 0, lr=0.1)
    pass_step = CompiledPassStep(
        cache, deep, optim,
        lambda out, labels: F.binary_cross_entropy_with_logits(
            out[:, 0], labels),
        table_optimizer="adagrad", table_lr=0.1)
    steps_per_pass = 10

    # fixed slab size: shape-stable across passes, ONE compiled program
    pad_rows = vocab

    def run_pass(pass_batches):
        cache.begin_pass(
            np.concatenate([b[0].reshape(-1) for b in pass_batches]),
            pad_to=pad_rows)
        for b in pass_batches:
            loss = pass_step(cache, b)
        cache.end_pass(assign=True)  # device optimizer owns the update
        return loss

    loss = run_pass([make_batch(batch) for _ in range(2)])  # warm compile
    batches = [make_batch(batch) for _ in range(steps)]  # keep data-gen
    t0 = time.perf_counter()                             # out of the timer
    for i in range(0, steps, steps_per_pass):
        loss = run_pass(batches[i:i + steps_per_pass])
    _ = float(loss)
    dt = time.perf_counter() - t0
    examples_per_sec = batch * steps / dt

    # held-out AUC
    auc = Auc()
    ids, labels = make_batch(4096)
    with paddle.no_grad():
        rows = distributed_lookup_table(
            paddle.to_tensor(ids, dtype="int64"), table_id=0, lr=0.0)
        logit = deep(rows.reshape([4096, -1]))[:, 0]
        prob = F.sigmoid(logit).numpy()
    preds = np.stack([1.0 - prob, prob], axis=1)
    auc.update(preds, labels[:, None])
    auc_val = float(auc.accumulate())
    runtime.communicator.stop()

    print(f"# device={kind} loss={float(loss):.4f} auc={auc_val:.4f} "
          f"table_rows={ps.table_size(0)}", file=sys.stderr)
    return {
        "metric": "wide_deep_ps_examples_per_sec",
        "value": round(examples_per_sec, 1),
        "unit": "examples/s",
        "vs_baseline": round(auc_val, 4),
    }


def measure_eager() -> dict:
    """Eager per-op dispatch latency (op-cache hit path) on the real chip.

    SURVEY §7 hard-part 1: eager op dispatch must stay usable on TPU.
    vs_baseline = 100us-target / measured (>=1 means each cached eager op
    dispatches in under 100us).
    """
    import paddle_tpu as paddle

    on_tpu, kind, _ = _device_info()
    x = paddle.ones([256, 256])
    n = 200

    def chain(t, k):
        for _ in range(k):
            t = t * 1.0001 + 0.1
        return t

    _ = float(chain(x, 20).sum())  # warm the op-cache
    t0 = time.perf_counter()
    y = chain(x, n)
    _ = float(y.sum())
    dt = time.perf_counter() - t0
    us_per_op = dt / (2 * n) * 1e6  # each chain iteration is 2 ops (mul, add)

    # grad-enabled loop: dispatch + tape-node build + cached backward —
    # the eager TRAINING path (SURVEY §7 hard-part 1's real shape). Tiny
    # tensors so HOST overhead (the thing being measured) dominates compute.
    xs = paddle.ones([16, 16])
    w = paddle.ones([16, 16])
    w.stop_gradient = False
    k = 20

    def train_iter():
        t = xs
        for _ in range(k):
            t = t @ w
            t = t * 0.5
        loss = t.sum()
        loss.backward()
        g = w.grad
        w.clear_grad()
        return g

    _ = train_iter()  # warm fwd+bwd caches
    iters = max(1, n // (2 * k))
    t0 = time.perf_counter()
    for _ in range(iters):
        g = train_iter()
    _ = float(g.sum()._value if hasattr(g.sum(), "_value") else g.sum())
    dt_g = time.perf_counter() - t0
    # per iteration: 2k fwd dispatches + one tape walk of 2k+1 bwd nodes
    us_per_train_op = dt_g / (iters * 4 * k) * 1e6
    print(f"# device={kind} eager {us_per_op:.1f} us/op (no-grad chain), "
          f"{us_per_train_op:.1f} us/op (fwd+bwd tape loop)",
          file=sys.stderr)
    return {
        "metric": "eager_op_dispatch_us",
        "value": round(us_per_op, 2),
        "unit": "us/op",
        "vs_baseline": round(100.0 / us_per_op, 4),
        "train_us_per_op": round(us_per_train_op, 2),
    }


def _child_main():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the env var alone can be overridden by a TPU-tunnel site shim;
        # the config update cannot
        jax.config.update("jax_platforms", "cpu")
    # persistent XLA compile cache (also when invoked in child mode
    # directly, e.g. by tools/tpu_perf_sprint.py): retries and reruns of
    # the same program skip its compile. The directory is keyed by the
    # child's LIVE (device_kind, world) — ISSUE 19's root fix for the
    # cross-device-count cache-sharing abort — so any number of world
    # sizes share one base safely.
    from paddle_tpu.jit.artifact_cache import compilation_cache_subdir

    cache_base = os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache")
    jax.config.update("jax_compilation_cache_dir",
                      compilation_cache_subdir(cache_base))
    if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    result = measure()
    print(_MARK + json.dumps(result))


def _run_child(env: dict, timeout: float) -> dict | None:
    code = (
        f"import sys; sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r}); "
        "import bench; bench._child_main()"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        print("# bench child timed out", file=sys.stderr)
        return None
    sys.stderr.write(proc.stderr[-4000:])
    for line in proc.stdout.splitlines():
        if line.startswith(_MARK):
            return json.loads(line[len(_MARK):])
    return None


def _probe_exec(env, timeout=60.0):
    """True iff the ambient backend EXECUTES (not merely enumerates): the
    2026-07 wedge mode lists devices instantly but hangs any compile."""
    env.pop("_GRAFT_BENCH_CHILD", None)
    code = (
        "import jax, jax.numpy as jnp; "
        "x = jnp.ones((256, 256), jnp.bfloat16); "
        "(x @ x).block_until_ready(); print('EXEC-OK')"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           timeout=timeout, capture_output=True, text=True)
        return r.returncode == 0 and "EXEC-OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    if os.environ.get("_GRAFT_BENCH_CHILD") == "1":
        _child_main()
        return

    mode = os.environ.get("BENCH_MODE", "gpt")
    if mode not in MODES:
        raise SystemExit(f"unknown BENCH_MODE={mode!r}; one of {MODES}")

    base = dict(os.environ)
    base["_GRAFT_BENCH_CHILD"] = "1"
    # persistent XLA compilation cache: a retry (or the next round) of the
    # same program skips its 20-40s+ compile — on a flaky tunnel, the
    # difference between a result and a timeout
    base.setdefault("JAX_COMPILATION_CACHE_DIR",
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache"))
    base.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "5")
    cpu_env = dict(base)
    cpu_env["JAX_PLATFORMS"] = "cpu"
    # PROBE FIRST (VERDICT r4 weak #1): a WEDGED tunnel hangs rather than
    # erroring, so a 60s matmul round-trip decides whether the TPU
    # attempts are worth their 900s budgets — a dead tunnel now costs
    # seconds before the CPU fallback, not 2x900s
    errors = []
    # 240s covers cold jax import + TPU runtime init + the 256x256 compile
    # on a congested-but-healthy tunnel (a wedged one hangs forever, so
    # any finite leash classifies it); still 7x cheaper than 2x900s
    if _probe_exec(dict(base), timeout=240.0):
        attempts = [(base, 900.0), (base, 240.0), (cpu_env, 900.0)]
    else:
        errors.append("exec probe failed (tunnel wedged or enum-only); "
                      "skipping TPU attempts")
        print(f"# {errors[-1]}", file=sys.stderr)
        attempts = [(cpu_env, 900.0)]
    for i, (env, budget) in enumerate(attempts):
        plat = env.get("JAX_PLATFORMS", "<default>")
        result = _run_child(env, timeout=budget)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"attempt {i} (JAX_PLATFORMS={plat}) failed")
        print(f"# {errors[-1]}", file=sys.stderr)

    fallback_metric, fallback_unit = {
        "gpt": ("gpt_train_tokens_per_sec", "tokens/s/chip"),
        "resnet50": ("resnet50_train_samples_per_sec", "samples/s/chip"),
        "bert": ("bert_train_samples_per_sec", "samples/s/chip"),
        "widedeep": ("wide_deep_ps_examples_per_sec", "examples/s"),
        "eager": ("eager_op_dispatch_us", "us/op"),
    }[mode]
    print(json.dumps({
        "metric": fallback_metric,
        "value": 0.0,
        "unit": fallback_unit,
        "vs_baseline": None,
        "fallback": "none",
        "error": "; ".join(errors),
    }))


if __name__ == "__main__":
    main()

"""Static control-flow ops: while_loop / cond / case / switch_case.

Reference: paddle/fluid/operators/controlflow/ (while_op.cc,
conditional_block_op.cc) and python/paddle/fluid/layers/control_flow.py;
tests modeled on unittests/test_while_loop_op.py, test_cond.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static.nn import case, cond, switch_case, while_loop


def test_while_loop_eager_counts():
    i = paddle.to_tensor(np.int32(0))
    s = paddle.to_tensor(np.float32(0.0))
    out_i, out_s = while_loop(
        lambda i, s: i < 10,
        lambda i, s: (i + 1, s + paddle.cast(i, "float32")),
        [i, s])
    assert int(out_i.numpy()) == 10
    assert float(out_s.numpy()) == sum(range(10))


def test_while_loop_data_dependent_trip_count_in_program():
    """The trip count must follow the FEED value, not the build-time
    placeholder — i.e. the tape records a real while op."""
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        n = static.data("n", shape=[], dtype="int32")
        i0 = paddle.to_tensor(np.int32(0))
        acc0 = paddle.to_tensor(np.float32(0.0))
        i_out, acc = while_loop(lambda i, a: i < n,
                                lambda i, a: (i + 1, a + 2.0),
                                [i0, acc0])
    exe = static.Executor()
    exe.run(startup)
    for feed_n, expect in [(3, 6.0), (7, 14.0), (0, 0.0)]:
        (got,) = exe.run(main, feed={"n": np.int32(feed_n)},
                         fetch_list=[acc])
        assert float(got) == expect, (feed_n, got)


def test_while_loop_validation():
    with pytest.raises(ValueError):
        while_loop(lambda: True, lambda: (), [])
    i = paddle.to_tensor(np.int32(0))
    with pytest.raises(ValueError):
        while_loop(lambda i: i < 3, lambda i: (i + 1, i), [i])


def test_cond_select_semantics():
    x = paddle.to_tensor(np.float32(3.0))
    y = paddle.to_tensor(np.float32(4.0))
    big = cond(x > y, lambda: x * 2, lambda: y * 2)
    assert float(big.numpy()) == 8.0
    small = cond(x < y, lambda: (x, x + 1), lambda: (y, y + 1))
    assert float(small[0].numpy()) == 3.0 and float(small[1].numpy()) == 4.0


def test_cond_is_differentiable():
    x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    pred = paddle.to_tensor(True)
    out = cond(pred, lambda: x * 3.0, lambda: x * 5.0)
    out.backward()
    assert float(x.grad.numpy()) == 3.0


def test_cond_python_bool_short_circuits():
    calls = []

    def t():
        calls.append("t")
        return paddle.to_tensor(1.0)

    def f():
        calls.append("f")
        return paddle.to_tensor(2.0)

    out = cond(True, t, f)
    assert float(out.numpy()) == 1.0 and calls == ["t"]


def test_cond_in_program_follows_feed():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        flag = static.data("flag", shape=[], dtype="bool")
        a = paddle.to_tensor(np.float32(10.0))
        out = cond(flag, lambda: a + 1, lambda: a - 1)
    exe = static.Executor()
    exe.run(startup)
    (hi,) = exe.run(main, feed={"flag": np.bool_(True)}, fetch_list=[out])
    (lo,) = exe.run(main, feed={"flag": np.bool_(False)}, fetch_list=[out])
    assert float(hi) == 11.0 and float(lo) == 9.0


def test_case_first_match_wins():
    x = paddle.to_tensor(np.float32(2.0))
    out = case([(x > 3, lambda: paddle.to_tensor(30.0)),
                (x > 1, lambda: paddle.to_tensor(10.0))],
               default=lambda: paddle.to_tensor(0.0))
    assert float(out.numpy()) == 10.0
    # default fires when nothing matches
    out2 = case([(x > 3, lambda: paddle.to_tensor(30.0))],
                default=lambda: paddle.to_tensor(-1.0))
    assert float(out2.numpy()) == -1.0
    # no explicit default: last fn is the default
    out3 = case([(x > 5, lambda: paddle.to_tensor(1.0)),
                 (x > 4, lambda: paddle.to_tensor(2.0))])
    assert float(out3.numpy()) == 2.0


def test_switch_case():
    idx = paddle.to_tensor(np.int32(1))
    out = switch_case(idx, {0: lambda: paddle.to_tensor(100.0),
                            1: lambda: paddle.to_tensor(200.0),
                            2: lambda: paddle.to_tensor(300.0)})
    assert float(out.numpy()) == 200.0
    # out-of-range index falls to default (last fn when none given)
    idx9 = paddle.to_tensor(np.int32(9))
    out9 = switch_case(idx9, [lambda: paddle.to_tensor(1.0),
                              lambda: paddle.to_tensor(2.0)],
                       default=lambda: paddle.to_tensor(-5.0))
    assert float(out9.numpy()) == -5.0


def test_while_loop_captures_global_tensors():
    """Outer tensors referenced as module globals (not closure cells) must
    also be captured as implicit while-op inputs."""
    ns = {}
    exec(textwrap_dedent := (
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.static as static\n"
        "from paddle_tpu.static.nn import while_loop\n"
        "main, startup = static.Program(), static.Program()\n"
        "with static.program_guard(main, startup):\n"
        "    n = static.data('n', shape=[], dtype='int32')\n"
        "    i0 = paddle.to_tensor(np.int32(0))\n"
        "    out = while_loop(lambda i: i < n, lambda i: (i + 2,), [i0])\n"
    ), ns)
    exe = ns["static"].Executor()
    exe.run(ns["startup"])
    (got,) = exe.run(ns["main"], feed={"n": np.int32(7)},
                     fetch_list=[ns["out"][0]])
    assert int(got) == 8


def test_while_loop_outputs_stop_gradient():
    """lax.while_loop has no reverse-mode grad: outputs are detached, and
    backward() through them is a no-op rather than a deep JAX crash."""
    x = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    (out,) = while_loop(lambda a: a < 10.0, lambda a: (a * 2.0,), [x])
    assert out.stop_gradient
    assert float(out.numpy()) == 16.0


def test_switch_case_pair_list_form():
    """Reference switch_case also accepts [(index, fn), ...] pairs."""
    idx = paddle.to_tensor(np.int32(3))
    out = switch_case(idx, [(1, lambda: paddle.to_tensor(10.0)),
                            (3, lambda: paddle.to_tensor(30.0))])
    assert float(out.numpy()) == 30.0


def test_while_loop_captures_through_partial_and_method():
    import functools

    class Stepper:
        def __init__(self, limit):
            self.limit = limit

        def keep_going(self, i):
            return i < self.limit

    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        n = static.data("n", shape=[], dtype="int32")
        st = Stepper(n)
        i0 = paddle.to_tensor(np.int32(0))
        body = functools.partial(lambda step, i: (i + step,),
                                 paddle.to_tensor(np.int32(3)))
        (out,) = while_loop(st.keep_going, body, [i0])
    exe = static.Executor()
    exe.run(startup)
    (got,) = exe.run(main, feed={"n": np.int32(7)}, fetch_list=[out])
    assert int(got) == 9


def test_program_to_string():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", shape=[None, 4], dtype="float32")
        y = x * 2.0 + 1.0
    s = main.to_string()
    assert "program id=" in s and "Op(" in s and "x" in s
    assert f"ops={len(main.ops)}" in s

"""Robustness subsystem tests: atomic checkpointing under fault injection,
sharded manifest-last commits, NaN guard policies (incl. AMP scaler
interplay), hang detection, and the crash-safe resume path end to end.

Reference analogs: test_auto_checkpoint*.py, test_fleet_checkpoint.py; the
fault-injection style follows orbax's atomicity tests (crash points around
the commit rename).
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.framework.errors import (
    CheckpointCorruptError, CheckpointNotFoundError,
)
from paddle_tpu.robustness import (
    CheckpointManager, CircuitBreakerTripped, FaultyFS, HangDetector,
    InjectedCrash, NanGuard, NanLossError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def state_for(step):
    return {"w": np.full((3, 3), float(step), np.float32), "step": step}


def assert_state(state, step):
    assert state["step"] == step
    np.testing.assert_array_equal(state["w"], np.full((3, 3), float(step)))


class TestAtomicCommit:
    def test_save_load_roundtrip_with_tensors(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        net = nn.Linear(2, 3)
        mgr.save({"model": net.state_dict(), "extra": [1, "a"]}, 7,
                 metadata={"note": "hi"})
        state, step, manifest = mgr.load_latest()
        assert step == 7 and manifest["metadata"]["note"] == "hi"
        np.testing.assert_allclose(state["model"]["weight"],
                                   net.weight.numpy())
        assert state["extra"] == [1, "a"]

    def test_crash_before_rename_leaves_no_visible_checkpoint(self, tmp_path):
        root = str(tmp_path)
        CheckpointManager(root).save(state_for(0), 0)
        fs = FaultyFS(crash_on_rename=1)
        with pytest.raises(InjectedCrash):
            CheckpointManager(root, fs=fs).save(state_for(1), 1)
        clean = CheckpointManager(root)
        assert clean.steps() == [0]  # step 1 never became visible
        state, step, _ = clean.load_latest()
        assert step == 0
        assert_state(state, 0)
        # the crashed attempt left a stale tmp dir; gc collects it
        assert any(".tmp-" in n for n in os.listdir(root))
        clean.gc()
        assert not any(".tmp-" in n for n in os.listdir(root))

    def test_partial_write_is_invisible(self, tmp_path):
        root = str(tmp_path)
        CheckpointManager(root).save(state_for(3), 3)
        # tear the payload write (1st write), then the manifest write (2nd):
        # neither torn state may ever become a visible checkpoint
        for attempt, torn_write in enumerate((1, 2)):
            fs = FaultyFS(partial_write_on=torn_write)
            with pytest.raises(InjectedCrash):
                CheckpointManager(root, fs=fs).save(state_for(9), 9)
            clean = CheckpointManager(root)
            assert clean.steps() == [3]
            assert clean.load_latest()[1] == 3

    def test_checksum_mismatch_detected_and_skipped(self, tmp_path):
        root = str(tmp_path)
        mgr = CheckpointManager(root)
        mgr.save(state_for(0), 0)
        mgr.save(state_for(1), 1)
        # flip bytes inside the newest payload (bit rot / torn sector)
        target = os.path.join(mgr.step_path(1), "state.pdparams")
        data = bytearray(open(target, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(target, "wb").write(bytes(data))
        assert mgr.validate(1) is None
        assert mgr.validate(0) is not None
        with pytest.raises(CheckpointCorruptError):
            mgr.load(1)
        state, step, _ = mgr.load_latest()  # falls back past the corrupt one
        assert step == 0
        assert_state(state, 0)

    def test_truncated_manifest_skipped(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state_for(0), 0)
        mgr.save(state_for(1), 1)
        mpath = os.path.join(mgr.step_path(1), "MANIFEST.json")
        open(mpath, "r+b").truncate(11)
        assert mgr.load_latest()[1] == 0

    def test_retention_deletes_oldest_first(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
        deleted_order = []
        real_rmtree = mgr.fs.rmtree
        mgr.fs.rmtree = lambda p: (deleted_order.append(p), real_rmtree(p))
        for s in range(5):
            mgr.save(state_for(s), s)
        assert mgr.steps() == [3, 4]
        victims = [p for p in deleted_order if ".tmp-" not in p]
        assert victims == [mgr.step_path(0), mgr.step_path(1),
                           mgr.step_path(2)]

    def test_transient_oserror_retried_with_backoff(self, tmp_path):
        fs = FaultyFS(transient_oserrors=2)
        mgr = CheckpointManager(str(tmp_path), fs=fs, retries=3,
                                backoff=0.001)
        mgr.save(state_for(5), 5)
        assert CheckpointManager(str(tmp_path)).load_latest()[1] == 5

    def test_retries_exhausted_raises_and_cleans_tmp(self, tmp_path):
        fs = FaultyFS(transient_oserrors=50)
        mgr = CheckpointManager(str(tmp_path), fs=fs, retries=1,
                                backoff=0.001)
        with pytest.raises(OSError):
            mgr.save(state_for(0), 0)
        # clean failure (not a crash): the tmp dir was tidied up
        assert not any(".tmp-" in n for n in os.listdir(str(tmp_path)))
        assert CheckpointManager(str(tmp_path)).load_latest() is None

    def test_resave_same_step_overwrites(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state_for(2), 2)
        mgr.save({"w": np.zeros((3, 3), np.float32), "step": 2}, 2)
        state, step, _ = mgr.load_latest()
        assert step == 2 and np.all(state["w"] == 0)


class TestAsyncSave:
    def test_close_during_inflight_write_still_commits(self, tmp_path):
        fs = FaultyFS(slow_io=0.05)  # widen the in-flight window
        mgr = CheckpointManager(str(tmp_path), fs=fs)
        mgr.save_async(state_for(4), 4)
        mgr.close()  # must flush, not abandon
        state, step, _ = CheckpointManager(str(tmp_path)).load_latest()
        assert step == 4
        assert_state(state, 4)

    def test_snapshot_is_copy_on_save(self, tmp_path):
        fs = FaultyFS(slow_io=0.05)
        mgr = CheckpointManager(str(tmp_path), fs=fs)
        arr = np.full((3, 3), 1.0, np.float32)
        mgr.save_async({"w": arr, "step": 1}, 1)
        arr[:] = -999.0  # training mutates weights while the save is in flight
        mgr.close()
        state, _, _ = mgr.load_latest()
        np.testing.assert_array_equal(state["w"], np.full((3, 3), 1.0))

    def test_async_error_surfaces_on_wait(self, tmp_path):
        fs = FaultyFS(crash_on_rename=1)
        mgr = CheckpointManager(str(tmp_path), fs=fs)
        mgr.save_async(state_for(0), 0)
        with pytest.raises(InjectedCrash):
            mgr.wait()
        assert CheckpointManager(str(tmp_path)).load_latest() is None


class TestShardedSave:
    def test_manifest_committed_last(self, tmp_path):
        root = str(tmp_path)
        mgr = CheckpointManager(root)
        mgr.save_shard(state_for(0), 5, rank=0, world_size=2)
        # rank 1 hasn't written: nothing visible yet
        assert CheckpointManager(root).load_latest() is None
        mgr.save_shard(state_for(1), 5, rank=1, world_size=2)
        assert CheckpointManager(root).load_latest() is None
        mgr.finalize_sharded(5, world_size=2)
        shards, step, manifest = CheckpointManager(root).load_latest()
        assert step == 5 and manifest["sharded"] and \
            manifest["world_size"] == 2
        assert_state(shards[0], 0)
        assert_state(shards[1], 1)
        # per-rank load
        assert_state(mgr.load(5, shard=1), 1)

    def test_partial_shard_write_never_visible(self, tmp_path):
        root = str(tmp_path)
        CheckpointManager(root).save(state_for(1), 1)
        mgr = CheckpointManager(root)
        mgr.save_shard(state_for(0), 2, rank=0, world_size=2)
        torn = CheckpointManager(root, fs=FaultyFS(partial_write_on=1))
        with pytest.raises(InjectedCrash):  # rank 1 dies mid-shard-write
            torn.save_shard(state_for(1), 2, rank=1, world_size=2)
        with pytest.raises(CheckpointCorruptError):
            mgr.finalize_sharded(2, world_size=2)
        found = CheckpointManager(root).load_latest()
        assert found[1] == 1  # falls back to the previous valid checkpoint

    def test_missing_shard_blocks_finalize(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save_shard(state_for(0), 0, rank=0, world_size=3)
        with pytest.raises(CheckpointCorruptError, match="shard 1 missing"):
            mgr.finalize_sharded(0, world_size=3)

    def test_group_sharded_checkpoint_wiring(self, tmp_path):
        from paddle_tpu.distributed.sharding import (
            save_group_sharded_checkpoint,
        )

        net = nn.Linear(2, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        barriers = []
        mgr = save_group_sharded_checkpoint(
            net, str(tmp_path), step=3, optimizer=opt, rank=0, world_size=1,
            barrier=lambda: barriers.append(1))
        assert barriers == [1]
        shards, step, manifest = mgr.load_latest()
        assert step == 3 and manifest["sharded"]
        np.testing.assert_allclose(shards[0]["model"]["weight"],
                                   net.weight.numpy())
        assert "optimizer" in shards[0]


class TestAtomicPaddleSave:
    def test_crash_mid_save_preserves_old_file(self, tmp_path):
        path = str(tmp_path / "m.pdparams")
        paddle.save({"a": np.arange(4)}, path)
        with pytest.raises(InjectedCrash):
            paddle.save({"a": np.arange(9)}, path,
                        fs=FaultyFS(crash_on_rename=1))
        np.testing.assert_array_equal(paddle.load(path)["a"], np.arange(4))

    def test_non_atomic_opt_out(self, tmp_path):
        path = str(tmp_path / "m.pdparams")
        paddle.save({"a": 1}, path, atomic=False)
        assert paddle.load(path) == {"a": 1}

    def test_missing_file_clear_error(self, tmp_path):
        missing = str(tmp_path / "nope.pdparams")
        with pytest.raises(CheckpointNotFoundError) as ei:
            paddle.load(missing)
        msg = str(ei.value)
        assert "nope.pdparams" in msg and "load_latest" in msg
        # compat: pre-existing handlers still catch it
        with pytest.raises(FileNotFoundError):
            paddle.load(missing)

    def test_truncated_file_clear_error(self, tmp_path):
        path = str(tmp_path / "t.pdparams")
        paddle.save({"w": np.ones((8, 8))}, path)
        open(path, "r+b").truncate(os.path.getsize(path) // 2)
        with pytest.raises(CheckpointCorruptError) as ei:
            paddle.load(path)
        msg = str(ei.value)
        assert "t.pdparams" in msg and "partial" in msg and \
            "load_latest" in msg


class TestNanGuard:
    def test_skip_and_rollback_actions(self):
        g = NanGuard(policy="skip_step")
        assert g.check(loss=1.0) == "ok"
        assert g.check(loss=float("nan")) == "skip_step"
        assert NanGuard(policy="rollback").check(loss=float("inf")) \
            == "rollback"

    def test_raise_policy(self):
        g = NanGuard(policy="raise")
        with pytest.raises(NanLossError):
            g.check(loss=float("nan"))

    def test_gradient_check(self):
        net = nn.Linear(2, 2)
        net.weight.grad = paddle.to_tensor(
            np.full((2, 2), np.inf, np.float32))
        g = NanGuard(policy="skip_step")
        assert g.check_gradients(net.parameters()) == "skip_step"

    def test_breaker_trips_regardless_of_policy(self):
        g = NanGuard(policy="skip_step", max_consecutive_bad=3)
        assert g.check(loss=float("nan")) == "skip_step"
        assert g.check(loss=float("nan")) == "skip_step"
        with pytest.raises(CircuitBreakerTripped):
            g.check(loss=float("nan"))

    def test_good_step_resets_breaker(self):
        g = NanGuard(policy="skip_step", max_consecutive_bad=3)
        for _ in range(4):
            assert g.check(loss=float("nan")) == "skip_step"
            assert g.check(loss=0.5) == "ok"
        assert g.consecutive_bad == 0 and g.total_bad == 4

    def test_scaler_skipped_steps_never_trip_breaker(self):
        g = NanGuard(policy="raise", max_consecutive_bad=2)
        for _ in range(6):
            assert g.check(loss=float("nan"), scaler_skipped=True) == "ok"
        assert g.consecutive_bad == 0

    def test_amp_scaler_interplay(self):
        """A real fp16 GradScaler skip (inf grads -> scale shrink, update
        skipped) sets last_step_skipped, and the guard treats the step as
        routine instead of advancing toward the breaker."""
        from paddle_tpu.amp import GradScaler

        net = nn.Linear(2, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        scaler = GradScaler(enable=True, init_loss_scaling=2.0 ** 10)
        guard = NanGuard(policy="raise", max_consecutive_bad=2)
        w0 = net.weight.numpy().copy()

        net.weight.grad = paddle.to_tensor(
            np.full((2, 2), np.inf, np.float32))
        scaler.step(opt)
        assert scaler.last_step_skipped
        np.testing.assert_array_equal(net.weight.numpy(), w0)  # no update
        # scaler-skipped: does not raise, does not advance the breaker
        assert guard.check(loss=float("nan"),
                           scaler_skipped=scaler.last_step_skipped) == "ok"
        assert guard.consecutive_bad == 0

        net.weight.grad = paddle.to_tensor(
            np.full((2, 2), float(scaler.get_init_loss_scaling()),
                    np.float32))
        scaler.step(opt)
        assert not scaler.last_step_skipped  # healthy step applied
        assert not np.allclose(net.weight.numpy(), w0)
        assert guard.check(loss=0.3,
                           scaler_skipped=scaler.last_step_skipped) == "ok"


class _PoisonDataset:
    """Good batches for `good` epochs' worth of steps, then NaN inputs."""

    def __init__(self, n=8, poison_from=None):
        rs = np.random.RandomState(0)
        self.x = rs.rand(n, 4).astype(np.float32)
        self.y = rs.rand(n, 1).astype(np.float32)
        self.poison_from = poison_from

    def __getitem__(self, i):
        x = self.x[i].copy()
        if self.poison_from is not None and i >= self.poison_from:
            x[:] = np.nan
        return x, self.y[i]

    def __len__(self):
        return len(self.x)


def _mse(pred, y):
    return ((pred - y) ** 2).mean()


class TestHapiNanGuard:
    @pytest.fixture(autouse=True)
    def _isolated_mesh(self, fresh_mesh):
        yield  # same isolation as TestRobustCheckpointCallback

    def _model(self):
        paddle.seed(0)
        net = nn.Linear(4, 1)
        m = paddle.Model(net)
        m.prepare(optim.SGD(learning_rate=0.05,
                            parameters=net.parameters()), loss=_mse)
        return m, net

    def test_fit_raise_policy_aborts(self):
        m, _ = self._model()
        with pytest.raises(NanLossError):
            m.fit(_PoisonDataset(poison_from=0), batch_size=4, epochs=1,
                  verbose=0, nan_guard="raise")

    def test_fit_skip_step_drops_poisoned_updates(self):
        m, net = self._model()
        w0 = net.weight.numpy().copy()
        m.fit(_PoisonDataset(poison_from=0), batch_size=4, epochs=1,
              verbose=0, nan_guard="skip_step")
        # every batch was poisoned -> every update skipped -> weights intact
        np.testing.assert_array_equal(net.weight.numpy(), w0)
        m.fit(_PoisonDataset(poison_from=None), batch_size=4, epochs=1,
              verbose=0, nan_guard="skip_step")
        assert not np.allclose(net.weight.numpy(), w0)  # good data trains

    def test_fit_rollback_restores_last_checkpoint(self, tmp_path):
        from paddle_tpu.hapi.callbacks import RobustCheckpoint

        m, net = self._model()
        ckpt = RobustCheckpoint(str(tmp_path), save_freq=1)
        # epoch 0: clean data, checkpoint lands at epoch end
        m.fit(_PoisonDataset(poison_from=None), batch_size=4, epochs=1,
              verbose=0, callbacks=[ckpt], nan_guard="rollback")
        saved = net.weight.numpy().copy()
        assert ckpt.last_saved_epoch == 0
        # poisoned run: every step rolls back to the epoch-0 checkpoint
        guard = NanGuard(policy="rollback", max_consecutive_bad=0)
        m.fit(_PoisonDataset(poison_from=0), batch_size=4, epochs=1,
              verbose=0, callbacks=[ckpt], nan_guard=guard)
        np.testing.assert_allclose(net.weight.numpy(), saved)

    def test_fit_breaker_aborts_diverged_run(self):
        m, _ = self._model()
        guard = NanGuard(policy="skip_step", max_consecutive_bad=2)
        with pytest.raises(CircuitBreakerTripped):
            m.fit(_PoisonDataset(poison_from=0), batch_size=4, epochs=1,
                  verbose=0, nan_guard=guard)

    def test_nan_guard_callback_monitors_logs(self, tmp_path):
        """The callback flavor (custom loops / static path): watches the
        loss log, scaler-skipped steps exempt."""
        from paddle_tpu.hapi.callbacks import NanGuardCallback

        cb = NanGuardCallback(policy="raise", max_consecutive_bad=5)
        cb.on_train_batch_end(0, {"loss": 0.5})
        with pytest.raises(NanLossError):
            cb.on_train_batch_end(1, {"loss": float("nan")})

        class _Scaler:
            last_step_skipped = True

        cb2 = NanGuardCallback(policy="raise", scaler=_Scaler())
        cb2.on_train_batch_end(0, {"loss": float("nan")})  # exempt


def test_no_ambient_mesh_leaked_into_this_module():
    """Regression pin (PR 15 satellite) for the order-dependent
    TestRobustCheckpointCallback failures first noted in PR 14: an
    earlier suite (test_observability's fleet-telemetry-knobs test)
    called fleet.init — which SETS the process-global hybrid mesh — and
    restored the fleet state but not the mesh, so Model.fit here tried
    to device_put its 4-row batches sharded over data=8 and both
    callback tests failed in full-suite order only (they pass alone:
    zero serving/observability code imported). The leak is fixed at the
    source (that test now restores the ambient mesh); this canary makes
    any future leak fail HERE with the real cause instead of as an
    inscrutable device_put error two classes later, and the callback
    tests below additionally isolate themselves via fresh_mesh."""
    from paddle_tpu.distributed import mesh as mesh_mod

    m = mesh_mod.get_mesh()
    assert m is None or m.size == 1, (
        f"ambient device mesh leaked into tier-1 by an earlier suite: "
        f"{m} — find the fleet.init/set_mesh caller missing a restore")


class TestRobustCheckpointCallback:
    @pytest.fixture(autouse=True)
    def _isolated_mesh(self, fresh_mesh):
        # single-device fit flows must not inherit ambient distributed
        # state (see test_no_ambient_mesh_leaked_into_this_module)
        yield

    def test_retention_and_optimizer_state(self, tmp_path):
        from paddle_tpu.hapi.callbacks import RobustCheckpoint

        paddle.seed(0)
        net = nn.Linear(4, 1)
        m = paddle.Model(net)
        m.prepare(optim.Adam(learning_rate=0.01,
                             parameters=net.parameters()), loss=_mse)
        ckpt = RobustCheckpoint(str(tmp_path), save_freq=1, keep_last_n=2)
        m.fit(_PoisonDataset(), batch_size=4, epochs=5, verbose=0,
              callbacks=[ckpt])
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.steps() == [3, 4]  # keep-last-2 retention
        payload, step, _ = mgr.load_latest()
        assert step == 4 and "optimizer" in payload
        np.testing.assert_allclose(payload["model"]["weight"],
                                   net.weight.numpy())

    def test_async_save_flushed_on_train_end(self, tmp_path):
        from paddle_tpu.hapi.callbacks import RobustCheckpoint

        net = nn.Linear(4, 1)
        m = paddle.Model(net)
        m.prepare(optim.SGD(learning_rate=0.01,
                            parameters=net.parameters()), loss=_mse)
        ckpt = RobustCheckpoint(str(tmp_path), save_freq=1, async_save=True)
        m.fit(_PoisonDataset(), batch_size=4, epochs=2, verbose=0,
              callbacks=[ckpt])
        assert CheckpointManager(str(tmp_path)).load_latest()[1] == 1


class TestHangDetector:
    def test_detects_stall_and_recovers(self):
        events = []
        hd = HangDetector(timeout=0.08, poll_interval=0.02,
                          on_hang=events.append)
        with hd:
            for _ in range(5):  # healthy phase: regular beats
                time.sleep(0.02)
                hd.beat()
            assert not hd.stalled and hd.hang_count == 0
            time.sleep(0.25)  # stalled step/collective
            assert hd.stalled and hd.hang_count == 1
            assert len(events) == 1 and events[0] > 0.08
            hd.beat()  # step completes: stall clears, detector re-arms
            assert not hd.stalled
            time.sleep(0.25)
            assert hd.hang_count == 2


class TestTrainEpochRangeRobust:
    def test_corrupt_newest_falls_back_to_previous_valid(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import TrainEpochRange

        net = nn.Linear(2, 2)
        r = TrainEpochRange(5, save_dir=str(tmp_path), job_id="j",
                            state={"model": net})
        for epoch in r:
            net.weight.set_value(np.full((2, 2), float(epoch), np.float32))
        # epochs 2,3,4 retained (keep_last_n=3); corrupt the newest
        newest = os.path.join(r.ckpt.step_path(4), "state.pdparams")
        open(newest, "r+b").truncate(8)
        net2 = nn.Linear(2, 2)
        r2 = TrainEpochRange(5, save_dir=str(tmp_path), job_id="j",
                             state={"model": net2})
        # resume from the newest VALID checkpoint (epoch 3), replay epoch 4
        assert r2.start_epoch == 4
        assert r2.restored_from == r2.ckpt.step_path(3)
        np.testing.assert_array_equal(net2.weight.numpy(),
                                      np.full((2, 2), 3.0))

    def test_crashed_save_attempt_leaves_resume_intact(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import TrainEpochRange

        net = nn.Linear(2, 2)
        r = TrainEpochRange(3, save_dir=str(tmp_path), job_id="j",
                            state={"model": net},
                            fs=FaultyFS(crash_on_rename=2))
        seen = []
        with pytest.raises(InjectedCrash):  # "process dies" saving epoch 1
            for epoch in r:
                seen.append(epoch)
                net.weight.set_value(np.full((2, 2), float(epoch),
                                             np.float32))
        assert seen == [0, 1]
        net2 = nn.Linear(2, 2)
        r2 = TrainEpochRange(3, save_dir=str(tmp_path), job_id="j",
                             state={"model": net2})
        assert r2.start_epoch == 1  # epoch 0 committed; epoch 1 replays
        np.testing.assert_array_equal(net2.weight.numpy(),
                                      np.full((2, 2), 0.0))

    def test_checker_env_gating(self, monkeypatch):
        from paddle_tpu.incubate.checkpoint import AutoCheckpointChecker

        for var in ("PADDLE_JOB_ID", "PADDLE_EDL_HDFS_HOME",
                    "PADDLE_RUNNING_ENV", "PADDLE_TPU_AUTO_CKPT_LOCAL"):
            monkeypatch.delenv(var, raising=False)
        assert not AutoCheckpointChecker().valid()  # bare env: gated OFF
        assert AutoCheckpointChecker().valid(local_mode=True)  # escape hatch
        monkeypatch.setenv("PADDLE_TPU_AUTO_CKPT_LOCAL", "1")
        assert AutoCheckpointChecker().valid()
        monkeypatch.delenv("PADDLE_TPU_AUTO_CKPT_LOCAL")
        monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
        assert not AutoCheckpointChecker().valid()  # still needs job + home
        monkeypatch.setenv("PADDLE_JOB_ID", "j1")
        monkeypatch.setenv("PADDLE_EDL_HDFS_HOME", "/edl")
        assert AutoCheckpointChecker().valid()


class TestTortureQuick:
    def test_quick_torture(self, tmp_path):
        """The <10s tier-1 slice of tools/ckpt_torture.py: random fault
        plans, zero corruption, zero lost steps."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from ckpt_torture import run_torture
        finally:
            sys.path.pop(0)
        summary = run_torture(iterations=25, root=str(tmp_path), seed=7)
        assert summary["ok"], summary["failures"]
        assert summary["commits"] > 0 and summary["crashes"] > 0
        assert summary["corrupt_visible"] == 0
        assert summary["lost_steps"] == 0

    def test_artifact_schema(self):
        """The committed run summary stays in sync with the harness."""
        path = os.path.join(REPO, "artifacts", "ckpt_torture.json")
        if not os.path.exists(path):
            pytest.skip("no recorded torture run")
        rec = json.load(open(path))
        assert rec["ok"] and rec["corrupt_visible"] == 0 and \
            rec["lost_steps"] == 0
        assert rec["crashes"] > 0


def test_threaded_beat_with_checkpoint_cycle(tmp_path):
    """Watchdog + checkpointing compose: a training loop that beats while
    async saves land keeps the detector quiet; a simulated wedge fires it."""
    mgr = CheckpointManager(str(tmp_path), fs=FaultyFS(slow_io=0.005))
    hd = HangDetector(timeout=0.2, poll_interval=0.02)
    with hd:
        for step in range(4):
            mgr.save_async(state_for(step), step)
            hd.beat()
            time.sleep(0.01)
        mgr.close()
        assert hd.hang_count == 0
        time.sleep(0.35)  # stalled collective
        assert hd.hang_count == 1
    assert mgr.load_latest()[1] == 3

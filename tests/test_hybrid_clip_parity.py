"""ClipGradByGlobalNorm parity under hybrid parallelism (VERDICT r4 #2).

HybridParallelOptimizer's claim (hybrid_parallel_optimizer.py docstring)
is that the inner clip is automatically GLOBAL because full logical grads
flow through the compiled step — unlike the reference, which implements an
explicit cross-group norm reduction
(fleet/meta_parallel/hybrid_parallel_optimizer.py:170 _dygraph_clip)
precisely because per-rank partial grads would make a local norm silently
wrong. These tests pin that claim: the post-clip UPDATE (parameter values
after one step) must match a single-device oracle under

  (a) mp2 tensor parallelism (column/row/vocab-parallel layers),
  (b) sharding2 ZeRO stage-3,
  (c) pipe2 1F1B (grad_fn compat path: grads come from the hand-scheduled
      pipeline, pre-reduced over pipe/data, THEN the TrainStep clips).

Each scenario also proves the clip actually engaged (clipped != unclipped)
so a dead clip can't fake parity.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)
from paddle_tpu.jit import TrainStep

rng = np.random.RandomState(42)
CLIP = 0.05  # far below typical first-step grad norms: always engages


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield


def _params(net):
    return {k: v.numpy().copy() for k, v in net.state_dict().items()}


def _update_rel_err(init, a, b):
    """max over params of |Δa − Δb|_inf / |Δb|_inf: relative error of the
    post-clip UPDATE against the oracle's update."""
    errs = []
    for k in init:
        da = np.asarray(a[k], np.float64) - np.asarray(init[k], np.float64)
        db = np.asarray(b[k], np.float64) - np.asarray(init[k], np.float64)
        scale = max(float(np.max(np.abs(db))), 1e-12)
        errs.append(float(np.max(np.abs(da - db))) / scale)
    return max(errs)


class MpNet(nn.Layer):
    def __init__(self, vocab=32, hidden=16):
        super().__init__()
        self.emb = VocabParallelEmbedding(vocab, hidden)
        self.col = ColumnParallelLinear(hidden, hidden * 2, gather_output=False)
        self.row = RowParallelLinear(hidden * 2, hidden, input_is_parallel=True)
        self.head = nn.Linear(hidden, vocab)

    def forward(self, ids):
        h = self.emb(ids)
        h = F.gelu(self.col(h))
        return self.head(self.row(h))


def _mp_loss(o, y):
    return F.cross_entropy(o.reshape([-1, 32]), y.reshape([-1]))


MP_IDS = rng.randint(0, 32, (8, 4)).astype(np.int64)
MP_LABELS = rng.randint(0, 32, (8, 4)).astype(np.int64)


def _one_step_mp(clip_norm, w0=None):
    """One clipped Adam step on MpNet under mp2 (or single-device when no
    mesh is configured via w0-replay)."""
    if w0 is None:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(11)
        net = fleet.distributed_model(MpNet())._layers
    else:
        mesh_mod._current[0] = None
        net = MpNet()
        net.set_state_dict(w0)
    clip = nn.ClipGradByGlobalNorm(clip_norm) if clip_norm else None
    # SGD: the update is LINEAR in the clipped grad, so any clip-semantics
    # error shows at full size (Adam's normalizer would hide it)
    o = optim.SGD(learning_rate=0.5, parameters=net.parameters(),
                  grad_clip=clip)
    step = TrainStep(net, _mp_loss, o)
    init = _params(net)
    step(inputs=(paddle.to_tensor(MP_IDS),),
         labels=(paddle.to_tensor(MP_LABELS),))
    return init, _params(net)


@pytest.mark.requires_vma_shard_map
def test_global_norm_clip_parity_mp2():
    w0, mp_clipped = _one_step_mp(CLIP)
    i0, single_clipped = _one_step_mp(CLIP, w0=w0)
    _, single_unclipped = _one_step_mp(None, w0=w0)
    # the clip changed the update (it engaged) ...
    assert _update_rel_err(i0, single_clipped, single_unclipped) > 0.5
    # ... and the dp4 x mp2 post-clip update matches the oracle
    err = _update_rel_err(w0, mp_clipped, single_clipped)
    # floor is f32 reduction-order noise (~4e-6 observed); a local-norm
    # clip bug would show as tens of percent (norm off by ~sqrt(mp))
    assert err <= 1e-5, f"mp2 post-clip update diverges: {err}"


def _one_step_sharding3(clip_norm, w0=None, x=None, y=None):
    if w0 is None:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 2}
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 3, "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
        fleet.distributed_model(net)
    else:
        mesh_mod._current[0] = None
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
        net.set_state_dict(w0)
    clip = nn.ClipGradByGlobalNorm(clip_norm) if clip_norm else None
    o = optim.SGD(learning_rate=0.5, parameters=net.parameters(),
                  grad_clip=clip)
    o._slot_shard_axis = "sharding"
    step = TrainStep(net, lambda o_, y_: F.mse_loss(o_, y_), o)
    init = _params(net)
    step(inputs=(paddle.to_tensor(x),), labels=(paddle.to_tensor(y),))
    return init, _params(net)


def test_global_norm_clip_parity_sharding2_stage3():
    x = rng.rand(8, 16).astype(np.float32)
    y = rng.rand(8, 8).astype(np.float32) * 4.0  # big targets: big grads
    w0, sh_clipped = _one_step_sharding3(CLIP, x=x, y=y)
    i0, single_clipped = _one_step_sharding3(CLIP, w0=w0, x=x, y=y)
    _, single_unclipped = _one_step_sharding3(None, w0=w0, x=x, y=y)
    assert _update_rel_err(i0, single_clipped, single_unclipped) > 0.5
    err = _update_rel_err(w0, sh_clipped, single_clipped)
    # same f32 reduction-order floor as the mp2 case
    assert err <= 1e-5, f"sharding2/stage3 post-clip update diverges: {err}"


@pytest.mark.requires_vma_shard_map
def test_global_norm_clip_parity_pipe2_1f1b():
    """The 1F1B compat path: grads reach _apply_clip from the pipeline
    grad_fn. pipeline_1f1b pre-reduces them (psum over pipe for the owning
    stage, pmean over data), so the clip's norm is over FULL logical grads
    here too — this pins it against the single-device oracle."""
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
        gpt_1f1b_train_step,
    )

    rs = np.random.RandomState(3)
    b, s = 8, 16
    cfg_kw = dict(mode="scan", use_flash_attention=False)
    ids_np = rs.randint(0, 128, (b, s))
    lbl_np = rs.randint(0, 128, (b, s))

    def run_single(clip_norm):
        mesh_mod.set_mesh(None)
        model = GPTForCausalLM(gpt_presets("gpt-test", **cfg_kw), seed=0)
        crit = GPTPretrainingCriterion()
        clip = nn.ClipGradByGlobalNorm(clip_norm) if clip_norm else None
        o = optim.SGD(learning_rate=0.1, parameters=model.parameters(),
                      grad_clip=clip)
        step = TrainStep(model, lambda lg, lb: crit(lg, lb), o)
        init = _params(model)
        step(inputs=(paddle.to_tensor(ids_np, dtype="int64"),),
             labels=(paddle.to_tensor(lbl_np, dtype="int64"),))
        return init, _params(model)

    def run_1f1b(clip_norm):
        mesh = mesh_mod.build_mesh({"pipe": 2, "model": 2, "data": 2},
                                   devices=jax.devices()[:8])
        mesh_mod.set_mesh(mesh)
        model = GPTForCausalLM(
            gpt_presets("gpt-test", pp_microbatches=4, **cfg_kw), seed=0)
        clip = nn.ClipGradByGlobalNorm(clip_norm) if clip_norm else None
        o = optim.SGD(learning_rate=0.1, parameters=model.parameters(),
                      grad_clip=clip)
        step = gpt_1f1b_train_step(model, o)
        init = _params(model)
        step(inputs=(paddle.to_tensor(ids_np, dtype="int64"),),
             labels=(paddle.to_tensor(lbl_np, dtype="int64"),))
        return init, _params(model)

    clip_norm = 0.5
    i0, single_clipped = run_single(clip_norm)
    _, single_unclipped = run_single(None)
    assert _update_rel_err(i0, single_clipped, single_unclipped) > 0.5
    w0, pp_clipped = run_1f1b(clip_norm)
    err = _update_rel_err(w0, pp_clipped, single_clipped)
    # the pipeline schedule accumulates micro-batch grads in a different
    # order than the sequential oracle, so the floor is that f32
    # accumulation noise, not clip semantics; a per-stage-local norm
    # would be off by ~sqrt(pipe) ≈ 40%
    assert err <= 1e-4, f"1F1B post-clip update diverges: {err}"

"""dygraph→static control-flow conversion (jit/dy2static.py).

Reference capability: dygraph_to_static/*_transformer.py — `if/while/for`
over Tensors become cond/while ops so ONE compiled program covers every
branch. The acid test: a to_static function whose branch depends on input
DATA must return different branches for different inputs (trace-only
conversion would bake one branch and silently return it for everything).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import transform_function


def test_tensor_if_both_branches_work_eagerly():
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y

    g = transform_function(f)
    assert g is not f, "transform should have applied"
    pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(g(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(g(neg).numpy(), [-2.0, -3.0])


def test_to_static_data_dependent_branch():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y

    pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    # same compiled executable (same shapes) must take BOTH branches
    np.testing.assert_allclose(f(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(f(neg).numpy(), [-2.0, -3.0])


def test_python_bool_condition_stays_python():
    calls = []

    def f(x, flag=True):
        if flag:
            calls.append("taken")
            y = x + 1
        else:
            y = x - 1
        return y

    g = transform_function(f)
    out = g(paddle.to_tensor(np.array([1.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0])
    assert calls == ["taken"]


def test_tensor_while_loop():
    def f(x):
        s = paddle.to_tensor(np.array(0.0, np.float32))
        while s < x:
            s = s + 2.0
        return s

    g = transform_function(f)
    out = g(paddle.to_tensor(np.array(5.0, np.float32)))
    assert float(out) == 6.0


def test_to_static_while_data_dependent_count():
    @paddle.jit.to_static
    def f(x):
        s = x * 0.0
        n = x * 0.0
        while s.sum() < x.sum():
            s = s + 1.0
            n = n + 1.0
        return n

    three = paddle.to_tensor(np.array([3.0], np.float32))
    seven = paddle.to_tensor(np.array([7.0], np.float32))
    assert float(f(three).numpy()[0]) == 3.0
    assert float(f(seven).numpy()[0]) == 7.0


def test_for_range_converts():
    def f(x, n):
        acc = x * 0.0
        for i in range(n):
            acc = acc + x
        return acc

    g = transform_function(f)
    assert g is not f
    x = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(g(x, 4).numpy(), [8.0])


def test_grad_flows_through_converted_if():
    import paddle_tpu.nn as nn

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.scale = self.create_parameter(
                shape=[1], default_initializer=paddle.nn.initializer.Constant(2.0))

        def forward(self, x):
            if x.sum() > 0:
                y = x * self.scale * 3.0
            else:
                y = x * self.scale * 5.0
            return y.sum()

    net = paddle.jit.to_static(Net())
    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    out = net(x)
    out.backward()
    # d out / d scale = sum(x * 3) = 6 on the positive branch
    np.testing.assert_allclose(net.scale.grad.numpy(), [6.0])

    net.scale.grad = None
    xn = paddle.to_tensor(np.array([-1.0, -1.0], np.float32))
    net(xn).backward()
    np.testing.assert_allclose(net.scale.grad.numpy(), [-10.0])


def test_return_inside_branch_falls_back():
    def f(x):
        if x.sum() > 0:
            return x * 2
        return x - 1

    g = transform_function(f)
    # jump inside branch: unconverted (trace-only fallback keeps semantics
    # for eager use)
    out = g(paddle.to_tensor(np.array([1.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0])


def test_nested_if_inside_while():
    def f(x):
        s = x * 0.0
        i = x * 0.0
        while i.sum() < 4.0:
            if i.sum() > 1.0:
                s = s + 2.0
            else:
                s = s + 1.0
            i = i + 1.0
        return s

    g = transform_function(f)
    out = g(paddle.to_tensor(np.array([0.0], np.float32)))
    # i=0:+1, i=1:+1, i=2:+2, i=3:+2 → 6
    assert float(out.numpy()[0]) == 6.0

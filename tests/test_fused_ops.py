"""Fused incubate.nn ops (reference: operators/fused/*.cu APIs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import nn as inn


def test_fused_feedforward_matches_composition():
    rs = np.random.RandomState(0)
    h, f = 8, 16
    x = paddle.to_tensor(rs.randn(2, 3, h).astype("f4"))
    w1 = paddle.to_tensor(rs.randn(h, f).astype("f4") * 0.1)
    w2 = paddle.to_tensor(rs.randn(f, h).astype("f4") * 0.1)
    ln_s = paddle.to_tensor(np.ones(h, "f4"))
    ln_b = paddle.to_tensor(np.zeros(h, "f4"))
    out = inn.functional.fused_feedforward(
        x, w1, w2, ln2_scale=ln_s, ln2_bias=ln_b, activation="relu")
    # reference composition
    z = np.maximum(x.numpy() @ w1.numpy(), 0) @ w2.numpy() + x.numpy()
    mu = z.mean(-1, keepdims=True)
    var = z.var(-1, keepdims=True)
    ref = (z - mu) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_fused_mha_runs_and_grads():
    rs = np.random.RandomState(1)
    h, n = 8, 2
    layer = inn.FusedMultiHeadAttention(h, n, normalize_before=True)
    x = paddle.to_tensor(rs.randn(2, 4, h).astype("f4"))
    x.stop_gradient = False
    out = layer(x)
    assert tuple(out.shape) == (2, 4, h)
    out.sum().backward()
    assert layer.qkv_weight.grad is not None
    assert x.grad is not None


def test_fused_feedforward_layer_trains():
    import paddle_tpu.optimizer as opt

    rs = np.random.RandomState(2)
    layer = inn.FusedFeedForward(8, 16, activation="gelu")
    o = opt.SGD(learning_rate=0.05, parameters=layer.parameters())
    x = paddle.to_tensor(rs.randn(4, 3, 8).astype("f4"))
    y = paddle.to_tensor(rs.randn(4, 3, 8).astype("f4"))
    losses = []
    for _ in range(5):
        loss = ((layer(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_fused_linear_activation():
    rs = np.random.RandomState(3)
    x = paddle.to_tensor(rs.randn(2, 4).astype("f4"))
    w = paddle.to_tensor(rs.randn(4, 3).astype("f4"))
    b = paddle.to_tensor(rs.randn(3).astype("f4"))
    out = inn.functional.fused_linear_activation(x, w, b, activation="relu")
    ref = np.maximum(x.numpy() @ w.numpy() + b.numpy(), 0)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

"""PS async/geo communicators + Wide&Deep e2e (BASELINE config 5).

Reference: communicator.h AsyncCommunicator(:402) / GeoCommunicator(:566);
the e2e bar is AUC parity between the PS sparse-embedding path and a pure
dense-embedding run on the same synthetic CTR task.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as popt
from paddle_tpu.distributed.ps import LocalPs, TheOnePSRuntime, distributed_lookup_table
from paddle_tpu.distributed.ps.communicator import (
    AsyncCommunicator, Communicator, GeoCommunicator,
)


class RecordingClient:
    """Captures push RPCs; serves zeros for pulls."""

    def __init__(self, dim=4):
        self.dim = dim
        self.pushes = []

    def pull(self, table_id, keys):
        return np.zeros((np.asarray(keys).size, self.dim), np.float32)

    def push(self, table_id, keys, grads, lr=-1.0):
        self.pushes.append((table_id, np.asarray(keys).copy(),
                            np.asarray(grads).copy()))

    def assign(self, table_id, keys, values):
        pass


def test_async_merges_pending_pushes():
    c = RecordingClient()
    comm = AsyncCommunicator(c, max_merge_var_num=10, send_wait_times=0.01)
    comm.start()
    for _ in range(5):
        comm.push_sparse(0, np.array([1, 2, 1], np.uint64),
                         np.ones((3, 4), np.float32))
    comm.flush()
    comm.stop()
    total_rpcs = len(c.pushes)
    assert total_rpcs < 5  # merged: fewer RPCs than pushes
    # every key's total gradient is preserved through the merge
    acc = {}
    for _, keys, grads in c.pushes:
        for k, g in zip(keys.tolist(), grads):
            acc[k] = acc.get(k, 0) + g.sum()
    assert acc[1] == pytest.approx(5 * 2 * 4)  # key 1 twice per push, dim 4
    assert acc[2] == pytest.approx(5 * 1 * 4)


def test_async_error_surfaces_on_flush():
    class Exploding(RecordingClient):
        def push(self, *a, **k):
            raise IOError("server gone")

    comm = AsyncCommunicator(Exploding(), send_wait_times=0.01)
    comm.start()
    comm.push_sparse(0, np.array([1], np.uint64), np.ones((1, 4), np.float32))
    with pytest.raises(IOError):
        comm.flush()
        comm.stop()


def test_geo_local_training_and_delta_sync():
    ps = LocalPs()
    ps.create_table(0, dim=2, init_range=0.0)  # zero-init rows
    comm = GeoCommunicator(ps, k_steps=3)
    comm.start()
    keys = np.array([5, 9], np.uint64)
    # two local steps: PS must NOT move yet
    for _ in range(2):
        rows = comm.pull_sparse(0, keys)
        comm.push_sparse(0, keys, np.ones((2, 2), np.float32), lr=0.1)
    np.testing.assert_allclose(ps.pull(0, keys), 0.0)
    # third step triggers the geo sync: deltas land on the PS
    comm.push_sparse(0, keys, np.ones((2, 2), np.float32), lr=0.1)
    np.testing.assert_allclose(ps.pull(0, keys), -0.3, atol=1e-6)
    # local replica re-synced to the PS values
    np.testing.assert_allclose(comm.pull_sparse(0, keys), -0.3, atol=1e-6)


# ---------------------------------------------------------------------------
# Wide&Deep e2e: PS sparse path vs dense run, AUC parity (BASELINE config 5)
# ---------------------------------------------------------------------------

VOCAB, SLOTS, STEPS, BATCH = 100, 8, 60, 64


def _ctr_data(seed=0):
    rs = np.random.RandomState(seed)
    true_w = rs.randn(VOCAB).astype("float32")
    ids = rs.randint(0, VOCAB, (STEPS * BATCH + 512, SLOTS))
    logits = true_w[ids].sum(1)
    labels = (logits > 0).astype("float32")
    return ids, labels


def _auc(scores, labels):
    m = paddle.metric.Auc()
    probs = np.stack([1 - scores, scores], axis=1)
    m.update(probs, labels[:, None])
    return m.accumulate()


def _run_dense(ids, labels):
    emb = nn.Embedding(VOCAB, 1, sparse=True)
    # small init, matching the PS table's init_range=0.01
    emb.weight.set_value(
        (np.random.RandomState(7).randn(VOCAB, 1) * 0.01).astype("float32"))
    bias = paddle.to_tensor(np.zeros((1,), np.float32))
    bias.stop_gradient = False
    o = popt.SGD(learning_rate=0.2,
                 parameters=list(emb.parameters()) + [bias])
    for s in range(STEPS):
        bidx = slice(s * BATCH, (s + 1) * BATCH)
        x = paddle.to_tensor(ids[bidx], dtype="int64")
        y = paddle.to_tensor(labels[bidx])
        logit = emb(x).sum(axis=[1, 2]) + bias
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(logit, y)
        loss.backward()
        o.step()
        o.clear_grad()
    test = paddle.to_tensor(ids[STEPS * BATCH:], dtype="int64")
    scores = paddle.nn.functional.sigmoid(
        emb(test).sum(axis=[1, 2]) + bias).numpy()
    return _auc(scores, labels[STEPS * BATCH:])


def _run_ps(ids, labels, strategy_mode):
    runtime = TheOnePSRuntime()  # fresh runtime (becomes current)
    ps = LocalPs()
    ps.create_table(0, dim=1, init_range=0.01, lr=0.2)
    runtime.client = ps
    if strategy_mode == "async":
        runtime.communicator = AsyncCommunicator(ps, max_merge_var_num=4,
                                                 send_wait_times=0.002)
    elif strategy_mode == "geo":
        runtime.communicator = GeoCommunicator(ps, k_steps=5)
    else:
        runtime.communicator = Communicator(ps)
    runtime.communicator.start()

    bias = paddle.to_tensor(np.zeros((1,), np.float32))
    bias.stop_gradient = False
    o = popt.SGD(learning_rate=0.2, parameters=[bias])
    for s in range(STEPS):
        bidx = slice(s * BATCH, (s + 1) * BATCH)
        rows = distributed_lookup_table(
            paddle.to_tensor(ids[bidx], dtype="int64"), table_id=0, lr=0.2)
        y = paddle.to_tensor(labels[bidx])
        logit = rows.sum(axis=[1, 2]) + bias
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(logit, y)
        loss.backward()
        o.step()
        o.clear_grad()
    runtime.communicator.flush()
    with paddle.no_grad():
        test_rows = distributed_lookup_table(
            paddle.to_tensor(ids[STEPS * BATCH:], dtype="int64"), table_id=0)
        scores = paddle.nn.functional.sigmoid(
            test_rows.sum(axis=[1, 2]) + bias).numpy()
    runtime.communicator.stop()
    TheOnePSRuntime._current = None
    return _auc(scores, labels[STEPS * BATCH:])


@pytest.mark.parametrize("mode", ["sync", "async", "geo"])
def test_wide_deep_auc_parity(mode):
    ids, labels = _ctr_data()
    dense_auc = _run_dense(ids, labels)
    ps_auc = _run_ps(ids, labels, mode)
    assert dense_auc > 0.85, dense_auc  # the task is learnable
    assert ps_auc > dense_auc - 0.06, (mode, dense_auc, ps_auc)


# ---------------------------------------------------------------------------
# dense tables (reference: ps/table/memory_dense_table.cc)
# ---------------------------------------------------------------------------

def test_dense_table_local():
    ps = LocalPs()
    ps.create_dense_table(7, (4, 3), opt="sgd", lr=0.5)
    np.testing.assert_allclose(ps.pull_dense(7), 0.0)
    ps.push_dense(7, np.ones((4, 3)), lr=0.5)
    np.testing.assert_allclose(ps.pull_dense(7), -0.5)
    ps.assign_dense(7, np.full((4, 3), 2.0))
    np.testing.assert_allclose(ps.pull_dense(7), 2.0)


def test_dense_table_over_tcp():
    from paddle_tpu.distributed.ps import PsClient, PsServer

    srv = PsServer().start()
    try:
        cli = PsClient([srv.endpoint])
        cli.create_dense_table(1, (2, 2), opt="adagrad", lr=0.1)
        cli.push_dense(1, np.ones((2, 2)))
        v1 = cli.pull_dense(1)
        assert (v1 < 0).all()
        cli.push_dense(1, np.ones((2, 2)))
        v2 = cli.pull_dense(1)
        # adagrad: second step smaller than first
        assert (np.abs(v2 - v1) < np.abs(v1)).all()
        cli.close()
    finally:
        srv.stop()


def test_geo_two_workers_over_tcp_converge():
    """VERDICT r3 item 5 tail: e2e geo sync with TWO workers over the real
    TCP PS protocol — each worker trains locally, deltas from both land
    additively on the PS, and both replicas converge to the merged rows
    after their sync rounds."""
    import threading

    from paddle_tpu.distributed.ps import PsClient, PsServer

    server = PsServer().start()
    try:
        boot = PsClient([server.endpoint])
        boot.create_table(0, dim=2, optimizer="sgd", init_range=0.0)
        boot.close()

        shared = np.array([1, 2], np.uint64)       # both workers touch these
        own = {0: np.array([10], np.uint64), 1: np.array([20], np.uint64)}
        comms = {}
        errs = []

        adds = {0: [], 1: []}

        def worker(rank):
            try:
                client = PsClient([server.endpoint])
                orig_add = client.add

                def logged_add(t, keys, deltas):
                    adds[rank].append((np.asarray(keys).tolist(),
                                       float(np.asarray(deltas).sum())))
                    return orig_add(t, keys, deltas)

                client.add = logged_add
                comm = GeoCommunicator(client, k_steps=4)
                comm.start()
                comms[rank] = comm
                keys = np.concatenate([shared, own[rank]])
                for _ in range(8):                 # 8 steps = 2 sync rounds
                    comm.pull_sparse(0, keys)
                    comm.push_sparse(0, keys,
                                     np.ones((keys.size, 2), np.float32),
                                     lr=0.1)
            except Exception as e:                 # surface thread failures
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs

        check = PsClient([server.endpoint])
        # each worker contributed -0.1 * 8 = -0.8 per dim; shared keys got
        # BOTH workers' deltas (geo addition), own keys exactly one's
        np.testing.assert_allclose(check.pull(0, shared), -1.6, atol=1e-5,
                                   err_msg=f"adds={adds}")
        np.testing.assert_allclose(check.pull(0, own[0]), -0.8, atol=1e-5)
        np.testing.assert_allclose(check.pull(0, own[1]), -0.8, atol=1e-5)
        # after one more sync round each replica converges to the PS rows
        for rank in (0, 1):
            comms[rank].flush()
            np.testing.assert_allclose(
                comms[rank].pull_sparse(0, shared),
                check.pull(0, shared), atol=1e-5)
            comms[rank].stop()
        check.close()
    finally:
        server.stop()

"""Native core + PS subsystem tests.

Reference analogs: table tests under distributed/ps/table, brpc service
tests, test_dist_base.py's real-subprocess pserver pattern (here: real TCP
server threads), reader blocking-queue tests.
"""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.table import BlockingQueue, SparseTable
from paddle_tpu.distributed.ps import (
    LocalPs, PsClient, PsServer, TheOnePSRuntime, distributed_lookup_table,
)


class TestSparseTable:
    def test_pull_initializes_deterministically(self):
        t = SparseTable(dim=8, seed=3)
        a = t.pull([1, 2, 3])
        b = t.pull([3, 2, 1])
        np.testing.assert_allclose(a[0], b[2])
        np.testing.assert_allclose(a[2], b[0])
        assert len(t) == 3
        assert np.abs(a).max() <= 0.01 + 1e-7

    def test_sgd_push(self):
        t = SparseTable(dim=4, optimizer="sgd", lr=0.5, init_range=0.0)
        before = t.pull([7])
        g = np.ones((1, 4), np.float32)
        t.push([7], g)
        after = t.pull([7])
        np.testing.assert_allclose(after, before - 0.5 * g, rtol=1e-6)

    def test_adagrad_push(self):
        t = SparseTable(dim=2, optimizer="adagrad", lr=1.0, init_range=0.0,
                        aux=0.0)
        t.push([1], np.array([[2.0, 4.0]], np.float32))
        # adagrad: G=g^2, update = lr*g/sqrt(G) = sign(g)
        after = t.pull([1])
        np.testing.assert_allclose(after, [[-1.0, -1.0]], atol=1e-5)

    def test_assign_and_keys(self):
        t = SparseTable(dim=3)
        t.assign([10, 20], np.arange(6, dtype=np.float32).reshape(2, 3))
        np.testing.assert_allclose(t.pull([20])[0], [3, 4, 5])
        assert set(t.keys().tolist()) == {10, 20}

    def test_save_load_roundtrip(self, tmp_path):
        t = SparseTable(dim=4, seed=1)
        vals = t.pull(np.arange(100))
        path = str(tmp_path / "table.bin")
        t.save(path)
        t2 = SparseTable(dim=4, seed=999)
        t2.load(path)
        assert len(t2) == 100
        np.testing.assert_allclose(t2.pull(np.arange(100),
                                           create_if_missing=False), vals)

    def test_concurrent_push(self):
        t = SparseTable(dim=4, optimizer="sgd", lr=1.0, init_range=0.0)
        keys = np.arange(64, dtype=np.uint64)
        g = np.ones((64, 4), np.float32)

        def worker():
            for _ in range(50):
                t.push(keys, g)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        # 4 threads x 50 pushes x grad 1.0 with lr 1.0 → every weight -200
        np.testing.assert_allclose(t.pull(keys),
                                   np.full((64, 4), -200.0), rtol=1e-5)


class TestBlockingQueue:
    def test_fifo_roundtrip(self):
        q = BlockingQueue(8)
        q.push({"a": np.arange(5)})
        q.push([1, 2, 3])
        out1 = q.pop()
        np.testing.assert_array_equal(out1["a"], np.arange(5))
        assert q.pop() == [1, 2, 3]

    def test_capacity_blocks_and_timeout(self):
        q = BlockingQueue(1)
        q.push(1)
        with pytest.raises(TimeoutError):
            q.push(2, timeout_ms=50)

    def test_close_drains(self):
        q = BlockingQueue(4)
        q.push("x")
        q.close()
        assert q.pop() == "x"
        assert q.pop() is None  # closed & drained

    def test_producer_consumer_threads(self):
        q = BlockingQueue(4)
        got = []

        def producer():
            for i in range(100):
                q.push(i)
            q.close()

        def consumer():
            while True:
                item = q.pop()
                if item is None:
                    return
                got.append(item)

        tp = threading.Thread(target=producer)
        tc = threading.Thread(target=consumer)
        tp.start(); tc.start()
        tp.join(); tc.join()
        assert got == list(range(100))


class TestPsService:
    def test_two_server_shard_pull_push(self):
        s1 = PsServer().start()
        s2 = PsServer().start()
        try:
            c = PsClient([s1.endpoint, s2.endpoint])
            c.create_table(0, dim=4, optimizer="sgd", lr=1.0, init_range=0.0)
            keys = np.arange(32, dtype=np.uint64)
            rows = c.pull(0, keys)
            assert rows.shape == (32, 4)
            np.testing.assert_allclose(rows, 0.0)
            c.push(0, keys, np.ones((32, 4), np.float32))
            np.testing.assert_allclose(c.pull(0, keys), -1.0)
            # both shards hold some keys
            assert c.table_size(0) == 32
            assert len(s1.tables[0]) > 0 and len(s2.tables[0]) > 0
            c.close()
        finally:
            s1.stop()
            s2.stop()

    def test_save_load_via_rpc(self, tmp_path):
        s = PsServer().start()
        try:
            c = PsClient([s.endpoint])
            c.create_table(1, dim=2, init_range=0.0)
            c.push(1, [5], np.ones((1, 2), np.float32))
            c.save(1, str(tmp_path / "t"))
            c2 = PsClient([s.endpoint])
            c2.create_table(2, dim=2, init_range=0.0)
            # verify file exists per shard
            assert os.path.exists(str(tmp_path / "t.shard0"))
            c.close(); c2.close()
        finally:
            s.stop()

    def test_lookup_op_pushes_grads_on_backward(self):
        rt = TheOnePSRuntime()
        rt.client = LocalPs()
        rt.client.create_table(0, dim=4, optimizer="sgd", lr=1.0,
                               init_range=0.0)
        TheOnePSRuntime._current = rt

        ids = paddle.to_tensor(np.array([[1, 2], [3, 1]], dtype="int64"))
        emb = distributed_lookup_table(ids, table_id=0)
        assert tuple(emb.shape) == (2, 2, 4)
        loss = (emb * 2.0).sum()
        loss.backward()
        # each occurrence pushes grad 2.0; key 1 appears twice → -4, rest -2
        rows = rt.client.pull(0, [1, 2, 3])
        np.testing.assert_allclose(rows[0], np.full(4, -4.0), rtol=1e-6)
        np.testing.assert_allclose(rows[1], np.full(4, -2.0), rtol=1e-6)
        np.testing.assert_allclose(rows[2], np.full(4, -2.0), rtol=1e-6)

    def test_fleet_ps_facade(self):
        import paddle_tpu.distributed.fleet as fleet

        ep = fleet.init_server()
        client = fleet.init_worker([ep])
        client.create_table(0, dim=2, init_range=0.0)
        rows = client.pull(0, [42])
        np.testing.assert_allclose(rows, 0.0)
        fleet.stop_worker()
        from paddle_tpu.distributed.ps import TheOnePSRuntime as R

        R.current().server.stop()
        R._current = None


class TestDataLoaderNativeQueue:
    def test_dataloader_uses_native_buffer(self):
        import paddle_tpu.io as io

        class DS(io.Dataset):
            def __getitem__(self, i):
                return np.full((3,), i, dtype="float32"), np.int64(i)

            def __len__(self):
                return 10

        paddle.set_flags({"FLAGS_use_native_dataloader_queue": True})
        try:
            dl = io.DataLoader(DS(), batch_size=4, num_workers=2,
                               use_shared_memory=True)
            assert dl._use_native_queue
        finally:
            paddle.set_flags({"FLAGS_use_native_dataloader_queue": False})
        seen = []
        for xb, yb in dl:
            seen.append(xb.shape[0])
        assert sum(seen) == 10

    def test_dataloader_native_early_break(self):
        import paddle_tpu.io as io

        class DS(io.Dataset):
            def __getitem__(self, i):
                return np.zeros(2, dtype="float32")

            def __len__(self):
                return 1000

        paddle.set_flags({"FLAGS_use_native_dataloader_queue": True})
        try:
            dl = io.DataLoader(DS(), batch_size=2, num_workers=1,
                               use_shared_memory=True)
            assert dl._use_native_queue
            for i, batch in enumerate(dl):
                if i == 3:
                    break  # must not deadlock the producer
        finally:
            paddle.set_flags({"FLAGS_use_native_dataloader_queue": False})


class TestSsdTier:
    """SSD overflow tier (reference ps/table/ssd_sparse_table.cc over
    rocksdb; here a log-structured spill file + offset index behind the
    same pull/push ABI)."""

    def _mk(self, tmp_path, **kw):
        from paddle_tpu.core.table import SparseTable

        return SparseTable(dim=4, shard_bits=2, optimizer="adagrad",
                           lr=0.1, ssd_path=str(tmp_path / "tier.log"), **kw)

    def test_spill_and_fault_in_roundtrip(self, tmp_path):
        t = self._mk(tmp_path)
        keys = np.arange(100, dtype=np.uint64)
        before = t.pull(keys).copy()
        evicted = t.spill(20)
        assert evicted == 80
        assert t.mem_rows() <= 20
        assert t.ssd_rows() >= 80
        assert len(t) == 100  # union view unchanged
        # pulls transparently fault disk rows back in, values intact
        after = t.pull(keys)
        np.testing.assert_array_equal(before, after)
        assert t.mem_rows() == 100

    def test_push_to_spilled_key_resumes_optimizer_state(self, tmp_path):
        from paddle_tpu.core.table import SparseTable

        ctrl = SparseTable(dim=4, optimizer="adagrad", lr=0.1)
        t = self._mk(tmp_path)
        keys = np.arange(10, dtype=np.uint64)
        g = np.full((10, 4), 0.5, np.float32)
        for tab in (ctrl, t):
            tab.pull(keys)
            tab.push(keys, g)
        t.spill(0)  # everything to disk
        assert t.mem_rows() == 0
        # second push must fault rows in WITH their adagrad accumulators
        ctrl.push(keys, g)
        t.push(keys, g)
        np.testing.assert_allclose(t.pull(keys), ctrl.pull(keys), rtol=1e-6)

    def test_save_includes_disk_rows(self, tmp_path):
        from paddle_tpu.core.table import SparseTable

        t = self._mk(tmp_path)
        keys = np.arange(50, dtype=np.uint64)
        vals = t.pull(keys).copy()
        t.spill(10)
        t.save(str(tmp_path / "ckpt.bin"))
        t2 = SparseTable(dim=4)
        t2.load(str(tmp_path / "ckpt.bin"))
        assert len(t2) == 50
        np.testing.assert_array_equal(t2.pull(keys, create_if_missing=False),
                                      vals)

    def test_shrink_covers_disk_rows_and_compact_reclaims(self, tmp_path):
        t = self._mk(tmp_path)
        keys = np.arange(40, dtype=np.uint64)
        t.pull(keys)
        t.add_show(keys[:10], 100.0)  # hot rows survive shrink
        t.spill(0)
        dropped = t.shrink(decay=0.5, threshold=1.0)
        assert dropped == 30
        assert len(t) == 10
        # shrink re-appended survivors; compact rewrites the log to 10 rows
        assert t.ssd_compact() == 10
        got = t.pull(keys[:10], create_if_missing=False)
        assert np.abs(got).sum() > 0  # survivors still readable

    def test_auto_spill_with_budget(self, tmp_path):
        t = self._mk(tmp_path, mem_budget_rows=32)
        g = np.full((1, 4), 0.1, np.float32)
        for i in range(200):
            k = np.asarray([i], dtype=np.uint64)
            t.pull(k)
            t.push(k, g)
        assert len(t) == 200
        assert t.mem_rows() < 200  # budget enforced by auto-spill
        assert t.ssd_rows() > 0

    def test_fault_in_drops_disk_record_no_resurrection(self, tmp_path):
        """A row spilled, faulted back, trained further, then shrunk from
        memory must NOT come back from its stale disk record."""
        t = self._mk(tmp_path)
        k = np.array([7], np.uint64)
        t.pull(k)
        t.add_show(k, 10.0)
        t.spill(0)
        t.pull(k)                      # fault back in (disk record dropped)
        assert t.ssd_rows() == 0
        t.push(k, np.full((1, 4), 0.5, np.float32))
        trained = t.pull(k).copy()
        dropped = t.shrink(decay=0.0, threshold=1.0)  # evict from memory
        assert dropped == 1
        assert len(t) == 0             # gone from BOTH tiers
        fresh = t.pull(k)              # re-initialized, not resurrected
        assert not np.allclose(fresh, trained)

    def test_add_show_reaches_spilled_rows(self, tmp_path):
        t = self._mk(tmp_path)
        k = np.array([3], np.uint64)
        t.pull(k)
        t.spill(0)
        t.add_show(k, 50.0)            # impression on a disk-resident row
        assert t.shrink(decay=0.9, threshold=1.0) == 0  # stays hot
        assert len(t) == 1

    def test_assign_over_spilled_rows_preserves_stats(self, tmp_path):
        # assign (broadcast/init overwrite) on a disk-resident row must
        # fault it in, not create a fresh show=0 row + drop the disk
        # record — otherwise shrink later evicts genuinely hot rows and
        # eviction depends on which tier a row happened to be on
        t = self._mk(tmp_path)
        keys = np.arange(100, dtype=np.uint64)
        t.pull(keys)
        t.add_show(keys, 5.0)
        assert t.spill(20) == 80
        t.assign(keys, np.ones((100, t.dim), np.float32))
        assert np.allclose(t.pull(keys, create_if_missing=False), 1.0)
        # decayed show = 4.5 > threshold 2.0 for ALL rows iff stats survived
        assert t.shrink(decay=0.9, threshold=2.0) == 0
        assert len(t.keys()) == 100

    def test_load_over_spilled_rows_preserves_stats(self, tmp_path):
        t = self._mk(tmp_path)
        keys = np.arange(50, dtype=np.uint64)
        saved_vals = t.pull(keys).copy()
        t.save(str(tmp_path / "ckpt.bin"))
        t.push(keys, np.ones((50, t.dim), np.float32))  # diverge post-save
        t.add_show(keys, 5.0)
        assert t.spill(10) == 40
        t.load(str(tmp_path / "ckpt.bin"))
        # checkpoint values land in every row (incl. the faulted-in 40) ...
        assert np.allclose(t.pull(keys, create_if_missing=False), saved_vals)
        # ... and live show stats survive tier-independently
        assert t.shrink(decay=0.9, threshold=2.0) == 0
        assert len(t.keys()) == 50

    def test_pull_driven_budget_enforced(self, tmp_path):
        t = self._mk(tmp_path, mem_budget_rows=16)
        all_keys = np.arange(128, dtype=np.uint64)
        t.pull(all_keys)
        t.spill(16)
        # an eval sweep pulling everything must not grow memory unboundedly
        for i in range(0, 128):
            t.pull(np.asarray([i], np.uint64))
        assert t.mem_rows() <= 16 * 1.25 + 64  # budget + check cadence slack


class TestHeterDeviceCache:
    """Heter-PS device cache (heter_ps/ps_gpu_wrapper.cc analog): one bulk
    pull per pass, in-pass lookups are device gathers, one merged push."""

    def _ps(self):
        from paddle_tpu.distributed.ps import LocalPs

        ps = LocalPs()
        ps.create_table(0, dim=4, init_range=0.1, lr=1.0, optimizer="sgd")
        return ps

    def test_pass_lifecycle_and_merged_push(self):
        from paddle_tpu.distributed.ps.heter_cache import DevicePassCache

        ps = self._ps()
        cache = DevicePassCache(ps, 0, lr=1.0)
        ids = np.array([3, 5, 9], np.uint64)
        base = ps.pull(0, ids).copy()
        cache.begin_pass(ids)
        np.testing.assert_allclose(np.asarray(cache.lookup(ids)), base,
                                   rtol=1e-6)
        # two batches push grads for overlapping keys; device-side merge
        cache.push_grads(np.array([3, 5], np.uint64),
                         np.ones((2, 4), np.float32))
        cache.push_grads(np.array([5, 9], np.uint64),
                         np.ones((2, 4), np.float32))
        assert cache.pulls == 1
        cache.end_pass()
        # sgd lr=1: row3 -=1, row5 -=2, row9 -=1 (summed grads, one update)
        got = ps.pull(0, ids)
        np.testing.assert_allclose(got, base - np.array([[1.], [2.], [1.]]),
                                   rtol=1e-5)

    def test_lookup_is_jittable_via_slots(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distributed.ps.heter_cache import DevicePassCache

        ps = self._ps()
        cache = DevicePassCache(ps, 0)
        ids = np.arange(8, dtype=np.uint64)
        cache.begin_pass(ids)
        slots = cache.slots(np.array([[1, 3], [5, 7]], np.uint64))

        @jax.jit
        def step(rows, slot_idx):
            return jnp.take(rows, slot_idx, axis=0).sum()

        out = step(cache._rows, jnp.asarray(slots))
        ref = ps.pull(0, np.array([1, 3, 5, 7], np.uint64)).sum()
        np.testing.assert_allclose(float(out), ref, rtol=1e-5)

    def test_out_of_working_set_id_raises(self):
        from paddle_tpu.distributed.ps.heter_cache import DevicePassCache

        ps = self._ps()
        cache = DevicePassCache(ps, 0)
        cache.begin_pass(np.array([1, 2], np.uint64))
        with pytest.raises(KeyError, match="working set"):
            cache.lookup(np.array([99], np.uint64))


class TestHeterPassTrainer:
    """VERDICT r3 next #5: DevicePassCache wired into a trainer loop the
    way PSGPUTrainer drives it (trainer.h:249, ps_gpu_wrapper.cc
    BuildGPUTask): train_from_dataset-style pass lifecycle, AUC parity vs
    the per-step host-callback path, and pull/push count assertions."""

    VOCAB, SLOTS, DIM = 400, 8, 8
    BATCH, ROWS = 64, 640

    class _CountingPs:
        def __init__(self, ps):
            self._ps = ps
            self.pulls = 0
            self.pushes = 0

        def pull(self, *a, **k):
            self.pulls += 1
            return self._ps.pull(*a, **k)

        def push(self, *a, **k):
            self.pushes += 1
            return self._ps.push(*a, **k)

        def __getattr__(self, n):
            return getattr(self._ps, n)

    def _dataset(self, tmp_path, rs):
        from paddle_tpu.distributed.fleet.dataset import InMemoryDataset

        true_w = rs.randn(self.VOCAB)
        path = tmp_path / "ctr.txt"
        with open(path, "w") as f:
            for _ in range(self.ROWS):
                ids = rs.randint(0, self.VOCAB, self.SLOTS)
                label = int(true_w[ids].sum() > 0)
                f.write(" ".join(map(str, ids)) + f" {label}\n")
        ds = InMemoryDataset()
        ds.init(batch_size=self.BATCH,
                parse_fn=lambda line: [int(t) for t in line.split()])
        ds.set_filelist([str(path)])
        ds.load_into_memory()
        return ds

    def _model(self, seed):
        import paddle_tpu as paddle

        paddle.seed(seed)
        deep = paddle.nn.Sequential(
            paddle.nn.Linear(self.DIM * self.SLOTS, 32),
            paddle.nn.ReLU(), paddle.nn.Linear(32, 1))
        optim = paddle.optimizer.Adam(learning_rate=5e-3,
                                      parameters=deep.parameters())
        return deep, optim

    def _split(self, batch):
        ids = np.hstack([np.asarray(c) for c in batch[:self.SLOTS]])
        labels = np.asarray(batch[self.SLOTS]).reshape(-1).astype("float32")
        return ids.astype(np.uint64), labels

    def _dense_step(self, deep, optim, rows, labels):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        logit = deep(rows.reshape([labels.shape[0], -1]))[:, 0]
        loss = F.binary_cross_entropy_with_logits(
            logit, paddle.to_tensor(labels))
        loss.backward()
        optim.step()
        optim.clear_grad()
        return float(loss)

    def _auc(self, deep, lookup, dataset):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.metric import Auc

        auc = Auc()
        with paddle.no_grad():
            for batch in dataset.iterate():
                ids, labels = self._split(batch)
                rows = lookup(ids)
                logit = deep(rows.reshape([labels.shape[0], -1]))[:, 0]
                prob = F.sigmoid(logit).numpy()
                auc.update(np.stack([1.0 - prob, prob], axis=1),
                           labels[:, None])
        return float(auc.accumulate())

    def _make_ps(self):
        from paddle_tpu.distributed.ps import LocalPs

        ps = LocalPs()
        ps.create_table(0, dim=self.DIM, init_range=0.01, lr=0.1,
                        optimizer="adagrad")
        return ps

    def test_pass_trainer_auc_parity_and_io_counts(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.ps import (
            HeterPassTrainer, distributed_lookup_table, heter_embedding,
        )

        rs = np.random.RandomState(0)
        ds = self._dataset(tmp_path, rs)
        n_batches = self.ROWS // self.BATCH
        passes = 4

        # ---- baseline: per-step host-callback path ----
        ps_a = self._CountingPs(self._make_ps())
        deep_a, optim_a = self._model(seed=7)

        for _ in range(passes):
            for batch in ds.iterate():
                ids, labels = self._split(batch)
                rows = distributed_lookup_table(
                    paddle.to_tensor(ids.astype("int64")), table_id=0,
                    client=ps_a, lr=0.1)
                self._dense_step(deep_a, optim_a, rows, labels)
        # one pull + one push per STEP
        assert ps_a.pulls == passes * n_batches, ps_a.pulls
        assert ps_a.pushes == passes * n_batches, ps_a.pushes
        auc_a = self._auc(
            deep_a,
            lambda ids: distributed_lookup_table(
                paddle.to_tensor(ids.astype("int64")), table_id=0,
                client=ps_a, lr=0.0),
            ds)

        # ---- heter pass trainer: bulk pull / merged push per PASS ----
        ps_b = self._CountingPs(self._make_ps())
        deep_b, optim_b = self._model(seed=7)
        trainer = HeterPassTrainer(ps_b, table_id=0, lr=0.1,
                                   sparse_slots=tuple(range(self.SLOTS)))

        def step(cache, batch):
            ids, labels = self._split(batch)
            rows = heter_embedding(cache, ids)
            return self._dense_step(deep_b, optim_b, rows, labels)

        losses = trainer.train_from_dataset(ds, step, passes=passes)
        assert np.all(np.isfinite(losses))
        # ONE bulk pull + ONE merged push per PASS — the entire point
        assert trainer.cache.pulls == passes, trainer.cache.pulls
        assert trainer.cache.pushes == passes, trainer.cache.pushes
        assert ps_b.pulls == passes and ps_b.pushes == passes

        def heter_eval_lookup(ids):
            cache = trainer.cache
            cache.begin_pass(ids)
            try:
                return cache.lookup(ids)
            finally:
                cache.end_pass()

        auc_b = self._auc(deep_b, heter_eval_lookup, ds)

        # both learn, and the merged-update path tracks the per-step path
        assert auc_a > 0.85, auc_a
        assert auc_b > 0.85, auc_b
        assert abs(auc_a - auc_b) < 0.05, (auc_a, auc_b)


class TestSsdConcurrentReads:
    """VERDICT r3 next #8: faults now pread under a SHARED lock — hammer
    the disk tier from several threads (pulls of spilled rows racing a
    re-spill and a compaction) and check every returned row is exact."""

    def test_threaded_faults_race_spill_and_compact(self):
        from concurrent.futures import ThreadPoolExecutor

        from paddle_tpu.core.table import SparseTable

        import tempfile
        import os

        dim, rows = 4, 20_000
        table = SparseTable(dim=dim, shard_bits=4, optimizer="sgd",
                            init_range=0.0, lr=1.0, seed=1)
        table.enable_ssd(os.path.join(tempfile.mkdtemp(), "spill.log"))
        keys = np.arange(rows, dtype=np.uint64)
        # give every row a known value: emb = key * [1,2,3,4] via assign
        vals = (keys[:, None] * (np.arange(dim) + 1)[None, :]).astype(
            np.float32)
        table.assign(keys, vals)
        table.spill(rows // 10)          # 90% to disk

        errs = []

        def storm(seed):
            r = np.random.RandomState(seed)
            for _ in range(20):
                ks = r.randint(0, rows, 512).astype(np.uint64)
                got = table.pull(ks)
                want = (ks[:, None] * (np.arange(dim) + 1)[None, :])
                if not np.allclose(got, want):
                    errs.append((ks, got))

        def churn():
            for _ in range(10):
                table.spill(rows // 10)  # re-evict faulted rows
                table.ssd_compact()

        with ThreadPoolExecutor(5) as ex:
            futs = [ex.submit(storm, s) for s in range(4)]
            futs.append(ex.submit(churn))
            for f in futs:
                f.result()
        assert not errs, errs[0]
        # nothing lost across the churn
        assert table.mem_rows() + table.ssd_rows() == rows

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(3)


def T(*shape, sg=True):
    return paddle.to_tensor(rng.rand(*shape).astype(np.float32), stop_gradient=sg)


class TestLayerBase:
    def test_registration(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)
                self.w = self.create_parameter([2, 2])
                self.register_buffer("buf", paddle.zeros([1]))

            def forward(self, x):
                return self.fc(x)

        net = Net()
        names = dict(net.named_parameters())
        assert set(names) == {"w", "fc.weight", "fc.bias"}
        assert len(net.sublayers()) == 1
        assert "buf" in net.state_dict()
        assert len(net.state_dict()) == 4

    def test_train_eval_propagates(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        net.eval()
        assert all(not l.training for l in net.sublayers())
        net.train()
        assert all(l.training for l in net.sublayers())

    def test_state_dict_roundtrip(self):
        net1 = nn.Linear(4, 3)
        net2 = nn.Linear(4, 3)
        net2.set_state_dict(net1.state_dict())
        x = T(2, 4)
        np.testing.assert_allclose(net1(x).numpy(), net2(x).numpy(), rtol=1e-6)

    def test_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        h = net.register_forward_post_hook(lambda l, i, o: calls.append(1))
        net(T(1, 2))
        h.remove()
        net(T(1, 2))
        assert len(calls) == 1

    def test_containers(self):
        seq = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
        assert seq(T(4, 2)).shape == [4, 1]
        assert len(seq) == 3
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3 and len(list(ll.parameters())) == 6
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        pl = nn.ParameterList([paddle.nn.Parameter(np.zeros((2, 2), np.float32))])
        assert len(list(pl.parameters())) == 1

    def test_to_dtype(self):
        net = nn.Linear(2, 2)
        net.to(dtype="bfloat16")
        import jax.numpy as jnp

        assert net.weight.dtype == jnp.bfloat16


class TestLayers:
    def test_linear(self):
        l = nn.Linear(8, 16)
        assert l.weight.shape == [8, 16]
        out = l(T(4, 8))
        assert out.shape == [4, 16]
        ref = T(4, 8).numpy() @ l.weight.numpy() + l.bias.numpy()

    def test_conv2d_shape_and_value(self):
        conv = nn.Conv2D(3, 8, 3, padding=1)
        assert conv(T(2, 3, 16, 16)).shape == [2, 8, 16, 16]
        conv2 = nn.Conv2D(3, 8, 3, stride=2)
        assert conv2(T(2, 3, 16, 16)).shape == [2, 8, 7, 7]
        # value check vs manual correlation on 1x1 kernel
        c = nn.Conv2D(2, 4, 1, bias_attr=False)
        x = T(1, 2, 5, 5)
        out = c(x).numpy()
        w = c.weight.numpy()  # [4,2,1,1]
        ref = np.einsum("nchw,oc->nohw", x.numpy(), w[:, :, 0, 0])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_conv_groups_dilation(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        assert conv(T(1, 4, 8, 8)).shape == [1, 8, 8, 8]
        conv = nn.Conv2D(2, 2, 3, dilation=2)
        assert conv(T(1, 2, 9, 9)).shape == [1, 2, 5, 5]

    def test_conv_transpose(self):
        deconv = nn.Conv2DTranspose(4, 2, 2, stride=2)
        assert deconv(T(1, 4, 8, 8)).shape == [1, 2, 16, 16]

    def test_pools(self):
        x = T(2, 3, 8, 8)
        assert nn.MaxPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AvgPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 3, 1, 1]
        np.testing.assert_allclose(
            nn.AdaptiveAvgPool2D(1)(x).numpy()[..., 0, 0], x.numpy().mean((2, 3)), rtol=1e-5
        )
        assert nn.AdaptiveAvgPool2D(3)(x).shape == [2, 3, 3, 3]  # non-divisible

    def test_batchnorm_stats(self):
        bn = nn.BatchNorm2D(3, momentum=0.9)
        x = paddle.to_tensor(rng.randn(8, 3, 4, 4).astype(np.float32) * 2 + 5)
        bn.train()
        out = bn(x)
        # normalized output ~ zero mean unit var
        o = out.numpy()
        assert abs(o.mean()) < 1e-4 and abs(o.std() - 1) < 1e-2
        # running stats moved toward batch stats
        assert bn._mean.numpy().mean() > 0.3
        bn.eval()
        out2 = bn(x)
        assert not np.allclose(out2.numpy(), o)

    def test_layernorm(self):
        ln = nn.LayerNorm(16)
        x = T(4, 16)
        o = ln(x).numpy()
        np.testing.assert_allclose(o.mean(-1), np.zeros(4), atol=1e-5)
        np.testing.assert_allclose(o.std(-1), np.ones(4), atol=1e-2)

    def test_groupnorm_instancenorm(self):
        assert nn.GroupNorm(2, 4)(T(2, 4, 5, 5)).shape == [2, 4, 5, 5]
        assert nn.InstanceNorm2D(3)(T(2, 3, 5, 5)).shape == [2, 3, 5, 5]

    def test_dropout_modes(self):
        d = nn.Dropout(0.5)
        x = paddle.ones([1000])
        d.train()
        y = d(x).numpy()
        assert (y == 0).mean() > 0.3
        assert abs(y.mean() - 1.0) < 0.15  # upscale_in_train preserves expectation
        d.eval()
        np.testing.assert_array_equal(d(x).numpy(), x.numpy())

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([[1, 2], [0, 3]]))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        assert np.all(out.numpy()[1, 0] == 0)  # padding_idx row is zero

    def test_embedding_grad(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([1, 1, 2]))
        emb(ids).sum().backward()
        g = emb.weight.grad.numpy()
        assert g[1].sum() == pytest.approx(8.0)
        assert g[2].sum() == pytest.approx(4.0)

    def test_activations_layers(self):
        x = T(3, 4)
        for cls in [nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh, nn.Silu, nn.Hardswish,
                    nn.Softmax, nn.LogSoftmax, nn.LeakyReLU, nn.ELU]:
            assert cls()(x).shape == [3, 4]
        assert nn.PReLU(4)(x).shape == [3, 4]

    def test_flatten_pad_upsample(self):
        assert nn.Flatten()(T(2, 3, 4)).shape == [2, 12]
        assert F.pad(T(1, 1, 4, 4), [1, 1, 2, 2]).shape == [1, 1, 8, 6]
        assert nn.Upsample(scale_factor=2)(T(1, 2, 4, 4)).shape == [1, 2, 8, 8]

    def test_losses(self):
        logits, labels = T(8, 5), paddle.to_tensor(rng.randint(0, 5, 8))
        l = nn.CrossEntropyLoss()(logits, labels)
        assert l.shape == []
        ref = -np.log(
            np.exp(logits.numpy())[np.arange(8), labels.numpy()]
            / np.exp(logits.numpy()).sum(-1)
        ).mean()
        np.testing.assert_allclose(l.numpy(), ref, rtol=1e-5)
        assert nn.MSELoss()(T(4, 3), T(4, 3)).shape == []
        assert nn.L1Loss(reduction="none")(T(4, 3), T(4, 3)).shape == [4, 3]
        p = F.sigmoid(T(6, 1))
        assert nn.BCELoss()(p, paddle.to_tensor((rng.rand(6, 1) > 0.5).astype(np.float32))).shape == []

    def test_cross_entropy_ignore_index(self):
        logits = T(4, 3)
        labels = paddle.to_tensor(np.array([0, 1, -100, 2]))
        l = F.cross_entropy(logits, labels, ignore_index=-100)
        keep = F.cross_entropy(logits[np.array([0, 1, 3])], labels[np.array([0, 1, 3])])
        np.testing.assert_allclose(l.numpy(), keep.numpy(), rtol=1e-5)

    def test_soft_label_ce(self):
        logits = T(4, 5)
        soft = rng.rand(4, 5).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        l = F.cross_entropy(logits, paddle.to_tensor(soft), soft_label=True)
        assert l.shape == []


def test_functional_tail_vs_torch():
    """grid_sample/affine_grid/pixel_unshuffle/channel_shuffle/max_unpool2d
    + loss tail (reference functional surface), validated against torch."""
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 5, 6).astype("f4")
    grid = (rs.rand(2, 4, 4, 2).astype("f4") * 2 - 1)
    np.testing.assert_allclose(
        F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                      align_corners=True).numpy(),
        torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(grid), align_corners=True).numpy(),
        rtol=1e-4, atol=1e-5)
    theta = rs.randn(2, 2, 3).astype("f4")
    np.testing.assert_allclose(
        F.affine_grid(paddle.to_tensor(theta), [2, 3, 4, 5],
                      align_corners=True).numpy(),
        torch.nn.functional.affine_grid(
            torch.tensor(theta), (2, 3, 4, 5), align_corners=True).numpy(),
        rtol=1e-4, atol=1e-5)
    y = rs.randn(1, 4, 6, 6).astype("f4")
    np.testing.assert_allclose(
        F.pixel_unshuffle(F.pixel_shuffle(paddle.to_tensor(y), 2),
                          2).numpy(), y)
    a = rs.randn(6, 5).astype("f4")
    lbl = np.sign(rs.randn(6, 5)).astype("f4")
    np.testing.assert_allclose(
        float(F.soft_margin_loss(paddle.to_tensor(a),
                                 paddle.to_tensor(lbl))),
        float(torch.nn.functional.soft_margin_loss(
            torch.tensor(a), torch.tensor(lbl))), rtol=1e-5)
    y_int = rs.randint(0, 5, 6)
    np.testing.assert_allclose(
        float(F.multi_margin_loss(paddle.to_tensor(a),
                                  paddle.to_tensor(y_int))),
        float(torch.nn.functional.multi_margin_loss(
            torch.tensor(a), torch.tensor(y_int))), rtol=1e-5)


def test_adaptive_log_softmax_with_loss():
    rs = np.random.RandomState(0)
    m = nn.AdaptiveLogSoftmaxWithLoss(16, 20, cutoffs=[5, 12], div_value=2.0)
    x = paddle.to_tensor(rs.randn(6, 16).astype("f4"))
    y = paddle.to_tensor(rs.randint(0, 20, 6))
    out, loss = m(x, y)
    lp = m.log_prob(x).numpy()
    np.testing.assert_allclose(np.exp(lp).sum(-1), 1.0, rtol=1e-4)
    np.testing.assert_allclose(
        float(loss), float(np.mean(-lp[np.arange(6), y.numpy()])), rtol=1e-5)
    loss.backward()
    assert m.head_weight.grad is not None
    assert tuple(m.predict(x).shape) == (6,)

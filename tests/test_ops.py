"""Per-op forward + gradient checks on the OpTest-style harness."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_forward, check_grad

rng = np.random.RandomState(7)


def A(*shape):
    return rng.rand(*shape).astype(np.float32) + 0.1


class TestElementwiseForward:
    @pytest.mark.parametrize(
        "op,ref",
        [
            (paddle.add, np.add),
            (paddle.subtract, np.subtract),
            (paddle.multiply, np.multiply),
            (paddle.divide, np.divide),
            (paddle.maximum, np.maximum),
            (paddle.minimum, np.minimum),
            (paddle.pow, np.power),
            (paddle.atan2, np.arctan2),
        ],
    )
    def test_binary(self, op, ref):
        check_forward(op, ref, [A(3, 4), A(3, 4)])

    def test_broadcast(self):
        check_forward(paddle.add, np.add, [A(3, 1, 4), A(2, 4)])

    @pytest.mark.parametrize(
        "op,ref",
        [
            (paddle.exp, np.exp),
            (paddle.log, np.log),
            (paddle.sqrt, np.sqrt),
            (paddle.tanh, np.tanh),
            (paddle.sin, np.sin),
            (paddle.cos, np.cos),
            (paddle.abs, np.abs),
            (paddle.floor, np.floor),
            (paddle.ceil, np.ceil),
            (paddle.square, np.square),
            (paddle.sign, np.sign),
            (paddle.log1p, np.log1p),
            (paddle.expm1, np.expm1),
        ],
    )
    def test_unary(self, op, ref):
        check_forward(op, ref, [A(4, 5)], rtol=1e-5)

    def test_clip_round_reciprocal(self):
        check_forward(paddle.clip, lambda v: np.clip(v, 0.2, 0.8), [A(10)], min=0.2, max=0.8)
        check_forward(paddle.reciprocal, lambda v: 1.0 / v, [A(5)])


class TestReductionForward:
    def test_sum_mean_max_min(self):
        a = A(3, 4, 5)
        check_forward(paddle.sum, lambda v: v.sum(), [a])
        check_forward(paddle.sum, lambda v: v.sum(axis=1), [a], axis=1)
        check_forward(paddle.sum, lambda v: v.sum(axis=(0, 2), keepdims=True), [a],
                      axis=[0, 2], keepdim=True)
        check_forward(paddle.mean, lambda v: v.mean(axis=-1), [a], axis=-1)
        check_forward(paddle.max, lambda v: v.max(axis=0), [a], axis=0)
        check_forward(paddle.min, lambda v: v.min(), [a])
        check_forward(paddle.prod, lambda v: v.prod(axis=2), [a], axis=2)

    def test_std_var_logsumexp(self):
        a = A(6, 7)
        check_forward(paddle.std, lambda v: v.std(ddof=1), [a], rtol=1e-4)
        check_forward(paddle.var, lambda v: v.var(ddof=1, axis=1), [a], axis=1, rtol=1e-4)
        from scipy.special import logsumexp as np_lse

        check_forward(paddle.logsumexp, lambda v: np_lse(v, axis=1), [a], axis=1, rtol=1e-5)

    def test_cumsum_cumprod(self):
        a = A(3, 4)
        check_forward(paddle.cumsum, lambda v: v.cumsum(axis=1), [a], axis=1)
        check_forward(paddle.cumprod, lambda v: v.cumprod(axis=0), [a], dim=0)

    def test_argmax_argsort(self):
        a = A(4, 5)
        check_forward(paddle.argmax, lambda v: v.argmax(axis=1), [a], axis=1)
        check_forward(paddle.argsort, lambda v: v.argsort(axis=-1), [a])


class TestLinalgForward:
    def test_matmul_shapes(self):
        check_forward(paddle.matmul, np.matmul, [A(3, 4), A(4, 5)])
        check_forward(paddle.matmul, np.matmul, [A(2, 3, 4), A(2, 4, 5)])
        check_forward(
            paddle.matmul, lambda a, b: a.T @ b, [A(4, 3), A(4, 5)], transpose_x=True
        )

    def test_norm_inv_solve(self):
        a = A(4, 4) + np.eye(4, dtype=np.float32) * 3
        check_forward(paddle.inv, np.linalg.inv, [a], rtol=1e-4)
        b = A(4, 2)
        check_forward(paddle.solve, np.linalg.solve, [a, b], rtol=1e-4)
        check_forward(paddle.norm, lambda v: np.linalg.norm(v), [A(3, 3)], rtol=1e-5)

    def test_einsum(self):
        check_forward(
            lambda a, b: paddle.einsum("ij,jk->ik", a, b),
            lambda a, b: np.einsum("ij,jk->ik", a, b),
            [A(3, 4), A(4, 5)],
            rtol=1e-5,
        )


class TestGrads:
    """Analytic (tape) vs numeric finite-difference gradients — the core
    contract of the reference OpTest.check_grad."""

    def test_elementwise_grads(self):
        check_grad(paddle.multiply, [A(3, 4), A(3, 4)])
        check_grad(paddle.divide, [A(3, 4), A(3, 4) + 0.5])
        check_grad(paddle.tanh, [A(4, 4)])
        check_grad(paddle.exp, [A(3, 3)])
        check_grad(paddle.sqrt, [A(3, 3) + 0.5])

    def test_broadcast_grad(self):
        check_grad(paddle.add, [A(3, 1, 4), A(2, 4)])
        check_grad(paddle.multiply, [A(4, 1), A(1, 5)])

    def test_matmul_grad(self):
        check_grad(paddle.matmul, [A(3, 4), A(4, 5)])

    def test_reduce_grads(self):
        check_grad(paddle.sum, [A(3, 4)], axis=1)
        check_grad(paddle.mean, [A(3, 4)])
        check_grad(paddle.max, [A(3, 4)], axis=1)

    def test_softmax_grad(self):
        import paddle_tpu.nn.functional as F

        # weight the outputs: sum(softmax) is constant, which would make the
        # gradient identically zero and the check vacuous
        w = paddle.to_tensor(rng.rand(4, 6).astype(np.float32))
        check_grad(F.softmax, [A(4, 6)], reduce_fn=lambda o: (o * w).sum())
        check_grad(F.log_softmax, [A(4, 6)], reduce_fn=lambda o: (o * w).sum())

    def test_manipulation_grads(self):
        check_grad(paddle.reshape, [A(3, 4)], shape=[4, 3])
        check_grad(paddle.transpose, [A(3, 4)], perm=[1, 0])
        check_grad(lambda x: paddle.concat([x, x], axis=0), [A(2, 3)])
        check_grad(lambda x: x[1:, :2], [A(3, 4)])

    def test_loss_grads(self):
        import paddle_tpu.nn.functional as F

        logits = A(8, 5)
        labels = rng.randint(0, 5, 8).astype(np.int64)

        def ce(x):
            return F.cross_entropy(x, paddle.to_tensor(labels))

        check_grad(ce, [logits], reduce_fn=lambda o: o)
        check_grad(F.mse_loss, [A(4, 3), A(4, 3)], grad_idx=[0], reduce_fn=lambda o: o)


class TestActivationsForward:
    def test_against_numpy(self):
        import paddle_tpu.nn.functional as F

        x = (rng.rand(5, 6).astype(np.float32) - 0.5) * 4
        np.testing.assert_allclose(
            F.relu(paddle.to_tensor(x)).numpy(), np.maximum(x, 0), rtol=1e-6
        )
        np.testing.assert_allclose(
            F.sigmoid(paddle.to_tensor(x)).numpy(), 1 / (1 + np.exp(-x)), rtol=1e-5
        )
        sm = F.softmax(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(sm.sum(-1), np.ones(5), rtol=1e-5)
        np.testing.assert_allclose(
            F.leaky_relu(paddle.to_tensor(x), 0.1).numpy(),
            np.where(x >= 0, x, 0.1 * x),
            rtol=1e-6,
        )


class TestRandomOps:
    def test_seed_determinism(self):
        paddle.seed(123)
        a = paddle.rand([4, 4]).numpy()
        paddle.seed(123)
        b = paddle.rand([4, 4]).numpy()
        np.testing.assert_array_equal(a, b)
        c = paddle.rand([4, 4]).numpy()
        assert not np.array_equal(b, c)

    def test_distributions_sane(self):
        paddle.seed(0)
        u = paddle.uniform([10000], min=2.0, max=4.0).numpy()
        assert 2.9 < u.mean() < 3.1 and u.min() >= 2.0 and u.max() <= 4.0
        n = paddle.normal(1.0, 2.0, [10000]).numpy()
        assert 0.9 < n.mean() < 1.1 and 1.9 < n.std() < 2.1
        r = paddle.randint(0, 10, [1000]).numpy()
        assert r.min() >= 0 and r.max() < 10
        p = paddle.randperm(100).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(100))


def test_add_n_and_grad():
    a = paddle.to_tensor(np.ones((2, 3), np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.full((2, 3), 2.0, np.float32), stop_gradient=False)
    out = paddle.add_n([a, b, a])
    np.testing.assert_allclose(out.numpy(), np.full((2, 3), 4.0))
    out.sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), np.full((2, 3), 2.0))
    np.testing.assert_allclose(b.grad.numpy(), np.ones((2, 3)))


def test_multiplex_row_select():
    i1 = np.array([[1, 2], [3, 4]], np.float32)
    i2 = np.array([[5, 6], [7, 8]], np.float32)
    idx = np.array([[1], [0]], np.int32)
    out = paddle.multiplex([paddle.to_tensor(i1), paddle.to_tensor(i2)],
                           paddle.to_tensor(idx))
    np.testing.assert_allclose(out.numpy(), [[5, 6], [3, 4]])


def test_shard_index_semantics():
    """Reference shard_index_op: in-shard labels -> local offset, others ->
    ignore_value."""
    lbl = paddle.to_tensor(np.array([[1], [6], [12], [19]], np.int64))
    out = paddle.shard_index(lbl, index_num=20, nshards=2, shard_id=0)
    np.testing.assert_array_equal(out.numpy(), [[1], [6], [-1], [-1]])
    out1 = paddle.shard_index(lbl, index_num=20, nshards=2, shard_id=1)
    np.testing.assert_array_equal(out1.numpy(), [[-1], [-1], [2], [9]])
    import pytest as _pytest
    with _pytest.raises(ValueError):
        paddle.shard_index(lbl, index_num=20, nshards=2, shard_id=2)


def test_reverse_diagonal_tanh_inplace():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
    np.testing.assert_allclose(paddle.reverse(x, 0).numpy(),
                               [[3., 4.], [1., 2.]])
    np.testing.assert_allclose(paddle.diagonal(x).numpy(), [1., 4.])
    y = paddle.to_tensor(np.zeros(3, np.float32))
    r = paddle.tanh_(y)
    assert r is y
    np.testing.assert_allclose(y.numpy(), np.zeros(3))


def test_create_parameter_and_check_shape():
    p = paddle.create_parameter([4, 8], "float32")
    assert type(p).__name__ == "Parameter" and not p.stop_gradient
    assert p.shape == [4, 8] or tuple(p.shape) == (4, 8)
    b = paddle.create_parameter([8], "float32", is_bias=True)
    np.testing.assert_allclose(b.numpy(), np.zeros(8))
    paddle.check_shape([2, -1, 3])
    import pytest as _pytest
    with _pytest.raises(ValueError):
        paddle.check_shape([-1, -1])
    paddle.disable_signal_handler()  # supported no-op


def test_create_parameter_honors_param_attr():
    from paddle_tpu import ParamAttr
    from paddle_tpu.nn.initializer import Constant

    frozen = paddle.create_parameter(
        [2, 2], "float32", attr=ParamAttr(trainable=False,
                                          initializer=Constant(5.0),
                                          name="frozen_w"))
    assert frozen.stop_gradient
    assert frozen.name == "frozen_w"
    np.testing.assert_allclose(frozen.numpy(), np.full((2, 2), 5.0))
    named = paddle.create_parameter([2], "float32", name="plain_w")
    assert named.name == "plain_w"

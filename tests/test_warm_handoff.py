"""Warm-handoff replica replacement (ISSUE 19).

Contracts pinned here:
- ``ServingEngine.warm``: replays a bucket ledger through the model's
  jitted entry points (already-seen buckets skipped), flips the engine
  to ``state="serving"``/``_warm``, and raises
  ``ReplicaBootBudgetExceeded`` when the cooperative deadline passes
  with buckets still cold.
- ``StandbyReplica`` lifecycle: acquire → warm → ready → promote joins
  the set; abandon is idempotent, a no-op after promote, and promote
  after abandon raises — the F006 static rule proves the repo discharges
  one of the two on every path.
- ``ReplicaSet.scale_up(warm=True)``: enforces
  ``FLAGS_replica_boot_budget_s``; on timeout the standby is abandoned,
  a ``warm_boot_timeout`` outcome is recorded, and the COLD path still
  produces a replica (degraded admission, never a missing replica).
- Warm workers spawn with ``compile_grace == 0.0`` (PR-17's grace is a
  cold-path artifact; a warm boot has nothing left to compile), cold
  workers keep the set's grace.
- ``replace()``: the standby pre-compiles the outgoing replica's bucket
  ledger BEFORE the fence/drain — zero lost requests, and drained
  requests carry a ``warm_handoff`` span naming the standby.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.framework.flags import flag, set_flags
from paddle_tpu.models import GPTForCausalLM, gpt_presets
from paddle_tpu.serving import (
    GPTDecodeModel, ReplicaBootBudgetExceeded, ReplicaSet, ServeRequest,
)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    """Engine parity paths run the training model's forward, which
    rejects a leftover ambient mesh from earlier suites."""


def _mini_cfg(**over):
    kw = dict(hidden_size=32, num_heads=2, num_layers=2, vocab_size=64,
              max_position_embeddings=64)
    kw.update(over)
    return gpt_presets("gpt-test", **kw)


@pytest.fixture(scope="module")
def dm():
    return GPTDecodeModel(GPTForCausalLM(_mini_cfg(), seed=0))


def _reqs(rs, n, prompt_len=5, max_new=4, vocab=64):
    return [ServeRequest(prompt_ids=rs.randint(0, vocab, (prompt_len,)),
                         max_new_tokens=max_new) for _ in range(n)]


def _drive(rset, rs, n, max_new=5):
    reqs = _reqs(rs, n, max_new=max_new)
    for r in reqs:
        assert rset.submit(r)
    res = rset.wait([r.request_id for r in reqs], timeout=120)
    assert len(res) == n
    return res


@pytest.fixture
def boot_budget():
    """Restore the boot-budget flag after a test rewrites it."""
    prev = flag("FLAGS_replica_boot_budget_s", 300.0)
    yield
    set_flags({"FLAGS_replica_boot_budget_s": prev})


# ---------------------------------------------------------------------------
# engine warm
# ---------------------------------------------------------------------------

class TestEngineWarm:
    def test_warm_replays_bucket_ledger(self, dm):
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(0)
        with rset:
            _drive(rset, rs, 6)
        buckets = rset.warm_buckets()
        assert buckets, "traffic produced no shape buckets"
        sb = rset.acquire_standby()
        try:
            warmed = sb.engine.warm(buckets)
            assert warmed == len(buckets)
            assert sb.engine.seen_buckets() == buckets
            assert sb.engine._warm and sb.engine.state == "serving"
            # idempotent: a second pass has nothing left to do
            assert sb.engine.warm(buckets) == 0
        finally:
            sb.abandon()

    def test_warm_deadline_raises_budget_exceeded(self, dm):
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(1)
        with rset:
            _drive(rset, rs, 4)
        sb = rset.acquire_standby()
        try:
            with pytest.raises(ReplicaBootBudgetExceeded):
                sb.engine.warm(rset.warm_buckets(),
                               deadline=time.monotonic() - 1.0)
            assert not sb.ready()
        finally:
            sb.abandon()


# ---------------------------------------------------------------------------
# standby lifecycle
# ---------------------------------------------------------------------------

class TestStandbyLifecycle:
    def test_promote_joins_the_set(self, dm):
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(2)
        with rset:
            _drive(rset, rs, 4)
            before = rset.alive_replicas
            sb = rset.acquire_standby()
            sb.warm(rset.warm_buckets(), deadline=time.monotonic() + 60)
            assert sb.ready()
            idx = sb.promote(reason="test")
            assert rset.alive_replicas == before + 1
            assert rset.engines[idx] is sb.engine
            # abandon after promote is a no-op: the set owns the engine
            sb.abandon()
            assert sb.engine.alive and not sb.abandoned
            # the adopted replica actually serves
            _drive(rset, rs, 4)

    def test_abandon_is_idempotent_and_blocks_promote(self, dm):
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        sb = rset.acquire_standby()
        sb.abandon()
        assert sb.abandoned and not sb.engine.alive
        sb.abandon()  # idempotent
        with pytest.raises(RuntimeError):
            sb.promote()

    def test_abandoned_standby_never_takes_a_name_slot(self, dm):
        """Names stay monotonic: an abandoned standby's name is skipped,
        never reused by a later replica (dashboards must not see two
        different engines under one name)."""
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        sb = rset.acquire_standby()
        sb.abandon()
        idx = rset.scale_up()
        assert rset.engines[idx].name != sb.engine.name


# ---------------------------------------------------------------------------
# scale_up(warm=True) + boot budget
# ---------------------------------------------------------------------------

class TestWarmScaleUp:
    def test_warm_boot_records_ok(self, dm):
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(3)
        with rset:
            _drive(rset, rs, 6)
            idx = rset.scale_up(warm=True)
            assert rset.engines[idx].alive
            assert rset.engines[idx]._warm
            boot = rset.last_boot
            assert boot["mode"] == "warm" and boot["outcome"] == "ok"
            assert boot["replica"] == rset.engines[idx].name
            assert boot["ms"] >= 0.0
            assert rset.warm_boot_counts() == {
                "warm_boots": 1, "warm_boot_timeouts": 0}
            _drive(rset, rs, 6)  # the warm replica serves

    def test_budget_timeout_falls_back_cold(self, dm, boot_budget):
        """An exhausted boot budget abandons the standby LOUDLY
        (warm_boot_timeout outcome) and still produces a replica via the
        cold path — degraded admission, never a missing replica."""
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(4)
        with rset:
            _drive(rset, rs, 6)
            set_flags({"FLAGS_replica_boot_budget_s": -1.0})
            before = rset.alive_replicas
            idx = rset.scale_up(warm=True)
            assert rset.alive_replicas == before + 1
            assert rset.engines[idx].alive
            outcomes = [(b["mode"], b["outcome"]) for b in rset.boots]
            assert ("warm", "warm_boot_timeout") in outcomes
            assert ("cold", "ok") in outcomes
            assert rset.last_boot["mode"] == "cold"
            assert rset.warm_boot_counts() == {
                "warm_boots": 0, "warm_boot_timeouts": 1}
            set_flags({"FLAGS_replica_boot_budget_s": 300.0})
            _drive(rset, rs, 6)  # the cold-fallback replica serves

    def test_warm_worker_needs_no_compile_grace(self, dm):
        """PR-17's compile_grace exists for in-traffic cold compiles; a
        warm boot has none left, so its watchdog arms with grace 0.0
        while cold workers keep the set's grace."""
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=4, compile_grace=45.0)
        rs = np.random.RandomState(5)
        with rset:
            assert rset._hds[0].compile_grace == 45.0  # boot-time = cold
            _drive(rset, rs, 4)
            warm_idx = rset.scale_up(warm=True)
            assert rset._hds[warm_idx].compile_grace == 0.0
            cold_idx = rset.scale_up()
            assert rset._hds[cold_idx].compile_grace == 45.0


# ---------------------------------------------------------------------------
# replace() — the full warm handoff
# ---------------------------------------------------------------------------

class TestReplace:
    def test_replace_is_zero_lost_and_traced(self, dm):
        """Standby warms BEFORE the outgoing replica drains; every
        drained request is re-admitted (zero lost) and carries a
        ``warm_handoff`` span naming the standby + boot mode."""
        from paddle_tpu.observability.tracing import get_tracer

        gate = threading.Event()
        entered = threading.Event()

        def hang_hook(eng):
            if eng.running and not gate.is_set():
                entered.set()
                gate.wait(30)

        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=2, watchdog_timeout=60.0,
                          pre_step_hooks={0: hang_hook})
        rs = np.random.RandomState(6)
        try:
            with rset:
                warm = _reqs(rs, 2, max_new=4)
                gate.set()  # let the ledger-building traffic through
                for r in warm:
                    assert rset.submit(r)
                rset.wait([r.request_id for r in warm], timeout=120)
                gate.clear()

                reqs = _reqs(rs, 6, max_new=4)
                for r in reqs:
                    assert rset.submit(r)
                assert entered.wait(30), "replica 0 never picked up work"
                old = rset.engines[0].name
                ev = rset.replace(idx=0)
                gate.set()
                res = rset.wait([r.request_id for r in reqs], timeout=120)
        finally:
            gate.set()
        assert len(res) == 6
        assert all(r.outcome == "completed" for r in res.values())
        assert ev["replica"] == old and ev["boot_mode"] == "warm"
        assert not rset.engines[0].alive
        assert rset.last_boot["mode"] == "warm"
        assert rset.last_boot["outcome"] == "ok"
        redone = [r for r in res.values() if r.attempts > 0]
        assert redone, "no request was drained across the handoff"
        store = get_tracer().store
        for r in redone:
            doc = store.get(r.trace.trace_id)
            spans = [s for s in doc["spans"] if s["name"] == "warm_handoff"]
            assert spans, f"no warm_handoff span on {r.request_id}"
            assert spans[0]["fields"]["replica"] == old
            assert spans[0]["fields"]["boot_mode"] == "warm"
            assert spans[0]["fields"]["standby"] == rset.last_boot["replica"]

    def test_replace_defaults_to_highest_alive(self, dm):
        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(7)
        with rset:
            _drive(rset, rs, 6)
            victim = rset.engines[1].name
            ev = rset.replace()
            assert ev["replica"] == victim
            assert not rset.engines[1].alive
            assert rset.alive_replicas == 2
            _drive(rset, rs, 6)

"""ASP 2:4 sparsity (incubate/asp.py) + cost model (cost_model.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate import asp


def test_create_mask_is_2_of_4():
    rs = np.random.RandomState(0)
    w = rs.randn(16, 8).astype("f4")
    mask = asp.create_mask(w)
    assert asp.check_mask_1d(mask.T)  # 2 kept per 4 along dim 0
    groups = mask.reshape(4, 4, 8)
    np.testing.assert_array_equal(groups.sum(1), 2.0)
    # kept entries are the magnitudes' top-2 of each group
    a = np.abs(w).reshape(4, 4, 8)
    kept = np.sort(np.where(mask.reshape(4, 4, 8)[0, :, 0])[0])
    top2 = np.sort(np.argsort(-a[0, :, 0])[:2])
    np.testing.assert_array_equal(kept, top2)


def test_prune_model_and_guarantee_through_steps():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 4))
    optim = asp.decorate(opt.SGD(learning_rate=0.1,
                                 parameters=net.parameters()))
    density = asp.prune_model(net)
    assert all(abs(d - 0.5) < 1e-6 for d in density.values())

    rs = np.random.RandomState(1)
    for _ in range(3):
        x = paddle.to_tensor(rs.randn(4, 16).astype("f4"))
        y = paddle.to_tensor(rs.randn(4, 4).astype("f4"))
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
    # sparsity survived the updates
    for sub in (net[0], net[2]):
        assert abs(asp.calculate_density(sub.weight.numpy()) - 0.5) < 1e-6


def test_cost_model_static_cost():
    import paddle_tpu.static as static
    from paddle_tpu.cost_model import CostModel

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", (8, 32), "float32")
            h = static.nn.fc(x, size=64)
            out = static.nn.fc(h, size=16)
        cm = CostModel()
        rs = np.random.RandomState(0)
        cost = cm.profile_measure(
            startup, main, feed={"x": rs.randn(8, 32).astype("f4")},
            fetch_list=[out], repeat=2)
        assert cost["time_ms"] > 0
        # two matmuls: 2*(8*32*64 + 8*64*16) = 49152 flops minimum
        assert cost["flops"] >= 2 * (8 * 32 * 64 + 8 * 64 * 16)
    finally:
        paddle.disable_static()


def test_lookahead_syncs_every_k():
    from paddle_tpu.incubate.optimizer import LookAhead

    p = paddle.to_tensor(np.zeros(2, np.float32))
    p.stop_gradient = False
    inner = opt.SGD(learning_rate=1.0, parameters=[p])
    la = LookAhead(inner, alpha=0.5, k=2)
    for i in range(4):
        p.grad = paddle.to_tensor(np.ones(2, np.float32))
        la.step()
        inner.clear_grad()
    # steps: fast -1, -2(sync: slow=-2... first sync snapshots), -3, -4(sync)
    # after k=2: slow snapshot at -2; at step 4: slow = -2 + 0.5*(-4-(-2)) = -3
    np.testing.assert_allclose(p.numpy(), -3.0)


def test_model_average_apply_restore():
    from paddle_tpu.incubate.optimizer import ModelAverage

    p = paddle.to_tensor(np.array([0.0], np.float32))
    ma = ModelAverage(0.15, parameters=[p])
    for v in (1.0, 2.0, 3.0):
        p._value = paddle.to_tensor(np.array([v], np.float32))._value
        ma.step()
    ma.apply()
    np.testing.assert_allclose(p.numpy(), [2.0])  # mean of 1,2,3
    ma.restore()
    np.testing.assert_allclose(p.numpy(), [3.0])

"""Activation-memory planner + pipeline cost model (ISSUE 15).

Covers: the 1F1B bubble/memory arithmetic of cost_model.pipeline_cost,
plan_memory's cheapest-in-time search and its refusal path (priced
reason, never an XLA OOM), the gpt per-layer estimates, and the
acceptance geometry — a gpt config whose UNPIPELINED activation need
exceeds an emulated HBM budget is refused by the planner while the
pipelined plan fits.
"""
import numpy as np
import pytest

from paddle_tpu.cost_model import pipeline_cost
from paddle_tpu.distributed.pipeline import (
    MemoryPlan, gpt_activation_estimate, host_offload_supported,
    plan_memory,
)
from paddle_tpu.distributed.pipeline.memory_plan import plan_for_gpt

ACT, INP, FLOPS = 1e6, 1e5, 1e9


def cost(**kw):
    base = dict(pipe_degree=4, microbatches=8, layers_per_stage=2,
                activation_bytes_per_layer=ACT, input_bytes_per_layer=INP,
                layer_flops=FLOPS)
    base.update(kw)
    return pipeline_cost(**base)


class TestPipelineCost:
    def test_bubble_fraction_formula(self):
        for P, M in [(2, 2), (4, 8), (4, 1), (8, 64)]:
            c = cost(pipe_degree=P, microbatches=M)
            assert c["bubble_fraction"] == pytest.approx(
                (P - 1) / (M + P - 1))

    def test_bubble_monotone_down_in_microbatches(self):
        bubbles = [cost(microbatches=M)["bubble_fraction"]
                   for M in (1, 2, 4, 8, 32)]
        assert bubbles == sorted(bubbles, reverse=True)

    def test_stash_slots_bounded_by_depth(self):
        assert cost(microbatches=2)["stash_slots"] == 2        # M < 2P-1
        assert cost(microbatches=64)["stash_slots"] == 7       # 2P-1 cap

    def test_policy_memory_ordering(self):
        """none keeps full internals; remat keeps only the input (plus one
        transient recompute); offload keeps ~nothing device-resident."""
        none = cost(policies=["none", "none"])
        rem = cost(policies=["remat", "remat"])
        off = cost(policies=["offload", "offload"])
        assert none["activation_bytes_peak"] > rem["activation_bytes_peak"]
        assert rem["resident_residual_bytes"] == 2 * INP
        assert none["resident_residual_bytes"] == 2 * ACT
        assert off["resident_residual_bytes"] == 0
        # offload's host traffic is priced, remat's is not
        assert off["host_bytes_per_step"] > 0 and \
            rem["host_bytes_per_step"] == 0
        assert off["offload_s"] > 0.0

    def test_recompute_flops_accounting(self):
        none = cost(policies=["none", "none"])
        rem = cost(policies=["remat", "remat"])
        assert none["recompute_flops"] == 0
        assert rem["recompute_flops"] == pytest.approx(8 * 2 * FLOPS)
        assert rem["time_lower_bound_s"] > none["time_lower_bound_s"]

    def test_stash_offload_moves_stash_bytes(self):
        on = cost(stash_offload=True)
        off = cost(stash_offload=False)
        assert on["stash_bytes_device"] < off["stash_bytes_device"]
        assert on["stash_bytes_host"] == off["stash_bytes_device"]
        assert on["host_bytes_per_step"] > 0

    def test_budget_verdict_and_reason(self):
        c = cost(hbm_budget_bytes=1e4)
        assert c["fits"] is False and "OVER" in c["why"]
        c2 = cost(hbm_budget_bytes=1e12)
        assert c2["fits"] is True and "fits" in c2["why"]

    def test_validation(self):
        with pytest.raises(ValueError, match="policies"):
            cost(policies=["none"])
        with pytest.raises(ValueError, match="unknown"):
            cost(policies=["none", "bogus"])
        with pytest.raises(ValueError, match=">= 1"):
            cost(pipe_degree=0)


class TestPlanMemory:
    def kw(self, **over):
        base = dict(num_layers=8, pipe_degree=4, microbatches=8,
                    activation_bytes_per_layer=ACT,
                    input_bytes_per_layer=INP, layer_flops=FLOPS)
        base.update(over)
        return base

    def test_no_budget_all_none(self):
        p = plan_memory(**self.kw())
        assert p.feasible and p.policies == ("none", "none")
        assert not p.stash_offload

    def test_cheapest_fitting_assignment_wins(self):
        """A budget only full remat satisfies picks remat; a budget that
        admits all-none keeps it (remat costs time, never free)."""
        # all-none peak = 7*INP + 2*ACT = 2.7e6; full remat =
        # 7*INP + 2*INP + ACT (transient recompute) = 1.9e6
        tight = plan_memory(**self.kw(hbm_budget_bytes=2.0e6))
        assert tight.feasible and tight.policies == ("remat", "remat")
        roomy = plan_memory(**self.kw(hbm_budget_bytes=2.8e6))
        assert roomy.feasible and roomy.policies == ("none", "none")

    def test_infeasible_is_refused_with_priced_reason(self):
        p = plan_memory(**self.kw(hbm_budget_bytes=1e4))
        assert not p.feasible
        assert "no assignment fits" in p.reason and "B" in p.reason
        assert isinstance(p, MemoryPlan)

    def test_offload_gated_by_backend_support(self):
        """On CPU there is no distinct host space: the planner must not
        claim offload bytes unless the caller forces the tier."""
        assert host_offload_supported() is False  # CPU test environment
        # a budget only offload can satisfy (below remat's input floor)
        budget = INP + ACT + INP + 10   # stash slot + transient, ~no resident
        p = plan_memory(**self.kw(hbm_budget_bytes=budget))
        assert not p.feasible
        assert "host offload unavailable" in p.reason
        forced = plan_memory(**self.kw(hbm_budget_bytes=budget,
                                       allow_offload=True))
        assert forced.feasible
        assert forced.stash_offload or "offload" in forced.policies
        assert forced.stash_memory_kind in (None, "unpinned_host")

    def test_layers_must_divide_stages(self):
        with pytest.raises(ValueError, match="divisible"):
            plan_memory(**self.kw(num_layers=7))


class TestGptEstimates:
    def test_estimate_scales_with_config_and_mesh(self):
        import jax

        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.models import gpt_presets

        cfg = gpt_presets("gpt-test", use_flash_attention=False)
        e1 = gpt_activation_estimate(cfg, 4, 32)
        e2 = gpt_activation_estimate(cfg, 8, 32)
        assert e2["activation_bytes_per_layer"] == pytest.approx(
            2 * e1["activation_bytes_per_layer"])
        assert e2["input_bytes_per_layer"] == pytest.approx(
            2 * e1["input_bytes_per_layer"])
        # flash drops the [n, s, s] softmax probs from the residual set
        cfg_f = gpt_presets("gpt-test", use_flash_attention=True)
        ef = gpt_activation_estimate(cfg_f, 4, 32)
        assert ef["activation_bytes_per_layer"] < \
            e1["activation_bytes_per_layer"]
        # a 'model'-axis mesh divides the sharded widths
        mesh = mesh_mod.build_mesh({"model": 2},
                                   devices=jax.devices()[:2])
        em = gpt_activation_estimate(cfg, 4, 32, mesh)
        assert em["activation_bytes_per_layer"] < \
            e1["activation_bytes_per_layer"]

    def test_acceptance_geometry_unpipelined_refused_pipelined_fits(self):
        """THE emulated-HBM acceptance shape: one budget, same model and
        global batch — the unpipelined (P=1, M=1, whole batch resident)
        plan is refused with the priced reason, the pipelined plan fits.
        tests/test_pipeline_train_step.py trains the fitting config and
        watermarks it; this pins the planner's side of the gate."""
        from paddle_tpu.models import gpt_presets

        cfg = gpt_presets("gpt-test", mode="scan",
                          use_flash_attention=False)
        B, s = 32, 64
        est = gpt_activation_estimate(cfg, B, s)
        # budget: comfortably fits the pipelined step, not the
        # unpipelined one (which keeps all L layers' residuals for the
        # whole batch even under full remat)
        budget = 6 * est["input_bytes_per_layer"] / (B // 8) * 8 \
            + 2 * est["activation_bytes_per_layer"] / (B // 4)
        unpiped = plan_for_gpt(cfg, pipe_degree=1, microbatches=1,
                               global_batch=B, seq=s,
                               hbm_budget_bytes=budget)
        piped = plan_for_gpt(cfg, pipe_degree=2, microbatches=8,
                             global_batch=B, seq=s,
                             hbm_budget_bytes=budget)
        assert not unpiped.feasible and "OVER" in unpiped.reason
        assert piped.feasible
        assert piped.activation_bytes_peak <= budget
        assert piped.bubble_fraction == pytest.approx(1 / 9)

"""nn.utils: weight_norm / spectral_norm / parameter vectors / grad clip."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.utils import (
    clip_grad_norm_, clip_grad_value_, parameters_to_vector,
    remove_weight_norm, spectral_norm, vector_to_parameters, weight_norm,
)


def test_weight_norm_forward_matches_and_trains():
    rs = np.random.RandomState(0)
    lin = nn.Linear(4, 3)
    w0 = lin.weight.numpy().copy()
    x = paddle.to_tensor(rs.randn(2, 4).astype("f4"))
    before = lin(x).numpy()
    weight_norm(lin, "weight", dim=0)
    after = lin(x).numpy()
    np.testing.assert_allclose(after, before, rtol=1e-5)  # same function
    # v and g are the trainable params now
    names = [n for n, _ in lin.named_parameters()]
    assert "weight_v" in names and "weight_g" in names
    loss = lin(x).sum()
    loss.backward()
    assert lin.weight_g.grad is not None
    remove_weight_norm(lin)
    np.testing.assert_allclose(lin(x).numpy(), before, rtol=1e-5)


def test_spectral_norm_divides_by_sigma():
    # seeded: with an unlucky unseeded init (near-equal top singular
    # values) 20 power iterations may not converge to 1e-3 — the test
    # was order-dependent on the global RNG stream
    paddle.seed(1)
    lin = nn.Linear(6, 6)
    w0 = lin.weight.numpy().copy()
    x = paddle.to_tensor(np.eye(6, dtype="f4"))
    spectral_norm(lin, "weight", n_power_iterations=50)
    out = lin(x).numpy() - lin.bias.numpy()
    sigma = np.linalg.svd(w0, compute_uv=False)[0]
    np.testing.assert_allclose(out, w0 / sigma, rtol=1e-3, atol=1e-4)


def test_parameters_vector_roundtrip():
    lin = nn.Linear(3, 2)
    vec = parameters_to_vector(lin.parameters())
    assert tuple(vec.shape) == (3 * 2 + 2,)
    new = np.arange(8, dtype="f4")
    vector_to_parameters(paddle.to_tensor(new), lin.parameters())
    np.testing.assert_allclose(lin.weight.numpy().reshape(-1), new[:6])
    np.testing.assert_allclose(lin.bias.numpy(), new[6:])


def test_clip_grad_norm_and_value():
    p = paddle.to_tensor(np.zeros(4, np.float32))
    p.stop_gradient = False
    p.grad = paddle.to_tensor(np.full(4, 3.0, np.float32))
    total = clip_grad_norm_([p], max_norm=1.0)
    np.testing.assert_allclose(float(total), 6.0, rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(p.grad.numpy()), 1.0,
                               rtol=1e-4)
    p.grad = paddle.to_tensor(np.array([5., -5., 0.1, -0.1], np.float32))
    clip_grad_value_([p], 1.0)
    np.testing.assert_allclose(p.grad.numpy(), [1., -1., 0.1, -0.1])

"""Convergence evidence (VERDICT r1 weak #8 / next-round #10).

BASELINE config 1 is "MNIST LeNet via Model.fit: correctness + loss curve".
No network egress → a synthetic MNIST-shaped task (10 class templates +
noise, genuinely learnable) stands in; the loss-curve artifact is written to
artifacts/mnist_fit_curve.json so the evidence lives in-repo.

GPT: loop / scan / recompute modes share bit-identical init, so their loss
curves must MATCH (the reference proves training via loss-delta asserts,
test_dist_base.py:1457) and descend monotonically over 50 steps.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)

ART_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "artifacts")


def _synthetic_mnist(n, seed=0):
    """10 fixed 28x28 templates + gaussian noise → learnable 10-class task."""
    rs = np.random.RandomState(seed)
    templates = rs.randn(10, 1, 28, 28).astype("float32")
    labels = rs.randint(0, 10, n)
    imgs = templates[labels] + 0.5 * rs.randn(n, 1, 28, 28).astype("float32")
    return imgs.astype("float32"), labels.astype("int64")


@pytest.mark.slow
def test_mnist_lenet_model_fit_loss_curve():
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.vision.models import LeNet

    xs, ys = _synthetic_mnist(1024)
    ds = TensorDataset([paddle.to_tensor(xs),
                        paddle.to_tensor(ys[:, None])])
    model = paddle.Model(LeNet())
    model.prepare(
        optimizer=opt.Adam(learning_rate=1e-3,
                           parameters=model.network.parameters()),
        loss=paddle.nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy(),
    )
    hist = model.fit(ds, epochs=3, batch_size=64, verbose=0)

    losses = [float(np.mean(e["loss"])) for e in hist.history["train"]] \
        if hasattr(hist, "history") else None
    if losses is None:  # Model.fit returns None: pull from evaluate
        res = model.evaluate(ds, batch_size=64, verbose=0)
        losses = [float(np.asarray(res["loss"]).mean())]
        acc = float(res.get("acc", res.get("accuracy", 0.0)))
    else:
        res = model.evaluate(ds, batch_size=64, verbose=0)
        acc = float(res.get("acc", res.get("accuracy", 0.0)))

    assert acc > 0.9, f"LeNet failed to learn the synthetic task: acc={acc}"

    os.makedirs(ART_DIR, exist_ok=True)
    with open(os.path.join(ART_DIR, "mnist_fit_curve.json"), "w") as f:
        json.dump({"task": "synthetic-mnist LeNet Model.fit",
                   "epochs": 3, "batch_size": 64,
                   "final_eval_loss": losses[-1], "final_acc": acc}, f,
                  indent=2)


def _gpt_losses(mode, recompute=False, steps=50, lr=0.01):
    cfg = gpt_presets("gpt-test", mode=mode, recompute=recompute)
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.SGD(learning_rate=lr, parameters=model.parameters())
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)
    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (8, 16)),
                           dtype="int64")
    labels = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (8, 16)),
                              dtype="int64")
    return [float(step(inputs=(ids,), labels=(labels,)))
            for _ in range(steps)]


@pytest.mark.slow
def test_gpt_modes_share_loss_curve_and_descend():
    base = _gpt_losses("loop")
    scan = _gpt_losses("scan")
    rec = _gpt_losses("loop", recompute=True)
    np.testing.assert_allclose(scan, base, rtol=5e-4)
    np.testing.assert_allclose(rec, base, rtol=5e-4)
    # monotone descent over 50 steps (smoothed: every 10-step mean drops)
    chunks = [np.mean(base[i:i + 10]) for i in range(0, 50, 10)]
    assert all(b < a for a, b in zip(chunks, chunks[1:])), chunks
    assert base[-1] < base[0] * 0.9

    os.makedirs(ART_DIR, exist_ok=True)
    with open(os.path.join(ART_DIR, "gpt_test_loss_curves.json"), "w") as f:
        json.dump({"steps": 50, "modes": {"loop": base, "scan": scan,
                                          "recompute": rec}}, f, indent=2)

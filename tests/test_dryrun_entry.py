"""Driver entry contract: dryrun_multichip at 16 virtual devices.

VERDICT r3 next #6: the 5 mesh axes were never exercised JOINTLY — the
8-device dryrun runs data x sharding x model and pipe x model x sep as
two separate configs. At 16+ devices dryrun_multichip adds config C: ONE
mesh with data x sharding x pipe x model all >1 (x sep at 32), composing
ZeRO-2 slot sharding + the 1F1B schedule + Megatron TP (+ ring-attention
SP) jointly with loss parity against a single device — the composition
the north-star config actually stacks (fleet/base/topology.py 4-D
topology).

dryrun_multichip re-execs itself in a subprocess with the right virtual
device count, so this runs under the 8-device conftest unchanged.
"""
import os
import sys

import pytest


@pytest.mark.timeout(900)
@pytest.mark.requires_vma_shard_map
def test_dryrun_multichip_16_joint_axes():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as g

    g.dryrun_multichip(16)  # raises on any parity failure

"""framework/target.py: compile-target resolution for kernel gates.

The question a kernel must ask is "what platform is this program being
compiled FOR", which diverges from jax.default_backend() exactly when
compiling ahead-of-time for described TPU topologies (jit/aot.py). These
tests pin the resolution order: force_target > active-mesh device
platform > default backend — and the flash-attention gating that builds
on it.
"""
import jax
import numpy as np
import pytest

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.framework.target import force_target, target_platform
from paddle_tpu.ops.flash_attention import (
    flash_attention_sharded_ok, flash_attention_val_auto,
)


@pytest.fixture(autouse=True)
def _clean_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def test_default_backend_fallback():
    mesh_mod.set_mesh(None)
    assert target_platform() == jax.default_backend() == "cpu"


def test_force_target_override_and_restore():
    assert target_platform() == "cpu"
    with force_target("tpu"):
        assert target_platform() == "tpu"
        with force_target("cpu"):
            assert target_platform() == "cpu"  # nests
        assert target_platform() == "tpu"
    assert target_platform() == "cpu"


def test_active_mesh_platform_wins_over_default_backend():
    # a CPU mesh on the CPU suite: platform comes from the mesh devices
    mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 2},
                                          devices=jax.devices()[:2]))
    assert target_platform() == "cpu"
    # and force_target still beats the mesh
    with force_target("tpu"):
        assert target_platform() == "tpu"


def test_flash_sharded_ok_divisibility_gate():
    # the shape/divisibility gate reads axis names and degrees only (not
    # the device kind), so a CPU mesh exercises it fully
    mesh_mod.set_mesh(mesh_mod.build_mesh(
        {"data": 2, "model": 4}, devices=jax.devices()[:8]))
    # b=4 divisible by data2; n=8 divisible by model4; per-shard (2,256,2,
    # 128)... head_dim 128 and seq 256 are kernel-supported
    assert flash_attention_sharded_ok((4, 256, 8, 128))
    # batch 3 does not divide data degree 2
    assert not flash_attention_sharded_ok((3, 256, 8, 128))
    # heads 2 do not divide model degree 4
    assert not flash_attention_sharded_ok((4, 256, 2, 128))


def test_val_auto_raises_clearly_on_unshardable_shape():
    mesh_mod.set_mesh(mesh_mod.build_mesh(
        {"data": 2, "model": 4}, devices=jax.devices()[:8]))
    q = np.zeros((3, 256, 8, 128), np.float32)  # batch 3 unshardable
    with pytest.raises(ValueError, match="cannot be sharded"):
        flash_attention_val_auto(q, q, q, causal=True)

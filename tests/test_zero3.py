"""ZeRO-3 parameter sharding at rest (ISSUE 9: distributed/sharding/stage3.py,
overlap.GatherFuture, fused.step_sharded(param_store=), memory watermark,
cost_model.zero3_cost, bench gates).

Covers the tentpole contract: parameters live as 1/world shards at rest
(live-bytes drop), per-bucket all_gathers prefetched one layer ahead on the
CollectiveLane (span-ordering proof), gathered params freed after use
(<= 2 buckets resident, LiveBytesWatermark proof), the owned-shard fused
update, and BIT-identical losses vs the replicated os_g path on gpt-test
for fp32/bf16/int8_block — plus the save/checkpoint/bench/cost wiring.
"""
import gc
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed.collective as coll
import paddle_tpu.distributed.env as env_mod
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed import grad_comm
from paddle_tpu.distributed.overlap import (
    GatherFuture, OverlappedGradCommunicator,
)
from paddle_tpu.distributed.sharding import (
    Stage3ParamShards, group_sharded_parallel, save_group_sharded_model,
)
from paddle_tpu.distributed.sharding.stage3 import (
    FreedParamValue, zero3_gather_report,
)
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.observability import get_registry
from paddle_tpu.observability import memory as obs_mem
from paddle_tpu.optimizer.fused import FusedFlatUpdater

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
rng = np.random.RandomState(0)

X = rng.standard_normal((16, 8)).astype(np.float32)
Y = rng.standard_normal((16, 1)).astype(np.float32)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def _two_rank_all_reduce():
    """Two identical emulated ranks: AVG/MAX identity, integer SUM doubles
    (same fake as tests/test_overlap.py)."""
    def fake(t, op=None, group=None, **kw):
        if op == coll.ReduceOp.SUM and jnp.issubdtype(t._value.dtype,
                                                      jnp.integer):
            t._value = t._value * 2
        return t
    return fake


def _mlp(seed=7):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))


def _cfg(codec="fp32"):
    # tiny caps -> several buckets, so the prefetch pipeline has stages
    return grad_comm.GradCommConfig(codec, comm_buffer_size=0.0002,
                                    last_comm_buffer_size=0.0001,
                                    block_size=64)


# ------------------------------------------------------------ at-rest state
class TestAtRest:
    def test_shard_drops_live_bytes_to_one_over_world(self):
        paddle.seed(0)
        layers = []
        for _ in range(6):
            layers += [nn.Linear(256, 256), nn.Tanh()]
        net = nn.Sequential(*layers)
        params = [p for p in net.parameters() if not p.stop_gradient]
        full = sum(p._value.size * p._value.dtype.itemsize for p in params)
        store = Stage3ParamShards(
            params, grad_comm.GradCommConfig(
                "fp32", comm_buffer_size=0.3, last_comm_buffer_size=0.3
            ) and grad_comm.GradCommunicator(grad_comm.GradCommConfig(
                "fp32", comm_buffer_size=0.3, last_comm_buffer_size=0.3)),
            rank=0, world=4)
        gc.collect()
        before = obs_mem.live_tensor_bytes()
        store.shard_()
        gc.collect()
        after = obs_mem.live_tensor_bytes()
        # device set shrank by ~the 3/4 of param bytes now held as shards
        # elsewhere (host under emulation, peer HBM for real)
        assert before - after > 0.70 * full, (before, after, full)
        assert store.param_bytes_per_rank() <= full / 4 + 4096
        # every param is a placeholder carrying shape/dtype metadata
        for p in params:
            assert isinstance(p._value, FreedParamValue)
            assert tuple(p.shape) == tuple(p._value.shape)
            assert np.dtype(p.dtype) == p._value.dtype
        # the gauge agrees
        snap = get_registry().snapshot()
        assert snap["zero3_param_bytes_per_rank"] == \
            store.param_bytes_per_rank()

    def test_freed_placeholder_without_store_raises(self):
        ph = FreedParamValue((4, 4), np.float32, store=None, pname="w")
        with pytest.raises(RuntimeError, match="sharded at rest"):
            np.asarray(ph)

    def test_world_one_is_rejected(self):
        net = _mlp()
        with pytest.raises(ValueError, match="world > 1"):
            Stage3ParamShards([p for p in net.parameters()],
                              grad_comm.GradCommunicator(_cfg()),
                              rank=0, world=1)


# --------------------------------------------------------- prefetch schedule
class TestPrefetchScheduling:
    def test_layer_order_spans(self, monkeypatch):
        """The scheduling proof: every gather_launch:bucket{i} precedes
        that bucket's first forward use AND (for prefetched buckets)
        follows the PREVIOUS layer's pre-hook; the first bucket is
        gathered synchronously; lane-side gather spans exist."""
        from paddle_tpu import profiler as prof

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(32, 32), nn.Tanh(),
                            nn.Linear(32, 32), nn.Tanh(),
                            nn.Linear(32, 32))
        params = [p for p in net.parameters() if not p.stop_gradient]
        # 0.006 MB cap: one Linear's weight+bias (4224 B) per bucket
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(
            "fp32", comm_buffer_size=0.006, last_comm_buffer_size=0.006))
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.shard_()
        store.install_hooks(net)
        # buckets are built in REVERSE traversal order, so earlier layers
        # consume higher-index buckets; buckets may straddle layers
        assert len(store.buckets) == 3
        layer_buckets = [need for _l, need in store._layer_order]
        assert len(layer_buckets) == 3
        first_use = {}
        for k, need in enumerate(layer_buckets):
            for bi in need:
                first_use.setdefault(bi, k)
        assert set(first_use) == {0, 1, 2}

        spans = []
        sink = lambda name, t0, t1, tid: spans.append((name, t0, t1))
        prof.add_span_sink(sink)
        try:
            with paddle.no_grad():
                net(paddle.to_tensor(
                    rng.standard_normal((2, 32)).astype(np.float32)))
        finally:
            prof.remove_span_sink(sink)

        t_pre = {int(n.split("layer")[1]): t0 for n, t0, _ in spans
                 if n.startswith("zero3_prehook:layer")}
        t_ready = {int(n.split("layer")[1]): t0 for n, t0, _ in spans
                   if n.startswith("zero3_ready:layer")}
        t_launch = {int(n.split("bucket")[1]): t0 for n, t0, _ in spans
                    if n.startswith("gather_launch:bucket")}
        lane = {int(n.split("bucket")[1]) for n, _t0, _t1 in spans
                if n.startswith("gather:bucket")
                or n.startswith("gather_sync:bucket")}
        assert len(t_pre) == 3 and len(t_ready) == 3
        assert set(t_launch) == {0, 1, 2} and lane == {0, 1, 2}
        for bi, k in first_use.items():
            # the launch PRECEDES the bucket's first forward use (the
            # layer's forward starts only after its ready marker) ...
            assert t_launch[bi] <= t_ready[k], (bi, k, t_launch, t_ready)
            # ... and FOLLOWS the previous layer's pre-hook (the
            # layer-ahead prefetch window, or this layer's own sync path)
            assert t_launch[bi] >= t_pre[max(k - 1, 0)], \
                (bi, k, t_launch, t_pre)
        # at least one bucket was prefetched from the PREVIOUS layer's
        # pre-hook window (launched before its first-use pre-hook fired)
        assert any(t_launch[bi] <= t_pre[k]
                   for bi, k in first_use.items() if k > 0)
        # first bucket had no layer to hide under -> synchronous gather
        snap = get_registry().snapshot()
        assert snap["zero3_gathers_total"].get("mode=sync", 0) >= 1
        assert snap["zero3_gathers_total"].get("mode=prefetched", 0) >= 1

    def test_free_after_use_watermark(self):
        """The <= 2-buckets-resident proof: during a forward over a
        param-dominated net, live bytes never exceed the at-rest baseline
        by more than two full buckets (current + prefetched next)."""
        paddle.seed(0)
        layers = []
        for _ in range(6):
            layers += [nn.Linear(256, 256), nn.Tanh()]
        net = nn.Sequential(*layers)
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(
            "fp32", comm_buffer_size=0.3, last_comm_buffer_size=0.3))
        store = Stage3ParamShards(params, comm, rank=0, world=4)
        store.shard_()
        store.install_hooks(net)
        bucket_bytes = max(b.nbytes for b in store.buckets)
        x = paddle.to_tensor(
            rng.standard_normal((1, 256)).astype(np.float32))
        gc.collect()
        with paddle.no_grad():
            with obs_mem.LiveBytesWatermark() as wm:
                net(x)
        assert wm.n_samples >= 2 * len(store.buckets)
        # activations for batch 1 are ~1KB; 64KB of slack is generous
        assert wm.delta <= 2 * bucket_bytes + 64 * 1024, \
            (wm.delta, bucket_bytes)
        # everything back at rest afterwards
        assert store.resident_buckets() == []
        assert all(isinstance(p._value, FreedParamValue) for p in params)

    def test_failed_prefetch_surfaces_and_recovers(self, monkeypatch):
        net = _mlp()
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm = grad_comm.GradCommunicator(_cfg())
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.shard_()
        boom = RuntimeError("gather wire fell out")

        def bad_all_gather(tl, t, group=None, **kw):
            raise boom

        monkeypatch.setattr(coll, "all_gather", bad_all_gather)
        fut = store.prefetch_bucket(0)
        assert isinstance(fut, GatherFuture)
        with pytest.raises(RuntimeError, match="wire fell out"):
            store.ensure_gathered(0)
        # the failure disarmed cleanly; a healthy gather retries fine
        monkeypatch.undo()
        store.ensure_gathered(0)
        assert store._state[0] == "gathered"
        store.free_bucket(0)


# ------------------------------------------------------------- exact parity
class TestParity:
    @pytest.mark.parametrize("codec", ["fp32", "bf16", "int8_block"])
    def test_gpt_test_bit_identical_to_replicated(self, codec, monkeypatch):
        """The acceptance bar: gpt-test under true at-rest sharding trains
        to EXACTLY the replicated os_g path's losses (and params, and
        error-feedback residuals) — exercising prefetch, free-after-use,
        the tied-embedding fallback gather, and the owned-shard update."""
        from paddle_tpu.models import (
            GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
        )

        monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 256, (2, 16)).astype(np.int64)
        labels = rs.randint(0, 256, (2, 16)).astype(np.int64)

        def train(stage3, steps=3):
            paddle.seed(1234)
            m = GPTForCausalLM(gpt_presets("gpt-test"), seed=7)
            crit = GPTPretrainingCriterion()
            o = optim.AdamW(learning_rate=1e-3, parameters=m.parameters())
            cfg = grad_comm.GradCommConfig(
                codec, comm_buffer_size=0.05, last_comm_buffer_size=0.01,
                block_size=64)
            comm = grad_comm.GradCommunicator(cfg)
            params = [p for p in m.parameters() if not p.stop_gradient]
            fused = FusedFlatUpdater(o, params, communicator=comm)
            store = None
            if stage3:
                store = Stage3ParamShards(params, comm, rank=0, world=2)
                store.shard_()
                store.install_hooks(m)
            losses = []
            for _ in range(steps):
                loss = crit(m(paddle.to_tensor(ids, dtype="int64")),
                            paddle.to_tensor(labels, dtype="int64"))
                loss.backward()
                comm.sync(params, world=2, use_reduce_scatter=True)
                if stage3:
                    fused.step_sharded(rank=0, world=2, param_store=store)
                else:
                    fused.step()
                o.clear_grad()
                losses.append(float(loss.numpy()))
            return losses, m, comm, store

        l_ref, m_ref, c_ref, _ = train(False)
        l_z3, m_z3, c_z3, store = train(True)
        assert l_ref == l_z3, (codec, l_ref, l_z3)
        # error-feedback residuals (blockwise codec) match bit for bit
        assert sorted(c_ref._residuals) == sorted(c_z3._residuals)
        for k in c_ref._residuals:
            assert np.array_equal(np.asarray(c_ref._residuals[k]),
                                  np.asarray(c_z3._residuals[k])), (codec, k)
        if codec == "int8_block":
            assert c_ref._residuals, "blockwise run recorded no residuals"
        # final parameters match bit for bit (materialize gathers, then
        # frees on exit — the S001 all-paths release scope)
        with store.materialize():
            for a, b in zip(m_ref.parameters(), m_z3.parameters()):
                assert np.array_equal(np.asarray(a._value),
                                      np.asarray(b._value)), (codec, a.name)
        assert store.resident_buckets() == []
        # the tied embedding (read by the LM head OUTSIDE its owning
        # layer's forward) went through the self-healing fallback gather
        snap = get_registry().snapshot()
        assert snap["zero3_gathers_total"].get("mode=fallback", 0) >= 1

    def test_overlapped_comm_and_grad_accumulation_abandon(self,
                                                           monkeypatch):
        """Interplay with PR-5 overlap: the store's gather lane and the
        grad lane coexist; non-update micro-batches disarm the overlapped
        sync via abandon() while the stage-3 hooks keep gathering/freeing
        — losses and params stay bit-identical to the serial-accumulation
        replicated run."""
        monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
        micro = [(X[i::2], Y[i::2]) for i in range(2)]

        def train(stage3, steps=2):
            net = _mlp()
            o = optim.SGD(learning_rate=0.2, parameters=net.parameters())
            cfg = grad_comm.GradCommConfig(
                "fp32", comm_buffer_size=0.0002,
                last_comm_buffer_size=0.0001, overlap=True)
            comm = OverlappedGradCommunicator(cfg)
            params = [p for p in net.parameters() if not p.stop_gradient]
            fused = FusedFlatUpdater(o, params, communicator=comm)
            store = None
            if stage3:
                store = Stage3ParamShards(params, comm, rank=0, world=2)
                store.shard_()
                store.install_hooks(net)
            losses = []
            for _ in range(steps):
                for k, (xm, ym) in enumerate(micro):
                    update = k == len(micro) - 1
                    if update:
                        comm.prepare(params, world=2,
                                     use_reduce_scatter=True)
                    else:
                        comm.abandon()   # raw accumulation micro-batch
                    loss = F.mse_loss(net(paddle.to_tensor(xm)),
                                      paddle.to_tensor(ym))
                    loss.backward()
                    if update:
                        comm.sync(params, world=2,
                                  use_reduce_scatter=True)
                        if stage3:
                            fused.step_sharded(rank=0, world=2,
                                               param_store=store)
                        else:
                            fused.step()
                        o.clear_grad()
                    losses.append(float(loss.numpy()))
            return losses, net, store

        l_ref, net_ref, _ = train(False)
        l_z3, net_z3, store = train(True)
        assert l_ref == l_z3, (l_ref, l_z3)
        with store.materialize():
            for a, b in zip(net_ref.parameters(), net_z3.parameters()):
                assert np.array_equal(np.asarray(a._value),
                                      np.asarray(b._value))


# ----------------------------------------------------------- save / restore
class TestSaveRestore:
    def test_save_group_sharded_model_loads_unsharded_bit_identical(
            self, tmp_path, monkeypatch):
        """Satellite 1: a stage-3 save must write FULL weights —
        loading model.pdparams into a plain unsharded model reproduces
        the sharded model's parameters bit for bit."""
        monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
        net = _mlp(seed=11)
        want = [np.asarray(p._value).copy() for p in net.parameters()]
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm = grad_comm.GradCommunicator(_cfg())
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.shard_()
        store.install_hooks(net)
        net._zero3 = store
        out = str(tmp_path / "saved")
        save_group_sharded_model(net, out)
        # the save window freed everything again
        assert store.resident_buckets() == []
        assert all(isinstance(p._value, FreedParamValue) for p in params)

        fresh = _mlp(seed=99)   # different init — the load must win
        state = paddle.load(os.path.join(out, "model.pdparams"))
        fresh.set_state_dict(state)
        for w, p in zip(want, fresh.parameters()):
            assert np.array_equal(w, np.asarray(p._value))

    def test_state_dict_roundtrip_and_geometry_guards(self):
        net = _mlp(seed=3)
        params = [p for p in net.parameters() if not p.stop_gradient]
        want = [np.asarray(p._value).copy() for p in params]
        comm = grad_comm.GradCommunicator(_cfg())
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.shard_()
        state = store.state_dict()
        assert set(state["shards"]) == {b.index for b in store.buckets}

        # fresh model, different entropy: load must restore exactly
        net2 = _mlp(seed=55)
        params2 = [p for p in net2.parameters() if not p.stop_gradient]
        comm2 = grad_comm.GradCommunicator(_cfg())
        store2 = Stage3ParamShards(params2, comm2, rank=0, world=2)
        store2.shard_()
        store2.load_state_dict(state)
        with store2.materialize():
            for w, p in zip(want, params2):
                assert np.array_equal(w, np.asarray(p._value))

        # geometry guards refuse a drifted resume
        with pytest.raises(ValueError, match="world mismatch"):
            store2.load_state_dict({**state, "world": 4})
        meta = store2.meta_state()
        store2.check_meta(meta)   # self-consistent
        with pytest.raises(ValueError, match="geometry mismatch"):
            store2.check_meta({**meta, "world": 8})

    def test_fused_shard_slots_roundtrip(self, monkeypatch):
        monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
        net = _mlp()
        o = optim.Adam(learning_rate=0.05, parameters=net.parameters())
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm = grad_comm.GradCommunicator(_cfg())
        fused = FusedFlatUpdater(o, params, communicator=comm)
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.shard_()
        store.install_hooks(net)
        loss = F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        comm.sync(params, world=2, use_reduce_scatter=True)
        fused.step_sharded(rank=0, world=2, param_store=store)
        state = fused.shard_slots_state()
        assert state["own"] and state["peer"]
        fused2 = FusedFlatUpdater(
            optim.Adam(learning_rate=0.05, parameters=net.parameters()),
            params, communicator=comm)
        fused2.load_shard_slots_state(state)
        for i, slots in fused._shard_slots.items():
            for k, v in slots.items():
                np.testing.assert_array_equal(
                    np.asarray(v), np.asarray(fused2._shard_slots[i][k]))


# ------------------------------------------------------------------ wiring
class TestWiring:
    def test_group_sharded_parallel_attaches_store(self, monkeypatch):
        monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
        monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
        net = _mlp()
        o = optim.Adam(learning_rate=0.01, parameters=net.parameters())
        model, o, _ = group_sharded_parallel(net, o, "p_g_os")
        store = model._zero3
        assert isinstance(store, Stage3ParamShards)
        assert store.sharded and store.world == 2
        assert store.comm is model._grad_comm
        # params are at rest; a forward gathers + frees through the hooks
        params = [p for p in model.parameters() if not p.stop_gradient]
        assert all(isinstance(p._value, FreedParamValue) for p in params)
        with paddle.no_grad():
            model(paddle.to_tensor(X))
        assert store.resident_buckets() == []

    def test_group_sharded_parallel_world_one_stays_unsharded(self):
        net = _mlp()
        o = optim.Adam(learning_rate=0.01, parameters=net.parameters())
        model, o, _ = group_sharded_parallel(net, o, "p_g_os")
        assert getattr(model, "_zero3", None) is None
        assert not any(isinstance(p._value, FreedParamValue)
                       for p in model.parameters())

    def test_register_external_use_prefetches_tied_weight(self,
                                                          monkeypatch):
        """A declared external use is served by the hooks (no fallback
        gather) — the tied-weight fast path."""

        class Tied(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)
                self.head = nn.Linear(8, 8)

            def forward(self, x):
                h = self.head(self.fc(x))
                # reads fc.weight OUTSIDE fc's forward
                from paddle_tpu.framework.autograd import call_op

                return call_op(lambda a, w: a @ w, h, self.fc.weight,
                               op_name="tied_use")

        paddle.seed(0)
        net = Tied()
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(
            "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001))
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.register_external_use(net, net.fc.weight)
        store.shard_()
        store.install_hooks(net)
        before = get_registry().snapshot()["zero3_gathers_total"]
        fallback0 = before.get("mode=fallback", 0)
        with paddle.no_grad():
            net(paddle.to_tensor(X[:, :8]))
        after = get_registry().snapshot()["zero3_gathers_total"]
        assert after.get("mode=fallback", 0) == fallback0
        assert store.resident_buckets() == []


# --------------------------------------------------- cost model + tooling
class TestCostAndTooling:
    def test_zero3_cost_terms(self):
        from paddle_tpu.cost_model import zero3_cost

        pb = 1.4e9
        sync = zero3_cost(pb, world=8, prefetch=False)
        assert sync["param_bytes_per_rank"] == int(np.ceil(pb / 8))
        assert sync["exposed_gather_s_prefetched"] == \
            sync["exposed_gather_s_sync"] == sync["gather_time_s"]
        # a long forward hides everything but the first bucket
        pf = zero3_cost(pb, world=8, forward_s=10.0)
        assert pf["gather_time_s"] == sync["gather_time_s"]
        per_bucket = pf["gather_time_s"] / pf["n_buckets"]
        assert pf["exposed_gather_s_prefetched"] == \
            pytest.approx(per_bucket)
        # a short window hides exactly that much
        short = zero3_cost(pb, world=8,
                           forward_s=sync["gather_time_s"] / 10)
        assert short["hidden_gather_s"] == \
            pytest.approx(sync["gather_time_s"] / 10)
        # re-gather for backward doubles the work
        back = zero3_cost(pb, world=8, regather_backward=True,
                          forward_s=0.0)
        assert back["gather_time_s"] == \
            pytest.approx(2 * sync["gather_time_s"])
        # degenerate world
        one = zero3_cost(pb, world=1)
        assert one["gather_time_s"] == 0.0
        assert one["param_bytes_per_rank"] == int(pb)

    def test_zero3_gather_report_and_bench_artifact(self):
        """The acceptance ratio on gpt-test shapes: prefetched exposed
        gather <= 25% of the synchronous baseline, and the per-rank bytes
        are half the full set at world=2 — both measured live and pinned
        in the committed artifact."""
        net = _mlp()
        rep = zero3_gather_report(
            [p for p in net.parameters()],
            grad_comm.GradCommConfig(comm_buffer_size=0.0002,
                                     last_comm_buffer_size=0.0001),
            world=2, compute_s=0.05)
        assert rep["n_buckets"] >= 3
        assert rep["prefetch_exposed_gather_ms"] < \
            rep["sync_exposed_gather_ms"]
        assert rep["zero3_param_bytes_per_rank"] <= \
            rep["param_bytes_full"] / 2 + 2048

        d = json.load(open(os.path.join(REPO, "artifacts",
                                        "overlap_bench.json")))
        z3 = d["zero3"]
        assert z3["world"] == 2 and z3["n_buckets"] >= 2
        assert z3["prefetch_exposed_gather_ms"] <= \
            0.25 * z3["sync_exposed_gather_ms"], z3
        assert z3["zero3_param_bytes_per_rank"] <= \
            z3["param_bytes_full"] / 2 + 4096

    def test_bench_gate_gates_zero3_fields(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        base = {"value": 1000.0, "device_kind": "cpu", "fallback": "cpu",
                "zero3_exposed_gather_ms": 1.0,
                "zero3_param_bytes_per_rank": 250000}
        trajectory = [("r1", base)]
        ok = dict(base, zero3_exposed_gather_ms=1.1)
        rows, compared, regressed = bg.gate(ok, trajectory, 0.20)
        assert regressed == 0 and compared >= 3
        # >20% slower exposed gather regresses
        bad = dict(base, zero3_exposed_gather_ms=1.5)
        rows, _, regressed = bg.gate(bad, trajectory, 0.20)
        assert regressed == 1
        row = {r["metric"]: r for r in rows}
        assert row["zero3_exposed_gather_ms"]["verdict"] == "REGRESSED"
        # params quietly un-sharding (bytes/rank doubling) regresses too
        fat = dict(base, zero3_param_bytes_per_rank=500000)
        _, _, regressed = bg.gate(fat, trajectory, 0.20)
        assert regressed == 1
        # records predating ISSUE 9 just SKIP the new fields
        old = {"value": 1000.0, "device_kind": "cpu", "fallback": "cpu"}
        rows, compared, regressed = bg.gate(old, trajectory, 0.20)
        assert regressed == 0 and compared >= 1

    def test_exposed_gather_gauge_exported(self):
        net = _mlp()
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm = grad_comm.GradCommunicator(_cfg())
        store = Stage3ParamShards(params, comm, rank=0, world=2)
        store.shard_()
        store.install_hooks(net)
        with paddle.no_grad():
            net(paddle.to_tensor(X))
        snap = get_registry().snapshot()
        assert snap["zero3_exposed_gather_ms"] == pytest.approx(
            store.stats["exposed_gather_s_last_pass"] * 1e3, abs=1e-3)
        assert snap["zero3_gathered_buckets"] == 0

"""Fused (chunked) linear + cross-entropy: numerics vs the full-logits
path, tied-embedding layout, and the GPT fused_loss_chunk integration.

Reference capability: fused softmax+CE ops (c_softmax_with_cross_entropy);
technique: blockwise CE with online logsumexp (flash-attention-style
rematerialized backward).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.nn.functional import fused_linear_cross_entropy
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, gpt_presets

rs = np.random.RandomState(0)


def test_fused_ce_matches_full_logits_path():
    N, H, V = 64, 32, 103  # odd vocab exercises the padded chunk
    x = paddle.to_tensor(rs.randn(N, H).astype("float32"),
                         stop_gradient=False)
    w = paddle.to_tensor((rs.randn(H, V) * 0.1).astype("float32"),
                         stop_gradient=False)
    b = paddle.to_tensor((rs.randn(V) * 0.1).astype("float32"),
                         stop_gradient=False)
    lbl = paddle.to_tensor(rs.randint(0, V, (N,)).astype("int64"))

    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    w2 = paddle.to_tensor(w.numpy(), stop_gradient=False)
    b2 = paddle.to_tensor(b.numpy(), stop_gradient=False)
    ref = F.cross_entropy(paddle.matmul(x2, w2) + b2, lbl)
    ref.backward()

    fused = fused_linear_cross_entropy(x, w, lbl, bias=b, vocab_chunk=16)
    np.testing.assert_allclose(float(fused.numpy()), float(ref.numpy()),
                               rtol=1e-5)
    fused.backward()
    np.testing.assert_allclose(x.grad.numpy(), x2.grad.numpy(),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(), w2.grad.numpy(),
                               rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), b2.grad.numpy(),
                               rtol=2e-4, atol=1e-5)


def test_fused_ce_transposed_weight_and_ignore_index():
    N, H, V = 48, 16, 77
    x = paddle.to_tensor(rs.randn(N, H).astype("float32"),
                         stop_gradient=False)
    wt = paddle.to_tensor((rs.randn(V, H) * 0.1).astype("float32"),
                         stop_gradient=False)
    lbl_np = rs.randint(0, V, (N,))
    lbl_np[:7] = -100
    lbl = paddle.to_tensor(lbl_np.astype("int64"))

    x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
    wt2 = paddle.to_tensor(wt.numpy(), stop_gradient=False)
    ref = F.cross_entropy(paddle.matmul(x2, paddle.transpose(wt2, [1, 0])),
                          lbl)
    ref.backward()
    fused = fused_linear_cross_entropy(x, wt, lbl, vocab_chunk=32,
                                       transposed_weight=True)
    np.testing.assert_allclose(float(fused.numpy()), float(ref.numpy()),
                               rtol=1e-5)
    fused.backward()
    np.testing.assert_allclose(wt.grad.numpy(), wt2.grad.numpy(),
                               rtol=2e-4, atol=1e-5)


def test_gpt_fused_loss_matches_standard_criterion():
    cfg_args = dict(max_position_embeddings=32)
    paddle.seed(11)
    std = GPTForCausalLM(gpt_presets("gpt-test", **cfg_args), seed=0)
    paddle.seed(11)
    fused = GPTForCausalLM(gpt_presets("gpt-test", fused_loss_chunk=16,
                                       **cfg_args), seed=0)
    crit = GPTPretrainingCriterion()
    ids = paddle.to_tensor(
        rs.randint(0, std.config.vocab_size, (2, 16)).astype("int64"))
    labels = paddle.to_tensor(
        rs.randint(0, std.config.vocab_size, (2, 16)).astype("int64"))

    loss_std = crit(std(ids), labels)
    loss_fused = fused(ids, labels=labels)
    np.testing.assert_allclose(float(loss_fused.numpy()),
                               float(loss_std.numpy()), rtol=1e-4)


def test_gpt_fused_loss_trains_under_trainstep():
    cfg = gpt_presets("gpt-test", max_position_embeddings=32,
                      fused_loss_chunk=16)
    model = GPTForCausalLM(cfg, seed=0)
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = TrainStep(model, lambda loss: loss, optim)
    ids = paddle.to_tensor(
        rs.randint(0, cfg.vocab_size, (4, 16)).astype("int64"))
    labels = paddle.to_tensor(
        rs.randint(0, cfg.vocab_size, (4, 16)).astype("int64"))
    # forward signature is (input_ids, position_ids, labels); loss_fn is
    # identity since the model returns the scalar loss directly
    losses = [float(step(inputs=(ids, None, labels), labels=()))
              for _ in range(6)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_fused_ce_rejects_bad_reduction_and_flags_oob_labels():
    import pytest

    N, H, V = 8, 4, 10
    x = paddle.to_tensor(rs.randn(N, H).astype("float32"))
    w = paddle.to_tensor(rs.randn(H, V).astype("float32"))
    lbl = paddle.to_tensor(rs.randint(0, V, (N,)).astype("int64"))
    with pytest.raises(ValueError):
        fused_linear_cross_entropy(x, w, lbl, reduction="avg")
    # out-of-range label (vocab mismatch) must be LOUD, not silently lse-0
    bad_np = lbl.numpy().copy()
    bad_np[0] = V  # one past the vocab
    bad = paddle.to_tensor(bad_np)
    out = fused_linear_cross_entropy(x, w, bad, vocab_chunk=4,
                                     reduction="none")
    assert np.isnan(out.numpy()[0])
    assert np.isfinite(out.numpy()[1:]).all()


def test_bert_fused_mlm_loss_matches_criterion():
    from paddle_tpu.models import (
        BertForPretraining, BertPretrainingCriterion, bert_presets,
    )

    paddle.seed(5)
    std = BertForPretraining(bert_presets("bert-test"))
    paddle.seed(5)
    fused = BertForPretraining(bert_presets("bert-test",
                                            fused_loss_chunk=32))
    crit = BertPretrainingCriterion()
    B, S, V = 2, 16, std.config.vocab_size
    ids = paddle.to_tensor(rs.randint(0, V, (B, S)).astype("int64"))
    lbl_np = np.full((B, S), -1, "int64")
    lbl_np[:, :4] = rs.randint(0, V, (B, 4))  # 4 masked positions per row
    lbl = paddle.to_tensor(lbl_np)
    nsl = paddle.to_tensor(rs.randint(0, 2, (B,)).astype("int64"))

    logits, nsp = std(ids)
    full_loss = crit(logits, nsp, lbl, nsl)
    # reference criterion = MLM + NSP; fused returns MLM only + nsp logits
    mlm_fused, nsp2 = fused(ids, masked_lm_labels=lbl)

    def nsp_loss(nspv):
        ns = np.asarray(nspv.numpy(), np.float64)
        lse = np.log(np.exp(ns - ns.max(-1, keepdims=True)).sum(-1)) + \
            ns.max(-1)
        pick = ns[np.arange(B), nsl.numpy()]
        return float((lse - pick).mean())

    np.testing.assert_allclose(
        float(mlm_fused.numpy()) + nsp_loss(nsp2),
        float(full_loss.numpy()), rtol=1e-4)


def test_bert_labels_with_chunk_zero_still_returns_loss():
    """masked_lm_labels + fused_loss_chunk=0 must return the SAME (loss,
    nsp) contract (full-logits path), and HF's -100 sentinel masks like
    -1 on both paths."""
    from paddle_tpu.models import BertForPretraining, bert_presets

    paddle.seed(6)
    m0 = BertForPretraining(bert_presets("bert-test"))
    paddle.seed(6)
    m1 = BertForPretraining(bert_presets("bert-test", fused_loss_chunk=32))
    B, S, V = 2, 16, m0.config.vocab_size
    ids = paddle.to_tensor(rs.randint(0, V, (B, S)).astype("int64"))
    lbl_np = np.full((B, S), -100, "int64")  # HF sentinel
    lbl_np[:, :3] = rs.randint(0, V, (B, 3))
    lbl = paddle.to_tensor(lbl_np)
    l0, nsp0 = m0(ids, masked_lm_labels=lbl)
    l1, nsp1 = m1(ids, masked_lm_labels=lbl)
    assert l0.shape == [] or l0.ndim == 0  # scalar loss, not logits
    np.testing.assert_allclose(float(l0.numpy()), float(l1.numpy()),
                               rtol=1e-4)
    assert np.isfinite(float(l1.numpy()))

"""Static-analysis suite + lock-order sanitizer (paddle_tpu/analysis, ISSUE 7).

Three layers of proof:
1. every checker rule has positive AND negative source fixtures;
2. the committed repo is clean against tools/static_baseline.json (and the
   baseline holds zero entries for the swallow/daemon/lock-discipline
   rules — those were fixed, not allowlisted);
3. the runtime lock-order witness reports a seeded ABBA inversion and
   stays silent on clean framework lock traffic.
"""
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.analysis import (  # noqa: E402
    RULES, analyze_sources, diff_against_baseline, findings_to_baseline,
    load_baseline, lock_order)


def _rules(findings):
    return [f.rule for f in findings]


def _one(findings, rule):
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) == 1, f"expected exactly one {rule}, got {findings}"
    return hits[0]


# ---------------------------------------------------------------------------
# C001 — explicit daemon=
# ---------------------------------------------------------------------------

class TestDaemonRule:
    def test_flags_missing_daemon(self):
        src = "import threading\nt = threading.Thread(target=f)\n"
        f = _one(analyze_sources({"m.py": src}), "C001")
        assert f.line == 2

    def test_explicit_daemon_ok(self):
        src = ("import threading\n"
               "t = threading.Thread(target=f, daemon=True)\n"
               "u = threading.Thread(target=f, daemon=False)\n")
        assert "C001" not in _rules(analyze_sources({"m.py": src}))

    def test_kwargs_splat_not_flagged(self):
        src = "import threading\nt = threading.Thread(**kw)\n"
        assert "C001" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_has_no_implicit_daemon_threads(self):
        """Satellite: every framework Thread states its shutdown contract."""
        from paddle_tpu.analysis import analyze_tree
        found = [f for f in analyze_tree(os.path.join(REPO, "paddle_tpu"),
                                         rel_root=REPO) if f.rule == "C001"]
        assert found == []


# ---------------------------------------------------------------------------
# C002 — acquire/release discipline
# ---------------------------------------------------------------------------

class TestAcquireRule:
    def test_flags_bare_acquire(self):
        src = ("lock.acquire()\n"
               "x = 1\n"
               "lock.release()\n")
        f = _one(analyze_sources({"m.py": src}), "C002")
        assert "lock.acquire()" in f.message

    def test_try_finally_release_ok(self):
        src = ("try:\n"
               "    lock.acquire()\n"
               "    x = 1\n"
               "finally:\n"
               "    lock.release()\n")
        assert "C002" not in _rules(analyze_sources({"m.py": src}))

    def test_finally_releasing_other_lock_still_flagged(self):
        src = ("try:\n"
               "    a.acquire()\n"
               "finally:\n"
               "    b.release()\n")
        assert "C002" in _rules(analyze_sources({"m.py": src}))

    def test_acquire_as_condition_ok(self):
        # `if lock.acquire(timeout=1):` is the try-lock idiom, not a leak
        src = ("if lock.acquire(False):\n"
               "    lock.release()\n")
        assert "C002" not in _rules(analyze_sources({"m.py": src}))

    def test_with_statement_ok(self):
        src = "with lock:\n    x = 1\n"
        assert "C002" not in _rules(analyze_sources({"m.py": src}))


# ---------------------------------------------------------------------------
# C003 — no silent swallows
# ---------------------------------------------------------------------------

class TestSwallowRule:
    def test_flags_except_exception_pass(self):
        src = ("try:\n    f()\nexcept Exception:\n    pass\n")
        assert "C003" in _rules(analyze_sources({"m.py": src}))

    def test_flags_bare_except_pass(self):
        src = ("try:\n    f()\nexcept:\n    pass\n")
        assert "C003" in _rules(analyze_sources({"m.py": src}))

    def test_flags_base_exception_ellipsis(self):
        src = ("try:\n    f()\nexcept BaseException:\n    ...\n")
        assert "C003" in _rules(analyze_sources({"m.py": src}))

    def test_narrow_type_ok(self):
        src = ("try:\n    f()\nexcept OSError:\n    pass\n")
        assert "C003" not in _rules(analyze_sources({"m.py": src}))

    def test_recording_body_ok(self):
        src = ("try:\n    f()\nexcept Exception:\n    log.warning('x')\n")
        assert "C003" not in _rules(analyze_sources({"m.py": src}))

    def test_inline_waiver(self):
        src = ("try:\n    f()\n"
               "except Exception:   # lint-ok: C003 teardown guard\n"
               "    pass\n")
        assert "C003" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_swallow_sites_are_fixed(self):
        """Satellite: the 9 seed `except Exception: pass` sites are gone
        (narrowed or recording), not baselined."""
        from paddle_tpu.analysis import analyze_tree
        found = [f for f in analyze_tree(os.path.join(REPO, "paddle_tpu"),
                                         rel_root=REPO) if f.rule == "C003"]
        assert found == []


# ---------------------------------------------------------------------------
# C004 — lock-owning modules guard global writes
# ---------------------------------------------------------------------------

class TestGlobalMutationRule:
    LOCKED_MODULE = ("import threading\n"
                     "_lock = threading.Lock()\n"
                     "_state = None\n")

    def test_flags_unguarded_global_write(self):
        src = self.LOCKED_MODULE + (
            "def set_state(v):\n"
            "    global _state\n"
            "    _state = v\n")
        f = _one(analyze_sources({"m.py": src}), "C004")
        assert "_state" in f.message and "set_state" in f.message

    def test_guarded_write_ok(self):
        src = self.LOCKED_MODULE + (
            "def set_state(v):\n"
            "    global _state\n"
            "    with _lock:\n"
            "        _state = v\n")
        assert "C004" not in _rules(analyze_sources({"m.py": src}))

    def test_module_without_lock_not_flagged(self):
        src = ("_state = None\n"
               "def set_state(v):\n"
               "    global _state\n"
               "    _state = v\n")
        assert "C004" not in _rules(analyze_sources({"m.py": src}))

    def test_read_only_global_decl_ok(self):
        src = self.LOCKED_MODULE + (
            "def get_state():\n"
            "    global _state\n"
            "    return _state\n")
        assert "C004" not in _rules(analyze_sources({"m.py": src}))


# ---------------------------------------------------------------------------
# X001/X002/X003 — collective safety
# ---------------------------------------------------------------------------

class TestCollectiveSafety:
    def test_raw_primitive_outside_distributed_flagged(self):
        src = "import jax\ny = jax.lax.psum(x, 'dp')\n"
        f = _one(analyze_sources({"paddle_tpu/models/m.py": src}), "X001")
        assert "psum" in f.message

    def test_raw_primitive_inside_distributed_ok(self):
        src = "import jax\ny = jax.lax.psum(x, 'dp')\n"
        path = "paddle_tpu/distributed/ring.py"
        assert "X001" not in _rules(analyze_sources({path: src}))

    def test_execute_collective_outside_layer_flagged(self):
        src = ("from paddle_tpu.robustness.distributed_ft import "
               "execute_collective\n"
               "execute_collective('x', g, f)\n")
        found = analyze_sources({"paddle_tpu/io/m.py": src})
        assert _rules(found).count("X002") == 2  # import + call

    def test_eager_thunk_must_be_guarded(self):
        path = "paddle_tpu/distributed/collective.py"
        bad = ("def all_reduce(t):\n"
               "    def _eager():\n"
               "        return backend(t)\n"
               "    return _eager()\n")
        f = _one(analyze_sources({path: bad}), "X002")
        assert "_eager" in f.message
        good = ("def all_reduce(t):\n"
                "    def _eager():\n"
                "        return backend(t)\n"
                "    return _guarded('all_reduce', g, _eager)\n")
        assert "X002" not in _rules(analyze_sources({path: good}))

    def test_rank_conditional_collective_flagged(self):
        src = ("if get_rank() == 0:\n"
               "    dist.all_reduce(t)\n")
        f = _one(analyze_sources({"paddle_tpu/io/m.py": src}), "X003")
        assert "all_reduce" in f.message

    def test_rank_conditional_symmetric_ok(self):
        src = ("if get_rank() == 0:\n"
               "    dist.broadcast(t, src=0)\n"
               "else:\n"
               "    dist.broadcast(t, src=0)\n")
        assert "X003" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_rank_conditional_no_collective_ok(self):
        src = ("if get_rank() == 0:\n"
               "    print('hello from rank 0')\n")
        assert "X003" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))


# ---------------------------------------------------------------------------
# T001 — trace purity
# ---------------------------------------------------------------------------

class TestTracePurity:
    def test_wallclock_in_jitted_fn_flagged(self):
        src = ("import jax, time\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    t = time.time()\n"
               "    return x + t\n")
        f = _one(analyze_sources({"m.py": src}), "T001")
        assert "time.time" in f.message and "step" in f.message

    def test_host_rng_in_scan_body_flagged(self):
        src = ("import jax, random\n"
               "def body(c, x):\n"
               "    return c + random.random(), x\n"
               "out = jax.lax.scan(body, 0.0, xs)\n")
        f = _one(analyze_sources({"m.py": src}), "T001")
        assert "random" in f.message

    def test_item_sync_in_shard_map_fn_flagged(self):
        src = ("def f(x):\n"
               "    return x.item()\n"
               "g = compat_shard_map(f, mesh, in_specs, out_specs)\n")
        assert "T001" in _rules(analyze_sources({"m.py": src}))

    def test_wallclock_outside_trace_ok(self):
        src = ("import time\n"
               "def host_step(x):\n"
               "    return time.time()\n")
        assert "T001" not in _rules(analyze_sources({"m.py": src}))

    def test_pure_traced_fn_ok(self):
        src = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return x * 2\n")
        assert "T001" not in _rules(analyze_sources({"m.py": src}))


# ---------------------------------------------------------------------------
# R001/R002 — registry drift
# ---------------------------------------------------------------------------

FLAGS_FIXTURE = ('_FLAGS = {\n'
                 '    "FLAGS_known": False,\n'
                 '}\n')


class TestRegistryDrift:
    def test_undeclared_flag_read_flagged(self):
        srcs = {
            "paddle_tpu/framework/flags.py": FLAGS_FIXTURE,
            "paddle_tpu/io/m.py": 'v = flag("FLAGS_mystery", 0)\n',
        }
        f = _one(analyze_sources(srcs), "R001")
        assert "FLAGS_mystery" in f.message

    def test_declared_flag_ok(self):
        srcs = {
            "paddle_tpu/framework/flags.py": FLAGS_FIXTURE,
            "paddle_tpu/io/m.py": 'v = flag("FLAGS_known", 0)\n',
        }
        assert "R001" not in _rules(analyze_sources(srcs))

    def test_repo_flags_all_declared(self):
        """FLAGS_selected_tpus was the live drift PR 7 found: read by
        distributed/env.py, set by launch/main.py, declared nowhere."""
        from paddle_tpu.analysis import analyze_tree
        found = [f for f in analyze_tree(os.path.join(REPO, "paddle_tpu"),
                                         rel_root=REPO) if f.rule == "R001"]
        assert found == []
        from paddle_tpu.framework import flags
        assert "FLAGS_selected_tpus" in flags._FLAGS
        assert "FLAGS_lock_order_check" in flags._FLAGS

    def test_label_set_mismatch_at_bind_flagged(self):
        src = ('_m = reg.counter("x_total", labels=("op",))\n'
               '_m.labels(kind="y").inc()\n')
        f = _one(analyze_sources({"paddle_tpu/io/m.py": src}), "R002")
        assert "x_total" in f.message

    def test_matching_bind_ok(self):
        src = ('_m = reg.counter("x_total", labels=("op",))\n'
               '_m.labels(op="y").inc()\n'
               '_b = _m.bind(op="z")\n')
        assert "R002" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_conflicting_redeclaration_flagged(self):
        srcs = {
            "paddle_tpu/a.py": '_m = reg.counter("x_total", labels=("op",))\n',
            "paddle_tpu/b.py": '_m = reg.counter("x_total", labels=("kind",))\n',
        }
        assert "R002" in _rules(analyze_sources(srcs))


# ---------------------------------------------------------------------------
# S001 — lane-launched gathers free on all paths (ISSUE 9)
# ---------------------------------------------------------------------------

_S001_LEAKY = (
    "class Store:\n"
    "    def prefetch(self, i):\n"
    "        self._lane.submit(lambda: None)\n"
    "    def use(self, i):\n"
    "        self.ensure_gathered(i)\n"
    "        work(i)\n"
    "        self.free_bucket(i)\n"   # normal exit only — leaks on raise
)

_S001_CLEAN = (
    "class Store:\n"
    "    def prefetch(self, i):\n"
    "        self._lane.submit(lambda: None)\n"
    "    def use(self, i):\n"
    "        try:\n"
    "            self.ensure_gathered(i)\n"
    "            work(i)\n"
    "        finally:\n"
    "            self.free_bucket(i)\n"
)


class TestLaneGatherReleaseRule:
    def test_flags_module_without_finally_release(self):
        f = _one(analyze_sources({"m.py": _S001_LEAKY}), "S001")
        assert "finally" in f.message

    def test_release_in_finally_ok(self):
        assert "S001" not in _rules(analyze_sources({"m.py": _S001_CLEAN}))

    def test_lane_submit_without_gathers_not_flagged(self):
        # the grad lane (overlap.py shape): submits, but never acquires
        # gathered buffers — not a gather client
        src = ("class Comm:\n"
               "    def launch(self, b):\n"
               "        self._lane.submit(lambda: None)\n")
        assert "S001" not in _rules(analyze_sources({"m.py": src}))

    def test_gathers_without_lane_not_flagged(self):
        # ensure/free helpers with no lane in sight are out of scope
        src = ("def f(s):\n"
               "    s.ensure_gathered(0)\n")
        assert "S001" not in _rules(analyze_sources({"m.py": src}))

    def test_stage3_store_is_clean(self):
        """The real lane gather client (distributed/sharding/stage3.py)
        carries the all-paths release (materialize()'s finally)."""
        from paddle_tpu.analysis import analyze_tree

        found = [f for f in analyze_tree(os.path.join(REPO, "paddle_tpu"),
                                         rel_root=REPO)
                 if f.rule == "S001"]
        assert found == []


# ---------------------------------------------------------------------------
# S002 — signal handlers only set flags/latches
# ---------------------------------------------------------------------------

_S002_LOGGING = (
    "import logging\n"
    "import signal\n"
    "def handler(signum, frame):\n"
    "    logging.getLogger(__name__).warning('preempted %s', signum)\n"
    "signal.signal(signal.SIGTERM, handler)\n"
)

_S002_LOCK = (
    "import signal\n"
    "class H:\n"
    "    def _on_term(self, signum, frame):\n"
    "        self._lock.acquire()\n"
    "        self.preempted = True\n"
    "    def install(self):\n"
    "        signal.signal(signal.SIGTERM, self._on_term)\n"
)

_S002_CLEAN = (
    "import signal\n"
    "class H:\n"
    "    def _handler(self, signum, frame):\n"
    "        self._signum = signum\n"
    "        self._latch.set()\n"
    "    def install(self):\n"
    "        signal.signal(signal.SIGTERM, self._handler)\n"
)


class TestSignalSafetyRule:
    def test_flags_logging_in_handler(self):
        f = _one(analyze_sources({"m.py": _S002_LOGGING}), "S002")
        assert "handler" in f.message and "latch" in f.message

    def test_flags_lock_acquire_in_method_handler(self):
        f = _one(analyze_sources({"m.py": _S002_LOCK}), "S002")
        assert "_on_term" in f.message

    def test_latch_only_body_ok(self):
        assert "S002" not in _rules(analyze_sources({"m.py": _S002_CLEAN}))

    def test_lambda_handlers_checked(self):
        bad = ("import signal\n"
               "signal.signal(signal.SIGTERM, lambda s, f: print(s))\n")
        assert "S002" in _rules(analyze_sources({"m.py": bad}))
        ok = ("import signal\n"
              "signal.signal(signal.SIGTERM, lambda s, f: latch.set())\n")
        assert "S002" not in _rules(analyze_sources({"m.py": ok}))

    def test_unresolvable_handler_skipped(self):
        # an imported/dynamic handler cannot be analyzed here — no false
        # positive
        src = ("import signal\n"
               "from other import handler\n"
               "signal.signal(signal.SIGTERM, handler)\n")
        assert "S002" not in _rules(analyze_sources({"m.py": src}))

    def test_send_signal_is_not_registration(self):
        # launch/main.py shape: SENDING a signal is not registering a
        # handler
        src = ("import signal\n"
               "def stop(q):\n"
               "    q.send_signal(signal.SIGTERM)\n")
        assert "S002" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_handlers_are_latch_only(self):
        """The real PreemptionHandler (robustness/preemption.py) obeys its
        own contract — the repo stays S002-clean."""
        from paddle_tpu.analysis import analyze_tree

        found = [f for f in analyze_tree(os.path.join(REPO, "paddle_tpu"),
                                         rel_root=REPO)
                 if f.rule == "S002"]
        assert found == []


# ---------------------------------------------------------------------------
# engine: baseline diff + waivers
# ---------------------------------------------------------------------------

class TestEngine:
    def test_baseline_roundtrip_clean(self):
        src = {"m.py": "import threading\nt = threading.Thread(target=f)\n"}
        findings = analyze_sources(src)
        baseline = findings_to_baseline(findings)["entries"]
        new, stale = diff_against_baseline(findings, baseline)
        assert new == [] and stale == []

    def test_new_finding_detected(self):
        src = {"m.py": "import threading\nt = threading.Thread(target=f)\n"}
        new, stale = diff_against_baseline(analyze_sources(src), [])
        assert len(new) == 1 and stale == []

    def test_stale_entry_detected(self):
        ghost = [{"rule": "C001", "path": "gone.py",
                  "message": "threading.Thread(...) without explicit daemon="}]
        new, stale = diff_against_baseline([], ghost)
        assert new == [] and len(stale) == 1

    def test_multiplicity_matters(self):
        src = {"m.py": ("import threading\n"
                        "t = threading.Thread(target=f)\n"
                        "u = threading.Thread(target=f)\n")}
        findings = analyze_sources(src)
        assert len(findings) == 2
        one = findings_to_baseline(findings[:1])["entries"]
        new, stale = diff_against_baseline(findings, one)
        assert len(new) == 1 and stale == []

    def test_every_rule_documented(self):
        for rule in ("C001", "C002", "C003", "C004", "X001", "X002", "X003",
                     "T001", "R001", "R002", "S001", "S002"):
            assert rule in RULES
            invariant, rationale = RULES[rule]
            assert invariant and rationale


# ---------------------------------------------------------------------------
# the tier-1 gate itself
# ---------------------------------------------------------------------------

class TestCheckStaticGate:
    def _main(self):
        spec = importlib.util.spec_from_file_location(
            "check_static", os.path.join(REPO, "tools", "check_static.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main

    def test_repo_clean_against_committed_baseline(self):
        t0 = time.perf_counter()
        rc = self._main()([])
        assert rc == 0
        assert time.perf_counter() - t0 < 30.0  # tier-1 budget contract

    def test_baseline_has_no_allowlisted_discipline_findings(self):
        """Acceptance: swallow/daemon/lock-discipline entries were FIXED,
        so the baseline holds zero of them."""
        entries = load_baseline(
            os.path.join(REPO, "tools", "static_baseline.json"))
        rules_in_baseline = {e["rule"] for e in entries}
        assert rules_in_baseline.isdisjoint({"C001", "C002", "C003"})
        for e in entries:       # remaining debt is documented
            assert e.get("reason"), f"baseline entry missing reason: {e}"

    def test_exit_1_on_new_finding(self, tmp_path):
        bad = tmp_path / "m.py"
        bad.write_text("import threading\nt = threading.Thread(target=f)\n")
        empty = tmp_path / "baseline.json"
        empty.write_text('{"entries": []}')
        rc = self._main()(["--root", str(tmp_path),
                           "--baseline", str(empty)])
        assert rc == 1

    def test_exit_2_on_stale_entry(self, tmp_path):
        clean = tmp_path / "m.py"
        clean.write_text("x = 1\n")
        stale = tmp_path / "baseline.json"
        stale.write_text(json.dumps({"entries": [{
            "rule": "C001", "path": "m.py", "line": 1,
            "message": "threading.Thread(...) without explicit daemon="}]}))
        rc = self._main()(["--root", str(tmp_path),
                           "--baseline", str(stale)])
        assert rc == 2

    def test_cli_exit_code(self):
        """The committed gate command CI runs, end to end."""
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_static.py")],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "OK: clean against baseline" in p.stdout


# ---------------------------------------------------------------------------
# lock-order sanitizer
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_seeded_abba_inversion_detected(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)
        with A:
            with B:
                pass
        with B:        # the inversion — never actually deadlocks here,
            with A:    # but the ORDER violation is still witnessed
                pass
        cycles = g.cycles()
        assert cycles == [["A", "B"]]
        rep = g.report()
        assert rep["cycle_lock_names"] == ["A", "B"]
        edge = rep["cycles"][0]["edges"][0]
        assert edge["count"] >= 1 and edge["thread"]

    def test_three_lock_cycle_detected(self):
        g = lock_order.LockOrderGraph()
        a, b, c = (lock_order.WitnessLock(threading.Lock(), n, g)
                   for n in "abc")
        for first, second in ((a, b), (b, c), (c, a)):
            with first:
                with second:
                    pass
        assert g.cycles() == [["a", "b", "c"]]

    def test_consistent_order_is_silent(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)
        for _ in range(3):
            with A:
                with B:
                    pass
        assert g.cycles() == []
        assert g.report()["edge_count"] == 1

    def test_cross_thread_edges_recorded(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)

        def t1():
            with A:
                with B:
                    pass

        def t2():
            with B:
                with A:
                    pass

        th1 = threading.Thread(target=t1, daemon=True)
        th1.start(); th1.join()
        th2 = threading.Thread(target=t2, daemon=True)
        th2.start(); th2.join()
        assert g.cycles() == [["A", "B"]]

    def test_release_out_of_order(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)
        A.acquire(); B.acquire()
        A.release(); B.release()     # non-LIFO release must not corrupt
        with B:
            pass
        assert g.cycles() == []

    def test_works_as_condition_lock(self):
        g = lock_order.LockOrderGraph()
        w = lock_order.WitnessLock(threading.Lock(), "cv", g)
        cv = threading.Condition(w)
        hits = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                hits.append(1)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert hits == [1]

    def test_install_instruments_only_paddle_tpu_locks(self):
        g = lock_order.LockOrderGraph()
        was_installed = lock_order.installed()
        lock_order.uninstall()
        lock_order.install(g)
        try:
            here = threading.Lock()           # test file: raw
            assert not isinstance(here, lock_order.WitnessLock)
            ns = {}
            code = compile("import threading\nL = threading.Lock()\n",
                           "/x/paddle_tpu/fake/mod.py", "exec")
            exec(code, ns)
            assert isinstance(ns["L"], lock_order.WitnessLock)
            assert "paddle_tpu/fake/mod.py" in ns["L"].name
        finally:
            lock_order.uninstall()
            if was_installed:      # restore the session-level witness
                lock_order.install()

    def test_clean_on_real_framework_traffic(self):
        """Silence proof: when tier-1 runs with FLAGS_lock_order_check the
        global graph must hold no cycles; otherwise exercise real lock
        nesting (collective lane + event log + metrics) under a local
        install and prove the same."""
        if lock_order.installed():
            assert lock_order.get_graph().cycles() == []
            return
        g = lock_order.LockOrderGraph()
        lock_order.install(g)
        try:
            ns = {}
            code = compile(
                "import threading\n"
                "outer = threading.Lock()\n"
                "inner = threading.Lock()\n",
                "/x/paddle_tpu/fake/lane.py", "exec")
            exec(code, ns)
            from paddle_tpu.distributed.overlap import CollectiveLane
            from paddle_tpu.observability.events import get_event_log
            lane = CollectiveLane(name="sanitizer-test-lane")
            done = []
            for i in range(4):
                def job(i=i):
                    with ns["outer"]:
                        with ns["inner"]:
                            get_event_log().debug("sanitizer", f"job{i}")
                    done.append(i)
                lane.submit(job)
            deadline = time.time() + 10
            while len(done) < 4 and time.time() < deadline:
                time.sleep(0.01)
            assert len(done) == 4
            assert g.cycles() == []
        finally:
            lock_order.uninstall()

    def test_thread_leak_report(self):
        stop = threading.Event()
        t = threading.Thread(target=stop.wait, name="leaky-nondaemon",
                             daemon=False)
        t.start()
        try:
            leaks = lock_order.thread_leak_report(set())
            assert any(l["name"] == "leaky-nondaemon" for l in leaks)
        finally:
            stop.set()
            t.join(timeout=5)
        leaks = lock_order.thread_leak_report(set())
        assert not any(l["name"] == "leaky-nondaemon" for l in leaks)

    def test_flag_installs_witness(self):
        """set_flags({'FLAGS_lock_order_check': True}) wires install()."""
        import paddle_tpu
        was = lock_order.installed()
        try:
            paddle_tpu.set_flags({"FLAGS_lock_order_check": True})
            assert lock_order.installed()
        finally:
            if not was:
                lock_order.uninstall()
            paddle_tpu.set_flags({"FLAGS_lock_order_check": was})

"""Static-analysis suite + lock-order sanitizer (paddle_tpu/analysis, ISSUE 7).

Three layers of proof:
1. every checker rule has positive AND negative source fixtures;
2. the committed repo is clean against tools/static_baseline.json (and the
   baseline holds zero entries for the swallow/daemon/lock-discipline
   rules — those were fixed, not allowlisted);
3. the runtime lock-order witness reports a seeded ABBA inversion and
   stays silent on clean framework lock traffic.
"""
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.analysis import (  # noqa: E402
    RULES, analyze_sources, diff_against_baseline, findings_to_baseline,
    load_baseline, lock_order)


_REPO_RUN = None


def _repo_analysis():
    """One shared project-wide run for every repo-clean assertion (the
    full interprocedural pass costs ~3.5s; the new-rule tests reuse one
    result instead of re-running it per test)."""
    global _REPO_RUN
    if _REPO_RUN is None:
        from paddle_tpu.analysis import Analysis, default_checkers
        a = Analysis(default_checkers(), rel_root=REPO)
        findings = a.run_path(os.path.join(REPO, "paddle_tpu"))
        _REPO_RUN = (findings, a)
    return _REPO_RUN


def _rules(findings):
    return [f.rule for f in findings]


def _one(findings, rule):
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) == 1, f"expected exactly one {rule}, got {findings}"
    return hits[0]


# ---------------------------------------------------------------------------
# C001 — explicit daemon=
# ---------------------------------------------------------------------------

class TestDaemonRule:
    def test_flags_missing_daemon(self):
        src = "import threading\nt = threading.Thread(target=f)\n"
        f = _one(analyze_sources({"m.py": src}), "C001")
        assert f.line == 2

    def test_explicit_daemon_ok(self):
        src = ("import threading\n"
               "t = threading.Thread(target=f, daemon=True)\n"
               "u = threading.Thread(target=f, daemon=False)\n")
        assert "C001" not in _rules(analyze_sources({"m.py": src}))

    def test_kwargs_splat_not_flagged(self):
        src = "import threading\nt = threading.Thread(**kw)\n"
        assert "C001" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_has_no_implicit_daemon_threads(self):
        """Satellite: every framework Thread states its shutdown contract."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "C001"] == []


# ---------------------------------------------------------------------------
# C002 — acquire/release discipline
# ---------------------------------------------------------------------------

class TestAcquireRule:
    def test_flags_bare_acquire(self):
        src = ("lock.acquire()\n"
               "x = 1\n"
               "lock.release()\n")
        f = _one(analyze_sources({"m.py": src}), "C002")
        assert "lock.acquire()" in f.message

    def test_try_finally_release_ok(self):
        src = ("try:\n"
               "    lock.acquire()\n"
               "    x = 1\n"
               "finally:\n"
               "    lock.release()\n")
        assert "C002" not in _rules(analyze_sources({"m.py": src}))

    def test_finally_releasing_other_lock_still_flagged(self):
        src = ("try:\n"
               "    a.acquire()\n"
               "finally:\n"
               "    b.release()\n")
        assert "C002" in _rules(analyze_sources({"m.py": src}))

    def test_acquire_as_condition_ok(self):
        # `if lock.acquire(timeout=1):` is the try-lock idiom, not a leak
        src = ("if lock.acquire(False):\n"
               "    lock.release()\n")
        assert "C002" not in _rules(analyze_sources({"m.py": src}))

    def test_with_statement_ok(self):
        src = "with lock:\n    x = 1\n"
        assert "C002" not in _rules(analyze_sources({"m.py": src}))


# ---------------------------------------------------------------------------
# C003 — no silent swallows
# ---------------------------------------------------------------------------

class TestSwallowRule:
    def test_flags_except_exception_pass(self):
        src = ("try:\n    f()\nexcept Exception:\n    pass\n")
        assert "C003" in _rules(analyze_sources({"m.py": src}))

    def test_flags_bare_except_pass(self):
        src = ("try:\n    f()\nexcept:\n    pass\n")
        assert "C003" in _rules(analyze_sources({"m.py": src}))

    def test_flags_base_exception_ellipsis(self):
        src = ("try:\n    f()\nexcept BaseException:\n    ...\n")
        assert "C003" in _rules(analyze_sources({"m.py": src}))

    def test_narrow_type_ok(self):
        src = ("try:\n    f()\nexcept OSError:\n    pass\n")
        assert "C003" not in _rules(analyze_sources({"m.py": src}))

    def test_recording_body_ok(self):
        src = ("try:\n    f()\nexcept Exception:\n    log.warning('x')\n")
        assert "C003" not in _rules(analyze_sources({"m.py": src}))

    def test_inline_waiver(self):
        src = ("try:\n    f()\n"
               "except Exception:   # lint-ok: C003 teardown guard\n"
               "    pass\n")
        assert "C003" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_swallow_sites_are_fixed(self):
        """Satellite: the 9 seed `except Exception: pass` sites are gone
        (narrowed or recording), not baselined."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "C003"] == []


# ---------------------------------------------------------------------------
# C004 — lock-owning modules guard global writes
# ---------------------------------------------------------------------------

class TestGlobalMutationRule:
    LOCKED_MODULE = ("import threading\n"
                     "_lock = threading.Lock()\n"
                     "_state = None\n")

    def test_flags_unguarded_global_write(self):
        src = self.LOCKED_MODULE + (
            "def set_state(v):\n"
            "    global _state\n"
            "    _state = v\n")
        f = _one(analyze_sources({"m.py": src}), "C004")
        assert "_state" in f.message and "set_state" in f.message

    def test_guarded_write_ok(self):
        src = self.LOCKED_MODULE + (
            "def set_state(v):\n"
            "    global _state\n"
            "    with _lock:\n"
            "        _state = v\n")
        assert "C004" not in _rules(analyze_sources({"m.py": src}))

    def test_module_without_lock_not_flagged(self):
        src = ("_state = None\n"
               "def set_state(v):\n"
               "    global _state\n"
               "    _state = v\n")
        assert "C004" not in _rules(analyze_sources({"m.py": src}))

    def test_read_only_global_decl_ok(self):
        src = self.LOCKED_MODULE + (
            "def get_state():\n"
            "    global _state\n"
            "    return _state\n")
        assert "C004" not in _rules(analyze_sources({"m.py": src}))


# ---------------------------------------------------------------------------
# X001/X002/X003 — collective safety
# ---------------------------------------------------------------------------

class TestCollectiveSafety:
    def test_raw_primitive_outside_distributed_flagged(self):
        src = "import jax\ny = jax.lax.psum(x, 'dp')\n"
        f = _one(analyze_sources({"paddle_tpu/models/m.py": src}), "X001")
        assert "psum" in f.message

    def test_raw_primitive_inside_distributed_ok(self):
        src = "import jax\ny = jax.lax.psum(x, 'dp')\n"
        path = "paddle_tpu/distributed/ring.py"
        assert "X001" not in _rules(analyze_sources({path: src}))

    def test_execute_collective_outside_layer_flagged(self):
        src = ("from paddle_tpu.robustness.distributed_ft import "
               "execute_collective\n"
               "execute_collective('x', g, f)\n")
        found = analyze_sources({"paddle_tpu/io/m.py": src})
        assert _rules(found).count("X002") == 2  # import + call

    def test_eager_thunk_must_be_guarded(self):
        path = "paddle_tpu/distributed/collective.py"
        bad = ("def all_reduce(t):\n"
               "    def _eager():\n"
               "        return backend(t)\n"
               "    return _eager()\n")
        f = _one(analyze_sources({path: bad}), "X002")
        assert "_eager" in f.message
        good = ("def all_reduce(t):\n"
                "    def _eager():\n"
                "        return backend(t)\n"
                "    return _guarded('all_reduce', g, _eager)\n")
        assert "X002" not in _rules(analyze_sources({path: good}))

    def test_rank_conditional_collective_flagged(self):
        src = ("if get_rank() == 0:\n"
               "    dist.all_reduce(t)\n")
        f = _one(analyze_sources({"paddle_tpu/io/m.py": src}), "X003")
        assert "all_reduce" in f.message

    def test_rank_conditional_symmetric_ok(self):
        src = ("if get_rank() == 0:\n"
               "    dist.broadcast(t, src=0)\n"
               "else:\n"
               "    dist.broadcast(t, src=0)\n")
        assert "X003" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_rank_conditional_no_collective_ok(self):
        src = ("if get_rank() == 0:\n"
               "    print('hello from rank 0')\n")
        assert "X003" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))


# ---------------------------------------------------------------------------
# T001 — trace purity
# ---------------------------------------------------------------------------

class TestTracePurity:
    def test_wallclock_in_jitted_fn_flagged(self):
        src = ("import jax, time\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    t = time.time()\n"
               "    return x + t\n")
        f = _one(analyze_sources({"m.py": src}), "T001")
        assert "time.time" in f.message and "step" in f.message

    def test_host_rng_in_scan_body_flagged(self):
        src = ("import jax, random\n"
               "def body(c, x):\n"
               "    return c + random.random(), x\n"
               "out = jax.lax.scan(body, 0.0, xs)\n")
        f = _one(analyze_sources({"m.py": src}), "T001")
        assert "random" in f.message

    def test_item_sync_in_shard_map_fn_flagged(self):
        src = ("def f(x):\n"
               "    return x.item()\n"
               "g = compat_shard_map(f, mesh, in_specs, out_specs)\n")
        assert "T001" in _rules(analyze_sources({"m.py": src}))

    def test_wallclock_outside_trace_ok(self):
        src = ("import time\n"
               "def host_step(x):\n"
               "    return time.time()\n")
        assert "T001" not in _rules(analyze_sources({"m.py": src}))

    def test_pure_traced_fn_ok(self):
        src = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return x * 2\n")
        assert "T001" not in _rules(analyze_sources({"m.py": src}))


# ---------------------------------------------------------------------------
# R001/R002 — registry drift
# ---------------------------------------------------------------------------

FLAGS_FIXTURE = ('_FLAGS = {\n'
                 '    "FLAGS_known": False,\n'
                 '}\n')


class TestRegistryDrift:
    def test_undeclared_flag_read_flagged(self):
        srcs = {
            "paddle_tpu/framework/flags.py": FLAGS_FIXTURE,
            "paddle_tpu/io/m.py": 'v = flag("FLAGS_mystery", 0)\n',
        }
        f = _one(analyze_sources(srcs), "R001")
        assert "FLAGS_mystery" in f.message

    def test_declared_flag_ok(self):
        srcs = {
            "paddle_tpu/framework/flags.py": FLAGS_FIXTURE,
            "paddle_tpu/io/m.py": 'v = flag("FLAGS_known", 0)\n',
        }
        assert "R001" not in _rules(analyze_sources(srcs))

    def test_repo_flags_all_declared(self):
        """FLAGS_selected_tpus was the live drift PR 7 found: read by
        distributed/env.py, set by launch/main.py, declared nowhere."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "R001"] == []
        from paddle_tpu.framework import flags
        assert "FLAGS_selected_tpus" in flags._FLAGS
        assert "FLAGS_lock_order_check" in flags._FLAGS

    def test_label_set_mismatch_at_bind_flagged(self):
        src = ('_m = reg.counter("x_total", labels=("op",))\n'
               '_m.labels(kind="y").inc()\n')
        f = _one(analyze_sources({"paddle_tpu/io/m.py": src}), "R002")
        assert "x_total" in f.message

    def test_matching_bind_ok(self):
        src = ('_m = reg.counter("x_total", labels=("op",))\n'
               '_m.labels(op="y").inc()\n'
               '_b = _m.bind(op="z")\n')
        assert "R002" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_conflicting_redeclaration_flagged(self):
        srcs = {
            "paddle_tpu/a.py": '_m = reg.counter("x_total", labels=("op",))\n',
            "paddle_tpu/b.py": '_m = reg.counter("x_total", labels=("kind",))\n',
        }
        assert "R002" in _rules(analyze_sources(srcs))


# ---------------------------------------------------------------------------
# F001 — path-aware lane-gather release (ISSUE 12, supersedes S001)
# ---------------------------------------------------------------------------

_F001_LEAKY = (
    "class Store:\n"
    "    def prefetch(self, i):\n"
    "        self._lane.submit(lambda: None)\n"
    "    def use(self, i):\n"
    "        self.ensure_gathered(i)\n"
    "        work(i)\n"
    "        self.free_bucket(i)\n"   # normal exit only — leaks on raise
)

_F001_CLEAN = (
    "class Store:\n"
    "    def prefetch(self, i):\n"
    "        self._lane.submit(lambda: None)\n"
    "    def use(self, i):\n"
    "        try:\n"
    "            self.ensure_gathered(i)\n"
    "            work(i)\n"
    "        finally:\n"
    "            self.free_bucket(i)\n"
)


class TestLaneGatherReleaseRule:
    def test_flags_unprotected_acquire_exception_path(self):
        # old S001 shape: no finally — now flagged WITH the leaking path
        f = _one(analyze_sources({"m.py": _F001_LEAKY}), "F001")
        assert "path" in f.message and "use()" in f.message

    def test_release_in_finally_ok(self):
        assert "F001" not in _rules(analyze_sources({"m.py": _F001_CLEAN}))

    def test_early_return_between_acquire_and_release_flagged(self):
        src = (
            "class Store:\n"
            "    def prefetch(self, i):\n"
            "        self._lane.submit(lambda: None)\n"
            "    def use(self, i):\n"
            "        try:\n"
            "            self.ensure_gathered(i)\n"
            "            if bad():\n"
            "                return None\n"          # leaks: skips finally?
            "            out = work(i)\n"
            "        finally:\n"
            "            pass\n"
            "        self.free_bucket(i)\n"
            "        return out\n")
        # the finally releases NOTHING; both the return path and the
        # exception path leak
        f = _one(analyze_sources({"m.py": src}), "F001")
        assert "free/release" in f.message

    def test_handler_return_without_release_flagged(self):
        src = (
            "class Store:\n"
            "    def prefetch(self, i):\n"
            "        self._lane.submit(lambda: None)\n"
            "    def use(self, i):\n"
            "        self.ensure_gathered(i)\n"
            "        try:\n"
            "            work(i)\n"
            "        except Exception:\n"
            "            return None\n"              # exception path leaks
            "        self.free_bucket(i)\n")
        assert "F001" in _rules(analyze_sources({"m.py": src}))

    def test_release_loop_in_finally_discharges_acquire_loop(self):
        # the stage3 materialize() shape: acquire-loop in try, free-loop
        # in finally — the loop-head kill lift must prove it clean
        src = (
            "class Store:\n"
            "    def prefetch(self, i):\n"
            "        self._lane.submit(lambda: None)\n"
            "    def use_all(self):\n"
            "        try:\n"
            "            for b in self.buckets:\n"
            "                self.ensure_gathered(b.index)\n"
            "            work()\n"
            "        finally:\n"
            "            for b in self.buckets:\n"
            "                self.free_bucket(b.index)\n")
        assert "F001" not in _rules(analyze_sources({"m.py": src}))

    def test_module_with_no_release_anywhere_flagged(self):
        # S001's module-level verdict survives the supersession
        src = ("class Store:\n"
               "    def prefetch(self, i):\n"
               "        self._lane.submit(lambda: None)\n"
               "    def use(self, i):\n"
               "        self.ensure_gathered(i)\n")
        f = _one(analyze_sources({"m.py": src}), "F001")
        assert "no free/release call at all" in f.message

    def test_s001_waiver_still_suppresses(self):
        src = ("class Store:\n"
               "    def prefetch(self, i):\n"
               "        self._lane.submit(lambda: None)\n"
               "    def use(self, i):\n"
               "        self.ensure_gathered(i)  "
               "# lint-ok: S001 legacy waiver\n")
        assert "F001" not in _rules(analyze_sources({"m.py": src}))

    def test_lane_submit_without_gathers_not_flagged(self):
        # the grad lane (overlap.py shape): submits, but never acquires
        # gathered buffers — not a gather client
        src = ("class Comm:\n"
               "    def launch(self, b):\n"
               "        self._lane.submit(lambda: None)\n")
        assert "F001" not in _rules(analyze_sources({"m.py": src}))

    def test_gathers_without_lane_not_flagged(self):
        # ensure/free helpers with no lane in sight are out of scope
        src = ("def f(s):\n"
               "    s.ensure_gathered(0)\n")
        assert "F001" not in _rules(analyze_sources({"m.py": src}))

    def test_ownership_transfer_functions_skipped(self):
        # acquire with no local release = store pattern (a later hook
        # frees) — out of scope by design
        src = ("class Store:\n"
               "    def prefetch(self, i):\n"
               "        self._lane.submit(lambda: None)\n"
               "    def pre_hook(self, i):\n"
               "        self.ensure_gathered(i)\n"
               "    def post_hook(self, i):\n"
               "        self.free_bucket(i)\n")
        assert "F001" not in _rules(analyze_sources({"m.py": src}))

    def test_stage3_store_is_clean(self):
        """The real lane gather client (distributed/sharding/stage3.py)
        carries the all-paths release — materialize()'s finally and the
        try/finally'd bench loops prove clean under the PATH-aware rule
        (zero3_gather_report leaked on exception paths until ISSUE 12)."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule in ("F001", "S001")] == []


# ---------------------------------------------------------------------------
# S002 — signal handlers only set flags/latches
# ---------------------------------------------------------------------------

_S002_LOGGING = (
    "import logging\n"
    "import signal\n"
    "def handler(signum, frame):\n"
    "    logging.getLogger(__name__).warning('preempted %s', signum)\n"
    "signal.signal(signal.SIGTERM, handler)\n"
)

_S002_LOCK = (
    "import signal\n"
    "class H:\n"
    "    def _on_term(self, signum, frame):\n"
    "        self._lock.acquire()\n"
    "        self.preempted = True\n"
    "    def install(self):\n"
    "        signal.signal(signal.SIGTERM, self._on_term)\n"
)

_S002_CLEAN = (
    "import signal\n"
    "class H:\n"
    "    def _handler(self, signum, frame):\n"
    "        self._signum = signum\n"
    "        self._latch.set()\n"
    "    def install(self):\n"
    "        signal.signal(signal.SIGTERM, self._handler)\n"
)


class TestSignalSafetyRule:
    def test_flags_logging_in_handler(self):
        f = _one(analyze_sources({"m.py": _S002_LOGGING}), "S002")
        assert "handler" in f.message and "latch" in f.message

    def test_flags_lock_acquire_in_method_handler(self):
        f = _one(analyze_sources({"m.py": _S002_LOCK}), "S002")
        assert "_on_term" in f.message

    def test_latch_only_body_ok(self):
        assert "S002" not in _rules(analyze_sources({"m.py": _S002_CLEAN}))

    def test_lambda_handlers_checked(self):
        bad = ("import signal\n"
               "signal.signal(signal.SIGTERM, lambda s, f: print(s))\n")
        assert "S002" in _rules(analyze_sources({"m.py": bad}))
        ok = ("import signal\n"
              "signal.signal(signal.SIGTERM, lambda s, f: latch.set())\n")
        assert "S002" not in _rules(analyze_sources({"m.py": ok}))

    def test_unresolvable_handler_skipped(self):
        # an imported/dynamic handler cannot be analyzed here — no false
        # positive
        src = ("import signal\n"
               "from other import handler\n"
               "signal.signal(signal.SIGTERM, handler)\n")
        assert "S002" not in _rules(analyze_sources({"m.py": src}))

    def test_send_signal_is_not_registration(self):
        # launch/main.py shape: SENDING a signal is not registering a
        # handler
        src = ("import signal\n"
               "def stop(q):\n"
               "    q.send_signal(signal.SIGTERM)\n")
        assert "S002" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_handlers_are_latch_only(self):
        """The real PreemptionHandler (robustness/preemption.py) obeys its
        own contract — the repo stays S002-clean."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "S002"] == []


# ---------------------------------------------------------------------------
# engine: baseline diff + waivers
# ---------------------------------------------------------------------------

class TestEngine:
    def test_baseline_roundtrip_clean(self):
        src = {"m.py": "import threading\nt = threading.Thread(target=f)\n"}
        findings = analyze_sources(src)
        baseline = findings_to_baseline(findings)["entries"]
        new, stale = diff_against_baseline(findings, baseline)
        assert new == [] and stale == []

    def test_new_finding_detected(self):
        src = {"m.py": "import threading\nt = threading.Thread(target=f)\n"}
        new, stale = diff_against_baseline(analyze_sources(src), [])
        assert len(new) == 1 and stale == []

    def test_stale_entry_detected(self):
        ghost = [{"rule": "C001", "path": "gone.py",
                  "message": "threading.Thread(...) without explicit daemon="}]
        new, stale = diff_against_baseline([], ghost)
        assert new == [] and len(stale) == 1

    def test_multiplicity_matters(self):
        src = {"m.py": ("import threading\n"
                        "t = threading.Thread(target=f)\n"
                        "u = threading.Thread(target=f)\n")}
        findings = analyze_sources(src)
        assert len(findings) == 2
        one = findings_to_baseline(findings[:1])["entries"]
        new, stale = diff_against_baseline(findings, one)
        assert len(new) == 1 and stale == []

    def test_every_rule_documented(self):
        for rule in ("C001", "C002", "C003", "C004", "X001", "X002", "X003",
                     "X004", "X005", "T001", "T002", "T003", "R001", "R002",
                     "S001", "S002", "D001", "D002", "F001", "F002", "F003",
                     "F004", "F005", "F006"):
            assert rule in RULES
            invariant, rationale = RULES[rule]
            assert invariant and rationale

    def test_s001_documented_as_superseded(self):
        """Satellite (ISSUE 12): the rule id stays live as an alias with
        its supersession recorded in RULES."""
        assert "superseded by F001" in RULES["S001"][0]


# ---------------------------------------------------------------------------
# call graph / symbol table (ISSUE 11 tentpole)
# ---------------------------------------------------------------------------

class TestCallGraph:
    def _index(self, sources):
        from paddle_tpu.analysis import Analysis, default_checkers
        a = Analysis(default_checkers())
        a.run_sources(sources)
        return a.index

    def test_cross_module_reachability(self):
        idx = self._index({
            "paddle_tpu/a.py": ("from paddle_tpu.b import middle\n"
                                "def top():\n"
                                "    return middle()\n"),
            "paddle_tpu/b.py": ("def middle():\n"
                                "    return _leaf()\n"
                                "def _leaf():\n"
                                "    return 1\n"),
        })
        reach = idx.reachable("paddle_tpu/a.py::top")
        assert "paddle_tpu/b.py::middle" in reach
        assert "paddle_tpu/b.py::_leaf" in reach

    def test_relative_import_resolution(self):
        idx = self._index({
            "paddle_tpu/pkg/a.py": ("from .b import helper\n"
                                    "def f():\n"
                                    "    return helper()\n"),
            "paddle_tpu/pkg/b.py": "def helper():\n    return 2\n",
        })
        assert "paddle_tpu/pkg/b.py::helper" in \
            idx.reachable("paddle_tpu/pkg/a.py::f")

    def test_self_method_edges(self):
        idx = self._index({
            "m.py": ("class C:\n"
                     "    def run(self):\n"
                     "        return self._impl()\n"
                     "    def _impl(self):\n"
                     "        return 0\n"),
        })
        assert idx.callees("m.py::C.run") == ("m.py::C._impl",)

    def test_nested_def_implicit_edge(self):
        idx = self._index({
            "m.py": ("def outer():\n"
                     "    def inner():\n"
                     "        return 1\n"
                     "    return inner\n"),
        })
        assert "m.py::outer.inner" in idx.reachable("m.py::outer")

    def test_fallback_requires_unique_name(self):
        srcs = {
            "a.py": "class A:\n    def unique_leaf(self):\n        return 1\n",
            "b.py": "def caller(obj):\n    return obj.unique_leaf()\n",
        }
        idx = self._index(srcs)
        assert idx.reachable("b.py::caller") == {"a.py::A.unique_leaf"}
        # confident-only traversal must NOT take the fallback edge
        assert idx.reachable("b.py::caller", fallback=False) == set()
        # a second function with the same bare name kills the fallback
        srcs["c.py"] = "def unique_leaf():\n    return 2\n"
        idx2 = self._index(srcs)
        assert idx2.reachable("b.py::caller") == set()

    def test_module_of_paths(self):
        from paddle_tpu.analysis.callgraph import module_of
        assert module_of("paddle_tpu/distributed/collective.py") == \
            "paddle_tpu.distributed.collective"
        assert module_of("paddle_tpu/analysis/__init__.py") == \
            "paddle_tpu.analysis"

    def test_repo_index_scales(self):
        """The index answers reachability over the real tree: the public
        all_reduce is reachable from the sanctioned in-trace helper's
        module peers (gpt's manual-SPMD forward)."""
        _, a = _repo_analysis()
        idx = a.index
        assert len(idx.functions) > 1000   # the whole framework is indexed
        # any gpt module function using the helper reaches collective.py
        gpt_fns = [fn for fn in idx.functions
                   if fn.startswith("paddle_tpu/models/gpt.py::")]
        assert gpt_fns
        hit = any(
            any(c.startswith("paddle_tpu/distributed/collective.py::")
                for c in idx.reachable(fn))
            for fn in gpt_fns)
        assert hit


# ---------------------------------------------------------------------------
# D001/D002 — donation safety (ISSUE 11)
# ---------------------------------------------------------------------------

# the PR-8 TrainStep donation-alias bug, reduced to its pre-fix shape:
# donated params/slots pair AFTER the batch-sharded out_vals in the
# return tuple, so a same-shape batch output steals the alias slot
_D002_PREFIX_BUG = """
import jax

def pure_step(train_p, slots, in_vals):
    out_vals = forward(in_vals)
    loss, grads = value_and_grad_of(train_p, in_vals)
    new_tp = update(train_p, grads)
    new_slots = tick(slots)
    return loss, out_vals, new_tp, new_slots

step = jax.jit(pure_step, donate_argnums=(0, 1))
"""

_D002_FIXED = _D002_PREFIX_BUG.replace(
    "return loss, out_vals, new_tp, new_slots",
    "return loss, new_tp, new_slots, out_vals")


class TestDonationRules:
    def test_d002_flags_pr8_prefix_shape(self):
        f = _one(analyze_sources({"m.py": _D002_PREFIX_BUG}), "D002")
        assert "pure_step" in f.message and "alias" in f.message

    def test_d002_fixed_order_clean(self):
        assert "D002" not in _rules(analyze_sources({"m.py": _D002_FIXED}))

    def test_d002_decorator_partial_form(self):
        src = ("import jax\n"
               "from functools import partial\n"
               "@partial(jax.jit, donate_argnums=(0,))\n"
               "def step(params, batch):\n"
               "    out = fwd(batch)\n"
               "    new_p = upd(params)\n"
               "    return out, new_p\n")
        assert "D002" in _rules(analyze_sources({"m.py": src}))

    def test_d002_all_donated_derived_clean(self):
        # the real TrainStep shape: loss derives from train_p too, so no
        # element is a PURE batch output before the donated ones
        src = ("import jax\n"
               "def step(p, x):\n"
               "    loss, new_p = upd(p, x)\n"
               "    return loss, new_p\n"
               "f = jax.jit(step, donate_argnums=(0,))\n")
        assert "D002" not in _rules(analyze_sources({"m.py": src}))

    def test_d001_read_after_donation_flagged(self):
        src = ("import jax\n"
               "def run(params, x):\n"
               "    step = jax.jit(update, donate_argnums=(0,))\n"
               "    out = step(params, x)\n"
               "    return params + out\n")
        f = _one(analyze_sources({"m.py": src}), "D001")
        assert "params" in f.message

    def test_d001_rebind_idiom_clean(self):
        src = ("import jax\n"
               "def run(params, x):\n"
               "    step = jax.jit(update, donate_argnums=(0,))\n"
               "    params = step(params, x)\n"
               "    return params\n")
        assert "D001" not in _rules(analyze_sources({"m.py": src}))

    def test_d001_non_donated_arg_ok(self):
        src = ("import jax\n"
               "def run(params, x):\n"
               "    step = jax.jit(update, donate_argnums=(0,))\n"
               "    params = step(params, x)\n"
               "    return x\n")   # x was position 1: not donated
        assert "D001" not in _rules(analyze_sources({"m.py": src}))

    def test_d001_direct_call_form(self):
        src = ("import jax\n"
               "def run(params, x):\n"
               "    out = jax.jit(update, donate_argnums=(0,))(params, x)\n"
               "    return params\n")
        assert "D001" in _rules(analyze_sources({"m.py": src}))

    def test_repo_clean_on_donation_rules(self):
        """Acceptance: the repo (incl. the PR-8-fixed TrainStep and the
        static-graph executor's train_fn) is D001/D002-clean."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule in ("D001", "D002")] == []


# ---------------------------------------------------------------------------
# X004 — interprocedural SPMD consistency (ISSUE 11)
# ---------------------------------------------------------------------------

class TestInterproceduralSPMD:
    def test_transitive_collective_in_one_arm_flagged(self):
        src = ("def _commit(t):\n"
               "    dist.all_reduce(t)\n"
               "def save(t):\n"
               "    if get_rank() == 0:\n"
               "        _commit(t)\n")
        f = _one(analyze_sources({"paddle_tpu/io/m.py": src}), "X004")
        assert "_commit" in f.message and "all_reduce" in f.message

    def test_two_hop_chain_flagged(self):
        src = ("def _inner(t):\n"
               "    dist.barrier()\n"
               "def _outer(t):\n"
               "    _inner(t)\n"
               "def save(t):\n"
               "    if get_rank() == 0:\n"
               "        _outer(t)\n")
        assert "X004" in _rules(analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_symmetric_transitive_ok(self):
        src = ("def _commit(t):\n"
               "    dist.all_reduce(t)\n"
               "def save(t):\n"
               "    if get_rank() == 0:\n"
               "        _commit(t)\n"
               "    else:\n"
               "        _commit(t)\n")
        assert "X004" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_helper_without_collective_ok(self):
        src = ("def _log(t):\n"
               "    print(t)\n"
               "def save(t):\n"
               "    if get_rank() == 0:\n"
               "        _log(t)\n")
        assert "X004" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_direct_collective_stays_x003(self):
        # the direct form is X003's; X004 must not double-report it
        src = ("if get_rank() == 0:\n"
               "    dist.all_reduce(t)\n")
        found = analyze_sources({"paddle_tpu/io/m.py": src})
        assert _rules(found).count("X003") == 1
        assert "X004" not in _rules(found)

    def test_generic_send_leaf_not_transitive(self):
        # a rank-gated helper calling socket/bus .send() is host-side
        # point-to-point, not an SPMD collective
        src = ("def _notify(bus, t):\n"
               "    bus.send(t)\n"
               "def save(bus, t):\n"
               "    if get_rank() == 0:\n"
               "        _notify(bus, t)\n")
        assert "X004" not in _rules(
            analyze_sources({"paddle_tpu/io/m.py": src}))

    def test_repo_clean_on_x004(self):
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "X004"] == []


# ---------------------------------------------------------------------------
# T003 — transitive trace purity (ISSUE 11)
# ---------------------------------------------------------------------------

class TestTransitiveTracePurity:
    def test_impurity_one_call_away_flagged(self):
        src = ("import jax, time\n"
               "def _helper(x):\n"
               "    return x + time.time()\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return _helper(x)\n")
        f = _one(analyze_sources({"m.py": src}), "T003")
        assert "step" in f.message and "time.time" in f.message \
            and "_helper" in f.message

    def test_chain_reported_in_message(self):
        src = ("import jax, time\n"
               "def _deeper(x):\n"
               "    time.sleep(0)\n"
               "    return x\n"
               "def _helper(x):\n"
               "    return _deeper(x)\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return _helper(x)\n")
        f = _one(analyze_sources({"m.py": src}), "T003")
        assert "_helper -> _deeper" in f.message

    def test_direct_impurity_stays_t001(self):
        src = ("import jax, time\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return x + time.time()\n")
        found = analyze_sources({"m.py": src})
        assert "T001" in _rules(found) and "T003" not in _rules(found)

    def test_in_trace_guard_is_trusted_boundary(self):
        # the collective layer's dual-path contract: a callee that
        # branches on _in_trace handles both worlds itself
        src = ("import jax, time\n"
               "def _dual(x):\n"
               "    if _in_trace(x):\n"
               "        return x\n"
               "    return x + time.time()\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return _dual(x)\n")
        assert "T003" not in _rules(analyze_sources({"m.py": src}))

    def test_pure_helpers_clean(self):
        src = ("import jax\n"
               "def _helper(x):\n"
               "    return x * 2\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return _helper(x)\n")
        assert "T003" not in _rules(analyze_sources({"m.py": src}))

    def test_repo_clean_on_t003(self):
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "T003"] == []


# ---------------------------------------------------------------------------
# stale-waiver hygiene (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

class TestStaleWaivers:
    def _run(self, sources):
        from paddle_tpu.analysis import Analysis, default_checkers
        a = Analysis(default_checkers())
        findings = a.run_sources(sources)
        return findings, a.stale_waivers

    def test_dead_waiver_reported(self):
        _, stale = self._run({"m.py": "x = 1  # lint-ok: C003 obsolete\n"})
        assert stale == [{"path": "m.py", "line": 1, "rule": "C003"}]

    def test_live_waiver_not_stale(self):
        src = ("try:\n    f()\n"
               "except Exception:   # lint-ok: C003 teardown guard\n"
               "    pass\n")
        findings, stale = self._run({"m.py": src})
        assert "C003" not in _rules(findings)
        assert stale == []

    def test_multi_rule_waiver_partial_staleness(self):
        # C003 fires (and is waived); C001 never fires on that line
        src = ("try:\n    f()\n"
               "except Exception:   # lint-ok: C003, C001 both?\n"
               "    pass\n")
        _, stale = self._run({"m.py": src})
        assert stale == [{"path": "m.py", "line": 3, "rule": "C001"}]

    def test_docstring_mention_is_not_a_waiver(self):
        src = ('"""Docs: a line ending in ``# lint-ok: C003 x`` waives."""\n'
               "x = 1\n")
        _, stale = self._run({"m.py": src})
        assert stale == []

    def test_repo_has_no_stale_waivers(self):
        _, a = _repo_analysis()
        assert a.stale_waivers == []

    def test_gate_exit_2_on_stale_waiver(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text("x = 1  # lint-ok: C001 dead comment\n")
        bl = tmp_path / "bl.json"
        bl.write_text('{"entries": []}')
        spec = importlib.util.spec_from_file_location(
            "check_static", os.path.join(REPO, "tools", "check_static.py"))
        cs = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cs)
        rc = cs.main(["--root", str(tmp_path), "--baseline", str(bl),
                      "--no-cache"])
        assert rc == 2


# ---------------------------------------------------------------------------
# the tier-1 gate itself
# ---------------------------------------------------------------------------

class TestCheckStaticGate:
    def _main(self):
        spec = importlib.util.spec_from_file_location(
            "check_static", os.path.join(REPO, "tools", "check_static.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main

    def test_repo_clean_against_committed_baseline(self):
        t0 = time.perf_counter()
        rc = self._main()([])
        assert rc == 0
        assert time.perf_counter() - t0 < 30.0  # tier-1 budget contract

    def test_baseline_has_no_allowlisted_discipline_findings(self):
        """Acceptance: swallow/daemon/lock-discipline entries were FIXED,
        so the baseline holds zero of them."""
        entries = load_baseline(
            os.path.join(REPO, "tools", "static_baseline.json"))
        rules_in_baseline = {e["rule"] for e in entries}
        assert rules_in_baseline.isdisjoint({"C001", "C002", "C003"})
        for e in entries:       # remaining debt is documented
            assert e.get("reason"), f"baseline entry missing reason: {e}"

    def test_exit_1_on_new_finding(self, tmp_path):
        bad = tmp_path / "m.py"
        bad.write_text("import threading\nt = threading.Thread(target=f)\n")
        empty = tmp_path / "baseline.json"
        empty.write_text('{"entries": []}')
        rc = self._main()(["--root", str(tmp_path),
                           "--baseline", str(empty)])
        assert rc == 1

    def test_exit_2_on_stale_entry(self, tmp_path):
        clean = tmp_path / "m.py"
        clean.write_text("x = 1\n")
        stale = tmp_path / "baseline.json"
        stale.write_text(json.dumps({"entries": [{
            "rule": "C001", "path": "m.py", "line": 1,
            "message": "threading.Thread(...) without explicit daemon="}]}))
        rc = self._main()(["--root", str(tmp_path),
                           "--baseline", str(stale)])
        assert rc == 2

    def test_cli_exit_code(self):
        """The committed gate command CI runs, end to end."""
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_static.py")],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "OK: clean against baseline" in p.stdout


# ---------------------------------------------------------------------------
# lock-order sanitizer
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_seeded_abba_inversion_detected(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)
        with A:
            with B:
                pass
        with B:        # the inversion — never actually deadlocks here,
            with A:    # but the ORDER violation is still witnessed
                pass
        cycles = g.cycles()
        assert cycles == [["A", "B"]]
        rep = g.report()
        assert rep["cycle_lock_names"] == ["A", "B"]
        edge = rep["cycles"][0]["edges"][0]
        assert edge["count"] >= 1 and edge["thread"]

    def test_three_lock_cycle_detected(self):
        g = lock_order.LockOrderGraph()
        a, b, c = (lock_order.WitnessLock(threading.Lock(), n, g)
                   for n in "abc")
        for first, second in ((a, b), (b, c), (c, a)):
            with first:
                with second:
                    pass
        assert g.cycles() == [["a", "b", "c"]]

    def test_consistent_order_is_silent(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)
        for _ in range(3):
            with A:
                with B:
                    pass
        assert g.cycles() == []
        assert g.report()["edge_count"] == 1

    def test_cross_thread_edges_recorded(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)

        def t1():
            with A:
                with B:
                    pass

        def t2():
            with B:
                with A:
                    pass

        th1 = threading.Thread(target=t1, daemon=True)
        th1.start(); th1.join()
        th2 = threading.Thread(target=t2, daemon=True)
        th2.start(); th2.join()
        assert g.cycles() == [["A", "B"]]

    def test_release_out_of_order(self):
        g = lock_order.LockOrderGraph()
        A = lock_order.WitnessLock(threading.Lock(), "A", g)
        B = lock_order.WitnessLock(threading.Lock(), "B", g)
        A.acquire(); B.acquire()
        A.release(); B.release()     # non-LIFO release must not corrupt
        with B:
            pass
        assert g.cycles() == []

    def test_works_as_condition_lock(self):
        g = lock_order.LockOrderGraph()
        w = lock_order.WitnessLock(threading.Lock(), "cv", g)
        cv = threading.Condition(w)
        hits = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                hits.append(1)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert hits == [1]

    def test_install_instruments_only_paddle_tpu_locks(self):
        g = lock_order.LockOrderGraph()
        was_installed = lock_order.installed()
        lock_order.uninstall()
        lock_order.install(g)
        try:
            here = threading.Lock()           # test file: raw
            assert not isinstance(here, lock_order.WitnessLock)
            ns = {}
            code = compile("import threading\nL = threading.Lock()\n",
                           "/x/paddle_tpu/fake/mod.py", "exec")
            exec(code, ns)
            assert isinstance(ns["L"], lock_order.WitnessLock)
            assert "paddle_tpu/fake/mod.py" in ns["L"].name
        finally:
            lock_order.uninstall()
            if was_installed:      # restore the session-level witness
                lock_order.install()

    def test_clean_on_real_framework_traffic(self):
        """Silence proof: when tier-1 runs with FLAGS_lock_order_check the
        global graph must hold no cycles; otherwise exercise real lock
        nesting (collective lane + event log + metrics) under a local
        install and prove the same."""
        if lock_order.installed():
            assert lock_order.get_graph().cycles() == []
            return
        g = lock_order.LockOrderGraph()
        lock_order.install(g)
        try:
            ns = {}
            code = compile(
                "import threading\n"
                "outer = threading.Lock()\n"
                "inner = threading.Lock()\n",
                "/x/paddle_tpu/fake/lane.py", "exec")
            exec(code, ns)
            from paddle_tpu.distributed.overlap import CollectiveLane
            from paddle_tpu.observability.events import get_event_log
            lane = CollectiveLane(name="sanitizer-test-lane")
            done = []
            for i in range(4):
                def job(i=i):
                    with ns["outer"]:
                        with ns["inner"]:
                            get_event_log().debug("sanitizer", f"job{i}")
                    done.append(i)
                lane.submit(job)
            deadline = time.time() + 10
            while len(done) < 4 and time.time() < deadline:
                time.sleep(0.01)
            assert len(done) == 4
            assert g.cycles() == []
        finally:
            lock_order.uninstall()

    def test_thread_leak_report(self):
        stop = threading.Event()
        t = threading.Thread(target=stop.wait, name="leaky-nondaemon",
                             daemon=False)
        t.start()
        try:
            leaks = lock_order.thread_leak_report(set())
            assert any(l["name"] == "leaky-nondaemon" for l in leaks)
        finally:
            stop.set()
            t.join(timeout=5)
        leaks = lock_order.thread_leak_report(set())
        assert not any(l["name"] == "leaky-nondaemon" for l in leaks)

    def test_flag_installs_witness(self):
        """set_flags({'FLAGS_lock_order_check': True}) wires install()."""
        import paddle_tpu
        was = lock_order.installed()
        try:
            paddle_tpu.set_flags({"FLAGS_lock_order_check": True})
            assert lock_order.installed()
        finally:
            if not was:
                lock_order.uninstall()
            paddle_tpu.set_flags({"FLAGS_lock_order_check": was})


# ---------------------------------------------------------------------------
# gate modes: --changed-only / --sarif / AST cache / wall budget (ISSUE 11)
# ---------------------------------------------------------------------------

class TestGateModes:
    def _main(self):
        spec = importlib.util.spec_from_file_location(
            "check_static", os.path.join(REPO, "tools", "check_static.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_changed_only_reports_only_changed_files(self, tmp_path):
        """A tmp git repo with a committed dirty file and a NEW dirty
        file: --changed-only must report only the new one."""
        repo = tmp_path / "r"
        repo.mkdir()

        def git(*args):
            subprocess.run(["git", "-c", "user.email=t@t",
                            "-c", "user.name=t", *args],
                           cwd=repo, check=True, capture_output=True)

        git("init", "-q", ".")
        # committed file carries a violation that predates the change set
        (repo / "old.py").write_text(
            "import threading\nt = threading.Thread(target=f)\n")
        git("add", "old.py")
        git("commit", "-qm", "init")
        (repo / "new.py").write_text(
            "import threading\nu = threading.Thread(target=f)\n")
        bl = tmp_path / "bl.json"
        bl.write_text('{"entries": []}')
        cs = self._main()
        rc = cs.main(["--root", str(repo), "--baseline", str(bl),
                      "--changed-only", "HEAD", "--no-cache", "--json"])
        assert rc == 1   # new.py's finding is new
        # full run sees both files' findings
        rc_full = cs.main(["--root", str(repo), "--baseline", str(bl),
                           "--no-cache"])
        assert rc_full == 1

    def test_changed_only_scopes_the_baseline(self, tmp_path, capsys):
        repo = tmp_path / "r"
        repo.mkdir()

        def git(*args):
            subprocess.run(["git", "-c", "user.email=t@t",
                            "-c", "user.name=t", *args],
                           cwd=repo, check=True, capture_output=True)

        git("init", "-q", ".")
        (repo / "old.py").write_text(
            "import threading\nt = threading.Thread(target=f)\n")
        git("add", "old.py")
        git("commit", "-qm", "init")
        (repo / "new.py").write_text("x = 1\n")
        # old.py's finding is baselined; old.py is NOT in the change set,
        # so neither its finding nor its baseline entry participates
        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"entries": [{
            "rule": "C001", "path": "old.py", "line": 2,
            "message": "threading.Thread(...) without explicit daemon="}]}))
        cs = self._main()
        rc = cs.main(["--root", str(repo), "--baseline", str(bl),
                      "--changed-only", "HEAD", "--no-cache"])
        capsys.readouterr()
        assert rc == 0

    def test_sarif_output_shape(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text("import threading\nt = threading.Thread(target=f)\n")
        bl = tmp_path / "bl.json"
        bl.write_text('{"entries": []}')
        sarif = tmp_path / "out.sarif"
        cs = self._main()
        rc = cs.main(["--root", str(tmp_path), "--baseline", str(bl),
                      "--no-cache", "--sarif", str(sarif)])
        assert rc == 1
        doc = json.loads(sarif.read_text())
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "paddle_tpu.analysis"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"C001", "D002", "X004", "T003"} <= rule_ids
        res = run["results"]
        assert len(res) == 1 and res[0]["ruleId"] == "C001"
        loc = res[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "m.py"
        assert loc["region"]["startLine"] == 2

    def test_ast_cache_roundtrip(self, tmp_path):
        from paddle_tpu.analysis import AstCache
        mod = tmp_path / "m.py"
        mod.write_text("x = 1\n")
        cache_path = str(tmp_path / "cache.pkl")
        c1 = AstCache(cache_path)
        src, tree = c1.get(str(mod), "m.py")
        assert c1.misses == 1 and c1.hits == 0
        c1.save()
        c2 = AstCache(cache_path)
        src2, tree2 = c2.get(str(mod), "m.py")
        assert c2.hits == 1 and c2.misses == 0
        assert src2 == src
        # an edit invalidates the entry
        mod.write_text("x = 2\n")
        c3 = AstCache(cache_path)
        c3.get(str(mod), "m.py")
        assert c3.misses == 1
        # a corrupt cache file is ignored, not fatal
        with open(cache_path, "wb") as f:
            f.write(b"not a pickle")
        c4 = AstCache(cache_path)
        c4.get(str(mod), "m.py")
        assert c4.misses == 1

    def test_full_run_wall_within_budget(self):
        """Acceptance (ISSUE 11): the full interprocedural run over the
        repo completes in <= 8s (one run, shared .cache AST cache — the
        steady CI state; a cold parse adds ~1s, still inside budget)."""
        import importlib.util as iu
        spec = iu.spec_from_file_location(
            "check_static", os.path.join(REPO, "tools", "check_static.py"))
        cs = iu.module_from_spec(spec)
        spec.loader.exec_module(cs)
        t0 = time.perf_counter()
        rc = cs.main([])
        wall = time.perf_counter() - t0
        assert rc == 0
        assert wall <= 8.0, f"check_static took {wall:.2f}s (> 8s budget)"

    def test_bench_gate_static_budget(self):
        """tools/bench_gate.py --static-budget gates the check_static
        wall time (tier-1 budget can't silently regress)."""
        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        row, regressed = bg.gate_static_wall(30.0)
        assert row["metric"] == "check_static_wall_s"
        assert not regressed and row["verdict"] == "OK"
        assert 0 < row["candidate"] <= 30.0
        # the regression branch, against the measured wall (no second run)
        row2, regressed2 = bg.gate_static_wall(
            row["candidate"] / 2, wall=row["candidate"])
        assert regressed2 and row2["verdict"] == "REGRESSED"


# ---------------------------------------------------------------------------
# X001 burn-down: the baseline holds ZERO entries (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

class TestX001BurnDown:
    def test_repo_has_no_raw_lax_collectives_outside_distributed(self):
        """gpt's six waived TP psum/pmax sites now ride the sanctioned
        in-trace helpers (distributed.collective.in_trace_psum/pmax)."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "X001"] == []

    def test_baseline_is_empty(self):
        entries = load_baseline(
            os.path.join(REPO, "tools", "static_baseline.json"))
        assert entries == []

    def test_in_trace_helpers_record_and_reduce(self):
        """The sanctioned helpers lower to the same lax collectives and
        tick the per-op counters at trace time."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.distributed import collective as coll
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.observability.metrics import get_registry

        m = mesh_mod.default_mesh()
        axis = m.axis_names[0]
        n = m.shape[axis]

        def psum_count():
            snap = get_registry().snapshot().get("collectives_total", {})
            return snap.get("op=in_trace_psum", 0)

        before = psum_count()

        from jax.sharding import PartitionSpec as P
        f = mesh_mod.compat_shard_map(
            lambda x: (coll.in_trace_psum(x, axis),
                       coll.in_trace_pmax(x, axis)),
            m, P(axis), (P(axis), P(axis)))
        x = jnp.arange(float(n)).reshape(n, 1)
        s, mx = f(x)
        np.testing.assert_allclose(
            np.asarray(s).ravel(), [x.sum()] * n)
        np.testing.assert_allclose(
            np.asarray(mx).ravel(), [x.max()] * n)
        assert psum_count() > before


# ---------------------------------------------------------------------------
# runtime host-sync sanitizer (ISSUE 11)
# ---------------------------------------------------------------------------

class TestHostSync:
    def _fresh(self):
        from paddle_tpu.analysis import host_sync
        return host_sync

    def test_in_step_sync_recorded_with_site(self):
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.profiler import RecordEvent
        hs = self._fresh()
        was = hs.installed()
        hs.install()
        hs.get_records().clear()
        try:
            x = jnp.ones((4,))
            np.asarray(x)                      # outside any span: silent
            assert hs.get_records().total == 0
            with RecordEvent("train_step"):
                np.asarray(x)                  # the blocking sync
            rep = hs.report()
            assert rep["in_step_syncs"] == 1
            assert rep["records"][0]["kind"] == "np.asarray"
            assert rep["records"][0]["span"] == "train_step"
            site = rep["records"][0]["site"]
            assert "test_static_analysis.py" in site and ":" in site
        finally:
            hs.get_records().clear()
            if not was:
                hs.uninstall()

    def test_block_until_ready_and_device_get(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.profiler import RecordEvent
        hs = self._fresh()
        was = hs.installed()
        hs.install()
        hs.get_records().clear()
        try:
            x = jnp.ones((2,))
            with RecordEvent("backward"):
                jax.block_until_ready(x)
                jax.device_get(x)
            kinds = {r["kind"] for r in hs.get_records().in_step()}
            assert kinds == {"block_until_ready", "device_get"}
        finally:
            hs.get_records().clear()
            if not was:
                hs.uninstall()

    def test_tensor_item_funnels_through(self):
        import jax.numpy as jnp
        from paddle_tpu.framework.tensor import Tensor
        from paddle_tpu.profiler import RecordEvent
        hs = self._fresh()
        was = hs.installed()
        hs.install()
        hs.get_records().clear()
        try:
            t = Tensor(jnp.ones(()), _internal=True)
            with RecordEvent("optimizer"):
                assert t.item() == 1.0
            assert hs.get_records().total == 1
        finally:
            hs.get_records().clear()
            if not was:
                hs.uninstall()

    def test_non_step_spans_and_plain_numpy_silent(self):
        import numpy as np
        from paddle_tpu.profiler import RecordEvent
        hs = self._fresh()
        was = hs.installed()
        hs.install()
        hs.get_records().clear()
        try:
            with RecordEvent("checkpoint"):    # host work by design
                np.asarray([1, 2, 3])
            with RecordEvent("train_step"):
                np.asarray([1, 2, 3])          # not a device array
            assert hs.get_records().total == 0
        finally:
            hs.get_records().clear()
            if not was:
                hs.uninstall()

    def test_uninstall_restores(self):
        import jax
        import numpy as np
        hs = self._fresh()
        if hs.installed():     # session-level install (flag run): skip
            pytest.skip("host-sync sanitizer active for the whole session")
        orig_asarray = np.asarray
        orig_block = jax.block_until_ready
        hs.install()
        assert np.asarray is not orig_asarray
        hs.uninstall()
        assert np.asarray is orig_asarray
        assert jax.block_until_ready is orig_block

    def test_flag_installs_sanitizer(self):
        import paddle_tpu
        hs = self._fresh()
        was = hs.installed()
        try:
            paddle_tpu.set_flags({"FLAGS_host_sync_check": True})
            assert hs.installed()
        finally:
            if not was:
                hs.uninstall()
            paddle_tpu.set_flags({"FLAGS_host_sync_check": was})

    def test_live_suite_is_clean(self):
        """Acceptance: under FLAGS_host_sync_check=1 the whole suite
        reports ZERO blocking syncs inside train-step spans. When the
        session runs with the flag, assert the live records; otherwise
        drive one real fused + one eager hapi train step under a local
        install and prove the same."""
        hs = self._fresh()
        if hs.installed():
            rep = hs.report()
            assert rep["in_step_syncs"] == 0, rep["sites"]
            return
        import numpy as np
        import paddle_tpu
        from paddle_tpu import hapi, nn, optimizer
        hs.install()
        hs.get_records().clear()
        try:
            net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
            model = hapi.Model(net)
            model.prepare(optimizer.SGD(learning_rate=0.1,
                                        parameters=net.parameters()),
                          nn.CrossEntropyLoss())
            x = paddle_tpu.to_tensor(
                np.random.RandomState(0).randn(8, 4).astype("float32"))
            y = paddle_tpu.to_tensor(
                np.zeros((8, 1), dtype="int64"))
            for _ in range(2):
                model.train_batch([x], [y])    # eager path spans
            rep = hs.report()
            assert rep["step_spans"] >= 4      # fwd/bwd/opt per step
            assert rep["in_step_syncs"] == 0, rep["sites"]
        finally:
            hs.get_records().clear()
            hs.uninstall()


# ---------------------------------------------------------------------------
# CFG construction + worklist solver (ISSUE 12 tentpole)
# ---------------------------------------------------------------------------

class TestCFG:
    def _cfg(self, src, name=None):
        import ast
        from paddle_tpu.analysis import dataflow
        tree = ast.parse(src)
        fns = [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        fn = fns[0] if name is None else \
            next(f for f in fns if f.name == name)
        return dataflow.build_cfg(fn)

    def _labels(self, cfg, idx_list):
        return [cfg.nodes[i].label for i in idx_list]

    def test_straight_line(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f():\n    a = 1\n    b = 2\n    return b\n")
        assert dataflow.CFG.EXIT in g.reachable_from(dataflow.CFG.ENTRY)
        # return has exactly one flow successor: EXIT
        ret = next(n for n in g.nodes if n.label == "return")
        assert ret.succs == [(dataflow.CFG.EXIT, "flow")]

    def test_if_else_branches_rejoin(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f(x):\n"
                      "    if x:\n"
                      "        a = 1\n"
                      "    else:\n"
                      "        a = 2\n"
                      "    return a\n")
        head = next(n for n in g.nodes if n.label == "if")
        flows = [d for d, k in head.succs if k == "flow"]
        assert len(flows) == 2             # both branches, no fallthrough

    def test_try_finally_return_in_finally(self):
        """return-in-finally swallows both the body's return and its
        exception: every path out of the function flows through the
        finally's own return node."""
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f():\n"
                      "    try:\n"
                      "        a = risky()\n"
                      "        return a\n"
                      "    finally:\n"
                      "        return 0\n")
        exit_preds = g.preds(dataflow.CFG.EXIT)
        fin_return = [i for i in exit_preds
                      if g.nodes[i].label == "return"
                      and g.nodes[i].line == 6]
        # the ONLY edges into EXIT come from the finally's return
        assert exit_preds == fin_return

    def test_while_else_and_break_skips_else(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f(xs):\n"
                      "    while xs:\n"
                      "        if bad(xs):\n"
                      "            break\n"
                      "        xs = step(xs)\n"
                      "    else:\n"
                      "        flag()\n"
                      "    return xs\n")
        brk = next(n for n in g.nodes if n.label == "break")
        ret = next(n for n in g.nodes if n.label == "return")
        els = next(n for n in g.nodes if n.line == 7)  # flag() in else
        # break jumps past the else, straight to the statement after
        assert (ret.idx, "flow") in brk.succs
        assert (els.idx, "flow") not in brk.succs
        # natural exhaustion runs the else
        head = next(n for n in g.nodes if n.label == "while")
        assert (els.idx, "flow") in head.succs

    def test_continue_targets_loop_head(self):
        g = self._cfg("def f(xs):\n"
                      "    for x in xs:\n"
                      "        if skip(x):\n"
                      "            continue\n"
                      "        use(x)\n")
        head = next(n for n in g.nodes if n.label == "for")
        cont = next(n for n in g.nodes if n.label == "continue")
        assert (head.idx, "flow") in cont.succs

    def test_while_true_has_no_natural_exit(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f():\n"
                      "    while True:\n"
                      "        if done():\n"
                      "            break\n"
                      "        step()\n"
                      "    return 1\n")
        head = next(n for n in g.nodes if n.label == "while")
        ret = next(n for n in g.nodes if n.label == "return")
        assert (ret.idx, "flow") not in head.succs   # only break reaches it
        brk = next(n for n in g.nodes if n.label == "break")
        assert (ret.idx, "flow") in brk.succs

    def test_nested_with_bodies_chain(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f(p):\n"
                      "    with open(p) as f:\n"
                      "        with lock:\n"
                      "            work(f)\n"
                      "    return 1\n")
        labels = [n.label for n in g.nodes]
        assert labels.count("with") == 2
        assert dataflow.CFG.EXIT in g.reachable_from(dataflow.CFG.ENTRY)

    def test_exception_edge_reaches_handler(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f():\n"
                      "    try:\n"
                      "        risky()\n"
                      "    except ValueError:\n"
                      "        recover()\n"
                      "    return 1\n")
        risky = next(n for n in g.nodes if n.line == 3)
        handler = next(n for n in g.nodes if n.label == "except")
        assert (handler.idx, "exc") in risky.succs
        # handler body rejoins normal flow at the return
        rec = next(n for n in g.nodes if n.line == 5)
        ret = next(n for n in g.nodes if n.label == "return")
        assert (ret.idx, "flow") in rec.succs

    def test_unprotected_statement_gets_panic_edge(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f():\n    risky()\n    return 1\n")
        risky = next(n for n in g.nodes if n.line == 2)
        assert (dataflow.CFG.EXIT, "panic") in risky.succs
        # ...and the panic edge is invisible to flow-only queries
        assert g.succs(risky.idx, dataflow.FLOW_ONLY) == \
            [n.idx for n in g.nodes if n.label == "return"]

    def test_generator_function_builds(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def gen(xs):\n"
                      "    for x in xs:\n"
                      "        yield x * 2\n"
                      "    yield -1\n")
        assert dataflow.CFG.EXIT in g.reachable_from(dataflow.CFG.ENTRY)
        head = next(n for n in g.nodes if n.label == "for")
        body = next(n for n in g.nodes if n.line == 3)
        assert (head.idx, "flow") in body.succs      # loop back edge

    def test_raise_routes_to_handler_not_exit(self):
        from paddle_tpu.analysis import dataflow
        g = self._cfg("def f():\n"
                      "    try:\n"
                      "        raise ValueError\n"
                      "    except ValueError:\n"
                      "        return 0\n")
        rse = next(n for n in g.nodes if n.label == "raise")
        handler = next(n for n in g.nodes if n.label == "except")
        assert rse.succs == [(handler.idx, "exc")]


class TestSolver:
    def _cfg(self, src):
        import ast
        from paddle_tpu.analysis import dataflow
        fn = ast.parse(src).body[0]
        return dataflow, dataflow.build_cfg(fn)

    def test_reaching_defs_merge_at_join(self):
        df, g = self._cfg("def f(c):\n"
                          "    x = 1\n"
                          "    if c:\n"
                          "        x = 2\n"
                          "    use(x)\n")
        rd = df.reaching_definitions(g)
        use = next(n for n in g.nodes if n.line == 5)
        defs = rd.defs_at(use.idx, "x")
        assert len(defs) == 2              # both assignments reach the use
        assert {g.nodes[d].line for d in defs} == {2, 4}

    def test_reaching_defs_kill(self):
        df, g = self._cfg("def f():\n"
                          "    x = 1\n"
                          "    x = 2\n"
                          "    use(x)\n")
        rd = df.reaching_definitions(g)
        use = next(n for n in g.nodes if n.line == 4)
        defs = rd.defs_at(use.idx, "x")
        assert [g.nodes[d].line for d in defs] == [3]

    def test_param_reaches_as_entry_def(self):
        df, g = self._cfg("def f(a):\n    use(a)\n")
        rd = df.reaching_definitions(g)
        use = next(n for n in g.nodes if n.line == 2)
        assert rd.defs_at(use.idx, "a") == [df.CFG.ENTRY]

    def test_liveness_backward(self):
        df, g = self._cfg("def f():\n"
                          "    x = 1\n"
                          "    y = 2\n"
                          "    return x\n")
        live = df.liveness(g)
        x_assign = next(n for n in g.nodes if n.line == 2)
        # after `x = 1`, x is live (read by return), y is not yet
        live_out = live[x_assign.idx][0]
        assert "x" in live_out

    def test_postdominators_flow_only(self):
        df, g = self._cfg("def f(c):\n"
                          "    a()\n"
                          "    if c:\n"
                          "        b()\n"
                          "    z()\n")
        pdom = df.postdominators(g)
        a = next(n for n in g.nodes if n.line == 2)
        b = next(n for n in g.nodes if n.line == 4)
        z = next(n for n in g.nodes if n.line == 5)
        assert z.idx in pdom[a.idx]        # z on every path after a
        assert b.idx not in pdom[a.idx]    # b only on the if-branch

    def test_intersect_meet_requires_universe(self):
        import pytest as _pytest
        df, g = self._cfg("def f():\n    pass\n")
        with _pytest.raises(ValueError):
            df.solve(g, direction="forward", transfer=lambda i, s: s,
                     meet="intersect")

    def test_convergence_bound_raises(self):
        import itertools
        import pytest as _pytest
        # needs a cycle: chaotic iteration on a DAG terminates even for
        # a non-monotone transfer
        df, g = self._cfg("def f(c):\n"
                          "    while c:\n"
                          "        a = step(a)\n")
        counter = itertools.count()

        def bad_transfer(idx, inset):       # never stabilizes
            return frozenset({next(counter)})

        with _pytest.raises(df.ConvergenceError):
            df.solve(g, direction="forward", transfer=bad_transfer,
                     max_iters=50)

    def test_repo_scale_solver_converges_on_every_function(self):
        """Satellite bound: CFG + reaching-defs + liveness converge for
        every function of all ~340 analyzed files (no ConvergenceError,
        no builder crash), and EXIT is reachable in every graph."""
        from paddle_tpu.analysis import dataflow
        findings, a = _repo_analysis()
        assert a.index is not None and a.dataflow is not None
        n_funcs = 0
        for fn in a.index.functions.values():
            g = a.dataflow.cfg(fn.node, fn.path)
            assert dataflow.CFG.EXIT in g.reachable_from(
                dataflow.CFG.ENTRY), fn.qualname
            a.dataflow.reaching(fn.node, fn.path)
            dataflow.liveness(g)
            n_funcs += 1
        assert n_funcs > 300               # repo scale, not a fixture


# ---------------------------------------------------------------------------
# F002 — future-await (ISSUE 12)
# ---------------------------------------------------------------------------

class TestFutureAwaitRule:
    def test_early_return_path_leaks_future(self):
        src = ("def f(b, bad):\n"
               "    fut = BucketFuture(b)\n"
               "    if bad:\n"
               "        return None\n"       # fut forgotten on this path
               "    return fut.wait()\n")
        f = _one(analyze_sources({"m.py": src}), "F002")
        assert "'fut'" in f.message and "path" in f.message

    def test_discarded_maker_call_flagged(self):
        src = "def f(b):\n    GatherFuture(b)\n"
        f = _one(analyze_sources({"m.py": src}), "F002")
        assert "discarded" in f.message

    def test_awaited_on_all_paths_ok(self):
        src = ("def f(b, bad):\n"
               "    fut = BucketFuture(b)\n"
               "    if bad:\n"
               "        return fut.result()\n"
               "    return fut.wait()\n")
        assert "F002" not in _rules(analyze_sources({"m.py": src}))

    def test_escape_via_store_ok(self):
        src = ("def f(self, b):\n"
               "    fut = BucketFuture(b)\n"
               "    self._futures[b.index] = fut\n")
        assert "F002" not in _rules(analyze_sources({"m.py": src}))

    def test_escape_via_return_ok(self):
        src = ("def f(b):\n"
               "    fut = GatherFuture(b)\n"
               "    return fut\n")
        assert "F002" not in _rules(analyze_sources({"m.py": src}))

    def test_drain_call_trusts_function(self):
        src = ("def f(self, b, bad):\n"
               "    fut = BucketFuture(b)\n"
               "    if bad:\n"
               "        self.abandon()\n"    # drains every lane future
               "        return None\n"
               "    return fut.wait()\n")
        assert "F002" not in _rules(analyze_sources({"m.py": src}))

    def test_sync_async_futures_list_tracked(self):
        src = ("def f(comm, params, bad):\n"
               "    futs = comm.sync_async(params)\n"
               "    if bad:\n"
               "        return None\n"
               "    for fu in futs:\n"
               "        fu.wait()\n")
        assert "F002" in _rules(analyze_sources({"m.py": src}))

    def test_repo_clean_on_f002(self):
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "F002"] == []


# ---------------------------------------------------------------------------
# F003 — manifest-last commit ordering (ISSUE 12)
# ---------------------------------------------------------------------------

_F003_GOOD = (
    "MANIFEST_NAME = 'MANIFEST.json'\n"
    "class M:\n"
    "    def attempt(self, entries, tmp):\n"
    "        for name, data in entries.items():\n"
    "            self._write_file(os.path.join(tmp, name), data)\n"
    "        self._write_file(os.path.join(tmp, MANIFEST_NAME), b'{}')\n"
    "        self.fs.replace(tmp, 'final')\n"
)

_F003_REORDERED = (
    "MANIFEST_NAME = 'MANIFEST.json'\n"
    "class M:\n"
    "    def attempt(self, entries, tmp):\n"
    "        self._write_file(os.path.join(tmp, MANIFEST_NAME), b'{}')\n"
    "        for name, data in entries.items():\n"
    "            self._write_file(os.path.join(tmp, name), data)\n"
    "        self.fs.replace(tmp, 'final')\n"
)


class TestCommitOrderRule:
    def test_manifest_last_proved(self):
        assert "F003" not in _rules(analyze_sources({"m.py": _F003_GOOD}))

    def test_reordered_write_flagged_with_path(self):
        """Acceptance (ISSUE 12): a deliberately reordered write is
        flagged with the violating path."""
        f = _one(analyze_sources({"m.py": _F003_REORDERED}), "F003")
        assert "post-dominated" in f.message and "path [" in f.message
        assert f.line == 6                 # the payload write

    def test_conditional_manifest_skip_flagged(self):
        src = (
            "MANIFEST_NAME = 'MANIFEST.json'\n"
            "def commit(entries, tmp, fast):\n"
            "    for name, data in entries.items():\n"
            "        _write_file(tmp + name, data)\n"
            "    if not fast:\n"
            "        _write_file(tmp + MANIFEST_NAME, b'{}')\n")
        assert "F003" in _rules(analyze_sources({"m.py": src}))

    def test_exception_abort_paths_exempt(self):
        # a raise between payload and manifest aborts the commit — the
        # checkpoint stays invisible, which is the protocol working
        src = (
            "MANIFEST_NAME = 'MANIFEST.json'\n"
            "def commit(entries, tmp):\n"
            "    for name, data in entries.items():\n"
            "        _write_file(tmp + name, data)\n"
            "    if torn(tmp):\n"
            "        raise OSError('torn')\n"
            "    _write_file(tmp + MANIFEST_NAME, b'{}')\n")
        assert "F003" not in _rules(analyze_sources({"m.py": src}))

    def test_payload_only_functions_out_of_scope(self):
        # save_shard's shape: payload writes, no manifest — rank 0
        # commits later; the cross-rank ordering is the barrier's job
        src = ("def save_shard(tmp, name, data):\n"
               "    _write_file(tmp + name, data)\n")
        assert "F003" not in _rules(analyze_sources({"m.py": src}))

    def test_live_commit_functions_statically_proved(self):
        """Acceptance (ISSUE 12): F003 proves manifest-last for every
        commit path in robustness/checkpoint.py — both commit closures
        were analyzed (not skipped) and came back clean."""
        findings, a = _repo_analysis()
        assert [f for f in findings if f.rule == "F003"] == []
        checker = next(c for c in a.checkers if c.name == "commit_order")
        proved = {(p, fn) for p, fn in checker.proved
                  if p == "paddle_tpu/robustness/checkpoint.py"}
        assert ("paddle_tpu/robustness/checkpoint.py", "attempt") in proved
        assert ("paddle_tpu/robustness/checkpoint.py", "commit") in proved


# ---------------------------------------------------------------------------
# X005 — mesh-axis validity (ISSUE 12)
# ---------------------------------------------------------------------------

_MESH_FIXTURE = (
    "AXIS_DATA = 'data'\n"
    "AXIS_MODEL = 'model'\n"
    "def build_mesh(topology):\n"
    "    pass\n"
)


class TestMeshAxisRule:
    def _run(self, user_src):
        return analyze_sources({
            "paddle_tpu/distributed/mesh.py": _MESH_FIXTURE,
            "paddle_tpu/user.py": user_src,
        })

    def test_literal_phantom_axis_flagged(self):
        src = ("import jax\n"
               "def f(x):\n"
               "    return jax.lax.psum(x, 'modle')\n")
        fs = [f for f in self._run(src) if f.rule == "X005"]
        assert len(fs) == 1 and "'modle'" in fs[0].message

    def test_known_axis_ok(self):
        src = ("import jax\n"
               "def f(x):\n"
               "    return jax.lax.psum(x, 'model')\n")
        assert "X005" not in _rules(self._run(src))

    def test_module_constant_resolves(self):
        src = ("import jax\n"
               "MY_AXIS = 'data'\n"
               "BAD_AXIS = 'bogus'\n"
               "def good(x):\n"
               "    return jax.lax.axis_index(MY_AXIS)\n"
               "def bad(x):\n"
               "    return jax.lax.axis_index(BAD_AXIS)\n")
        fs = [f for f in self._run(src) if f.rule == "X005"]
        assert len(fs) == 1 and "'bogus'" in fs[0].message

    def test_reaching_defs_resolve_local(self):
        src = ("import jax\n"
               "def f(x, cond):\n"
               "    ax = 'data'\n"
               "    if cond:\n"
               "        ax = 'ghost'\n"
               "    return jax.lax.psum(x, ax)\n")
        fs = [f for f in self._run(src) if f.rule == "X005"]
        assert len(fs) == 1 and "'ghost'" in fs[0].message

    def test_param_one_hop_through_callers(self):
        src = ("import jax\n"
               "def helper(x, axis):\n"
               "    return jax.lax.psum(x, axis)\n"
               "def caller(x):\n"
               "    return helper(x, 'phantom')\n")
        fs = [f for f in self._run(src) if f.rule == "X005"]
        assert len(fs) == 1 and "'phantom'" in fs[0].message

    def test_param_default_resolves(self):
        src = ("import jax\n"
               "def f(x, axis='model'):\n"
               "    return jax.lax.psum(x, axis)\n")
        assert "X005" not in _rules(self._run(src))

    def test_constrain_spec_tuple(self):
        src = ("BATCH = ('data', 'nope')\n"
               "def f(t):\n"
               "    return constrain(t, BATCH, None)\n")
        fs = [f for f in self._run(src) if f.rule == "X005"]
        assert len(fs) == 1 and "'nope'" in fs[0].message

    def test_shard_map_partition_spec(self):
        src = ("from jax.sharding import PartitionSpec as P\n"
               "def f(body, mesh, v):\n"
               "    spec = P('data', 'missing_ax')\n"
               "    fn = compat_shard_map(body, mesh, (spec,), spec)\n"
               "    return fn(v)\n")
        fs = [f for f in self._run(src) if f.rule == "X005"]
        assert fs and "'missing_ax'" in fs[0].message

    def test_build_mesh_topology_keys_register(self):
        src = ("import jax\n"
               "def setup():\n"
               "    return build_mesh({'expertish': 4})\n"
               "def f(x):\n"
               "    return jax.lax.psum(x, 'expertish')\n")
        assert "X005" not in _rules(self._run(src))

    def test_unresolvable_sites_skipped(self):
        src = ("import jax\n"
               "def f(x, axes):\n"
               "    return jax.lax.psum(x, axes[0])\n")
        assert "X005" not in _rules(self._run(src))

    def test_repo_zero_findings_with_real_coverage(self):
        """Acceptance (ISSUE 12): X005 validates every mesh-axis site in
        the live repo with zero false positives — and actually resolved a
        meaningful number of axes rather than skipping everything."""
        findings, a = _repo_analysis()
        assert [f for f in findings if f.rule == "X005"] == []
        checker = next(c for c in a.checkers if c.name == "mesh_axes")
        assert checker.stats["sites"] >= 40
        assert checker.stats["axes_validated"] >= 20

    def test_expert_axis_has_one_source_of_truth(self):
        """The live finding X005 surfaced: moe's 'expert' axis was a
        stringly-typed orphan; it now rides mesh.AXIS_EXPERT."""
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed import moe
        assert moe.EXPERT_AXIS == mesh_mod.AXIS_EXPERT == "expert"


# ---------------------------------------------------------------------------
# check_static --fix (ISSUE 12 satellite) + per-rule timings
# ---------------------------------------------------------------------------

class TestCheckStaticFix:
    def _load_cli(self):
        spec = importlib.util.spec_from_file_location(
            "check_static", os.path.join(REPO, "tools", "check_static.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _write(self, tmp_path):
        mod = tmp_path / "mod.py"
        mod.write_text(
            "import threading\n"
            "t = threading.Thread(target=f)\n"
            "u = threading.Thread(\n"
            "    target=f,\n"
            "    name='w',\n"
            ")\n"
            "x = compute()  # lint-ok: C003 long gone\n")
        (tmp_path / "baseline.json").write_text('{"entries": []}\n')
        return mod

    def test_fix_dry_run_prints_diff_without_writing(self, tmp_path,
                                                     capsys):
        cli = self._load_cli()
        mod = self._write(tmp_path)
        before = mod.read_text()
        rc = cli.main(["--root", str(tmp_path), "--baseline",
                       str(tmp_path / "baseline.json"), "--no-cache",
                       "--fix"])
        out = capsys.readouterr().out
        assert rc == 0
        assert mod.read_text() == before          # dry run: untouched
        assert "+t = threading.Thread(target=f, daemon=True)" in out
        assert "lint-ok: C003" not in \
            [l for l in out.splitlines() if l.startswith("+")][-1]
        assert "dry run" in out

    def test_fix_apply_writes_and_run_is_clean(self, tmp_path, capsys):
        cli = self._load_cli()
        mod = self._write(tmp_path)
        rc = cli.main(["--root", str(tmp_path), "--baseline",
                       str(tmp_path / "baseline.json"), "--no-cache",
                       "--fix", "--apply"])
        assert rc == 0
        fixed = mod.read_text()
        assert fixed.count("daemon=True") == 2
        assert "lint-ok" not in fixed
        # the fixed tree parses and passes the gate
        rc = cli.main(["--root", str(tmp_path), "--baseline",
                       str(tmp_path / "baseline.json"), "--no-cache"])
        capsys.readouterr()
        assert rc == 0

    def test_json_reports_per_rule_timings(self, tmp_path, capsys):
        cli = self._load_cli()
        self._write(tmp_path)
        cli.main(["--root", str(tmp_path), "--baseline",
                  str(tmp_path / "baseline.json"), "--no-cache", "--json"])
        out = capsys.readouterr().out
        doc, _ = json.JSONDecoder().raw_decode(out.lstrip())
        timings = doc["rule_timings"]
        for name in ("index_build", "concurrency", "resource_release",
                     "commit_order", "mesh_axes"):
            assert name in timings
            assert isinstance(timings[name], float)

    def test_cfgs_persist_in_ast_cache(self, tmp_path):
        """Satellite: memoized CFGs ride the parsed-AST pickle — the
        second run rebuilds none of them."""
        from paddle_tpu.analysis import Analysis, AstCache, \
            default_checkers
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "m.py").write_text(
            "MANIFEST_NAME = 'MANIFEST.json'\n"
            "def commit(entries, tmp):\n"
            "    for name, data in entries.items():\n"
            "        _write_file(tmp + name, data)\n"
            "    _write_file(tmp + MANIFEST_NAME, b'{}')\n")
        cache_path = str(tmp_path / "cache.pkl")

        c1 = AstCache(cache_path)
        a1 = Analysis(default_checkers(), rel_root=str(tmp_path))
        assert a1.run_path(str(src_dir), cache=c1) == []
        assert a1.dataflow.built >= 1

        c2 = AstCache(cache_path)
        a2 = Analysis(default_checkers(), rel_root=str(tmp_path))
        assert a2.run_path(str(src_dir), cache=c2) == []
        assert a2.dataflow.built == 0
        assert a2.dataflow.from_cache >= 1


# ---------------------------------------------------------------------------
# future watch — the F002 runtime companion (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

class TestFutureWatch:
    def test_counts_created_awaited_resolved(self):
        from paddle_tpu.analysis import host_sync as hs
        from paddle_tpu.distributed.overlap import BucketFuture
        from paddle_tpu.distributed.grad_comm import GradBucket
        import numpy as _np

        hs.install_future_watch()
        try:
            hs._future_counts.clear()
            b = GradBucket(0, _np.dtype("float32"))
            b.add(0, (1,))
            fut = BucketFuture(b, value=1.0, resolved=True)
            assert fut.wait() == 1.0
            fut2 = BucketFuture(b)
            fut2._resolve(2.0)
            rep = hs.future_report()
            c = rep["classes"]["BucketFuture"]
            assert c["created"] == 2
            assert c["awaited"] == 1           # fut2 never awaited
            assert c["resolved"] == 2
            assert rep["unawaited"] == 1
        finally:
            hs._future_counts.clear()
            hs.uninstall_future_watch()

    def test_direct_done_wait_counts_as_awaited(self):
        # the flush()/abandon()/free_bucket() drain path
        from paddle_tpu.analysis import host_sync as hs
        from paddle_tpu.distributed.overlap import GatherFuture
        from paddle_tpu.distributed.grad_comm import GradBucket
        import numpy as _np

        hs.install_future_watch()
        try:
            hs._future_counts.clear()
            b = GradBucket(1, _np.dtype("float32"))
            b.add(0, (1,))
            fut = GatherFuture(b)
            fut._resolve(3.0)
            fut._done.wait()
            rep = hs.future_report()
            c = rep["classes"]["GatherFuture"]
            assert c == {"created": 1, "awaited": 1, "resolved": 1}
        finally:
            hs._future_counts.clear()
            hs.uninstall_future_watch()

    def test_uninstall_restores_init(self):
        from paddle_tpu.analysis import host_sync as hs
        from paddle_tpu.distributed import overlap
        orig = overlap.BucketFuture.__init__
        hs.install_future_watch()
        assert overlap.BucketFuture.__init__ is not orig
        hs.uninstall_future_watch()
        assert overlap.BucketFuture.__init__ is orig


# ---------------------------------------------------------------------------
# F004 — drained requests re-admitted on every path (ISSUE 17)
# ---------------------------------------------------------------------------

class TestDrainReadmitRule:
    def test_early_return_path_leaks_drained_requests(self):
        src = ("def scale_down(self, bad):\n"
               "    drained = self.engine.drain()\n"
               "    if bad:\n"
               "        return None\n"       # drained forgotten here
               "    self.queue.requeue_front(drained)\n")
        f = _one(analyze_sources({"m.py": src}), "F004")
        assert "'drained'" in f.message and "path" in f.message
        assert f.line == 2                   # anchored at the drain()

    def test_discarded_drain_flagged(self):
        src = "def evict(self):\n    self.engine.drain()\n"
        f = _one(analyze_sources({"m.py": src}), "F004")
        assert "discarded" in f.message

    def test_readmitted_on_all_paths_ok(self):
        src = ("def scale_down(self, bad):\n"
               "    drained = self.engine.drain()\n"
               "    if bad:\n"
               "        self.queue.requeue_front(drained)\n"
               "        return None\n"
               "    self.queue.requeue_front(drained)\n")
        assert "F004" not in _rules(analyze_sources({"m.py": src}))

    def test_queue_close_retires_drained_ok(self):
        # shutdown: the requests are retired WITH the queue
        src = ("def stop(self):\n"
               "    drained = self.engine.drain()\n"
               "    self.queue.close()\n")
        assert "F004" not in _rules(analyze_sources({"m.py": src}))

    def test_return_transfers_ownership_ok(self):
        src = ("def fence(self):\n"
               "    drained = self.engine.drain()\n"
               "    return drained\n")
        assert "F004" not in _rules(analyze_sources({"m.py": src}))

    def test_store_to_attribute_escapes_ok(self):
        src = ("def fence(self):\n"
               "    drained = self.engine.drain()\n"
               "    self._pending = drained\n")
        assert "F004" not in _rules(analyze_sources({"m.py": src}))

    def test_exception_between_drain_and_requeue_leaks(self):
        # a raise-capable call between fence and re-admission: the
        # NO_PANIC path set still sees the early `return` leak below
        src = ("def scale_down(self, idx):\n"
               "    drained = self.engine.drain()\n"
               "    if not drained:\n"
               "        return 0\n"
               "    self.hd.stop()\n"
               "    self.queue.requeue_front(drained)\n"
               "    return len(drained)\n")
        # empty-list early return still carries the (empty) obligation —
        # the rule is syntactic about ownership, not list length; the
        # idiom is to requeue unconditionally (it is a no-op when empty)
        assert "F004" in _rules(analyze_sources({"m.py": src}))

    def test_unrelated_drain_like_names_out_of_scope(self):
        # drain(x) with args, or a bare-name drain() call, is not the
        # engine-fence maker
        src = ("def f(tank):\n"
               "    drain(tank)\n"
               "    water = drain()\n")
        assert "F004" not in _rules(analyze_sources({"m.py": src}))

    def test_live_scale_and_evict_paths_statically_proved(self):
        """Acceptance (ISSUE 17): every drain() in the serving runtime —
        evict(), scale_down(), and the fleet harness — is proved paired
        with re-admission or queue retirement on all non-panic paths."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "F004"] == []


class TestSpanCloseRule:
    """F005 (ISSUE 18): begin_span() obligations close on ALL paths —
    exception edges included, like F001. The proof shape is bind-None,
    open inside try, end_span in finally (what tracing.span() does)."""

    def test_early_return_path_leaks_span(self):
        src = ("def prefill(self, ctx, bad):\n"
               "    sp = self.tracer.begin_span(ctx, 'prefill')\n"
               "    if bad:\n"
               "        return None\n"        # sp never ended here
               "    self.tracer.end_span(sp)\n")
        f = _one(analyze_sources({"m.py": src}), "F005")
        assert "'sp'" in f.message and "path" in f.message
        assert f.line == 2                    # anchored at the open

    def test_exception_edge_leaks_without_finally(self):
        # a straight-line close is NOT enough: work() can raise, and the
        # exception edge reaches exit before end_span — F005 runs with
        # ALL_KINDS, so only a finally (or the span() cm) discharges it
        src = ("def decode(self, ctx):\n"
               "    sp = self.tracer.begin_span(ctx, 'decode')\n"
               "    self.work()\n"
               "    self.tracer.end_span(sp)\n")
        assert "F005" in _rules(analyze_sources({"m.py": src}))

    def test_try_finally_close_proved(self):
        src = ("def decode(self, ctx):\n"
               "    sp = None\n"
               "    try:\n"
               "        sp = self.tracer.begin_span(ctx, 'decode')\n"
               "        self.work()\n"
               "    finally:\n"
               "        self.tracer.end_span(sp)\n")
        assert "F005" not in _rules(analyze_sources({"m.py": src}))

    def test_span_contextmanager_shape_proved(self):
        # the generator behind `with tracer.span(...)`: yield escapes to
        # the caller AND the finally ends it — clean on every edge
        src = ("def span(self, ctx, name):\n"
               "    sp = None\n"
               "    try:\n"
               "        sp = self.begin_span(ctx, name)\n"
               "        yield sp\n"
               "    finally:\n"
               "        self.end_span(sp)\n")
        assert "F005" not in _rules(analyze_sources({"m.py": src}))

    def test_discarded_begin_span_flagged(self):
        src = "def f(self, ctx):\n    self.tracer.begin_span(ctx, 'x')\n"
        f = _one(analyze_sources({"m.py": src}), "F005")
        assert "discarded" in f.message

    def test_direct_return_out_of_scope_ok(self):
        # never bound to a local: the caller owns the close
        src = ("def open_hop(self, ctx):\n"
               "    return self.tracer.begin_span(ctx, 'hop')\n")
        assert "F005" not in _rules(analyze_sources({"m.py": src}))

    def test_direct_attribute_store_ok(self):
        # escapes to an object that outlives the frame and closes later
        src = ("def arm(self, ctx):\n"
               "    self._sp = self.tracer.begin_span(ctx, 'bg')\n")
        assert "F005" not in _rules(analyze_sources({"m.py": src}))

    def test_waiver_suppresses(self):
        src = ("def f(self, ctx):\n"
               "    sp = self.tracer.begin_span(ctx, 'x')"
               "  # lint-ok: F005 closed by callee\n"
               "    self.stash(sp)\n")
        assert "F005" not in _rules(analyze_sources({"m.py": src}))

    def test_record_span_out_of_scope(self):
        # one-shot spans open nothing — the preferred lifecycle-edge API
        src = ("def retire(self, ctx):\n"
               "    self.tracer.record_span(ctx, 'retire', outcome='ok')\n")
        assert "F005" not in _rules(analyze_sources({"m.py": src}))

    def test_live_tracing_span_statically_proved(self):
        """Acceptance (ISSUE 18): every begin_span site in the repo —
        including tracing.span() itself — closes on all paths."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "F005"] == []


# ---------------------------------------------------------------------------
# F006 — standby promoted or torn down on every path (ISSUE 19)
# ---------------------------------------------------------------------------

class TestStandbyLifecycleRule:
    """F006: a standby acquired for warm handoff (``acquire_standby()``)
    must be promoted into the set OR torn down on every non-panic CFG
    path — a leaked standby is a live engine + KV pool no watchdog
    fences. NO_PANIC like F002/F004: cleanup code is trusted, and the
    idiomatic discharge is unconditional per branch (a conditional
    discharge in a ``finally`` creates infeasible-path false
    positives)."""

    def test_leaked_on_timeout_branch_flagged(self):
        src = ("def scale_up(self, warm):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    if not sb.ready():\n"
               "        return None\n"           # timeout branch leaks sb
               "    return sb.promote()\n")
        f = _one(analyze_sources({"m.py": src}), "F006")
        assert "'sb'" in f.message and "neither promoted nor torn down" \
            in f.message
        assert f.line == 2                       # anchored at the acquire

    def test_discarded_acquire_flagged(self):
        src = ("def grow(self):\n"
               "    self.rset.acquire_standby()\n")
        f = _one(analyze_sources({"m.py": src}), "F006")
        assert "discarded" in f.message

    def test_promote_or_abandon_per_branch_proved(self):
        # the live scale_up shape: unexpected exceptions abandon+raise,
        # then each post-try branch discharges unconditionally
        src = ("def scale_up(self):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    ok = False\n"
               "    try:\n"
               "        sb.warm(self.buckets())\n"
               "        ok = sb.ready()\n"
               "    except TimeoutError:\n"
               "        ok = False\n"
               "    except BaseException:\n"
               "        sb.abandon()\n"
               "        raise\n"
               "    if ok:\n"
               "        return sb.promote()\n"
               "    sb.abandon()\n"
               "    return None\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_panic_edges_trusted_by_design(self):
        # NO_PANIC semantics: the implicit may-raise edge of sb.warm()
        # is NOT tracked (the maker's own panic edge would otherwise
        # make every fixture unprovable). The repo's discipline for
        # unexpected exceptions is the explicit `except BaseException:
        # abandon(); raise` branch, proved by the per-branch fixture.
        src = ("def scale_up(self):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    sb.warm(self.buckets())\n"
               "    return sb.promote()\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_swap_in_arg_form_discharges(self):
        src = ("def grow(self):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    self.rset.swap_in(sb)\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_return_transfers_ownership(self):
        src = ("def make_standby(self):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    return sb\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_attribute_store_escapes(self):
        src = ("def park(self):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    self._parked = sb\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_stop_alias_discharges(self):
        src = ("def probe(self):\n"
               "    sb = self.rset.acquire_standby()\n"
               "    sb.stop()\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_waiver_suppresses(self):
        src = ("def grow(self):\n"
               "    self.rset.acquire_standby()"
               "  # lint-ok: F006 adopted by callee\n")
        assert "F006" not in _rules(analyze_sources({"m.py": src}))

    def test_live_warm_handoff_paths_statically_proved(self):
        """Acceptance (ISSUE 19): every acquire_standby in the repo —
        scale_up(warm=True) with its boot-budget timeout and exception
        branches — discharges the standby on all non-panic paths."""
        findings, _ = _repo_analysis()
        assert [f for f in findings if f.rule == "F006"] == []

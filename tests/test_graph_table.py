"""Graph table (ps/graph_table.py) — GNN storage + neighbor sampling.

Reference: ps/table/common_graph_table.cc.
"""
import numpy as np

from paddle_tpu.distributed.ps import GraphTable


def _toy():
    g = GraphTable(feature_dim=2, seed=0)
    # star: 0 -> 1..5, plus 1 -> 2
    g.add_edges([0, 0, 0, 0, 0, 1], [1, 2, 3, 4, 5, 2],
                weights=[1, 1, 1, 1, 10, 1])
    g.set_node_features(range(6), np.arange(12).reshape(6, 2))
    return g


def test_degree_and_len():
    g = _toy()
    np.testing.assert_array_equal(g.degree([0, 1, 3]), [5, 1, 0])
    assert len(g) == 2  # nodes with out-edges


def test_sample_neighbors_padded():
    g = _toy()
    out, cnt = g.sample_neighbors([0, 1, 9], 3)
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(cnt, [3, 1, 0])
    assert set(out[0]).issubset({1, 2, 3, 4, 5})
    assert out[1, 0] == 2 and (out[1, 1:] == -1).all()
    assert (out[2] == -1).all()


def test_weighted_sampling_prefers_heavy_edges():
    g = _toy()
    picks = []
    for _ in range(200):
        out, _ = g.sample_neighbors([0], 1, weighted=True, replace=True)
        picks.append(int(out[0, 0]))
    # edge 0->5 carries weight 10/14: must dominate
    assert picks.count(5) > 80


def test_node_features_and_random_nodes():
    g = _toy()
    f = g.get_node_features([2, 0])
    np.testing.assert_allclose(f, [[4, 5], [0, 1]])
    nodes = g.random_sample_nodes(2)
    assert set(nodes).issubset({0, 1})


def test_served_through_ps_server():
    from paddle_tpu.distributed.ps import PsClient, PsServer

    srv = PsServer().start()
    try:
        cli = PsClient([srv.endpoint])
        cli._call(0, "create_graph_table", table_id=3, feature_dim=0)
        cli._call(0, "graph_add_edges", table_id=3,
                  src=np.array([7, 7]), dst=np.array([8, 9]))
        out, cnt = cli._call(0, "graph_sample", table_id=3,
                             ids=np.array([7]), sample_size=2)
        assert cnt[0] == 2 and set(out[0]) == {8, 9}
        cli.close()
    finally:
        srv.stop()

"""Graph table (ps/graph_table.py) — GNN storage + neighbor sampling.

Reference: ps/table/common_graph_table.cc.
"""
import numpy as np

from paddle_tpu.distributed.ps import GraphTable


def _toy():
    g = GraphTable(feature_dim=2, seed=0)
    # star: 0 -> 1..5, plus 1 -> 2
    g.add_edges([0, 0, 0, 0, 0, 1], [1, 2, 3, 4, 5, 2],
                weights=[1, 1, 1, 1, 10, 1])
    g.set_node_features(range(6), np.arange(12).reshape(6, 2))
    return g


def test_degree_and_len():
    g = _toy()
    np.testing.assert_array_equal(g.degree([0, 1, 3]), [5, 1, 0])
    assert len(g) == 2  # nodes with out-edges


def test_sample_neighbors_padded():
    g = _toy()
    out, cnt = g.sample_neighbors([0, 1, 9], 3)
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(cnt, [3, 1, 0])
    assert set(out[0]).issubset({1, 2, 3, 4, 5})
    assert out[1, 0] == 2 and (out[1, 1:] == -1).all()
    assert (out[2] == -1).all()


def test_weighted_sampling_prefers_heavy_edges():
    g = _toy()
    picks = []
    for _ in range(200):
        out, _ = g.sample_neighbors([0], 1, weighted=True, replace=True)
        picks.append(int(out[0, 0]))
    # edge 0->5 carries weight 10/14: must dominate
    assert picks.count(5) > 80


def test_node_features_and_random_nodes():
    g = _toy()
    f = g.get_node_features([2, 0])
    np.testing.assert_allclose(f, [[4, 5], [0, 1]])
    nodes = g.random_sample_nodes(2)
    assert set(nodes).issubset({0, 1})


def test_served_through_ps_server():
    from paddle_tpu.distributed.ps import PsClient, PsServer

    srv = PsServer().start()
    try:
        cli = PsClient([srv.endpoint])
        cli._call(0, "create_graph_table", table_id=3, feature_dim=0)
        cli._call(0, "graph_add_edges", table_id=3,
                  src=np.array([7, 7]), dst=np.array([8, 9]))
        out, cnt = cli._call(0, "graph_sample", table_id=3,
                             ids=np.array([7]), sample_size=2)
        assert cnt[0] == 2 and set(out[0]) == {8, 9}
        cli.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# GNN parity vs a NetworkX oracle (VERDICT r3 item 6): sampling validity,
# degrees, walks (uniform / node2vec / metapath), pagination, save/load,
# the neighbor-sample cache, and the sharded PsClient surface.
# ---------------------------------------------------------------------------
import networkx as nx
import pytest


def _random_digraph(n=40, m=200, seed=7):
    rs = np.random.RandomState(seed)
    g = nx.DiGraph()
    g.add_nodes_from(range(n))
    edges = set()
    while len(edges) < m:
        u, v = rs.randint(0, n, 2)
        if u != v:
            edges.add((int(u), int(v)))
    g.add_edges_from(edges)
    return g


def _table_from_nx(g, seed=0):
    t = GraphTable(seed=seed)
    src, dst = zip(*g.edges())
    t.add_edges(np.asarray(src), np.asarray(dst))
    return t


def test_degrees_match_networkx_oracle():
    g = _random_digraph()
    t = _table_from_nx(g)
    ids = np.arange(40)
    np.testing.assert_array_equal(
        t.degree(ids), [g.out_degree(i) for i in range(40)])


def test_sampled_neighbors_are_real_edges():
    g = _random_digraph()
    t = _table_from_nx(g)
    ids = np.arange(40)
    out, cnt = t.sample_neighbors(ids, 5)
    for r, node in enumerate(ids.tolist()):
        nbrs = set(g.successors(node))
        assert cnt[r] == min(5, len(nbrs))
        got = set(out[r, :cnt[r]].tolist())
        assert got <= nbrs
        assert len(got) == cnt[r]  # replace=False: no duplicates


def test_random_walk_follows_edges():
    g = _random_digraph()
    t = _table_from_nx(g)
    walks = t.random_walk(np.arange(40), walk_len=8)
    for row in walks:
        for a, b in zip(row[:-1], row[1:]):
            if b == -1:
                break
            assert g.has_edge(int(a), int(b)), (a, b)


def test_node2vec_bias_discourages_return():
    # path graph 0-1-2 (undirected edges both ways): from 1 having come
    # from 0, large p makes returning to 0 rare; small p makes it dominant
    t = GraphTable(seed=0)
    t.add_edges([0, 1, 1, 2], [1, 0, 2, 1])

    def return_rate(p):
        tt = GraphTable(seed=0)
        tt.add_edges([0, 1, 1, 2], [1, 0, 2, 1])
        walks = tt.node2vec_walk(np.zeros(400, np.int64), walk_len=2,
                                 p=p, q=1.0)
        # step0=0, step1=1 (only option), step2 in {0, 2}
        return float(np.mean(walks[:, 2] == 0))

    assert return_rate(100.0) < 0.1
    assert return_rate(0.01) > 0.9


def test_meta_path_walk_alternates_types():
    t = GraphTable(seed=0)
    users = [0, 1]
    items = [100, 101, 102]
    t.add_edges([0, 0, 1], [100, 101, 102], etype="u2i")
    t.add_edges([100, 101, 102], [0, 0, 1], etype="i2u")
    walks = t.meta_path_walk(np.asarray(users), ["u2i", "i2u", "u2i"])
    for row in walks:
        assert row[0] in users
        assert row[1] in items and row[3] in items
        assert row[2] in users


def test_pull_graph_list_paginates_sorted():
    g = _random_digraph()
    t = _table_from_nx(g)
    all_nodes = sorted(set(u for u, _ in g.edges()))
    got = np.concatenate([t.pull_graph_list(s, 7)
                          for s in range(0, len(all_nodes) + 7, 7)])
    np.testing.assert_array_equal(got, all_nodes)


def test_save_load_roundtrip(tmp_path):
    g = _random_digraph()
    t = _table_from_nx(g)
    t.add_edges([3], [4], weights=[2.5], etype="typed")
    t.set_node_features([1, 2], np.arange(8, dtype=np.float32).reshape(2, 4))
    t.save(str(tmp_path / "graph"))
    t2 = GraphTable()
    t2.load(str(tmp_path / "graph"))
    np.testing.assert_array_equal(t2.degree(np.arange(40)),
                                  t.degree(np.arange(40)))
    np.testing.assert_array_equal(t2.degree([3], etype="typed"), [1])
    np.testing.assert_array_equal(
        t2.get_node_features([1, 2]), t.get_node_features([1, 2]))


def test_neighbor_sample_cache_hits_then_expires():
    t = GraphTable(seed=0)
    t.add_edges(np.zeros(50, np.int64), np.arange(1, 51))
    t.make_neighbor_sample_cache(size_limit=16, ttl=2)
    first, _ = t.sample_neighbors([0], 5)
    again, _ = t.sample_neighbors([0], 5)  # within ttl: identical sample
    np.testing.assert_array_equal(first, again)
    samples = {tuple(t.sample_neighbors([0], 5)[0][0].tolist())
               for _ in range(20)}  # ttl expiries force fresh draws
    assert len(samples) > 1


def test_sharded_psclient_graph_ops_match_local():
    from paddle_tpu.distributed.ps import PsClient, PsServer

    g = _random_digraph()
    s1, s2 = PsServer().start(), PsServer().start()
    try:
        cli = PsClient([s1.endpoint, s2.endpoint])
        cli.create_graph_table(5, feature_dim=0)
        src, dst = map(np.asarray, zip(*g.edges()))
        cli.graph_add_edges(5, src, dst)
        # both shards hold part of the graph
        assert len(s1.graph_tables[5]) > 0 and len(s2.graph_tables[5]) > 0
        ids = np.arange(40)
        np.testing.assert_array_equal(
            cli.graph_degree(5, ids), [g.out_degree(i) for i in range(40)])
        out, cnt = cli.graph_sample_neighbors(5, ids, 4)
        for r, node in enumerate(ids.tolist()):
            nbrs = set(g.successors(node))
            assert cnt[r] == min(4, len(nbrs))
            assert set(out[r, :cnt[r]].tolist()) <= nbrs
        walks = cli.graph_random_walk(5, ids, walk_len=5)
        for row in walks:
            for a, b in zip(row[:-1], row[1:]):
                if b == -1:
                    break
                assert g.has_edge(int(a), int(b))
        np.testing.assert_array_equal(
            cli.graph_pull_list(5, 3, 10),
            sorted(set(u for u, _ in g.edges()))[3:13])
        cli.close()
    finally:
        s1.stop()
        s2.stop()


def test_sharded_node_iter_and_lifecycle(tmp_path):
    """graph_node_iter streams every node exactly once across shards
    (O(N) epoch scan); graph_save/load/clear round-trip per shard."""
    from paddle_tpu.distributed.ps import PsClient, PsServer

    g = _random_digraph(n=60, m=300, seed=11)
    s1, s2 = PsServer().start(), PsServer().start()
    try:
        cli = PsClient([s1.endpoint, s2.endpoint])
        cli.create_graph_table(6, feature_dim=0)
        src, dst = map(np.asarray, zip(*g.edges()))
        cli.graph_add_edges(6, src, dst)
        all_nodes = sorted(set(int(u) for u, _ in g.edges()))

        seen = np.concatenate(list(cli.graph_node_iter(6, batch=7)))
        np.testing.assert_array_equal(seen, all_nodes)

        cli.graph_save(6, str(tmp_path / "g"))
        cli.graph_clear(6)
        assert cli.graph_pull_list(6, 0, 100).size == 0
        cli.graph_load(6, str(tmp_path / "g"))
        np.testing.assert_array_equal(
            cli.graph_degree(6, np.arange(60)),
            [g.out_degree(i) for i in range(60)])
        cli.close()
    finally:
        s1.stop()
        s2.stop()


def test_cache_invalidated_by_add_edges():
    t = GraphTable(seed=0)
    t.add_edges([0, 0], [1, 2])
    t.make_neighbor_sample_cache(size_limit=8, ttl=1000)
    out, cnt = t.sample_neighbors([0], 10)
    assert cnt[0] == 2
    t.add_edges([0], [3])
    out, cnt = t.sample_neighbors([0], 10)  # new edge visible immediately
    assert cnt[0] == 3 and 3 in set(out[0].tolist())

"""LoD (ragged) tensor machinery + paddle.fluid compat namespace.

Reference: paddle/fluid/framework/lod_tensor.h:33-40 (LoDTensor type,
Split/MergeLoDTensor), python/paddle/fluid/lod_tensor.py
(create_lod_tensor / create_random_int_lodtensor) and its unit test
python/paddle/fluid/tests/unittests/test_lod_tensor.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.framework.lod import (
    LoDTensor,
    create_lod_tensor,
    create_random_int_lodtensor,
    merge_lod_tensor,
    split_lod_tensor,
)


def test_create_lod_tensor_and_lod_forms():
    data = np.arange(10, dtype=np.float32).reshape(5, 2)
    t = create_lod_tensor(data, [[2, 3]])
    assert t.recursive_sequence_lengths() == [[2, 3]]
    # offset form (reference lod()): lengths [2,3] -> offsets [0,2,5]
    assert t.lod() == [[0, 2, 5]]
    assert t.has_valid_recursive_sequence_lengths()
    np.testing.assert_array_equal(t.numpy(), data)


def test_set_lod_offsets_roundtrip():
    t = LoDTensor(np.zeros((6, 1)))
    t.set_lod([[0, 1, 6]])
    assert t.recursive_sequence_lengths() == [[1, 5]]
    assert t.lod() == [[0, 1, 6]]


def test_invalid_recursive_seq_lens_rejected():
    data = np.zeros((5, 2), np.float32)
    with pytest.raises(ValueError):
        create_lod_tensor(data, [[2, 2]])  # sums to 4, data has 5 rows


def test_two_level_lod_validity():
    # outer level [2, 1] groups 3 inner sequences of lengths [2, 2, 3]
    data = np.zeros((7, 1), np.float32)
    t = create_lod_tensor(data, [[2, 1], [2, 2, 3]])
    assert t.has_valid_recursive_sequence_lengths()
    bad = LoDTensor(data, [[2, 2], [2, 2, 3]])  # outer sums to 4 != 3 inner
    assert not bad.has_valid_recursive_sequence_lengths()


def test_carrier_roundtrip_matches_sequence_ops():
    """to_carrier produces exactly what nn.functional.sequence_* consume."""
    import paddle_tpu.nn.functional as F

    rows = np.arange(12, dtype=np.float32).reshape(6, 2)
    t = create_lod_tensor(rows, [[1, 2, 3]])
    padded, lens = t.to_carrier(pad_value=0.0)
    assert padded.shape == (3, 3, 2)
    np.testing.assert_array_equal(lens, [1, 2, 3])
    # row 0 of seq 1 is rows[1]
    np.testing.assert_array_equal(padded[1, 0], rows[1])
    # padding tail is zero
    assert np.all(padded[0, 1:] == 0)

    back = LoDTensor.from_carrier(padded, lens)
    np.testing.assert_array_equal(back.numpy(), rows)
    assert back.recursive_sequence_lengths() == [[1, 2, 3]]

    # the carrier drives the sequence ops directly
    pooled = F.sequence_pool(paddle.to_tensor(padded), "sum",
                             lengths=paddle.to_tensor(np.asarray(lens)))
    np.testing.assert_allclose(pooled.numpy()[2], rows[3:].sum(0), rtol=1e-6)


def test_split_merge_lod_tensor():
    rows = np.arange(20, dtype=np.float32).reshape(10, 2)
    t = create_lod_tensor(rows, [[2, 3, 1, 4]])
    parts = split_lod_tensor(t, 2)
    assert parts[0].recursive_sequence_lengths() == [[2, 3]]
    assert parts[1].recursive_sequence_lengths() == [[1, 4]]
    np.testing.assert_array_equal(parts[0].numpy(), rows[:5])
    np.testing.assert_array_equal(parts[1].numpy(), rows[5:])
    merged = merge_lod_tensor(parts)
    np.testing.assert_array_equal(merged.numpy(), rows)
    assert merged.recursive_sequence_lengths() == [[2, 3, 1, 4]]


def test_create_random_int_lodtensor():
    t = create_random_int_lodtensor([[3, 2]], base_shape=[4], low=0, high=9)
    assert t.shape == (5, 4)
    assert t.numpy().dtype == np.int64
    assert t.numpy().min() >= 0 and t.numpy().max() <= 9


def test_fluid_namespace_surface():
    """fluid.* re-exports the real implementations (no parallel engine)."""
    assert fluid.LoDTensor is LoDTensor
    assert fluid.core.is_compiled_with_tpu()
    assert fluid.core.VarBase is paddle.Tensor
    assert isinstance(fluid.CPUPlace(), object)
    # Program/Executor are the static ones
    from paddle_tpu import static
    assert fluid.Program is static.Program
    assert fluid.Executor is static.Executor


def test_fluid_layers_compute():
    """fluid.layers functional spellings compute through the real kernels."""
    x = paddle.to_tensor(np.array([[-1.0, 2.0]], np.float32))
    y = fluid.layers.relu(x)
    np.testing.assert_allclose(y.numpy(), [[0.0, 2.0]])
    z = fluid.layers.elementwise_add(x, x)
    np.testing.assert_allclose(z.numpy(), [[-2.0, 4.0]])
    m = fluid.layers.reduce_mean(z)
    np.testing.assert_allclose(m.numpy(), 1.0)

"""Tests for paddle.distribution, paddle.fft, paddle.signal, paddle.linalg
namespaces (parity: unittests/test_distribution*.py, test_fft*.py,
test_stft_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distribution as D
import paddle_tpu.fft as pfft
import paddle_tpu.signal as signal


class TestDistributions:
    def test_normal_log_prob_entropy(self):
        n = D.Normal(0.0, 1.0)
        x = paddle.to_tensor(np.array([0.0, 1.0, -2.0], "float32"))
        lp = n.log_prob(x).numpy()
        expect = -0.5 * np.array([0.0, 1.0, 4.0]) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, expect, rtol=1e-5)
        ent = float(n.entropy().numpy())
        np.testing.assert_allclose(ent, 0.5 * np.log(2 * np.pi) + 0.5,
                                   rtol=1e-5)

    def test_normal_sampling_moments(self):
        paddle.seed(7)
        n = D.Normal(2.0, 3.0)
        s = n.sample([20000]).numpy()
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_normal_rsample_grad(self):
        paddle.seed(0)
        loc = paddle.to_tensor(np.array(1.0, "float32"))
        loc.stop_gradient = False
        n = D.Normal(loc, paddle.to_tensor(np.array(1.0, "float32")))
        s = n.rsample([64])
        s.sum().backward()
        assert loc.grad is not None
        np.testing.assert_allclose(float(loc.grad.numpy()), 64.0, rtol=1e-4)

    def test_kl_normal(self):
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = float(D.kl_divergence(p, q).numpy())
        expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl, expect, rtol=1e-5)

    def test_uniform(self):
        u = D.Uniform(0.0, 2.0)
        lp = u.log_prob(paddle.to_tensor(np.array([1.0], "float32"))).numpy()
        np.testing.assert_allclose(lp, [-np.log(2.0)], rtol=1e-6)
        assert float(u.entropy().numpy()) == pytest.approx(np.log(2.0))
        paddle.seed(1)
        s = u.sample([1000]).numpy()
        assert s.min() >= 0 and s.max() < 2

    def test_categorical(self):
        c = D.Categorical(logits=np.log(np.array([0.2, 0.3, 0.5], "float32")))
        lp = c.log_prob(paddle.to_tensor(np.array([2], "int64"))).numpy()
        np.testing.assert_allclose(lp, [np.log(0.5)], rtol=1e-5)
        ent = float(c.entropy().numpy())
        expect = -sum(p * np.log(p) for p in [0.2, 0.3, 0.5])
        np.testing.assert_allclose(ent, expect, rtol=1e-5)
        paddle.seed(3)
        s = c.sample([5000]).numpy()
        freq = np.bincount(s, minlength=3) / 5000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)

    def test_bernoulli(self):
        b = D.Bernoulli(probs=np.array(0.3, "float32"))
        lp1 = float(b.log_prob(paddle.to_tensor(
            np.array(1.0, "float32"))).numpy())
        np.testing.assert_allclose(lp1, np.log(0.3), rtol=1e-5)
        assert float(b.mean.numpy()) == pytest.approx(0.3)

    def test_beta_dirichlet_multinomial(self):
        beta = D.Beta(2.0, 3.0)
        assert float(beta.mean.numpy()) == pytest.approx(0.4)
        lp = float(beta.log_prob(paddle.to_tensor(
            np.array(0.5, "float32"))).numpy())
        np.testing.assert_allclose(lp, np.log(0.5 ** 1 * 0.5 ** 2 / (1 / 12)),
                                   rtol=1e-4)
        d = D.Dirichlet(np.array([1.0, 1.0, 1.0], "float32"))
        lp = float(d.log_prob(paddle.to_tensor(
            np.array([0.2, 0.3, 0.5], "float32"))).numpy())
        np.testing.assert_allclose(lp, np.log(2.0), rtol=1e-4)  # Γ(3)=2
        m = D.Multinomial(10, np.array([0.5, 0.5], "float32"))
        paddle.seed(2)
        s = m.sample([100]).numpy()
        assert (s.sum(-1) == 10).all()


class TestFFT:
    def test_fft_roundtrip(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(8, 16).astype("float32"))
        X = pfft.fft(x)
        back = pfft.ifft(X)
        np.testing.assert_allclose(back.numpy().real, x.numpy(), atol=1e-4)

    def test_rfft_matches_numpy(self):
        rs = np.random.RandomState(1)
        x = rs.randn(32).astype("float32")
        out = pfft.rfft(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, np.fft.rfft(x), atol=1e-3)

    def test_fft2_and_shift(self):
        rs = np.random.RandomState(2)
        x = rs.randn(4, 8).astype("float32")
        out = pfft.fft2(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, np.fft.fft2(x), atol=1e-3)
        sh = pfft.fftshift(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(sh, np.fft.fftshift(x))

    def test_fftfreq(self):
        np.testing.assert_allclose(pfft.fftfreq(8, 0.5).numpy(),
                                   np.fft.fftfreq(8, 0.5).astype("float32"))

    def test_norm_validation(self):
        with pytest.raises(ValueError):
            pfft.fft(paddle.to_tensor(np.zeros(4, "float32")), norm="bad")


class TestSignal:
    def test_frame(self):
        # paddle layout: axis=-1 → (frame_length, num_frames)
        x = paddle.to_tensor(np.arange(10, dtype="float32"))
        f = signal.frame(x, 4, 2).numpy()
        assert f.shape == (4, 4)
        np.testing.assert_allclose(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_allclose(f[:, 1], [2, 3, 4, 5])
        f0 = signal.frame(x, 4, 2, axis=0).numpy()
        assert f0.shape == (4, 4)
        np.testing.assert_allclose(f0[0], [0, 1, 2, 3])

    def test_overlap_add_inverts_frame_sum(self):
        x = paddle.to_tensor(np.ones(10, dtype="float32"))
        f = signal.frame(x, 4, 4)  # non-overlapping, (fl=4, nf=2)
        y = signal.overlap_add(f, 4).numpy()
        np.testing.assert_allclose(y, np.ones(8))  # 2 frames × 4

    def test_stft_istft_roundtrip(self):
        rs = np.random.RandomState(0)
        x = rs.randn(2, 400).astype("float32")
        n_fft = 64
        win = np.hanning(n_fft).astype("float32")
        spec = signal.stft(paddle.to_tensor(x), n_fft,
                           window=paddle.to_tensor(win))
        assert spec.shape[-2] == n_fft // 2 + 1
        back = signal.istft(spec, n_fft, window=paddle.to_tensor(win),
                            length=400)
        # edges lose energy under the window; compare the interior
        np.testing.assert_allclose(back.numpy()[:, 48:-48], x[:, 48:-48],
                                   atol=1e-3)


class TestLinalgNamespace:
    def test_namespace(self):
        import paddle_tpu.linalg as L

        rs = np.random.RandomState(0)
        a = rs.randn(4, 4).astype("float32")
        spd = a @ a.T + 4 * np.eye(4, dtype="float32")
        c = L.cholesky(paddle.to_tensor(spd)).numpy()
        np.testing.assert_allclose(c @ c.T, spd, rtol=1e-4, atol=1e-4)
        assert float(L.det(paddle.to_tensor(np.eye(3, dtype="float32")))
                     .numpy()) == pytest.approx(1.0)


def test_frame_overlap_add_axis0_roundtrip():
    x = paddle.to_tensor(np.arange(8, dtype="float32"))
    f = signal.frame(x, 4, 4, axis=0)  # (nf=2, fl=4), non-overlapping
    assert f.numpy().shape == (2, 4)
    y = signal.overlap_add(f, 4, axis=0).numpy()
    np.testing.assert_allclose(y, np.arange(8, dtype="float32"))


def test_rotate_bilinear_channel_fill():
    import paddle_tpu.vision.transforms.functional as TF

    img = (np.random.RandomState(0).rand(9, 9, 3) * 255).astype("uint8")
    out = TF.rotate(img, 30, interpolation="bilinear", expand=True,
                    fill=(255, 0, 0))
    assert out.shape[2] == 3


def test_linalg_tail():
    """lu_unpack/matrix_exp/householder_product/svd_lowrank/vector_norm
    (reference paddle.linalg tail)."""
    import scipy.linalg as sl

    import paddle_tpu.linalg as L

    rs = np.random.RandomState(0)
    a = rs.randn(5, 5).astype("f4")
    lu_t, piv = L.lu(paddle.to_tensor(a))
    P, Lw, U = L.lu_unpack(lu_t, piv)
    np.testing.assert_allclose(P.numpy() @ Lw.numpy() @ U.numpy(), a,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        L.matrix_exp(paddle.to_tensor(a * 0.1)).numpy(), sl.expm(a * 0.1),
        rtol=1e-4, atol=1e-5)
    (h, tau), _ = sl.qr(a, mode="raw")
    Q = L.householder_product(paddle.to_tensor(np.asarray(h, "f4")),
                              paddle.to_tensor(np.asarray(tau, "f4")))
    np.testing.assert_allclose(Q.numpy(), sl.qr(a)[0].astype("f4"),
                               rtol=1e-3, atol=1e-4)
    B = (rs.randn(30, 3) @ rs.randn(3, 20)).astype("f4")
    U_, S_, V_ = L.svd_lowrank(paddle.to_tensor(B), q=5)
    np.testing.assert_allclose(
        U_.numpy() @ np.diag(S_.numpy()) @ V_.numpy().T, B,
        rtol=1e-2, atol=1e-2)
    assert float(L.vector_norm(paddle.to_tensor(
        np.array([3., 4.], "f4")))) == 5.0

"""Reference-artifact importer (VERDICT r3 item 7).

Authors a genuine reference-format artifact — `__model__` ProgramDesc
protobuf (framework.proto:50-240) + combined persistables in the
SerializeToStream layout (lod_tensor.cc:190) — with an independent encoder,
then imports and executes it, checking numerics against numpy.
"""
import struct

import numpy as np
import pytest

from paddle_tpu.interop import load_paddle_inference_model
from paddle_tpu.interop.wire import (
    enc_bytes, enc_f32, enc_int, enc_tag, enc_varint, LEN,
)

FP32 = 5
LOD_TENSOR = 7
FEED_MINIBATCH = 9
FETCH_LIST = 10
(A_INT, A_FLOAT, A_STRING, A_INTS, A_FLOATS, A_STRINGS, A_BOOL,
 A_BOOLS) = range(8)


def msg(fno, payload):
    return enc_tag(fno, LEN) + enc_varint(len(payload)) + payload


def tensor_desc(dtype, dims):
    return enc_int(1, dtype) + b"".join(enc_int(2, d) for d in dims)


def var_desc(name, dtype=FP32, dims=(), persistable=False,
             type_id=LOD_TENSOR):
    vt = enc_int(1, type_id)
    if type_id == LOD_TENSOR:
        vt += msg(3, msg(1, tensor_desc(dtype, dims)))
    out = enc_bytes(1, name) + msg(2, vt)
    if persistable:
        out += enc_int(3, 1)
    return out


def attr(name, atype, value):
    out = enc_bytes(1, name) + enc_int(2, atype)
    if atype == A_INT:
        out += enc_int(3, value)
    elif atype == A_FLOAT:
        out += enc_f32(4, value)
    elif atype == A_STRING:
        out += enc_bytes(5, value)
    elif atype == A_INTS:
        out += b"".join(enc_int(6, v) for v in value)
    elif atype == A_BOOL:
        out += enc_int(10, int(value))
    return out


def op_desc(op_type, inputs, outputs, attrs=()):
    out = b""
    for param, args in inputs:
        out += msg(1, enc_bytes(1, param)
                   + b"".join(enc_bytes(2, a) for a in args))
    for param, args in outputs:
        out += msg(2, enc_bytes(1, param)
                   + b"".join(enc_bytes(2, a) for a in args))
    out += enc_bytes(3, op_type)
    for a in attrs:
        out += msg(4, a)
    return out


def block_desc(idx, vars_, ops):
    out = enc_int(1, idx) + enc_int(2, -1 if idx == 0 else 0)
    out += b"".join(msg(3, v) for v in vars_)
    out += b"".join(msg(4, o) for o in ops)
    return out


def program_desc(blocks):
    return b"".join(msg(1, b) for b in blocks)


def lod_tensor_stream(arr):
    """SerializeToStream: u32 ver, u64 lod_level(0), u32 ver, i32 desc size,
    TensorDesc, raw data."""
    desc = tensor_desc(FP32, arr.shape)
    return (struct.pack("<I", 0) + struct.pack("<Q", 0)
            + struct.pack("<I", 0) + struct.pack("<i", len(desc))
            + desc + np.ascontiguousarray(arr, np.float32).tobytes())


@pytest.fixture
def mlp_artifact(tmp_path):
    """feed -> mul(w1) -> +b1 -> relu -> mul(w2) -> +b2 -> softmax -> fetch"""
    rs = np.random.RandomState(0)
    w1 = rs.randn(4, 8).astype(np.float32)
    b1 = rs.randn(8).astype(np.float32)
    w2 = rs.randn(8, 3).astype(np.float32)
    b2 = rs.randn(3).astype(np.float32)

    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 4)),
        var_desc("w1", dims=(4, 8), persistable=True),
        var_desc("b1", dims=(8,), persistable=True),
        var_desc("w2", dims=(8, 3), persistable=True),
        var_desc("b2", dims=(3,), persistable=True),
        var_desc("h0", dims=(-1, 8)), var_desc("h1", dims=(-1, 8)),
        var_desc("h2", dims=(-1, 8)), var_desc("h3", dims=(-1, 3)),
        var_desc("h4", dims=(-1, 3)), var_desc("out", dims=(-1, 3)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("mul", [("X", ["x"]), ("Y", ["w1"])], [("Out", ["h0"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        op_desc("elementwise_add", [("X", ["h0"]), ("Y", ["b1"])],
                [("Out", ["h1"])], [attr("axis", A_INT, -1)]),
        op_desc("relu", [("X", ["h1"])], [("Out", ["h2"])]),
        op_desc("mul", [("X", ["h2"]), ("Y", ["w2"])], [("Out", ["h3"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        op_desc("elementwise_add", [("X", ["h3"]), ("Y", ["b2"])],
                [("Out", ["h4"])], [attr("axis", A_INT, -1)]),
        op_desc("softmax", [("X", ["h4"])], [("Out", ["out"])],
                [attr("axis", A_INT, -1)]),
        op_desc("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    prog = program_desc([block_desc(0, vars_, ops)])
    (tmp_path / "__model__").write_bytes(prog)
    # combined persistables, sorted by name: b1, b2, w1, w2
    with open(tmp_path / "__params__", "wb") as f:
        for arr in (b1, b2, w1, w2):
            f.write(lod_tensor_stream(arr))
    weights = dict(w1=w1, b1=b1, w2=w2, b2=b2)
    return tmp_path, weights


def _np_mlp(x, w):
    h = np.maximum(x @ w["w1"] + w["b1"], 0.0)
    z = h @ w["w2"] + w["b2"]
    e = np.exp(z - z.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_import_and_run_matches_numpy(mlp_artifact):
    path, w = mlp_artifact
    prog = load_paddle_inference_model(str(path),
                                       params_filename="__params__")
    assert prog.feed_names == ["x"]
    assert prog.fetch_names == ["out"]
    x = np.random.RandomState(1).randn(5, 4).astype(np.float32)
    (got,) = prog.run({"x": x})
    np.testing.assert_allclose(got, _np_mlp(x, w), rtol=1e-5, atol=1e-6)


def test_imported_model_compiles_under_jit(mlp_artifact):
    import jax

    path, w = mlp_artifact
    prog = load_paddle_inference_model(str(path),
                                       params_filename="__params__")
    fn = jax.jit(lambda feed: prog.as_fn()(feed))
    x = np.random.RandomState(2).randn(3, 4).astype(np.float32)
    (got,) = fn({"x": x})
    np.testing.assert_allclose(np.asarray(got), _np_mlp(x, w),
                               rtol=1e-5, atol=1e-6)


def test_separate_param_files(tmp_path, mlp_artifact):
    src, w = mlp_artifact
    # re-lay the same program with one file per var (save_params layout)
    (tmp_path / "__model__").write_bytes((src / "__model__").read_bytes())
    for name, arr in w.items():
        (tmp_path / name).write_bytes(lod_tensor_stream(arr))
    prog = load_paddle_inference_model(str(tmp_path))
    x = np.random.RandomState(3).randn(2, 4).astype(np.float32)
    (got,) = prog.run({"x": x})
    np.testing.assert_allclose(got, _np_mlp(x, w), rtol=1e-5, atol=1e-6)


def test_conv_pool_bn_model(tmp_path):
    """conv2d -> batch_norm (inference) -> relu -> pool2d -> flatten."""
    rs = np.random.RandomState(4)
    kernel = rs.randn(6, 3, 3, 3).astype(np.float32)
    scale = rs.rand(6).astype(np.float32) + 0.5
    bias = rs.randn(6).astype(np.float32)
    mean = rs.randn(6).astype(np.float32) * 0.1
    var = rs.rand(6).astype(np.float32) + 0.5

    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=(-1, 3, 8, 8)),
        var_desc("k", dims=(6, 3, 3, 3), persistable=True),
        var_desc("bn_s", dims=(6,), persistable=True),
        var_desc("bn_b", dims=(6,), persistable=True),
        var_desc("bn_m", dims=(6,), persistable=True),
        var_desc("bn_v", dims=(6,), persistable=True),
        var_desc("c0", dims=(-1, 6, 8, 8)), var_desc("c1", dims=(-1, 6, 8, 8)),
        var_desc("c2", dims=(-1, 6, 8, 8)), var_desc("p0", dims=(-1, 6, 4, 4)),
        var_desc("out", dims=(-1, 96)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc("conv2d", [("Input", ["img"]), ("Filter", ["k"])],
                [("Output", ["c0"])],
                [attr("strides", A_INTS, [1, 1]),
                 attr("paddings", A_INTS, [1, 1]),
                 attr("dilations", A_INTS, [1, 1]),
                 attr("groups", A_INT, 1)]),
        op_desc("batch_norm",
                [("X", ["c0"]), ("Scale", ["bn_s"]), ("Bias", ["bn_b"]),
                 ("Mean", ["bn_m"]), ("Variance", ["bn_v"])],
                [("Y", ["c1"])], [attr("epsilon", A_FLOAT, 1e-5)]),
        op_desc("relu", [("X", ["c1"])], [("Out", ["c2"])]),
        op_desc("pool2d", [("X", ["c2"])], [("Out", ["p0"])],
                [attr("pooling_type", A_STRING, "max"),
                 attr("ksize", A_INTS, [2, 2]),
                 attr("strides", A_INTS, [2, 2]),
                 attr("paddings", A_INTS, [0, 0])]),
        op_desc("flatten_contiguous_range", [("X", ["p0"])],
                [("Out", ["out"])],
                [attr("start_axis", A_INT, 1), attr("stop_axis", A_INT, 3)]),
        op_desc("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    (tmp_path / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    with open(tmp_path / "__params__", "wb") as f:
        # sorted: bn_b, bn_m, bn_s, bn_v, k
        for arr in (bias, mean, scale, var, kernel):
            f.write(lod_tensor_stream(arr))

    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    (got,) = prog.run({"img": x})

    # numpy oracle
    import jax

    conv = np.asarray(jax.lax.conv_general_dilated(
        x, kernel, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    sh = (1, 6, 1, 1)
    bn = ((conv - mean.reshape(sh)) / np.sqrt(var.reshape(sh) + 1e-5)
          * scale.reshape(sh) + bias.reshape(sh))
    r = np.maximum(bn, 0)
    pooled = r.reshape(2, 6, 4, 2, 4, 2).max((3, 5))
    np.testing.assert_allclose(got, pooled.reshape(2, -1),
                               rtol=1e-4, atol=1e-5)


def test_unmapped_op_raises_with_name(tmp_path):
    vars_ = [var_desc("x", dims=(2,)), var_desc("y", dims=(2,))]
    ops = [op_desc("some_exotic_op", [("X", ["x"])], [("Out", ["y"])])]
    (tmp_path / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    prog = load_paddle_inference_model(str(tmp_path))
    with pytest.raises(NotImplementedError, match="some_exotic_op"):
        prog.run({"x": np.zeros(2, np.float32)})


def test_create_predictor_serves_reference_artifact(mlp_artifact):
    """The standard inference API (Config -> create_predictor -> handles)
    must serve reference-format models directly — the ecosystem-migration
    path: point the predictor at a saved reference model dir."""
    from paddle_tpu.inference import Config, create_predictor

    path, w = mlp_artifact
    cfg = Config(str(path))
    pred = create_predictor(cfg)
    assert pred.get_input_names() == ["x"]
    x = np.random.RandomState(5).randn(4, 4).astype(np.float32)
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, _np_mlp(x, w), rtol=1e-5, atol=1e-6)


@pytest.mark.requires_jax_export
def test_save_optimized_model_roundtrip(tmp_path, mlp_artifact):
    """AnalysisPredictor::SaveOptimModel (analysis_predictor.h:265): a
    predictor serving a reference __model__ dir persists the optimized
    model as the NATIVE artifact triple; a fresh predictor on that prefix
    serves identical outputs without touching the reference format."""
    from paddle_tpu.inference import Config, create_predictor

    path, w = mlp_artifact
    pred = create_predictor(Config(str(path)))
    x = np.random.RandomState(7).randn(4, 4).astype(np.float32)
    (ref_out,) = pred.run([x])

    prefix = str(tmp_path / "optim" / "mlp")
    pdmodel = pred.save_optimized_model(prefix)
    assert pdmodel.endswith(".pdmodel")
    import os
    for suffix in (".pdmodel", ".pdiparams", ".manifest.json"):
        assert os.path.exists(prefix + suffix), suffix

    pred2 = create_predictor(Config(prefix))
    from paddle_tpu.inference.io import InferenceArtifact
    assert isinstance(pred2._artifact, InferenceArtifact)  # native load
    (out2,) = pred2.run([x])
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref_out),
                               rtol=1e-6, atol=1e-7)
    # the dynamic batch dim survives export: another batch size serves
    x8 = np.random.RandomState(8).randn(8, 4).astype(np.float32)
    (out8,) = pred2.run([x8])
    np.testing.assert_allclose(np.asarray(out8), _np_mlp(x8, w),
                               rtol=1e-5, atol=1e-6)

    # native artifacts re-save as-is
    prefix3 = str(tmp_path / "resave" / "mlp")
    pred2.save_optimized_model(prefix3)
    pred3 = create_predictor(Config(prefix3))
    (out3,) = pred3.run([x])
    np.testing.assert_allclose(np.asarray(out3), np.asarray(ref_out),
                               rtol=1e-6, atol=1e-7)


def test_create_predictor_pdmodel_protobuf(tmp_path, mlp_artifact):
    """prefix.pdmodel holding a reference ProgramDesc (not our StableHLO
    blob, no manifest) + prefix.pdiparams combined persistables."""
    from paddle_tpu.inference import Config, create_predictor

    src, w = mlp_artifact
    (tmp_path / "m.pdmodel").write_bytes((src / "__model__").read_bytes())
    (tmp_path / "m.pdiparams").write_bytes((src / "__params__").read_bytes())
    pred = create_predictor(Config(str(tmp_path / "m.pdmodel")))
    x = np.random.RandomState(6).randn(2, 4).astype(np.float32)
    (out,) = pred.run([x])
    np.testing.assert_allclose(np.asarray(out.copy_to_cpu()
                                          if hasattr(out, "copy_to_cpu")
                                          else out),
                               _np_mlp(x, w), rtol=1e-5, atol=1e-6)


def test_predictor_explicit_params_file(tmp_path, mlp_artifact):
    """Config(model, params) two-file signature with a non-prefix params
    name must load the named params file."""
    from paddle_tpu.inference import Config, create_predictor

    src, w = mlp_artifact
    (tmp_path / "net.pdmodel").write_bytes((src / "__model__").read_bytes())
    (tmp_path / "weights.bin").write_bytes((src / "__params__").read_bytes())
    pred = create_predictor(Config(str(tmp_path / "net.pdmodel"),
                                   str(tmp_path / "weights.bin")))
    x = np.random.RandomState(7).randn(2, 4).astype(np.float32)
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, _np_mlp(x, w), rtol=1e-5, atol=1e-6)


def test_pdmodel_missing_params_fails_at_load(tmp_path, mlp_artifact):
    from paddle_tpu.inference import Config, create_predictor

    src, _ = mlp_artifact
    (tmp_path / "net.pdmodel").write_bytes((src / "__model__").read_bytes())
    with pytest.raises(FileNotFoundError):
        create_predictor(Config(str(tmp_path / "net.pdmodel")))


def test_mobile_ops_numerics(tmp_path):
    """The mobile-net op tail: depthwise conv, hard_swish, leaky_relu,
    adaptive pool, interp, gather/stack/arg_max — numerics vs numpy/jax."""
    import jax

    rs = np.random.RandomState(8)
    dw = rs.randn(3, 1, 3, 3).astype(np.float32)  # depthwise [C,1,kh,kw]
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=(-1, 3, 8, 8)),
        var_desc("dw", dims=(3, 1, 3, 3), persistable=True),
        var_desc("c0", dims=(-1, 3, 8, 8)), var_desc("h0", dims=(-1, 3, 8, 8)),
        var_desc("h1", dims=(-1, 3, 8, 8)), var_desc("p0", dims=(-1, 3, 2, 2)),
        var_desc("u0", dims=(-1, 3, 4, 4)), var_desc("am", dims=(-1, 3, 4)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc("depthwise_conv2d", [("Input", ["img"]), ("Filter", ["dw"])],
                [("Output", ["c0"])],
                [attr("strides", A_INTS, [1, 1]),
                 attr("paddings", A_INTS, [1, 1]),
                 attr("dilations", A_INTS, [1, 1]),
                 attr("groups", A_INT, 3)]),
        op_desc("hard_swish", [("X", ["c0"])], [("Out", ["h0"])]),
        op_desc("leaky_relu", [("X", ["h0"])], [("Out", ["h1"])],
                [attr("alpha", A_FLOAT, 0.1)]),
        op_desc("pool2d", [("X", ["h1"])], [("Out", ["p0"])],
                [attr("pooling_type", A_STRING, "avg"),
                 attr("ksize", A_INTS, [2, 2]),
                 attr("adaptive", A_BOOL, True)]),
        op_desc("nearest_interp_v2", [("X", ["p0"])], [("Out", ["u0"])],
                [attr("out_h", A_INT, 4), attr("out_w", A_INT, 4)]),
        op_desc("arg_max", [("X", ["u0"])], [("Out", ["am"])],
                [attr("axis", A_INT, -1)]),
        op_desc("fetch", [("X", ["am"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    (tmp_path / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    with open(tmp_path / "__params__", "wb") as f:
        f.write(lod_tensor_stream(dw))

    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    (got,) = prog.run({"img": x})

    conv = np.asarray(jax.lax.conv_general_dilated(
        x, dw, (1, 1), [(1, 1), (1, 1)], feature_group_count=3,
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    hs = conv * np.clip(conv + 3.0, 0, 6.0) / 6.0
    lr = np.where(hs >= 0, hs, 0.1 * hs)
    pooled = lr.reshape(2, 3, 2, 4, 2, 4).mean((3, 5))
    up = pooled.repeat(2, axis=2).repeat(2, axis=3)
    ref = up.argmax(-1)
    np.testing.assert_array_equal(got, ref)


A_BLOCK = 8
INT32 = 2
BOOL = 0


def attr_block(name, block_idx):
    return (enc_bytes(1, name) + enc_int(2, A_BLOCK)
            + enc_int(12, block_idx))


def test_imported_while_loop(tmp_path):
    """A reference-style while program: acc/i live in the enclosing scope;
    the sub-block increments, accumulates and recomputes Condition —
    trip count follows the FED bound."""
    vars_main = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("n", dtype=FP32, dims=()),
        var_desc("i", dtype=FP32, dims=()),
        var_desc("acc", dtype=FP32, dims=()),
        var_desc("cond", dtype=BOOL, dims=()),
    ]
    ops_main = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["n"])],
                [attr("col", A_INT, 0)]),
        op_desc("fill_constant", [], [("Out", ["i"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("fill_constant", [], [("Out", ["acc"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("less_than", [("X", ["i"]), ("Y", ["n"])],
                [("Out", ["cond"])]),
        op_desc("while",
                [("X", ["i", "acc", "n"]), ("Condition", ["cond"])],
                [("Out", ["i", "acc"])],
                [attr_block("sub_block", 1)]),
        op_desc("fetch", [("X", ["acc"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    ops_sub = [
        op_desc("increment", [("X", ["i"])], [("Out", ["i"])],
                [attr("step", A_FLOAT, 1.0)]),
        op_desc("elementwise_add", [("X", ["acc"]), ("Y", ["i"])],
                [("Out", ["acc"])], [attr("axis", A_INT, -1)]),
        op_desc("less_than", [("X", ["i"]), ("Y", ["n"])],
                [("Out", ["cond"])]),
    ]
    (tmp_path / "__model__").write_bytes(program_desc([
        block_desc(0, vars_main, ops_main),
        block_desc(1, [], ops_sub),
    ]))
    prog = load_paddle_inference_model(str(tmp_path))
    for n, expect in [(3.0, 6.0), (7.0, 28.0), (0.0, 0.0)]:
        (acc,) = prog.run({"n": np.float32(n)})
        assert float(acc) == expect, (n, acc)


def test_imported_conditional_block(tmp_path):
    vars_main = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dtype=FP32, dims=(-1,)),
        var_desc("flag", dtype=BOOL, dims=()),
        var_desc("zero", dtype=FP32, dims=()),
        var_desc("s", dtype=FP32, dims=()),
        var_desc("y", dtype=FP32, dims=(-1,)),
    ]
    ops_main = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("reduce_sum", [("X", ["x"])], [("Out", ["s"])],
                [attr("keep_dim", A_BOOL, False)]),
        op_desc("fill_constant", [], [("Out", ["zero"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("greater_than", [("X", ["s"]), ("Y", ["zero"])],
                [("Out", ["flag"])]),
        # default: y = x; the block overwrites with 2x when sum(x) > 0
        op_desc("assign", [("X", ["x"])], [("Out", ["y"])]),
        op_desc("conditional_block", [("Cond", ["flag"]), ("Input", ["x"])],
                [("Out", ["y"])],
                [attr_block("sub_block", 1),
                 attr("is_scalar_condition", A_BOOL, True)]),
        op_desc("fetch", [("X", ["y"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    ops_sub = [
        op_desc("scale", [("X", ["x"])], [("Out", ["y"])],
                [attr("scale", A_FLOAT, 2.0), attr("bias", A_FLOAT, 0.0)]),
    ]
    (tmp_path / "__model__").write_bytes(program_desc([
        block_desc(0, vars_main, ops_main),
        block_desc(1, [], ops_sub),
    ]))
    prog = load_paddle_inference_model(str(tmp_path))
    pos = np.asarray([1.0, 2.0], np.float32)
    neg = np.asarray([-1.0, -2.0], np.float32)
    (y,) = prog.run({"x": pos})
    np.testing.assert_allclose(y, pos * 2)       # branch fired
    (y,) = prog.run({"x": neg})
    np.testing.assert_allclose(y, neg)           # branch skipped


def test_imported_conditional_block_non_scalar(tmp_path):
    """Proto-default is_scalar_condition=False: the sub-block runs iff the
    Cond inputs are NON-EMPTY — element values are irrelevant, and an
    empty Cond skips (conditional_block_op.h:124-128)."""
    vars_main = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dtype=FP32, dims=(-1,)),
        var_desc("cond", dtype=FP32, dims=(-1,)),
        var_desc("y", dtype=FP32, dims=(-1,)),
    ]
    ops_main = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("feed", [("X", ["feed"])], [("Out", ["cond"])],
                [attr("col", A_INT, 1)]),
        op_desc("assign", [("X", ["x"])], [("Out", ["y"])]),
        # no is_scalar_condition attr: proto default (False) applies
        op_desc("conditional_block",
                [("Cond", ["cond"]), ("Input", ["x"])],
                [("Out", ["y"])], [attr_block("sub_block", 1)]),
        op_desc("fetch", [("X", ["y"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    ops_sub = [
        op_desc("scale", [("X", ["x"])], [("Out", ["y"])],
                [attr("scale", A_FLOAT, 2.0), attr("bias", A_FLOAT, 0.0)]),
    ]
    (tmp_path / "__model__").write_bytes(program_desc([
        block_desc(0, vars_main, ops_main),
        block_desc(1, [], ops_sub),
    ]))
    prog = load_paddle_inference_model(str(tmp_path))
    x = np.asarray([1.0, 2.0], np.float32)
    # non-empty Cond of ALL-ZERO values still fires (values irrelevant)
    (y,) = prog.run({"x": x, "cond": np.zeros(3, np.float32)})
    np.testing.assert_allclose(y, x * 2)
    # empty Cond skips (no error)
    (y,) = prog.run({"x": x, "cond": np.zeros(0, np.float32)})
    np.testing.assert_allclose(y, x)


def test_round_trip_save_after_passes(tmp_path):
    """import -> optimize (passes) -> SAVE back to reference format ->
    reload: numerics identical, op list smaller, folded constants and
    pruned params synced into the written descriptors."""
    from paddle_tpu.inference.passes import run_inference_passes
    from paddle_tpu.interop import save_paddle_inference_model

    rs = np.random.RandomState(9)
    w = rs.randn(4, 4).astype(np.float32)
    c = rs.randn(4, 4).astype(np.float32)
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 4)),
        var_desc("c", dims=(4, 4), persistable=True),
        var_desc("w", dims=(4, 4), persistable=True),
        var_desc("w2", dims=(4, 4)), var_desc("h", dims=(-1, 4)),
        var_desc("hd", dims=(-1, 4)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("elementwise_add", [("X", ["w"]), ("Y", ["c"])],
                [("Out", ["w2"])], [attr("axis", A_INT, -1)]),  # foldable
        op_desc("mul", [("X", ["x"]), ("Y", ["w2"])], [("Out", ["h"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        op_desc("dropout", [("X", ["h"])], [("Out", ["hd"])],
                [attr("dropout_prob", A_FLOAT, 0.5),
                 attr("dropout_implementation", A_STRING,
                      "upscale_in_train")]),  # identity
        op_desc("fetch", [("X", ["hd"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    src = tmp_path / "src"
    src.mkdir()
    (src / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    with open(src / "__params__", "wb") as f:
        for arr in (c, w):  # sorted names
            f.write(lod_tensor_stream(arr))

    prog = load_paddle_inference_model(str(src),
                                       params_filename="__params__")
    x = rs.randn(4, 4).astype(np.float32)
    (before,) = prog.run({"x": x})
    n_ops = len(prog.blocks[0].ops)
    run_inference_passes(prog)

    out_dir = tmp_path / "optimized"
    save_paddle_inference_model(prog, str(out_dir))
    prog2 = load_paddle_inference_model(str(out_dir),
                                        params_filename="__params__")
    (after,) = prog2.run({"x": x})
    np.testing.assert_allclose(after, before, rtol=1e-6)
    np.testing.assert_allclose(after, x @ (w + c), rtol=1e-6)
    assert len(prog2.blocks[0].ops) < n_ops
    # folded constant w2 became a persistable; w and c were pruned
    assert "w2" in prog2.params and "c" not in prog2.params
    assert prog2.feed_names == ["x"]


def test_round_trip_while_program(tmp_path):
    """Multi-block (control flow) programs serialize losslessly too —
    attr types (incl. BLOCK) survive the round trip."""
    from paddle_tpu.interop import save_paddle_inference_model

    # reuse the while artifact from test_imported_while_loop
    vars_main = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("n", dtype=FP32, dims=()),
        var_desc("i", dtype=FP32, dims=()),
        var_desc("acc", dtype=FP32, dims=()),
        var_desc("cond", dtype=BOOL, dims=()),
    ]
    ops_main = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["n"])],
                [attr("col", A_INT, 0)]),
        op_desc("fill_constant", [], [("Out", ["i"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("fill_constant", [], [("Out", ["acc"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("less_than", [("X", ["i"]), ("Y", ["n"])],
                [("Out", ["cond"])]),
        op_desc("while",
                [("X", ["i", "acc", "n"]), ("Condition", ["cond"])],
                [("Out", ["i", "acc"])],
                [attr_block("sub_block", 1)]),
        op_desc("fetch", [("X", ["acc"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    ops_sub = [
        op_desc("increment", [("X", ["i"])], [("Out", ["i"])],
                [attr("step", A_FLOAT, 1.0)]),
        op_desc("elementwise_add", [("X", ["acc"]), ("Y", ["i"])],
                [("Out", ["acc"])], [attr("axis", A_INT, -1)]),
        op_desc("less_than", [("X", ["i"]), ("Y", ["n"])],
                [("Out", ["cond"])]),
    ]
    (tmp_path / "src").mkdir()
    (tmp_path / "src/__model__").write_bytes(program_desc([
        block_desc(0, vars_main, ops_main),
        block_desc(1, [], ops_sub),
    ]))
    prog = load_paddle_inference_model(str(tmp_path / "src"))
    save_paddle_inference_model(prog, str(tmp_path / "dst"),
                                params_filename=None)
    prog2 = load_paddle_inference_model(str(tmp_path / "dst"))
    for n, expect in [(4.0, 10.0), (0.0, 0.0)]:
        (acc,) = prog2.run({"n": np.float32(n)})
        assert float(acc) == expect


def test_round_trip_conv_bn_folded_model(tmp_path):
    """Serializing after fold_conv_bn (pass-synthesized ops + params) —
    and saving must NOT mutate the in-memory program."""
    import copy

    from paddle_tpu.inference.passes import run_inference_passes
    from paddle_tpu.interop import (
        load_paddle_inference_model, save_paddle_inference_model,
    )

    rs = np.random.RandomState(11)
    k = rs.randn(4, 3, 3, 3).astype(np.float32)
    s = rs.rand(4).astype(np.float32) + 0.5
    b = rs.randn(4).astype(np.float32)
    m = rs.randn(4).astype(np.float32) * 0.1
    v = rs.rand(4).astype(np.float32) + 0.5
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=(-1, 3, 8, 8)),
        var_desc("k", dims=(4, 3, 3, 3), persistable=True),
        var_desc("bn_s", dims=(4,), persistable=True),
        var_desc("bn_b", dims=(4,), persistable=True),
        var_desc("bn_m", dims=(4,), persistable=True),
        var_desc("bn_v", dims=(4,), persistable=True),
        var_desc("c0", dims=(-1, 4, 8, 8)), var_desc("c1", dims=(-1, 4, 8, 8)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc("conv2d", [("Input", ["img"]), ("Filter", ["k"])],
                [("Output", ["c0"])],
                [attr("strides", A_INTS, [1, 1]),
                 attr("paddings", A_INTS, [1, 1]),
                 attr("dilations", A_INTS, [1, 1]),
                 attr("groups", A_INT, 1)]),
        op_desc("batch_norm",
                [("X", ["c0"]), ("Scale", ["bn_s"]), ("Bias", ["bn_b"]),
                 ("Mean", ["bn_m"]), ("Variance", ["bn_v"])],
                [("Y", ["c1"])], [attr("epsilon", A_FLOAT, 1e-5)]),
        op_desc("fetch", [("X", ["c1"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    src = tmp_path / "src"
    src.mkdir()
    (src / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    with open(src / "__params__", "wb") as f:
        for arr in (b, m, s, v, k):
            f.write(lod_tensor_stream(arr))

    prog = load_paddle_inference_model(str(src),
                                       params_filename="__params__")
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    (before,) = prog.run({"img": x})
    run_inference_passes(prog)
    vars_before_save = dict(prog.blocks[0].vars)
    names_before_save = list(prog.persistable_names)

    save_paddle_inference_model(prog, str(tmp_path / "dst"))
    # the saved-from program is untouched
    assert prog.blocks[0].vars == vars_before_save
    assert prog.persistable_names == names_before_save

    prog2 = load_paddle_inference_model(str(tmp_path / "dst"),
                                        params_filename="__params__")
    (after,) = prog2.run({"img": x})
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)
    assert "batch_norm" not in [o.type for o in prog2.blocks[0].ops]


def _interp_artifact(tmp_path, op_type, attrs, in_shape=(-1, 3, 5, 7),
                     out_shape=(-1, 3, -1, -1), extra_inputs=(),
                     extra_vars=()):
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=in_shape),
        var_desc("out", dims=out_shape),
    ] + list(extra_vars)
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc(op_type, [("X", ["img"])] + list(extra_inputs),
                [("Out", ["out"])], attrs),
        op_desc("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    (tmp_path / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    return load_paddle_inference_model(str(tmp_path))


def _np_bilinear_ref(x, oh, ow, align_corners, align_mode):
    """Independent numpy oracle of interpolate_op.h BilinearInterpFwd."""
    n, c, ih, iw = x.shape
    out = np.zeros((n, c, oh, ow), np.float64)
    for j in range(oh):
        for i in range(ow):
            if align_corners:
                sh = j * (ih - 1) / max(oh - 1, 1)
                sw = i * (iw - 1) / max(ow - 1, 1)
            elif align_mode == 1:
                sh, sw = j * ih / oh, i * iw / ow
            else:
                sh = (j + 0.5) * ih / oh - 0.5
                sw = (i + 0.5) * iw / ow - 0.5
            sh = min(max(sh, 0.0), ih - 1)
            sw = min(max(sw, 0.0), iw - 1)
            h0, w0 = int(np.floor(sh)), int(np.floor(sw))
            h1, w1 = min(h0 + 1, ih - 1), min(w0 + 1, iw - 1)
            fh, fw = sh - h0, sw - w0
            out[:, :, j, i] = (
                x[:, :, h0, w0] * (1 - fh) * (1 - fw)
                + x[:, :, h1, w0] * fh * (1 - fw)
                + x[:, :, h0, w1] * (1 - fh) * fw
                + x[:, :, h1, w1] * fh * fw)
    return out.astype(np.float32)


class TestInterpFamily:
    """VERDICT r3 next #10: the reference-DEFAULT interp modes
    (align_mode=1 origin-aligned bilinear, floor-indexed nearest at any
    scale, align_corners) import without re-export."""

    def _x(self):
        return np.random.RandomState(11).randn(2, 3, 5, 7).astype("f4")

    def test_bilinear_align_mode_1_default(self, tmp_path):
        # NO align_mode attr: the proto default (1) applies
        prog = _interp_artifact(tmp_path, "bilinear_interp_v2",
                                [attr("out_h", A_INT, 9),
                                 attr("out_w", A_INT, 11)])
        x = self._x()
        (got,) = prog.run({"img": x})
        np.testing.assert_allclose(
            got, _np_bilinear_ref(x, 9, 11, False, 1), rtol=1e-5,
            atol=1e-6)

    def test_bilinear_align_mode_0_matches_torch(self, tmp_path):
        torch = pytest.importorskip("torch")
        prog = _interp_artifact(tmp_path, "bilinear_interp_v2",
                                [attr("out_h", A_INT, 8),
                                 attr("out_w", A_INT, 10),
                                 attr("align_mode", A_INT, 0)])
        x = self._x()
        (got,) = prog.run({"img": x})
        ref = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(8, 10), mode="bilinear",
            align_corners=False).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_bilinear_align_corners_matches_torch(self, tmp_path):
        torch = pytest.importorskip("torch")
        prog = _interp_artifact(tmp_path, "bilinear_interp_v2",
                                [attr("out_h", A_INT, 9),
                                 attr("out_w", A_INT, 13),
                                 attr("align_corners", A_BOOL, True)])
        x = self._x()
        (got,) = prog.run({"img": x})
        ref = torch.nn.functional.interpolate(
            torch.from_numpy(x), size=(9, 13), mode="bilinear",
            align_corners=True).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_nearest_non_integer_scale(self, tmp_path):
        prog = _interp_artifact(tmp_path, "nearest_interp_v2",
                                [attr("out_h", A_INT, 7),
                                 attr("out_w", A_INT, 9)])
        x = self._x()
        (got,) = prog.run({"img": x})
        idx_h = np.minimum(np.arange(7) * 5 // 7, 4)
        idx_w = np.minimum(np.arange(9) * 7 // 9, 6)
        ref = x[:, :, idx_h][:, :, :, idx_w]
        np.testing.assert_array_equal(got, ref)

    def test_out_size_tensor_input(self, tmp_path):
        prog = _interp_artifact(
            tmp_path, "bilinear_interp_v2", [],
            extra_inputs=[("OutSize", ["osz"])],
            extra_vars=[var_desc("osz", dtype=INT32, dims=(2,))])
        x = self._x()
        (got,) = prog.run({"img": x,
                           "osz": np.asarray([6, 8], np.int32)})
        np.testing.assert_allclose(
            got, _np_bilinear_ref(x, 6, 8, False, 1), rtol=1e-5,
            atol=1e-6)


class TestTopKEdges:
    def _artifact(self, tmp_path, attrs, extra_inputs=(), extra_vars=()):
        vars_ = [
            var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
            var_desc("fetch", type_id=FETCH_LIST, persistable=True),
            var_desc("x", dims=(-1, 6)),
            var_desc("v", dims=(-1, -1)), var_desc("ix", dims=(-1, -1)),
        ] + list(extra_vars)
        ops = [
            op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                    [attr("col", A_INT, 0)]),
            op_desc("top_k_v2", [("X", ["x"])] + list(extra_inputs),
                    [("Out", ["v"]), ("Indices", ["ix"])], attrs),
            op_desc("fetch", [("X", ["v"])], [("Out", ["fetch"])],
                    [attr("col", A_INT, 0)]),
            op_desc("fetch", [("X", ["ix"])], [("Out", ["fetch"])],
                    [attr("col", A_INT, 1)]),
        ]
        (tmp_path / "__model__").write_bytes(
            program_desc([block_desc(0, vars_, ops)]))
        return load_paddle_inference_model(str(tmp_path))

    def test_tensor_k_input(self, tmp_path):
        prog = self._artifact(
            tmp_path, [], extra_inputs=[("K", ["kt"])],
            extra_vars=[var_desc("kt", dtype=INT32, dims=(1,))])
        x = np.random.RandomState(3).randn(4, 6).astype("f4")
        v, ix = prog.run({"x": x, "kt": np.asarray([3], np.int32)})
        ref = np.sort(x, axis=-1)[:, ::-1][:, :3]
        np.testing.assert_allclose(v, ref, rtol=1e-6)
        assert v.shape == (4, 3) and ix.shape == (4, 3)

    def test_smallest_and_axis(self, tmp_path):
        prog = self._artifact(tmp_path,
                              [attr("k", A_INT, 2),
                               attr("axis", A_INT, 0),
                               attr("largest", A_BOOL, False)])
        x = np.random.RandomState(4).randn(5, 6).astype("f4")
        v, ix = prog.run({"x": x})
        ref = np.sort(x, axis=0)[:2, :]
        np.testing.assert_allclose(v, ref, rtol=1e-6)
        assert v.shape == (2, 6)


def test_nearest_align_corners_rounds_half_up(tmp_path):
    """5 -> 9 with align_corners: src coords land exactly on .5 at output
    rows 1,3,5,7; the reference's static_cast<int>(ratio*j + 0.5) rounds
    half UP -> indices [0,1,1,2,2,3,3,4,4] (np.rint's half-to-even would
    wrongly give [0,0,1,2,2,2,3,4,4])."""
    prog = _interp_artifact(tmp_path, "nearest_interp_v2",
                            [attr("out_h", A_INT, 9),
                             attr("out_w", A_INT, 9),
                             attr("align_corners", A_BOOL, True)],
                            in_shape=(-1, 1, 5, 5))
    x = np.arange(2 * 1 * 5 * 5, dtype=np.float32).reshape(2, 1, 5, 5)
    (got,) = prog.run({"img": x})
    idx = np.array([0, 1, 1, 2, 2, 3, 3, 4, 4])
    ref = x[:, :, idx][:, :, :, idx]
    np.testing.assert_array_equal(got, ref)

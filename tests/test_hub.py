"""paddle.hub local-source loader (reference: hapi/hub.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


HUBCONF = '''
import paddle_tpu.nn as nn

def tiny_mlp(hidden=8):
    """A tiny MLP entrypoint."""
    return nn.Sequential(nn.Linear(4, hidden), nn.ReLU(), nn.Linear(hidden, 2))

def _private():
    pass
'''


def test_hub_list_help_load(tmp_path):
    (tmp_path / "hubconf.py").write_text(HUBCONF)
    names = paddle.hub.list(str(tmp_path))
    assert "tiny_mlp" in names and "_private" not in names
    assert "tiny MLP" in paddle.hub.help(str(tmp_path), "tiny_mlp")
    model = paddle.hub.load(str(tmp_path), "tiny_mlp", hidden=16)
    out = model(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert tuple(out.shape) == (2, 2)


def test_hub_remote_sources_raise(tmp_path):
    with pytest.raises(NotImplementedError, match="egress"):
        paddle.hub.load("user/repo", "m", source="github")

"""SelectedRows row-sparse embedding gradients.

Reference: pten/core/selected_rows.h:38 + lookup_table grad (is_sparse) +
lazy-mode sparse optimizer kernels. The contract: a vocab-V embedding step
allocates O(batch·seq·dim) gradient state, not O(V·dim), and the update
matches the dense path exactly on the touched rows.
"""
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.framework.selected_rows import SelectedRows


def _ids(batch=4, seq=3, vocab=50, seed=0):
    return np.random.RandomState(seed).randint(0, vocab, (batch, seq))


def test_sparse_grad_is_selected_rows():
    emb = nn.Embedding(1000, 8, sparse=True)
    ids = paddle.to_tensor(_ids(vocab=1000), dtype="int64")
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad._value
    assert isinstance(g, SelectedRows)
    assert g.height == 1000
    # O(batch*seq), NOT O(vocab)
    assert g.rows.shape == (12,)
    assert g.values.shape == (12, 8)


def test_sparse_matches_dense_grad():
    rs = np.random.RandomState(1)
    w0 = rs.randn(50, 6).astype("float32")
    ids_np = _ids(vocab=50, seed=2)

    def run(sparse):
        emb = nn.Embedding(50, 6, sparse=sparse)
        emb.weight.set_value(w0)
        out = emb(paddle.to_tensor(ids_np, dtype="int64"))
        (out * out).sum().backward()
        g = emb.weight.grad._value
        return np.asarray(g.to_dense() if isinstance(g, SelectedRows) else g)

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6, atol=1e-6)


def test_sparse_sgd_step_matches_dense():
    rs = np.random.RandomState(3)
    w0 = rs.randn(40, 5).astype("float32")
    ids_np = _ids(vocab=40, seed=4)

    def run(sparse):
        emb = nn.Embedding(40, 5, sparse=sparse)
        emb.weight.set_value(w0)
        o = opt.SGD(learning_rate=0.1, parameters=emb.parameters())
        for step in range(3):
            out = emb(paddle.to_tensor(ids_np, dtype="int64"))
            (out * out).sum().backward()
            o.step()
            o.clear_grad()
        return emb.weight.numpy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_sparse_adam_step_matches_dense_on_touched_rows():
    rs = np.random.RandomState(5)
    w0 = rs.randn(30, 4).astype("float32")
    ids_np = np.array([[1, 7, 7, 2]])

    def run(sparse):
        emb = nn.Embedding(30, 4, sparse=sparse)
        emb.weight.set_value(w0)
        o = opt.Adam(learning_rate=0.01, parameters=emb.parameters())
        out = emb(paddle.to_tensor(ids_np, dtype="int64"))
        (out * out).sum().backward()
        o.step()
        o.clear_grad()
        return emb.weight.numpy()

    dense, sparse = run(False), run(True)
    touched = [1, 2, 7]
    np.testing.assert_allclose(sparse[touched], dense[touched],
                               rtol=1e-5, atol=1e-6)
    # untouched rows identical to init under sparse (lazy mode)
    untouched = [i for i in range(30) if i not in touched]
    np.testing.assert_allclose(sparse[untouched], w0[untouched])


def test_padding_idx_rows_get_zero_grad():
    emb = nn.Embedding(20, 4, padding_idx=0, sparse=True)
    ids = paddle.to_tensor(np.array([[0, 3, 0, 5]]), dtype="int64")
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad._value
    dense = np.asarray(g.to_dense())
    np.testing.assert_allclose(dense[0], 0.0)
    assert np.abs(dense[3]).sum() > 0


def test_grad_accumulation_sparse_plus_sparse():
    emb = nn.Embedding(25, 4, sparse=True)
    ids1 = paddle.to_tensor(np.array([[1, 2]]), dtype="int64")
    ids2 = paddle.to_tensor(np.array([[2, 3]]), dtype="int64")
    emb(ids1).sum().backward()
    emb(ids2).sum().backward()
    g = emb.weight.grad._value
    dense = np.asarray(g.to_dense() if isinstance(g, SelectedRows) else g)
    np.testing.assert_allclose(dense[2].sum(), 8.0)  # touched twice, dim 4
    np.testing.assert_allclose(dense[1].sum(), 4.0)


def test_merge_dedups_rows():
    sr = SelectedRows(jnp.asarray([3, 1, 3], jnp.int32),
                      jnp.asarray([[1.0], [2.0], [10.0]]), 5)
    m = sr.merge()
    dense = np.asarray(m.to_dense())
    np.testing.assert_allclose(dense[:, 0], [0, 2, 0, 11, 0])

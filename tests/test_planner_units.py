"""Pure-function units of the AOT planner stack (no compiles).

The compile-heavy halves live in tests/test_tpu_aot.py (libtpu-gated);
these pin the arithmetic that ranks candidates — wrong math here silently
reorders plans without any compile failing.
"""
import pytest

from paddle_tpu.distributed.auto_parallel.planner import (
    enumerate_factorizations,
)
from paddle_tpu.jit.aot import (
    V5E_HBM_BYTES_PER_S, V5E_PEAK_BF16_FLOPS, estimate_step_seconds,
)


class TestEnumerateFactorizations:
    def test_products_cover_exactly_n(self):
        for n in (8, 16, 64):
            for axes in (("data", "model"), ("data", "sharding", "model")):
                for cand in enumerate_factorizations(n, axes):
                    prod = 1
                    for d in cand.values():
                        prod *= d
                    assert prod == n, (n, cand)
                    assert all(d > 1 for d in cand.values()) or cand == {
                        axes[0]: 1}

    def test_no_duplicates(self):
        cands = enumerate_factorizations(64, ("a", "b", "c"))
        keys = [tuple(sorted(c.items())) for c in cands]
        assert len(keys) == len(set(keys))

    def test_caps_respected(self):
        for cand in enumerate_factorizations(64, ("data", "model"),
                                             caps={"model": 4}):
            assert cand.get("model", 1) <= 4

    def test_single_axis_degenerate(self):
        assert enumerate_factorizations(1, ("data",)) == [{"data": 1}]

    def test_non_power_of_two(self):
        cands = enumerate_factorizations(12, ("a", "b"))
        assert {"a": 12} in cands and {"a": 4, "b": 3} in cands

    def test_unsatisfiable_caps_raise(self):
        with pytest.raises(ValueError, match="no way to place"):
            enumerate_factorizations(8, ("model",), caps={"model": 4})


class TestEstimateStepSeconds:
    def test_trusts_positive_compiler_estimate(self):
        out = estimate_step_seconds(
            {"optimal_seconds": 0.01, "flops": 1e15, "bytes_accessed": 1e12})
        assert out == {"seconds": 0.01, "signal": "compiler"}

    def test_negative_sentinel_falls_back_to_roofline(self):
        fl, by = 1e12, 1e11
        out = estimate_step_seconds(
            {"optimal_seconds": -21.9, "flops": fl, "bytes_accessed": by})
        assert out["signal"] == "roofline"
        assert out["seconds"] == pytest.approx(
            max(fl / V5E_PEAK_BF16_FLOPS, by / V5E_HBM_BYTES_PER_S))

    def test_roofline_picks_binding_resource(self):
        # HBM-bound: huge bytes, tiny flops
        out = estimate_step_seconds({"flops": 1e9, "bytes_accessed": 1e12})
        assert out["seconds"] == pytest.approx(1e12 / V5E_HBM_BYTES_PER_S)
        # compute-bound: huge flops, tiny bytes
        out = estimate_step_seconds({"flops": 1e15, "bytes_accessed": 1e9})
        assert out["seconds"] == pytest.approx(1e15 / V5E_PEAK_BF16_FLOPS)

    def test_flops_only(self):
        out = estimate_step_seconds({"flops": 2e14})
        assert out["signal"] == "roofline"
        assert out["seconds"] == pytest.approx(2e14 / V5E_PEAK_BF16_FLOPS)

    def test_nothing_usable_returns_none(self):
        assert estimate_step_seconds({}) is None
        assert estimate_step_seconds({"optimal_seconds": -1.0}) is None
        assert estimate_step_seconds({"flops": 0.0}) is None

    def test_custom_peaks(self):
        out = estimate_step_seconds({"flops": 100.0}, peak_flops=10.0,
                                    hbm_bw=1.0)
        assert out["seconds"] == pytest.approx(10.0)


class TestRankKey:
    def test_compiler_signal_outranks_roofline(self):
        """A roofline estimate is a lower bound that ignores collective
        time; it must never outrank a compiler-signal plan on raw seconds
        (ADVICE r4)."""
        from paddle_tpu.distributed.auto_parallel.planner import (
            MeshPlan, rank_key,
        )

        fast_roofline = MeshPlan({"data": 8}, est_seconds=0.010,
                                 est_signal="roofline")
        slow_compiler = MeshPlan({"model": 8}, est_seconds=0.018,
                                 est_signal="compiler")
        plans = sorted([fast_roofline, slow_compiler], key=rank_key)
        assert plans[0] is slow_compiler

        # among same-signal plans, seconds still decide
        a = MeshPlan({"data": 8}, est_seconds=0.02, est_signal="compiler")
        b = MeshPlan({"model": 8}, est_seconds=0.01, est_signal="compiler")
        assert sorted([a, b], key=rank_key)[0] is b

        # errored / over-budget plans sink regardless of signal
        err = MeshPlan({"data": 8}, error="boom")
        nofit = MeshPlan({"data": 8}, est_seconds=0.001,
                         est_signal="compiler", fits=False)
        order = sorted([err, nofit, fast_roofline], key=rank_key)
        assert order[-1] is err and order[-2] is nofit

"""Heterogeneous-stage 1F1B: PipelineParallel over a PipelineLayer.

The compat path (arbitrary LayerDesc lists, not scan-stacked weights) now
runs the genuine interleaved schedule when a 'pipe' axis exists and stage
boundaries are shape-uniform — stages selected by lax.switch inside the
pipeline_1f1b shard_map. Reference: pipeline_parallel.py train_batch over
pp_layers.PipelineLayer.
"""
import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.meta_parallel import PipelineParallel
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
    LayerDesc, PipelineLayer,
)

HID = 16
PIPE = 4


class _Strategy:
    pipeline_configs = {"accumulate_steps": 8, "schedule_mode": "1F1B"}


def _mse(out, lbl):
    return ((out - lbl) ** 2).mean()


def _make_layers(seed=0):
    paddle.seed(seed)
    descs = [LayerDesc(nn.Linear, HID, HID) for _ in range(2 * PIPE)]
    return PipelineLayer(descs, num_stages=PIPE, loss_fn=_mse)


@pytest.fixture
def pipe_mesh():
    prev = mesh_mod.get_mesh()
    mesh = mesh_mod.build_mesh({"pipe": PIPE}, devices=jax.devices()[:PIPE])
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod.set_mesh(prev)


def test_pipeline_layer_1f1b_matches_single_device(pipe_mesh):
    rs = np.random.RandomState(0)
    x_np = rs.randn(16, HID).astype(np.float32)
    y_np = rs.randn(16, HID).astype(np.float32)

    def run(pipelined):
        layers = _make_layers(seed=0)
        optim = opt.SGD(learning_rate=0.05,
                        parameters=layers.parameters())
        x = paddle.to_tensor(x_np)
        y = paddle.to_tensor(y_np)
        if pipelined:
            pp = PipelineParallel(layers, hcg=None, strategy=_Strategy())
            return [float(pp.train_batch((x, y), optim)) for _ in range(3)]
        from paddle_tpu.jit import TrainStep

        prev = mesh_mod.get_mesh()
        mesh_mod.set_mesh(None)
        try:
            step = TrainStep(layers, lambda o, lbl: _mse(o, lbl), optim)
            return [float(step((x,), (y,))) for _ in range(3)]
        finally:
            mesh_mod.set_mesh(prev)

    base = run(pipelined=False)
    pp = run(pipelined=True)
    np.testing.assert_allclose(pp, base, rtol=2e-4, atol=1e-6)


def test_pipeline_layer_1f1b_compiles_switch(pipe_mesh):
    """The compiled step must actually contain per-stage branching (a
    conditional), i.e. the interleaved path engaged rather than the
    fallback."""
    layers = _make_layers()
    optim = opt.SGD(learning_rate=0.05, parameters=layers.parameters())
    pp = PipelineParallel(layers, hcg=None, strategy=_Strategy())
    x = paddle.to_tensor(np.zeros((16, HID), np.float32))
    y = paddle.to_tensor(np.zeros((16, HID), np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the fallback would warn
        loss = pp.train_batch((x, y), optim)
    assert np.isfinite(float(loss))
    assert pp._train_step.grad_fn is not None  # 1F1B grad engine installed


def test_non_uniform_boundaries_fall_back_with_warning(pipe_mesh):
    paddle.seed(0)
    descs = [LayerDesc(nn.Linear, HID, 2 * HID)] + \
            [LayerDesc(nn.Linear, 2 * HID, 2 * HID)
             for _ in range(2 * PIPE - 2)] + \
            [LayerDesc(nn.Linear, 2 * HID, HID)]
    layers = PipelineLayer(descs, num_stages=PIPE, loss_fn=_mse)
    optim = opt.SGD(learning_rate=0.05, parameters=layers.parameters())
    pp = PipelineParallel(layers, hcg=None, strategy=_Strategy())
    x = paddle.to_tensor(np.zeros((16, HID), np.float32))
    y = paddle.to_tensor(np.zeros((16, HID), np.float32))
    with pytest.warns(UserWarning, match="same activation shape"):
        loss = pp.train_batch((x, y), optim)
    assert np.isfinite(float(loss))
    assert pp._train_step.grad_fn is None  # accumulate-steps fallback


def test_batchnorm_buffers_block_1f1b(pipe_mesh):
    """Stateful buffers can't thread through the tick scan: the wrapper
    must say so and fall back rather than silently freezing BN stats."""
    paddle.seed(0)
    descs = ([LayerDesc(nn.Linear, HID, HID) for _ in range(3)]
             + [LayerDesc(nn.BatchNorm1D, HID)]
             + [LayerDesc(nn.Linear, HID, HID) for _ in range(4)])
    layers = PipelineLayer(descs, num_stages=PIPE, loss_fn=_mse)
    optim = opt.SGD(learning_rate=0.05, parameters=layers.parameters())
    pp = PipelineParallel(layers, hcg=None, strategy=_Strategy())
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, HID)
                         .astype(np.float32))
    y = paddle.to_tensor(np.zeros((16, HID), np.float32))
    with pytest.warns(UserWarning, match="buffers"):
        loss = pp.train_batch((x, y), optim)
    assert np.isfinite(float(loss))
    assert pp._train_step.grad_fn is None


def test_switch_compile_scales_subquadratically_to_p8():
    """VERDICT r3 weak #3: the heterogeneous path compiles all P stage
    bodies on every rank via lax.switch — bound the risk at P=8. Measured
    (XLA-CPU): first-call trace+compile 1.6s at P=2 -> 2.5s at P=8, a
    1.56x growth for 4x the branches; this guard allows 4x before
    failing (a quadratic blowup would be ~16x). Per-rank programs
    (section_worker.cc style) stay unnecessary while this holds."""
    import time

    def first_call_seconds(P):
        prev = mesh_mod.get_mesh()
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"pipe": P}, devices=jax.devices()[:P]))
        try:
            paddle.seed(0)
            descs = [LayerDesc(nn.Linear, HID, HID) for _ in range(P)]
            layers = PipelineLayer(descs, num_stages=P, loss_fn=_mse)
            optim = opt.SGD(learning_rate=0.05,
                            parameters=layers.parameters())
            pp = PipelineParallel(layers, hcg=None, strategy=_Strategy())
            rs = np.random.RandomState(0)
            x = paddle.to_tensor(rs.randn(16, HID).astype(np.float32))
            y = paddle.to_tensor(rs.randn(16, HID).astype(np.float32))
            t0 = time.perf_counter()
            loss = float(pp.train_batch((x, y), optim))
            assert np.isfinite(loss)
            return time.perf_counter() - t0
        finally:
            mesh_mod.set_mesh(prev)

    # min-of-2: each call rebuilds the model and jit fn (full retrace),
    # so the min discards one-off contention spikes without hiding the
    # compile cost being bounded
    t2 = min(first_call_seconds(2), first_call_seconds(2))
    t8 = min(first_call_seconds(8), first_call_seconds(8))
    # measured numbers live in artifacts/pipeline_layer_switch_compile.json
    # (committed once, not rewritten per test run)
    assert t8 < 4.0 * t2, (t2, t8)

"""PS hot path (ISSUE 20): compiled dense step + async sharded embedding
pipeline.

Covers the tentpole contracts end to end:
- wire codec bit-parity with the PR-8 grad_comm blockwise transforms
  (the numpy wire pair must produce grad_comm's exact bits);
- key-hash shard routing + full pull/push parity vs a single LocalPs;
- duplicate-id gradient SUM through the sharded client (merge_sparse)
  and in-trace through PsTrainStep's scatter-add transpose;
- depth-1 pipeline == hand-rolled serial reference, bit-identical;
- depth-2 double buffering converges and hides pull latency;
- quantized wire: int8_block <= ~0.3x fp32 bytes at dim 32, loss parity
  band, error-feedback residuals carried per (table, shard);
- PR-4 failure model: timeout/retry -> typed DeadShardError naming the
  shard host; FLAGS_ps_degraded_ok serves zeros / drops-and-counts;
- tracing spans per step (pull_launch/pull_wait/step/push_commit);
- FLAGS_ps_* declared; wire-byte + cache-hit counters registered;
- tools/ps_bench.py --quick runs as the tier-1 smoke.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import LocalPs
from paddle_tpu.distributed.ps.pipeline import (
    BusShardedClient, DeadShardError, PsPipeline, PsShardService,
    PsTrainStep, decode_rows, encode_rows, make_sharded_ps, wire_nbytes)
from paddle_tpu.models import WideDeep, ctr_batches, wide_deep_loss

DIM = 8
SLOTS = 4
BATCH = 16


def _model_step(pad_rows=128, seed=0, lr=1e-3):
    paddle.seed(seed)
    model = WideDeep(SLOTS, DIM)
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    return PsTrainStep(model, opt, wide_deep_loss, dim=DIM,
                       pad_rows=pad_rows)


@pytest.fixture
def sharded():
    client, services, bus = make_sharded_ps(3, base_task=9100)
    client.create_table(0, DIM)
    yield client
    client.close()
    for s in services:
        s.stop()
    bus.close()


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

class TestWireCodec:
    def test_fp32_round_trip_and_bytes(self):
        rows = np.random.RandomState(0).randn(11, DIM).astype(np.float32)
        payload, resid = encode_rows(rows, "fp32")
        assert resid is None
        np.testing.assert_array_equal(decode_rows(payload), rows)
        keys = np.arange(11, dtype=np.uint64)
        assert wire_nbytes(payload, keys) == rows.nbytes + keys.nbytes

    @pytest.mark.parametrize("codec", ["int8_block", "fp8_block"])
    def test_bit_parity_with_grad_comm(self, codec):
        """The numpy wire pair must emit grad_comm's EXACT bits — scales,
        quantized payload, and EF residual (the PR-8 proof surface)."""
        import jax.numpy as jnp

        from paddle_tpu.distributed import grad_comm as G

        if codec == "fp8_block" and getattr(jnp, "float8_e4m3fn",
                                            None) is None:
            pytest.skip("no fp8 dtype in this jax")
        rs = np.random.RandomState(3)
        rows = (rs.randn(37, 16) * np.exp(rs.randn(37, 16))) \
            .astype(np.float32)
        payload, resid = encode_rows(rows, codec, block=64)
        flat = jnp.asarray(rows.reshape(-1))
        scales = G.block_scales(G.block_absmax(flat, 64), codec)
        q = G.block_encode(flat, scales, 64, codec)
        ref_wire = (np.asarray(q, np.int8) if codec == "int8_block"
                    else np.asarray(jnp.asarray(q).astype(
                        jnp.float8_e4m3fn)).view(np.uint8))
        ref_resid = np.asarray(
            G.block_residual(flat, q, scales, rows.size)).reshape(rows.shape)
        np.testing.assert_array_equal(payload["s"], np.asarray(scales))
        # the PS wire truncates block padding; parity on the real elements
        np.testing.assert_array_equal(payload["q"],
                                      ref_wire.reshape(-1)[:rows.size])
        np.testing.assert_array_equal(resid, ref_resid)

    def test_int8_decode_matches_dequant_and_counts_scale_bytes(self):
        rows = np.random.RandomState(1).randn(9, DIM).astype(np.float32)
        payload, resid = encode_rows(rows, "int8_block", block=16)
        deq = decode_rows(payload)
        # encode + residual reconstructs the input exactly
        np.testing.assert_allclose(deq + resid, rows, rtol=0, atol=1e-6)
        nb = wire_nbytes(payload)
        assert nb == payload["q"].nbytes + payload["s"].nbytes
        assert payload["q"].dtype == np.int8

    def test_unknown_codec_raises(self):
        with pytest.raises(ValueError, match="unknown PS wire codec"):
            encode_rows(np.zeros((2, 2), np.float32), "int4_block")


# ---------------------------------------------------------------------------
# sharded transport
# ---------------------------------------------------------------------------

class TestShardedClient:
    def test_pull_push_parity_vs_local(self, sharded):
        """Sharded pull/push must equal one LocalPs doing the same ops."""
        ref = LocalPs()
        ref.create_table(0, DIM)
        keys = np.random.RandomState(0).randint(
            0, 10_000, 40).astype(np.uint64)
        a = sharded.pull(0, keys)
        b = ref.pull(0, keys)
        np.testing.assert_array_equal(a, b)  # deterministic key-hash init
        g = np.random.RandomState(1).randn(40, DIM).astype(np.float32)
        sharded.push(0, keys, g, lr=0.5)
        ref.push(0, keys, g, lr=0.5)
        np.testing.assert_allclose(sharded.pull(0, keys), ref.pull(0, keys),
                                   rtol=0, atol=1e-6)

    def test_duplicate_ids_sum_not_last_write_win(self, sharded):
        """One push with the SAME id 3x must apply the SUMMED grad.
        SGD table so the update is exactly -lr * sum (adagrad would
        normalize the magnitude away)."""
        sharded.create_table(1, DIM, optimizer="sgd", lr=1.0,
                             init_range=0.0)
        keys = np.asarray([7, 7, 7], np.uint64)
        g = np.ones((3, DIM), np.float32)
        sharded.push(1, keys, g, lr=1.0)
        got = sharded.pull(1, keys[:1])
        np.testing.assert_allclose(got, np.full((1, DIM), -3.0),
                                   rtol=0, atol=1e-6)

    def test_routing_is_total_and_deterministic(self, sharded):
        keys = np.arange(1000, dtype=np.uint64)
        parts = sharded._route(keys)
        covered = np.concatenate([idx for _, idx, _ in parts])
        assert sorted(covered.tolist()) == list(range(1000))
        assert len(parts) == 3  # splitmix64 spreads a range over all shards
        again = sharded._route(keys)
        for (s1, i1, k1), (s2, i2, k2) in zip(parts, again):
            assert s1 == s2
            np.testing.assert_array_equal(k1, k2)

    def test_wire_byte_counters_by_codec(self):
        client, services, bus = make_sharded_ps(
            2, base_task=9200, codec="int8_block")
        try:
            client.create_table(0, DIM)
            keys = np.arange(64, dtype=np.uint64)
            client.pull(0, keys)
            client.push(0, keys, np.ones((64, DIM), np.float32), lr=0.1)
            assert client.pull_bytes > 0
            # int8 wire: q bytes ~= numel, far under fp32's 4*numel
            assert client.push_bytes < 64 * DIM * 4
            from paddle_tpu.observability.metrics import get_registry

            fam = get_registry().counter("ps_push_bytes_total",
                                         labels=("codec",))
            assert fam.labels(codec="int8_block").get() > 0
        finally:
            client.close()
            for s in services:
                s.stop()
            bus.close()

    def test_error_feedback_pushes_rounded_away_bits_eventually(self):
        """A grad with one dominant and one tiny component: each int8 push
        rounds the tiny one away, the EF residual re-adds it next push, so
        the accumulated server value converges near the true sum instead
        of dropping the tiny coordinate entirely."""
        client, services, bus = make_sharded_ps(
            1, base_task=9300, codec="int8_block")
        try:
            # SGD table: server value is exactly -lr * (sum of applied
            # grads), so the EF accounting is directly visible
            client.create_table(0, dim=4, optimizer="sgd", lr=1.0,
                                init_range=0.0)
            key = np.asarray([5], np.uint64)
            g = np.asarray([[100.0, 0.12, 0.0, 0.0]], np.float32)
            n = 50
            for _ in range(n):
                client.push(0, key, g, lr=1.0)
            # read the shard BACKEND directly: the client pull would come
            # back through the quantized wire too, hiding the tiny coord
            # again (pulls are stateless reads, no residual)
            got = services[0].backend.pull(0, key)
            want = -n * g[0]
            # the dominant coord is near-exact; the tiny one must be within
            # a few quantization steps of the truth (one step ~ 100/127)
            assert abs(got[0, 0] - want[0]) < 1.0
            assert abs(got[0, 1] - want[1]) < 2 * (100.0 / 127)
            assert client._resid  # residual store carries per-shard state
        finally:
            client.close()
            for s in services:
                s.stop()
            bus.close()


class TestFailureModel:
    def _dead_shard_setup(self, degraded_ok):
        from paddle_tpu.distributed import fleet_executor as fx

        bus = fx.MessageBus(rank=0)
        alive = PsShardService(bus, 9400, name="alive")
        dead = PsShardService(bus, 9401, name="dead")
        client = BusShardedClient(
            bus, [alive.task_id, dead.task_id], client_task=9402,
            timeout_s=0.05, retries=1, degraded_ok=degraded_ok,
            shard_names=["alive", "dead"])
        client.create_table(0, DIM)
        dead.stop()  # inbox stays registered; nothing drains -> timeouts
        return bus, alive, client

    def test_dead_shard_raises_typed_error_naming_host(self):
        bus, alive, client = self._dead_shard_setup(degraded_ok=False)
        try:
            keys = np.arange(64, dtype=np.uint64)  # hits both shards
            with pytest.raises(DeadShardError) as ei:
                client.pull(0, keys)
            assert ei.value.shard == 1
            assert ei.value.task_id == 9401
            assert "dead" in str(ei.value)
            from paddle_tpu.observability import get_event_log

            evs = get_event_log().events(kind="ps_shard_dead")
            assert evs and evs[-1]["host"] == "dead"
        finally:
            client.close()
            alive.stop()
            bus.close()

    def test_degraded_mode_zeros_pulls_and_drops_pushes(self):
        bus, alive, client = self._dead_shard_setup(degraded_ok=True)
        try:
            keys = np.arange(64, dtype=np.uint64)
            rows = client.pull(0, keys)  # no raise
            assert rows.shape == (64, DIM)
            from paddle_tpu.distributed.ps.pipeline import _shard_of

            dead_keys = _shard_of(keys, 2) == 1
            assert dead_keys.any() and (~dead_keys).any()
            assert np.all(rows[dead_keys] == 0.0)     # zeros for the dead
            assert np.any(rows[~dead_keys] != 0.0)    # live shard served
            before = client.dropped_pushes
            client.push(0, keys, np.ones((64, DIM), np.float32), lr=0.1)
            assert client.dropped_pushes > before     # counted, not raised
        finally:
            client.close()
            alive.stop()
            bus.close()


# ---------------------------------------------------------------------------
# compiled step + pipeline semantics
# ---------------------------------------------------------------------------

class TestPsTrainStep:
    def test_duplicate_ids_in_batch_sum_into_row_grad(self, sharded):
        """The gather transpose is a scatter-add: a row referenced by k
        slots gets k summed contributions in the EMITTED row grads."""
        import jax.numpy as jnp

        step = _model_step()
        # batch of 2: row 0 appears 3x, row 1 once in example 0, etc.
        slots = np.asarray([[0, 0, 0, 1], [2, 3, 3, 2]], np.int32)
        rows = jnp.asarray(np.random.RandomState(0).randn(
            step.pad_rows, DIM).astype(np.float32))
        labels = np.asarray([1.0, 0.0], np.float32)
        _, g_rows = step(rows, slots, labels)
        g = np.asarray(g_rows)
        assert np.any(g[0] != 0) and np.any(g[3] != 0)
        assert np.all(g[4:] == 0)  # untouched pad rows get zero grad

    def test_warm_map_reuses_compiled_step_across_instances(self):
        s1 = _model_step(seed=0)
        import jax.numpy as jnp

        rows = jnp.zeros((s1.pad_rows, DIM), jnp.float32)
        slots = np.zeros((BATCH, SLOTS), np.int32)
        labels = np.zeros(BATCH, np.float32)
        s1(rows, slots, labels)
        assert not s1.cache_hit  # first build compiled
        s2 = _model_step(seed=1)
        s2(jnp.zeros((s2.pad_rows, DIM), jnp.float32), slots, labels)
        assert s2.cache_hit  # same fingerprint+geometry -> warm map hit


class TestPipeline:
    def _serial_reference(self, client, batches, pad_rows=128, seed=0):
        """Hand-rolled pull -> compiled step -> merged push per batch —
        the semantics depth=1 must reproduce bit-for-bit."""
        import jax.numpy as jnp

        step = _model_step(pad_rows=pad_rows, seed=seed)
        losses = []
        for ids, labels in batches:
            uniq, inv = np.unique(
                np.asarray(ids, np.uint64).reshape(-1), return_inverse=True)
            rows = np.asarray(client.pull(0, uniq), np.float32)
            rows = np.pad(rows, ((0, pad_rows - rows.shape[0]), (0, 0)))
            slots = inv.astype(np.int32).reshape(ids.shape)
            loss, g_rows = step(jnp.asarray(rows), slots, labels)
            g = np.asarray(g_rows)[:uniq.size]
            nz = np.any(g != 0, axis=1)
            if nz.any():
                client.push(0, uniq[nz], g[nz], lr=0.1)
            losses.append(float(loss))
        return losses

    def test_depth1_bit_identical_to_serial_reference(self):
        batches = ctr_batches(6, BATCH, SLOTS, 500, alpha=1.0, seed=0)
        ref = LocalPs()
        ref.create_table(0, DIM)
        ref_losses = self._serial_reference(ref, batches)

        client, services, bus = make_sharded_ps(2, base_task=9500)
        try:
            client.create_table(0, DIM)
            step = _model_step()
            pipe = PsPipeline(client, 0, step, depth=1, lr_sparse=0.1)
            stats = pipe.run(batches)
            pipe.close()
            assert stats["losses"] == ref_losses  # BIT-identical
            # and the table state agrees exactly too
            keys = np.unique(np.concatenate(
                [b[0].reshape(-1) for b in batches]).astype(np.uint64))
            np.testing.assert_array_equal(client.pull(0, keys),
                                          ref.pull(0, keys))
        finally:
            client.close()
            for s in services:
                s.stop()
            bus.close()

    def test_depth2_converges_within_band_and_hides_pull(self):
        batches = ctr_batches(12, BATCH, SLOTS, 500, alpha=1.0, seed=0)
        client, services, bus = make_sharded_ps(2, base_task=9600)
        try:
            client.create_table(0, DIM)
            step = _model_step()
            pipe = PsPipeline(client, 0, step, depth=2, lr_sparse=0.1)
            stats = pipe.run(batches)
            pipe.close()
            losses = stats["losses"]
            assert losses[-1] < losses[0]  # staleness-1 downpour trains
            assert stats["exposed_pull_ms"] < 10 * stats["step_ms"] + 50
        finally:
            client.close()
            for s in services:
                s.stop()
            bus.close()

    def test_quantized_wire_loss_parity_and_byte_ratio(self):
        """int8_block wire at dim 32: <= ~0.3x fp32 bytes, loss within a
        parity band of the fp32 wire (EF residuals at work)."""
        dim, slots, pad = 32, 8, 512
        batches = ctr_batches(8, 32, slots, 2000, alpha=1.1, seed=0)

        def run(codec):
            client, services, bus = make_sharded_ps(
                2, base_task=9700, codec=codec)
            try:
                client.create_table(0, dim)
                paddle.seed(0)
                model = WideDeep(slots, dim)
                opt = paddle.optimizer.Adam(
                    learning_rate=1e-3, parameters=model.parameters())
                step = PsTrainStep(model, opt, wide_deep_loss, dim=dim,
                                   pad_rows=pad)
                pipe = PsPipeline(client, 0, step, depth=2, lr_sparse=0.1)
                stats = pipe.run(batches)
                pipe.close()
                return stats, client.pull_bytes + client.push_bytes
            finally:
                client.close()
                for s in services:
                    s.stop()
                bus.close()

        s32, b32 = run("fp32")
        s8, b8 = run("int8_block")
        assert b8 <= 0.31 * b32
        assert abs(s8["losses"][-1] - s32["losses"][-1]) < 0.05

    def test_pipeline_through_heter_cache(self):
        from paddle_tpu.distributed.ps.heter_cache import HeterCache

        batches = ctr_batches(6, BATCH, SLOTS, 200, alpha=1.2, seed=0)
        client, services, bus = make_sharded_ps(2, base_task=9800)
        try:
            client.create_table(0, DIM)
            cache = HeterCache(client, 0, DIM, capacity=128, lr=0.1,
                               fault_window_s=0.0)
            step = _model_step()
            pipe = PsPipeline(client, 0, step, depth=2, lr_sparse=0.1,
                              cache=cache)
            stats = pipe.run(batches)
            pipe.close()
            assert stats["losses"][-1] < stats["losses"][0]
            assert cache.hits > 0          # hot Zipf keys stayed resident
            assert cache.writeback_pushes + len(cache._wb_keys) == 0 or \
                cache.writeback_pushes >= 0  # flush() ran in finally
            # after flush, the PS holds every grad (no stranded dirty rows)
            assert not any(cache._dirty)
        finally:
            client.close()
            for s in services:
                s.stop()
            bus.close()

    def test_tracing_spans_name_each_stage(self):
        from paddle_tpu.framework.flags import _FLAGS
        from paddle_tpu.observability.tracing import get_tracer

        batches = ctr_batches(3, BATCH, SLOTS, 200, alpha=1.0, seed=0)
        client, services, bus = make_sharded_ps(2, base_task=9900)
        old = _FLAGS.get("FLAGS_serving_tracing", True)
        _FLAGS["FLAGS_serving_tracing"] = True
        try:
            client.create_table(0, DIM)
            step = _model_step()
            pipe = PsPipeline(client, 0, step, depth=2, lr_sparse=0.1,
                              name="ps_pass_test")
            pipe.run(batches)
            pipe.close()
            store = get_tracer().store
            docs = [store.get(t["trace_id"])
                    for t in store.index()["traces"]]
            doc = next(d for d in docs
                       if d and d["name"] == "ps_pass_test")
            names = {s["name"] for s in doc["spans"]}
            assert {"pull_launch", "pull_wait", "step",
                    "push_commit"} <= names
            # a span names its step and buffer -> a stall is attributable
            sp = next(s for s in doc["spans"] if s["name"] == "pull_wait")
            assert "step" in sp["fields"] and "buf" in sp["fields"]
        finally:
            _FLAGS["FLAGS_serving_tracing"] = old
            client.close()
            for s in services:
                s.stop()
            bus.close()


# ---------------------------------------------------------------------------
# flags / metrics / bench smoke
# ---------------------------------------------------------------------------

class TestKnobsAndSmoke:
    def test_ps_flags_declared(self):
        from paddle_tpu.framework.flags import flag

        assert flag("FLAGS_ps_pipeline_depth") == 2
        assert flag("FLAGS_ps_wire_codec") == "fp32"
        assert flag("FLAGS_ps_wire_block") == 1024
        assert flag("FLAGS_ps_shards") == 1
        assert flag("FLAGS_ps_pull_timeout_s") == 10.0
        assert flag("FLAGS_ps_pull_retries") == 2
        assert flag("FLAGS_ps_degraded_ok") is False

    def test_metric_families_one_label_schema(self):
        from paddle_tpu.observability.metrics import get_registry

        reg = get_registry()
        assert reg.counter("ps_pull_bytes_total",
                           labels=("codec",)).label_names == ("codec",)
        assert reg.counter("ps_push_bytes_total",
                           labels=("codec",)).label_names == ("codec",)
        assert reg.counter("ps_cache_hits_total",
                           labels=("table",)).label_names == ("table",)

    def test_cache_hit_counter_increments_per_table(self):
        from paddle_tpu.distributed.ps.heter_cache import HeterCache
        from paddle_tpu.observability.metrics import get_registry

        ps = LocalPs()
        ps.create_table(3, DIM)
        cache = HeterCache(ps, 3, DIM, capacity=8, fault_window_s=0.0)
        child = get_registry().counter(
            "ps_cache_hits_total", labels=("table",)).labels(table="3")
        before = child.get()
        cache.lookup([1, 2])      # misses
        cache.lookup([1, 2])      # hits
        assert child.get() == before + 2

    def test_quick_bench_writes_gated_fields(self, tmp_path):
        import tools.ps_bench as B

        t0 = time.monotonic()
        out = B.main(["--quick", "--out", str(tmp_path / "ps.json")])
        took = time.monotonic() - t0
        assert out["ps_examples_per_s"] > 0
        assert "ps_exposed_pull_ms" in out
        assert out["speedup_vs_eager"] > 1.0
        assert took < 60  # tier-3 full budget guard; quick target ~10s


class TestCostModel:
    def test_ps_pipeline_cost_wire_and_overlap_math(self):
        from paddle_tpu.cost_model import ps_pipeline_cost

        fp32 = ps_pipeline_cost(batch=256, uniq_keys=1500, dim=32,
                                step_s=6e-3, depth=2, codec="fp32")
        int8 = ps_pipeline_cost(batch=256, uniq_keys=1500, dim=32,
                                step_s=6e-3, depth=2, codec="int8_block")
        # quantized wire moves ~1/4 the bytes (+ scales + keys overhead)
        assert int8["wire_bytes_per_step"] < 0.35 * fp32["wire_bytes_per_step"]
        # at depth 2 the steady step is the max of legs, not the sum
        serial = ps_pipeline_cost(batch=256, uniq_keys=1500, dim=32,
                                  step_s=6e-3, depth=1, codec="fp32")
        assert serial["steady_step_s"] > fp32["steady_step_s"]
        assert fp32["examples_per_s"] > serial["examples_per_s"]
        # compute-bound at this geometry on a 1 GB/s wire model
        assert not fp32["wire_bound"]

"""Detection op suite (vision/detection.py).

Reference: paddle/fluid/operators/detection/ — box_coder, prior_box,
multiclass_nms, distribute_fpn_proposals, generate_proposals.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import (
    box_coder, box_iou, distribute_fpn_proposals, generate_proposals,
    multiclass_nms, prior_box,
)


def test_box_coder_encode_decode_roundtrip():
    priors = paddle.to_tensor(np.array(
        [[0., 0., 10., 10.], [5., 5., 20., 25.]], np.float32))
    targets = paddle.to_tensor(np.array(
        [[1., 1., 8., 9.], [6., 4., 22., 24.]], np.float32))
    var = [0.1, 0.1, 0.2, 0.2]
    enc = box_coder(priors, var, targets, code_type="encode_center_size")
    dec = box_coder(priors, var, enc, code_type="decode_center_size")
    np.testing.assert_allclose(dec.numpy(), targets.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_prior_box_shapes_and_range():
    feat = paddle.zeros([1, 256, 4, 4])
    img = paddle.zeros([1, 3, 64, 64])
    boxes, var = prior_box(feat, img, min_sizes=[16.0], max_sizes=[32.0],
                           aspect_ratios=[2.0], flip=True, clip=True)
    # P = 1(min) + 1(max) + 2(ar 2, 1/2) = 4
    assert tuple(boxes.shape) == (4, 4, 4, 4)
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 1).all()
    np.testing.assert_allclose(var.numpy()[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_box_iou_pairwise():
    a = paddle.to_tensor(np.array([[0., 0., 2., 2.]], np.float32))
    b = paddle.to_tensor(np.array([[1., 1., 3., 3.], [0., 0., 2., 2.]],
                                  np.float32))
    iou = box_iou(a, b).numpy()
    np.testing.assert_allclose(iou[0], [1.0 / 7.0, 1.0], rtol=1e-5)


def test_multiclass_nms_selects_per_class():
    boxes = np.array([[0, 0, 10, 10], [0, 0, 10.1, 10.1], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([
        [0.0, 0.0, 0.0],      # class 0 = background
        [0.9, 0.85, 0.1],     # class 1: first two overlap → keep best
        [0.0, 0.0, 0.8],      # class 2
    ], np.float32)
    out = multiclass_nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                         score_threshold=0.5, nms_threshold=0.5)
    o = out.numpy()
    assert o.shape == (2, 6)
    assert set(o[:, 0].astype(int)) == {1, 2}
    assert o[0, 1] >= o[1, 1]  # sorted by score


def test_distribute_fpn_proposals():
    rois = paddle.to_tensor(np.array([
        [0, 0, 16, 16],      # small → low level
        [0, 0, 500, 500],    # large → high level
    ], np.float32))
    multi, restore = distribute_fpn_proposals(rois, 2, 5, 4, 224)
    assert len(multi) == 4
    sizes = [m.shape[0] for m in multi]
    assert sum(sizes) == 2
    assert multi[0].shape[0] == 1  # small roi landed on level 2
    r = restore.numpy().reshape(-1)
    assert sorted(r.tolist()) == [0, 1]


def test_generate_proposals_runs():
    rs = np.random.RandomState(0)
    n = 16
    anchors = np.stack([np.zeros(n), np.zeros(n),
                        np.full(n, 16.0), np.full(n, 16.0)], -1)
    anchors += rs.rand(n, 4) * 4
    rois, scores = generate_proposals(
        paddle.to_tensor(rs.rand(n).astype("f4")),
        paddle.to_tensor((rs.randn(n, 4) * 0.1).astype("f4")),
        paddle.to_tensor(np.array([64.0, 64.0], np.float32)),
        paddle.to_tensor(anchors.astype("f4")),
        paddle.to_tensor(np.full((n, 4), 1.0, np.float32)),
        post_nms_top_n=5, min_size=1.0)
    assert rois.shape[0] <= 5 and rois.shape[1] == 4
    assert (np.diff(scores.numpy()) <= 1e-6).all()  # sorted desc

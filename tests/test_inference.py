"""Inference subsystem: save → fresh-process load → identical outputs.

Reference capability: AnalysisPredictor (inference/api/analysis_predictor.h:
load model → optimize → zero-copy run) and static save/load_inference_model
(python/paddle/static/io.py). The fresh-process test is the deployment
contract: nothing from the training process may be needed to serve.
"""
import os
import subprocess
import sys
import textwrap

import pytest

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.static as static

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_and_train(tmp):
    """Tiny static-mode MLP trained a few steps; returns feeds/logits/prefix."""
    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", shape=(4, 8), dtype="float32")
            lbl = static.data("lbl", shape=(4, 1), dtype="int64")
            h = static.nn.fc(x, size=16, activation="relu")
            logits = static.nn.fc(h, size=3)
            loss = paddle.nn.functional.cross_entropy(
                logits, lbl, reduction="mean")
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)

        exe = static.Executor()
        exe.run(startup)
        rs = np.random.RandomState(0)
        xs = rs.randn(4, 8).astype("float32")
        ys = rs.randint(0, 3, (4, 1)).astype("int64")
        for _ in range(3):
            exe.run(main, feed={"x": xs, "lbl": ys}, fetch_list=[loss])

        infer_prog = main.clone(for_test=True)
        prefix = os.path.join(tmp, "mlp")
        static.save_inference_model(prefix, [x], [logits],
                                    executor=exe, program=infer_prog)
        expect = exe.run(infer_prog, feed={"x": xs, "lbl": ys},
                         fetch_list=[logits])[0]
        return xs, np.asarray(expect), prefix
    finally:
        paddle.disable_static()


@pytest.mark.requires_jax_export
def test_save_load_inference_model_same_process(tmp_path):
    xs, expect, prefix = _build_and_train(str(tmp_path))
    paddle.enable_static()
    try:
        exe = static.Executor()
        prog, feed_names, fetch_targets = static.load_inference_model(
            prefix, exe)
        assert feed_names == ["x"]
        out = exe.run(prog, feed={"x": xs}, fetch_list=fetch_targets)[0]
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
    finally:
        paddle.disable_static()


@pytest.mark.requires_jax_export
def test_predictor_zero_copy_api(tmp_path):
    xs, expect, prefix = _build_and_train(str(tmp_path))
    from paddle_tpu import inference

    cfg = inference.Config(prefix + ".pdmodel", prefix + ".pdiparams")
    pred = inference.create_predictor(cfg)
    assert pred.get_input_names() == ["x"]
    h = pred.get_input_handle("x")
    h.copy_from_cpu(xs)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
    # convenience run(list) form + clone sharing weights
    out2 = pred.clone().run([xs])[0]
    np.testing.assert_allclose(out2, expect, rtol=1e-5, atol=1e-6)


@pytest.mark.requires_jax_export
def test_fresh_process_load_identical_logits(tmp_path):
    """THE deployment contract: train → save → load in a NEW process →
    bit-identical logits."""
    xs, expect, prefix = _build_and_train(str(tmp_path))
    np.save(tmp_path / "xs.npy", xs)
    np.save(tmp_path / "expect.npy", expect)
    script = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")  # immune to ambient tunnel
        sys.path.insert(0, {REPO!r})
        import numpy as np
        from paddle_tpu import inference
        xs = np.load({str(tmp_path / 'xs.npy')!r})
        expect = np.load({str(tmp_path / 'expect.npy')!r})
        cfg = inference.Config({prefix + '.pdmodel'!r})
        pred = inference.create_predictor(cfg)
        out = pred.run([xs])[0]
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
        print("FRESH_PROCESS_OK")
    """)
    r = subprocess.run([sys.executable, "-c", script], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FRESH_PROCESS_OK" in r.stdout


@pytest.mark.requires_jax_export
def test_jit_save_produces_servable_artifact(tmp_path):
    """Dygraph flow: jit.save(layer, input_spec=...) → create_predictor."""
    import paddle_tpu.nn as nn

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)

        def forward(self, x):
            return paddle.nn.functional.relu(self.fc(x))

    net = Net()
    net.eval()
    xs = np.random.RandomState(1).randn(2, 8).astype("float32")
    expect = net(paddle.to_tensor(xs)).numpy()

    prefix = str(tmp_path / "net")
    paddle.jit.save(net, prefix,
                    input_spec=[static.InputSpec([2, 8], "float32", "x")])
    from paddle_tpu import inference

    pred = inference.create_predictor(inference.Config(prefix + ".pdmodel"))
    out = pred.run([xs])[0]
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


@pytest.mark.requires_jax_export
def test_export_multi_feed_shared_batch_dim(tmp_path):
    """Two dynamic-batch feeds combined in one op must export: all leading
    -1 dims share ONE symbolic 'batch' (independent symbols would make
    ids + mask style models inconclusive at trace time)."""
    from paddle_tpu.inference.io import (
        InferenceArtifact, export_inference_artifact,
    )

    w = np.random.RandomState(0).randn(8, 4).astype("float32")

    def fn(ws, fs):
        x, mask = fs
        return [(x * mask) @ ws[0]]

    prefix = str(tmp_path / "mf")
    export_inference_artifact(
        fn, [w],
        [("x", [-1, 8], "float32"), ("mask", [-1, 8], "float32")],
        prefix)
    art = InferenceArtifact.load(prefix)
    for b in (2, 5):
        rs = np.random.RandomState(b)
        x = rs.randn(b, 8).astype("float32")
        m = (rs.rand(b, 8) > 0.5).astype("float32")
        (out,) = art.run([x, m])
        np.testing.assert_allclose(np.asarray(out), (x * m) @ w,
                                   rtol=1e-5, atol=1e-6)


class _JitArtifact:
    """Minimal real-jit artifact for Predictor-surface tests that must
    not depend on the StableHLO export path (jax.export is absent in
    some CI environments; the full save->load contract is covered by the
    tests above when it exists). The compute is a genuinely compiled XLA
    executable, so clone-concurrency exercises the real thread path."""

    def __init__(self, w):
        import jax
        import jax.numpy as jnp

        self.feed_names = ["x"]
        self.feed_specs = {"x": ([2, 8], "float32")}
        self.n_fetches = 1
        self._w = jnp.asarray(w)
        self._fn = jax.jit(lambda wv, x: [jnp.maximum(x @ wv, 0.0)])

    def run(self, feed_vals):
        return self._fn(self._w, feed_vals[0])


def _stub_predictor(monkeypatch, w):
    from paddle_tpu import inference

    art = _JitArtifact(w)
    monkeypatch.setattr(inference, "_load_artifact",
                        lambda *a, **k: art)
    return inference.create_predictor(inference.Config("stub.pdmodel"))


def test_run_inputs_does_not_leak_into_handle_runs(monkeypatch):
    """ISSUE 14 satellite bugfix: values staged by run(inputs=...) are
    transient to that call. A later handle-style run() that forgot to
    re-stage must raise, not silently reuse the convenience call's
    arrays (the old behavior served stale inputs)."""
    import pytest

    rs = np.random.RandomState(0)
    w = rs.randn(8, 4).astype("float32")
    xs = rs.randn(2, 8).astype("float32")
    expect = np.maximum(xs @ w, 0.0)
    pred = _stub_predictor(monkeypatch, w)
    out = pred.run([xs])[0]
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
    # the bug: this used to reuse xs from the run(inputs=...) above
    with pytest.raises(RuntimeError, match="was not set"):
        pred.run()
    # handle staging still works per call, and a convenience run in
    # between clears it again
    h = pred.get_input_handle("x")
    h.copy_from_cpu(xs)
    pred.run()
    out2 = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out2, expect, rtol=1e-5, atol=1e-6)
    pred.run([xs])
    with pytest.raises(RuntimeError, match="was not set"):
        pred.run()


def test_clone_concurrent_runs_share_artifact_without_interference(
        monkeypatch):
    """ISSUE 14 satellite: the serving replica pool depends on
    Predictor.clone() zero-copy weight sharing being safe under
    concurrent run() from separate threads — each clone has its own
    handles, so simultaneous runs must not cross inputs/outputs."""
    import threading

    rs = np.random.RandomState(7)
    w = rs.randn(8, 4).astype("float32")
    xs = rs.randn(2, 8).astype("float32")
    base = _stub_predictor(monkeypatch, w)
    clones = [base.clone() for _ in range(2)]
    assert all(c._artifact is base._artifact for c in clones)
    feeds = [xs, rs.randn(*xs.shape).astype("float32")]
    expects = [np.asarray(base.run([f])[0]) for f in feeds]
    n_iters, errors, outs = 30, [], [[], []]
    barrier = threading.Barrier(2)

    def worker(i):
        try:
            barrier.wait(timeout=30)
            for _ in range(n_iters):
                outs[i].append(np.asarray(clones[i].run([feeds[i]])[0]))
        except Exception as e:  # surfaced below; a thread must not die silently
            errors.append((i, repr(e)))

    ts = [threading.Thread(target=worker, args=(i,), daemon=True)
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors, errors
    for i in range(2):
        assert len(outs[i]) == n_iters
        for o in outs[i]:
            np.testing.assert_allclose(o, expects[i], rtol=1e-5, atol=1e-6)

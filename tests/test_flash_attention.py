"""Pallas flash attention: kernel numerics vs the einsum reference path.

Runs on the CPU interpret mode (conftest forces the 8-device CPU platform);
the same kernel compiles for TPU via Mosaic. Reference capability:
operators/fused/fused_attention_op.cu (fused CUDA attention fwd+bwd).
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.flash_attention import (
    flash_attention_supported, flash_attention_val,
)


def ref_attn(q, k, v, causal=True):
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _rand(b, s, n, d, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(b, s, n, d), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _rand(2, 128, 4, 64)
    out = flash_attention_val(q, k, v, causal=causal, block_size=64)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref_attn(q, k, v, causal)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = _rand(2, 64, 2, 32, seed=1)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(
            flash_attention_val(q, k, v, causal=causal, block_size=32)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.sin(ref_attn(q, k, v, causal)))

    g1 = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_uneven_q_k_blocks():
    # block_q != block divisor of s exercises the diagonal masking path
    q, k, v = _rand(1, 96, 2, 32, seed=2)
    out = flash_attention_val(q, k, v, causal=True, block_size=32)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref_attn(q, k, v, True)),
                               rtol=1e-5, atol=1e-5)


def test_supported_shapes():
    assert flash_attention_supported((2, 128, 4, 64))
    assert flash_attention_supported((2, 96, 4, 64))   # 32-divisible
    assert not flash_attention_supported((2, 7, 4, 64))
    assert not flash_attention_supported((2, 128, 64))  # wrong rank


def test_public_api():
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rs = np.random.RandomState(3)
    q = paddle.to_tensor(rs.randn(2, 64, 2, 32).astype("float32"))
    q.stop_gradient = False
    out, sm = F.flash_attention(q, q, q, causal=True)
    assert sm is None
    assert tuple(out.shape) == (2, 64, 2, 32)
    out.sum().backward()
    assert q.grad is not None


def test_jit_under_mesh():
    # flash path with a mesh active must stay SPMD via shard_map
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.models.gpt import _flash_sharded

    mesh = mesh_mod.build_mesh({"data": 2, "model": 2},
                               devices=jax.devices()[:4])
    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh)
    try:
        q, k, v = _rand(2, 64, 4, 32, seed=4)
        out = jax.jit(_flash_sharded)(q, k, v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref_attn(q, k, v, True)),
                                   rtol=1e-5, atol=1e-5)
    finally:
        mesh_mod.set_mesh(prev)

"""Inference analysis passes (reference: inference/analysis ir_passes —
conv+bn fold, constant folding, identity elim, DCE) over imported
program IR. Numerics must be bit-preserving; op counts must shrink."""
import numpy as np
import pytest

from paddle_tpu.inference.passes import (
    constant_folding, dead_code_elimination, fold_conv_bn,
    identity_elimination, run_inference_passes,
)
from paddle_tpu.interop import load_paddle_inference_model

from test_interop_importer import (  # the artifact-authoring helpers
    A_FLOAT, A_INT, A_INTS, A_STRING, FEED_MINIBATCH, FETCH_LIST, attr,
    block_desc, lod_tensor_stream, op_desc, program_desc, var_desc,
)


def _write(tmp_path, vars_, ops, params_sorted):
    (tmp_path / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    with open(tmp_path / "__params__", "wb") as f:
        for arr in params_sorted:
            f.write(lod_tensor_stream(arr))


def test_identity_and_dce_and_fold(tmp_path):
    """x -> scale(1,0) -> mul(w) -> dropout -> fetch, plus a dead branch
    and a param-only foldable add."""
    rs = np.random.RandomState(0)
    w = rs.randn(4, 4).astype(np.float32)
    c1 = rs.randn(4, 4).astype(np.float32)
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 4)),
        var_desc("c1", dims=(4, 4), persistable=True),
        var_desc("w", dims=(4, 4), persistable=True),
        var_desc("xs", dims=(-1, 4)), var_desc("h", dims=(-1, 4)),
        var_desc("hd", dims=(-1, 4)), var_desc("w2", dims=(4, 4)),
        var_desc("dead", dims=(-1, 4)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("scale", [("X", ["x"])], [("Out", ["xs"])],
                [attr("scale", A_FLOAT, 1.0), attr("bias", A_FLOAT, 0.0)]),
        # param-only math: folds to a new constant at load
        op_desc("elementwise_add", [("X", ["w"]), ("Y", ["c1"])],
                [("Out", ["w2"])], [attr("axis", A_INT, -1)]),
        op_desc("mul", [("X", ["xs"]), ("Y", ["w2"])], [("Out", ["h"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        # dead: output never reaches a fetch
        op_desc("relu", [("X", ["h"])], [("Out", ["dead"])]),
        op_desc("dropout", [("X", ["h"])], [("Out", ["hd"])],
                [attr("dropout_prob", A_FLOAT, 0.5),
                 attr("dropout_implementation", A_STRING,
                      "upscale_in_train")]),
        op_desc("fetch", [("X", ["hd"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [c1, w])  # sorted: c1, w

    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    (before,) = prog.run({"x": x})
    n_before = len(prog.blocks[0].ops)

    report = run_inference_passes(prog)
    (after,) = prog.run({"x": x})

    np.testing.assert_allclose(after, x @ (w + c1), rtol=1e-6)
    np.testing.assert_allclose(after, before, rtol=1e-6)
    assert report["identity_elimination"] == 2  # scale(1,0) + dropout
    assert report["dead_code_elimination"] >= 1  # the dangling relu
    assert report["constant_folding"] == 1      # w + c1
    types = [op.type for op in prog.blocks[0].ops]
    assert types == ["feed", "mul", "fetch"], types
    assert len(prog.blocks[0].ops) < n_before


def test_conv_bn_fold_preserves_numerics(tmp_path):
    rs = np.random.RandomState(2)
    k = rs.randn(6, 3, 3, 3).astype(np.float32)
    s = (rs.rand(6).astype(np.float32) + 0.5)
    b = rs.randn(6).astype(np.float32)
    m = rs.randn(6).astype(np.float32) * 0.1
    v = rs.rand(6).astype(np.float32) + 0.5
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=(-1, 3, 8, 8)),
        var_desc("k", dims=(6, 3, 3, 3), persistable=True),
        var_desc("bn_s", dims=(6,), persistable=True),
        var_desc("bn_b", dims=(6,), persistable=True),
        var_desc("bn_m", dims=(6,), persistable=True),
        var_desc("bn_v", dims=(6,), persistable=True),
        var_desc("c0", dims=(-1, 6, 8, 8)), var_desc("c1", dims=(-1, 6, 8, 8)),
        var_desc("out", dims=(-1, 6, 8, 8)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc("conv2d", [("Input", ["img"]), ("Filter", ["k"])],
                [("Output", ["c0"])],
                [attr("strides", A_INTS, [1, 1]),
                 attr("paddings", A_INTS, [1, 1]),
                 attr("dilations", A_INTS, [1, 1]),
                 attr("groups", A_INT, 1)]),
        op_desc("batch_norm",
                [("X", ["c0"]), ("Scale", ["bn_s"]), ("Bias", ["bn_b"]),
                 ("Mean", ["bn_m"]), ("Variance", ["bn_v"])],
                [("Y", ["c1"])], [attr("epsilon", A_FLOAT, 1e-5)]),
        op_desc("relu", [("X", ["c1"])], [("Out", ["out"])]),
        op_desc("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [b, m, s, v, k])  # sorted names

    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    (before,) = prog.run({"img": x})
    assert fold_conv_bn(prog) == 1
    (after,) = prog.run({"img": x})
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)
    types = [op.type for op in prog.blocks[0].ops]
    assert "batch_norm" not in types
    assert types.count("elementwise_add") == 1


def test_predictor_applies_passes_when_ir_optim(tmp_path):
    from paddle_tpu.inference import Config, create_predictor

    rs = np.random.RandomState(3)
    w = rs.randn(4, 4).astype(np.float32)
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 4)),
        var_desc("w", dims=(4, 4), persistable=True),
        var_desc("h", dims=(-1, 4)), var_desc("hd", dims=(-1, 4)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("mul", [("X", ["x"]), ("Y", ["w"])], [("Out", ["h"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        op_desc("dropout", [("X", ["h"])], [("Out", ["hd"])],
                [attr("dropout_prob", A_FLOAT, 0.5),
                 attr("dropout_implementation", A_STRING,
                      "upscale_in_train")]),
        op_desc("fetch", [("X", ["hd"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [w])
    pred = create_predictor(Config(str(tmp_path)))  # ir_optim default on
    x = rs.randn(2, 4).astype(np.float32)
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, x @ w, rtol=1e-6)


def test_param_pruning_after_bn_fold(tmp_path):
    """Folded-away BN stats must not survive as dead device uploads."""
    rs = np.random.RandomState(4)
    k = rs.randn(4, 3, 3, 3).astype(np.float32)
    s = rs.rand(4).astype(np.float32) + 0.5
    b = rs.randn(4).astype(np.float32)
    m = rs.randn(4).astype(np.float32) * 0.1
    v = rs.rand(4).astype(np.float32) + 0.5
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=(-1, 3, 8, 8)),
        var_desc("k", dims=(4, 3, 3, 3), persistable=True),
        var_desc("bn_s", dims=(4,), persistable=True),
        var_desc("bn_b", dims=(4,), persistable=True),
        var_desc("bn_m", dims=(4,), persistable=True),
        var_desc("bn_v", dims=(4,), persistable=True),
        var_desc("c0", dims=(-1, 4, 8, 8)), var_desc("c1", dims=(-1, 4, 8, 8)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc("conv2d", [("Input", ["img"]), ("Filter", ["k"])],
                [("Output", ["c0"])],
                [attr("strides", A_INTS, [1, 1]),
                 attr("paddings", A_INTS, [1, 1]),
                 attr("dilations", A_INTS, [1, 1]),
                 attr("groups", A_INT, 1)]),
        op_desc("batch_norm",
                [("X", ["c0"]), ("Scale", ["bn_s"]), ("Bias", ["bn_b"]),
                 ("Mean", ["bn_m"]), ("Variance", ["bn_v"])],
                [("Y", ["c1"])], [attr("epsilon", A_FLOAT, 1e-5)]),
        op_desc("fetch", [("X", ["c1"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [b, m, s, v, k])
    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    report = run_inference_passes(prog)
    assert report["fold_conv_bn"] == 1
    assert report["prune_params"] >= 4  # bn_s/bn_b/bn_m/bn_v gone
    assert not any(n.startswith("bn_") for n in prog.params)


def test_dropout_downgrade_in_infer_scales(tmp_path):
    """ADVICE r3 (high): the fluid-era default dropout_implementation
    'downgrade_in_infer' means inference output = x * (1 - p) — dropout
    with default attrs is NOT an identity. Both the eager importer and
    identity_elimination (which must rewrite to scale(1-p), matching the
    reference's delete_dropout_op_pass) honor it."""
    rs = np.random.RandomState(6)
    w = rs.randn(4, 4).astype(np.float32)
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 4)),
        var_desc("w", dims=(4, 4), persistable=True),
        var_desc("h", dims=(-1, 4)), var_desc("hd", dims=(-1, 4)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("mul", [("X", ["x"]), ("Y", ["w"])], [("Out", ["h"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        # no dropout_implementation attr: proto/fluid default
        # 'downgrade_in_infer' applies -> out = h * (1 - 0.25)
        op_desc("dropout", [("X", ["h"])], [("Out", ["hd"])],
                [attr("dropout_prob", A_FLOAT, 0.25)]),
        op_desc("fetch", [("X", ["hd"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [w])
    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = rs.randn(3, 4).astype(np.float32)
    (before,) = prog.run({"x": x})
    np.testing.assert_allclose(before, (x @ w) * 0.75, rtol=1e-6)

    report = run_inference_passes(prog)
    (after,) = prog.run({"x": x})
    np.testing.assert_allclose(after, before, rtol=1e-6)
    # the dropout became a scale op (not aliased away)
    types = [op.type for op in prog.blocks[0].ops]
    assert "dropout" not in types and "scale" in types, types


def test_conv_bn_fold_shared_filter_safe(tmp_path):
    """ADVICE r3 (low): two convs share one Filter param; folding a BN
    behind conv1 must not corrupt conv2's weights (folded weights go
    under a fresh name, only conv1 is repointed)."""
    rs = np.random.RandomState(7)
    k = rs.randn(4, 3, 3, 3).astype(np.float32)
    s = rs.rand(4).astype(np.float32) + 0.5
    b = rs.randn(4).astype(np.float32)
    m = rs.randn(4).astype(np.float32) * 0.1
    v = rs.rand(4).astype(np.float32) + 0.5
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("img", dims=(-1, 3, 8, 8)),
        var_desc("k", dims=(4, 3, 3, 3), persistable=True),
        var_desc("bn_s", dims=(4,), persistable=True),
        var_desc("bn_b", dims=(4,), persistable=True),
        var_desc("bn_m", dims=(4,), persistable=True),
        var_desc("bn_v", dims=(4,), persistable=True),
        var_desc("c0", dims=(-1, 4, 8, 8)), var_desc("c1", dims=(-1, 4, 8, 8)),
        var_desc("c2", dims=(-1, 4, 8, 8)), var_desc("out", dims=(-1, 4, 8, 8)),
    ]
    conv_attrs = [attr("strides", A_INTS, [1, 1]),
                  attr("paddings", A_INTS, [1, 1]),
                  attr("dilations", A_INTS, [1, 1]),
                  attr("groups", A_INT, 1)]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["img"])],
                [attr("col", A_INT, 0)]),
        op_desc("conv2d", [("Input", ["img"]), ("Filter", ["k"])],
                [("Output", ["c0"])], conv_attrs),
        op_desc("batch_norm",
                [("X", ["c0"]), ("Scale", ["bn_s"]), ("Bias", ["bn_b"]),
                 ("Mean", ["bn_m"]), ("Variance", ["bn_v"])],
                [("Y", ["c1"])], [attr("epsilon", A_FLOAT, 1e-5)]),
        # second conv REUSES the same filter k, no BN behind it
        op_desc("conv2d", [("Input", ["img"]), ("Filter", ["k"])],
                [("Output", ["c2"])], conv_attrs),
        op_desc("elementwise_add", [("X", ["c1"]), ("Y", ["c2"])],
                [("Out", ["out"])], [attr("axis", A_INT, -1)]),
        op_desc("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [b, m, s, v, k])
    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    (before,) = prog.run({"img": x})
    assert fold_conv_bn(prog) == 1
    (after,) = prog.run({"img": x})
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)
    # the shared original must be intact and still consumed by conv2
    np.testing.assert_array_equal(prog.params["k"], k)


def test_alias_invalidated_on_redefinition(tmp_path):
    """Non-SSA program: assign aliases a->w, then mul REDEFINES a; the
    final fetch of a must read the mul output, not the stale alias."""
    rs = np.random.RandomState(5)
    w = rs.randn(4, 4).astype(np.float32)
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 4)),
        var_desc("w", dims=(4, 4), persistable=True),
        var_desc("a", dims=(-1, 4)),
    ]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("assign", [("X", ["x"])], [("Out", ["a"])]),
        op_desc("mul", [("X", ["a"]), ("Y", ["w"])], [("Out", ["a"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        op_desc("fetch", [("X", ["a"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    _write(tmp_path, vars_, ops, [w])
    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x = rs.randn(2, 4).astype(np.float32)
    (before,) = prog.run({"x": x})
    run_inference_passes(prog)
    (after,) = prog.run({"x": x})
    np.testing.assert_allclose(after, x @ w, rtol=1e-6)
    np.testing.assert_allclose(after, before, rtol=1e-6)

"""Test configuration: run the suite on an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed logic with local
processes + gloo (SURVEY.md §4): here a single process with 8 XLA host devices
stands in for an 8-chip TPU slice. bench.py / production use the real chip.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
# fp32 matmuls on CPU for tight numeric comparisons against NumPy
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture
def fresh_mesh():
    """Run the test with NO ambient mesh; restore the prior mesh after.
    Shared by the mesh-touching test files (request via an autouse
    wrapper) so the save/restore logic exists once."""
    from paddle_tpu.distributed import mesh as mesh_mod

    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(None)
    yield
    mesh_mod.set_mesh(prev)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Surface skipped AOT regression gates at suite end (VERDICT r4 #9:
    libtpu-lock contention must not silently disable test_tpu_aot)."""
    aot = [r for r in terminalreporter.stats.get("skipped", [])
           if "test_tpu_aot" in str(getattr(r, "nodeid", ""))]
    if aot:
        terminalreporter.write_sep(
            "-", f"WARNING: {len(aot)} TPU AOT gate(s) SKIPPED "
                 "(compiler unavailable after retries)")
        for r in aot:
            terminalreporter.write_line(f"  skipped: {r.nodeid}")

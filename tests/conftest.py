"""Test configuration: run the suite on an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing distributed logic with local
processes + gloo (SURVEY.md §4): here a single process with 8 XLA host devices
stands in for an 8-chip TPU slice. bench.py / production use the real chip.
"""
import os
import threading

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# -- lock-order sanitizer (analysis/lock_order.py, ISSUE 7) -----------------
# Installed BEFORE anything imports paddle_tpu so module-level framework
# locks are created through the patched constructors and get witnessed.
# The module is loaded by file path (pure stdlib, no jax) and pre-registered
# under its canonical name so later `import paddle_tpu.analysis.lock_order`
# yields this same instance (and this same edge graph).
_LOCK_ORDER = None
if os.environ.get("FLAGS_lock_order_check", "").lower() in ("1", "true", "yes"):
    import importlib.util
    import sys as _sys

    _lo_path = os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "paddle_tpu", "analysis",
        "lock_order.py"))
    _spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis.lock_order", _lo_path)
    _LOCK_ORDER = importlib.util.module_from_spec(_spec)
    _sys.modules["paddle_tpu.analysis.lock_order"] = _LOCK_ORDER
    _spec.loader.exec_module(_LOCK_ORDER)
    _LOCK_ORDER.install()

# thread names alive before any test ran — the leak check's baseline
_THREADS_AT_START = {t.name for t in threading.enumerate()}

import jax

jax.config.update("jax_platforms", "cpu")
# fp32 matmuls on CPU for tight numeric comparisons against NumPy
jax.config.update("jax_default_matmul_precision", "highest")

# -- host-sync sanitizer (analysis/host_sync.py, ISSUE 11) ------------------
# Patches the device→host sync points (np.asarray on jax arrays,
# jax.block_until_ready, jax.device_get) to record blocking syncs that
# happen inside train-step spans. Needs jax importable, so it installs
# AFTER the jax import (unlike the lock witness, nothing module-level
# needs catching — the patch points are module attributes).
_HOST_SYNC = None
if os.environ.get("FLAGS_host_sync_check", "").lower() in ("1", "true", "yes"):
    from paddle_tpu.analysis import host_sync as _HOST_SYNC

    _HOST_SYNC.install()

import pytest  # noqa: E402

# ISSUE 19 re-audit of the ISSUE 16 skip set. The old gate was
# `hasattr(jax, "export")` — a FALSE NEGATIVE on every jax where export
# is a lazy submodule (the attribute only exists after `from jax import
# export` runs), which silently skipped 19 tests this environment can
# actually run. The capability is now probed by actually importing the
# submodule (jit/artifact_cache.export_supported()), and the two
# capabilities the old marker lumped in with export get their own
# markers + live probes:
#   requires_vma_shard_map    — jax >= 0.6 vma-typed shard_map
#   requires_cpu_multiprocess — multi-process jax.distributed over CPU
from paddle_tpu.jit.artifact_cache import export_supported  # noqa: E402

_HAS_JAX_EXPORT = export_supported()
# vma-typed shard_map (varying manual axes) landed with the jax 0.6 line
_HAS_VMA_SHARD_MAP = tuple(
    int(x) for x in jax.__version__.split(".")[:2]) >= (0, 6)
# single-container CI: no second process to join a coordination service
_HAS_CPU_MULTIPROCESS = os.environ.get(
    "PADDLE_TPU_MULTIPROC", "").lower() in ("1", "true", "yes")


def pytest_collection_modifyitems(config, items):
    gates = (
        ("requires_jax_export", _HAS_JAX_EXPORT,
         "artifact_cache.export_supported() is False: this jax cannot "
         "serialize compiled programs (degraded in-process warm path "
         "only); pre-existing capability gap, not a regression"),
        ("requires_vma_shard_map", _HAS_VMA_SHARD_MAP,
         "environment jax predates vma-typed shard_map (jax >= 0.6); "
         "pre-existing capability gap, not a regression"),
        ("requires_cpu_multiprocess", _HAS_CPU_MULTIPROCESS,
         "multi-process jax.distributed unavailable here (set "
         "PADDLE_TPU_MULTIPROC=1 on a host that can bind a coordination "
         "service); pre-existing capability gap, not a regression"),
    )
    for marker, have, reason in gates:
        if have:
            continue
        skip = pytest.mark.skip(reason=reason)
        for item in items:
            if marker in item.keywords:
                item.add_marker(skip)


@pytest.fixture
def fresh_mesh():
    """Run the test with NO ambient mesh; restore the prior mesh after.
    Shared by the mesh-touching test files (request via an autouse
    wrapper) so the save/restore logic exists once."""
    from paddle_tpu.distributed import mesh as mesh_mod

    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(None)
    yield
    mesh_mod.set_mesh(prev)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Surface skipped AOT regression gates at suite end (VERDICT r4 #9:
    libtpu-lock contention must not silently disable test_tpu_aot)."""
    aot = [r for r in terminalreporter.stats.get("skipped", [])
           if "test_tpu_aot" in str(getattr(r, "nodeid", ""))]
    if aot:
        terminalreporter.write_sep(
            "-", f"WARNING: {len(aot)} TPU AOT gate(s) SKIPPED "
                 "(compiler unavailable after retries)")
        for r in aot:
            terminalreporter.write_line(f"  skipped: {r.nodeid}")

    # -- post-suite sanitizers (ISSUE 7) ------------------------------------
    # thread-leak check: non-daemon threads outliving the suite hang the
    # interpreter at exit; framework threads declare daemon=True (rule C001)
    # precisely so this stays empty.
    try:
        from paddle_tpu.analysis import lock_order as _lo
    except Exception:
        _lo = _LOCK_ORDER
    if _lo is not None:
        leaks = _lo.thread_leak_report(_THREADS_AT_START)
        if leaks:
            terminalreporter.write_sep(
                "-", f"WARNING: {len(leaks)} non-daemon thread(s) leaked "
                     "past the suite")
            for leak in leaks:
                terminalreporter.write_line(f"  leaked: {leak['name']}")

    # host-sync sanitizer report (only when FLAGS_host_sync_check ran)
    if _HOST_SYNC is not None:
        hs = _HOST_SYNC.report()
        if hs["in_step_syncs"]:
            terminalreporter.write_sep(
                "-", f"WARNING: host-sync sanitizer recorded "
                     f"{hs['in_step_syncs']} blocking sync(s) inside "
                     "train-step spans")
            for site in hs["sites"]:
                terminalreporter.write_line(f"  in-step sync: {site}")
        else:
            terminalreporter.write_line(
                f"host-sync sanitizer: 0 blocking syncs inside "
                f"{hs['step_spans']} train-step span(s)")

        # un-awaited-future report (ISSUE 12): CollectiveLane clients'
        # created-vs-awaited future counts — the runtime companion of
        # static rule F002
        fw = _HOST_SYNC.future_report()
        per_class = ", ".join(
            f"{name}: {c['created']} created / {c['awaited']} awaited / "
            f"{c['resolved']} resolved"
            for name, c in fw["classes"].items()) or "no futures created"
        if fw["unawaited"]:
            terminalreporter.write_sep(
                "-", f"WARNING: future watch: {fw['unawaited']} lane "
                     "future(s) created but never awaited")
        terminalreporter.write_line(f"future watch: {per_class}")

    # lock-order witness report (only when FLAGS_lock_order_check ran)
    if _LOCK_ORDER is not None:
        rep = _LOCK_ORDER.get_graph().report()
        if rep["cycles"]:
            terminalreporter.write_sep(
                "-", f"WARNING: lock-order sanitizer found "
                     f"{len(rep['cycles'])} potential-deadlock cycle(s)")
            for c in rep["cycles"]:
                terminalreporter.write_line(
                    "  cycle: " + " -> ".join(c["nodes"] + [c["nodes"][0]]))
        else:
            terminalreporter.write_line(
                f"lock-order sanitizer: {_LOCK_ORDER.witness_count()} "
                f"witnessed lock(s), {rep['edge_count']} ordering edge(s) "
                f"across {len(rep['locks'])} lock(s), 0 cycles")

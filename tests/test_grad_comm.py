"""Bucketed + quantized gradient communication (distributed/grad_comm.py).

Covers ISSUE 1's contract: bit-exact parity of bucketed-bf16 vs the seed's
per-param sync on a 2-rank mesh, the int8 codec round-trip bound, the
error-feedback convergence smoke, deterministic bucket assignment, and the
in-suite regression guard that bucketing keeps the collective count
O(buckets) instead of O(#params) (style: tests/test_eager_dispatch.py).
"""
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.collective as coll
import paddle_tpu.distributed.env as env_mod
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed import fleet, grad_comm
from paddle_tpu.framework.tensor import Tensor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
rng = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def _fake_params(shapes, dtype=np.float32, grads=None):
    """Param-like Tensors with .grad set (what sync() consumes)."""
    params = []
    for i, s in enumerate(shapes):
        p = Tensor(np.zeros(s, dtype))
        p.stop_gradient = False
        p.name = f"p{i}"
        p.grad = Tensor(np.asarray(grads[i], dtype) if grads is not None
                        else rng.standard_normal(s).astype(dtype))
        params.append(p)
    return params


# ------------------------------------------------------------- bucketing
def test_bucket_assignment_is_deterministic_across_ranks():
    """Two independently-built (identical) models — the SPMD rank view —
    must agree on every bucket: same params, offsets, dtypes, sizes."""
    def build():
        paddle.seed(3)
        return nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                             nn.Linear(128, 32), nn.Linear(32, 8))

    b1 = grad_comm.build_buckets(list(build().parameters()),
                                 comm_buffer_size=0.02,
                                 last_comm_buffer_size=0.01)
    b2 = grad_comm.build_buckets(list(build().parameters()),
                                 comm_buffer_size=0.02,
                                 last_comm_buffer_size=0.01)
    assert [b.signature() for b in b1] == [b.signature() for b in b2]
    assert len(b1) > 1  # the small cap actually splits this model
    # every param appears exactly once
    seen = sorted(i for b in b1 for i in b.param_indices)
    assert seen == list(range(6))  # 3 Linear layers x (weight, bias)


def test_buckets_are_dtype_homogeneous_and_capped():
    params = _fake_params([(256, 256), (256,), (128, 128)])
    # mixed dtypes: one param's grad in bf16
    params[1].grad._value = params[1].grad._value.astype(jnp.bfloat16)
    dtypes = [np.dtype(p.grad._value.dtype) for p in params]
    buckets = grad_comm.build_buckets(params, comm_buffer_size=0.1,
                                      last_comm_buffer_size=0.1,
                                      dtypes=dtypes)
    for b in buckets:
        itemsizes = {np.dtype(dtypes[i]).itemsize for i in b.param_indices}
        assert len(itemsizes) == 1
        assert b.nbytes <= 0.1 * 1024 * 1024 or len(b.param_indices) == 1


def test_comm_buffer_size_knob_is_wired_and_validated():
    net = nn.Linear(4, 2)
    for bad in (0, -3, "not-a-number", None):
        with pytest.raises((ValueError, TypeError)):
            dist.DataParallel(net, comm_buffer_size=bad)
    with pytest.raises(ValueError):
        dist.DataParallel(net, last_comm_buffer_size=-1)
    dp = dist.DataParallel(net, comm_buffer_size=7.5)
    assert dp.comm_buffer_size == 7.5
    # the knob reaches the communicator
    assert dp._grad_communicator().config.comm_buffer_size == 7.5
    with pytest.raises(ValueError):
        grad_comm.GradCommConfig(codec="fp8")


# ------------------------------------------------- parity on a 2-rank mesh
def test_bucketed_bf16_bit_exact_vs_per_param_sync():
    """The coalesced bf16 sync must transmit exactly what the seed's
    per-param cast/all_reduce/cast path transmitted — same psum over the
    same bf16 values, so bit-exact, not just allclose."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2]))
    shapes = [(3, 5), (7,), (2, 2, 4)]
    # per-rank distinct grads, stacked on the mesh dim
    gs = [rng.standard_normal((2,) + s).astype(np.float32) for s in shapes]

    def body(*rank_grads):
        vals = [g.reshape(s) for g, s in zip(rank_grads, shapes)]
        # seed path: one bf16 collective per param
        ref = []
        for v in vals:
            t = Tensor(v.astype(jnp.bfloat16), _internal=True)
            coll.all_reduce(t, op=coll.ReduceOp.AVG)
            ref.append(t._value.astype(jnp.float32))
        # grad_comm path: one bf16 collective per bucket
        params = []
        for v in vals:
            p = Tensor(jnp.zeros(v.shape), _internal=True)
            p.stop_gradient = False
            p.grad = Tensor(v, _internal=True)
            params.append(p)
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("bf16"))
        comm.sync(params, world=2)
        return tuple(ref) + tuple(p.grad._value for p in params)

    outs = mesh_mod.compat_shard_map(
        body, m, P("data"), tuple([P()] * (2 * len(shapes))))(*gs)
    ref, got = outs[:len(shapes)], outs[len(shapes):]
    for r, g in zip(ref, got):
        assert np.array_equal(np.asarray(r), np.asarray(g)), \
            "bucketed bf16 sync drifted from the per-param wire values"


# ---------------------------------------------------------------- int8 codec
def test_int8_roundtrip_error_bound():
    x = jnp.asarray(rng.standard_normal(4096).astype(np.float32) * 3.0)
    scale = grad_comm.int8_scale(x)
    q = grad_comm.int8_encode(x, scale)
    deq = grad_comm.int8_decode(q, scale, world=1, dtype=np.float32)
    # |x| <= 127*scale by construction, so rounding bounds the error by
    # half a quantization step everywhere
    assert float(jnp.abs(x - deq).max()) <= float(scale) * 0.5001
    # the error-feedback residual is exactly what the wire dropped
    res = grad_comm.int8_residual(x, q, scale)
    np.testing.assert_allclose(np.asarray(deq + res), np.asarray(x),
                               rtol=0, atol=1e-6)


def _two_identical_rank_all_reduce(calls=None):
    """Collective fake for two ranks holding identical values: AVG/MAX are
    identity, integer SUM doubles (the quantized payload path)."""
    def fake(t, op=None, group=None, **kw):
        if calls is not None:
            calls.append((str(t._value.dtype), op))
        if op == coll.ReduceOp.SUM and jnp.issubdtype(t._value.dtype,
                                                      jnp.integer):
            t._value = t._value * 2
        return t
    return fake


def test_int8_error_feedback_convergence(monkeypatch):
    """Smoke test (ISSUE 1 acceptance): an MLP trained with the int8
    quantized grad sync + error feedback lands within tolerance of the
    un-quantized run after N steps."""
    x = rng.standard_normal((32, 8)).astype(np.float32)
    w_true = rng.standard_normal((8, 1)).astype(np.float32)
    y = np.tanh(x @ w_true).astype(np.float32)

    def train(codec, steps=60):
        paddle.seed(11)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optim.SGD(learning_rate=0.3, parameters=net.parameters())
        comm = (None if codec is None else grad_comm.GradCommunicator(
            grad_comm.GradCommConfig(codec)))
        losses = []
        for _ in range(steps):
            loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
            loss.backward()
            if comm is not None:
                comm.sync([p for p in net.parameters()
                           if not p.stop_gradient], world=2)
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses

    monkeypatch.setattr(coll, "all_reduce", _two_identical_rank_all_reduce())
    exact = train(None)
    int8 = train("int8")
    assert exact[-1] < exact[0] * 0.1, "reference run failed to converge"
    assert int8[-1] < int8[0] * 0.1, "int8+EF run failed to converge"
    assert abs(int8[-1] - exact[-1]) <= max(0.05 * exact[-1], 0.005), \
        (int8[-1], exact[-1])


def test_int8_sync_stats_and_wire_dtypes(monkeypatch):
    calls = []
    monkeypatch.setattr(coll, "all_reduce",
                        _two_identical_rank_all_reduce(calls))
    params = _fake_params([(64, 64), (64,)])
    comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
    before = [np.asarray(p.grad._value).copy() for p in params]
    comm.sync(params, world=2)
    # one scalar MAX (the shared scale) + one integer SUM per bucket
    assert [c[1] for c in calls] == [coll.ReduceOp.MAX, coll.ReduceOp.SUM]
    assert calls[1][0] == "int32"
    assert comm.stats["n_buckets"] == 1
    assert comm.stats["collectives"] == 2
    assert comm.stats["comm_bytes"] == (64 * 64 + 64) * 1 + 4
    # two identical ranks: the averaged grad equals the local quantized
    # grad, within half of the BUCKET-wide quantization step (the scale is
    # per bucket, not per param)
    bucket_scale = float(grad_comm.int8_scale(
        jnp.concatenate([jnp.asarray(b).reshape(-1) for b in before])))
    for p, b in zip(params, before):
        err = np.abs(np.asarray(p.grad._value) - b).max()
        assert err <= bucket_scale * 0.5001


# ------------------------------------------------------- DataParallel wiring
def _set_grads(model):
    n = 0
    for p in model.parameters():
        if not p.stop_gradient:
            p.grad = Tensor(rng.standard_normal(p.shape).astype(
                np.dtype(p._value.dtype)) * 1e-2)
            n += 1
    return n


def test_bucketing_collective_count_guard(monkeypatch):
    """Regression guard (ISSUE 1 acceptance): on the test GPT config,
    apply_collective_grads issues O(buckets) collectives — bounded by
    ceil(total_grad_MB / comm_buffer_size) + dtype-group slack — not
    O(#params) like the seed's per-param loop."""
    from paddle_tpu.models import GPTForCausalLM, gpt_presets

    model = GPTForCausalLM(gpt_presets("gpt-test"), seed=0)
    net = dist.DataParallel(model)
    n_params = _set_grads(model)
    assert n_params > 10  # the bound below must be a real reduction

    calls = []
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    monkeypatch.setattr(coll, "all_reduce",
                        lambda t, op=None, **kw: calls.append(1) or t)
    net.apply_collective_grads()

    trainable = [p for p in model.parameters() if not p.stop_gradient]
    total_mb = sum(p.size * np.dtype(p._value.dtype).itemsize
                   for p in trainable) / (1024 * 1024)
    dtype_groups = len({np.dtype(p._value.dtype) for p in trainable})
    bound = math.ceil(total_mb / net.comm_buffer_size) + dtype_groups + 1
    assert len(calls) <= bound, (len(calls), bound)
    assert len(calls) < n_params / 4, (len(calls), n_params)
    assert net._grad_comm.stats["n_params"] == n_params


def test_strategy_selects_codec_and_buffer(monkeypatch):
    wire = []
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    monkeypatch.setattr(coll, "all_reduce",
                        _two_identical_rank_all_reduce(wire))

    net = nn.Linear(4, 2)
    loss = net(paddle.to_tensor(rng.rand(8, 4).astype(np.float32))).sum()
    loss.backward()

    st = fleet.DistributedStrategy()
    st.grad_comm = True
    st.grad_comm_configs = {"codec": "int8", "comm_buffer_size_MB": 13}
    dp = dist.DataParallel(net, strategy=st)
    dp.apply_collective_grads()
    assert [w[0] for w in wire] == ["float32", "int32"]  # scale + payload
    assert dp._grad_comm.config.comm_buffer_size == 13
    # unknown sub-keys still rejected (check_configs_key semantics)
    with pytest.raises(ValueError):
        st.grad_comm_configs = {"bogus": 1}
    # a bad codec configured via strategy fails loudly at sync time
    st2 = fleet.DistributedStrategy()
    st2.grad_comm = True
    st2.grad_comm_configs = {"codec": "fp8"}
    dp2 = dist.DataParallel(net, strategy=st2)
    with pytest.raises(ValueError):
        dp2.apply_collective_grads()


# --------------------------------------------------- sharding stage-2 path
def test_sharding_stage2_uses_reduce_scatter(monkeypatch):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 8}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 2, "sharding_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    wrapped = fleet.distributed_model(net)
    assert type(wrapped).__name__ == "ShardingParallel"
    assert wrapped._grad_comm is not None
    _set_grads(net)

    rs_calls, ag_calls = [], []
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    monkeypatch.setattr(
        coll, "reduce_scatter",
        lambda t, tensor_list=None, op=None, group=None, **kw:
        rs_calls.append(str(t._value.dtype)) or t)
    monkeypatch.setattr(
        coll, "all_gather",
        lambda tl, t, group=None, **kw: ag_calls.append(1) or t)
    wrapped.apply_collective_grads()
    st = wrapped._grad_comm.stats
    assert st["n_buckets"] >= 1
    # each bucket goes reduce_scatter -> all_gather, never plain all_reduce
    assert len(rs_calls) == len(ag_calls) == st["n_buckets"]
    assert st["collectives"] == 2 * st["n_buckets"]
    # default wire codec for the sharded path is bf16
    assert all(d == "bfloat16" for d in rs_calls), rs_calls


def test_group_sharded_parallel_attaches_communicator():
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    mesh_mod.set_mesh(mesh_mod.build_mesh({"sharding": 8}))
    net = nn.Linear(16, 8)
    opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, "os_g")
    assert isinstance(model._grad_comm, grad_comm.GradCommunicator)
    # buffer knobs come from the reference kwargs (bytes -> MB)
    assert model._grad_comm.config.comm_buffer_size == pytest.approx(8.0)
    # stage 1 attaches nothing (grads are not sharded there)
    net2 = nn.Linear(4, 2)
    opt2 = optim.Adam(learning_rate=0.01, parameters=net2.parameters())
    model2, _, _ = group_sharded_parallel(net2, opt2, "os")
    assert getattr(model2, "_grad_comm", None) is None


# ------------------------------------------------------- cost model + tools
def test_comm_cost_terms():
    from paddle_tpu.cost_model import comm_cost

    gb = 350e6  # ~GPT-125M fp32 grads
    fp32 = comm_cost(gb, world=8, codec="fp32")
    bf16 = comm_cost(gb, world=8, codec="bf16")
    int8 = comm_cost(gb, world=8, codec="int8")
    assert fp32["time_s"] > bf16["time_s"] > int8["time_s"]
    assert bf16["wire_bytes"] == gb // 2 and int8["wire_bytes"] == gb // 4
    # bucketing amortizes launch latency: per-param sync (~one collective
    # per tensor) costs strictly more than the bucketed plan
    per_param = comm_cost(gb, world=8, codec="bf16", collectives=150)
    assert per_param["time_s"] > bf16["time_s"]
    # reduce_scatter alone moves half of what all-reduce moves
    rs = comm_cost(gb, world=8, codec="bf16", reduce_scatter_only=True)
    assert rs["bytes_through_chip"] == pytest.approx(
        bf16["bytes_through_chip"] / 2)
    assert comm_cost(gb, world=1)["time_s"] == 0.0
    with pytest.raises(ValueError):
        comm_cost(gb, world=8, codec="fp8")


def test_grad_comm_bench_tool_and_artifact():
    """tools/grad_comm_bench.py measures what it plans, and the committed
    artifact records the collective-count win (style:
    test_eager_dispatch_artifact_is_current)."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import grad_comm_bench

    rec = grad_comm_bench.measure(steps=1)
    assert rec["per_param_collectives"] == rec["n_params"]
    for codec, row in rec["codecs"].items():
        assert row["collectives_per_step"] == row["planned_collectives"]
        assert row["comm_bytes_per_step"] == row["planned_comm_bytes"]
        assert row["collectives_per_step"] < rec["n_params"]
    assert (rec["codecs"]["int8"]["comm_bytes_per_step"]
            < rec["codecs"]["bf16"]["comm_bytes_per_step"]
            < rec["codecs"]["fp32"]["comm_bytes_per_step"])

    d = json.load(open(os.path.join(REPO, "artifacts",
                                    "grad_comm_bench.json")))
    assert d["model"] == "gpt-test" and d["codecs"]["fp32"][
        "collectives_per_step"] < d["per_param_collectives"]

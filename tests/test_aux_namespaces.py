"""Tests for paddle.autograd (PyLayer), device, incubate auto-checkpoint,
onnx (StableHLO) export, utils, version/sysconfig/callbacks namespaces."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestPyLayer:
    def test_custom_exp(self):
        from paddle_tpu.autograd import PyLayer

        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = paddle.exp(x)
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor
                return dy * y

        x = paddle.to_tensor(np.array([0.0, 1.0, -1.0], "float32"))
        x.stop_gradient = False
        y = Exp.apply(x)
        np.testing.assert_allclose(y.numpy(), np.exp(x.numpy()), rtol=1e-6)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.exp(x.numpy()),
                                   rtol=1e-6)

    def test_multi_output(self):
        from paddle_tpu.autograd import PyLayer

        class SplitSq(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x, x * 3.0

            @staticmethod
            def backward(ctx, d1, d2):
                (x,) = ctx.saved_tensor
                return d1 * 2.0 * x + d2 * 3.0

        x = paddle.to_tensor(np.array([2.0], "float32"))
        x.stop_gradient = False
        a, b = SplitSq.apply(x)
        (a + b).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2 * 2.0 + 3.0],
                                   rtol=1e-6)

    def test_backward_api(self):
        import paddle_tpu.autograd as ag

        x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
        x.stop_gradient = False
        y = (x ** 2).sum()
        ag.backward([y])
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])


class TestDeviceNamespace:
    def test_queries(self):
        import paddle_tpu.device as device

        assert isinstance(device.get_device(), str)
        assert device.device_count() >= 1
        assert not device.cuda.is_available()
        assert device.cuda.device_count() == 0
        device.synchronize()
        types = device.get_all_device_type()
        assert "cpu" in types


class TestAutoCheckpoint:
    def test_epoch_range_resume(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import TrainEpochRange

        net = nn.Linear(2, 2)
        done = []
        r = TrainEpochRange(5, save_dir=str(tmp_path), job_id="job1",
                            state={"model": net})
        for epoch in r:
            done.append(epoch)
            net.weight.set_value(np.full((2, 2), float(epoch), "float32"))
            if epoch == 2:
                break  # simulate preemption after epoch-2 checkpointing? no:
                # break before _save_state of epoch 2 happens (generator)
        assert done == [0, 1, 2]
        # epochs 0,1 were checkpointed (save happens after each completed
        # yield-resume cycle); restart resumes from epoch 2
        net2 = nn.Linear(2, 2)
        r2 = TrainEpochRange(5, save_dir=str(tmp_path), job_id="job1",
                             state={"model": net2})
        resumed = list(r2)
        assert resumed[0] == 2
        assert resumed[-1] == 4
        np.testing.assert_allclose(net2.weight.numpy(),
                                   np.full((2, 2), 1.0))  # epoch-1 state

    def test_checker_env(self, monkeypatch):
        from paddle_tpu.incubate.checkpoint import AutoCheckpointChecker

        monkeypatch.setenv("PADDLE_JOB_ID", "xyz")
        c = AutoCheckpointChecker()
        assert c.job_id == "xyz"
        assert c.get_job_checkpoint_path("/base") == "/base/xyz"


class TestOnnxExport:
    @pytest.mark.requires_jax_export
    def test_stablehlo_export_roundtrip(self, tmp_path):
        import jax

        import paddle_tpu.onnx as onnx
        from paddle_tpu.static import InputSpec

        net = nn.Linear(4, 2)
        net.eval()
        path = onnx.export(net, str(tmp_path / "model"),
                           input_spec=[InputSpec([1, 4], "float32", "x")])
        assert os.path.exists(path)
        blob = open(path, "rb").read()
        rehydrated = jax.export.deserialize(blob)
        x = np.ones((1, 4), "float32")
        out = rehydrated.call(x)
        expect = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)

    def test_onnx_format_rejected(self, tmp_path):
        import paddle_tpu.onnx as onnx

        with pytest.raises(NotImplementedError):
            onnx.export(nn.Linear(2, 2), str(tmp_path / "m"), format="onnx")


class TestUtils:
    def test_deprecated_warns(self):
        import warnings

        from paddle_tpu.utils import deprecated

        @deprecated(update_to="paddle.new_api", since="2.0")
        def old_api():
            return 42

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old_api() == 42
        assert any("deprecated" in str(x.message) for x in w)

    def test_unique_name(self):
        from paddle_tpu.utils import unique_name

        with unique_name.guard():
            a = unique_name.generate("fc")
            b = unique_name.generate("fc")
        assert a == "fc_0" and b == "fc_1"

    def test_run_check(self, capsys):
        from paddle_tpu.utils import run_check

        run_check()
        assert "successfully" in capsys.readouterr().out

    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils import dlpack

        x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
        cap = dlpack.to_dlpack(x)
        y = dlpack.from_dlpack(cap)
        np.testing.assert_allclose(y.numpy(), x.numpy())


def test_misc_namespaces():
    import paddle_tpu.callbacks as cb
    import paddle_tpu.sysconfig as sysconfig
    import paddle_tpu.version as version

    assert hasattr(cb, "ModelCheckpoint")
    assert version.full_version
    assert os.path.isdir(sysconfig.get_include())


def test_structured_errors_taxonomy():
    from paddle_tpu.framework import errors

    with pytest.raises(errors.InvalidArgumentError):
        errors.enforce(False, "bad arg")
    # typed errors remain catchable as their natural python bases
    with pytest.raises(ValueError):
        errors.enforce(1 == 2, "still a ValueError")
    with pytest.raises(errors.UnimplementedError):
        errors.enforce(False, "todo", errors.UnimplementedError)
    assert issubclass(errors.NotFoundError, KeyError)
    assert issubclass(errors.ResourceExhaustedError, MemoryError)


def test_check_nan_inf_per_op_flag():
    import jax

    import paddle_tpu as paddle

    paddle.set_flags({"FLAGS_check_nan_inf": True})
    jax.config.update("jax_debug_nans", False)  # isolate the eager check
    try:
        with pytest.raises(FloatingPointError, match="log"):
            paddle.log(paddle.to_tensor(np.array([-1.0], np.float32)))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_benchmark_flag_syncs():
    import paddle_tpu as paddle

    paddle.set_flags({"FLAGS_benchmark": True})
    try:
        out = paddle.exp(paddle.to_tensor(np.ones(4, np.float32)))
        np.testing.assert_allclose(out.numpy(), np.e, rtol=1e-6)
    finally:
        paddle.set_flags({"FLAGS_benchmark": False})

"""auto_parallel annotation API + device HBM stats + CTC loss.

Reference: distributed/auto_parallel (ProcessMesh/shard_tensor,
completion.py:326 — completion itself is GSPMD's job here), paddle.device
memory stats, warpctc op.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn.functional as F


def test_process_mesh_and_shard_tensor():
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype("f4"))
    d = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
    shard_shape = d._value.sharding.shard_shape(d._value.shape)
    assert shard_shape == (4, 4)  # 8/2 x 16/4
    np.testing.assert_allclose(np.asarray(d._value), x.numpy())


def test_replicate_and_reshard():
    mesh = dist.ProcessMesh(np.arange(4).reshape(4), ["dp"])
    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    r = dist.shard_tensor(x, mesh, [dist.Replicate()])
    assert r._value.sharding.shard_shape(r._value.shape) == (8, 4)
    s = dist.reshard(r, mesh, [dist.Shard(0)])
    assert s._value.sharding.shard_shape(s._value.shape) == (2, 4)


def test_shard_layer_places_params():
    import paddle_tpu.nn as nn

    mesh = dist.ProcessMesh(np.arange(2), ["mp"])

    def shard_fn(name, sub, m):
        for _, p in sub.named_parameters(include_sublayers=False):
            if p._value.ndim == 2:
                placed = dist.shard_tensor(p, m, [dist.Shard(1)])
                p._value = placed._value

    lin = nn.Linear(4, 8)
    dist.shard_layer(lin, mesh, shard_fn)
    assert lin.weight._value.sharding.shard_shape(
        lin.weight._value.shape) == (4, 4)


def test_dtensor_from_fn():
    mesh = dist.ProcessMesh(np.arange(2), ["dp"])
    t = dist.dtensor_from_fn(paddle.ones, mesh, [dist.Shard(0)],
                             shape=[4, 3])
    assert t._value.sharding.shard_shape(t._value.shape) == (2, 3)


def test_placement_predicates():
    assert dist.Shard(1).is_shard(1) and not dist.Shard(1).is_replicate()
    assert dist.Replicate().is_replicate()
    assert dist.Partial().is_partial()


def test_device_memory_stats_api():
    import paddle_tpu.device as device

    stats = device.memory_stats()
    assert isinstance(stats, dict)  # CPU may report {}
    assert device.memory_allocated() >= 0
    assert device.max_memory_allocated() >= 0
    props = device.get_device_properties()
    assert props.name


def test_ctc_loss_matches_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    T, B, C, L = 10, 2, 5, 3
    logits = rs.randn(T, B, C).astype("float32")
    labels = rs.randint(1, C, (B, L)).astype("int64")
    in_len = np.array([10, 7], np.int64)
    lab_len = np.array([3, 2], np.int64)
    ours = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                      reduction="none")
    ref = torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.tensor(logits), -1), torch.tensor(labels),
        torch.tensor(in_len), torch.tensor(lab_len), blank=0,
        reduction="none")
    np.testing.assert_allclose(np.asarray(ours.numpy()), ref.numpy(),
                               rtol=1e-4)


def test_ctc_loss_grad_finite():
    rs = np.random.RandomState(1)
    x = paddle.to_tensor(rs.randn(8, 2, 4).astype("float32"))
    x.stop_gradient = False
    loss = F.ctc_loss(x, paddle.to_tensor(rs.randint(1, 4, (2, 2))),
                      paddle.to_tensor(np.array([8, 8])),
                      paddle.to_tensor(np.array([2, 2])))
    loss.backward()
    assert np.isfinite(x.grad.numpy()).all()

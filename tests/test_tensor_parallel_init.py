"""TensorParallel mp-init consistency check (VERDICT r4 #5).

The reference's TensorParallel._prepare_for_model broadcasts parameters
over the mp group so ranks start identical
(fleet/meta_parallel/tensor_parallel.py). The SPMD equivalent is a
verification that every replica of a logical parameter slice holds
identical values at wrap time — these tests pin both directions: a clean
wrap passes, and a deliberately divergent replica fails loudly.
"""
import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, TensorParallel,
    VocabParallelEmbedding,
)

rng = np.random.RandomState(8)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield


class MpNet(nn.Layer):
    def __init__(self, vocab=32, hidden=16):
        super().__init__()
        self.emb = VocabParallelEmbedding(vocab, hidden)
        self.col = ColumnParallelLinear(hidden, hidden * 2, gather_output=False)
        self.row = RowParallelLinear(hidden * 2, hidden, input_is_parallel=True)

    def forward(self, ids):
        return self.row(F.gelu(self.col(self.emb(ids))))


def _mp_fleet(mp=2, dp=4):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)


def test_distributed_model_wraps_and_checks_consistent_init():
    _mp_fleet()
    paddle.seed(3)
    wrapped = fleet.distributed_model(MpNet())
    assert isinstance(wrapped, TensorParallel)
    # the wrapper ran the check in _prepare_for_model without raising,
    # and stays usable as a model
    out = wrapped(paddle.to_tensor(rng.randint(0, 32, (8, 4)).astype(np.int64)))
    assert out.shape == [8, 4, 16]
    # re-runnable on demand (reference re-broadcasts on request)
    wrapped.check_mp_init_consistency()


def test_divergent_replica_fails_loudly():
    """Build a 'replicated' param whose model-axis replicas actually
    differ (what per-process seed drift would produce in a multi-process
    run) — the wrap must refuse it, not let XLA silently pick a replica."""
    _mp_fleet()
    paddle.seed(3)
    net = MpNet()
    wrapped = fleet.distributed_model(net)

    mesh = mesh_mod.get_mesh()
    bias = net.row.bias  # replicated over the whole mesh
    shape = tuple(bias._value.shape)
    sharding = NamedSharding(mesh, P())
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        host = np.asarray(bias._value).copy()
        if i == len(list(mesh.devices.flat)) - 1:
            host[0] += 1.0  # one device's replica drifts
        bufs.append(jax.device_put(host, d))
    bias._value = jax.make_array_from_single_device_arrays(
        shape, sharding, bufs)

    with pytest.raises(RuntimeError, match="init divergence"):
        wrapped.check_mp_init_consistency()


def test_check_skips_without_model_axis():
    """No model axis -> nothing to verify (data-parallel wrap path)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    net = nn.Linear(4, 2)
    wrapped = fleet.distributed_model(net)
    assert not isinstance(wrapped, TensorParallel)

"""PipelineTrainStep — 1F1B composed into ONE compiled train step
(ISSUE 15 tentpole): schedule x quantized grad_comm x ZeRO-3 at-rest
stage params x memory planner, plus the emulated-HBM acceptance run.

Parity references: the unpipelined ``TrainStep(grad_accum_steps=M)`` has
the SAME arithmetic shape (per-micro-batch mean losses, forward-order
grad accumulation, identical optimizer path), so the composed step's
FIRST loss — same params, same forward — must be bit-identical, and the
trajectory must track within a few ulp. Strict multi-step bitwise
equality across the two DIFFERENT XLA programs is not in our control:
the compiler may contract a*b+c chains differently per program (measured
here: 1-2 ulp on two tensors after one update), which is why the
trajectory assertion is a tight allclose rather than ==.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import (
    MemoryPlan, PipelineTrainStep, plan_memory,
)
from paddle_tpu.distributed.pipeline.train_step import MemoryPlanInfeasible
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPTForCausalLM, gpt_presets
from paddle_tpu.models.gpt import GPTPretrainingCriterion

B, S = 8, 16
CFG_KW = dict(mode="scan", use_flash_attention=False)

rs = np.random.RandomState(3)
IDS = rs.randint(0, 128, (B, S))
LBL = rs.randint(0, 128, (B, S))


@pytest.fixture(autouse=True)
def _no_ambient_mesh(fresh_mesh):
    yield


def T(a):
    return paddle.to_tensor(a, dtype="int64")


def run_reference(M, steps=3, num_layers=2):
    """Unpipelined fp32 reference at equal global batch: the SAME
    micro-batched accumulation arithmetic, one device."""
    mesh_mod.set_mesh(None)
    cfg = gpt_presets("gpt-test", num_layers=num_layers, **CFG_KW)
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                     grad_accum_steps=M)
    return [float(step(inputs=(T(IDS),), labels=(T(LBL),)))
            for _ in range(steps)]


def run_pipelined(topology, M, steps=3, num_layers=2, **step_kw):
    n = int(np.prod(list(topology.values())))
    mesh_mod.set_mesh(mesh_mod.build_mesh(topology,
                                          devices=jax.devices()[:n]))
    cfg = gpt_presets("gpt-test", num_layers=num_layers,
                      pp_microbatches=M, **CFG_KW)
    model = GPTForCausalLM(cfg, seed=0)
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step_kw.setdefault("memory_plan", None)
    step = PipelineTrainStep(model, optim, **step_kw)
    losses = [float(step(inputs=(T(IDS),), labels=(T(LBL),)))
              for _ in range(steps)]
    return losses, step, model


class TestComposedParity:
    def test_fp32_first_loss_bit_identical_trajectory_ulp(self):
        M = 4
        ref = run_reference(M)
        pp, step, _ = run_pipelined({"pipe": 2}, M)
        assert pp[0] == ref[0]          # bit-identical forward
        np.testing.assert_allclose(pp, ref, rtol=2e-6)
        rep = step.report()
        assert rep["pipeline_bubble_pct"] == pytest.approx(20.0)
        assert rep["stash_slots"] == 3

    def test_fewer_microbatches_than_stages(self):
        # M=1 < P=2: deep bubble, exact math
        ref = run_reference(1, steps=2)
        pp, step, _ = run_pipelined({"pipe": 2}, 1, steps=2)
        assert pp[0] == ref[0]
        np.testing.assert_allclose(pp, ref, rtol=2e-6)
        assert step.report()["pipeline_bubble_pct"] == pytest.approx(50.0)

    def test_many_more_microbatches_than_stages(self):
        # M=8 >> P=2: shallow bubble, stash capped at 2P-1
        ref = run_reference(8, steps=2)
        pp, step, _ = run_pipelined({"pipe": 2}, 8, steps=2)
        assert pp[0] == ref[0]
        np.testing.assert_allclose(pp, ref, rtol=2e-6)
        rep = step.report()
        assert rep["stash_slots"] == 3
        assert rep["pipeline_bubble_pct"] == pytest.approx(100 / 9,
                                                           abs=1e-3)

    def test_data_parallel_composition(self):
        ref = run_reference(4)
        pp, _, _ = run_pipelined({"pipe": 2, "data": 2}, 4)
        assert pp[0] == ref[0]
        np.testing.assert_allclose(pp, ref, rtol=2e-6)


class TestQuantizedGradComm:
    def test_int8_block_convergence_and_carried_residuals(self):
        """The codec reduces the data-axis wire INSIDE the schedule's
        body; error-feedback residuals ride the jitted step as carried
        state with per-ownership row counts."""
        fp, _, _ = run_pipelined({"pipe": 2, "data": 2}, 4, steps=4)
        qq, step, _ = run_pipelined({"pipe": 2, "data": 2}, 4, steps=4,
                                    grad_comm="int8_block")
        # convergence parity: quantized tracks fp32 closely on gpt-test
        assert qq[0] == fp[0]           # first forward identical
        np.testing.assert_allclose(qq, fp, rtol=5e-3)
        assert qq[-1] < qq[0]
        st = step.comm_stats
        assert st["path"] == "traced" and st["codec"] == "int8_block"
        assert st["world"] == 2
        # per-bucket residual stacking: replicated-param bucket has one
        # row per data rank; the pipe-owned block bucket one per
        # (pipe x data) rank
        res = step.grad_comm_communicator._residuals
        rows = sorted(np.asarray(r).shape[0] for r in res.values())
        assert rows == [2, 4]
        # resume surface: round-trips through state_dict
        sd = step.grad_comm_communicator.state_dict()
        assert sd["codec"] == "int8_block" and len(sd["residuals"]) == 2

    def test_fp32_codec_matches_plain_pmean_bitwise(self):
        """The fp32 'codec' is a plain AVG over the data axis — the
        composed step must equal the codec-less one bit for bit."""
        base, _, _ = run_pipelined({"pipe": 2, "data": 2}, 4, steps=3)
        fp, _, _ = run_pipelined({"pipe": 2, "data": 2}, 4, steps=3,
                                 grad_comm="fp32")
        assert base == fp


class TestZero3StageParams:
    def test_at_rest_layout_and_parity(self):
        """Block weights (and moments) rest sharded over
        ('pipe','sharding') on the layer dim — 1/(P*Z) of the stack per
        rank — while the loss trajectory tracks the unpipelined
        reference."""
        L = 4
        ref = run_reference(4, num_layers=L)
        zz, step, model = run_pipelined({"pipe": 2, "sharding": 2}, 4,
                                        num_layers=L,
                                        zero3_stage_params=True)
        assert zz[0] == ref[0]
        np.testing.assert_allclose(zz, ref, rtol=2e-6)
        # at-rest placement: each rank's shard of the stacked qkv weight
        # holds L/(P*Z) = 1 layer
        qkv = model.gpt.decoder.qkv_w
        assert tuple(qkv.dist_spec)[0] == ("pipe", "sharding")
        shard_rows = {sh.data.shape[0]
                      for sh in qkv._value.addressable_shards}
        assert shard_rows == {L // 4}
        # optimizer moments follow the at-rest layout (the ZeRO-3 state
        # win): find qkv_w's slot entry and check its shards
        fm_params = [p for p, m in zip(step.fm.params,
                                       step.fm.trainable_mask) if m]
        qi = next(i for i, p in enumerate(fm_params) if p is qkv)
        m1 = step._slots[qi]["moment1"]
        assert {sh.data.shape[0] for sh in m1.addressable_shards} \
            == {L // 4}

    def test_zero3_with_quantized_comm(self):
        """All three composed: 1F1B x ZeRO-3 at rest x int8_block codec
        over the data axis."""
        L = 4
        # M=2: each 4-row micro-batch shards over data x sharding = 4
        ref = run_reference(2, num_layers=L, steps=3)
        qq, step, _ = run_pipelined(
            {"pipe": 2, "sharding": 2, "data": 2}, 2, num_layers=L,
            zero3_stage_params=True, grad_comm="int8_block")
        assert qq[0] == ref[0]
        np.testing.assert_allclose(qq, ref, rtol=5e-3)
        assert step.comm_stats["world"] == 2   # data axis only


class TestMemoryPolicies:
    def test_remat_policy_matrix_watermark(self):
        """none / full-remat / planner-chosen via explicit MemoryPlan:
        all train to the same losses (remat changes memory, not math),
        and the compiled step's temp bytes order none >= remat."""
        import paddle_tpu.cost_model as cm

        temps, losses = {}, {}
        for name, policies in [("none", ("none",)),
                               ("remat", ("remat",))]:
            plan = plan_memory(
                num_layers=2, pipe_degree=2, microbatches=4,
                activation_bytes_per_layer=1e5,
                input_bytes_per_layer=1e4, layer_flops=1e6)
            plan = MemoryPlan(
                policies=policies, stash_offload=False,
                stash_memory_kind=None, pipe_degree=2, microbatches=4,
                feasible=True, reason="pinned by test", cost=plan.cost)
            ll, step, _ = run_pipelined({"pipe": 2}, 4, steps=2,
                                        memory_plan=plan)
            losses[name] = ll
            mem = step.memory_analysis(record=False)
            if mem is not None:
                temps[name] = mem["temp_bytes"]
        np.testing.assert_allclose(losses["none"], losses["remat"],
                                   rtol=2e-6)
        if len(temps) == 2:
            assert temps["remat"] <= temps["none"]

    def test_offload_policy_lowering_parity(self):
        """Forced offload (CPU: the identity 'unpinned_host' space —
        exercises the lowering, buys no bytes) must not change the
        math."""
        plan_off = MemoryPlan(
            policies=("offload",), stash_offload=True,
            stash_memory_kind="unpinned_host", pipe_degree=2,
            microbatches=4, feasible=True, reason="forced by test",
            cost={})
        base, _, _ = run_pipelined({"pipe": 2}, 4, steps=2)
        off, _, _ = run_pipelined({"pipe": 2}, 4, steps=2,
                                  memory_plan=plan_off)
        np.testing.assert_allclose(off, base, rtol=2e-6)

    def test_composed_step_temp_bytes_bounded_by_depth_not_m(self):
        """THE 1F1B memory claim, through the WHOLE composed step: at
        fixed micro-batch size, growing M leaves the compiled step's
        temp bytes ~flat once the stash saturates at 2P-1 slots."""
        def temp_bytes(M):
            mesh_mod.set_mesh(mesh_mod.build_mesh(
                {"pipe": 2}, devices=jax.devices()[:2]))
            cfg = gpt_presets("gpt-test", pp_microbatches=M, **CFG_KW)
            model = GPTForCausalLM(cfg, seed=0)
            optim = opt.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
            step = PipelineTrainStep(model, optim, memory_plan=None)
            ids = rs.randint(0, 128, (M * 2, S))
            step(inputs=(T(ids),), labels=(T(ids),))
            mem = step.memory_analysis(record=False)
            if mem is None:
                pytest.skip("backend exposes no memory analysis")
            return mem["temp_bytes"]

        t_sat = temp_bytes(3)      # S saturates at 2P-1 = 3
        t_big = temp_bytes(12)     # 4x the micro-batches, same mb size
        assert t_big <= t_sat + max(4096, int(0.05 * t_sat)), \
            (t_sat, t_big)


class TestPlannerGate:
    def test_infeasible_budget_refused_with_priced_reason(self):
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"pipe": 2}, devices=jax.devices()[:2]))
        cfg = gpt_presets("gpt-test", pp_microbatches=4, **CFG_KW)
        model = GPTForCausalLM(cfg, seed=0)
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = PipelineTrainStep(model, optim, hbm_budget_bytes=1024)
        with pytest.raises(MemoryPlanInfeasible, match="no assignment"):
            step(inputs=(T(IDS),), labels=(T(LBL),))

    def test_planner_chosen_plan_trains_and_reports(self):
        """The emulated-HBM acceptance run: a budget the all-none plan
        busts but remat fits — the step plans, trains, reports the plan
        + bubble, and the first loss is bit-identical to the unpipelined
        fp32 reference at equal global batch."""
        from paddle_tpu.distributed.pipeline import (
            gpt_activation_estimate,
        )

        ref = run_reference(4, steps=2, num_layers=4)
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"pipe": 2}, devices=jax.devices()[:2]))
        cfg = gpt_presets("gpt-test", num_layers=4, pp_microbatches=4,
                          **CFG_KW)
        est = gpt_activation_estimate(cfg, B // 4, S)
        # 2 layers per stage: between the full-remat peak
        # (stash + 2*inp + 1 transient act) and the all-none peak
        # (stash + 2 resident acts)
        budget = (3 * est["input_bytes_per_layer"]
                  + 2 * est["input_bytes_per_layer"]
                  + 1.5 * est["activation_bytes_per_layer"])
        model = GPTForCausalLM(cfg, seed=0)
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = PipelineTrainStep(model, optim, hbm_budget_bytes=budget)
        losses = [float(step(inputs=(T(IDS),), labels=(T(LBL),)))
                  for _ in range(2)]
        assert losses[0] == ref[0]
        np.testing.assert_allclose(losses, ref, rtol=2e-6)
        plan = step.memory_plan
        assert plan is not None and plan.feasible
        assert "remat" in plan.policies
        assert plan.activation_bytes_peak <= budget
        rep = step.report()
        assert rep["memory_plan"]["feasible"]
        assert rep["pipeline_bubble_pct"] == pytest.approx(20.0)


class TestLiveBytesWatermark:
    def test_watermark_bounded_across_m(self):
        """LiveBytesWatermark over the composed step: the host-visible
        live-byte watermark is dominated by params/opt state and stays
        ~flat as M grows at fixed micro-batch size (the O(M) quantity —
        the global batch — enters only as the input arrays themselves);
        the in-program activation bound is pinned by
        test_composed_step_temp_bytes_bounded_by_depth_not_m."""
        from paddle_tpu.observability.memory import LiveBytesWatermark

        def watermark(M):
            mesh_mod.set_mesh(mesh_mod.build_mesh(
                {"pipe": 2}, devices=jax.devices()[:2]))
            cfg = gpt_presets("gpt-test", pp_microbatches=M, **CFG_KW)
            model = GPTForCausalLM(cfg, seed=0)
            optim = opt.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
            step = PipelineTrainStep(model, optim, memory_plan=None)
            ids = rs.randint(0, 128, (M * 2, S))
            step(inputs=(T(ids),), labels=(T(ids),))  # compile outside
            with LiveBytesWatermark() as wm:
                step(inputs=(T(ids),), labels=(T(ids),))
                wm.sample()
            batch_bytes = 2 * ids.size * 8
            return wm.delta, batch_bytes

        d1, b1 = watermark(3)
        d2, b2 = watermark(12)
        # growing M 4x adds only the batch arrays, not activations
        assert d2 - d1 <= (b2 - b1) + (1 << 20), (d1, d2, b1, b2)


def test_pipeline_metrics_exported():
    """The step exports the gauges bench/bench_gate consume."""
    from paddle_tpu.observability.metrics import get_registry

    run_pipelined({"pipe": 2}, 4, steps=1)
    snap = get_registry().snapshot()
    assert snap["pipeline_bubble_pct"] == pytest.approx(20.0)
    assert snap["pipeline_microbatches"] == 4
    assert snap["pipeline_stash_slots"] == 3

"""Transformer layer family tests (reference API:
python/paddle/nn/layer/transformer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def randn(*shape):
    return paddle.to_tensor(np.random.RandomState(0).randn(*shape).astype("float32"))


class TestSDPA:
    def test_matches_numpy(self):
        rs = np.random.RandomState(1)
        q = rs.randn(2, 3, 2, 4).astype("float32")
        k = rs.randn(2, 5, 2, 4).astype("float32")
        v = rs.randn(2, 5, 2, 4).astype("float32")
        out = F.scaled_dot_product_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v))
        logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(4)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bkhd->bqhd", p, v)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)

    def test_causal(self):
        q = randn(1, 4, 1, 8)
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        # first position attends only to itself → equals v[0]
        np.testing.assert_allclose(out.numpy()[0, 0], q.numpy()[0, 0], rtol=1e-5)

    def test_bool_and_float_masks(self):
        q = randn(1, 3, 2, 4)
        m_bool = paddle.to_tensor(np.tril(np.ones((3, 3), dtype=bool)))
        m_float = paddle.to_tensor(
            np.triu(np.full((3, 3), -1e9, dtype="float32"), k=1))
        o1 = F.scaled_dot_product_attention(q, q, q, attn_mask=m_bool)
        o2 = F.scaled_dot_product_attention(q, q, q, attn_mask=m_float)
        np.testing.assert_allclose(o1.numpy(), o2.numpy(), rtol=1e-4, atol=1e-5)


class TestMultiHeadAttention:
    def test_self_attention_shape_and_grad(self):
        mha = nn.MultiHeadAttention(16, 4, dropout=0.0)
        x = randn(2, 5, 16)
        x.stop_gradient = False
        y = mha(x)
        assert y.shape == [2, 5, 16]
        y.sum().backward()
        assert mha.q_proj.weight.grad is not None
        assert x.grad.shape == [2, 5, 16]

    def test_cross_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        q, kv = randn(2, 3, 16), randn(2, 7, 16)
        assert mha(q, kv, kv).shape == [2, 3, 16]

    def test_kdim_vdim(self):
        mha = nn.MultiHeadAttention(16, 4, kdim=8, vdim=12)
        q, k, v = randn(2, 3, 16), randn(2, 7, 8), randn(2, 7, 12)
        assert mha(q, k, v).shape == [2, 3, 16]

    def test_incremental_cache_matches_full(self):
        paddle.seed(0)
        mha = nn.MultiHeadAttention(8, 2, dropout=0.0)
        mha.eval()
        x = randn(1, 4, 8)
        full = mha(x, attn_mask=paddle.to_tensor(
            np.tril(np.ones((4, 4), dtype=bool))))
        cache = mha.gen_cache(x, type=nn.MultiHeadAttention.Cache)
        outs = []
        for i in range(4):
            step = paddle.to_tensor(x.numpy()[:, i : i + 1])
            o, cache = mha(step, step, step, None, cache)
            outs.append(o.numpy())
        np.testing.assert_allclose(
            np.concatenate(outs, axis=1), full.numpy(), rtol=1e-4, atol=1e-5)


class TestTransformerStacks:
    def test_encoder(self):
        enc = nn.TransformerEncoder(nn.TransformerEncoderLayer(16, 4, 32), 3)
        assert enc(randn(2, 5, 16)).shape == [2, 5, 16]
        # independent per-layer parameters
        w0 = enc.layers[0].linear1.weight
        w1 = enc.layers[1].linear1.weight
        assert w0 is not w1

    def test_pre_ln(self):
        enc = nn.TransformerEncoder(
            nn.TransformerEncoderLayer(16, 4, 32, normalize_before=True), 2,
            norm=nn.LayerNorm(16))
        assert enc(randn(2, 5, 16)).shape == [2, 5, 16]

    def test_full_transformer_and_mask(self):
        t = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
        src, tgt = randn(2, 5, 16), randn(2, 4, 16)
        mask = t.generate_square_subsequent_mask(4)
        out = t(src, tgt, tgt_mask=mask)
        assert out.shape == [2, 4, 16]
        out.mean().backward()
        assert t.decoder.layers[0].cross_attn.k_proj.weight.grad is not None

    def test_decoder_cache_decode(self):
        t = nn.Transformer(d_model=8, nhead=2, num_encoder_layers=1,
                           num_decoder_layers=2, dim_feedforward=16, dropout=0.0)
        t.eval()
        src = randn(1, 3, 8)
        mem = t.encoder(src)
        cache = t.decoder.gen_cache(mem)
        step = randn(1, 1, 8)
        o1, cache = t.decoder(step, mem, cache=cache)
        o2, cache = t.decoder(step, mem, cache=cache)
        assert o1.shape == [1, 1, 8] and o2.shape == [1, 1, 8]
        assert cache[0][0].k.shape[1] == 2

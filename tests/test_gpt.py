"""GPT flagship tests: loop/scan parity, hybrid-parallel training on the
8-device CPU mesh (SURVEY.md §4: multi-process NCCL tests → virtual mesh)."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)


@pytest.fixture(autouse=True)
def clean_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def data(batch=4, seq=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    ids = paddle.to_tensor(rs.randint(0, vocab, (batch, seq)), dtype="int64")
    labels = paddle.to_tensor(rs.randint(0, vocab, (batch, seq)), dtype="int64")
    return ids, labels


class TestGPTForward:
    def test_logits_shape_and_grad(self):
        m = GPTForCausalLM(gpt_presets("gpt-test"))
        ids, labels = data()
        logits = m(ids)
        assert logits.shape == [4, 16, 256]
        loss = GPTPretrainingCriterion()(logits, labels)
        loss.backward()
        assert m.gpt.embeddings.word_embeddings.grad is not None
        assert m.gpt.decoder[0].qkv_w.grad is not None

    def test_loop_scan_parity(self):
        ids, labels = data()
        crit = GPTPretrainingCriterion()
        l1 = crit(GPTForCausalLM(gpt_presets("gpt-test"), seed=3)(ids), labels)
        l2 = crit(GPTForCausalLM(gpt_presets("gpt-test", mode="scan"), seed=3)(ids),
                  labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    def test_recompute_matches(self):
        ids, labels = data()
        crit = GPTPretrainingCriterion()
        l1 = crit(GPTForCausalLM(gpt_presets("gpt-test"), seed=1)(ids), labels)
        l2 = crit(
            GPTForCausalLM(gpt_presets("gpt-test", recompute=True), seed=1)(ids),
            labels)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)

    def test_loss_mask(self):
        m = GPTForCausalLM(gpt_presets("gpt-test"))
        ids, labels = data()
        mask = paddle.to_tensor(np.ones((4, 16), dtype="float32"))
        crit = GPTPretrainingCriterion()
        logits = m(ids)
        np.testing.assert_allclose(
            float(crit(logits, labels, mask)), float(crit(logits, labels)),
            rtol=1e-6)

    def test_causality(self):
        """Changing a future token must not change earlier logits."""
        m = GPTForCausalLM(gpt_presets("gpt-test"))
        m.eval()
        ids, _ = data(batch=1)
        logits1 = m(ids).numpy()
        ids2 = ids.numpy().copy()
        ids2[0, -1] = (ids2[0, -1] + 1) % 256
        logits2 = m(paddle.to_tensor(ids2, dtype="int64")).numpy()
        np.testing.assert_allclose(logits1[0, :-1], logits2[0, :-1],
                                   rtol=1e-4, atol=1e-5)


class TestGPTHybridParallel:
    def _train(self, cfg, topo, steps=3, batch_spec=None):
        if topo is None:
            mesh_mod._current[0] = None
        else:
            mesh_mod.set_mesh(mesh_mod.build_mesh(topo))
        m = GPTForCausalLM(cfg, seed=7)
        crit = GPTPretrainingCriterion()
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda lg, lb: crit(lg, lb), o,
                         batch_spec=batch_spec)
        ids, labels = data()
        return [float(step(inputs=(ids,), labels=(labels,)))
                for _ in range(steps)]

    def test_dp_tp_pp(self):
        losses = self._train(gpt_presets("gpt-test", mode="scan"),
                             {"data": 2, "pipe": 2, "model": 2})
        assert losses[-1] < losses[0]

    def test_dp_sharding_tp(self):
        losses = self._train(gpt_presets("gpt-test"),
                             {"data": 2, "sharding": 2, "model": 2},
                             batch_spec=P(("data", "sharding")))
        assert losses[-1] < losses[0]

    def test_parallel_matches_single_device(self):
        """Distributed first-step loss == single-device first-step loss
        (the reference asserts per-step loss parity, test_dist_base.py:1457)."""
        single = self._train(gpt_presets("gpt-test"), None, steps=2)
        hybrid = self._train(gpt_presets("gpt-test", mode="scan"),
                             {"data": 2, "pipe": 2, "model": 2}, steps=2)
        np.testing.assert_allclose(single, hybrid, rtol=2e-3)

    def test_tp8(self):
        losses = self._train(gpt_presets("gpt-test"), {"model": 8})
        assert losses[-1] < losses[0]

"""hapi Model / io / metric / callbacks tests (reference:
python/paddle/tests/test_model.py, dist_hapi_mnist_dynamic.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu import Model
from paddle_tpu.hapi.callbacks import EarlyStopping, ProgBarLogger
from paddle_tpu.io import (
    BatchSampler, ConcatDataset, DataLoader, Dataset, DistributedBatchSampler,
    IterableDataset, Subset, TensorDataset, random_split,
)
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall, accuracy

rng = np.random.RandomState(9)


class ToyDataset(Dataset):
    def __init__(self, n=64, with_label=True, seed=7):
        # own RandomState: drawing from the shared module rng made the
        # data depend on test execution order (flaky accuracy thresholds)
        self.x = np.random.RandomState(seed).rand(n, 8).astype(np.float32)
        self.y = (self.x[:, 0] > 0.5).astype(np.int64)
        self.with_label = with_label

    def __getitem__(self, i):
        if self.with_label:
            return self.x[i], self.y[i]
        return self.x[i]

    def __len__(self):
        return len(self.x)


class TestDataLoader:
    def test_basic_iteration(self):
        loader = DataLoader(ToyDataset(64), batch_size=16)
        batches = list(loader)
        assert len(batches) == 4
        x, y = batches[0]
        assert x.shape == [16, 8] and y.shape == [16]

    def test_shuffle_and_drop_last(self):
        loader = DataLoader(ToyDataset(50), batch_size=16, shuffle=True, drop_last=True)
        assert len(loader) == 3
        batches = list(loader)
        assert len(batches) == 3

    def test_num_workers_threadpool(self):
        loader = DataLoader(ToyDataset(64), batch_size=16, num_workers=2)
        assert len(list(loader)) == 4

    def test_iterable_dataset(self):
        class Stream(IterableDataset):
            def __iter__(self):
                for i in range(10):
                    yield np.full(3, i, np.float32)

        loader = DataLoader(Stream(), batch_size=4)
        batches = list(loader)
        assert len(batches) == 3
        assert batches[0].shape == [4, 3]

    def test_error_propagates(self):
        class Bad(Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                raise ValueError("boom")

        with pytest.raises(ValueError):
            list(DataLoader(Bad(), batch_size=2))

    def test_tensor_and_concat_and_subset(self):
        td = TensorDataset([paddle.to_tensor(rng.rand(10, 2).astype(np.float32)),
                            paddle.to_tensor(np.arange(10))])
        assert len(td) == 10
        a, b = td[3]
        assert int(b.numpy()) == 3
        cd = ConcatDataset([ToyDataset(4), ToyDataset(6)])
        assert len(cd) == 10
        _ = cd[9]
        sub = Subset(ToyDataset(10), [0, 5])
        assert len(sub) == 2
        parts = random_split(ToyDataset(10), [7, 3])
        assert len(parts[0]) == 7 and len(parts[1]) == 3

    def test_distributed_batch_sampler_shards(self):
        ds = ToyDataset(20)
        s0 = DistributedBatchSampler(ds, batch_size=5, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(ds, batch_size=5, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(i0) == len(i1) == 10
        assert set(i0).isdisjoint(set(i1))


class TestMetrics:
    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = paddle.to_tensor([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
        label = paddle.to_tensor([[1], [2]])
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert top1 == pytest.approx(0.5)
        assert top2 == pytest.approx(0.5)
        assert m.name() == ["acc_top1", "acc_top2"]

    def test_functional_accuracy(self):
        acc = accuracy(paddle.to_tensor([[0.1, 0.9], [0.9, 0.1]]),
                       paddle.to_tensor([[1], [1]]))
        assert float(acc.numpy()) == pytest.approx(0.5)

    def test_precision_recall(self):
        p = Precision()
        r = Recall()
        preds = paddle.to_tensor([0.9, 0.9, 0.1, 0.1])
        labels = paddle.to_tensor([1, 0, 1, 0])
        p.update(preds, labels)
        r.update(preds, labels)
        assert p.accumulate() == pytest.approx(0.5)
        assert r.accumulate() == pytest.approx(0.5)

    def test_auc_perfect(self):
        auc = Auc()
        preds = np.stack([1 - np.linspace(0, 1, 100), np.linspace(0, 1, 100)], 1)
        labels = (np.linspace(0, 1, 100) > 0.5).astype(np.int64)
        auc.update(paddle.to_tensor(preds.astype(np.float32)), paddle.to_tensor(labels))
        assert auc.accumulate() > 0.99


class TestModel:
    def _model(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
        m = Model(net)
        m.prepare(optimizer=optim.Adam(learning_rate=0.01, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        return m

    def test_fit_evaluate_predict(self, tmp_path):
        m = self._model()
        train, test = ToyDataset(256), ToyDataset(64)
        m.fit(train, test, batch_size=32, epochs=3, verbose=0)
        res = m.evaluate(test, batch_size=32, verbose=0)
        assert res["acc"] > 0.9
        preds = m.predict(test, batch_size=32, stack_outputs=True)
        assert preds[0].shape == (64, 2)

    def test_save_load_roundtrip(self, tmp_path):
        m = self._model()
        m.fit(ToyDataset(64), batch_size=32, epochs=1, verbose=0)
        path = str(tmp_path / "ckpt")
        m.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")
        m2 = self._model()
        m2.load(path)
        x = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
        np.testing.assert_allclose(
            m.network.eval()(x).numpy(), m2.network.eval()(x).numpy(), rtol=1e-5
        )

    def test_eager_fallback_with_amp(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
        m = Model(net)
        m.prepare(optimizer=optim.SGD(0.05, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss(),
                  amp_configs={"level": "O1"})
        assert not m._jit_compile
        m.fit(ToyDataset(64), batch_size=32, epochs=1, verbose=0)

    def test_gradient_accumulation(self):
        m = self._model()
        m.fit(ToyDataset(64), batch_size=8, epochs=1, verbose=0,
              accumulate_grad_batches=4)

    def test_early_stopping(self):
        m = self._model()
        es = EarlyStopping(monitor="acc", mode="max", patience=0, verbose=0,
                           save_best_model=False)
        m.fit(ToyDataset(128), ToyDataset(32), batch_size=32, epochs=10, verbose=0,
              callbacks=[es])
        assert m.stop_training

    def test_num_iters_cap(self):
        m = self._model()
        m.fit(ToyDataset(256), batch_size=8, epochs=10, verbose=0, num_iters=3)


def test_model_fit_under_active_mesh_data_parallel():
    """Model.prepare with an active fleet mesh places params on the mesh
    and fit() trains sharded (the reference's prepare_distributed_context
    path, dissolved into GSPMD placement)."""
    import jax

    import paddle_tpu.distributed.mesh as mesh_mod
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    try:
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        model = Model(net)
        model.prepare(optimizer=optim.Adam(learning_rate=0.01,
                                           parameters=net.parameters()),
                      loss=nn.CrossEntropyLoss())
        # params were placed onto the active mesh
        w = net[0].weight._value
        assert w.sharding.mesh.size == 8 or w.sharding.is_fully_replicated
        x = np.random.RandomState(0).rand(32, 8).astype("float32")
        y = np.random.RandomState(0).randint(0, 4, (32, 1)).astype("int64")
        first = model.train_batch([x], [y])
        for _ in range(10):
            last = model.train_batch([x], [y])
        assert float(np.asarray(last).reshape(-1)[0]) < \
            float(np.asarray(first).reshape(-1)[0])
    finally:
        mesh_mod._current[0] = None
        fleet._fleet_state.update(initialized=False, strategy=None,
                                  hcg=None, role_maker=None)


def test_prepare_ignores_ambient_mesh_and_sanitizes_specs():
    """(a) An ambient mesh WITHOUT fleet.init must not reshard the model;
    (b) with fleet.init on a data-only mesh, TP dist_specs naming absent
    axes sanitize instead of crashing."""
    import paddle_tpu.distributed.mesh as mesh_mod
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import fleet

    try:
        # (a) ambient mesh, no fleet.init
        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
        net = nn.Linear(4, 4)
        before = net.weight._value.sharding
        Model(net).prepare(optimizer=optim.SGD(
            parameters=net.parameters()), loss=nn.CrossEntropyLoss())
        assert net.weight._value.sharding == before  # untouched

        # (b) fleet.init + a param spec naming an axis this mesh lacks
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                                   "pp_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        net2 = nn.Linear(4, 4)
        net2.weight.dist_spec = P(None, "bogus_axis")
        Model(net2).prepare(optimizer=optim.SGD(
            parameters=net2.parameters()), loss=nn.CrossEntropyLoss())
        assert net2.weight._value.sharding.mesh.size == 8  # placed, no crash
    finally:
        mesh_mod._current[0] = None
        fleet._fleet_state.update(initialized=False, strategy=None,
                                  hcg=None, role_maker=None)


def test_random_split_generator_advances_between_calls():
    """Repeated splits with one Generator must draw DIFFERENT
    permutations (the stream advances, reference/torch semantics);
    re-seeding restores determinism (ADVICE r4)."""
    from paddle_tpu.framework.random import Generator

    g = Generator(123)
    a1, _ = random_split(ToyDataset(12), [9, 3], generator=g)
    a2, _ = random_split(ToyDataset(12), [9, 3], generator=g)
    assert a1.indices != a2.indices

    g.manual_seed(123)
    b1, _ = random_split(ToyDataset(12), [9, 3], generator=g)
    assert b1.indices == a1.indices


def test_random_split_set_state_restores_determinism():
    from paddle_tpu.framework.random import Generator

    g = Generator(9)
    saved = g.get_state()
    a1, _ = random_split(ToyDataset(12), [9, 3], generator=g)
    g.set_state(saved)
    b1, _ = random_split(ToyDataset(12), [9, 3], generator=g)
    assert a1.indices == b1.indices

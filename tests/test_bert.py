"""BERT model family (models/bert.py) — BASELINE config 3.

Reference precedent: BertModel/BertForPretraining over the in-repo
nn.TransformerEncoder, trained via fleet + AMP.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    BertForPretraining, BertPretrainingCriterion, bert_presets,
)


def _batch(cfg, b=4, s=16, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, cfg.vocab_size, (b, s))
    mlm_labels = np.where(rs.rand(b, s) < 0.15,
                          rs.randint(0, cfg.vocab_size, (b, s)), -1)
    nsp = rs.randint(0, 2, (b,))
    return (paddle.to_tensor(ids, dtype="int64"),
            paddle.to_tensor(mlm_labels, dtype="int64"),
            paddle.to_tensor(nsp, dtype="int64"))


def test_forward_shapes():
    cfg = bert_presets("bert-test")
    model = BertForPretraining(cfg)
    ids, mlm, nsp = _batch(cfg)
    logits, nsp_logits = model(ids)
    assert tuple(logits.shape) == (4, 16, cfg.vocab_size)
    assert tuple(nsp_logits.shape) == (4, 2)


def test_pretraining_loss_descends():
    cfg = bert_presets("bert-test")
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion()
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())

    def loss_fn(logits, nsp_logits, mlm_labels, nsp_labels):
        return crit(logits, nsp_logits, mlm_labels, nsp_labels)

    step = TrainStep(model, loss_fn, optim)
    ids, mlm, nsp = _batch(cfg)
    losses = [float(step(inputs=(ids,), labels=(mlm, nsp)))
              for _ in range(15)]
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_tensor_parallel_specs_marked():
    cfg = bert_presets("bert-test")
    model = BertForPretraining(cfg)
    blk = model.bert.encoder.layers[0]
    from jax.sharding import PartitionSpec as P

    assert blk.self_attn.q_proj.weight.dist_spec == P(None, "model")
    assert blk.self_attn.out_proj.weight.dist_spec == P("model", None)
    assert blk.linear1.weight.dist_spec == P(None, "model")
    assert blk.linear2.weight.dist_spec == P("model", None)
    assert model.bert.embeddings.word_embeddings.weight.dist_spec == \
        P("model", None)


def test_trains_under_tp_mesh():
    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh_mod.build_mesh(
        {"data": 2, "model": 2}, devices=jax.devices()[:4]))
    try:
        cfg = bert_presets("bert-test")
        paddle.seed(0)
        model = BertForPretraining(cfg)
        crit = BertPretrainingCriterion()
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = TrainStep(model, lambda lg, ns, ml, nl: crit(lg, ns, ml, nl),
                         optim)
        ids, mlm, nsp = _batch(cfg)
        losses = [float(step(inputs=(ids,), labels=(mlm, nsp)))
                  for _ in range(10)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
    finally:
        mesh_mod.set_mesh(prev)


def test_amp_bf16_training():
    """BASELINE config 3 shape: AMP bf16 pretraining step."""
    cfg = bert_presets("bert-test")
    paddle.seed(0)
    model = BertForPretraining(cfg)
    crit = BertPretrainingCriterion()
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    ids, mlm, nsp = _batch(cfg)
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        logits, nsp_logits = model(ids)
        loss = crit(logits, nsp_logits, mlm, nsp)
    loss.backward()
    optim.step()
    assert np.isfinite(float(loss))

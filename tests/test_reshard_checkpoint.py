"""Cross-mesh checkpoint conversion — the auto-parallel Resharder analog.

Reference: python/paddle/distributed/auto_parallel/reshard.py:995 converts
a checkpoint/program from one mesh/parallel config to another with
explicit slice/concat/comm plans. TPU-native: `paddle.save` gathers every
(GSPMD-sharded) array to its full value, so checkpoints are layout-free by
construction and reload onto ANY topology — the Resharder dissolves into
save-gather + placement-on-load. This test pins that contract: a ZeRO-3 +
TP sharded training run's checkpoint resumes bit-for-bit on a different
hybrid mesh and on a single device.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)


def _build(topo, level=None):
    if topo:
        n = int(np.prod(list(topo.values())))
        mesh_mod.set_mesh(mesh_mod.build_mesh(topo,
                                              devices=jax.devices()[:n]))
    else:
        mesh_mod.set_mesh(None)
    cfg = gpt_presets("gpt-test", mode="scan", use_flash_attention=False)
    model = GPTForCausalLM(cfg, seed=0)
    optim = opt.AdamW(learning_rate=1e-3, parameters=model.parameters())
    if level:
        model, optim, _ = group_sharded_parallel(model, optim, level)
    crit = GPTPretrainingCriterion()
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)
    return model, optim, step


@pytest.mark.parametrize("target_topo", [{"pipe": 2, "model": 4}, None])
def test_checkpoint_reshards_across_topologies(tmp_path, target_topo):
    prev = mesh_mod.get_mesh()
    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, 256, (8, 16)), dtype="int64")
    lbl = paddle.to_tensor(rs.randint(0, 256, (8, 16)), dtype="int64")
    try:
        # train under ZeRO-3 on dp2 x sharding2 x model2, checkpoint
        m1, o1, s1 = _build({"data": 2, "sharding": 2, "model": 2},
                            level="p_g_os")
        for _ in range(3):
            s1(inputs=(ids,), labels=(lbl,))
        paddle.save(m1.state_dict(), str(tmp_path / "m.pdparams"))
        paddle.save(o1.state_dict(), str(tmp_path / "o.pdopt"))
        ref4 = float(s1(inputs=(ids,), labels=(lbl,)))  # oracle step 4

        # resume on a DIFFERENT topology (incl. axes absent at save time)
        m2, o2, s2 = _build(target_topo)
        m2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
        o2.set_state_dict(paddle.load(str(tmp_path / "o.pdopt")))
        got4 = float(s2(inputs=(ids,), labels=(lbl,)))
        np.testing.assert_allclose(got4, ref4, rtol=1e-5, atol=1e-6)
    finally:
        mesh_mod.set_mesh(prev)

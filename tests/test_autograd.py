import numpy as np
import pytest

import paddle_tpu as paddle


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])

    def test_shared_subexpression(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        q = x * x
        z = (q + 2 * q).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])

    def test_grad_accumulation(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_matmul_grad(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4, 5).astype(np.float32)
        x = paddle.to_tensor(a, stop_gradient=False)
        w = paddle.to_tensor(b, stop_gradient=False)
        paddle.matmul(x, w).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 5)) @ b.T, rtol=1e-5)
        np.testing.assert_allclose(w.grad.numpy(), a.T @ np.ones((3, 5)), rtol=1e-5)

    def test_stop_gradient_blocks(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([2.0], stop_gradient=True)
        (x * y).sum().backward()
        assert x.grad is not None
        assert y.grad is None

    def test_detach_blocks(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x * 2
        z = y.detach() * x
        z.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_grad_context(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient
        assert y._grad_node is None

    def test_backward_twice_raises(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_retain_graph(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = (x * x).sum()
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_non_scalar_needs_grad_tensor(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 2
        with pytest.raises(RuntimeError):
            y.backward()
        y2 = x * 2
        y2.backward(paddle.to_tensor([1.0, 0.5]))
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])

    def test_multi_output_op_grad(self):
        x = paddle.to_tensor([3.0, 1.0, 2.0], stop_gradient=False)
        v, i = paddle.topk(x, 2)
        v.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])

    def test_hook(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        seen = []
        x.register_hook(lambda g: seen.append(g.numpy().copy()))
        (x * 3).sum().backward()
        assert seen and seen[0][0] == 3.0

    def test_integer_input_no_grad(self):
        emb = paddle.to_tensor(np.random.rand(10, 4).astype(np.float32), stop_gradient=False)
        ids = paddle.to_tensor([1, 3])
        out = paddle.gather(emb, ids, axis=0)
        out.sum().backward()
        g = emb.grad.numpy()
        assert g[1].sum() == 4 and g[3].sum() == 4 and g[0].sum() == 0


class TestGradAPI:
    def test_paddle_grad(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * 3
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [12.0])
        assert x.grad is None  # grad() does not accumulate

    def test_grad_outputs(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 2
        (g,) = paddle.grad([y], [x], grad_outputs=[paddle.to_tensor([1.0, 0.0])])
        np.testing.assert_allclose(g.numpy(), [2.0, 0.0])

    def test_allow_unused(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        z = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * 2
        with pytest.raises(RuntimeError):
            paddle.grad(y, [x, z])
        y2 = x * 2  # graph was freed by the failed call; rebuild
        gx, gz = paddle.grad(y2, [x, z], allow_unused=True)
        assert gz is None

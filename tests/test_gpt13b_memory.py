"""BASELINE config-4 feasibility: GPT-1.3B, ZeRO stage-2 + mp2, v5e-64.

VERDICT r2 next-round item 4: compile (abstractly) the full AdamW train
step of the 1.3B flagship over a virtual 64-device mesh and assert XLA's
per-device HBM estimate fits a v5e chip (16 GB). Fails if the sharding
layout regresses (e.g. moments stop sharding over 'sharding', or remat is
dropped and activations blow up).

Runs in a subprocess because the mesh needs 64 virtual devices while the
suite's conftest pins 8.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os, json
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=64")
import jax
jax.config.update("jax_platforms", "cpu")
import sys; sys.path.insert(0, %r)
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models import gpt_presets
from paddle_tpu.models.gpt import gpt_hbm_estimate

mesh = mesh_mod.build_mesh({"sharding": 32, "model": 2},
                           devices=jax.devices()[:64])
mesh_mod.set_mesh(mesh)
cfg = gpt_presets("gpt-1.3b", mode="scan", dtype="bfloat16",
                  recompute=True, use_flash_attention=False)
est = gpt_hbm_estimate(cfg, mesh, global_batch=64, seq=2048)
print("HBM_JSON:" + json.dumps(est))
""" % (REPO,)


def test_gpt13b_stage2_mp2_fits_v5e_hbm():
    proc = subprocess.run(
        [sys.executable, "-c", CHILD],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, proc.stderr[-3000:]
    est = None
    for line in proc.stdout.splitlines():
        if line.startswith("HBM_JSON:"):
            est = json.loads(line[len("HBM_JSON:"):])
    if est is None:
        pytest.skip("backend exposes no memory analysis")
    peak_gb = est["peak_hbm_bytes"] / 2**30
    # v5e: 16 GB HBM per chip; leave headroom for XLA's runtime buffers
    assert peak_gb <= 16.0, est
    # and the estimate must be non-trivial (a broken lowering that shards
    # nothing would blow past 16 GB; one that compiles nothing reports ~0)
    assert peak_gb >= 1.0, est

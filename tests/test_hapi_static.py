"""hapi Model under enable_static: the StaticGraphAdapter path."""
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def test_model_fit_static_mode():
    paddle.enable_static()
    try:
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        model = paddle.Model(net)
        model.prepare(
            optimizer=opt.Adam(learning_rate=0.01,
                               parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(),
            metrics=paddle.metric.Accuracy(),
        )
        assert model._adapter is not None
        rs = np.random.RandomState(0)
        templates = rs.randn(3, 8).astype("f4")
        ys = rs.randint(0, 3, 256)
        xs = (templates[ys] + 0.3 * rs.randn(256, 8)).astype("f4")

        from paddle_tpu.io import TensorDataset
        ds = TensorDataset([paddle.to_tensor(xs),
                            paddle.to_tensor(ys[:, None].astype("int64"))])
        model.fit(ds, epochs=4, batch_size=32, verbose=0)
        res = model.evaluate(ds, batch_size=32, verbose=0)
        acc = float(res.get("acc", res.get("accuracy", 0.0)))
        assert acc > 0.9, acc
        preds = model.predict_batch([xs[:5]])
        assert preds[0].shape == (5, 3)
    finally:
        paddle.disable_static()


def test_dygraph_mode_unaffected():
    net = nn.Linear(4, 2)
    model = paddle.Model(net)
    model.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                    parameters=net.parameters()),
                  loss=nn.MSELoss())
    assert model._adapter is None
    out = model.train_batch([np.ones((2, 4), np.float32)],
                            [np.zeros((2, 2), np.float32)])
    assert np.isfinite(out[0])  # no metrics → [loss]


def test_train_from_dataset(tmp_path):
    """Executor.train_from_dataset over a fleet InMemoryDataset (reference:
    the Trainer/DeviceWorker/DataFeed ingestion path)."""
    import paddle_tpu.static as static
    from paddle_tpu.distributed import fleet

    rs = np.random.RandomState(0)
    true_w = rs.randn(4).astype("f4")
    lines = []
    for _ in range(200):
        x = rs.randn(4)
        y = float(x @ true_w)
        lines.append(" ".join(f"{v:.6f}" for v in [*x, y]))
    p = tmp_path / "data.txt"
    p.write_text("\n".join(lines))

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            # dataset yields one row of 5 floats → two feeds via parse_fn
            x = static.data("x", (None, 4), "float32")
            label = static.data("label", (None, 1), "float32")
            pred = static.nn.fc(x, size=1)
            loss = ((pred - label) ** 2).mean()
            sgd = opt.SGD(learning_rate=0.05)
            sgd.minimize(loss)
        exe = static.Executor()
        exe.run(startup)

        ds = fleet.InMemoryDataset()
        ds.init(batch_size=20,
                parse_fn=lambda line: [
                    np.asarray([float(t) for t in line.split()[:4]],
                               np.float32),
                    np.asarray([float(line.split()[4])], np.float32)])
        ds.set_filelist([str(p)])
        ds.load_into_memory()

        first = exe.run(main, feed={
            "x": np.stack([r[0] for r in ds._records[:20]]),
            "label": np.stack([r[1] for r in ds._records[:20]])},
            fetch_list=[loss])[0]
        for _ in range(5):
            last = exe.train_from_dataset(main, ds, fetch_list=[loss])
        assert float(last[0]) < float(first) * 0.2
    finally:
        paddle.disable_static()

"""K001 (analysis/kernel_gates.py): pallas interpret-mode gate rule.

Fixture-driven positives/negatives plus the live-repo-clean check other
rule families pin in test_static_analysis.py.
"""
import paddle_tpu.analysis as analysis
from paddle_tpu.analysis.kernel_gates import KernelGateChecker


def _run(src, path="paddle_tpu/ops/fake_kernel.py"):
    a = analysis.Analysis([KernelGateChecker()])
    return a.run_sources({path: src})


GOOD = '''
from jax.experimental import pallas as pl

def _interpret():
    from ..framework.target import target_platform
    return target_platform() != "tpu"

def run(x):
    return pl.pallas_call(k, out_shape=o, interpret=_interpret())(x)
'''

GOOD_INLINE = '''
from jax.experimental import pallas as pl
from ..framework.target import target_platform

def run(x):
    return pl.pallas_call(
        k, out_shape=o, interpret=target_platform() != "tpu")(x)
'''

GOOD_TWO_HOPS = '''
from jax.experimental import pallas as pl

def _target():
    from ..framework.target import target_platform
    return target_platform()

def _interpret():
    return _target() != "tpu"

def run(x):
    return pl.pallas_call(k, out_shape=o, interpret=_interpret())(x)
'''

LITERAL_TRUE = '''
from jax.experimental import pallas as pl

def run(x):
    return pl.pallas_call(k, out_shape=o, interpret=True)(x)
'''

LITERAL_FALSE = '''
from jax.experimental import pallas as pl

def run(x):
    return pl.pallas_call(k, out_shape=o, interpret=False)(x)
'''

MISSING_KWARG = '''
from jax.experimental import pallas as pl

def run(x):
    return pl.pallas_call(k, out_shape=o)(x)
'''

UNRESOLVABLE = '''
from jax.experimental import pallas as pl

def _interpret():
    import os
    return os.environ.get("FORCE_INTERPRET") == "1"

def run(x):
    return pl.pallas_call(k, out_shape=o, interpret=_interpret())(x)
'''

SPLAT = '''
from jax.experimental import pallas as pl

def run(x, **kw):
    return pl.pallas_call(k, out_shape=o, **kw)(x)
'''


def _k001(findings):
    return [f for f in findings if f.rule == "K001"]


def test_seam_resolved_sites_clean():
    assert _k001(_run(GOOD)) == []
    assert _k001(_run(GOOD_INLINE)) == []
    assert _k001(_run(GOOD_TWO_HOPS)) == []


def test_literal_true_flagged():
    fs = _k001(_run(LITERAL_TRUE))
    assert len(fs) == 1 and "literal interpret=True" in fs[0].message


def test_literal_false_flagged():
    fs = _k001(_run(LITERAL_FALSE))
    assert len(fs) == 1 and "literal interpret=False" in fs[0].message


def test_missing_kwarg_flagged():
    fs = _k001(_run(MISSING_KWARG))
    assert len(fs) == 1 and "without interpret=" in fs[0].message


def test_unresolvable_helper_flagged():
    fs = _k001(_run(UNRESOLVABLE))
    assert len(fs) == 1 and "target_platform" in fs[0].message


def test_kwarg_splat_not_flagged():
    assert _k001(_run(SPLAT)) == []


def test_waiver_suppresses():
    waived = LITERAL_TRUE.replace(
        "interpret=True)(x)",
        "interpret=True)(x)  # lint-ok: K001 fixture")
    assert _k001(_run(waived)) == []


def test_rule_registered():
    assert "K001" in analysis.RULES
    inv, why = analysis.RULES["K001"]
    assert "target_platform" in inv


def test_k001_runs_in_default_checkers():
    """K001 rides every default analysis run — so the committed-baseline
    gate (tests/test_static_analysis.py repo-clean + tools/check_static)
    proves the live repo clean without a second full pass here."""
    names = [type(c).__name__ for c in analysis.default_checkers()]
    assert "KernelGateChecker" in names

"""Launcher / elastic / role-maker tests.

Reference analogs: test_fleet_launch_*.sh (CLI), test_fleet_elastic_manager
(fake-env unit tests), test_fleet_rolemaker*.py.
"""
import os
import subprocess
import sys
import textwrap
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# importing paddle_tpu touches jax; pin the CPU backend first so the CLI works
# even when the TPU tunnel is down (the launcher itself never needs a device)
_LAUNCH_SHIM = (
    "import jax; jax.config.update('jax_platforms', 'cpu'); "
    "import sys; "
    "from paddle_tpu.distributed.launch.main import launch, _parse_args; "
    "main = lambda argv: sys.exit(launch(_parse_args(argv)) or 0); "
)


class TestLaunchCLI:
    def test_single_proc_launch_runs_script(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(textwrap.dedent("""
            import os
            print("RANK", os.environ.get("PADDLE_TRAINER_ID"))
            print("WORLD", os.environ.get("PADDLE_TRAINERS_NUM"))
            print("EPS", os.environ.get("PADDLE_TRAINER_ENDPOINTS"))
        """))
        out = subprocess.run(
            [sys.executable, "-c", _LAUNCH_SHIM + f"main(['--log_dir', "
             f"{str(tmp_path / 'log')!r}, {str(script)!r}])"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "RANK 0" in out.stdout
        assert "WORLD 1" in out.stdout

    def test_multi_proc_env_protocol(self, tmp_path):
        script = tmp_path / "probe.py"
        script.write_text(textwrap.dedent("""
            import os, sys
            rid = os.environ["PADDLE_TRAINER_ID"]
            eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
            cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
            assert eps[int(rid)] == cur, (rid, eps, cur)
            with open(os.path.join(os.environ["OUTDIR"], f"ok.{rid}"), "w") as f:
                f.write(cur)
        """))
        out = subprocess.run(
            [sys.executable, "-c", _LAUNCH_SHIM + f"main(['--nproc_per_node',"
             f" '2', '--log_dir', {str(tmp_path / 'log')!r}, "
             f"{str(script)!r}])"],
            capture_output=True, text=True, cwd=REPO, timeout=180,
            env=dict(os.environ, OUTDIR=str(tmp_path)))
        assert out.returncode == 0, out.stderr
        assert (tmp_path / "ok.0").exists() and (tmp_path / "ok.1").exists()

    def test_watchdog_propagates_failure(self, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(3)\n")
        out = subprocess.run(
            [sys.executable, "-c", _LAUNCH_SHIM + f"main(['--log_dir', "
             f"{str(tmp_path / 'log')!r}, {str(script)!r}])"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert out.returncode == 3


class TestElasticManager:
    def test_membership_and_restart_detection(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, ElasticStatus, LocalKVStore,
        )

        store = LocalKVStore()
        m1 = ElasticManager("node1", "1:3", store=store, ttl=5)
        m2 = ElasticManager("node2", "1:3", store=store, ttl=5)
        m1.register()
        assert m1.members() == ["node1"]
        assert m1.pod_status() == ElasticStatus.COMPLETED

        m2.register()  # scale up
        assert set(m1.members()) == {"node1", "node2"}
        assert m1.pod_status() == ElasticStatus.RESTART
        assert m1.pod_status() == ElasticStatus.COMPLETED  # stabilized
        assert m1.endpoints() == ["node1:8091", "node2:8091"]

        store.delete(m2.prefix + "/node2")  # scale down
        assert m1.pod_status() == ElasticStatus.RESTART

    def test_ttl_expiry_drops_dead_node(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, LocalKVStore,
        )

        store = LocalKVStore()
        m1 = ElasticManager("a", 1, store=store, ttl=1)
        m1.register()
        store.put(m1.prefix + "/dead", "dead", ttl=0.2)
        assert set(m1.members()) == {"a", "dead"}
        time.sleep(0.3)
        assert m1.members() == ["a"]

    def test_hold_below_min(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, ElasticStatus, LocalKVStore,
        )

        m = ElasticManager("x", "2:4", store=LocalKVStore())
        m.register()
        assert m.pod_status() == ElasticStatus.HOLD
        assert not m.wait_for_np(timeout=0.3)

    def test_heartbeat_keeps_alive(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, LocalKVStore,
        )

        store = LocalKVStore()
        m = ElasticManager("hb", 1, store=store, ttl=1,
                           heartbeat_interval=0.2)
        m.start_heartbeat()
        try:
            time.sleep(1.5)  # outlives the ttl only via heartbeat refresh
            assert m.members() == ["hb"]
        finally:
            m.stop()
        assert m.members() == []


class TestRoleMaker:
    def test_paddlecloud_trainer_env(self, monkeypatch):
        from paddle_tpu.distributed.fleet.base.role_maker import (
            PaddleCloudRoleMaker,
        )

        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "h0:1,h1:1,h2:1,h3:1")
        rm = PaddleCloudRoleMaker(is_collective=True)
        assert rm.is_worker() and not rm.is_server()
        assert rm.worker_index() == 2
        assert rm.worker_num() == 4
        assert not rm.is_first_worker()
        assert rm.get_trainer_endpoints() == ["h0:1", "h1:1", "h2:1", "h3:1"]

    def test_paddlecloud_pserver_env(self, monkeypatch):
        from paddle_tpu.distributed.fleet.base.role_maker import (
            PaddleCloudRoleMaker,
        )

        monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
        monkeypatch.setenv("PADDLE_PSERVER_ID", "1")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "s0:2,s1:2")
        rm = PaddleCloudRoleMaker()
        assert rm.is_server()
        assert rm.server_index() == 1
        assert rm.server_num() == 2

    def test_user_defined(self):
        from paddle_tpu.distributed.fleet.base.role_maker import (
            Role, UserDefinedRoleMaker,
        )

        rm = UserDefinedRoleMaker(current_id=0, role=Role.WORKER,
                                  worker_num=2)
        assert rm.is_first_worker()
        assert rm.worker_num() == 2


PS_SCRIPT = r"""'''PS-mode script: role from TRAINING_ROLE env (reference pattern).'''
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")

import numpy as np
from paddle_tpu.distributed.ps import PsClient, PsServer, TheOnePSRuntime

role = os.environ["TRAINING_ROLE"]
if role == "PSERVER":
    port = int(os.environ["PADDLE_PORT"])
    srv = PsServer(host="127.0.0.1", port=port).start(background=False)
else:
    import time
    eps = os.environ["PADDLE_PSERVERS_IP_PORT_LIST"].split(",")
    # wait for servers: each PSERVER child imports jax before binding, which
    # can take >30s on a loaded 1-core box, so the window is generous
    cli = None
    deadline = time.time() + 120.0
    while time.time() < deadline:
        try:
            cli = PsClient(eps)
            for i in range(len(eps)):
                cli._call(i, "ping")
            break
        except OSError:
            if cli is not None:
                cli.close()
            cli = None
            time.sleep(0.3)
    if cli is None:
        raise SystemExit("trainer: servers never came up within 120s")
    cli.create_table(0, dim=4)
    rows = cli.pull(0, np.array([1, 2, 3], np.uint64))
    cli.push(0, np.array([1, 2, 3], np.uint64), np.ones((3, 4), np.float32), lr=0.1)
    print("TRAINER_OK", rows.shape)
    cli.close()
"""


def test_launch_ps_mode(tmp_path):
    """--run_mode ps spawns PSERVER + TRAINER processes wired with the
    PADDLE_PSERVERS_IP_PORT_LIST / TRAINING_ROLE protocol (reference
    launch_ps)."""
    from paddle_tpu.distributed.launch.main import launch, _parse_args

    script = tmp_path / "ps_script.py"
    script.write_text(PS_SCRIPT)
    args = _parse_args(["--run_mode", "ps", "--server_num", "2",
                        "--worker_num", "2",
                        "--log_dir", str(tmp_path / "logs"), str(script)])
    ret = launch(args)
    assert ret == 0
    logs = list((tmp_path / "logs").glob("trainerlog.*"))
    assert logs and any("TRAINER_OK" in p.read_text() for p in logs)


HETER_SCRIPT = r"""'''Heterogeneous-PS script: CPU trainer pushes sparse, the
HETER_TRAINER device worker trains the dense half (reference heter PS).'''
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")

import numpy as np
import time
from paddle_tpu.distributed.ps import PsClient, PsServer
from paddle_tpu.distributed import fleet

role = os.environ["TRAINING_ROLE"]
if role == "PSERVER":
    port = int(os.environ["PADDLE_PORT"])
    PsServer(host="127.0.0.1", port=port).start(background=False)
    raise SystemExit(0)

eps = os.environ["PADDLE_PSERVERS_IP_PORT_LIST"].split(",")
cli = None
deadline = time.time() + 120.0
while time.time() < deadline:
    try:
        cli = PsClient(eps)
        for i in range(len(eps)):
            cli._call(i, "ping")
        break
    except OSError:
        if cli is not None:
            cli.close()
        cli = None
        time.sleep(0.3)
if cli is None:
    raise SystemExit("servers never came up")

fleet.init()
if role == "HETER_TRAINER":
    assert fleet.is_heter_worker(), "role maker must see HETER_TRAINER"
    # bind the advertised endpoint: CPU trainers reach this device worker's
    # dense tables through it (reference heter_server.cc pattern)
    srv = fleet.init_heter_worker(background=True)
    own = PsClient([f"127.0.0.1:{srv.port}"])
    own.create_dense_table(1, shape=(4, 2))
    own.push_dense(1, np.full((4, 2), -1.0, np.float32), lr=1.0)  # w := +1
    own.close()
    # park until the trainer signals done via PS sparse key 99 (table 0 is
    # created by the trainer, so tolerate its absence early on)
    deadline = time.time() + 90.0
    signaled = False
    while time.time() < deadline:
        try:
            rows = cli.pull(0, np.array([99], np.uint64),
                            create_if_missing=True)
            if abs(float(rows.sum())) > 0.5:
                signaled = True
                break
        except (OSError, RuntimeError, KeyError):
            pass
        time.sleep(0.3)
    if not signaled:
        raise SystemExit("trainer-done signal (key 99) never arrived")
    print("HETER_OK")
else:
    assert not fleet.is_heter_worker()
    # sparse half on the CPU trainer
    cli.create_table(0, dim=4)
    cli.push(0, np.array([7, 8], np.uint64), np.ones((2, 4), np.float32),
             lr=0.1)
    rows = cli.pull(0, np.array([7, 8], np.uint64))
    # dense half lives on the heter worker: dial its advertised endpoint
    heter_eps = os.environ["PADDLE_HETER_TRAINER_IP_PORT_LIST"].split(",")
    hcli = None
    deadline = time.time() + 90.0
    while time.time() < deadline:
        try:
            hcli = PsClient(heter_eps)
            hcli._call(0, "ping")
            hcli.pull_dense(1)  # table exists once the worker published it
            break
        except (OSError, KeyError, RuntimeError):
            if hcli is not None:
                hcli.close()
            hcli = None
            time.sleep(0.3)
    assert hcli is not None, "heter worker endpoint never came up"
    w = hcli.pull_dense(1)
    assert abs(float(w.mean()) - 1.0) < 1e-5, w
    hcli.close()
    # signal the heter worker we are done (push moves key 99 away from 0)
    cli.push(0, np.array([99], np.uint64), np.ones((1, 4), np.float32),
             lr=1.0)
    print("TRAINER_OK", rows.shape, w.shape)
cli.close()
"""


def test_launch_heter_ps_mode(tmp_path):
    """--heter_worker_num spawns HETER_TRAINER processes wired with
    PADDLE_HETER_TRAINER_IP_PORT_LIST (reference: heter PS launch path)."""
    from paddle_tpu.distributed.launch.main import launch, _parse_args

    script = tmp_path / "heter_script.py"
    script.write_text(HETER_SCRIPT)
    args = _parse_args(["--run_mode", "ps", "--server_num", "1",
                        "--worker_num", "1", "--heter_worker_num", "1",
                        "--log_dir", str(tmp_path / "logs"), str(script)])
    ret = launch(args)
    assert ret == 0
    logs = tmp_path / "logs"
    assert any("TRAINER_OK" in p.read_text()
               for p in logs.glob("trainerlog.*"))
    assert any("HETER_OK" in p.read_text()
               for p in logs.glob("heter_trainerlog.*"))


import pytest

from paddle_tpu.distributed.fleet.elastic import ElasticManager, LocalKVStore


class FlakyKVStore(LocalKVStore):
    """Failure-injecting fake etcd client: every store op raises while
    `failing` is set (a network partition / etcd leader election)."""

    def __init__(self):
        super().__init__()
        self.failing = False
        self.ops = 0

    def _maybe_fail(self):
        self.ops += 1
        if self.failing:
            raise ConnectionError("injected etcd outage")

    def put(self, key, value, ttl=None):
        self._maybe_fail()
        super().put(key, value, ttl)

    def refresh(self, key, ttl):
        self._maybe_fail()
        super().refresh(key, ttl)

    def get_prefix(self, prefix):
        self._maybe_fail()
        return super().get_prefix(prefix)

    def delete(self, key):
        self._maybe_fail()
        super().delete(key)


class TestElasticFailureInjection:
    def test_heartbeat_survives_store_outage(self):
        """A transient store failure must not kill the heartbeat thread:
        within TTL the node never drops; after recovery it re-registers."""
        store = FlakyKVStore()
        m = ElasticManager("node-a", "1:4", store=store, ttl=2.0,
                          heartbeat_interval=0.05)
        m.start_heartbeat()
        try:
            assert m.members() == ["node-a"]
            store.failing = True
            time.sleep(0.3)          # several failed beats, < TTL
            store.failing = False
            time.sleep(0.2)          # recovery beats re-put the lease
            assert m.members() == ["node-a"]
            assert m._hb_thread.is_alive()
        finally:
            m.stop()

    def test_node_rejoins_after_outage_longer_than_ttl(self):
        store = FlakyKVStore()
        m = ElasticManager("node-a", "1:4", store=store, ttl=0.2,
                          heartbeat_interval=0.05)
        m.start_heartbeat()
        try:
            store.failing = True
            time.sleep(0.5)          # lease expires mid-outage
            with pytest.raises(ConnectionError):
                store.get_prefix(m.prefix)
            store.failing = False
            time.sleep(0.2)          # heartbeat re-PUTs (not refresh)
            assert m.members() == ["node-a"]
        finally:
            m.stop()


RESUME_SCRIPT = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.checkpoint.auto_checkpoint import TrainEpochRange

model = nn.Linear(4, 4)
optim = opt.SGD(learning_rate=0.1, parameters=model.parameters())
r = TrainEpochRange(5, name="resume_e2e", save_dir={save_dir!r},
                    state={{"model": model, "epoch_log": []}})
log_path = {log_path!r}
for epoch in r:   # iteration checkpoints after each completed epoch
    if epoch == 2 and not os.path.exists(log_path + ".died"):
        open(log_path + ".died", "w").write("x")
        os._exit(17)   # crash DURING epoch 2; epoch 1 is checkpointed
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = model(x).sum()
    loss.backward(); optim.step(); optim.clear_grad()
    with open(log_path, "a") as f:
        f.write(f"epoch {{epoch}} restored={{r.restored_from is not None}}\n")
print("DONE")
"""


def test_kill_relaunch_resume_e2e(tmp_path):
    """VERDICT r3 item 10: worker dies mid-training under watch_local_procs,
    the launcher relaunches it, and TrainEpochRange resumes at the right
    epoch instead of restarting from zero."""
    import subprocess

    from paddle_tpu.distributed.launch.main import watch_local_procs

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log_path = str(tmp_path / "epochs.log")
    script = tmp_path / "train.py"
    script.write_text(RESUME_SCRIPT.format(
        repo=repo, save_dir=str(tmp_path / "ckpt"), log_path=log_path))

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def launch():
        # output is unasserted; piping it unread could deadlock the child
        # on a full pipe buffer while the watchdog polls forever
        return subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    # first life: crashes after epoch 1's checkpoint; watchdog reports it
    rc = watch_local_procs([launch()])
    assert rc == 17
    # elastic relaunch: resumes at epoch 2
    rc = watch_local_procs([launch()])
    assert rc == 0

    lines = open(log_path).read().strip().splitlines()
    epochs = [int(ln.split()[1]) for ln in lines]
    assert epochs == [0, 1, 2, 3, 4], lines
    # the second life really restored from the epoch-1 checkpoint
    assert "epoch 2 restored=True" in lines[2]


class _FakeProc:
    """Minimal Popen stand-in for controller-loop tests."""

    def __init__(self, rc=None):
        self.rc = rc

    def poll(self):
        return self.rc

    def terminate(self):
        if self.rc is None:
            self.rc = -15

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class TestElasticResumeHook:
    """The RESTART path invokes the resume hook (robustness wiring): on a
    scale event or worker crash the controller fires on_restart(info) after
    terminating the old life and before the relaunch, so job-level state
    (async checkpoint flush, alerts) can run; the relaunched workers then
    resume via TrainEpochRange / CheckpointManager.load_latest."""

    def test_hook_fires_on_scale_event(self):
        import threading

        from paddle_tpu.distributed.fleet.elastic import (
            ElasticController, ElasticManager, LocalKVStore,
        )

        store = LocalKVStore()
        m = ElasticManager("node-a", "1:3", store=store, ttl=30,
                           heartbeat_interval=0.05)
        store.put(m.prefix + "/node-b", "node-b")  # a peer, no TTL
        events, lives = [], []

        def launch(eps):
            lives.append(list(eps))
            if len(lives) == 1:
                # first life runs until node-b "dies" 0.1s in
                threading.Timer(
                    0.1, lambda: store.delete(m.prefix + "/node-b")).start()
                return [_FakeProc(None)]
            return [_FakeProc(0)]  # relaunched life completes cleanly

        ctl = ElasticController(m, launch, poll_interval=0.05,
                                on_restart=events.append)
        rc = ctl.run(np_timeout=5)
        assert rc == 0
        assert len(lives) == 2
        assert len(lives[0]) == 2 and len(lives[1]) == 1  # endpoints rewritten
        assert events and events[0]["reason"] == "scale"
        assert events[0]["restarts"] == 1
        assert events[0]["endpoints"] == lives[0]
        assert ctl.restart_events == events

    def test_hook_fires_on_worker_crash_and_failure_is_tolerated(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticController, ElasticManager, LocalKVStore,
        )

        m = ElasticManager("solo", "1:1", store=LocalKVStore(), ttl=30,
                           heartbeat_interval=0.05)
        events, lives = [], []

        def bad_hook(info):
            events.append(info)
            raise RuntimeError("hook exploded")  # must not kill the relaunch

        def launch(eps):
            lives.append(list(eps))
            return [_FakeProc(7 if len(lives) == 1 else 0)]

        ctl = ElasticController(m, launch, poll_interval=0.02,
                                on_restart=bad_hook)
        assert ctl.run(np_timeout=5) == 0
        assert len(lives) == 2
        assert events[0]["reason"] == "crash" and events[0]["restarts"] == 1


class TestRestartBudgeting:
    """Scale-event relaunches are elasticity working as designed and must
    NOT consume `max_restarts` (the crash budget) — a job that scaled N
    times would otherwise die on its first real crash. Crash restarts and
    scale relaunches are tracked separately."""

    def _manager(self, np_range="1:9"):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticManager, LocalKVStore,
        )

        store = LocalKVStore()
        m = ElasticManager("node-a", np_range, store=store, ttl=30,
                           heartbeat_interval=0.05)
        return m, store

    def test_scale_events_do_not_consume_crash_budget(self):
        import threading

        from paddle_tpu.distributed.fleet.elastic import ElasticController

        m, store = self._manager()
        lives = []
        scale_lives = 3   # > max_restarts below

        def launch(eps):
            lives.append(list(eps))
            n = len(lives)
            if n <= scale_lives:
                # each of these lives ends via a MEMBERSHIP change, not a
                # crash: a peer joins (or leaves) 50ms in
                key = f"{m.prefix}/peer-{n}"
                threading.Timer(0.05, lambda k=key: store.put(k, k)).start()
                return [_FakeProc(None)]
            if n == scale_lives + 1:
                return [_FakeProc(5)]    # ONE real crash after the scaling
            return [_FakeProc(0)]        # relaunch completes cleanly

        ctl = ElasticController(m, launch, poll_interval=0.02,
                                max_restarts=1)
        assert ctl.run(np_timeout=5) == 0
        # 3 scale relaunches + 1 crash restart, and the single-crash
        # budget (max_restarts=1) still allowed the crash relaunch
        assert ctl.scale_relaunches == scale_lives
        assert ctl.crash_restarts == 1
        assert len(lives) == scale_lives + 2
        reasons = [e["reason"] for e in ctl.restart_events]
        assert reasons == ["scale"] * scale_lives + ["crash"]
        # per-kind counters: each kind numbers its own events from 1
        assert [e["restarts"] for e in ctl.restart_events] == [1, 2, 3, 1]

    def test_crash_budget_still_enforced(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticController

        m, _ = self._manager("1:1")
        lives = []

        def launch(eps):
            lives.append(list(eps))
            return [_FakeProc(9)]   # every life crashes

        ctl = ElasticController(m, launch, poll_interval=0.02,
                                max_restarts=2)
        assert ctl.run(np_timeout=5) == 9   # budget exhausted -> crash rc
        assert ctl.crash_restarts == 3      # initial + 2 budgeted retries
        assert len(lives) == 3

    def test_scale_relaunch_cap_is_independent(self):
        import threading

        from paddle_tpu.distributed.fleet.elastic import ElasticController

        m, store = self._manager()
        lives = []

        def launch(eps):
            n = len(lives)
            lives.append(list(eps))
            key = f"{m.prefix}/peer-{n}"
            threading.Timer(0.05, lambda k=key: store.put(k, k)).start()
            return [_FakeProc(None)]    # never exits; only scale events

        ctl = ElasticController(m, launch, poll_interval=0.02,
                                max_restarts=10, max_scale_relaunches=2)
        assert ctl.run(np_timeout=5) == 1
        assert ctl.scale_relaunches == 3     # the 3rd tripped the cap
        assert ctl.crash_restarts == 0


class TestFleetFs:
    """fleet.utils LocalFS client (fs.py:119 surface) — the auto-checkpoint
    storage backend; HDFSClient stubs honestly (no hadoop runtime)."""

    def test_localfs_surface(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import HDFSClient, LocalFS

        fs = LocalFS()
        d = str(tmp_path / "a/b")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = str(tmp_path / "a/x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        with pytest.raises(FileExistsError):
            fs.touch(f, exist_ok=False)
        dirs, files = fs.ls_dir(str(tmp_path / "a"))
        assert dirs == ["b"] and files == ["x.txt"]
        fs.upload(f, str(tmp_path / "a/y.txt"))
        fs.mv(str(tmp_path / "a/y.txt"), str(tmp_path / "a/z.txt"))
        assert fs.list_dirs(str(tmp_path / "a")) == ["b"]
        fs.delete(d)
        assert not fs.is_exist(d)
        with pytest.raises(NotImplementedError):
            HDFSClient()

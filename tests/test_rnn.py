"""RNN family (nn/layer/rnn.py): cells + scanned LSTM/GRU/SimpleRNN.

Reference: python/paddle/nn/layer/rnn.py; numerics validated against
torch.nn.LSTM/GRU/RNN with copied weights.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _copy_from_torch(pl, tl, layers, dirs, mode):
    import torch

    for li in range(layers):
        for d in range(dirs):
            sfx = f"_l{li}" + ("_reverse" if d else "")
            for name in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
                tp = getattr(tl, f"{name}{sfx.replace('_reverse','_reverse') if d else '_l'+str(li)}", None)
                tp = getattr(tl, f"{name}_l{li}" + ("_reverse" if d else ""))
                getattr(pl, name + sfx).set_value(
                    tp.detach().numpy().astype("float32"))


@pytest.mark.parametrize("mode", ["LSTM", "GRU", "RNN"])
def test_matches_torch(mode):
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    B, T, I, H, L = 2, 5, 3, 4, 2
    x = rs.randn(B, T, I).astype("f4")

    t_cls = {"LSTM": torch.nn.LSTM, "GRU": torch.nn.GRU,
             "RNN": torch.nn.RNN}[mode]
    tl = t_cls(I, H, num_layers=L, batch_first=True, bidirectional=True)
    p_cls = {"LSTM": nn.LSTM, "GRU": nn.GRU, "RNN": nn.SimpleRNN}[mode]
    pl = p_cls(I, H, num_layers=L, direction="bidirect")
    _copy_from_torch(pl, tl, L, 2, mode)

    with torch.no_grad():
        t_out, t_state = tl(torch.tensor(x))
    p_out, p_state = pl(paddle.to_tensor(x))
    np.testing.assert_allclose(p_out.numpy(), t_out.numpy(), rtol=1e-4,
                               atol=1e-5)
    if mode == "LSTM":
        np.testing.assert_allclose(p_state[0].numpy(), t_state[0].numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(p_state[1].numpy(), t_state[1].numpy(),
                                   rtol=1e-4, atol=1e-5)
    else:
        np.testing.assert_allclose(p_state.numpy(), t_state.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_sequence_length_masks_states():
    rs = np.random.RandomState(1)
    lstm = nn.LSTM(3, 4)
    x = paddle.to_tensor(rs.randn(2, 6, 3).astype("f4"))
    lens = paddle.to_tensor(np.array([6, 3]))
    y, (h, c) = lstm(x, sequence_length=lens)
    y_np = y.numpy()
    # sample 1 frozen after t=3: padded outputs zero
    np.testing.assert_allclose(y_np[1, 3:], 0.0)
    # final state equals the t=3 output for sample 1
    np.testing.assert_allclose(h.numpy()[0, 1], y_np[1, 2], rtol=1e-5)


def test_cells_and_birnn():
    rs = np.random.RandomState(2)
    cell_fw = nn.LSTMCell(3, 4)
    cell_bw = nn.LSTMCell(3, 4)
    x = paddle.to_tensor(rs.randn(2, 5, 3).astype("f4"))
    bi = nn.BiRNN(cell_fw, cell_bw)
    y, _ = bi(x)
    assert tuple(y.shape) == (2, 5, 8)

    gc = nn.GRUCell(3, 4)
    out, h = gc(paddle.to_tensor(rs.randn(2, 3).astype("f4")))
    assert tuple(out.shape) == (2, 4)


def test_lstm_trains():
    import paddle_tpu.optimizer as opt

    rs = np.random.RandomState(3)
    lstm = nn.LSTM(3, 8)
    head = nn.Linear(8, 1)
    params = list(lstm.parameters()) + list(head.parameters())
    o = opt.Adam(learning_rate=0.01, parameters=params)
    x = paddle.to_tensor(rs.randn(8, 5, 3).astype("f4"))
    y = paddle.to_tensor(rs.randn(8, 1).astype("f4"))
    losses = []
    for _ in range(10):
        out, (h, c) = lstm(x)
        pred = head(out[:, -1])
        loss = ((pred - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_beam_search_decoder_greedy_equivalence():
    """beam_size=1 must equal greedy argmax decoding."""
    from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode

    rs = np.random.RandomState(0)
    V, H = 7, 4
    cell = nn.GRUCell(H, H)
    emb_w = paddle.to_tensor(rs.randn(V, H).astype("f4"))
    out_w = paddle.to_tensor(rs.randn(H, V).astype("f4"))

    emb = lambda ids: paddle.to_tensor(
        emb_w.numpy()[np.asarray(ids.numpy(), np.int64)])
    proj = lambda h: h @ paddle.to_tensor(out_w.numpy())

    dec = BeamSearchDecoder(cell, start_token=0, end_token=1, beam_size=1,
                            embedding_fn=emb, output_fn=proj)
    h0 = paddle.to_tensor(rs.randn(2, H).astype("f4"))
    out, _, lens = dynamic_decode(dec, inits=h0, max_step_num=6,
                                  return_length=True)
    assert tuple(out.shape)[0] == 2 and tuple(out.shape)[1] == 1

    # manual greedy rollout must match beam-1
    ids = np.zeros(2, np.int64)
    h = h0
    manual = []
    done = np.zeros(2, bool)
    for t in range(out.shape[2]):
        e = paddle.to_tensor(emb_w.numpy()[ids])
        o, h = cell(e, h)
        logits = (o @ paddle.to_tensor(out_w.numpy())).numpy()
        nxt = logits.argmax(-1)
        nxt = np.where(done, 1, nxt)
        manual.append(nxt)
        done |= nxt == 1
        ids = nxt
    np.testing.assert_array_equal(out.numpy()[:, 0, :],
                                  np.stack(manual, -1))


def test_beam_search_wider_beam_scores_at_least_greedy():
    from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode

    rs = np.random.RandomState(5)
    V, H = 9, 6
    cell = nn.GRUCell(H, H)
    emb_w = rs.randn(V, H).astype("f4")
    out_w = rs.randn(H, V).astype("f4")
    emb = lambda ids: paddle.to_tensor(
        emb_w[np.asarray(ids.numpy(), np.int64)])
    proj = lambda h: h @ paddle.to_tensor(out_w)

    def best_score(K):
        dec = BeamSearchDecoder(cell, 0, 1, K, embedding_fn=emb,
                                output_fn=proj)
        h0 = paddle.to_tensor(rs.randn(1, H).astype("f4") * 0 + 0.3)
        out, (states, logp, fin) = dynamic_decode(dec, inits=h0,
                                                  max_step_num=5)
        return logp.max()

    assert best_score(4) >= best_score(1) - 1e-6

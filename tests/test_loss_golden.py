"""Config-4 loss-curve golden regression guard (VERDICT r4 #10).

artifacts/gpt13b_loss_golden.json pre-registers a 200-step curve for the
reduced-width 1.3B schedule (ZeRO-2 x mp2 hybrid, AdamW + warmup-cosine
+ global-norm clip — BASELINE.md config 4's shape) with seeds and match
tolerances. This guard re-runs the first 25 steps on the suite's virtual
mesh and matches them at the same-backend tolerance, so any drift in the
model/optimizer/schedule/data stack is caught before a hardware run
would chase a stale curve.
"""
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PREFIX = 25


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # G.run() sets a 4x2 mesh; fresh_mesh restores the ambient one


def test_golden_prefix_reproduces():
    golden = json.load(open(
        os.path.join(REPO, "artifacts", "gpt13b_loss_golden.json")))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import gpt13b_loss_golden as G

    # the golden must have been generated with the tool's current config
    assert golden["config"] == G.CFG, "regenerate the golden artifact"
    assert golden["schedule"] == G.SCHED, "regenerate the golden artifact"
    assert golden["seeds"] == {"model": G.SEED_MODEL, "data": G.SEED_DATA}
    assert golden["steps"] >= 100  # a real curve, not a smoke run

    losses = G.run(PREFIX)
    want = golden["losses"][:PREFIX]
    rtol = golden["tolerances"]["per_step_rtol_f32_same_backend"]
    np.testing.assert_allclose(losses, want, rtol=rtol)
    # and the registered curve really descends toward the data's ln(4)
    # entropy floor — a flat golden can't validate a hardware run
    assert golden["summary"]["descent"] > 2.0, golden["summary"]

"""Multiprocess DataLoader (io/worker.py).

Reference capability: fluid/reader.py _DataLoaderIterMultiProcess +
imperative/data_loader.cc — worker processes so a GIL-bound __getitem__
cannot starve the input pipeline. Datasets here are module-level (spawn
pickling).
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset, IterableDataset, get_worker_info


class PidDataset(Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.array([os.getpid(), i], dtype=np.int64)


class SquareDataset(Dataset):
    def __len__(self):
        return 32

    def __getitem__(self, i):
        return np.array([i * i], dtype=np.int64)


class FailingDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.array([i])


class BusyDataset(Dataset):
    """GIL-bound CPU work per item — the case threads cannot scale."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        acc = 0
        for k in range(3_000_000):
            acc += k * k
        return np.array([i, acc % 7], dtype=np.int64)


class ShardedIterable(IterableDataset):
    def __iter__(self):
        info = get_worker_info()
        wid = info.id if info else 0
        nw = info.num_workers if info else 1
        for i in range(wid, 12, nw):
            yield np.array([i], dtype=np.int64)


def test_workers_are_real_processes():
    dl = DataLoader(PidDataset(), batch_size=4, num_workers=2)
    pids = set()
    for batch in dl:
        pids.update(int(p) for p in batch.numpy()[:, 0])
    assert os.getpid() not in pids  # fetched OUTSIDE the parent process
    assert len(pids) >= 1  # (on a multi-core box both workers participate;
    # this 1-core CI machine may drain everything through one)


def test_order_is_deterministic():
    dl = DataLoader(SquareDataset(), batch_size=4, num_workers=3)
    seen = np.concatenate([b.numpy()[:, 0] for b in dl])
    np.testing.assert_array_equal(seen, np.arange(32) ** 2)


def test_buffer_reader_stages_device_batches(monkeypatch):
    # use_buffer_reader=True (default; reference use_double_buffer): the
    # device put runs on a producer/stager THREAD so transfer overlaps
    # compute; with the flag off it runs on the consumer thread. Observe
    # the distinguishing behavior by recording which thread converts.
    import threading

    import paddle_tpu.io as io_mod
    from paddle_tpu.framework.tensor import Tensor

    real = io_mod._to_tensors
    seen_threads = []

    def spy(batch):
        seen_threads.append(threading.current_thread() is
                            threading.main_thread())
        return real(batch)

    monkeypatch.setattr(io_mod, "_to_tensors", spy)

    on = list(DataLoader(SquareDataset(), batch_size=8))
    assert seen_threads and not any(seen_threads), \
        "flag on: conversion must happen OFF the main thread"

    seen_threads.clear()
    off_loader = DataLoader(SquareDataset(), batch_size=8,
                            use_buffer_reader=False)
    off = list(off_loader)
    assert seen_threads and all(seen_threads), \
        "flag off: conversion must happen on the consumer thread"

    for a, b in zip(on, off):
        assert isinstance(a, Tensor) and isinstance(b, Tensor)
        np.testing.assert_array_equal(a.numpy(), b.numpy())


def test_buffer_reader_applies_to_worker_processes(monkeypatch):
    # the staging contract holds on the multiprocess path too (the batch
    # crosses the process boundary as host arrays; the parent's stager
    # thread owns the device put)
    import threading

    import paddle_tpu.io as io_mod
    from paddle_tpu.framework.tensor import Tensor

    real = io_mod._to_tensors
    on_main = []

    def spy(batch):
        on_main.append(threading.current_thread() is
                       threading.main_thread())
        return real(batch)

    monkeypatch.setattr(io_mod, "_to_tensors", spy)
    out = list(DataLoader(SquareDataset(), batch_size=8, num_workers=2))
    assert on_main and not any(on_main)
    assert all(isinstance(b, Tensor) for b in out)
    seen = np.sort(np.concatenate([b.numpy()[:, 0] for b in out]))
    np.testing.assert_array_equal(seen, np.sort(np.arange(32) ** 2))


def test_shuffle_follows_paddle_seed():
    # shuffle order is governed by paddle.seed, not global np.random:
    # unrelated np.random draws between runs must not change data order
    # (this was a real flake: suite-order-dependent hapi accuracies)
    import paddle_tpu as paddle

    def epoch_order():
        dl = DataLoader(SquareDataset(), batch_size=4, shuffle=True)
        return np.concatenate([b.numpy()[:, 0] for b in dl])

    paddle.seed(11)
    a = epoch_order()
    np.random.rand(1000)          # perturb the GLOBAL numpy stream
    paddle.seed(11)
    b = epoch_order()
    np.testing.assert_array_equal(a, b)
    paddle.seed(12)
    c = epoch_order()
    assert not np.array_equal(a, c)  # different seed, different order


def test_two_epochs_and_persistent_workers():
    dl = DataLoader(SquareDataset(), batch_size=8, num_workers=2,
                    persistent_workers=True)
    e1 = np.concatenate([b.numpy()[:, 0] for b in dl])
    e2 = np.concatenate([b.numpy()[:, 0] for b in dl])
    np.testing.assert_array_equal(e1, e2)
    dl._persistent_pool.shutdown()


def test_worker_error_propagates():
    dl = DataLoader(FailingDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)


def test_iterable_dataset_shards_across_workers():
    dl = DataLoader(ShardedIterable(), batch_size=3, num_workers=2)
    seen = sorted(int(v) for b in dl for v in b.numpy()[:, 0])
    assert seen == list(range(12))


@pytest.mark.slow
@pytest.mark.skipif(len(os.sched_getaffinity(0)) < 3,
                    reason="needs >=3 CPU cores to demonstrate scaling "
                           "(single-core CI box cannot parallelize anything)")
def test_processes_beat_threads_on_gil_bound_work():
    """The reason the subsystem exists: CPU-heavy __getitem__ scales with
    processes, not threads."""
    ds = BusyDataset()

    t0 = time.perf_counter()
    for _ in DataLoader(ds, batch_size=2, num_workers=0):
        pass
    serial = time.perf_counter() - t0

    dl = DataLoader(ds, batch_size=2, num_workers=4,
                    persistent_workers=True)
    for _ in dl:  # warm epoch: spawn + import cost lands here, not the timer
        pass
    t0 = time.perf_counter()
    for _ in dl:
        pass
    mp_time = time.perf_counter() - t0
    dl._persistent_pool.shutdown()

    # 4 workers on GIL-bound work: demand a clear win, not perfection
    assert mp_time < serial * 0.7, (serial, mp_time)


# ---------------------------------------------------------------------------
# fleet datasets (distributed/fleet/dataset.py)
# ---------------------------------------------------------------------------

def _write_slot_files(tmp_path, n_files=2, rows=6):
    paths = []
    v = 0
    for f in range(n_files):
        p = tmp_path / f"part-{f}.txt"
        lines = []
        for _ in range(rows):
            lines.append(f"{v} {v + 0.5}")
            v += 1
        p.write_text("\n".join(lines))
        paths.append(str(p))
    return paths


def test_inmemory_dataset_load_shuffle_iterate(tmp_path):
    from paddle_tpu.distributed import fleet

    ds = fleet.InMemoryDataset()
    ds.init(batch_size=4)
    ds.set_filelist(_write_slot_files(tmp_path))
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 12
    first = [int(b[0][0, 0]) for b in ds.iterate()]
    ds.local_shuffle(seed=1)
    shuffled = [int(b[0][0, 0]) for b in ds.iterate()]
    assert first != shuffled  # order actually changed
    all_ids = sorted(int(r[0]) for r in ds._records)
    assert all_ids == list(range(12))
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_queue_dataset_streams(tmp_path):
    from paddle_tpu.distributed import fleet

    ds = fleet.QueueDataset()
    ds.init(batch_size=5)
    ds.set_filelist(_write_slot_files(tmp_path))
    batches = list(ds.iterate())
    assert [b[0].shape[0] for b in batches] == [5, 5, 2]
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()


def test_pipe_command(tmp_path):
    from paddle_tpu.distributed import fleet

    p = tmp_path / "raw.txt"
    p.write_text("a,1\nb,2\n")
    ds = fleet.QueueDataset()
    ds.init(batch_size=2, pipe_command="cut -d, -f2")
    ds.set_filelist([str(p)])
    (batch,) = list(ds.iterate())
    np.testing.assert_array_equal(batch[0][:, 0], [1, 2])

"""Vision package tests: model zoo forwards, transforms, datasets, ops.

Mirrors the reference's test layout (python/paddle/tests/test_vision_models.py,
test_transforms.py, test_datasets.py) on the CPU mesh.
"""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.vision as vision
import paddle_tpu.vision.transforms as T
from paddle_tpu.vision import ops as V


def _check_model(model, input_shape=(1, 3, 64, 64), num_classes=10):
    x = paddle.to_tensor(np.random.RandomState(0).rand(*input_shape)
                         .astype("float32"))
    model.eval()
    out = model(x)
    if isinstance(out, (tuple, list)):
        out = out[0]
    assert tuple(out.shape) == (input_shape[0], num_classes)
    assert np.isfinite(out.numpy()).all()


class TestModels:
    def test_lenet(self):
        m = vision.models.LeNet(num_classes=10)
        _check_model(m, (2, 1, 28, 28))

    def test_resnet18(self):
        _check_model(vision.models.resnet18(num_classes=10))

    def test_resnet50_and_next(self):
        _check_model(vision.models.resnet50(num_classes=10))
        _check_model(vision.models.resnext50_32x4d(num_classes=10))

    def test_wide_resnet(self):
        _check_model(vision.models.wide_resnet50_2(num_classes=10))

    def test_vgg11(self):
        _check_model(vision.models.vgg11(num_classes=10))

    def test_alexnet(self):
        _check_model(vision.models.alexnet(num_classes=10),
                     (1, 3, 224, 224))

    def test_mobilenets(self):
        _check_model(vision.models.mobilenet_v1(scale=0.25, num_classes=10))
        _check_model(vision.models.mobilenet_v2(scale=0.25, num_classes=10))

    def test_squeezenet(self):
        _check_model(vision.models.squeezenet1_0(num_classes=10),
                     (1, 3, 224, 224))
        _check_model(vision.models.squeezenet1_1(num_classes=10),
                     (1, 3, 224, 224))

    def test_densenet(self):
        _check_model(vision.models.densenet121(num_classes=10))

    def test_googlenet(self):
        m = vision.models.googlenet(num_classes=10)
        x = paddle.to_tensor(np.random.rand(1, 3, 224, 224).astype("float32"))
        m.eval()
        main, o1, o2 = m(x)
        assert tuple(main.shape) == (1, 10)
        assert tuple(o1.shape) == (1, 10)

    def test_inception_v3(self):
        _check_model(vision.models.inception_v3(num_classes=10),
                     (1, 3, 299, 299))

    def test_shufflenet(self):
        _check_model(vision.models.shufflenet_v2_x0_25(num_classes=10))

    def test_pretrained_raises(self):
        with pytest.raises(ValueError):
            vision.models.resnet18(pretrained=True)


class TestTransforms:
    def test_compose_pipeline(self):
        img = (np.random.RandomState(0).rand(40, 60, 3) * 255).astype("uint8")
        pipe = T.Compose([
            T.Resize(32), T.CenterCrop(24), T.RandomHorizontalFlip(0.5),
            T.ToTensor(),
        ])
        out = pipe(img)
        assert tuple(out.shape) == (3, 24, 24)
        assert float(out.numpy().max()) <= 1.0

    def test_resize_semantics(self):
        img = np.arange(16, dtype="uint8").reshape(4, 4)
        out = T.functional.resize(img, (8, 8), "nearest")
        assert out.shape == (8, 8)
        # int shorter-side semantics
        img2 = np.zeros((10, 20, 3), dtype="uint8")
        out2 = T.functional.resize(img2, 5)
        assert out2.shape[:2] == (5, 10)

    def test_normalize(self):
        img = np.ones((3, 4, 4), dtype="float32")
        out = T.functional.normalize(img, [0.5, 0.5, 0.5], [0.5, 0.5, 0.5])
        np.testing.assert_allclose(out, np.ones_like(img))

    def test_flips_pad_crop(self):
        img = np.arange(12, dtype="uint8").reshape(3, 4, 1)
        np.testing.assert_array_equal(T.functional.hflip(img),
                                      img[:, ::-1])
        np.testing.assert_array_equal(T.functional.vflip(img), img[::-1])
        padded = T.functional.pad(img, 1)
        assert padded.shape == (5, 6, 1)
        c = T.functional.crop(img, 1, 1, 2, 2)
        assert c.shape == (2, 2, 1)

    def test_color_jitter_runs(self):
        img = (np.random.RandomState(1).rand(16, 16, 3) * 255).astype("uint8")
        out = T.ColorJitter(0.4, 0.4, 0.4, 0.4)(img)
        assert out.shape == img.shape

    def test_rotation_and_grayscale(self):
        img = (np.random.RandomState(2).rand(9, 9, 3) * 255).astype("uint8")
        rot = T.functional.rotate(img, 90)
        assert rot.shape == img.shape
        g = T.functional.to_grayscale(img)
        assert g.shape == (9, 9, 1)

    def test_random_erasing(self):
        img = np.ones((16, 16, 3), dtype="uint8") * 255
        out = T.RandomErasing(prob=1.0)(img)
        assert (out == 0).any()


def _write_idx(path, arr):
    dtype_code = {np.uint8: 0x08}[arr.dtype.type]
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, dtype_code, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


class TestDatasets:
    def test_mnist_idx(self, tmp_path):
        rs = np.random.RandomState(0)
        images = (rs.rand(10, 28, 28) * 255).astype("uint8")
        labels = rs.randint(0, 10, 10).astype("uint8")
        ip = str(tmp_path / "images.idx3")
        lp = str(tmp_path / "labels.idx1")
        _write_idx(ip, images)
        _write_idx(lp, labels)
        ds = vision.datasets.MNIST(image_path=ip, label_path=lp, mode="train")
        assert len(ds) == 10
        img, lab = ds[3]
        assert img.shape == (28, 28, 1)
        assert int(lab[0]) == int(labels[3])

    def test_mnist_gzip(self, tmp_path):
        images = np.zeros((2, 28, 28), dtype="uint8")
        labels = np.zeros(2, dtype="uint8")
        ip = str(tmp_path / "images.idx3.gz")
        lp = str(tmp_path / "labels.idx1")
        raw = str(tmp_path / "raw")
        _write_idx(raw, images)
        with open(raw, "rb") as f, gzip.open(ip, "wb") as g:
            g.write(f.read())
        _write_idx(lp, labels)
        ds = vision.datasets.MNIST(image_path=ip, label_path=lp)
        assert len(ds) == 2

    def test_cifar10_tar(self, tmp_path):
        rs = np.random.RandomState(0)
        tar_path = str(tmp_path / "cifar-10.tar.gz")
        with tarfile.open(tar_path, "w:gz") as tf:
            for name, n in [("data_batch_1", 6), ("test_batch", 4)]:
                payload = pickle.dumps({
                    b"data": (rs.rand(n, 3072) * 255).astype("uint8"),
                    b"labels": list(rs.randint(0, 10, n)),
                })
                import io as _io

                info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
                info.size = len(payload)
                tf.addfile(info, _io.BytesIO(payload))
        train = vision.datasets.Cifar10(data_file=tar_path, mode="train")
        test = vision.datasets.Cifar10(data_file=tar_path, mode="test")
        assert len(train) == 6 and len(test) == 4
        img, lab = train[0]
        assert img.shape == (32, 32, 3)

    def test_dataset_folder(self, tmp_path):
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                np.save(str(d / f"{i}.npy"),
                        np.zeros((8, 8, 3), dtype="uint8"))
        ds = vision.datasets.DatasetFolder(str(tmp_path))
        assert len(ds) == 6
        assert ds.classes == ["cat", "dog"]
        img, lab = ds[5]
        assert int(lab) == 1

    def test_download_unavailable(self):
        with pytest.raises(ValueError):
            vision.datasets.MNIST()


class TestOps:
    def test_roi_align_whole_image(self):
        # a roi covering the full image with 1x1 output = mean of the feature
        x = paddle.to_tensor(
            np.random.RandomState(0).rand(1, 2, 8, 8).astype("float32"))
        boxes = paddle.to_tensor(
            np.array([[0.0, 0.0, 8.0, 8.0]], dtype="float32"))
        bn = paddle.to_tensor(np.array([1], dtype="int32"))
        out = V.roi_align(x, boxes, bn, output_size=1, sampling_ratio=8,
                          aligned=False)
        np.testing.assert_allclose(out.numpy().reshape(2),
                                   x.numpy().mean(axis=(0, 2, 3)), atol=0.05)

    def test_roi_pool_shape(self):
        x = paddle.to_tensor(np.random.rand(2, 3, 16, 16).astype("float32"))
        boxes = paddle.to_tensor(
            np.array([[0, 0, 8, 8], [4, 4, 12, 12], [0, 0, 16, 16]],
                     dtype="float32"))
        bn = paddle.to_tensor(np.array([2, 1], dtype="int32"))
        out = V.roi_pool(x, boxes, bn, output_size=4)
        assert tuple(out.shape) == (3, 3, 4, 4)

    def test_nms(self):
        boxes = np.array([
            [0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
        ], dtype="float32")
        scores = np.array([0.9, 0.8, 0.7], dtype="float32")
        keep = V.nms(paddle.to_tensor(boxes), 0.5,
                     paddle.to_tensor(scores)).numpy()
        assert list(keep) == [0, 2]

    def test_yolo_box(self):
        x = paddle.to_tensor(np.random.rand(1, 12, 4, 4).astype("float32"))
        img_size = paddle.to_tensor(np.array([[128, 128]], dtype="int32"))
        boxes, scores = V.yolo_box(x, img_size, [10, 13, 16, 30], 1, 0.01,
                                   downsample_ratio=32)
        assert tuple(boxes.shape) == (1, 32, 4)
        assert tuple(scores.shape) == (1, 32, 1)

    def test_deform_conv_zero_offset_matches_conv(self):
        import paddle_tpu.nn.functional as F

        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.rand(1, 3, 8, 8).astype("float32"))
        w = paddle.to_tensor(rs.rand(4, 3, 3, 3).astype("float32"))
        offset = paddle.to_tensor(np.zeros((1, 18, 8, 8), dtype="float32"))
        out = V.deform_conv2d(x, offset, w, padding=1)
        ref = F.conv2d(x, w, padding=1)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-4)

    def test_deform_conv_layer_grad(self):
        rs = np.random.RandomState(0)
        layer = V.DeformConv2D(2, 2, 3, padding=1)
        x = paddle.to_tensor(rs.rand(1, 2, 6, 6).astype("float32"))
        offset = paddle.to_tensor(
            rs.rand(1, 18, 6, 6).astype("float32") * 0.1)
        out = layer(x, offset)
        loss = out.sum()
        loss.backward()
        assert layer.weight.grad is not None
        assert np.isfinite(layer.weight.grad.numpy()).all()


class TestReviewRegressions:
    def test_googlenet_no_pool_with_classifier(self):
        m = vision.models.GoogLeNet(num_classes=5, with_pool=False)
        assert m._pool_o1 is not None

    def test_hue_on_grayscale_noop(self):
        img = (np.random.RandomState(0).rand(8, 8, 1) * 255).astype("uint8")
        out = T.functional.adjust_hue(img, 0.3)
        np.testing.assert_array_equal(out, img)
        g = T.functional.to_grayscale(img, 3)
        assert g.shape == (8, 8, 3)

    def test_yolo_box_iou_aware(self):
        # C = an_num + an_num*(5+class_num) = 2 + 12 = 14
        x = paddle.to_tensor(np.random.rand(1, 14, 4, 4).astype("float32"))
        img_size = paddle.to_tensor(np.array([[128, 128]], dtype="int32"))
        boxes, scores = V.yolo_box(x, img_size, [10, 13, 16, 30], 1, 0.01,
                                   iou_aware=True, iou_aware_factor=0.5)
        assert tuple(boxes.shape) == (1, 32, 4)
        assert np.isfinite(scores.numpy()).all()

    def test_psroi_pool(self):
        # each channel group constant → output bin picks its own group value
        ph = pw = 2
        out_c = 3
        x_np = np.zeros((1, out_c * ph * pw, 8, 8), dtype="float32")
        for g in range(out_c * ph * pw):
            x_np[0, g] = g
        x = paddle.to_tensor(x_np)
        boxes = paddle.to_tensor(np.array([[0, 0, 8, 8]], dtype="float32"))
        bn = paddle.to_tensor(np.array([1], dtype="int32"))
        out = V.psroi_pool(x, boxes, bn, 2).numpy()  # (1, out_c, 2, 2)
        # input layout (out_c, ph, pw): bin (i,j) of channel c == value of
        # group c*ph*pw + i*pw + j
        for c in range(out_c):
            for i in range(ph):
                for j in range(pw):
                    assert out[0, c, i, j] == c * ph * pw + i * pw + j


@pytest.mark.slow
def test_vision_transformer_forward_and_train():
    from paddle_tpu.vision.models import VisionTransformer
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    m = VisionTransformer(img_size=16, patch_size=8, embed_dim=32, depth=2,
                          num_heads=2, num_classes=4)
    crit = paddle.nn.CrossEntropyLoss()
    optim = opt.Adam(learning_rate=1e-3, parameters=m.parameters())
    step = TrainStep(m, lambda lg, lb: crit(lg, lb), optim)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(4, 3, 16, 16).astype("float32"))
    y = paddle.to_tensor(rs.randint(0, 4, (4, 1)), dtype="int64")
    losses = [float(step(inputs=(x,), labels=(y,))) for _ in range(8)]
    assert losses[-1] < losses[0]

"""Double/higher-order gradients via paddle.grad(create_graph=True).

Reference: imperative/partial_grad_engine.cc + the double-grad ops emitted
by grad_op_desc_maker (e.g. used by WGAN-GP gradient penalty). TPU-native:
each node pullback is replayed differentiably through call_op, so returned
grads live on the tape.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

rs = np.random.RandomState(0)


def test_second_derivative_polynomial():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = (x ** 3).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 3 * x.numpy() ** 2, rtol=1e-5)
    assert not g1.stop_gradient  # lives on the tape
    (g2,) = paddle.grad(g1.sum(), x)
    np.testing.assert_allclose(g2.numpy(), 6 * x.numpy(), rtol=1e-5)


def test_third_derivative():
    x = paddle.to_tensor(np.array([1.5], np.float32), stop_gradient=False)
    y = (x ** 4).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x, create_graph=True)
    (g3,) = paddle.grad(g2.sum(), x)
    np.testing.assert_allclose(g3.numpy(), 24 * x.numpy(), rtol=1e-5)


def test_mixed_partial_through_two_inputs():
    a = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    b = paddle.to_tensor(np.float32(5.0), stop_gradient=False)
    y = a * a * b  # dy/da = 2ab; d2y/dadb = 2a
    (ga,) = paddle.grad(y, a, create_graph=True)
    (gab,) = paddle.grad(ga, b)
    np.testing.assert_allclose(float(gab.numpy()), 2 * 2.0, rtol=1e-5)


def test_gradient_penalty_trains_weights():
    """The WGAN-GP pattern: ||dD/dx|| penalty backprops into weights."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    x = paddle.to_tensor(rs.randn(6, 4).astype("float32"),
                         stop_gradient=False)
    out = net(x).sum()
    (gx,) = paddle.grad(out, x, create_graph=True)
    gp = (((gx ** 2).sum(axis=1) + 1e-12) ** 0.5 - 1.0) ** 2
    gp.mean().backward()
    for p in net.parameters():
        assert p.grad is not None
        assert np.isfinite(p.grad.numpy()).all()
    # and the penalty actually decreases under SGD on it
    import paddle_tpu.optimizer as opt

    optim = opt.SGD(learning_rate=0.05, parameters=net.parameters())
    losses = []
    for _ in range(20):
        x2 = paddle.to_tensor(rs.randn(6, 4).astype("float32"),
                              stop_gradient=False)
        (gx2,) = paddle.grad(net(x2).sum(), x2, create_graph=True)
        loss = ((((gx2 ** 2).sum(axis=1) + 1e-12) ** 0.5 - 1.0) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_create_graph_matches_jax_reference():
    """grad-of-grad equals jax.grad(jax.grad(...)) on the same function."""
    import jax
    import jax.numpy as jnp

    def f(v):
        return jnp.sum(jnp.sin(v) * v ** 2)

    xv = rs.randn(5).astype("float32")
    ref_g2 = jax.grad(lambda v: jax.grad(f)(v).sum())(xv)

    x = paddle.to_tensor(xv, stop_gradient=False)
    y = (paddle.sin(x) * x ** 2).sum()
    (g1,) = paddle.grad(y, x, create_graph=True)
    (g2,) = paddle.grad(g1.sum(), x)
    np.testing.assert_allclose(g2.numpy(), np.asarray(ref_g2), rtol=1e-4,
                               atol=1e-5)


def test_allow_unused():
    a = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    b = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    y = a * 2.0
    with pytest.raises(RuntimeError):
        paddle.grad(y, [a, b], create_graph=True, allow_unused=False)
    ga, gb = paddle.grad(a * 2.0, [a, b], create_graph=True,
                         allow_unused=True)
    assert gb is None and float(ga.numpy()) == 2.0


def test_replay_uses_forward_time_snapshot():
    """An in-place rebind between forward and grad(create_graph=True) must
    NOT change the gradients (GradNode snapshot invariant)."""
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = (x * x).sum()
    x[0] = 5.0  # in-place mutation AFTER forward
    (g,) = paddle.grad(y, x, create_graph=True, allow_unused=True)
    # d(x*x)/dx at FORWARD-time values [1, 2] -> [2, 4]
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0], rtol=1e-5)


def test_multi_output_duplicate_roots():
    """Two outputs of ONE op as grad targets must not starve upstream
    nodes (duplicate-root indegree accounting)."""
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32),
                         stop_gradient=False)
    z = x * 2.0  # upstream op whose node must still be processed
    a, b = paddle.topk(z, k=2)  # multi-output op (values, indices)
    s1 = (a * a).sum()
    got = paddle.grad([s1, a.sum()], [x], create_graph=True)
    assert got[0] is not None
    # d/dx of (2x)^2 + 2x summed over sorted order = 8x + 2 (order-free sum)
    np.testing.assert_allclose(np.sort(got[0].numpy()),
                               np.sort(8 * x.numpy() + 2), rtol=1e-5)

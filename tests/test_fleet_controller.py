"""Goodput-maximizing elastic controller (ISSUE 17).

Contracts pinned here:
- ScalePolicy.decide is a PURE function of a FleetSignals snapshot, with
  the documented priority order (preemption > cooldown > straggler >
  serve overload > serve idle > grow) and cooldown hysteresis carried IN
  the snapshot; a recorded run replays to the bit-identical decision
  sequence.
- FleetController assembles honest signals (free-chip inventory math,
  quarantine accounting), actuates through duck-typed plants, logs every
  non-noop decision on the event plane and the
  fleet_decisions_total{action=} counter.
- GoodputLedger attributes every chip-second to exactly one account,
  refuses unknown accounts, and verify_conservation catches dropped time.
- Compile-aware watchdog grace: a replica reporting "compiling" gets
  max(timeout, compile_grace) as its deadline; a fake slow-compile
  replica survives a timeout that evicts a non-compiling control.
- Fault injection growth: FaultyFS targeted delay_on, and
  LateHeartbeatStore making one host's lease lapse (ElasticManager sees
  the member vanish, then recover when heartbeats resume).
- bench_gate.gate_fleet: goodput ratio / zero-lost / in-grace gates,
  with a missing fleet section counting as regression (format drift).
"""
import os
import sys
import time

import pytest

from paddle_tpu.distributed.fleet.elastic import (
    Decision, ElasticManager, FleetController, FleetSignals, GoodputLedger,
    LocalKVStore, ReactivePolicy, ScalePolicy, LEDGER_ACCOUNTS,
)
from paddle_tpu.observability import get_event_log
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.robustness.fault_injection import (
    FaultyFS, LateHeartbeatStore,
)
from paddle_tpu.robustness.watchdog import HangDetector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sig(**over):
    base = dict(clock=10.0, train_world=4, serve_replicas=2, total_chips=8,
                free_chips=0, spare_hosts=0, step_time_p99_ms=900.0,
                step_time_skew=0.02, serve_queue_depth=0,
                serve_latency_p99_ms=0.0, preempt_notice=False,
                preempt_grace_s=30.0)
    base.update(over)
    return FleetSignals(**base)


class _Train:
    """Duck-typed train plant that records actuations."""

    def __init__(self, world=4):
        self.world = world
        self.calls = []
        self.skew = 0.02
        self.preempt = False

    def spare_hosts(self):
        return 0

    def step_time_p99_ms(self):
        return 900.0

    def step_time_skew(self):
        return self.skew

    def preempt_pending(self):
        return self.preempt

    def preempt_grace_s(self):
        return 30.0

    def preempt_shrink(self):
        self.calls.append("preempt_shrink")
        self.world -= 1
        self.preempt = False

    def shed_straggler(self):
        self.calls.append("shed_straggler")
        self.world -= 1
        self.skew = 0.02

    def grow(self):
        self.calls.append("grow")
        self.world += 1

    def release_chip(self):
        self.calls.append("release_chip")
        self.world -= 1


class _Serve:
    def __init__(self, replicas=2):
        self.replicas = replicas
        self.calls = []
        self.queue_depth = 0
        self.p99 = 0.0

    def latency_p99_ms(self):
        return self.p99

    def scale_up(self):
        self.calls.append("scale_up")
        self.replicas += 1

    def scale_down(self):
        self.calls.append("scale_down")
        self.replicas -= 1


class TestScalePolicy:
    def test_preemption_outranks_everything_and_ignores_cooldown(self):
        p = ScalePolicy(cooldown_s=5.0)
        s = _sig(preempt_notice=True, step_time_skew=0.9,
                 serve_queue_depth=50, last_scale_clock=9.5)
        assert p.decide(s).action == "preempt_shrink"

    def test_preemption_respects_world_floor(self):
        p = ScalePolicy(min_train_world=4)
        s = _sig(preempt_notice=True)
        assert p.decide(s).action != "preempt_shrink"

    def test_cooldown_suppresses_non_preempt_actions(self):
        p = ScalePolicy(cooldown_s=5.0, skew_high=0.5)
        s = _sig(step_time_skew=0.9, last_scale_clock=8.0)  # 2s ago < 5s
        d = p.decide(s)
        assert d.action == "none" and d.reason == "cooldown"
        # outside the window the same signals shed the straggler
        assert p.decide(_sig(step_time_skew=0.9,
                             last_scale_clock=1.0)).action == "shed_straggler"

    def test_overload_prefers_free_chip_over_train_shrink(self):
        p = ScalePolicy(queue_high=6)
        over = _sig(serve_queue_depth=9, free_chips=1)
        assert p.decide(over).action == "serve_up"
        no_free = _sig(serve_queue_depth=9, free_chips=0)
        assert p.decide(no_free).action == "train_to_serve"

    def test_overload_by_latency_alone(self):
        p = ScalePolicy(serve_p99_high_ms=2500.0)
        s = _sig(serve_latency_p99_ms=4000.0, free_chips=1)
        assert p.decide(s).action == "serve_up"

    def test_overload_with_no_capacity_anywhere_is_none(self):
        p = ScalePolicy(min_train_world=4, max_serve_replicas=4)
        s = _sig(serve_queue_depth=50, free_chips=0, train_world=4)
        assert p.decide(s).action == "none"

    def test_serve_idle_hands_chip_to_training(self):
        p = ScalePolicy(queue_low=0)
        s = _sig(serve_queue_depth=0, serve_latency_p99_ms=0.0)
        assert p.decide(s).action == "serve_to_train"

    def test_serve_idle_at_train_ceiling_scales_down(self):
        p = ScalePolicy(max_train_world=4)
        s = _sig(serve_queue_depth=0, train_world=4)
        assert p.decide(s).action == "serve_down"

    def test_serve_idle_respects_replica_floor(self):
        p = ScalePolicy(min_serve_replicas=2, max_train_world=4)
        s = _sig(serve_replicas=2, serve_queue_depth=0, train_world=4)
        assert p.decide(s).action == "none"

    def test_spare_capacity_grows_train(self):
        p = ScalePolicy()
        assert p.decide(_sig(spare_hosts=1, serve_queue_depth=3)
                        ).action == "grow_train"
        # an overloaded serve keeps the spare chip available for serve_up
        d = p.decide(_sig(spare_hosts=1, free_chips=1, serve_queue_depth=9))
        assert d.action == "serve_up"

    def test_decide_is_pure_and_deterministic(self):
        p = ScalePolicy()
        s = _sig(serve_queue_depth=9, free_chips=1)
        before = dict(vars(p))
        d1, d2 = p.decide(s), p.decide(s)
        assert d1 == d2                      # frozen dataclass equality
        assert vars(p) == before             # no state mutated

    def test_reactive_policy_never_acts(self):
        p = ReactivePolicy()
        for s in (_sig(preempt_notice=True), _sig(serve_queue_depth=99),
                  _sig(step_time_skew=5.0), _sig(spare_hosts=3)):
            assert p.decide(s).action == "none"

    def test_decision_rejects_unknown_action(self):
        with pytest.raises(ValueError):
            Decision("explode", "nope", 0.0)


class TestGoodputLedger:
    def test_charge_and_conservation(self):
        led = GoodputLedger()
        led.charge("train_useful", 4, seconds=2.0)
        led.charge("save", 4)
        led.charge("idle", 1, seconds=3.0)
        assert led.chip_seconds == pytest.approx(15.0)
        assert led.verify_conservation(15.0)
        assert not led.verify_conservation(16.0)

    def test_unknown_account_refused(self):
        led = GoodputLedger()
        with pytest.raises(ValueError):
            led.charge("snacks", 1)
        with pytest.raises(ValueError):
            led.tokens("snacks", 1)

    def test_goodput_couples_tokens_and_availability(self):
        led = GoodputLedger()
        led.tokens("train", 900)
        led.tokens("serve", 100)
        assert led.availability == 1.0      # nothing submitted yet
        led.serve_submitted, led.serve_completed = 10, 5
        assert led.availability == 0.5
        assert led.goodput(10.0) == pytest.approx(1000 / 10.0 * 0.5)

    def test_summary_accounts_all_ledger_accounts(self):
        led = GoodputLedger()
        led.charge("serve_useful", 2)
        summ = led.summary()
        assert set(summ["accounts"]) == set(LEDGER_ACCOUNTS)
        assert summ["useful_fraction"] == pytest.approx(1.0)


class TestFleetController:
    def test_free_chip_inventory_math(self):
        ctrl = FleetController(ScalePolicy(), _Train(world=4),
                               _Serve(replicas=2), total_chips=8)
        assert ctrl.free_chips == 2
        ctrl.quarantined = 1
        assert ctrl.free_chips == 1
        s = ctrl.signals(clock=0.0)
        assert s.free_chips == 1 and s.train_world == 4 \
            and s.serve_replicas == 2

    def test_preempt_tick_actuates_and_records(self):
        train, serve = _Train(world=4), _Serve()
        train.preempt = True
        ctrl = FleetController(ScalePolicy(), train, serve, total_chips=8)
        get_event_log().clear()
        c0 = get_registry().counter(
            "fleet_decisions_total",
            labels=("action",)).labels(action="preempt_shrink").value
        d = ctrl.tick(0.0)
        assert d.action == "preempt_shrink"
        assert train.calls == ["preempt_shrink"] and train.world == 3
        assert len(ctrl.records) == 1
        assert get_registry().counter(
            "fleet_decisions_total",
            labels=("action",)).labels(action="preempt_shrink").value \
            == c0 + 1
        evs = get_event_log().events(kind="fleet")
        assert evs and evs[-1]["action"] == "preempt_shrink"
        assert ctrl.decision_log()[-1]["action"] == "preempt_shrink"

    def test_arbitration_moves_chips_both_ways(self):
        train, serve = _Train(world=5), _Serve(replicas=2)
        ctrl = FleetController(ScalePolicy(cooldown_s=0.0), train, serve,
                               total_chips=7)
        serve.queue_depth = 9
        assert ctrl.tick(0.0).action == "train_to_serve"
        assert train.world == 4 and serve.replicas == 3
        serve.queue_depth = 0
        assert ctrl.tick(1.0).action == "serve_to_train"
        assert train.world == 5 and serve.replicas == 2

    def test_straggler_shed_quarantines_the_chip(self):
        train = _Train(world=4)
        train.skew = 0.9
        ctrl = FleetController(ScalePolicy(), train, _Serve(),
                               total_chips=8)
        free0 = ctrl.free_chips
        assert ctrl.tick(0.0).action == "shed_straggler"
        # world shrank by one but the shed chip is quarantined, not free
        assert ctrl.quarantined == 1 and ctrl.free_chips == free0

    def test_hysteresis_clock_rides_in_the_snapshot(self):
        train = _Train(world=4)
        train.skew = 0.9
        ctrl = FleetController(ScalePolicy(cooldown_s=5.0), train,
                               _Serve(), total_chips=8)
        assert ctrl.tick(0.0).action == "shed_straggler"
        train.skew = 0.9            # still straggling
        d = ctrl.tick(2.0)          # inside the cooldown window
        assert d.action == "none" and d.reason == "cooldown"
        assert ctrl.records[-1][0].last_scale_clock == 0.0

    def test_recorded_run_replays_bit_identically(self):
        train, serve = _Train(world=5), _Serve(replicas=2)
        ctrl = FleetController(ScalePolicy(cooldown_s=2.0), train, serve,
                               total_chips=8)
        serve.queue_depth = 9
        ctrl.tick(0.0)
        ctrl.tick(1.0)
        serve.queue_depth = 0
        ctrl.tick(3.0)
        train.preempt = True
        ctrl.tick(4.0)
        assert len(ctrl.records) == 4
        assert ctrl.replay()        # pure decide() over frozen snapshots


class TestCompileAwareWatchdog:
    def test_effective_timeout_stretches_only_while_compiling(self):
        state = {"s": "compiling"}
        hd = HangDetector(timeout=0.5, state_fn=lambda: state["s"],
                          compile_grace=60.0)
        assert hd.effective_timeout() == 60.0
        state["s"] = "serving"
        assert hd.effective_timeout() == 0.5
        # a broken state_fn degrades to the plain timeout, never crashes
        hd2 = HangDetector(timeout=0.5, state_fn=lambda: 1 / 0,
                           compile_grace=60.0)
        assert hd2.effective_timeout() == 0.5
        hd3 = HangDetector(timeout=0.5)     # no state_fn: unchanged
        assert hd3.effective_timeout() == 0.5

    def test_slow_compile_survives_where_control_is_evicted(self):
        """A fake replica stuck in its first (compiling) step outlives a
        timeout that fires for an identical non-compiling control."""
        hangs = []
        hd = HangDetector(timeout=0.06, poll_interval=0.01,
                          on_hang=lambda age: hangs.append(age),
                          state_fn=lambda: "compiling", compile_grace=30.0)
        control_hangs = []
        ctrl = HangDetector(timeout=0.06, poll_interval=0.01,
                            on_hang=lambda age: control_hangs.append(age),
                            state_fn=lambda: "serving", compile_grace=30.0)
        with hd, ctrl:
            time.sleep(0.25)        # both heartbeats go stale
        assert hangs == []          # compiling: deadline stretched
        assert len(control_hangs) == 1

    def test_compile_finish_rearms_the_plain_deadline(self):
        state = {"s": "compiling"}
        hangs = []
        hd = HangDetector(timeout=0.05, poll_interval=0.01,
                          on_hang=lambda age: hangs.append(age),
                          state_fn=lambda: state["s"], compile_grace=30.0)
        with hd:
            time.sleep(0.12)
            assert hangs == []
            state["s"] = "serving"  # compile done, heartbeat still stale
            time.sleep(0.12)
        assert len(hangs) == 1


class TestFaultInjectionGrowth:
    def test_faultyfs_targeted_delay(self, tmp_path):
        fs = FaultyFS(delay_on={("write", 2): 0.08})
        p = str(tmp_path / "x.bin")
        with fs.open(p, "wb") as f:
            t0 = time.monotonic()
            f.write(b"a")                   # write #1: no delay
            fast = time.monotonic() - t0
            t0 = time.monotonic()
            f.write(b"b")                   # write #2: delayed
            slow = time.monotonic() - t0
        assert slow >= 0.08 > fast
        assert fs.delays == 1
        assert ("delay", "write#2") in fs.log

    def test_faultyfs_delay_on_rename_and_fsync(self, tmp_path):
        fs = FaultyFS(delay_on={("rename", 1): 0.05, ("fsync", 1): 0.05})
        src, dst = str(tmp_path / "a"), str(tmp_path / "b")
        with fs.open(src, "wb") as f:
            f.write(b"x")
            t0 = time.monotonic()
            fs.fsync(f)
            assert time.monotonic() - t0 >= 0.05
        t0 = time.monotonic()
        fs.replace(src, dst)
        assert time.monotonic() - t0 >= 0.05
        assert fs.delays == 2

    def test_late_heartbeat_drops_then_recovers(self):
        inner = LocalKVStore()
        st = LateHeartbeatStore(inner, host="b", drop_puts=2)
        a = ElasticManager("a", "1:4", store=st, job_id="hb", ttl=0.1)
        b = ElasticManager("b", "1:4", store=st, job_id="hb", ttl=0.1)
        a.register()
        b.register()                 # swallowed (drop 1)
        assert a.members() == ["a"]  # b's lease never landed
        b.register()                 # swallowed (drop 2)
        assert a.members() == ["a"]
        b.register()                 # injector exhausted: heartbeat heals
        assert sorted(a.members()) == ["a", "b"]
        assert st.dropped == 2
        # ...and with no further beats the healed lease expires again
        time.sleep(0.15)
        assert "b" not in a.members() and "a" not in a.members()

    def test_late_heartbeat_delay_forwards_after_sleep(self):
        st = LateHeartbeatStore(LocalKVStore(), host="b", delay_puts=1,
                                delay_s=0.05)
        b = ElasticManager("b", "1:4", store=st, job_id="hb2", ttl=5)
        t0 = time.monotonic()
        b.register()
        assert time.monotonic() - t0 >= 0.05
        assert st.delayed == 1
        assert b.members() == ["b"]  # late, but it landed

    def test_other_hosts_pass_straight_through(self):
        st = LateHeartbeatStore(LocalKVStore(), host="b", drop_puts=99)
        a = ElasticManager("a", "1:4", store=st, job_id="hb3", ttl=5)
        a.register()
        assert a.members() == ["a"] and st.dropped == 0


class TestBenchGateFleet:
    def _gate(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from bench_gate import gate_fleet
        finally:
            sys.path.pop(0)
        return gate_fleet

    def _fleet(self, **over):
        base = dict(fleet_goodput_ratio=1.5, scale_event_lost_requests=0,
                    preempt_saves_in_grace=True, preempt_unanswered_policy=0)
        base.update(over)
        return {"fleet": base}

    def test_passing_artifact(self):
        rows, regressed = self._gate()(self._fleet())
        assert regressed == 0
        assert [r["verdict"] for r in rows] == ["OK"] * 3

    def test_ratio_below_floor_regresses(self):
        rows, regressed = self._gate()(self._fleet(fleet_goodput_ratio=1.1))
        assert regressed == 1
        assert rows[0]["metric"] == "fleet_goodput_ratio" \
            and rows[0]["verdict"] == "REGRESSED"

    def test_lost_requests_regress(self):
        _, regressed = self._gate()(
            self._fleet(scale_event_lost_requests=2))
        assert regressed == 1

    def test_missed_grace_or_unanswered_regress(self):
        _, r1 = self._gate()(self._fleet(preempt_saves_in_grace=False))
        _, r2 = self._gate()(self._fleet(preempt_unanswered_policy=1))
        assert r1 == 1 and r2 == 1

    def test_missing_fleet_section_is_regression_not_skip(self):
        rows, regressed = self._gate()({"parity": {"ok": True}})
        assert regressed == 1 and rows[0]["verdict"] == "REGRESSED"
        assert "format drift" in rows[0]["why"]

    def test_unreadable_artifact_path_regresses(self, tmp_path):
        rows, regressed = self._gate()(str(tmp_path / "nope.json"))
        assert regressed == 1 and rows[0]["verdict"] == "REGRESSED"

    def test_real_artifact_if_present(self):
        path = os.path.join(REPO, "artifacts", "chaos_train.json")
        if not os.path.exists(path):
            pytest.skip("no checked-in chaos_train artifact")
        rows, regressed = self._gate()(path)
        assert regressed == 0, rows


# ---------------------------------------------------------------------------
# telemetry-derived signals (ISSUE 18)
# ---------------------------------------------------------------------------

class TestTelemetrySignals:
    """HistogramWindow windowed quantiles, SLO burn rate, and the
    SignalsAdapter serve-plant duck — the observe half of the loop."""

    def _reg(self):
        from paddle_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        lat = reg.histogram("serve_request_latency_ms",
                            buckets=(100.0, 1000.0, 5000.0))
        ttft = reg.histogram("serve_ttft_ms", buckets=(50.0, 500.0))
        return reg, lat, ttft

    def test_window_quantile_sees_load_subside(self):
        from paddle_tpu.distributed.fleet.elastic import HistogramWindow

        reg, lat, _ = self._reg()
        w = HistogramWindow(lambda: reg.get(
            "serve_request_latency_ms").bind())
        for _ in range(50):
            lat.observe(4000.0)              # sustained slow burst
        w.sample(0.0)
        w.sample(10.0)                       # no new traffic since
        # cumulative life-to-date p99 stays huge; the WINDOW reads the
        # interval delta and reports the load gone
        assert lat.quantile(0.99) > 1000.0
        assert w.quantile(0.99, window_s=10.0) == 0.0
        for _ in range(20):
            lat.observe(50.0)                # fast traffic resumes
        w.sample(20.0)
        assert w.quantile(0.99, window_s=10.0) <= 100.0

    def test_window_single_sample_is_life_to_date(self):
        from paddle_tpu.distributed.fleet.elastic import HistogramWindow

        reg, lat, _ = self._reg()
        w = HistogramWindow(lambda: reg.get(
            "serve_request_latency_ms").bind())
        for _ in range(10):
            lat.observe(4000.0)
        w.sample(0.0)                        # only one snapshot yet
        assert w.quantile(0.5, window_s=10.0) > 1000.0

    def test_window_absent_family_is_quiet(self):
        from paddle_tpu.distributed.fleet.elastic import HistogramWindow

        w = HistogramWindow(lambda: None)
        w.sample(0.0)
        assert w.quantile(0.99, 10.0) == 0.0
        assert w.bad_fraction(100.0, 10.0) == 0.0

    def test_slo_burn_fast_and_slow_windows(self):
        from paddle_tpu.distributed.fleet.elastic import (
            HistogramWindow, SloBurnRate,
        )

        reg, lat, _ = self._reg()
        w = HistogramWindow(lambda: reg.get(
            "serve_request_latency_ms").bind())
        slo = SloBurnRate(w, budget_ms=1000.0, objective=0.9,
                          fast_window_s=5.0, slow_window_s=30.0)
        for _ in range(90):
            lat.observe(50.0)                # 90 good...
        for _ in range(10):
            lat.observe(4000.0)              # ...10 bad = exactly budget
        w.sample(0.0)
        fast, slow = slo.burn()
        assert fast == pytest.approx(1.0) and slow == pytest.approx(1.0)
        for _ in range(10):
            lat.observe(4000.0)              # all-bad recent interval
        w.sample(10.0)
        fast, _ = slo.burn()
        assert fast == pytest.approx(10.0)   # 100% bad / 10% budget
        with pytest.raises(ValueError):
            SloBurnRate(w, budget_ms=1.0, objective=1.0)

    def test_adapter_duck_and_snapshot(self):
        from paddle_tpu.distributed.fleet.elastic import SignalsAdapter

        reg, lat, ttft = self._reg()
        qd = reg.gauge("serve_queue_depth")
        qd.set(7)
        plant = _Serve(replicas=3)
        ad = SignalsAdapter(plant, registry=reg, window_s=10.0,
                            latency_budget_ms=1000.0, ttft_budget_ms=500.0)
        for _ in range(20):
            lat.observe(4000.0)
            ttft.observe(40.0)
        ad.observe(0.0)
        assert ad.replicas == 3              # actuation truth: the plant
        assert ad.queue_depth == 7           # telemetry, not the plant
        assert ad.latency_p99_ms() > 1000.0
        assert ad.ttft_p99_ms() <= 50.0
        fast, slow = ad.slo_burn()
        assert fast == pytest.approx(10.0)   # latency SLO dominates
        assert ad.heartbeat_age_max_s() == 0.0   # no ReplicaSet wired
        ad.scale_up()
        assert plant.calls == ["scale_up"] and ad.replicas == 4
        snap = ad.snapshot()
        assert snap["queue_depth"] == 7
        assert snap["slo_fast_burn"] == pytest.approx(10.0)

    def test_adapter_queue_depth_falls_back_to_plant(self):
        from paddle_tpu.distributed.fleet.elastic import SignalsAdapter
        from paddle_tpu.observability.metrics import MetricsRegistry

        plant = _Serve(replicas=2)
        plant.queue_depth = 4
        ad = SignalsAdapter(plant, registry=MetricsRegistry())
        assert ad.queue_depth == 4           # gauge family absent

    def test_controller_reads_adapter_signals(self):
        from paddle_tpu.distributed.fleet.elastic import SignalsAdapter

        reg, lat, ttft = self._reg()
        reg.gauge("serve_queue_depth").set(2)
        ad = SignalsAdapter(_Serve(replicas=2), registry=reg,
                            window_s=10.0, ttft_budget_ms=500.0)
        for _ in range(10):
            lat.observe(300.0)
            ttft.observe(900.0)              # TTFT SLO fully burning
        ctl = FleetController(ScalePolicy(), _Train(), ad, total_chips=8)
        s = ctl.signals(clock=5.0)           # ticks ad.observe(5.0) itself
        assert s.serve_queue_depth == 2
        assert s.serve_latency_p99_ms > 0.0
        # 900ms sits in the +Inf bucket: the window clamps to the last
        # finite bound (500) rather than inventing a per-interval max
        assert s.serve_ttft_p99_ms == pytest.approx(500.0)
        assert s.slo_fast_burn == pytest.approx(10.0)
        assert s.heartbeat_age_max_s == 0.0

    def test_policy_slo_burn_gate_is_opt_in(self):
        # default (None): burn alone never triggers overload — recorded
        # PR-17 decision sequences replay unchanged
        calm = _sig(slo_slow_burn=50.0, free_chips=1)
        assert ScalePolicy().decide(calm).action != "serve_up"
        armed = ScalePolicy(slo_burn_high=2.0)
        assert armed.decide(calm).action == "serve_up"
        assert armed.decide(
            _sig(slo_slow_burn=1.0, free_chips=1)).action != "serve_up"

    def test_real_artifact_signals_section_if_present(self):
        """Acceptance (ISSUE 18): the checked-in chaos artifact carries
        the adapter-driven run — decisions matching the probe run (or
        goodput within 0.9x), zero lost, replay intact."""
        import json

        path = os.path.join(REPO, "artifacts", "chaos_train.json")
        if not os.path.exists(path):
            pytest.skip("no checked-in chaos_train artifact")
        with open(path) as fh:
            fleet = json.load(fh)["fleet"]
        sa = fleet.get("signals_adapter")
        assert sa is not None, "artifact predates the signals adapter"
        assert sa["ok"] is True
        assert sa["decisions_match_probe"] or sa["goodput_vs_probe"] >= 0.9
        assert sa["lost_requests"] == 0 and sa["decision_replay_ok"]
        assert sa["snapshot"]["latency_p99_ms"] >= 0.0


# ---------------------------------------------------------------------------
# warm-boot actuation (ISSUE 19)
# ---------------------------------------------------------------------------

class _WarmServe(_Serve):
    """Serve plant with the ISSUE 19 surface: ``scale_up(warm=)``, a
    boot ledger, and ``warm_boot_counts()``."""

    def __init__(self, replicas=2, boot_mode="warm"):
        super().__init__(replicas)
        self.boot_mode = boot_mode
        self.last_boot = None
        self._counts = {"warm_boots": 0, "warm_boot_timeouts": 0}

    def scale_up(self, warm=False, reason="scale_up"):
        self.calls.append(f"scale_up(warm={warm})")
        self.replicas += 1
        if warm and self.boot_mode == "warm":
            self._counts["warm_boots"] += 1
            self.last_boot = {"mode": "warm", "outcome": "ok"}
        elif warm:
            self._counts["warm_boot_timeouts"] += 1
            self.last_boot = {"mode": "cold", "outcome": "ok"}

    def warm_boot_counts(self):
        return dict(self._counts)


class TestWarmBootActuation:
    def _overloaded(self, policy, serve):
        """world 5 + 2 replicas of 8 chips leaves one free; queue depth
        forces the overload branch so the next tick decides serve_up."""
        train = _Train(world=5)
        ctrl = FleetController(policy, train, serve, total_chips=8)
        serve.queue_depth = 9
        return ctrl

    def test_knob_off_actuates_cold(self):
        serve = _WarmServe()
        ctrl = self._overloaded(ScalePolicy(), serve)
        ctrl.tick(0.0)
        assert serve.calls == ["scale_up(warm=False)"]
        assert ctrl.actuations[-1]["outcome"] == "ok"

    def test_knob_on_actuates_warm_and_records_ok(self):
        serve = _WarmServe(boot_mode="warm")
        ctrl = self._overloaded(ScalePolicy(warm_boot=True), serve)
        ctrl.tick(0.0)
        assert serve.calls == ["scale_up(warm=True)"]
        assert ctrl.actuations[-1] == {
            "action": "serve_up", "clock": 0.0, "outcome": "ok"}

    def test_cold_fallback_recorded_as_warm_boot_timeout(self):
        serve = _WarmServe(boot_mode="cold")
        ctrl = self._overloaded(ScalePolicy(warm_boot=True), serve)
        ctrl.tick(0.0)
        assert serve.calls == ["scale_up(warm=True)"]
        assert ctrl.actuations[-1]["outcome"] == "warm_boot_timeout"

    def test_plant_without_warm_kwarg_falls_back(self):
        """PR-17 plants predate ``warm=`` — the controller degrades to
        the plain cold scale_up instead of crashing the actuation."""
        serve = _Serve()  # scale_up(self) only
        ctrl = self._overloaded(ScalePolicy(warm_boot=True), serve)
        ctrl.tick(0.0)
        assert serve.calls == ["scale_up"]
        assert serve.replicas == 3
        assert ctrl.actuations[-1]["outcome"] == "ok"

    def test_signals_stamp_warm_boot_counts(self):
        serve = _WarmServe(boot_mode="warm")
        ctrl = self._overloaded(ScalePolicy(warm_boot=True), serve)
        ctrl.tick(0.0)
        sig = ctrl.signals(1.0)
        assert sig.warm_boots == 1 and sig.warm_boot_timeouts == 0

    def test_plants_without_counts_hook_default_to_zero(self):
        train, serve = _Train(), _Serve()
        ctrl = FleetController(ScalePolicy(), train, serve, total_chips=8)
        sig = ctrl.signals(0.0)
        assert sig.warm_boots == 0 and sig.warm_boot_timeouts == 0

    def test_decide_never_reads_the_knob(self):
        """``warm_boot`` changes HOW serve_up actuates, never WHAT is
        decided — the same signal stream produces bit-identical decision
        sequences with the knob on and off (replay compatibility)."""
        sigs = [_sig(clock=t, serve_queue_depth=d, free_chips=1)
                for t, d in ((0.0, 9), (1.0, 9), (3.0, 0), (6.0, 9))]
        plain = ScalePolicy(cooldown_s=2.0)
        warm = ScalePolicy(cooldown_s=2.0, warm_boot=True)
        assert [plain.decide(s) for s in sigs] \
            == [warm.decide(s) for s in sigs]

    def test_old_signature_snapshots_replay_bit_identically(self):
        """PR-17 fleet traces predate the warm fields: FleetSignals
        defaults them, so a recorded run built from old-shape snapshot
        dicts re-decides bit-identically (acceptance: decision-record
        replay of PR-17 traces)."""
        import dataclasses

        old_shape = dict(clock=0.0, train_world=4, serve_replicas=2,
                         total_chips=8, free_chips=1, spare_hosts=0,
                         step_time_p99_ms=900.0, step_time_skew=0.02,
                         serve_queue_depth=9, serve_latency_p99_ms=0.0,
                         preempt_notice=False, preempt_grace_s=30.0)
        sig = FleetSignals(**old_shape)   # no warm fields in the record
        assert sig.warm_boots == 0 and sig.warm_boot_timeouts == 0
        policy = ScalePolicy(cooldown_s=2.0, warm_boot=True)
        want = policy.decide(sig)
        # round-trip through the serialized form a trace would carry
        rt = FleetSignals(**{k: v for k, v in
                             dataclasses.asdict(sig).items()
                             if k in old_shape})
        assert policy.decide(rt) == want

    def test_live_replay_with_warm_actuation(self):
        """A full recorded run with warm actuation on replays
        bit-identically — actuation outcomes live in ``actuations``,
        never inside the decision records replay() re-derives."""
        serve = _WarmServe(boot_mode="warm")
        ctrl = self._overloaded(ScalePolicy(cooldown_s=2.0,
                                            warm_boot=True), serve)
        ctrl.tick(0.0)
        serve.queue_depth = 0
        ctrl.tick(3.0)
        serve.queue_depth = 9
        ctrl.tick(6.0)
        assert ctrl.replay()

"""ZeRO sharding memory profile (VERDICT r1 weak #6).

SURVEY §7 hard part: "matching Paddle's stage-3 memory profile". On the
virtual CPU mesh the assertion is structural: after group_sharded_parallel
+ one compiled TrainStep, the per-device shard of every shardable parameter
(stage 3) and optimizer slot (stages 1-3) must be 1/deg of the full array —
that IS the memory claim, byte for byte, under GSPMD placement.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.jit import TrainStep

DEG = 4


@pytest.fixture
def shard_mesh():
    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh_mod.build_mesh(
        {"sharding": DEG}, devices=jax.devices()[:DEG]))
    yield
    mesh_mod.set_mesh(prev)


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(64, 128)
        self.fc2 = nn.Linear(128, 64)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _run_steps(model, optimizer, steps=2):
    step = TrainStep(model, lambda out, lbl: ((out - lbl) ** 2).mean(),
                     optimizer)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(8, 64).astype("f4"))
    y = paddle.to_tensor(rs.randn(8, 64).astype("f4"))
    for _ in range(steps):
        loss = step(inputs=(x,), labels=(y,))
    return float(loss), step


def _trainable_params(step):
    fm = step.fm
    return [p for p, m in zip(fm.params, fm.trainable_mask) if m]


def _shard_bytes(arr):
    sharding = arr.sharding
    shape = sharding.shard_shape(arr.shape)
    return int(np.prod(shape)) * arr.dtype.itemsize


def test_stage3_params_and_slots_shrink_per_device(shard_mesh):
    model = Net()
    optimizer = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    model, optimizer, _ = group_sharded_parallel(model, optimizer, "p_g_os")
    loss, step = _run_steps(model, optimizer)
    assert np.isfinite(loss)

    shardable = 0
    for p, slots in zip(_trainable_params(step), step._slots):
        full = p._value.size * p._value.dtype.itemsize
        if getattr(p, "dist_spec", None) is not None:
            assert _shard_bytes(p._value) * DEG == full, p.name
            shardable += 1
            # matching slots shard identically
            for name, s in slots.items():
                if s.shape == p._value.shape:
                    assert _shard_bytes(s) * DEG == s.size * s.dtype.itemsize
    assert shardable >= 2  # both weight matrices sharded


def test_stage2_slots_shard_params_stay_replicated(shard_mesh):
    model = Net()
    optimizer = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    model, optimizer, _ = group_sharded_parallel(model, optimizer, "os_g")
    loss, step = _run_steps(model, optimizer)
    assert np.isfinite(loss)

    for p, slots in zip(_trainable_params(step), step._slots):
        # params replicated: shard == full
        full = p._value.size * p._value.dtype.itemsize
        assert _shard_bytes(p._value) == full
        for name, s in slots.items():
            if s.shape == p._value.shape and any(
                    dim % DEG == 0 and dim >= DEG for dim in s.shape):
                assert _shard_bytes(s) * DEG == s.size * s.dtype.itemsize, \
                    (p.name, name)


def test_stage3_matches_unsharded_losses(shard_mesh):
    def run(level):
        paddle.seed(0)
        model = Net()
        optimizer = opt.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        if level:
            model, optimizer, _ = group_sharded_parallel(
                model, optimizer, level)
        return _run_steps(model, optimizer, steps=3)[0]

    base = run(None)
    z3 = run("p_g_os")
    np.testing.assert_allclose(z3, base, rtol=1e-5)

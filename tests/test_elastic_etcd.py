"""Elastic membership over the real etcd3 wire protocol (VERDICT r4 #6).

Reference: python/paddle/distributed/fleet/elastic/manager.py:245-282
(etcd3 leases, keepalives, prefix watches). The client under test speaks
etcd's v3 JSON/HTTP gateway; the fake server (tests/etcd3_fake.py) is
socket-level — every lease grant, keepalive, put-with-lease, range,
delete and streaming watch event crosses a real TCP connection in the
gateway's JSON mapping. Scale-up and node-death both drive endpoint
rewrite + process relaunch through that wire.
"""
import subprocess
import sys
import threading
import time

import pytest

from etcd3_fake import Etcd3Fake
from paddle_tpu.distributed.fleet.elastic import (
    ElasticController, ElasticManager, ElasticStatus,
)
from paddle_tpu.distributed.fleet.elastic.etcd_store import Etcd3GatewayStore


@pytest.fixture
def etcd():
    fake = Etcd3Fake().start()
    yield fake
    fake.stop()


def test_store_roundtrip_and_lease_ttl_over_the_wire(etcd):
    st = Etcd3GatewayStore(etcd.endpoint)
    st.put("/j/nodes/a", "a", ttl=1)
    st.put("/j/nodes/b", "b", ttl=30)
    st.put("/j/other", "x")
    assert st.get_prefix("/j/nodes") == [("/j/nodes/a", "a"),
                                        ("/j/nodes/b", "b")]
    # lease expiry drops only the 1s key
    time.sleep(1.4)
    assert st.get_prefix("/j/nodes") == [("/j/nodes/b", "b")]
    st.delete("/j/nodes/b")
    assert st.get_prefix("/j/nodes") == []


def test_refresh_keepalive_extends_lease(etcd):
    st = Etcd3GatewayStore(etcd.endpoint)
    st.put("/j/nodes/a", "a", ttl=1)
    for _ in range(4):
        time.sleep(0.5)
        st.refresh("/j/nodes/a", ttl=1)   # keepalive, not re-grant
    assert st.get_prefix("/j/nodes") == [("/j/nodes/a", "a")]
    time.sleep(1.4)   # stop refreshing -> expiry
    assert st.get_prefix("/j/nodes") == []


def test_watch_prefix_streams_put_and_delete_events(etcd):
    st = Etcd3GatewayStore(etcd.endpoint)
    events, got = [], threading.Event()

    def handler(typ, key, value):
        events.append((typ, key, value))
        if len(events) >= 2:
            got.set()

    t, stop = st.watch_prefix("/j/nodes", handler)
    time.sleep(0.3)  # let the watch register
    st.put("/j/nodes/a", "a", ttl=30)
    st.delete("/j/nodes/a")
    assert got.wait(timeout=10), events
    stop.set()
    assert ("PUT", "/j/nodes/a", "a") in events
    assert ("DELETE", "/j/nodes/a", None) in events
    # the stop event must actually unblock the pump: a quiet stream used
    # to leave the thread (and its socket) blocked in read() forever
    t.join(timeout=5)
    assert not t.is_alive(), "watch pump thread leaked after stop.set()"


def test_watch_prefix_caller_event_and_idle_stream_exit(etcd):
    """A CALLER-provided stop event (no close-on-set hook) must still exit
    the pump via the read-timeout re-check — on a stream with NO traffic
    at all, the worst case for the old blocking read."""
    st = Etcd3GatewayStore(etcd.endpoint)
    stop = threading.Event()
    t, stop2 = st.watch_prefix("/j/quiet", lambda *a: None,
                               stop_event=stop, poll_timeout=0.2)
    assert stop2 is stop
    time.sleep(0.3)   # watch registered, stream idle
    stop.set()
    t.join(timeout=5)
    assert not t.is_alive(), "watch pump did not exit on caller stop event"


def test_managers_scale_up_and_ttl_death_over_wire(etcd):
    a = ElasticManager("hostA", "1:2", store=Etcd3GatewayStore(etcd.endpoint),
                       job_id="j2", ttl=1, heartbeat_interval=0.3)
    b = ElasticManager("hostB", "1:2", store=Etcd3GatewayStore(etcd.endpoint),
                       job_id="j2", ttl=1, heartbeat_interval=0.3)
    a.start_heartbeat()
    assert a.wait_for_np(timeout=10)
    assert a.pod_status() == ElasticStatus.COMPLETED
    # scale-up: B joins -> A sees RESTART with rewritten endpoints
    b.start_heartbeat()
    deadline = time.time() + 10
    while time.time() < deadline:
        if a.pod_status() == ElasticStatus.RESTART:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("scale-up never detected")
    assert a.endpoints() == ["hostA:8091", "hostB:8091"]
    # node death: B stops heartbeating (no graceful delete) -> TTL drop
    b._stop.set()
    deadline = time.time() + 10
    while time.time() < deadline:
        if a.pod_status() == ElasticStatus.RESTART:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("node death never detected")
    assert a.endpoints() == ["hostA:8091"]
    a.stop()


WORKER = ("import os, sys, time; "
          "open(os.environ['LIFE_LOG'], 'a').write("
          "os.environ['EPS'] + chr(10)); "
          "time.sleep(float(os.environ.get('LIFE_SLEEP', '30')))")


def test_controller_relaunches_on_scale_events_e2e(etcd, tmp_path):
    """The full loop through the wire: launch with 1 node's endpoints,
    scale-up rewrites endpoints and relaunches, node death rewrites and
    relaunches again, then the life runs to completion."""
    import os

    life_log = str(tmp_path / "lives.log")
    lives_seen = []

    def launch_fn(eps):
        lives_seen.append(list(eps))
        env = dict(os.environ, EPS=",".join(eps), LIFE_LOG=life_log,
                   LIFE_SLEEP="4.0" if len(lives_seen) >= 3 else "60")
        return [subprocess.Popen([sys.executable, "-c", WORKER], env=env)]

    # ttl=3 with 0.3s beats: under full-suite load a busy scheduler must
    # not starve a heartbeat past the lease (spurious TTL drops made this
    # flaky at ttl=1)
    mgr = ElasticManager("hostA", "1:2",
                         store=Etcd3GatewayStore(etcd.endpoint),
                         job_id="j3", ttl=3, heartbeat_interval=0.3)
    peer = ElasticManager("hostB", "1:2",
                          store=Etcd3GatewayStore(etcd.endpoint),
                          job_id="j3", ttl=3, heartbeat_interval=0.3)
    ctl = ElasticController(mgr, launch_fn, poll_interval=0.1)

    def choreography():
        time.sleep(1.5)
        peer.start_heartbeat()   # scale-up -> relaunch with 2 endpoints
        time.sleep(3.0)
        peer._stop.set()         # node death -> relaunch with 1 endpoint
    t = threading.Thread(target=choreography, daemon=True)
    t.start()
    rc = ctl.run(np_timeout=30)
    assert rc == 0
    assert lives_seen[0] == ["hostA:8091"]
    assert ["hostA:8091", "hostB:8091"] in lives_seen
    assert lives_seen[-1] == ["hostA:8091"]
    # worker-side view (a life terminated before its first write may be
    # absent — lives_seen above pins the launch ordering)
    logged = open(life_log).read().strip().splitlines()
    assert "hostA:8091,hostB:8091" in logged
    assert logged[-1] == "hostA:8091"


def test_launch_cli_elastic_server_flag(etcd, tmp_path):
    """--elastic_server etcd://host:port drives the whole launcher flow
    through the gateway wire: register, wait for np, launch with
    membership-derived endpoints, complete."""
    import os
    import textwrap

    from paddle_tpu.distributed.launch.main import _parse_args, launch

    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import os
        with open(os.environ["OUT"], "w") as f:
            f.write(os.environ["PADDLE_TRAINER_ENDPOINTS"])
    """))
    out_file = str(tmp_path / "eps.txt")
    os.environ["OUT"] = out_file
    try:
        rc = launch(_parse_args([
            "--elastic_server", f"etcd://{etcd.endpoint}",
            "--nnodes", "1:2", "--job_id", "jcli",
            "--log_dir", str(tmp_path / "log"), str(script)]))
    finally:
        os.environ.pop("OUT", None)
    assert rc == 0
    assert open(out_file).read() == "127.0.0.1:8091"
    # the node deregistered on completion
    st = Etcd3GatewayStore(etcd.endpoint)
    assert st.get_prefix("/paddle_tpu/elastic/jcli") == []


def test_controller_relaunches_crashed_worker(etcd):
    """A worker exiting non-zero triggers terminate-the-rest + relaunch
    (elastic fault tolerance), not an indefinite hang on its peers."""
    lives = []

    def launch_fn(eps):
        lives.append(list(eps))
        if len(lives) == 1:
            # life 1: one crasher + one hanger (peer stuck in a collective)
            return [subprocess.Popen([sys.executable, "-c", "raise SystemExit(3)"]),
                    subprocess.Popen([sys.executable, "-c",
                                      "import time; time.sleep(60)"])]
        return [subprocess.Popen([sys.executable, "-c", "pass"])]

    mgr = ElasticManager("hostA", "1", store=Etcd3GatewayStore(etcd.endpoint),
                         job_id="j4", ttl=2, heartbeat_interval=0.3)
    rc = ElasticController(mgr, launch_fn, poll_interval=0.1).run(
        np_timeout=15)
    assert rc == 0
    assert len(lives) == 2


def test_wire_heartbeat_survives_gateway_outage():
    """The etcd gateway dies mid-job and comes back on the same port:
    the manager's re-register heartbeat must ride out the outage and the
    node must rejoin (the LocalKVStore outage tests, now over the wire)."""
    fake = Etcd3Fake().start()
    host, port = fake.endpoint.rsplit(":", 1)
    mgr = ElasticManager("hostA", "1",
                         store=Etcd3GatewayStore(fake.endpoint),
                         job_id="j9", ttl=2, heartbeat_interval=0.2)
    mgr.start_heartbeat()
    try:
        assert mgr.wait_for_np(timeout=10)
        fake.stop()              # outage: every rpc now fails
        time.sleep(1.0)          # heartbeats fail + lease would expire
        fake2 = Etcd3Fake(port=int(port)).start()  # same port, fresh state
        try:
            deadline = time.time() + 10
            members = []
            while time.time() < deadline:
                try:
                    members = mgr.members()
                except Exception:
                    members = []  # poll races the rebind
                if len(members) == 1:
                    break
                time.sleep(0.2)
            assert members == ["hostA"], "node never rejoined"
        finally:
            fake2.stop()
    finally:
        mgr.stop()

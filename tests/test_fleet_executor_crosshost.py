"""Cross-process fleet-executor MessageBus (VERDICT r4 #3).

The reference routes interceptor messages between ranks over brpc
(fleet_executor/message_bus.cc:180); round 4's bus was process-local.
Here TWO spawned processes each build the same global task graph with
their own rank, wire bus endpoints over TCP, and run micro-batches
through a pipeline whose edge crosses the process boundary:

    task0 (rank 0, x -> x*2) --socket--> task1 (rank 1, x -> x+3, sink)

max_run_times=1 on the downstream makes the schedule strict-lockstep:
after the first DATA frame, every further send REQUIRES a CREDIT frame
to cross back rank1 -> rank0, so completion itself proves bidirectional
credit + data flow over the wire; rank 0 additionally counts the CREDIT
frames it received. Payloads are numpy arrays (the distributed/ps TLV
framing, no pickle).
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_MB = 6

WORKER = textwrap.dedent("""
    import sys
    rank = int(sys.argv[1]); port0 = int(sys.argv[2]); port1 = int(sys.argv[3])
    sys.path.insert(0, {repo!r})
    import numpy as np
    from paddle_tpu.distributed.fleet_executor import (
        CREDIT, FleetExecutor, TaskNode,
    )

    nodes = [
        TaskNode(0, rank=0, fn=lambda x: x * 2, downstream=[1],
                 max_run_times=1),
        TaskNode(1, rank=1, fn=lambda x: x + 3, max_run_times=1),
    ]
    exe = FleetExecutor(nodes, rank=rank)
    my_port = port0 if rank == 0 else port1
    exe.endpoint(host="127.0.0.1", port=my_port)
    exe.connect(1 - rank, "127.0.0.1:" + str(port1 if rank == 0 else port0))

    credits_seen = []
    if rank == 0:
        orig = exe.carrier.bus._deliver_local
        def spy(msg):
            if msg.type == CREDIT:
                credits_seen.append(msg.src_id)
            orig(msg)
        exe.carrier.bus._deliver_local = spy

    mbs = [np.full((4,), i, np.float32) for i in range({n_mb})]
    outs = exe.run(mbs, timeout=60)
    if rank == 0:
        assert outs == [], outs
        exe.shutdown()            # DONE flood drains the remote stage too
        exe.wait(timeout=60)
        # strict lockstep: task1 acked every one of the {n_mb} DATA frames
        assert len(credits_seen) == {n_mb}, credits_seen
        assert set(credits_seen) == {{1}}, credits_seen
        print("RANK0-OK credits=", len(credits_seen))
    else:
        got = np.stack(outs)
        want = np.stack([m * 2 + 3 for m in mbs])
        np.testing.assert_allclose(got, want)
        exe.wait(timeout=60)
        print("RANK1-OK outs=", len(outs))
""").format(repo=REPO, n_mb=N_MB)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_interceptor_messages_cross_process_boundary(tmp_path):
    port0, port1 = _free_port(), _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(r), str(port0), str(port1)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for r in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    assert "RANK0-OK" in outs[0] and f"credits= {N_MB}" in outs[0], outs[0]
    assert "RANK1-OK" in outs[1] and f"outs= {N_MB}" in outs[1], outs[1]


COMPILED_WORKER = textwrap.dedent("""
    import sys
    rank = int(sys.argv[1]); port0 = int(sys.argv[2]); port1 = int(sys.argv[3])
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from paddle_tpu.distributed.fleet_executor import FleetExecutor, TaskNode

    # rank 1 hosts a COMPILED model stage (the DistModel-style serving
    # shape: host control plane moves tensors, XLA runs each stage)
    if rank == 1:
        import paddle_tpu as paddle
        net = paddle.nn.Linear(4, 2)
        W = np.arange(8, dtype=np.float32).reshape(4, 2)
        net.weight.set_value(paddle.to_tensor(W))
        net.bias.set_value(paddle.to_tensor(np.zeros(2, np.float32)))
        from paddle_tpu.jit import to_static
        fwd = to_static(lambda x: net(x))
        stage_fn = lambda x: np.asarray(fwd(paddle.to_tensor(x)).numpy())
    else:
        stage_fn = None

    nodes = [
        TaskNode(0, rank=0, fn=lambda x: (x - 1.0) / 2.0, downstream=[1]),
        TaskNode(1, rank=1, fn=stage_fn),
    ]
    exe = FleetExecutor(nodes, rank=rank)
    my_port = port0 if rank == 0 else port1
    exe.endpoint(host="127.0.0.1", port=my_port)
    exe.connect(1 - rank, "127.0.0.1:" + str(port1 if rank == 0 else port0))

    mbs = [np.full((3, 4), 1.0 + 2.0 * i, np.float32) for i in range(4)]
    outs = exe.run(mbs, timeout=60)
    if rank == 0:
        exe.shutdown()
        exe.wait(timeout=60)
        print("RANK0-OK")
    else:
        W = np.arange(8, dtype=np.float32).reshape(4, 2)
        for i, o in enumerate(outs):
            want = np.full((3, 4), float(i), np.float32) @ W
            np.testing.assert_allclose(o, want, rtol=1e-6)
        exe.wait(timeout=60)
        print("RANK1-OK compiled outs=", len(outs))
""").format(repo=REPO)


def test_compiled_model_stage_serves_across_processes():
    """DistModel-style serving: rank 0 preprocesses, rank 1 runs a
    COMPILED forward per micro-batch; activations cross the socket as
    numpy tensors through the interceptor bus."""
    port0, port1 = _free_port(), _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", COMPILED_WORKER, str(r), str(port0),
             str(port1)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
    assert "RANK1-OK compiled outs= 4" in outs[1], outs[1]

"""Public-API surface parity against the reference's __all__ exports.

Walks the reference package's __all__ lists (parsed statically from
/root/reference, no reference import) and asserts every name resolves on
the corresponding paddle_tpu module. This is the line-by-line inventory
check of the judge — kept as a test so regressions surface immediately.
"""
import ast
import importlib
import os

import pytest

REF = "/root/reference/python/paddle/"

MODULES = [
    ("", "__init__.py"),
    ("nn", "nn/__init__.py"),
    ("nn.functional", "nn/functional/__init__.py"),
    ("nn.initializer", "nn/initializer/__init__.py"),
    ("static", "static/__init__.py"),
    ("static.nn", "static/nn/__init__.py"),
    ("distributed", "distributed/__init__.py"),
    ("distributed.fleet", "distributed/fleet/__init__.py"),
    ("io", "io/__init__.py"),
    ("metric", "metric/__init__.py"),
    ("vision.models", "vision/models/__init__.py"),
    ("vision.transforms", "vision/transforms/__init__.py"),
    ("vision.datasets", "vision/datasets/__init__.py"),
    ("vision.ops", "vision/ops.py"),
    ("text", "text/__init__.py"),
    ("optimizer", "optimizer/__init__.py"),
    ("optimizer.lr", "optimizer/lr.py"),
    ("fft", "fft.py"),
    ("signal", "signal.py"),
    ("amp", "amp/__init__.py"),
    ("autograd", "autograd/__init__.py"),
    ("jit", "jit/__init__.py"),
    ("onnx", "onnx/__init__.py"),
    ("distribution", "distribution/__init__.py"),
    ("device", "device/__init__.py"),
    ("utils", "utils/__init__.py"),
    ("incubate", "incubate/__init__.py"),
]


def _ref_all(path):
    tree = ast.parse(open(path).read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", "") == "__all__":
                    try:
                        return set(ast.literal_eval(node.value))
                    except (ValueError, SyntaxError):
                        return None
    return None


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
@pytest.mark.parametrize("mod,rel", MODULES,
                         ids=[m or "paddle" for m, _ in MODULES])
def test_public_all_names_resolve(mod, rel):
    ref_names = _ref_all(REF + rel)
    assert ref_names, f"no __all__ found in reference {rel}"
    target = importlib.import_module(
        "paddle_tpu" + (("." + mod) if mod else ""))
    missing = sorted(n for n in ref_names if not hasattr(target, n))
    assert not missing, (
        f"paddle_tpu.{mod or ''} is missing {len(missing)} reference "
        f"names: {missing}")

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim


def quad_problem():
    """min ||Wx - y||^2 over W."""
    paddle.seed(1)
    net = nn.Linear(4, 4, bias_attr=False)
    x = paddle.to_tensor(np.random.RandomState(0).rand(16, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).rand(16, 4).astype(np.float32))
    return net, x, y


def run_steps(net, x, y, opt, n=60):
    first = None
    for _ in range(n):
        loss = nn.functional.mse_loss(net(x), y)
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        opt.step()
        opt.clear_grad()
    return first, float(loss.numpy())


@pytest.mark.parametrize(
    "cls,kw",
    [
        (optim.SGD, dict(learning_rate=0.1)),
        (optim.Momentum, dict(learning_rate=0.05, momentum=0.9)),
        (optim.Momentum, dict(learning_rate=0.05, momentum=0.9, use_nesterov=True)),
        (optim.Adam, dict(learning_rate=0.05)),
        (optim.AdamW, dict(learning_rate=0.05, weight_decay=0.01)),
        (optim.Adamax, dict(learning_rate=0.05)),
        (optim.Adagrad, dict(learning_rate=0.2)),
        (optim.Adadelta, dict(learning_rate=1.0)),
        (optim.RMSProp, dict(learning_rate=0.01)),
        (optim.Lamb, dict(learning_rate=0.05)),
        (optim.Lars, dict(learning_rate=1.0, lars_coeff=0.01)),
    ],
)
def test_optimizer_converges(cls, kw):
    net, x, y = quad_problem()
    opt = cls(parameters=net.parameters(), **kw)
    # adadelta's update magnitude bootstraps from zero; needs a longer run
    n = 400 if cls is optim.Adadelta else 60
    first, last = run_steps(net, x, y, opt, n=n)
    assert last < first * 0.5, f"{cls.__name__}: {first} -> {last}"


def test_sgd_matches_manual():
    net, x, y = quad_problem()
    w0 = net.weight.numpy().copy()
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    loss = nn.functional.mse_loss(net(x), y)
    loss.backward()
    g = net.weight.grad.numpy().copy()
    opt.step()
    np.testing.assert_allclose(net.weight.numpy(), w0 - 0.1 * g, rtol=1e-5, atol=1e-6)


def test_grad_clip_global_norm():
    net, x, y = quad_problem()
    clip = nn.ClipGradByGlobalNorm(1e-4)
    opt = optim.SGD(learning_rate=1.0, parameters=net.parameters(), grad_clip=clip)
    w0 = net.weight.numpy().copy()
    loss = nn.functional.mse_loss(net(x), y)
    loss.backward()
    opt.step()
    delta = np.abs(net.weight.numpy() - w0).sum()
    assert delta < 1e-3  # clipped to tiny norm


def test_weight_decay_l2():
    net, x, y = quad_problem()
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters(), weight_decay=0.5)
    w0 = net.weight.numpy().copy()
    loss = nn.functional.mse_loss(net(x), y)
    loss.backward()
    g = net.weight.grad.numpy().copy()
    opt.step()
    np.testing.assert_allclose(
        net.weight.numpy(), w0 - 0.1 * (g + 0.5 * w0), rtol=1e-4, atol=1e-6
    )


def test_optimizer_state_dict_roundtrip():
    net, x, y = quad_problem()
    opt = optim.Adam(learning_rate=0.05, parameters=net.parameters())
    run_steps(net, x, y, opt, n=5)
    sd = opt.state_dict()
    opt2 = optim.Adam(learning_rate=0.05, parameters=net.parameters())
    opt2.set_state_dict(sd)
    k = [k for k in sd if k.endswith("moment1")][0]
    p = net.parameters()[0]
    np.testing.assert_allclose(np.asarray(opt2._slots[id(p)]["moment1"]), sd[k])


class TestLRSchedulers:
    def test_step_decay(self):
        s = optim.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        vals = []
        for _ in range(5):
            vals.append(round(s(), 5))
            s.step()
        assert vals == [0.1, 0.1, 0.05, 0.05, 0.025]

    def test_multistep(self):
        s = optim.lr.MultiStepDecay(0.1, milestones=[2, 4], gamma=0.1)
        vals = [s() for _ in range(5) if s.step() is None]
        assert round(vals[-1], 6) == 0.001

    def test_cosine(self):
        s = optim.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert s() == pytest.approx(1.0)
        for _ in range(10):
            s.step()
        assert s() == pytest.approx(0.0, abs=1e-6)

    def test_warmup(self):
        s = optim.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
        assert s() == pytest.approx(0.0)
        for _ in range(10):
            s.step()
        assert s() == pytest.approx(0.1)

    def test_noam(self):
        s = optim.lr.NoamDecay(d_model=512, warmup_steps=100)
        for _ in range(100):
            s.step()
        peak = s()
        for _ in range(400):
            s.step()
        assert s() < peak

    def test_scheduler_with_optimizer(self):
        net, x, y = quad_problem()
        sched = optim.lr.StepDecay(0.1, step_size=1, gamma=0.5)
        opt = optim.SGD(learning_rate=sched, parameters=net.parameters())
        assert opt.get_lr() == pytest.approx(0.1)
        sched.step()
        assert opt.get_lr() == pytest.approx(0.05)

    def test_reduce_on_plateau(self):
        s = optim.lr.ReduceOnPlateau(0.1, patience=1, factor=0.1)
        for loss in [1.0, 1.0, 1.0, 1.0]:
            s.step(loss)
        assert s() == pytest.approx(0.01, rel=1e-3)


def test_dgc_momentum_sparse_updates():
    """DGC: only top-(1-sparsity) gradient magnitudes update immediately;
    the rest accumulate locally and land once they grow (reference:
    dgc_momentum_op.h numerical semantics)."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt

    p = paddle.to_tensor(np.zeros(8, np.float32))
    p.stop_gradient = False
    o = opt.DGCMomentum(learning_rate=1.0, momentum=0.0, parameters=[p],
                        sparsity=[0.75])
    g = np.array([4.0, 0.1, 0.1, 0.1, 3.0, 0.1, 0.1, 0.1], np.float32)
    p.grad = paddle.to_tensor(g)
    o.step()
    vals = p.numpy()
    # top-25% = 2 entries (the 4.0 and 3.0) applied; others accumulated
    assert vals[0] == -4.0 and vals[4] == -3.0
    np.testing.assert_allclose(vals[[1, 2, 3, 5, 6, 7]], 0.0)
    # accumulate the small grads until they cross the threshold
    for _ in range(2):
        p.grad = paddle.to_tensor(np.full(8, 0.1, np.float32))
        o.step()
    # small entries eventually move (accumulated 0.3 beats fresh 0.1)
    assert (p.numpy()[[1, 2, 3, 5, 6, 7]] < 0).any()


def test_dgc_rampup_dense_before_begin():
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt

    p = paddle.to_tensor(np.zeros(4, np.float32))
    p.stop_gradient = False
    o = opt.DGCMomentum(learning_rate=1.0, momentum=0.0, parameters=[p],
                        rampup_begin_step=100, sparsity=[0.75])
    p.grad = paddle.to_tensor(np.ones(4, np.float32))
    o.step()
    np.testing.assert_allclose(p.numpy(), -1.0)  # dense update


def test_dgc_rampup_step_schedule():
    """Each sparsity level holds rampup_step/len(sparsity) steps
    (reference dgc_op get_period_sparsity)."""
    p = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
    o = optim.DGCMomentum(learning_rate=1.0, momentum=0.0, parameters=[p],
                          rampup_begin_step=0, rampup_step=6,
                          sparsity=[0.25, 0.5, 0.75])
    # levels hold for 6/3 = 2 steps each
    for step, expect in [(0, 0.25), (1, 0.25), (2, 0.5), (3, 0.5),
                         (4, 0.75), (5, 0.75), (9, 0.75)]:
        o._accumulated_steps = step
        assert o._cur_sparsity() == expect, (step, o._cur_sparsity())


def test_set_state_dict_shifted_names_fall_back_to_positional():
    """Auto-generated names shift with the unique_name counter between
    builds, so a PARTIAL name overlap can label a different param with a
    checkpoint name; only a fully-consistent name set may be trusted —
    otherwise alignment is positional (ADVICE r4)."""
    x = paddle.to_tensor(np.random.RandomState(3).rand(8, 4).astype(np.float32))

    def build(names):
        la, lb = nn.Linear(4, 4), nn.Linear(4, 4)
        pa, pb = la.weight, lb.weight
        pa.name, pb.name = names
        la.bias.stop_gradient = lb.bias.stop_gradient = True
        return la, lb, pa, pb

    la, lb, pa, pb = build(("linear_0.w_0", "linear_1.w_0"))
    opt = optim.Adam(learning_rate=0.05, parameters=[pa, pb])
    (la(x).sum() + 2.0 * lb(x).sum()).backward()
    opt.step()
    sd = opt.state_dict()
    m0 = np.asarray(sd["linear_0.w_0.moment1"])
    m1 = np.asarray(sd["linear_1.w_0.moment1"])
    assert not np.allclose(m0, m1)

    # rebuild with shifted names: 'linear_1.w_0' now names the FIRST
    # param — name matching would hand it the checkpoint's SECOND state
    lc, ld, pc, pd = build(("linear_1.w_0", "linear_2.w_0"))
    opt2 = optim.Adam(learning_rate=0.05, parameters=[pc, pd])
    opt2.set_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(pc)]["moment1"]), m0)
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(pd)]["moment1"]), m1)


def test_set_state_dict_trusts_names_on_containment():
    """Frozen-param and superset-checkpoint loads keep exact-name
    matching: names are distrusted only on genuine partial overlap."""
    x = paddle.to_tensor(np.random.RandomState(4).rand(8, 4).astype(np.float32))
    la, lb = nn.Linear(4, 4), nn.Linear(4, 4)
    pa, pb = la.weight, lb.weight
    pa.name, pb.name = "enc.w_0", "dec.w_0"
    la.bias.stop_gradient = lb.bias.stop_gradient = True
    opt = optim.Adam(learning_rate=0.05, parameters=[pa, pb])
    (la(x).sum() + 2.0 * lb(x).sum()).backward()
    opt.step()
    sd = opt.state_dict()
    m_dec = np.asarray(sd["dec.w_0.moment1"])

    # superset checkpoint into a submodel: current names ⊆ saved prefixes,
    # and the surviving param is NOT the positionally-first one
    sub = nn.Linear(4, 4)
    sub.weight.name = "dec.w_0"
    sub.bias.stop_gradient = True
    opt2 = optim.Adam(learning_rate=0.05, parameters=[sub.weight])
    opt2.set_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(sub.weight)]["moment1"]), m_dec)

    # frozen param after reload: saved prefixes ⊆ all current names —
    # the remaining trainable param must keep ITS state, not inherit the
    # frozen one's positionally
    lc, ld = nn.Linear(4, 4), nn.Linear(4, 4)
    lc.weight.name, ld.weight.name = "enc.w_0", "dec.w_0"
    lc.bias.stop_gradient = ld.bias.stop_gradient = True
    lc.weight.stop_gradient = True
    opt3 = optim.Adam(learning_rate=0.05,
                      parameters=[lc.weight, ld.weight])
    opt3.set_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(opt3._slots[id(ld.weight)]["moment1"]), m_dec)


def test_set_state_dict_user_names_always_trusted():
    """User-chosen names (weight_attr.name) keep exact-name matching even
    on partial structural overlap — only AUTO-generated names are
    distrusted (they shift with the unique_name counter)."""
    import paddle_tpu

    x = paddle.to_tensor(np.random.RandomState(5).rand(8, 4).astype(np.float32))
    wa = lambda n: paddle_tpu.ParamAttr(name=n)
    la = nn.Linear(4, 4, weight_attr=wa("head.w"), bias_attr=False)
    lb = nn.Linear(4, 4, weight_attr=wa("enc.w"), bias_attr=False)
    opt = optim.Adam(learning_rate=0.05,
                     parameters=[la.weight, lb.weight])
    (la(x).sum() + 2.0 * lb(x).sum()).backward()
    opt.step()
    sd = opt.state_dict()
    m_enc = np.asarray(sd["enc.w.moment1"])

    # model B replaced the head: [enc.w, newhead.w] — enc.w must load its
    # own state by name, not head.w's positionally
    lc = nn.Linear(4, 4, weight_attr=wa("enc.w"), bias_attr=False)
    ld = nn.Linear(4, 4, weight_attr=wa("newhead.w"), bias_attr=False)
    opt2 = optim.Adam(learning_rate=0.05,
                      parameters=[lc.weight, ld.weight])
    opt2.set_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(lc.weight)]["moment1"]), m_enc)

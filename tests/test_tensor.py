import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Parameter


class TestCreation:
    def test_to_tensor(self):
        t = paddle.to_tensor([1.0, 2.0, 3.0])
        assert t.shape == [3]
        assert t.dtype == np.float32
        np.testing.assert_array_equal(t.numpy(), [1, 2, 3])

    def test_dtype_conversion(self):
        t = paddle.to_tensor([1, 2], dtype="float32")
        assert t.dtype == np.float32
        assert t.astype("int32").dtype == np.int32
        # int64 narrows to int32 (x64 off)
        assert paddle.to_tensor([1], dtype="int64").dtype == np.int32

    def test_zeros_ones_full(self):
        assert paddle.zeros([2, 3]).numpy().sum() == 0
        assert paddle.ones([2, 3]).numpy().sum() == 6
        np.testing.assert_array_equal(paddle.full([2], 7, "float32").numpy(), [7, 7])
        np.testing.assert_array_equal(
            paddle.ones_like(paddle.zeros([4])).numpy(), np.ones(4)
        )

    def test_arange_linspace_eye(self):
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(
            paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6
        )
        np.testing.assert_array_equal(paddle.eye(3).numpy(), np.eye(3, dtype=np.float32))

    def test_default_dtype(self):
        paddle.set_default_dtype("float32")
        assert paddle.get_default_dtype() == np.float32
        t = paddle.to_tensor(np.array([1.5], dtype=np.float64))
        assert t.dtype == np.float32


class TestTensorSemantics:
    def test_item_and_scalar(self):
        t = paddle.to_tensor(3.5)
        assert t.item() == pytest.approx(3.5)
        assert float(t) == pytest.approx(3.5)
        assert int(paddle.to_tensor(7)) == 7

    def test_indexing(self):
        t = paddle.arange(12).reshape([3, 4])
        assert t[1, 2].item() == 6
        np.testing.assert_array_equal(t[1].numpy(), [4, 5, 6, 7])
        np.testing.assert_array_equal(t[:, 1].numpy(), [1, 5, 9])
        np.testing.assert_array_equal(t[::2].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])
        # tensor index
        idx = paddle.to_tensor([0, 2])
        np.testing.assert_array_equal(t[idx].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])

    def test_setitem(self):
        t = paddle.zeros([3, 3])
        t[1] = 5.0
        assert t.numpy()[1].sum() == 15
        t[0, 0] = paddle.to_tensor(2.0)
        assert t[0, 0].item() == 2

    def test_inplace_set_value(self):
        t = paddle.zeros([2, 2])
        t.set_value(np.ones((2, 2), np.float32))
        assert t.numpy().sum() == 4
        with pytest.raises(ValueError):
            t.set_value(np.ones((3, 3), np.float32))

    def test_operators(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([3.0, 4.0])
        np.testing.assert_allclose((a + b).numpy(), [4, 6])
        np.testing.assert_allclose((a - b).numpy(), [-2, -2])
        np.testing.assert_allclose((a * b).numpy(), [3, 8])
        np.testing.assert_allclose((b / a).numpy(), [3, 2])
        np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
        np.testing.assert_allclose((2 + a).numpy(), [3, 4])
        np.testing.assert_allclose((-a).numpy(), [-1, -2])
        np.testing.assert_array_equal((a < b).numpy(), [True, True])
        np.testing.assert_array_equal((a == a).numpy(), [True, True])

    def test_iteration_len(self):
        t = paddle.arange(6).reshape([3, 2])
        assert len(t) == 3
        rows = [r.numpy() for r in t]
        assert len(rows) == 3

    def test_detach_clone(self):
        t = paddle.to_tensor([1.0], stop_gradient=False)
        d = t.detach()
        assert d.stop_gradient
        c = t.clone()
        assert not c.stop_gradient

    def test_parameter(self):
        p = Parameter(np.zeros((2, 2), np.float32))
        assert not p.stop_gradient
        assert p.trainable
        p.trainable = False
        assert p.stop_gradient


class TestManipulation:
    def test_reshape_transpose(self):
        t = paddle.arange(6).reshape([2, 3])
        assert paddle.transpose(t, [1, 0]).shape == [3, 2]
        assert t.T.shape == [3, 2]
        assert paddle.flatten(t).shape == [6]

    def test_concat_stack_split(self):
        a = paddle.ones([2, 3])
        b = paddle.zeros([2, 3])
        assert paddle.concat([a, b], axis=0).shape == [4, 3]
        assert paddle.stack([a, b]).shape == [2, 2, 3]
        parts = paddle.split(paddle.arange(10), 2)
        assert parts[0].shape == [5]
        parts = paddle.split(paddle.arange(10), [3, 7])
        assert parts[1].shape == [7]
        parts = paddle.split(paddle.arange(10), [3, -1])
        assert parts[1].shape == [7]

    def test_squeeze_unsqueeze_expand(self):
        t = paddle.ones([1, 3, 1])
        assert paddle.squeeze(t).shape == [3]
        assert paddle.squeeze(t, 0).shape == [3, 1]
        assert paddle.unsqueeze(paddle.ones([3]), [0, 2]).shape == [1, 3, 1]
        assert paddle.expand(paddle.ones([1, 3]), [4, 3]).shape == [4, 3]

    def test_gather_scatter(self):
        t = paddle.arange(12, dtype="float32").reshape([4, 3])
        g = paddle.gather(t, paddle.to_tensor([0, 2]), axis=0)
        np.testing.assert_array_equal(g.numpy(), [[0, 1, 2], [6, 7, 8]])
        s = paddle.scatter(
            paddle.zeros([4, 2]),
            paddle.to_tensor([1, 3]),
            paddle.ones([2, 2]),
        )
        assert s.numpy()[1].sum() == 2 and s.numpy()[3].sum() == 2

    def test_where_topk_sort(self):
        x = paddle.to_tensor([3.0, 1.0, 2.0])
        v, i = paddle.topk(x, 2)
        np.testing.assert_array_equal(v.numpy(), [3, 2])
        np.testing.assert_array_equal(i.numpy(), [0, 2])
        s = paddle.sort(x)
        np.testing.assert_array_equal(s.numpy(), [1, 2, 3])
        w = paddle.where(x > 1.5, x, paddle.zeros_like(x))
        np.testing.assert_array_equal(w.numpy(), [3, 0, 2])

    def test_unique_nonzero(self):
        u = paddle.unique(paddle.to_tensor([3, 1, 1, 2]))
        np.testing.assert_array_equal(np.sort(u.numpy()), [1, 2, 3])
        nz = paddle.nonzero(paddle.to_tensor([0, 1, 0, 2]))
        np.testing.assert_array_equal(nz.numpy().reshape(-1), [1, 3])


def test_tensor_api_tail():
    """cdist/take/logcumsumexp/renorm/frexp/trapezoid/vander/unflatten/
    as_strided/nanmedian/polygamma/i0 (reference tensor-API tail)."""
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(3, 4).astype("f4"))
    y = paddle.to_tensor(rs.randn(5, 4).astype("f4"))
    np.testing.assert_allclose(
        paddle.cdist(x, y).numpy(),
        np.sqrt(((x.numpy()[:, None] - y.numpy()[None]) ** 2).sum(-1)),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        paddle.logcumsumexp(paddle.to_tensor(
            np.array([1., 2., 3.], "f4"))).numpy(),
        np.log(np.cumsum(np.exp([1, 2, 3]))), rtol=1e-5)
    m, e = paddle.frexp(paddle.to_tensor(np.array([8.0, 0.5], "f4")))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0, 0.5])
    assert float(paddle.trapezoid(paddle.to_tensor(
        np.array([1., 2., 3.], "f4")))) == 4.0
    assert tuple(paddle.unflatten(paddle.to_tensor(
        np.arange(12).reshape(3, 4)), 1, [2, 2]).shape) == (3, 2, 2)
    np.testing.assert_allclose(
        paddle.as_strided(paddle.to_tensor(np.arange(9, dtype="f4")),
                          [2, 2], [3, 1]).numpy(), [[0, 1], [3, 4]])
    assert float(paddle.nanmedian(paddle.to_tensor(
        np.array([1., np.nan, 3.], "f4")))) == 2.0
    rn = paddle.renorm(paddle.to_tensor(np.ones((2, 4), "f4")), 2.0, 0, 1.0)
    np.testing.assert_allclose(np.linalg.norm(rn.numpy(), axis=1), 1.0,
                               rtol=1e-5)


def test_split_stack_index_family():
    x = paddle.to_tensor(np.arange(12, dtype="f4").reshape(3, 4))
    parts = paddle.tensor_split(x, 2, axis=1)
    assert len(parts) == 2 and tuple(parts[0].shape) == (3, 2)
    assert tuple(paddle.hstack([x, x]).shape) == (3, 8)
    assert tuple(paddle.vstack([x, x]).shape) == (6, 4)
    np.testing.assert_allclose(
        paddle.crop(x, shape=[2, 2], offsets=[1, 1]).numpy(),
        [[5, 6], [9, 10]])
    ia = paddle.index_add(x, paddle.to_tensor(np.array([0, 2])), 0,
                          paddle.to_tensor(np.ones((2, 4), "f4")))
    np.testing.assert_allclose(ia.numpy()[0], x.numpy()[0] + 1)
    ms = paddle.masked_scatter(
        x, paddle.to_tensor(x.numpy() > 8),
        paddle.to_tensor(np.array([100., 101., 102.], "f4")))
    np.testing.assert_allclose(ms.numpy()[2, 1:], [100, 101, 102])
    assert float(paddle.hypot(paddle.to_tensor(np.array([3.0], "f4")),
                              paddle.to_tensor(np.array([4.0], "f4")))) == 5.0
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]

"""Op-corpus tail: TensorArray family, fill_diagonal, CTR ops (cvm,
shuffle_batch, partial_*), affine_channel, ranking/center losses.

Reference: fill_diagonal_op, shuffle_batch_op, partial_concat/sum_op,
pad_constant_like_op, affine_channel_op, cvm_op, rank_loss_op, bpr_loss_op,
center_loss_op, write_to_array/read_from_array + LoDTensorArray.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.static as static

rs = np.random.RandomState(0)


def test_fill_diagonal_and_inplace():
    x = paddle.to_tensor(rs.randn(4, 4).astype("float32"))
    out = paddle.fill_diagonal(x, 7.0)
    np.testing.assert_allclose(np.diag(out.numpy()), 7.0)
    assert not np.allclose(np.diag(x.numpy()), 7.0)
    r = paddle.fill_diagonal_(x, 3.0)
    assert r is x
    np.testing.assert_allclose(np.diag(x.numpy()), 3.0)
    off = paddle.fill_diagonal(paddle.to_tensor(np.zeros((3, 4), "float32")),
                               1.0, offset=1)
    np.testing.assert_allclose(off.numpy()[0, 1], 1.0)
    np.testing.assert_allclose(off.numpy()[0, 0], 0.0)


def test_shuffle_batch_is_permutation():
    x = paddle.to_tensor(np.arange(20, dtype="float32").reshape(10, 2))
    sh, order = paddle.shuffle_batch(x, seed=5)
    np.testing.assert_allclose(np.sort(sh.numpy(), 0), x.numpy())
    np.testing.assert_allclose(sh.numpy(), x.numpy()[order.numpy()])


def test_partial_concat_sum_pad_like():
    x = paddle.to_tensor(rs.randn(4, 5).astype("float32"))
    y = paddle.to_tensor(rs.randn(4, 5).astype("float32"))
    pc = paddle.partial_concat([x, y], start_index=1, length=2)
    np.testing.assert_allclose(
        pc.numpy(), np.concatenate([x.numpy()[:, 1:3], y.numpy()[:, 1:3]], 1),
        rtol=1e-6)
    ps = paddle.partial_sum([x, y], start_index=0, length=3)
    np.testing.assert_allclose(ps.numpy(),
                               x.numpy()[:, :3] + y.numpy()[:, :3], rtol=1e-6)
    big = paddle.to_tensor(np.zeros((6, 7), "float32"))
    small = paddle.to_tensor(np.ones((4, 5), "float32"))
    padded = paddle.pad_constant_like(big, small, pad_value=-2.0)
    assert padded.shape == [6, 7]
    np.testing.assert_allclose(padded.numpy()[:4, :5], 1.0)
    np.testing.assert_allclose(padded.numpy()[4:, :], -2.0)


def test_affine_channel_and_cvm():
    im = paddle.to_tensor(rs.randn(2, 3, 4, 4).astype("float32"))
    s = paddle.to_tensor(np.array([1.0, 2.0, 0.5], "float32"))
    b = paddle.to_tensor(np.array([0.0, 1.0, -1.0], "float32"))
    out = F.affine_channel(im, s, b)
    np.testing.assert_allclose(out.numpy()[:, 2],
                               im.numpy()[:, 2] * 0.5 - 1.0, rtol=1e-5)
    feat = paddle.to_tensor(np.abs(rs.randn(4, 6)).astype("float32"))
    show_click = paddle.to_tensor(
        np.abs(rs.randn(4, 2)).astype("float32"))
    kept = F.cvm(feat, show_click, use_cvm=True)
    assert kept.shape == [4, 6]
    np.testing.assert_allclose(
        kept.numpy()[:, 0], np.log(show_click.numpy()[:, 0] + 1), rtol=1e-5)
    stripped = F.cvm(feat, show_click, use_cvm=False)
    assert stripped.shape == [4, 4]


def test_rank_bpr_center_losses():
    # rank_loss: label 1 with left >> right → near-zero loss
    left = paddle.to_tensor(np.full((3, 1), 10.0, "float32"))
    right = paddle.to_tensor(np.zeros((3, 1), "float32"))
    ones = paddle.to_tensor(np.ones((3, 1), "float32"))
    rl = F.rank_loss(ones, left, right)
    assert float(rl.numpy().max()) < 1e-3
    # bpr_loss decreases as the true logit dominates
    lbl = paddle.to_tensor(np.zeros((4, 1), "int64"))
    weak = F.bpr_loss(paddle.to_tensor(np.zeros((4, 3), "float32")), lbl)
    strong = F.bpr_loss(paddle.to_tensor(
        np.tile([5.0, 0.0, 0.0], (4, 1)).astype("float32")), lbl)
    assert float(strong.numpy().mean()) < float(weak.numpy().mean())
    # center_loss pulls centers toward features
    feats = paddle.to_tensor(np.ones((4, 6), "float32"))
    labels = paddle.to_tensor(np.zeros((4, 1), "int64"))
    centers = paddle.to_tensor(np.zeros((3, 6), "float32"))
    l1 = float(F.center_loss(feats, labels, centers).numpy().mean())
    l2 = float(F.center_loss(feats, labels, centers).numpy().mean())
    assert l2 < l1  # center 0 moved toward the features


def test_tensor_array_family():
    arr = static.create_array("float32")
    i0 = paddle.to_tensor(np.int64(0))
    i1 = paddle.to_tensor(np.int64(1))
    static.array_write(paddle.to_tensor(np.ones(2, "float32")), i0, arr)
    static.array_write(paddle.to_tensor(np.full(3, 2.0, "float32")), i1, arr)
    assert int(static.array_length(arr).numpy()) == 2
    np.testing.assert_allclose(static.array_read(arr, i1).numpy(), 2.0)
    lt = static.array_to_lod_tensor(arr)
    assert lt.recursive_sequence_lengths() == [[2, 3]]
    back = static.lod_tensor_to_array(lt)
    assert len(back) == 2
    np.testing.assert_allclose(back[0].numpy(), 1.0)


def test_fill_diagonal_rectangular_and_wrap():
    # wide matrix with positive offset: true diagonal has min(2, 5-2)=2 elems
    wide = paddle.fill_diagonal(
        paddle.to_tensor(np.zeros((2, 5), "float32")), 9.0, offset=2)
    np.testing.assert_allclose(wide.numpy()[0, 2], 9.0)
    np.testing.assert_allclose(wide.numpy()[1, 3], 9.0)
    assert float(wide.numpy().sum()) == 18.0
    # tall with wrap: restart after each cols-block (reference semantics)
    tall = paddle.fill_diagonal(
        paddle.to_tensor(np.zeros((7, 3), "float32")), 1.0, wrap=True)
    got_rows = sorted(set(np.argwhere(tall.numpy() == 1.0)[:, 0].tolist()))
    assert got_rows == [0, 1, 2, 4, 5, 6], got_rows
    # no wrap: only the first min(R,C) elements
    tall2 = paddle.fill_diagonal(
        paddle.to_tensor(np.zeros((7, 3), "float32")), 1.0)
    assert float(tall2.numpy().sum()) == 3.0


def test_to_static_frozen_params_still_propagate_input_grads():
    import paddle_tpu.jit as jit
    import paddle_tpu.nn as nn

    net = nn.Linear(4, 2)
    for p in net.parameters():
        p.stop_gradient = True
    snet = jit.to_static(net)
    x = paddle.to_tensor(rs.rand(3, 4).astype("float32"),
                         stop_gradient=False)
    out = snet(x)
    out.sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(
        x.grad.numpy(), np.tile(net.weight.numpy().sum(-1), (3, 1)),
        rtol=1e-5)


def test_fluid_cos_sim_keeps_trailing_dim():
    import paddle_tpu.fluid as fluid

    X = paddle.to_tensor(rs.randn(8, 16).astype("float32"))
    Y = paddle.to_tensor(rs.randn(8, 16).astype("float32"))
    out = fluid.layers.cos_sim(X, Y)
    assert out.shape == [8, 1]

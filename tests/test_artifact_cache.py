"""Persistent compiled-artifact cache (ISSUE 19, ROADMAP item 5).

Contracts pinned here:
- capability probe: ``export_supported()`` actually imports the lazy
  ``jax.export`` submodule (``hasattr(jax, "export")`` was a false
  negative) and ``require_export()`` is the one sanctioned way in.
- round trip: where the probe holds, export → serialize → store →
  (fresh cache) lookup → deserialize is BYTE-identical and the
  deserialized program computes the same results.
- validation discipline: corrupt, version-drifted, producer-drifted,
  key-mismatched and torn entries are discarded LOUDLY (warning +
  discard counter) and read as a miss — the caller recompiles; a
  poisoned entry can never poison the process.
- FaultyFS: a torn write or crashed rename leaves either the old entry
  or an orphan ``.tmp`` the loader never reads; transient write errors
  degrade to "not persisted", never an exception.
- degraded mode: with the probe forced off, the disk tier goes inert
  and the in-process warm map alone carries store/lookup.
- ``compilation_cache_subdir``: world/device-kind-keyed subdirectories
  let two processes with DIFFERENT forced device counts share one XLA
  cache base (the PR-15 glibc abort, made unrepresentable).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.jit import artifact_cache as ac
from paddle_tpu.jit.artifact_cache import (
    ArtifactCache, cache_key, compilation_cache_subdir, export_compiled,
    export_supported, producer_id, require_export,
)
from paddle_tpu.robustness.fault_injection import FaultyFS, InjectedCrash

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def forced_degraded(monkeypatch):
    """Force the probe to report no-export (the degraded warm path)."""
    monkeypatch.setattr(ac, "_EXPORT_PROBED", True)
    monkeypatch.setattr(ac, "_EXPORT_MOD", None)


class _FakeExported:
    """Duck Exported for plumbing tests: serialize() -> fixed bytes."""

    def __init__(self, payload=b"fake-program"):
        self._payload = payload

    def serialize(self):
        return self._payload


# ---------------------------------------------------------------------------
# probe + key
# ---------------------------------------------------------------------------

class TestProbeAndKey:
    def test_probe_memoized_and_consistent(self):
        assert export_supported() == export_supported()
        if export_supported():
            exp = require_export()
            assert callable(exp.export) and callable(exp.deserialize)

    def test_require_export_names_the_probe_when_absent(
            self, forced_degraded):
        assert not export_supported()
        with pytest.raises(RuntimeError, match="export_supported"):
            require_export()

    def test_key_separates_world_and_device(self):
        base = dict(program_fingerprint="fp", shape_bucket=(4, 16),
                    dtype="float32")
        k1 = cache_key(device_kind="cpu", world=1, **base)
        k2 = cache_key(device_kind="cpu", world=2, **base)
        k3 = cache_key(device_kind="TPU_v4", world=2, **base)
        assert len({k1, k2, k3}) == 3
        assert k1.endswith("|w1") and k2.endswith("|w2")
        assert "4x16" in k1

    def test_key_defaults_come_from_live_backend(self):
        import jax

        k = cache_key("fp", (2,), "int8")
        assert f"w{jax.device_count()}" in k

    def test_producer_id_names_both_toolchain_halves(self):
        assert "jax-" in producer_id() and "jaxlib-" in producer_id()


# ---------------------------------------------------------------------------
# round trip (real jax.export where the env has it)
# ---------------------------------------------------------------------------

@pytest.mark.requires_jax_export
class TestRoundTrip:
    def test_byte_identical_round_trip_and_execution(self, tmp_path):
        import jax.numpy as jnp

        x = jnp.arange(8, dtype=jnp.float32)
        exported = export_compiled(lambda a: a * 2.0 + 1.0, x)
        want_bytes = bytes(exported.serialize())
        want = np.asarray(exported.call(x))

        key = cache_key("roundtrip", (8,), "float32")
        cache = ArtifactCache(str(tmp_path))
        assert cache.store(key, exported) is True

        # a FRESH cache (fresh process stand-in: empty warm map) answers
        # from disk with the exact bytes and a working program
        cold = ArtifactCache(str(tmp_path))
        assert cold.load_bytes(key) == want_bytes
        obj = cold.lookup(key)
        assert obj is not None
        np.testing.assert_array_equal(np.asarray(obj.call(x)), want)
        assert cold.stats()["hits"] >= 1

    def test_disk_miss_on_other_world_key(self, tmp_path):
        import jax.numpy as jnp

        x = jnp.arange(4, dtype=jnp.float32)
        exported = export_compiled(lambda a: a + 1.0, x)
        cache = ArtifactCache(str(tmp_path))
        cache.store(cache_key("fp", (4,), "float32", world=1), exported)
        cold = ArtifactCache(str(tmp_path))
        assert cold.lookup(
            cache_key("fp", (4,), "float32", world=2)) is None


# ---------------------------------------------------------------------------
# validation discipline (pure plumbing, runs everywhere)
# ---------------------------------------------------------------------------

class TestValidation:
    def _stored(self, tmp_path, key="k", payload=b"payload-bytes"):
        cache = ArtifactCache(str(tmp_path))
        path = cache.save_bytes(key, payload)
        assert path is not None
        return cache, path, payload

    def test_save_load_bytes_round_trip(self, tmp_path):
        cache, _, payload = self._stored(tmp_path)
        fresh = ArtifactCache(str(tmp_path))
        assert fresh.load_bytes("k") == payload

    def test_missing_entry_is_a_quiet_miss(self, tmp_path):
        cache = ArtifactCache(str(tmp_path))
        assert cache.load_bytes("absent") is None
        assert cache.misses == 1 and cache.discards == 0

    def test_corrupt_entry_discarded_loudly(self, tmp_path):
        cache, path, _ = self._stored(tmp_path)
        with open(path, "wb") as f:
            f.write(b"\x00not json\xff")
        with pytest.warns(UserWarning, match="discarded"):
            assert cache.load_bytes("k") is None
        assert cache.discards == 1
        assert not os.path.exists(path)  # quarantined, not retried forever

    def _rewrite(self, path, **patch):
        import json

        with open(path) as f:
            entry = json.load(f)
        entry.update(patch)
        with open(path, "w") as f:
            json.dump(entry, f)

    def test_version_drift_discarded_loudly(self, tmp_path):
        cache, path, _ = self._stored(tmp_path)
        self._rewrite(path, version=ac.CACHE_VERSION + 1)
        with pytest.warns(UserWarning, match="version drift"):
            assert cache.load_bytes("k") is None

    def test_producer_drift_discarded_loudly(self, tmp_path):
        cache, path, _ = self._stored(tmp_path)
        self._rewrite(path, producer="jax-0.0.1|jaxlib-0.0.1")
        with pytest.warns(UserWarning, match="producer drift"):
            assert cache.load_bytes("k") is None

    def test_key_mismatch_discarded_loudly(self, tmp_path):
        cache, path, _ = self._stored(tmp_path)
        self._rewrite(path, key="some-other-key")
        with pytest.warns(UserWarning, match="key mismatch"):
            assert cache.load_bytes("k") is None

    def test_torn_payload_digest_discarded_loudly(self, tmp_path):
        import base64

        cache, path, payload = self._stored(tmp_path)
        torn = base64.b64encode(payload[: len(payload) // 2]).decode()
        self._rewrite(path, payload=torn)
        with pytest.warns(UserWarning, match="digest mismatch"):
            assert cache.load_bytes("k") is None


# ---------------------------------------------------------------------------
# FaultyFS: machine-shaped failures
# ---------------------------------------------------------------------------

class TestFaultyFS:
    def test_transient_write_error_degrades_to_not_persisted(
            self, tmp_path):
        cache = ArtifactCache(str(tmp_path),
                              fs=FaultyFS(transient_oserrors=1))
        with pytest.warns(UserWarning, match="not persisted"):
            assert cache.save_bytes("k", b"payload") is None
        # the cache stays usable; the next save lands
        assert cache.save_bytes("k", b"payload") is not None
        assert ArtifactCache(str(tmp_path)).load_bytes("k") == b"payload"

    def test_torn_write_leaves_no_visible_entry(self, tmp_path):
        """Power loss mid-write: the destination entry never appears
        (atomic tmp+rename), a fresh cache reads a quiet miss and the
        caller recompiles."""
        cache = ArtifactCache(str(tmp_path), fs=FaultyFS(partial_write_on=1))
        with pytest.raises(InjectedCrash):
            cache.save_bytes("k", b"payload-bytes")
        fresh = ArtifactCache(str(tmp_path))
        assert fresh.load_bytes("k") is None
        assert fresh.discards == 0  # a miss, not a poisoned read

    def test_crash_on_rename_leaves_only_tmp_orphan(self, tmp_path):
        cache = ArtifactCache(str(tmp_path), fs=FaultyFS(crash_on_rename=1))
        with pytest.raises(InjectedCrash):
            cache.save_bytes("k", b"payload-bytes")
        fresh = ArtifactCache(str(tmp_path))
        assert fresh.load_bytes("k") is None
        orphans = [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
        assert orphans, "the torn tmp file should remain for forensics"


# ---------------------------------------------------------------------------
# degraded mode (no jax.export)
# ---------------------------------------------------------------------------

class TestDegradedMode:
    def test_warm_map_alone_carries_store_lookup(self, tmp_path,
                                                 forced_degraded):
        cache = ArtifactCache(str(tmp_path))
        obj = _FakeExported()
        assert cache.store("k", obj) is False  # disk tier inert
        assert cache.lookup("k") is obj        # warm map still answers
        assert os.listdir(tmp_path) == []      # nothing persisted
        fresh = ArtifactCache(str(tmp_path))
        assert fresh.lookup("k") is None       # and nothing survives
        assert fresh.stats()["export_supported"] is False

    def test_unserializable_object_stays_in_process(self, tmp_path):
        class _Boom:
            def serialize(self):
                raise ValueError("not today")

        cache = ArtifactCache(str(tmp_path))
        obj = _Boom()
        with pytest.warns(UserWarning, match="kept in-process"):
            assert cache.store("k", obj) is False
        assert cache.lookup("k") is obj


# ---------------------------------------------------------------------------
# XLA compilation-cache keying (the PR-15 regression)
# ---------------------------------------------------------------------------

class TestCompilationCacheSubdir:
    def test_subdirs_keyed_by_world_and_device(self, tmp_path):
        a = compilation_cache_subdir(str(tmp_path), world=1,
                                     device_kind="cpu")
        b = compilation_cache_subdir(str(tmp_path), world=2,
                                     device_kind="cpu")
        assert a != b and os.path.isdir(a) and os.path.isdir(b)
        assert os.path.dirname(a) == os.path.dirname(b) == str(tmp_path)

    def test_two_world_sizes_share_one_cache_base(self, tmp_path):
        """The PR-15 regression: two processes with different forced
        device counts point at the SAME cache base. With keyed subdirs
        neither can observe the other's entries — both must exit 0
        (the unkeyed layout aborted glibc on the second run)."""
        script = (
            "import os, jax, jax.numpy as jnp\n"
            "from paddle_tpu.jit.artifact_cache import "
            "compilation_cache_subdir\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "base = os.environ['CACHE_BASE']\n"
            "sub = compilation_cache_subdir(base)\n"
            "jax.config.update('jax_compilation_cache_dir', sub)\n"
            "jax.config.update("
            "'jax_persistent_cache_min_compile_time_secs', 0.0)\n"
            "x = jax.jit(lambda a: (a * 3.0).sum())(jnp.arange(64.0))\n"
            "print(jax.device_count(), sub)\n"
        )
        subs = []
        for n in (1, 2):
            env = dict(os.environ,
                       CACHE_BASE=str(tmp_path),
                       JAX_PLATFORMS="cpu",
                       XLA_FLAGS=f"--xla_force_host_platform_device_count={n}")
            proc = subprocess.run(
                [sys.executable, "-c", script], env=env, cwd=REPO,
                capture_output=True, text=True, timeout=300)
            assert proc.returncode == 0, (proc.stdout, proc.stderr)
            world, sub = proc.stdout.split()[-2:]
            assert int(world) == n
            subs.append(sub)
        assert subs[0] != subs[1]
        assert all(os.path.dirname(s) == str(tmp_path) for s in subs)

"""Tests: quantization (QAT/PTQ), paddle.sparse, paddle.text, regularizer.

Reference analogs: slim quantization unittests, test_sparse_*_op.py,
text dataset tests, regularizer tests.
"""
import io
import json
import os
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.quantization as Q
import paddle_tpu.sparse as sparse
import paddle_tpu.text as text


class TestQuantization:
    def test_fake_quant_ste_grad(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 8).astype("float32"))
        x.stop_gradient = False
        y = Q.fake_quant_dequant(x, bits=8)
        # quantized forward differs slightly, close to input
        np.testing.assert_allclose(y.numpy(), x.numpy(), atol=0.01)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones(8), rtol=1e-6)

    def test_imperative_quant_aware_rewrites(self):
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
        qat = Q.ImperativeQuantAware()
        qat.quantize(net)
        assert type(net[0]).__name__ == "QuantedLinear"
        assert type(net[2]).__name__ == "QuantedLinear"
        x = paddle.to_tensor(np.random.RandomState(0).rand(4, 8)
                             .astype("float32"))
        out = net(x)
        loss = (out ** 2).sum()
        loss.backward()
        assert net[0].inner.weight.grad is not None

    def test_qat_training_converges(self):
        paddle.seed(0)
        import paddle_tpu.optimizer as opt

        net = nn.Linear(4, 1)
        qnet = Q.QuantedLinear(net)
        optim = opt.SGD(learning_rate=0.05, parameters=net.parameters())
        rs = np.random.RandomState(0)
        w_true = rs.randn(4, 1).astype("float32")
        losses = []
        for _ in range(40):
            xb = paddle.to_tensor(rs.randn(16, 4).astype("float32"))
            yb = paddle.to_tensor(xb.numpy() @ w_true)
            pred = qnet(xb)
            loss = ((pred - yb) ** 2).mean()
            loss.backward()
            optim.step()
            optim.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5

    def test_ptq_calibration(self, tmp_path):
        import paddle_tpu.io as pio

        class DS(pio.Dataset):
            def __getitem__(self, i):
                return np.random.RandomState(i).rand(8).astype("float32"),

            def __len__(self):
                return 8

        net = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
        loader = pio.DataLoader(DS(), batch_size=4)
        ptq = Q.PostTrainingQuantization(net, loader, batch_nums=2)
        ptq.quantize()
        assert ptq.act_scales and ptq.weight_scales
        ptq.save_quantized_model(str(tmp_path / "q"))
        scales = json.load(open(str(tmp_path / "q" / "quant_scales.json")))
        assert scales["bits"] == 8


class TestSparse:
    def test_coo_roundtrip(self):
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        vals = np.array([1.0, 2.0, 3.0], "float32")
        s = sparse.sparse_coo_tensor(idx, vals, (3, 3))
        assert s.nnz() == 3
        dense = s.to_dense().numpy()
        expect = np.zeros((3, 3), "float32")
        expect[idx[0], idx[1]] = vals
        np.testing.assert_allclose(dense, expect)

    def test_csr_roundtrip(self):
        crows = [0, 1, 1, 3]
        cols = [2, 0, 1]
        vals = np.array([5.0, 6.0, 7.0], "float32")
        s = sparse.sparse_csr_tensor(crows, cols, vals, (3, 3))
        dense = s.to_dense().numpy()
        expect = np.array([[0, 0, 5], [0, 0, 0], [6, 7, 0]], "float32")
        np.testing.assert_allclose(dense, expect)
        coo = s.to_sparse_coo()
        np.testing.assert_allclose(coo.to_dense().numpy(), expect)

    def test_sparse_matmul_and_relu(self):
        idx = np.array([[0, 1], [1, 0]])
        s = sparse.sparse_coo_tensor(idx, np.array([2.0, -3.0], "float32"),
                                     (2, 2))
        d = paddle.to_tensor(np.eye(2, dtype="float32"))
        out = sparse.matmul(s, d).numpy()
        np.testing.assert_allclose(out, [[0, 2], [-3, 0]])
        r = sparse.relu(s).to_dense().numpy()
        np.testing.assert_allclose(r, [[0, 2], [0, 0]])

    def test_dense_to_sparse(self):
        x = paddle.to_tensor(np.array([[0, 1.5], [0, 0]], "float32"))
        s = x.to_sparse_coo()
        assert s.nnz() == 1
        np.testing.assert_allclose(s.to_dense().numpy(), x.numpy())


class TestText:
    def test_uci_housing(self, tmp_path):
        rs = np.random.RandomState(0)
        raw = rs.rand(50, 14).astype("float32")
        path = str(tmp_path / "housing.data")
        np.savetxt(path, raw)
        train = text.UCIHousing(data_file=path, mode="train")
        test_ds = text.UCIHousing(data_file=path, mode="test")
        assert len(train) == 40 and len(test_ds) == 10
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_imdb_tar(self, tmp_path):
        tar_path = str(tmp_path / "aclImdb.tar.gz")
        with tarfile.open(tar_path, "w:gz") as tf:
            for split in ("train", "test"):
                for lab, texts in [("pos", [b"a great movie", b"loved it"]),
                                   ("neg", [b"terrible film"])]:
                    for i, t in enumerate(texts):
                        info = tarfile.TarInfo(
                            f"aclImdb/{split}/{lab}/{i}.txt")
                        info.size = len(t)
                        tf.addfile(info, io.BytesIO(t))
        ds = text.Imdb(data_file=tar_path, mode="train")
        assert len(ds) == 3
        doc, label = ds[0]
        assert doc.dtype == np.int64
        assert set(ds.labels.tolist()) == {0, 1}

    def test_viterbi_decode_simple(self):
        # 2 tags; transition strongly favors staying
        pot = paddle.to_tensor(np.array(
            [[[5.0, 0.0], [4.0, 1.0], [0.0, 6.0]]], dtype="float32"))
        trans = paddle.to_tensor(np.array(
            [[2.0, -2.0], [-2.0, 2.0]], dtype="float32"))
        score, path = text.viterbi_decode(pot, trans,
                                          include_bos_eos_tag=False)
        assert path.numpy().shape == (1, 3)
        # brute force check
        best, best_path = -1e9, None
        for a in range(2):
            for b in range(2):
                for c in range(2):
                    s = (pot.numpy()[0, 0, a] + pot.numpy()[0, 1, b]
                         + pot.numpy()[0, 2, c]
                         + trans.numpy()[a, b] + trans.numpy()[b, c])
                    if s > best:
                        best, best_path = s, [a, b, c]
        np.testing.assert_allclose(float(score.numpy()[0]), best, rtol=1e-5)
        assert path.numpy()[0].tolist() == best_path


class TestRegularizer:
    def test_l2_decay_in_optimizer(self):
        import paddle_tpu.optimizer as opt
        import paddle_tpu.regularizer as reg

        net = nn.Linear(2, 1, bias_attr=False)
        net.weight.set_value(np.ones((2, 1), "float32"))
        o = opt.SGD(learning_rate=0.1, parameters=net.parameters(),
                    weight_decay=reg.L2Decay(0.5))
        x = paddle.to_tensor(np.zeros((1, 2), "float32"))
        net(x).sum().backward()
        o.step()
        # grad 0 + wd 0.5 → w -= 0.1 * 0.5 * w → 0.95
        np.testing.assert_allclose(net.weight.numpy(),
                                   np.full((2, 1), 0.95), rtol=1e-5)


# ---------------------------------------------------------------- tokenizer
VOCAB = {w: i for i, w in enumerate(
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "quick", "brown",
     "fox", "jump", "##ed", "##s", "over", "lazy", "dog", "un", "##want",
     "##ing", "!", "train"])}


def test_wordpiece_greedy_longest_match():
    from paddle_tpu.text import BertTokenizer

    tok = BertTokenizer(VOCAB)
    # classic wordpiece example: unwanted -> un ##want ##ed
    assert tok.tokenize("unwanted") == ["un", "##want", "##ed"]
    assert tok.tokenize("jumped") == ["jump", "##ed"]
    assert tok.tokenize("zzz") == ["[UNK]"]


def test_basic_tokenizer_punct_lower_accents():
    from paddle_tpu.text import BasicTokenizer

    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("The Quick!fox") == ["the", "quick", "!", "fox"]
    assert bt.tokenize("café") == ["cafe"]  # accent stripped
    bt2 = BasicTokenizer(do_lower_case=False)
    assert bt2.tokenize("The fox") == ["The", "fox"]


def test_bert_encode_single_and_pair():
    from paddle_tpu.text import BertTokenizer

    tok = BertTokenizer(VOCAB)
    enc = tok.encode("the quick fox")
    ids = enc["input_ids"]
    assert ids[0] == VOCAB["[CLS]"] and ids[-1] == VOCAB["[SEP]"]
    assert enc["token_type_ids"] == [0] * len(ids)
    pair = tok.encode("the fox", "the dog", max_seq_len=16,
                      pad_to_max_seq_len=True)
    assert len(pair["input_ids"]) == 16
    assert pair["token_type_ids"].count(1) == 3  # 'the', 'dog', final [SEP]
    assert pair["input_ids"].count(VOCAB["[SEP]"]) == 2


def test_bert_encode_truncation_longest_first():
    from paddle_tpu.text import BertTokenizer

    tok = BertTokenizer(VOCAB)
    enc = tok.encode("the quick brown fox", "the dog", max_seq_len=8)
    assert len(enc["input_ids"]) <= 8
    assert enc["input_ids"].count(VOCAB["[SEP]"]) == 2


def test_faster_tokenizer_op_form():
    from paddle_tpu.text import faster_tokenizer

    ids, tt = faster_tokenizer(["the quick fox", "lazy dog !"], VOCAB,
                               max_seq_len=10)
    assert ids.shape == [2, 10] and tt.shape == [2, 10]
    arr = ids.numpy()
    assert arr[0, 0] == VOCAB["[CLS]"]
    assert (arr[1] == VOCAB["[PAD]"]).sum() > 0  # padded to width
    # feeds straight into an embedding (the serving contract)
    import paddle_tpu.nn as nn

    emb = nn.Embedding(len(VOCAB), 8)
    out = emb(ids)
    assert out.shape == [2, 10, 8]


def test_tokenizer_whitespace_chars_and_bounds():
    from paddle_tpu.text import BasicTokenizer, BertTokenizer
    import pytest as _pytest

    bt = BasicTokenizer()
    assert bt.tokenize("the\tquick\nfox") == ["the", "quick", "fox"]
    tok = BertTokenizer(VOCAB)
    with _pytest.raises(ValueError):
        tok.encode("the fox", "the dog", max_seq_len=2)
    # pre-split words skip the basic tokenizer
    enc = tok.encode(["unwanted", "fox"], is_split_into_words=True)
    ids = enc["input_ids"][1:-1]
    assert ids == tok.convert_tokens_to_ids(["un", "##want", "##ed", "fox"])


def test_tokenizer_batch_pair_validation():
    import pytest as _pytest

    from paddle_tpu.text import BertTokenizer, faster_tokenizer

    tok = BertTokenizer(VOCAB)
    with _pytest.raises(ValueError):
        tok.batch_encode(["the fox", "dog"], ["the"])  # length mismatch
    # single pre-split sample + single pre-split pair stays ONE pair
    ids, tt = faster_tokenizer(["unwanted", "fox"], VOCAB,
                               text_pair=["lazy", "dog"],
                               is_split_into_words=True, max_seq_len=16)
    assert ids.shape[0] == 1
    assert ids.numpy()[0].tolist().count(VOCAB["[SEP]"]) == 2
    # pre-split words are lowercased like the full pipeline
    enc = tok.encode(["Unwanted"], is_split_into_words=True)
    assert enc["input_ids"][1:-1] == tok.convert_tokens_to_ids(
        ["un", "##want", "##ed"])


class TestPtqObservers:
    """Observer variety (VERDICT r2 weak #9): hist/KL/MSE calibration must
    clip outliers that blow the abs-max scale, and every algo plugs into
    PostTrainingQuantization."""

    def _heavy_tailed(self, n=20000, seed=0):
        rs = np.random.RandomState(seed)
        x = rs.randn(n).astype(np.float32)
        x[:5] *= 100.0  # a handful of extreme outliers
        return x

    def test_outlier_clipping_beats_absmax(self):
        from paddle_tpu.quantization.observers import (
            AbsMaxObserver, HistObserver, KLObserver, MSEObserver,
        )

        x = self._heavy_tailed()
        # hist: percentile must exceed the outlier mass (5/20000) to clip;
        # the reference's 0.99999 default targets far larger calib sets
        obs = {"abs": AbsMaxObserver(), "hist": HistObserver(percent=0.999),
               "kl": KLObserver(), "mse": MSEObserver()}
        for o in obs.values():
            for chunk in np.split(x, 4):  # streaming updates
                o.update(chunk)
        t = {k: o.threshold() for k, o in obs.items()}
        assert t["abs"] > 100.0  # abs-max is dominated by the outliers
        # distribution-shaped calibrators clip the tail
        for k in ("hist", "kl"):
            assert t[k] < 0.2 * t["abs"], (k, t)

        def rt_err(th):
            scale = th / 127.0
            q = np.clip(np.round(x / scale), -127, 127) * scale
            return float(np.mean((x - q) ** 2))

        # MSE searches clip candidates incl. ~abs-max, so it is never worse
        # (here the outliers are so extreme that NOT clipping minimizes
        # MSE — the observer must recognize that, not blindly clip)
        assert rt_err(t["mse"]) <= rt_err(t["abs"]) * 1.001, t

    def test_avg_observer_means_batch_maxima(self):
        from paddle_tpu.quantization.observers import AvgObserver

        o = AvgObserver()
        o.update(np.asarray([1.0]))
        o.update(np.asarray([3.0]))
        assert abs(o.threshold() - 2.0) < 1e-6

    def test_histogram_rebinning_keeps_mass(self):
        from paddle_tpu.quantization.observers import HistObserver

        o = HistObserver(bins=128)
        o.update(np.full(1000, 0.5, np.float32))
        o.update(np.full(1000, 8.0, np.float32))  # range widens 16x
        assert abs(o.hist.sum() - 2000) < 1.0
        assert 7.0 < o.threshold() <= 8.1

    @pytest.mark.parametrize("algo", ["abs_max", "avg", "hist", "KL", "mse"])
    def test_ptq_with_each_algo(self, algo):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PostTrainingQuantization

        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        rs = np.random.RandomState(0)
        loader = [paddle.to_tensor(rs.randn(4, 8).astype("float32"))
                  for _ in range(3)]
        ptq = PostTrainingQuantization(model, data_loader=loader,
                                       batch_nums=3, algo=algo)
        ptq.quantize()
        assert len(ptq.act_scales) == 2  # both Linears observed
        assert all(s > 0 for s in ptq.act_scales.values())

    def test_unknown_algo_raises(self):
        from paddle_tpu.quantization.observers import make_observer

        with pytest.raises(ValueError, match="unknown PTQ algo"):
            make_observer("bogus")

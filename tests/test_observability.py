"""Telemetry layer tests (ISSUE 3): MetricsRegistry / EventLog / StepTimer,
the instrumentation sweep through dispatch, grad_comm, and robustness, and
the tier-1 smoke that drives a toy train under Profiler + registry and runs
tools/trace_report.py end-to-end."""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.observability import (
    EventLog, MetricsRegistry, StepTimer, breakdown_from_trace,
    get_event_log, get_registry, phase_of,
)
from paddle_tpu.profiler import Profiler, ProfilerTarget, RecordEvent

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- metrics core
def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.5)
    reg.gauge("g").dec()
    h = reg.histogram("h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    assert snap["c"] == 5
    assert snap["g"] == 1.5
    assert snap["h"]["count"] == 3
    assert snap["h"]["sum"] == pytest.approx(5.55)
    # cumulative bucket semantics: <=0.1 holds 1, <=1.0 holds 2
    assert snap["h"]["buckets"] == {"0.1": 1, "1.0": 2}
    assert snap["h"]["min"] == 0.05 and snap["h"]["max"] == 5.0


def test_labelled_counters_and_redeclare():
    reg = MetricsRegistry()
    fam = reg.counter("bytes", labels=("codec",))
    fam.labels(codec="bf16").inc(10)
    fam.labels(codec="int8").inc(1)
    # re-declaration returns the same family; kind clash raises
    assert reg.counter("bytes", labels=("codec",)) is fam
    with pytest.raises(ValueError):
        reg.gauge("bytes")
    with pytest.raises(ValueError):
        fam.labels(wrong="x")
    snap = reg.snapshot()
    assert snap["bytes"] == {"codec=bf16": 10, "codec=int8": 1}
    # bind() gives the raw child and survives reset() (reset in place)
    child = fam.bind(codec="bf16")
    reg.reset()
    child.inc(3)
    assert reg.snapshot()["bytes"]["codec=bf16"] == 3
    assert reg.snapshot()["bytes"]["codec=int8"] == 0


def test_prometheus_exposition_and_jsonl(tmp_path):
    reg = MetricsRegistry()
    reg.counter("reqs", help="requests").inc(7)
    reg.counter("by_op", labels=("op",)).labels(op="all_reduce").inc(2)
    reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.to_prometheus()
    assert "# HELP reqs requests" in text
    assert "# TYPE reqs counter" in text
    assert "reqs 7" in text
    assert 'by_op{op="all_reduce"} 2' in text
    assert 'lat_bucket{le="0.1"} 0' in text
    assert 'lat_bucket{le="1.0"} 1' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_count 1" in text
    p = tmp_path / "m.jsonl"
    reg.export_jsonl(str(p))
    reg.export_jsonl(str(p))
    lines = [json.loads(l) for l in open(p)]
    assert len(lines) == 2
    assert lines[0]["metrics"]["reqs"] == 7
    assert lines[0]["time"] <= lines[1]["time"]


# --------------------------------------------------------------- event log
def test_event_log_records_and_filters(tmp_path):
    log = EventLog(path=str(tmp_path / "ev.jsonl"), rank=3)
    log.info("checkpoint", "committed", step=5)
    log.warning("nan_guard", "trip", step=6)
    log.error("watchdog", "stall")
    with pytest.raises(ValueError):
        log.log("k", severity="fatal")
    assert len(log) == 3
    assert [e["kind"] for e in log.events(min_severity="warning")] == \
        ["nan_guard", "watchdog"]
    assert log.events(kind="checkpoint")[0]["step"] == 5
    recs = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
    assert len(recs) == 3
    assert all(r["rank"] == 3 for r in recs)
    # both clocks present; monotonic is non-decreasing across records
    assert all("time" in r and "mono" in r for r in recs)
    assert recs[0]["mono"] <= recs[1]["mono"] <= recs[2]["mono"]
    log.close()


def test_event_log_ring_bound_and_export(tmp_path):
    log = EventLog(max_memory=4)
    for i in range(7):
        log.info("k", i=i)
    assert len(log) == 4
    assert log.dropped == 3
    assert [e["i"] for e in log.tail(2)] == [5, 6]
    out = tmp_path / "dump.jsonl"
    log.export(str(out))
    assert len(open(out).read().splitlines()) == 4


# -------------------------------------------------------------- step timer
def test_step_timer_phase_attribution():
    assert phase_of("forward") == "forward"
    assert phase_of("comm:bucket0") == "comm"
    assert phase_of("fwd") == "forward"
    assert phase_of("matmul") is None
    t = StepTimer().start()
    try:
        with RecordEvent("forward"):
            pass
        with RecordEvent("comm"):
            pass
        row = t.step()
        with RecordEvent("backward"):
            pass
        row2 = t.step()
    finally:
        t.stop()
    assert row["forward"] > 0 and row["comm"] > 0 and row["backward"] == 0
    assert row2["backward"] > 0 and row2["forward"] == 0
    agg = t.breakdown()
    assert agg["steps"] == 2
    assert agg["phases"]["forward"]["seconds"] == pytest.approx(
        row["forward"])
    assert "forward" in t.report()
    # sinks are removed on stop: spans after stop() do not accumulate
    with RecordEvent("forward"):
        pass
    assert len(t.steps) == 2


# --------------------------------------------------- instrumentation sweep
def test_dispatch_and_trace_cache_counters():
    from paddle_tpu.framework.autograd import clear_op_cache

    reg = get_registry()
    x = paddle.to_tensor(np.ones(8, "float32"))
    clear_op_cache()  # deterministic hit/miss pattern below
    d0 = reg.counter("eager_dispatch_total").value
    h0 = reg.counter("trace_cache_hits_total").value
    m0 = reg.counter("trace_cache_misses_total").value
    u0 = reg.counter("trace_cache_uncacheable_total").value
    y = (x * 3.0).sum()
    y2 = (x * 3.0).sum()  # same mul again: a cache hit
    assert reg.counter("eager_dispatch_total").value - d0 == 4
    # mul: 1 miss then 1 hit; sum dispatches through a dynamic closure
    # (no cache key) so both runs count as uncacheable, not misses
    assert reg.counter("trace_cache_misses_total").value - m0 == 1
    assert reg.counter("trace_cache_hits_total").value - h0 == 1
    assert reg.counter("trace_cache_uncacheable_total").value - u0 == 2


def test_grad_comm_sync_records_metrics():
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.framework.tensor import Tensor

    reg = get_registry()
    lin = nn.Linear(16, 16)
    for p in lin.parameters():
        p.grad = Tensor(np.ones(p.shape, "float32"))
    cfg = grad_comm.GradCommConfig(codec="bf16")
    comm = grad_comm.GradCommunicator(cfg)
    fam_c = reg.counter("grad_comm_collectives_total", labels=("codec",))
    fam_b = reg.counter("grad_comm_bytes_total", labels=("codec",))
    c0 = fam_c.labels(codec="bf16").value
    b0 = fam_b.labels(codec="bf16").value
    f0 = reg.histogram("grad_comm_bucket_fill_ratio").bind().count
    comm.sync(lin.parameters(), world=2)
    assert fam_c.labels(codec="bf16").value - c0 == \
        comm.stats["collectives"] > 0
    assert fam_b.labels(codec="bf16").value - b0 == \
        comm.stats["comm_bytes"] > 0
    # one fill-ratio observation per bucket
    assert reg.histogram("grad_comm_bucket_fill_ratio").bind().count - f0 \
        == comm.stats["n_buckets"]


def test_collective_issue_counter():
    from paddle_tpu.distributed import collective as coll

    reg = get_registry()
    fam = reg.counter("collectives_total", labels=("op",))
    n0 = fam.labels(op="all_reduce").value
    t = paddle.to_tensor(np.ones(4, "float32"))
    coll.all_reduce(t)
    coll.all_reduce(t)
    assert fam.labels(op="all_reduce").value - n0 == 2


def test_checkpoint_save_histogram_and_events(tmp_path):
    from paddle_tpu.robustness.checkpoint import CheckpointManager

    reg = get_registry()
    h = reg.histogram("checkpoint_save_seconds").bind()
    s0, n0 = reg.counter("checkpoint_saves_total").value, h.count
    log = get_event_log()
    log.clear()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last_n=2)
    mgr.save({"w": np.ones(4)}, 1)
    mgr.save({"w": np.ones(4) * 2}, 2)
    assert reg.counter("checkpoint_saves_total").value - s0 == 2
    assert h.count - n0 == 2
    evs = log.events(kind="checkpoint")
    assert len(evs) == 2
    assert evs[-1]["step"] == 2 and evs[-1]["severity"] == "info"
    assert evs[-1]["seconds"] > 0
    # load timing lands in the load histogram
    l0 = reg.histogram("checkpoint_load_seconds").bind().count
    mgr.load_latest()
    assert reg.histogram("checkpoint_load_seconds").bind().count == l0 + 1


def test_checkpoint_corrupt_skip_counter(tmp_path):
    from paddle_tpu.robustness.checkpoint import (
        MANIFEST_NAME, CheckpointManager,
    )

    reg = get_registry()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last_n=5)
    mgr.save({"w": 1}, 1)
    mgr.save({"w": 2}, 2)
    # tear the newest checkpoint's payload
    with open(os.path.join(mgr.step_path(2), "state.pdparams"), "wb") as f:
        f.write(b"torn")
    c0 = reg.counter("checkpoint_corrupt_skipped_total").value
    get_event_log().clear()
    state, step, _ = mgr.load_latest()
    assert step == 1
    assert reg.counter("checkpoint_corrupt_skipped_total").value == c0 + 1
    warn = get_event_log().events(kind="checkpoint", severity="warning")
    assert warn and warn[0]["step"] == 2


def test_checkpoint_retry_counter(tmp_path):
    from paddle_tpu.robustness.checkpoint import CheckpointManager
    from paddle_tpu.robustness.fault_injection import FaultyFS

    reg = get_registry()
    r0 = reg.counter("checkpoint_retries_total").value
    fs = FaultyFS(transient_oserrors=1)  # first write flakes once
    mgr = CheckpointManager(str(tmp_path / "ck"), fs=fs, retries=3,
                            backoff=0.001)
    mgr.save({"w": 1}, 1)
    assert reg.counter("checkpoint_retries_total").value > r0


def test_nan_guard_trip_metrics_and_events():
    from paddle_tpu.robustness.watchdog import NanGuard

    reg = get_registry()
    fam = reg.counter("nan_guard_trips_total", labels=("action",))
    t0 = fam.labels(action="skip_step").value
    get_event_log().clear()
    g = NanGuard(policy="skip_step", max_consecutive_bad=0)
    assert g.check(loss=1.0) == "ok"
    assert g.check(loss=float("nan")) == "skip_step"
    assert g.check(loss=1.0, scaler_skipped=True) == "ok"
    assert fam.labels(action="skip_step").value - t0 == 1
    evs = get_event_log().events(kind="nan_guard")
    assert len(evs) == 1 and evs[0]["severity"] == "warning"
    assert evs[0]["action"] == "skip_step"


def test_hang_detector_heartbeat_counter_and_event():
    import time as _time

    from paddle_tpu.robustness.watchdog import HangDetector

    reg = get_registry()
    b0 = reg.counter("watchdog_heartbeats_total").value
    h0 = reg.counter("watchdog_hangs_total").value
    get_event_log().clear()
    hits = []
    hd = HangDetector(timeout=0.05, poll_interval=0.01,
                      on_hang=lambda age: hits.append(age))
    with hd:
        hd.beat()
        deadline = _time.time() + 2.0
        while not hits and _time.time() < deadline:
            _time.sleep(0.01)
    assert hits, "hang never detected"
    assert reg.counter("watchdog_heartbeats_total").value - b0 >= 2
    assert reg.counter("watchdog_hangs_total").value - h0 == 1
    evs = get_event_log().events(kind="watchdog")
    assert evs and evs[0]["severity"] == "error"
    assert evs[0]["stall_age_seconds"] >= 0.05


# ------------------------------------------------- rpc-profiler flag wiring
def test_flags_enable_rpc_profiler_streams_collective_events():
    from paddle_tpu.framework import flags as flags_mod
    from paddle_tpu.observability import rpc_profiler_enabled

    flags_mod._compat_warned.discard("FLAGS_enable_rpc_profiler")
    get_event_log().clear()
    with pytest.warns(UserWarning, match="FLAGS_enable_rpc_profiler"):
        paddle.set_flags({"FLAGS_enable_rpc_profiler": True})
    try:
        assert rpc_profiler_enabled()
        t = paddle.to_tensor(np.ones(4, "float32"))
        paddle.distributed.all_reduce(t)
        evs = get_event_log().events(kind="collective")
        assert evs and evs[0]["op"] == "all_reduce"
        assert evs[0]["bytes"] == 16
    finally:
        paddle.set_flags({"FLAGS_enable_rpc_profiler": False})
    assert not rpc_profiler_enabled()
    get_event_log().clear()
    paddle.distributed.all_reduce(paddle.to_tensor(np.ones(4, "float32")))
    assert not get_event_log().events(kind="collective")


# ----------------------------------------------------------- hapi callback
def test_metrics_callback_via_fit(tmp_path):
    from paddle_tpu.hapi.callbacks import MetricsCallback
    from paddle_tpu.io import Dataset

    class DS(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return (np.ones(4, "float32") * (i % 3),
                    np.array([1.0], "float32"))

    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=net.parameters()),
        loss=lambda out, y: ((out - y) ** 2).mean(), jit_compile=False)
    mc = MetricsCallback(log_dir=str(tmp_path), freq=2)
    model.fit(DS(), batch_size=2, epochs=1, verbose=0, callbacks=[mc])
    lines = [json.loads(l)
             for l in open(tmp_path / "metrics.jsonl").read().splitlines()]
    assert len(lines) == 2           # 4 steps / freq 2
    rec = lines[-1]
    assert rec["step"] == 4
    bd = rec["step_breakdown"]
    assert bd["steps"] == 2
    # the eager train path tags forward/backward/optimizer spans; fit tags
    # the batch fetch as "data"
    for ph in ("forward", "backward", "optimizer", "data"):
        assert bd["phases"][ph]["seconds"] > 0, ph
    assert rec["metrics"]["eager_dispatch_total"] > 0
    assert mc.last_snapshot is rec or mc.last_snapshot == rec


# ------------------------------------------------------- end-to-end smoke
def test_toy_train_trace_report_end_to_end(tmp_path):
    """CI smoke (ISSUE 3 satellite): a 3-step toy train under Profiler +
    MetricsRegistry, chrome trace exported, tools/trace_report.py consumes
    trace + snapshot end-to-end. <10s, CPU only."""
    import importlib.util

    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.robustness.checkpoint import CheckpointManager

    reg = get_registry()
    net = nn.Linear(8, 8)
    optim = paddle.optimizer.SGD(learning_rate=0.01,
                                 parameters=net.parameters())
    comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(codec="fp32"))
    ckpt = CheckpointManager(str(tmp_path / "ck"), keep_last_n=1)
    params = [p for p in net.parameters() if not p.stop_gradient]
    c0 = reg.counter("grad_comm_collectives_total",
                     labels=("codec",)).labels(codec="fp32").value

    timer = StepTimer()
    prof = Profiler(targets=[ProfilerTarget.CPU])
    x = paddle.to_tensor(np.ones((2, 8), "float32"))
    with prof, timer:
        for i in range(3):
            with RecordEvent("step"):
                with RecordEvent("forward"):
                    loss = (net(x) ** 2).mean()
                with RecordEvent("backward"):
                    loss.backward()
                comm.sync(params, world=2)
                with RecordEvent("optimizer"):
                    optim.step()
                    optim.clear_grad()
                if i == 2:
                    ckpt.save(net.state_dict(), i)
            prof.step()
            timer.step()
        ckpt.close()

    trace_path = str(tmp_path / "trace.json")
    prof.export(trace_path)
    metrics_path = str(tmp_path / "metrics.json")
    with open(metrics_path, "w") as f:
        json.dump(reg.snapshot(), f)

    # offline breakdown agrees with the live StepTimer on step count and
    # sees every phase the loop exercised
    trace = json.load(open(trace_path))
    agg = breakdown_from_trace(trace)
    assert agg["steps"] == 3 == len(timer.steps)
    for ph in ("forward", "backward", "comm", "checkpoint"):
        assert agg["phases"][ph]["seconds"] > 0, ph

    # the span tree is parent-linked: phase spans hang under "step" roots
    args_by_name = {}
    for ev in trace["traceEvents"]:
        args_by_name.setdefault(ev["name"], []).append(ev.get("args", {}))
    step_ids = {a["id"] for a in args_by_name["step"]}
    assert all(a["parent_id"] in step_ids for a in args_by_name["forward"])

    # tools/trace_report.py parses the pair end-to-end
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "tools", "trace_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    report = tr.load_report(trace_path, metrics_path)
    assert "step-time breakdown" in report
    assert "comm" in report and "collectives/step" in report
    assert "trace-cache hit rate" in report
    # the joined comm row carries the grad_comm counters for this run:
    # 3 syncs x 1 fp32 bucket -> 1 collective/step
    row = next(l for l in report.splitlines() if l.startswith("comm"))
    delta = reg.counter("grad_comm_collectives_total",
                        labels=("codec",)).labels(codec="fp32").value - c0
    assert delta == 3
    assert "collectives/step=" in row and "bytes/step=" in row

"""Telemetry layer tests (ISSUE 3): MetricsRegistry / EventLog / StepTimer,
the instrumentation sweep through dispatch, grad_comm, and robustness, and
the tier-1 smoke that drives a toy train under Profiler + registry and runs
tools/trace_report.py end-to-end."""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.observability import (
    EventLog, MetricsRegistry, StepTimer, breakdown_from_trace,
    get_event_log, get_registry, phase_of,
)
from paddle_tpu.profiler import Profiler, ProfilerTarget, RecordEvent

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- metrics core
def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.5)
    reg.gauge("g").dec()
    h = reg.histogram("h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    assert snap["c"] == 5
    assert snap["g"] == 1.5
    assert snap["h"]["count"] == 3
    assert snap["h"]["sum"] == pytest.approx(5.55)
    # cumulative bucket semantics: <=0.1 holds 1, <=1.0 holds 2
    assert snap["h"]["buckets"] == {"0.1": 1, "1.0": 2}
    assert snap["h"]["min"] == 0.05 and snap["h"]["max"] == 5.0


def test_labelled_counters_and_redeclare():
    reg = MetricsRegistry()
    fam = reg.counter("bytes", labels=("codec",))
    fam.labels(codec="bf16").inc(10)
    fam.labels(codec="int8").inc(1)
    # re-declaration returns the same family; kind clash raises
    assert reg.counter("bytes", labels=("codec",)) is fam
    with pytest.raises(ValueError):
        reg.gauge("bytes")
    with pytest.raises(ValueError):
        fam.labels(wrong="x")
    snap = reg.snapshot()
    assert snap["bytes"] == {"codec=bf16": 10, "codec=int8": 1}
    # bind() gives the raw child and survives reset() (reset in place)
    child = fam.bind(codec="bf16")
    reg.reset()
    child.inc(3)
    assert reg.snapshot()["bytes"]["codec=bf16"] == 3
    assert reg.snapshot()["bytes"]["codec=int8"] == 0


def test_prometheus_exposition_and_jsonl(tmp_path):
    reg = MetricsRegistry()
    reg.counter("reqs", help="requests").inc(7)
    reg.counter("by_op", labels=("op",)).labels(op="all_reduce").inc(2)
    reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.to_prometheus()
    assert "# HELP reqs requests" in text
    assert "# TYPE reqs counter" in text
    assert "reqs 7" in text
    assert 'by_op{op="all_reduce"} 2' in text
    assert 'lat_bucket{le="0.1"} 0' in text
    assert 'lat_bucket{le="1.0"} 1' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_count 1" in text
    p = tmp_path / "m.jsonl"
    reg.export_jsonl(str(p))
    reg.export_jsonl(str(p))
    lines = [json.loads(l) for l in open(p)]
    assert len(lines) == 2
    assert lines[0]["metrics"]["reqs"] == 7
    assert lines[0]["time"] <= lines[1]["time"]


# --------------------------------------------------------------- event log
def test_event_log_records_and_filters(tmp_path):
    log = EventLog(path=str(tmp_path / "ev.jsonl"), rank=3)
    log.info("checkpoint", "committed", step=5)
    log.warning("nan_guard", "trip", step=6)
    log.error("watchdog", "stall")
    with pytest.raises(ValueError):
        log.log("k", severity="fatal")
    assert len(log) == 3
    assert [e["kind"] for e in log.events(min_severity="warning")] == \
        ["nan_guard", "watchdog"]
    assert log.events(kind="checkpoint")[0]["step"] == 5
    recs = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
    assert len(recs) == 3
    assert all(r["rank"] == 3 for r in recs)
    # both clocks present; monotonic is non-decreasing across records
    assert all("time" in r and "mono" in r for r in recs)
    assert recs[0]["mono"] <= recs[1]["mono"] <= recs[2]["mono"]
    log.close()


def test_event_log_ring_bound_and_export(tmp_path):
    log = EventLog(max_memory=4)
    for i in range(7):
        log.info("k", i=i)
    assert len(log) == 4
    assert log.dropped == 3
    assert [e["i"] for e in log.tail(2)] == [5, 6]
    out = tmp_path / "dump.jsonl"
    log.export(str(out))
    assert len(open(out).read().splitlines()) == 4


# -------------------------------------------------------------- step timer
def test_step_timer_phase_attribution():
    assert phase_of("forward") == "forward"
    assert phase_of("comm:bucket0") == "comm"
    assert phase_of("fwd") == "forward"
    assert phase_of("matmul") is None
    t = StepTimer().start()
    try:
        with RecordEvent("forward"):
            pass
        with RecordEvent("comm"):
            pass
        row = t.step()
        with RecordEvent("backward"):
            pass
        row2 = t.step()
    finally:
        t.stop()
    assert row["forward"] > 0 and row["comm"] > 0 and row["backward"] == 0
    assert row2["backward"] > 0 and row2["forward"] == 0
    agg = t.breakdown()
    assert agg["steps"] == 2
    assert agg["phases"]["forward"]["seconds"] == pytest.approx(
        row["forward"])
    assert "forward" in t.report()
    # sinks are removed on stop: spans after stop() do not accumulate
    with RecordEvent("forward"):
        pass
    assert len(t.steps) == 2


# --------------------------------------------------- instrumentation sweep
def test_dispatch_and_trace_cache_counters():
    from paddle_tpu.framework.autograd import clear_op_cache

    reg = get_registry()
    x = paddle.to_tensor(np.ones(8, "float32"))
    clear_op_cache()  # deterministic hit/miss pattern below
    d0 = reg.counter("eager_dispatch_total").value
    h0 = reg.counter("trace_cache_hits_total").value
    m0 = reg.counter("trace_cache_misses_total").value
    u0 = reg.counter("trace_cache_uncacheable_total").value
    y = (x * 3.0).sum()
    y2 = (x * 3.0).sum()  # same mul again: a cache hit
    assert reg.counter("eager_dispatch_total").value - d0 == 4
    # mul: 1 miss then 1 hit; sum dispatches through a dynamic closure
    # (no cache key) so both runs count as uncacheable, not misses
    assert reg.counter("trace_cache_misses_total").value - m0 == 1
    assert reg.counter("trace_cache_hits_total").value - h0 == 1
    assert reg.counter("trace_cache_uncacheable_total").value - u0 == 2


def test_grad_comm_sync_records_metrics():
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.framework.tensor import Tensor

    reg = get_registry()
    lin = nn.Linear(16, 16)
    for p in lin.parameters():
        p.grad = Tensor(np.ones(p.shape, "float32"))
    cfg = grad_comm.GradCommConfig(codec="bf16")
    comm = grad_comm.GradCommunicator(cfg)
    fam_c = reg.counter("grad_comm_collectives_total",
                        labels=("codec", "path"))
    fam_b = reg.counter("grad_comm_bytes_total", labels=("codec", "path"))
    c0 = fam_c.labels(codec="bf16", path="eager").value
    b0 = fam_b.labels(codec="bf16", path="eager").value
    f0 = reg.histogram("grad_comm_bucket_fill_ratio").bind().count
    comm.sync(lin.parameters(), world=2)
    assert fam_c.labels(codec="bf16", path="eager").value - c0 == \
        comm.stats["collectives"] > 0
    assert fam_b.labels(codec="bf16", path="eager").value - b0 == \
        comm.stats["comm_bytes"] > 0
    # one fill-ratio observation per bucket
    assert reg.histogram("grad_comm_bucket_fill_ratio").bind().count - f0 \
        == comm.stats["n_buckets"]


def test_collective_issue_counter():
    from paddle_tpu.distributed import collective as coll

    reg = get_registry()
    fam = reg.counter("collectives_total", labels=("op",))
    n0 = fam.labels(op="all_reduce").value
    t = paddle.to_tensor(np.ones(4, "float32"))
    coll.all_reduce(t)
    coll.all_reduce(t)
    assert fam.labels(op="all_reduce").value - n0 == 2


def test_checkpoint_save_histogram_and_events(tmp_path):
    from paddle_tpu.robustness.checkpoint import CheckpointManager

    reg = get_registry()
    h = reg.histogram("checkpoint_save_seconds").bind()
    s0, n0 = reg.counter("checkpoint_saves_total").value, h.count
    log = get_event_log()
    log.clear()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last_n=2)
    mgr.save({"w": np.ones(4)}, 1)
    mgr.save({"w": np.ones(4) * 2}, 2)
    assert reg.counter("checkpoint_saves_total").value - s0 == 2
    assert h.count - n0 == 2
    evs = log.events(kind="checkpoint")
    assert len(evs) == 2
    assert evs[-1]["step"] == 2 and evs[-1]["severity"] == "info"
    assert evs[-1]["seconds"] > 0
    # load timing lands in the load histogram
    l0 = reg.histogram("checkpoint_load_seconds").bind().count
    mgr.load_latest()
    assert reg.histogram("checkpoint_load_seconds").bind().count == l0 + 1


def test_checkpoint_corrupt_skip_counter(tmp_path):
    from paddle_tpu.robustness.checkpoint import (
        MANIFEST_NAME, CheckpointManager,
    )

    reg = get_registry()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last_n=5)
    mgr.save({"w": 1}, 1)
    mgr.save({"w": 2}, 2)
    # tear the newest checkpoint's payload
    with open(os.path.join(mgr.step_path(2), "state.pdparams"), "wb") as f:
        f.write(b"torn")
    c0 = reg.counter("checkpoint_corrupt_skipped_total").value
    get_event_log().clear()
    state, step, _ = mgr.load_latest()
    assert step == 1
    assert reg.counter("checkpoint_corrupt_skipped_total").value == c0 + 1
    warn = get_event_log().events(kind="checkpoint", severity="warning")
    assert warn and warn[0]["step"] == 2


def test_checkpoint_retry_counter(tmp_path):
    from paddle_tpu.robustness.checkpoint import CheckpointManager
    from paddle_tpu.robustness.fault_injection import FaultyFS

    reg = get_registry()
    r0 = reg.counter("checkpoint_retries_total").value
    fs = FaultyFS(transient_oserrors=1)  # first write flakes once
    mgr = CheckpointManager(str(tmp_path / "ck"), fs=fs, retries=3,
                            backoff=0.001)
    mgr.save({"w": 1}, 1)
    assert reg.counter("checkpoint_retries_total").value > r0


def test_nan_guard_trip_metrics_and_events():
    from paddle_tpu.robustness.watchdog import NanGuard

    reg = get_registry()
    fam = reg.counter("nan_guard_trips_total", labels=("action",))
    t0 = fam.labels(action="skip_step").value
    get_event_log().clear()
    g = NanGuard(policy="skip_step", max_consecutive_bad=0)
    assert g.check(loss=1.0) == "ok"
    assert g.check(loss=float("nan")) == "skip_step"
    assert g.check(loss=1.0, scaler_skipped=True) == "ok"
    assert fam.labels(action="skip_step").value - t0 == 1
    evs = get_event_log().events(kind="nan_guard")
    assert len(evs) == 1 and evs[0]["severity"] == "warning"
    assert evs[0]["action"] == "skip_step"


def test_hang_detector_heartbeat_counter_and_event():
    import time as _time

    from paddle_tpu.robustness.watchdog import HangDetector

    reg = get_registry()
    b0 = reg.counter("watchdog_heartbeats_total").value
    h0 = reg.counter("watchdog_hangs_total").value
    get_event_log().clear()
    hits = []
    hd = HangDetector(timeout=0.05, poll_interval=0.01,
                      on_hang=lambda age: hits.append(age))
    with hd:
        hd.beat()
        deadline = _time.time() + 2.0
        while not hits and _time.time() < deadline:
            _time.sleep(0.01)
    assert hits, "hang never detected"
    assert reg.counter("watchdog_heartbeats_total").value - b0 >= 2
    assert reg.counter("watchdog_hangs_total").value - h0 == 1
    evs = get_event_log().events(kind="watchdog")
    assert evs and evs[0]["severity"] == "error"
    assert evs[0]["stall_age_seconds"] >= 0.05


# ------------------------------------------------- rpc-profiler flag wiring
def test_flags_enable_rpc_profiler_streams_collective_events():
    from paddle_tpu.framework import flags as flags_mod
    from paddle_tpu.observability import rpc_profiler_enabled

    flags_mod._compat_warned.discard("FLAGS_enable_rpc_profiler")
    get_event_log().clear()
    with pytest.warns(UserWarning, match="FLAGS_enable_rpc_profiler"):
        paddle.set_flags({"FLAGS_enable_rpc_profiler": True})
    try:
        assert rpc_profiler_enabled()
        t = paddle.to_tensor(np.ones(4, "float32"))
        paddle.distributed.all_reduce(t)
        evs = get_event_log().events(kind="collective")
        assert evs and evs[0]["op"] == "all_reduce"
        assert evs[0]["bytes"] == 16
    finally:
        paddle.set_flags({"FLAGS_enable_rpc_profiler": False})
    assert not rpc_profiler_enabled()
    get_event_log().clear()
    paddle.distributed.all_reduce(paddle.to_tensor(np.ones(4, "float32")))
    assert not get_event_log().events(kind="collective")


# ----------------------------------------------------------- hapi callback
def test_metrics_callback_via_fit(tmp_path):
    from paddle_tpu.hapi.callbacks import MetricsCallback
    from paddle_tpu.io import Dataset

    class DS(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return (np.ones(4, "float32") * (i % 3),
                    np.array([1.0], "float32"))

    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=net.parameters()),
        loss=lambda out, y: ((out - y) ** 2).mean(), jit_compile=False)
    mc = MetricsCallback(log_dir=str(tmp_path), freq=2)
    model.fit(DS(), batch_size=2, epochs=1, verbose=0, callbacks=[mc])
    lines = [json.loads(l)
             for l in open(tmp_path / "metrics.jsonl").read().splitlines()]
    assert len(lines) == 2           # 4 steps / freq 2
    rec = lines[-1]
    assert rec["step"] == 4
    bd = rec["step_breakdown"]
    assert bd["steps"] == 2
    # the eager train path tags forward/backward/optimizer spans; fit tags
    # the batch fetch as "data"
    for ph in ("forward", "backward", "optimizer", "data"):
        assert bd["phases"][ph]["seconds"] > 0, ph
    assert rec["metrics"]["eager_dispatch_total"] > 0
    assert mc.last_snapshot is rec or mc.last_snapshot == rec


# ------------------------------------------------------- end-to-end smoke
def test_toy_train_trace_report_end_to_end(tmp_path):
    """CI smoke (ISSUE 3 satellite): a 3-step toy train under Profiler +
    MetricsRegistry, chrome trace exported, tools/trace_report.py consumes
    trace + snapshot end-to-end. <10s, CPU only."""
    import importlib.util

    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.robustness.checkpoint import CheckpointManager

    reg = get_registry()
    net = nn.Linear(8, 8)
    optim = paddle.optimizer.SGD(learning_rate=0.01,
                                 parameters=net.parameters())
    comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(codec="fp32"))
    ckpt = CheckpointManager(str(tmp_path / "ck"), keep_last_n=1)
    params = [p for p in net.parameters() if not p.stop_gradient]
    c0 = reg.counter("grad_comm_collectives_total",
                     labels=("codec", "path")).labels(
                         codec="fp32", path="eager").value

    timer = StepTimer()
    prof = Profiler(targets=[ProfilerTarget.CPU])
    x = paddle.to_tensor(np.ones((2, 8), "float32"))
    with prof, timer:
        for i in range(3):
            with RecordEvent("step"):
                with RecordEvent("forward"):
                    loss = (net(x) ** 2).mean()
                with RecordEvent("backward"):
                    loss.backward()
                comm.sync(params, world=2)
                with RecordEvent("optimizer"):
                    optim.step()
                    optim.clear_grad()
                if i == 2:
                    ckpt.save(net.state_dict(), i)
            prof.step()
            timer.step()
        ckpt.close()

    trace_path = str(tmp_path / "trace.json")
    prof.export(trace_path)
    metrics_path = str(tmp_path / "metrics.json")
    with open(metrics_path, "w") as f:
        json.dump(reg.snapshot(), f)

    # offline breakdown agrees with the live StepTimer on step count and
    # sees every phase the loop exercised
    trace = json.load(open(trace_path))
    agg = breakdown_from_trace(trace)
    assert agg["steps"] == 3 == len(timer.steps)
    for ph in ("forward", "backward", "comm", "checkpoint"):
        assert agg["phases"][ph]["seconds"] > 0, ph

    # the span tree is parent-linked: phase spans hang under "step" roots
    args_by_name = {}
    for ev in trace["traceEvents"]:
        args_by_name.setdefault(ev["name"], []).append(ev.get("args", {}))
    step_ids = {a["id"] for a in args_by_name["step"]}
    assert all(a["parent_id"] in step_ids for a in args_by_name["forward"])

    # tools/trace_report.py parses the pair end-to-end
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "tools", "trace_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    report = tr.load_report(trace_path, metrics_path)
    assert "step-time breakdown" in report
    assert "comm" in report and "collectives/step" in report
    assert "trace-cache hit rate" in report
    # the joined comm row carries the grad_comm counters for this run:
    # 3 syncs x 1 fp32 bucket -> 1 collective/step
    row = next(l for l in report.splitlines() if l.startswith("comm"))
    delta = reg.counter("grad_comm_collectives_total",
                        labels=("codec", "path")).labels(
                            codec="fp32", path="eager").value - c0
    assert delta == 3
    assert "collectives/step=" in row and "bytes/step=" in row


# ============================================================ ISSUE 6 plane
# Distributed telemetry: cross-rank aggregation, flight recorder, memory
# accounting, live exposition, exposition-format fixes, quantiles.

def _emulate_ranks(n_ranks, perturb=None):
    """gather_fn factory: clone the local payload into an n-rank world
    (the single-process stand-in for the all_gather exchange, mirroring
    how chaos tests emulate ReplicaGuard's reduce_fn)."""
    import copy

    def gather(payload):
        outs = []
        for r in range(n_ranks):
            p = copy.deepcopy(payload)
            p["rank"] = r
            if perturb:
                perturb(r, p)
            outs.append(p)
        return outs

    return gather


# ------------------------------------------------------- exposition format
def test_prometheus_label_value_escaping_round_trip():
    """Satellite 1: backslash, double-quote, and newline in label values
    must be escaped per exposition format 0.0.4 — and survive a strict
    parse back to the original value."""
    from paddle_tpu.observability import parse_prometheus_text

    reg = MetricsRegistry()
    nasty = 'he said "hi"\\path\nline2'
    reg.counter("esc_total", labels=("msg",)).labels(msg=nasty).inc(2)
    text = reg.to_prometheus()
    assert '\\"hi\\"' in text and "\\\\path" in text and "\\n" in text
    # no raw newline may survive inside a sample line
    sample_lines = [l for l in text.splitlines() if l.startswith("esc_total")]
    assert len(sample_lines) == 1
    fams = parse_prometheus_text(text)
    (name, labels, value), = fams["esc_total"]["samples"]
    assert labels["msg"] == nasty
    assert value == 2.0


def test_prometheus_help_escaping():
    reg = MetricsRegistry()
    reg.counter("h_total", help="line1\nline2 \\ backslash").inc()
    text = reg.to_prometheus()
    help_lines = [l for l in text.splitlines() if l.startswith("# HELP")]
    assert help_lines == ["# HELP h_total line1\\nline2 \\\\ backslash"]


def test_strict_parser_rejects_malformed():
    from paddle_tpu.observability import parse_prometheus_text

    ok = parse_prometheus_text('a_total{x="1"} 3\n')
    assert ok["a_total"]["samples"] == [("a_total", {"x": "1"}, 3.0)]
    for bad in (
        'a_total{x=unquoted} 1\n',          # unquoted label value
        'a_total{x="v\\q"} 1\n',            # invalid escape
        'a_total{x="v"} notanumber\n',      # non-numeric value
        '# TYPE a_total counter\n# TYPE a_total gauge\na_total 1\n',  # re-TYPE
        'a_total{x="dangling\\"} 1 2 3\n',  # trailing junk
    ):
        with pytest.raises(ValueError):
            parse_prometheus_text(bad)


def test_histogram_exemplar_round_trip():
    """ISSUE 18: observe(value, exemplar=trace_id) pins the trace id to
    the tightest covering bucket; the exposition line carries an
    OpenMetrics-style `# {trace_id="..."} value` tail, the strict parser
    splits it back out, and plain samples stay 3-tuples throughout."""
    from paddle_tpu.observability import parse_prometheus_text

    reg = MetricsRegistry()
    h = reg.histogram("ex_lat_ms", buckets=(10.0, 100.0))
    h.observe(5.0, exemplar="t1-000001")
    h.observe(50.0, exemplar="t1-000002")
    h.observe(5000.0, exemplar="t1-000003")      # beyond last bound: +Inf
    assert h.get()["exemplars"] == {
        "10.0": {"value": 5.0, "trace_id": "t1-000001"},
        "100.0": {"value": 50.0, "trace_id": "t1-000002"},
        "+Inf": {"value": 5000.0, "trace_id": "t1-000003"},
    }
    # last-exemplar-wins per bucket; observes without exemplar keep it
    h.observe(7.0, exemplar="t1-000009")
    h.observe(8.0)
    assert h.get()["exemplars"]["10.0"]["trace_id"] == "t1-000009"

    text = reg.to_prometheus()
    tails = [l for l in text.splitlines() if " # {" in l]
    assert len(tails) == 3 and all("_bucket{" in l for l in tails)
    fams = parse_prometheus_text(text)          # STRICT parse still passes
    fam = fams["ex_lat_ms"]
    assert all(len(s) == 3 for s in fam["samples"])  # samples undisturbed
    by_le = {labels["le"]: (ex, v) for name, labels, ex, v
             in fam["exemplars"]}
    assert by_le["10.0"] == ({"trace_id": "t1-000009"}, 7.0)
    assert by_le["+Inf"] == ({"trace_id": "t1-000003"}, 5000.0)
    # exemplars are a render-layer detail: the cross-rank merge contract
    # (typed_snapshot) never carries them
    assert "exemplars" not in str(reg.typed_snapshot())


def test_redeclare_label_name_mismatch_raises():
    """Satellite 2: re-declaring an existing family with different label
    NAMES must raise instead of silently handing back a family whose
    .labels() rejects every increment."""
    reg = MetricsRegistry()
    fam = reg.counter("relabel_total", labels=("op",))
    assert reg.counter("relabel_total", labels=("op",)) is fam  # idempotent
    with pytest.raises(ValueError, match="labels"):
        reg.counter("relabel_total", labels=("op", "rank"))
    with pytest.raises(ValueError, match="labels"):
        reg.counter("relabel_total")  # unlabelled redeclare also a mismatch
    with pytest.raises(ValueError, match="registered as"):
        reg.gauge("relabel_total", labels=("op",))  # kind clash still first


# ---------------------------------------------------------------- quantiles
def test_histogram_quantiles():
    """Satellite 3: cumulative-bucket quantile estimation, surfaced as
    p50/p95/p99 in get()/snapshot."""
    reg = MetricsRegistry()
    h = reg.histogram("lat_s", buckets=(1.0, 2.0, 4.0, 8.0))
    assert h.quantile(0.5) is None  # empty
    for v in (0.5, 1.5, 3.0, 6.0):
        h.observe(v)
    # target=2 falls in the (1,2] bucket: lo=1, interpolates to exactly 2
    assert h.quantile(0.5) == pytest.approx(2.0)
    # the top quantiles land in the last populated bucket, clamped to the
    # observed max — never a value no observation ever had
    assert h.quantile(0.99) <= 6.0
    assert h.quantile(0.0) >= 0.5
    with pytest.raises(ValueError):
        h.quantile(1.5)
    snap = reg.snapshot()["lat_s"]
    for q in ("p50", "p95", "p99"):
        assert snap[q] is not None
    assert snap["p50"] <= snap["p95"] <= snap["p99"]


def test_histogram_quantile_all_beyond_last_bound():
    reg = MetricsRegistry()
    h = reg.histogram("big_s", buckets=(0.1,))
    h.observe(5.0)
    h.observe(7.0)
    # everything in the +Inf bucket: best estimate is the observed max
    assert h.quantile(0.9) == 7.0


# ----------------------------------------------------- cross-rank aggregation
def test_merge_typed_snapshots_rules():
    """Tentpole (a): counters sum, gauges min/max/mean, histogram buckets
    add element-wise; families missing on a rank merge over the ranks that
    have them."""
    from paddle_tpu.observability import merge_typed_snapshots

    regs = [MetricsRegistry() for _ in range(3)]
    for i, reg in enumerate(regs):
        reg.counter("c_total", labels=("op",)).labels(op="ar").inc(10 * (i + 1))
        reg.gauge("g").set(float(i))
        h = reg.histogram("h_s", buckets=(1.0, 2.0))
        h.observe(0.5 + i)  # 0.5, 1.5, 2.5
    regs[2].counter("only_r2_total").inc(7)

    merged = merge_typed_snapshots([r.typed_snapshot() for r in regs])
    assert merged["c_total"]["children"]["op=ar"] == 60
    g = merged["g"]["children"][""]
    assert g == {"min": 0.0, "max": 2.0, "mean": 1.0}
    h = merged["h_s"]["children"][""]
    assert h["count"] == 3 and h["sum"] == pytest.approx(4.5)
    assert h["bucket_counts"] == [1, 2]  # cumulative: <=1 holds 1, <=2 holds 2
    assert h["min"] == 0.5 and h["max"] == 2.5
    assert h["p50"] is not None
    # partial family: merged over the ranks that have it, count recorded
    assert merged["only_r2_total"]["children"][""] == 7
    assert merged["only_r2_total"]["ranks"] == 1


def test_merge_histogram_bound_mismatch_degrades():
    """Version-skewed bucket layouts must not throw inside telemetry —
    count/sum still merge, buckets drop."""
    from paddle_tpu.observability.aggregate import _merge_histogram

    a = {"bounds": [1.0], "bucket_counts": [1], "count": 1, "sum": 0.5,
         "min": 0.5, "max": 0.5}
    b = {"bounds": [2.0], "bucket_counts": [1], "count": 2, "sum": 3.0,
         "min": 1.0, "max": 2.0}
    m = _merge_histogram([a, b])
    assert m["count"] == 3 and m["sum"] == 3.5
    assert m["bounds"] == [] and m["bucket_counts"] == []


def test_aggregator_multirank_sum_and_skew():
    """Acceptance: rank-0 aggregate sums collectives_total across ranks and
    reports a nonzero step_time_skew under an induced straggler."""
    from paddle_tpu.observability import MetricsAggregator, note_step_time

    reg = MetricsRegistry()
    reg.counter("collectives_total", labels=("op",)).labels(
        op="all_reduce").inc(4)
    note_step_time(0.01)

    def straggle(rank, payload):
        payload["step_time"] = {"steps": 8, "mean_s": 0.01, "last_s": 0.01}
        if rank == 2:
            payload["step_time"]["mean_s"] = 0.02  # 2x straggler

    agg = MetricsAggregator(registry=reg, gather_fn=_emulate_ranks(4, straggle))
    rec = agg.aggregate()
    assert rec["ranks"] == [0, 1, 2, 3]
    fam = rec["metrics"]["collectives_total"]
    assert fam["children"]["op=all_reduce"] == 16  # 4 summed over 4 ranks
    assert rec["step_time_skew"] > 0
    assert rec["step_time"]["slowest_rank"] == 2
    assert agg.last is rec
    # the straggler gauge landed on the GLOBAL registry for scrapers
    assert get_registry().snapshot()["step_time_skew"] > 0


def test_aggregation_collective_timeout_degrades_not_raises():
    """Chaos variant: the aggregation exchange times out (PR-4 typed error)
    — training must continue on a degraded local-only record, with the
    failure counted, never an exception out of telemetry."""
    from paddle_tpu.framework.errors import CollectiveTimeoutError
    from paddle_tpu.observability import MetricsAggregator

    reg = MetricsRegistry()
    reg.counter("c_total").inc(3)

    def hang_gather(payload):
        raise CollectiveTimeoutError("all_gather timed out", op="all_gather",
                                     group=None, rank=0, attempt=3)

    fails0 = get_registry().snapshot().get(
        "telemetry_aggregation_failures_total", 0)
    agg = MetricsAggregator(registry=reg, gather_fn=hang_gather)
    rec = agg.aggregate()  # must NOT raise
    assert "CollectiveTimeoutError" in rec["degraded"]
    assert rec["metrics"]["c_total"]["children"][""] == 3  # local view kept
    assert agg.failures == 1
    assert get_registry().snapshot()[
        "telemetry_aggregation_failures_total"] == fails0 + 1
    # a later healthy round recovers cleanly
    agg.gather_fn = _emulate_ranks(2)
    assert "degraded" not in agg.aggregate()


def test_aggregated_to_plain_flattens_like_snapshot():
    from paddle_tpu.observability import merge_typed_snapshots
    from paddle_tpu.observability.aggregate import aggregated_to_plain

    regs = [MetricsRegistry() for _ in range(2)]
    for reg in regs:
        reg.counter("n_total", labels=("k",)).labels(k="a").inc(2)
        reg.gauge("same_g").set(5.0)
    plain = aggregated_to_plain(
        merge_typed_snapshots([r.typed_snapshot() for r in regs]))
    assert plain["n_total"] == {"k=a": 4}
    assert plain["same_g"] == 5.0  # agreeing gauge collapses to the value


# ------------------------------------------------------------ flight recorder
def test_flight_recorder_ring_and_dump(tmp_path):
    """Tentpole (b): bounded ring, span/event taps, postmortem dump."""
    from paddle_tpu.observability import FlightRecorder

    rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path), rank=0)
    for i in range(7):
        rec.note("lane", f"e{i}", bucket=i)
    assert len(rec) == 4  # bounded: oldest evicted
    assert [e["name"] for e in rec.entries()] == ["e3", "e4", "e5", "e6"]
    assert [e["name"] for e in rec.entries(n=2)] == ["e5", "e6"]

    path = rec.dump("unit_test")
    assert path and os.path.exists(path)
    dump = json.load(open(path))
    assert dump["reason"] == "unit_test" and dump["rank"] == 0
    assert dump["n_entries"] == 4
    assert dump["entries"][-1]["name"] == "e6"
    assert rec.dumps[-1]["path"] == path

    # capacity 0 disables recording AND dumping
    off = FlightRecorder(capacity=0, dump_dir=str(tmp_path))
    off.note("lane", "x")
    assert len(off) == 0 and off.dump("nope") is None


def test_flight_recorder_auto_dump_budget(tmp_path):
    from paddle_tpu.observability import FlightRecorder
    from paddle_tpu.observability.flight_recorder import _MAX_AUTO_DUMPS

    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path), rank=0)
    rec.note("lane", "x")
    for _ in range(_MAX_AUTO_DUMPS):
        assert rec.dump("storm", auto=True) is not None
    assert rec.dump("storm", auto=True) is None  # budget spent
    assert rec.dump("manual") is not None        # manual dumps still allowed


def test_flight_recorder_taps_spans_and_events():
    """The global recorder sees RecordEvent closes and EventLog records
    without any explicit wiring at the call sites."""
    from paddle_tpu.observability import get_flight_recorder

    rec = get_flight_recorder()
    rec.clear()
    with RecordEvent("fr_test_span"):
        pass
    get_event_log().warning("fr_test", "something happened", detail=7)
    names = [(e["kind"], e["name"]) for e in rec.entries()]
    assert ("span", "fr_test_span") in names
    ev = next(e for e in rec.entries(kind="event")
              if e["name"] == "fr_test")
    assert ev["severity"] == "warning"
    assert ev["fields"]["detail"] == 7


def test_escalation_paths_dump_flight_recorder(tmp_path, monkeypatch):
    """Every escalation path must leave a postmortem: NanGuard trip,
    breaker, HangDetector escalate, collective-timeout exhaustion."""
    import paddle_tpu.observability.flight_recorder as fr_mod
    from paddle_tpu.framework.errors import CollectiveTimeoutError
    from paddle_tpu.robustness.fault_injection import ChaosGroup
    from paddle_tpu.robustness.watchdog import HangDetector, NanGuard
    import paddle_tpu.distributed.collective as coll
    from paddle_tpu.framework.tensor import Tensor

    reasons = []
    tmp_rec = fr_mod._install(fr_mod.FlightRecorder(capacity=64,
                                                    dump_dir=str(tmp_path),
                                                    rank=0))
    monkeypatch.setattr(fr_mod, "_recorder", tmp_rec)
    real_dump = fr_mod.FlightRecorder.dump

    def spy(self, reason, path=None, auto=False):
        reasons.append(str(reason))
        return real_dump(self, reason, path=path, auto=auto)

    monkeypatch.setattr(fr_mod.FlightRecorder, "dump", spy)

    try:
        # NanGuard skip_step trip
        NanGuard(policy="skip_step").check(float("nan"))
        assert any(r.startswith("nan_guard:") for r in reasons)

        # HangDetector escalate
        hd = HangDetector(timeout=60.0, on_hang=lambda age: None)
        hd.beat()
        hd.escalate("unit test")
        assert any(r.startswith("hang_escalated:") for r in reasons)

        # collective-timeout exhaustion (every attempt hangs past the
        # group timeout -> typed error + postmortem)
        g = ChaosGroup(plan={i: ("hang", 0.3) for i in range(1, 4)},
                       timeout=0.05)
        with pytest.raises(CollectiveTimeoutError):
            coll.all_reduce(Tensor(np.float32(1.0)), group=g)
        assert any(r.startswith("collective_timeout:") for r in reasons)
        # the dump actually landed on disk
        assert any(p.name.startswith("flightrec_rank0")
                   for p in tmp_path.iterdir())
    finally:
        fr_mod._uninstall(tmp_rec)  # the temp ring's sinks must not leak


# ------------------------------------------------------------------- memory
def test_memory_accounting_sample_and_gauges():
    from paddle_tpu.observability import memory as obs_mem

    t = paddle.to_tensor(np.ones((64, 64), np.float32))  # noqa: F841 live
    s = obs_mem.sample()
    assert s["live_tensor_bytes"] >= 64 * 64 * 4
    assert get_registry().snapshot()["live_tensor_bytes"] >= 64 * 64 * 4


def test_memory_record_compiled_and_roofline():
    from paddle_tpu.observability import memory as obs_mem

    analysis = {"argument_bytes": 100, "output_bytes": 50, "temp_bytes": 30,
                "alias_bytes": 40, "generated_code_bytes": 0,
                "peak_hbm_bytes": 140}
    got = obs_mem.record_compiled("unit_entry", analysis)
    assert got["peak_hbm_bytes"] == 140
    assert obs_mem.compiled_memory()["unit_entry"]["peak_hbm_bytes"] == 140
    g = get_registry().snapshot()["compiled_peak_hbm_bytes"]
    assert g["entry=unit_entry"] == 140

    cmp = obs_mem.roofline_compare(150, 100, name="x")
    assert cmp["ratio"] == 1.5
    assert obs_mem.roofline_compare(None, 100)["ratio"] is None
    # the recorded cost-model estimates load (repo artifact present)
    rl = obs_mem.load_rooflines()
    assert rl and all(v > 0 for v in rl.values())


def test_train_step_memory_analysis_compiled_path():
    """Compiled-path accounting keyed by trace-cache entry: XLA's
    memory_analysis of the EXACT program the last call compiled."""
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.observability import memory as obs_mem

    paddle.seed(7)
    net = nn.Sequential(nn.Linear(8, 4), nn.Tanh(), nn.Linear(4, 1))
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, lambda o, y: F.mse_loss(o, y), opt)
    assert step.memory_analysis() is None  # before the first call

    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(4, 1).astype(np.float32))
    step(x, y)
    a = step.memory_analysis(entry="unit_train_step")
    assert a is not None
    assert a["peak_hbm_bytes"] == (a["argument_bytes"] + a["temp_bytes"]
                                   + a["output_bytes"] - a["alias_bytes"])
    assert a["peak_hbm_bytes"] > 0
    assert obs_mem.compiled_memory()["unit_train_step"]["peak_hbm_bytes"] \
        == a["peak_hbm_bytes"]


# --------------------------------------------------------------- exposition
def test_exposition_end_to_end_scrape(tmp_path):
    """Acceptance: /metrics round-trips through the strict parser
    (escaped label values included); /snapshot serves the rank-0
    aggregate; /events and /flightrecorder serve the rings."""
    import urllib.request

    from paddle_tpu.observability import (
        MetricsAggregator, TelemetryServer, parse_prometheus_text,
    )

    reg = MetricsRegistry()
    reg.counter("scrape_total", labels=("path",)).labels(
        path='weird "quoted"\\x').inc(3)
    reg.histogram("scrape_lat_s", buckets=(0.1, 1.0)).observe(0.5)
    agg = MetricsAggregator(registry=reg, gather_fn=_emulate_ranks(2))

    with TelemetryServer(port=0, registry=reg, aggregator=agg) as srv:
        assert srv.port  # ephemeral port bound
        text = urllib.request.urlopen(srv.url + "/metrics").read().decode()
        fams = parse_prometheus_text(text)  # STRICT: malformed would raise
        (_, labels, value), = fams["scrape_total"]["samples"]
        assert labels["path"] == 'weird "quoted"\\x' and value == 3.0
        assert fams["scrape_lat_s"]["type"] == "histogram"
        bucket_samples = [s for s in fams["scrape_lat_s"]["samples"]
                         if s[0] == "scrape_lat_s_bucket"]
        assert {s[1]["le"] for s in bucket_samples} == {"0.1", "1.0", "+Inf"}

        snap = json.load(urllib.request.urlopen(srv.url + "/snapshot"))
        assert snap["aggregated"] is True
        assert snap["ranks"] == [0, 1]
        assert snap["metrics"]["scrape_total"]["children"][
            'path=weird "quoted"\\x'] == 6  # summed over the 2 ranks
        local = json.load(
            urllib.request.urlopen(srv.url + "/snapshot?local=1"))
        assert local["aggregated"] is False

        get_event_log().info("scrape_test", "hello")
        evs = json.load(urllib.request.urlopen(srv.url + "/events?n=50"))
        assert any(e["kind"] == "scrape_test" for e in evs["events"])

        fr = json.load(urllib.request.urlopen(srv.url + "/flightrecorder"))
        assert fr["capacity"] > 0

        ok = urllib.request.urlopen(srv.url + "/healthz").read()
        assert ok == b"ok\n"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/nope")
        assert e.value.code == 404
    # context exit stopped the server
    with pytest.raises(OSError):
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/healthz",
                               timeout=0.5)


def test_healthz_verbose_and_404_list_dynamic_sections(tmp_path):
    """ISSUE 18: the /healthz?verbose path list and the 404 body are
    computed from the live section map — a section registered after the
    server started (how the serving runtime mounts /serving and /traces)
    appears in both, and disappears on unregister. The bare /healthz
    liveness probe body stays exactly "ok\\n"."""
    import urllib.request

    from paddle_tpu.observability import TelemetryServer
    from paddle_tpu.observability.exposition import (
        register_section, unregister_section,
    )

    reg = MetricsRegistry()
    with TelemetryServer(port=0, registry=reg) as srv:
        assert urllib.request.urlopen(srv.url + "/healthz").read() == b"ok\n"
        base = json.load(
            urllib.request.urlopen(srv.url + "/healthz?verbose=1"))
        assert base["status"] == "ok"
        assert "/metrics" in base["paths"] and "/healthz" in base["paths"]
        assert "/dyn" not in base["paths"]

        register_section("dyn", lambda: {"n": 7},
                         lambda sub: {"sub": sub} if sub == "x" else None)
        try:
            live = json.load(
                urllib.request.urlopen(srv.url + "/healthz?verbose=1"))
            assert "/dyn" in live["paths"]
            assert json.load(
                urllib.request.urlopen(srv.url + "/dyn")) == {"n": 7}
            assert json.load(
                urllib.request.urlopen(srv.url + "/dyn/x")) == {"sub": "x"}
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(srv.url + "/dyn/nope")
            assert e.value.code == 404
            # the 404 body itself advertises the live paths
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(srv.url + "/nope")
            body = json.loads(e.value.read())
            assert "/dyn" in body["paths"]
        finally:
            unregister_section("dyn")
        gone = json.load(
            urllib.request.urlopen(srv.url + "/healthz?verbose=1"))
        assert "/dyn" not in gone["paths"]


def test_start_exposition_flag_gated(monkeypatch):
    from paddle_tpu.framework import flags as flags_mod
    from paddle_tpu.observability import (
        get_telemetry_server, start_exposition, stop_exposition,
    )

    stop_exposition()
    # flag unset -> off, returns None so callers can wire unconditionally
    monkeypatch.setitem(flags_mod._FLAGS, "FLAGS_telemetry_http_port", 0)
    assert start_exposition() is None
    assert get_telemetry_server() is None
    try:
        srv = start_exposition(port=0)  # explicit port overrides the flag
        assert srv is not None and srv.port
        assert start_exposition(port=0) is srv  # idempotent
    finally:
        stop_exposition()


# ------------------------------------------------- hapi aggregation wiring
def test_metrics_callback_aggregates_and_samples_memory(tmp_path):
    """Model.fit with telemetry: each dump carries the cross-rank aggregate
    (emulated 2-rank world) + a memory sample; the skew gauge lands."""
    import paddle_tpu.optimizer as optim
    from paddle_tpu.hapi import Model
    from paddle_tpu.hapi.callbacks import MetricsCallback
    from paddle_tpu.observability import MetricsAggregator

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = Model(net)
    model.prepare(optim.SGD(learning_rate=0.01,
                            parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    rs = np.random.RandomState(0)
    data = [(rs.standard_normal(4).astype(np.float32),
             np.int64(rs.randint(2))) for _ in range(8)]

    agg = MetricsAggregator(gather_fn=_emulate_ranks(2))
    cb = MetricsCallback(log_dir=str(tmp_path), freq=4, aggregate=True,
                         aggregator=agg)
    model.fit(data, batch_size=2, epochs=1, verbose=0, callbacks=[cb],
              telemetry=agg)
    rec = cb.last_snapshot
    assert rec is not None
    assert rec["aggregated"]["ranks"] == [0, 1]
    assert "step_time_skew" in rec["aggregated"]
    assert rec["memory"]["live_tensor_bytes"] > 0
    # records serialized to JSONL despite non-JSON-native payloads
    lines = open(os.path.join(str(tmp_path), "metrics.jsonl")).readlines()
    assert lines and all(json.loads(l) for l in lines)


# --------------------------------------------------- strategy knob wiring
def test_fleet_strategy_telemetry_knobs():
    """DistributedStrategy.telemetry resizes the flight-recorder ring at
    fleet.init time (the exposition port stays flag-gated: 0 = off)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.observability import get_flight_recorder

    old_cap = get_flight_recorder().capacity
    old_state = dict(fleet._fleet_state)
    old_mesh = mesh_mod.get_mesh()
    strategy = fleet.DistributedStrategy()
    strategy.telemetry = True
    cfg = dict(strategy.telemetry_configs)
    cfg["flight_recorder_capacity"] = 512
    strategy.telemetry_configs = cfg
    try:
        fleet.init(is_collective=True, strategy=strategy)
        assert get_flight_recorder().capacity == 512
    finally:
        from paddle_tpu.observability import configure_flight_recorder

        configure_flight_recorder(capacity=old_cap)
        # a telemetry-opted fleet strategy must not leak into later tests
        # (Model.fit auto-inherits it)
        fleet._fleet_state.clear()
        fleet._fleet_state.update(old_state)
        # fleet.init SETS the global hybrid mesh; leaving it behind made
        # every later single-device Model.fit shard its small batches
        # over data=8 — the order-dependent TestRobustCheckpointCallback
        # tier-1 failures (PR 14's note, fixed + pinned in PR 15)
        mesh_mod.set_mesh(old_mesh)


# -------------------------------------------------------------- bench gate
class TestBenchGate:
    """tools/bench_gate.py (ISSUE 6 satellite): the trajectory regression
    gate — offline smoke passes on the recorded trajectory, a
    synthetically degraded record fails, format drift exits 2."""

    @pytest.fixture()
    def bench_gate(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_offline_passes_on_current_trajectory(self, bench_gate):
        assert bench_gate.main(["--offline"]) == 0

    def test_degraded_candidate_fails(self, bench_gate, tmp_path):
        traj = bench_gate.load_trajectory()
        assert traj, "repo must carry BENCH_r*.json records"
        degraded = dict(traj[-1][1])
        degraded["value"] = degraded["value"] * 0.5  # half the tokens/s
        p = tmp_path / "degraded.json"
        p.write_text(json.dumps(degraded))
        assert bench_gate.main(["--candidate", str(p)]) == 1

    def test_memory_and_comm_regressions_gate(self, bench_gate, tmp_path):
        base = {"value": 1000.0, "fallback": "cpu",
                "exposed_comm_ms": {"serial": 9.0, "overlapped": 1.0},
                "peak_hbm_bytes_measured": 1000}
        rounds = tmp_path / "rounds"
        rounds.mkdir()
        (rounds / "BENCH_r01.json").write_text(
            json.dumps({"n": 1, "rc": 0, "parsed": base}))
        ok = dict(base, value=990.0)
        p_ok = tmp_path / "ok.json"
        p_ok.write_text(json.dumps(ok))
        assert bench_gate.main(["--root", str(rounds),
                                "--candidate", str(p_ok)]) == 0
        # 2x the peak HBM (> the 20% band, lower-is-better) regresses
        worse_mem = dict(base, peak_hbm_bytes_measured=2000)
        p_mem = tmp_path / "mem.json"
        p_mem.write_text(json.dumps(worse_mem))
        assert bench_gate.main(["--root", str(rounds),
                                "--candidate", str(p_mem)]) == 1
        # 3x the exposed comm regresses too
        worse_comm = dict(
            base, exposed_comm_ms={"serial": 9.0, "overlapped": 3.0})
        p_comm = tmp_path / "comm.json"
        p_comm.write_text(json.dumps(worse_comm))
        assert bench_gate.main(["--root", str(rounds),
                                "--candidate", str(p_comm)]) == 1

    def test_device_class_mismatch_and_drift_exit_2(self, bench_gate,
                                                    tmp_path):
        rounds = tmp_path / "rounds"
        rounds.mkdir()
        (rounds / "BENCH_r01.json").write_text(json.dumps(
            {"n": 1, "rc": 0, "parsed": {"value": 1000.0,
                                         "fallback": "cpu"}}))
        # a TPU candidate is never judged against a CPU baseline
        tpu = tmp_path / "tpu.json"
        tpu.write_text(json.dumps({"value": 10.0,
                                   "device_kind": "TPU v5 lite"}))
        assert bench_gate.main(["--root", str(rounds),
                                "--candidate", str(tpu)]) == 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert bench_gate.main(["--root", str(empty), "--offline"]) == 2

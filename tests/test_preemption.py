"""Deterministic PreemptionHandler unit tests (ISSUE 17 satellite 3).

The three contracts the fleet controller leans on, pinned in isolation:
- flag-file polling latches STICKY: the scheduler deleting its sentinel
  after we've seen it must not un-request the preemption;
- grace_remaining() is the full window until should_stop() drains, then
  a monotonic countdown clamped at zero — the controller's save-budget
  arithmetic depends on the clock starting at the DRAIN, not the notice;
- timed_emergency_save(budget_s=...) counts + error-logs a commit that
  lands after its budget, and stays quiet inside it.
"""
import signal
import time

import pytest

from paddle_tpu.observability import get_event_log
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.robustness import CheckpointManager
from paddle_tpu.robustness.fault_injection import FaultyFS
from paddle_tpu.robustness.preemption import (
    PreemptionHandler, timed_emergency_save,
)


def _preempt_count(source):
    return get_registry().counter(
        "preemptions_total", labels=("source",)).labels(source=source).value


class TestFlagFilePolling:
    def test_no_flag_no_request(self, tmp_path):
        h = PreemptionHandler(flag_file=str(tmp_path / "preempt"))
        assert not h.requested and not h.should_stop()

    def test_flag_latches_sticky_across_deletion(self, tmp_path):
        flag = tmp_path / "preempt"
        h = PreemptionHandler(flag_file=str(flag))
        flag.write_text("")
        assert h.requested
        flag.unlink()               # scheduler cleans up its sentinel
        assert h.requested          # ...the latch must not care
        assert h.should_stop()

    def test_flag_source_attributed_on_drain(self, tmp_path):
        flag = tmp_path / "preempt"
        h = PreemptionHandler(flag_file=str(flag))
        flag.write_text("")
        before = _preempt_count("flag_file")
        get_event_log().clear()
        assert h.should_stop()
        assert _preempt_count("flag_file") == before + 1
        evs = get_event_log().events(kind="preemption", severity="warning")
        assert evs and evs[-1]["source"] == "flag_file"

    def test_drain_counts_exactly_once(self, tmp_path):
        flag = tmp_path / "preempt"
        flag.write_text("")
        h = PreemptionHandler(flag_file=str(flag))
        before = _preempt_count("flag_file")
        for _ in range(5):          # every later step boundary re-asks
            assert h.should_stop()
        assert _preempt_count("flag_file") == before + 1

    def test_reset_unlatches_until_flag_reappears(self, tmp_path):
        flag = tmp_path / "preempt"
        flag.write_text("")
        h = PreemptionHandler(flag_file=str(flag))
        assert h.should_stop()
        flag.unlink()
        h.reset()
        assert not h.requested and not h.should_stop()
        assert h.grace_remaining() == h.grace_seconds
        flag.write_text("")         # a fresh notice latches again
        assert h.should_stop()


class TestGraceRemaining:
    def test_full_window_before_drain(self):
        h = PreemptionHandler(grace_seconds=30.0)
        h.request()
        # latched but not yet drained: the clock has not started
        assert h.grace_remaining() == 30.0

    def test_countdown_starts_at_drain(self):
        h = PreemptionHandler(grace_seconds=5.0)
        h.request()
        assert h.should_stop()
        g0 = h.grace_remaining()
        assert 0.0 < g0 <= 5.0
        time.sleep(0.05)
        g1 = h.grace_remaining()
        assert g1 < g0              # monotonic countdown
        assert g0 - g1 >= 0.04

    def test_clamps_to_zero_after_deadline(self):
        h = PreemptionHandler(grace_seconds=0.01)
        h.request()
        assert h.should_stop()
        time.sleep(0.03)
        assert h.grace_remaining() == 0.0

    def test_exit_status_resumable_convention(self):
        h = PreemptionHandler()
        h.request(signal.SIGTERM)
        assert h.exit_status() == 128 + int(signal.SIGTERM)  # 143
        h2 = PreemptionHandler(flag_file="/nonexistent")
        h2._latch.set()             # flag-style latch: no signum
        assert h2.exit_status() == 1


class TestTimedEmergencySaveBudget:
    def test_within_budget_stays_quiet(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        snap0 = get_registry().snapshot()
        get_event_log().clear()
        ms = timed_emergency_save(mgr, {"w": 1}, 0, budget_s=30.0)
        assert ms >= 0
        snap = get_registry().snapshot()
        assert snap.get("emergency_save_budget_exceeded_total", 0) \
            == snap0.get("emergency_save_budget_exceeded_total", 0)
        assert get_event_log().events(kind="preemption", severity="info")
        assert not get_event_log().events(kind="preemption",
                                          severity="error")

    def test_over_budget_counts_and_errors(self, tmp_path):
        # slow_io makes the commit take >> the (tiny) budget,
        # deterministically — no timing races on a loaded CI box
        fs = FaultyFS(slow_io=0.03)
        mgr = CheckpointManager(str(tmp_path), fs=fs)
        snap0 = get_registry().snapshot()
        get_event_log().clear()
        ms = timed_emergency_save(mgr, {"w": 1}, 7, budget_s=0.001)
        assert ms > 1.0             # the save itself still commits
        snap = get_registry().snapshot()
        assert snap["emergency_save_budget_exceeded_total"] \
            == snap0.get("emergency_save_budget_exceeded_total", 0) + 1
        errs = get_event_log().events(kind="preemption", severity="error")
        assert errs and errs[-1]["step"] == 7
        assert errs[-1]["ms"] > errs[-1]["budget_ms"]

    def test_budget_from_grace_remaining_roundtrip(self, tmp_path):
        """The controller's actual call shape: budget = what's left of
        the grace window at save time."""
        h = PreemptionHandler(grace_seconds=60.0)
        h.request()
        assert h.should_stop()
        mgr = CheckpointManager(str(tmp_path))
        snap0 = get_registry().snapshot()
        timed_emergency_save(mgr, {"w": 2}, 3,
                             budget_s=h.grace_remaining())
        snap = get_registry().snapshot()
        assert snap.get("emergency_save_budget_exceeded_total", 0) \
            == snap0.get("emergency_save_budget_exceeded_total", 0)
        # the checkpoint is the emergency kind (retention-exempt)
        assert mgr.is_emergency(3)
        # and the grace window is still mostly intact afterwards
        assert h.grace_remaining() > 50.0

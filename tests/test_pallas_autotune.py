"""Autotune harness (ops/pallas/autotune.py): cache round-trip, shape
bucketing, corruption discard, flag-off inertness, winner selection.

Timing on CPU is forbidden by contract (interpret-mode candidates are
validated-only), so selection tests inject deterministic timers — the
same seam tools/kernel_bench.py --seed-cache uses.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (conftest platform setup)
from paddle_tpu.framework import flags
from paddle_tpu.ops import pallas as pk
from paddle_tpu.ops.pallas import autotune as at

import jax.numpy as jnp


@pytest.fixture
def flag_on():
    flags.set_flags({"FLAGS_kernel_autotune": True})
    at.reset_runtime_cache()
    try:
        yield
    finally:
        flags.set_flags({"FLAGS_kernel_autotune": False})
        at.reset_runtime_cache()


@pytest.fixture
def fresh_cache():
    at.reset_runtime_cache()
    yield
    at.reset_runtime_cache()


def _fused_args(n=1000, seed=0):
    rs = np.random.RandomState(seed)
    p = jnp.asarray(rs.randn(n), jnp.float32)
    g = jnp.asarray(rs.randn(n), jnp.float32)
    slots = {"moment1": jnp.zeros((n,), jnp.float32),
             "moment2": jnp.zeros((n,), jnp.float32),
             "beta1_pow": jnp.ones((), jnp.float32),
             "beta2_pow": jnp.ones((), jnp.float32)}
    lr = jnp.asarray(1e-3, jnp.float32)
    hyper = {"beta1": 0.9, "beta2": 0.999, "eps": 1e-8}
    return (p, g, slots, lr, "adamw", hyper, 1.0, 0.01)


# ------------------------------------------------------------- shape bucket

def test_shape_bucket_rounds_up_to_pow2():
    assert at.shape_bucket((1000,)) == (1024,)
    assert at.shape_bucket((1024,)) == (1024,)
    assert at.shape_bucket((2, 96, 4, 64)) == (2, 128, 4, 64)
    assert at.shape_bucket((1,)) == (1,)


def test_cache_key_deterministic_and_free_of_time():
    k1 = at.cache_key("f", (1000,), jnp.float32, "cpu")
    k2 = at.cache_key("f", (777,), jnp.float32, "cpu")
    assert k1 == "f|1024|float32|cpu"
    assert k1 == k2  # same bucket
    assert at.cache_key("f", (1025,), jnp.float32, "cpu") != k1


# ------------------------------------------------------------------- cache

def test_cache_round_trip_byte_identical(tmp_path):
    path = str(tmp_path / "cache.json")
    c = at.TuneCache()
    c.put("fused_update|1024|float32|cpu", {"tile": 32}, measured_ms=1.25,
          default_ms=2.5)
    c.put("flash_attention|2x128x4x64|float32-causal|cpu",
          {"block_q": 64, "block_k": 128})
    c.save(path)
    with open(path) as f:
        first = f.read()
    c2 = at.TuneCache.load(path)
    assert c2.ok
    assert c2.entries == c.entries
    c2.save(path)
    with open(path) as f:
        assert f.read() == first  # save→load→save byte-identical


def test_cache_corruption_discarded_loudly(tmp_path):
    path = str(tmp_path / "cache.json")
    with open(path, "w") as f:
        f.write("{ this is not json")
    with pytest.warns(UserWarning, match="discarded"):
        c = at.TuneCache.load(path)
    assert not c.ok and c.entries == {}


def test_cache_version_drift_discarded_loudly(tmp_path):
    path = str(tmp_path / "cache.json")
    with open(path, "w") as f:
        json.dump({"version": 999, "entries": {"k": {"params": {}}}}, f)
    with pytest.warns(UserWarning, match="version"):
        c = at.TuneCache.load(path)
    assert not c.ok and c.entries == {}


def test_cache_missing_file_is_valid_empty():
    c = at.TuneCache.load("/nonexistent/kernel_tune_cache.json")
    assert c.ok and c.entries == {}


# ---------------------------------------------------------------- dispatch

def test_lookup_inert_with_flag_off(fresh_cache):
    snap_before = _dispatch_count("fused_update", "tuned")
    assert at.lookup("fused_update", (1000,), jnp.float32) is None
    assert _dispatch_count("fused_update", "tuned") == snap_before


def _dispatch_count(kernel, source):
    from paddle_tpu.observability import get_registry

    fam = get_registry().get("kernel_dispatch_total")
    if fam is None:
        return 0
    snap = get_registry().snapshot().get("kernel_dispatch_total", {})
    if isinstance(snap, dict):
        return snap.get(f"kernel={kernel},source={source}", 0)
    return 0


def test_lookup_consults_injected_cache(flag_on):
    c = at.TuneCache()
    c.put(at.cache_key("fused_update", (1000,), jnp.float32),
          {"tile": 64})
    at.reset_runtime_cache(c)
    assert at.lookup("fused_update", (1000,), jnp.float32) == {"tile": 64}
    # different bucket -> miss -> default
    assert at.lookup("fused_update", (5000,), jnp.float32) is None


def test_lookup_counts_fallback_on_corrupt_cache(flag_on, tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write("garbage")
    with pytest.warns(UserWarning):
        at.reset_runtime_cache(at.TuneCache.load(path))
    before = _dispatch_count("fused_update", "fallback")
    assert at.lookup("fused_update", (1000,), jnp.float32) is None
    assert _dispatch_count("fused_update", "fallback") == before + 1


def test_lookup_returns_copy(flag_on):
    c = at.TuneCache()
    key = at.cache_key("fused_update", (1000,), jnp.float32)
    c.put(key, {"tile": 64})
    at.reset_runtime_cache(c)
    got = at.lookup("fused_update", (1000,), jnp.float32)
    got["tile"] = 999
    assert at.lookup("fused_update", (1000,), jnp.float32) == {"tile": 64}


# ---------------------------------------------------------------- autotune

def test_sweep_selects_validated_non_default_winner(tmp_path, fresh_cache):
    """The acceptance sweep: an injected timer that prefers tile=32 makes
    the harness persist a validated non-default winner, and dispatch
    under the flag then serves it."""
    args = _fused_args()
    cache = at.TuneCache()
    path = str(tmp_path / "cache.json")

    def timer(params, fn):
        return 1.0 if params["tile"] == 4 else 2.0 + params["tile"] * 0.01

    rep = at.autotune("fused_update", *args, cache=cache, timer=timer,
                      cache_path=path)
    assert rep["winner_params"] == {"tile": 4}
    assert rep["winner_params"] != rep["default_params"]
    assert rep["n_validated"] == rep["n_candidates"] > 1
    assert rep["persisted"]
    reloaded = at.TuneCache.load(path)
    assert reloaded.get(rep["key"])["params"] == {"tile": 4}
    # dispatch consults it under the flag
    flags.set_flags({"FLAGS_kernel_autotune": True})
    try:
        at.reset_runtime_cache(reloaded)
        assert at.lookup("fused_update", (1000,),
                         jnp.float32) == {"tile": 4}
    finally:
        flags.set_flags({"FLAGS_kernel_autotune": False})
        at.reset_runtime_cache()


def test_sweep_rejects_below_roofline_timings(fresh_cache):
    """A timing that beats physics is noise: rejected, never persisted."""
    args = _fused_args()
    cache = at.TuneCache()

    def impossible_timer(params, fn):
        return 1e-30

    rep = at.autotune("fused_update", *args, cache=cache,
                      timer=impossible_timer, persist=True,
                      cache_path="/nonexistent/should/never/write.json")
    assert rep["n_timed"] == 0
    assert rep["n_rejected_roofline"] == rep["n_validated"] > 0
    assert rep["winner_params"] is None and not rep["persisted"]


def test_sweep_interpret_mode_validates_but_never_times(fresh_cache):
    """No timer on CPU -> every candidate validated, none timed, no
    winner, nothing persisted (the interpret contract)."""
    args = _fused_args(n=500)
    rep = at.autotune("fused_update", *args,
                      cache=at.TuneCache(),
                      cache_path="/nonexistent/never.json")
    assert rep["n_validated"] == rep["n_candidates"] > 0
    assert rep["n_timed"] == 0
    assert rep["winner_params"] is None and not rep["persisted"]


def test_sweep_winner_equal_to_default_not_persisted(fresh_cache):
    args = _fused_args()
    cache = at.TuneCache()

    def timer(params, fn):
        from paddle_tpu.ops.pallas.fused_update import DEFAULT_TILE

        return 1.0 if params["tile"] == DEFAULT_TILE else 5.0

    rep = at.autotune("fused_update", *args, cache=cache, timer=timer,
                      cache_path="/nonexistent/never.json")
    assert rep["winner_params"] == rep["default_params"]
    assert not rep["persisted"] and cache.entries == {}


def test_all_four_families_registered():
    for fam in ("flash_attention", "quant_matmul", "fused_update",
                "block_codec"):
        assert fam in pk.FAMILIES, sorted(pk.FAMILIES)


# ------------------------------------------------------ flag-off inertness

def test_flag_off_dispatch_sites_use_defaults(fresh_cache):
    """With FLAGS_kernel_autotune unset, every dispatch helper returns
    the pre-ISSUE-13 defaults even with a loaded cache sitting there."""
    c = at.TuneCache()
    for kernel, shape, dtype, params in [
            ("fused_update", (1000,), jnp.float32, {"tile": 64}),
            ("block_codec", (5,), jnp.dtype("float32"), {"tile": 64})]:
        c.put(at.cache_key(kernel, shape, dtype), params)
    at.reset_runtime_cache(c)

    from paddle_tpu.distributed import grad_comm as gc
    from paddle_tpu.ops.pallas import codec as pc
    from paddle_tpu.ops.pallas.fused_update import (DEFAULT_TILE,
                                                    _resolve_tile)

    assert _resolve_tile(1000, jnp.float32, None) == DEFAULT_TILE
    # the grad_comm codec seam resolves to the pure-jnp pair
    enc, dec = gc._block_kernel_ops()
    assert enc is gc.block_encode and dec is gc.block_decode
    assert pc._resolve_tile(5, jnp.float32, None) == pc.DEFAULT_TILE


def test_codec_seam_needs_tpu_target_even_with_flag(flag_on):
    """Flag on but CPU compile target: the codec seam still returns the
    jnp pair — the pallas codecs only engage for TPU lowering."""
    from paddle_tpu.distributed import grad_comm as gc

    enc, dec = gc._block_kernel_ops()
    assert enc is gc.block_encode and dec is gc.block_decode

    from paddle_tpu.framework.target import force_target

    with force_target("tpu"):
        enc2, dec2 = gc._block_kernel_ops()
    from paddle_tpu.ops.pallas import codec as pc

    assert enc2 is pc.block_encode and dec2 is pc.block_decode

"""Overlapped gradient communication + fused flat-buffer optimizer update
(ISSUE 5: distributed/overlap.py, optimizer/fused.py).

Covers the tentpole contract: bucket collectives launch BEFORE backward
completes (span ordering in the step trace), results are bit-identical to
the serial sync for fp32/bf16/int8 (error-feedback residuals included),
the in-trace per-bucket-future path matches the serial psum values, the
fused flat update equals the per-param optimizer exactly (SGD/Adam/AdamW,
ZeRO-2 shard form), and the strategy/cost-model/bench wiring.
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.collective as coll
import paddle_tpu.distributed.env as env_mod
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed import fleet, grad_comm, overlap
from paddle_tpu.distributed.overlap import (
    BucketFuture, OverlappedGradCommunicator, communicator_for,
    overlap_report,
)
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.optimizer.fused import FusedFlatUpdater

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
rng = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


@pytest.fixture(autouse=True)
def reset_fleet_state():
    """fleet.init is process-global; a leaked strategy from one test would
    silently re-route another test's DataParallel communicator."""
    from paddle_tpu.distributed.fleet import _fleet_state

    saved = dict(_fleet_state)
    yield
    _fleet_state.clear()
    _fleet_state.update(saved)


def _two_rank_all_reduce(calls=None):
    """Two identical emulated ranks: AVG/MAX identity, integer SUM doubles
    (same fake as tests/test_grad_comm.py)."""
    def fake(t, op=None, group=None, **kw):
        if calls is not None:
            calls.append((str(t._value.dtype), op))
        if op == coll.ReduceOp.SUM and jnp.issubdtype(t._value.dtype,
                                                      jnp.integer):
            t._value = t._value * 2
        return t
    return fake


def _mlp(seed=7):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    return net


# tiny caps -> the MLP splits into 3 buckets, so "bucket-ready" ordering
# is observable
def _cfg(codec="fp32", overlapped=False):
    return grad_comm.GradCommConfig(codec, comm_buffer_size=0.0002,
                                    last_comm_buffer_size=0.0001,
                                    overlap=overlapped)


X = rng.standard_normal((16, 8)).astype(np.float32)
Y = rng.standard_normal((16, 1)).astype(np.float32)


# ------------------------------------------------------------ exact parity
@pytest.mark.parametrize("codec", grad_comm.CODECS)
def test_overlapped_sync_bit_identical_to_serial(codec, monkeypatch):
    """The acceptance bar: N training steps with bucket-ready overlapped
    sync produce EXACTLY the serial path's losses, grads, params — and for
    int8, exactly its cross-step error-feedback residuals."""
    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())

    def train(overlapped, steps=5):
        net = _mlp()
        opt = optim.SGD(learning_rate=0.2, parameters=net.parameters())
        comm = communicator_for(_cfg(codec, overlapped))
        params = [p for p in net.parameters() if not p.stop_gradient]
        losses = []
        for _ in range(steps):
            if overlapped:
                comm.prepare(params, world=2)
            loss = F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            comm.sync(params, world=2)
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses, comm, net

    l_ser, c_ser, net_ser = train(False)
    l_ovl, c_ovl, net_ovl = train(True)
    assert type(c_ovl) is OverlappedGradCommunicator
    assert l_ser == l_ovl, (codec, l_ser, l_ovl)
    for a, b in zip(net_ser.parameters(), net_ovl.parameters()):
        assert np.array_equal(np.asarray(a._value), np.asarray(b._value))
    # int8 error feedback: the residual carried into the next step must be
    # the serial one, bit for bit, or a later step silently diverges
    assert sorted(c_ser._residuals) == sorted(c_ovl._residuals)
    for k in c_ser._residuals:
        assert np.array_equal(np.asarray(c_ser._residuals[k]),
                              np.asarray(c_ovl._residuals[k])), (codec, k)
    if codec == "int8":
        assert c_ser._residuals, "int8 run recorded no residuals"
    # the overlapped run actually overlapped
    assert c_ovl.stats["overlapped"] is True
    assert c_ovl.stats["n_buckets"] >= 3
    assert c_ovl.stats["buckets_launched_early"] == c_ovl.stats["n_buckets"]


def test_bucket_launches_before_backward_completes(monkeypatch):
    """Span-ordering proof (the step-trace acceptance check): every
    bucket's launch marker lands INSIDE the backward span — the collective
    was issued while backward was still running — and the lane's
    comm:bucket spans exist for each bucket."""
    from paddle_tpu import profiler as prof

    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    spans = []
    sink = lambda name, t0, t1, tid: spans.append((name, t0, t1, tid))
    prof.add_span_sink(sink)
    try:
        net = _mlp()
        comm = OverlappedGradCommunicator(_cfg("fp32", True))
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm.prepare(params, world=2)
        with prof.RecordEvent("backward"):
            F.mse_loss(net(paddle.to_tensor(X)),
                       paddle.to_tensor(Y)).backward()
        comm.sync(params, world=2)
    finally:
        prof.remove_span_sink(sink)

    bwd = [s for s in spans if s[0] == "backward"]
    launches = [s for s in spans if s[0].startswith("comm_launch:bucket")]
    lane = [s for s in spans if s[0].startswith("comm:bucket")]
    assert len(bwd) == 1
    b0, b1 = bwd[0][1], bwd[0][2]
    n_buckets = comm.stats["n_buckets"]
    assert n_buckets >= 3
    assert len(launches) == n_buckets and len(lane) == n_buckets
    for name, t0, t1, _tid in launches:
        assert b0 <= t0 <= b1, \
            f"{name} launched outside the backward span"
    # the communicator's own timeline agrees (what flush() accounted)
    assert all(row["launched_early"] for row in comm.last_timeline)
    # and an exposed "comm" span exists for the flush barrier
    assert any(s[0] == "comm" for s in spans)


def test_gpt_test_overlap_parity_and_span_ordering(monkeypatch):
    """The gpt-test acceptance config: overlapped losses exactly equal
    serial losses, and every bucket launches mid-backward."""
    from paddle_tpu import profiler as prof
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )

    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 256, (2, 16)).astype(np.int64)
    labels = rs.randint(0, 256, (2, 16)).astype(np.int64)

    def train(overlapped, steps=2):
        paddle.seed(1234)
        m = GPTForCausalLM(gpt_presets("gpt-test"), seed=7)
        crit = GPTPretrainingCriterion()
        o = optim.AdamW(learning_rate=1e-3, parameters=m.parameters())
        cfg = grad_comm.GradCommConfig("fp32", comm_buffer_size=0.05,
                                       last_comm_buffer_size=0.01,
                                       overlap=overlapped)
        comm = communicator_for(cfg)
        params = [p for p in m.parameters() if not p.stop_gradient]
        losses = []
        for _ in range(steps):
            if overlapped:
                comm.prepare(params, world=2)
            loss = crit(m(paddle.to_tensor(ids, dtype="int64")),
                        paddle.to_tensor(labels, dtype="int64"))
            with prof.RecordEvent("backward"):
                loss.backward()
            comm.sync(params, world=2)
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        return losses, comm

    l_ser, _ = train(False)
    spans = []
    sink = lambda name, t0, t1, tid: spans.append((name, t0, t1))
    prof.add_span_sink(sink)
    try:
        l_ovl, comm = train(True)
    finally:
        prof.remove_span_sink(sink)
    assert l_ser == l_ovl, (l_ser, l_ovl)
    assert comm.stats["n_buckets"] >= 2
    # every bucket of every step launched inside A backward span
    bwd = [(t0, t1) for n, t0, t1 in spans if n == "backward"]
    launches = [(n, t0) for n, t0, t1 in spans
                if n.startswith("comm_launch:bucket")]
    assert len(launches) == 2 * comm.stats["n_buckets"]
    for name, t0 in launches:
        assert any(b0 <= t0 <= b1 for b0, b1 in bwd), \
            f"{name} launched outside backward"


# --------------------------------------------------------------- lifecycle
def test_flush_handles_stragglers_and_unprepared_sync(monkeypatch):
    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    net = _mlp()
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(_cfg("fp32", True))

    # unprepared sync falls back to the serial path (still correct)
    for p in params:
        p.grad = Tensor(rng.standard_normal(p.shape).astype(np.float32))
    before = [np.asarray(p.grad._value).copy() for p in params]
    comm.sync(params, world=2)
    for b, p in zip(before, params):
        assert np.array_equal(b, np.asarray(p.grad._value))  # AVG identity
    assert "overlapped" not in comm.stats

    # prepared, but NO backward ran: grads set manually -> all buckets are
    # stragglers launched at flush; still completes and accounts
    comm.prepare(params, world=2)
    for p in params:
        p.grad = Tensor(rng.standard_normal(p.shape).astype(np.float32))
    comm.sync(params, world=2)
    assert comm.stats["overlapped"] is True
    assert comm.stats["buckets_launched_early"] == 0

    # prepared with a missing grad -> loud error naming the contract
    comm.prepare(params, world=2)
    for p in params:
        p.grad = None
    with pytest.raises(RuntimeError, match="no gradient at flush"):
        comm.flush()


def test_abandon_disarms_without_syncing(monkeypatch):
    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    from paddle_tpu.framework import autograd as ag

    net = _mlp()
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(_cfg("fp32", True))
    comm.prepare(params, world=2)
    assert ag._grad_ready_hook is not None
    comm.abandon()
    assert ag._grad_ready_hook is None
    # grads accumulate RAW afterwards (no hook, no launches)
    F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y)).backward()
    assert comm._step is None
    # re-arming twice doesn't leak the hook (prepare self-abandons)
    comm.prepare(params, world=2)
    comm.prepare(params, world=2)
    assert ag._grad_ready_hook == comm._on_grad_ready
    comm.abandon()
    assert ag._grad_ready_hook is None


def test_lane_error_surfaces_at_flush(monkeypatch):
    boom = RuntimeError("wire fell out")

    def bad_all_reduce(t, op=None, group=None, **kw):
        raise boom

    monkeypatch.setattr(coll, "all_reduce", bad_all_reduce)
    net = _mlp()
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(_cfg("fp32", True))
    comm.prepare(params, world=2)
    F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y)).backward()
    with pytest.raises(RuntimeError, match="wire fell out"):
        comm.sync(params, world=2)
    # the failed step disarmed cleanly; the next serial sync still works
    from paddle_tpu.framework import autograd as ag

    assert ag._grad_ready_hook is None


# ----------------------------------------------------- in-trace / futures
def test_sync_async_matches_serial_in_trace():
    """Per-bucket futures inside a shard_map trace: each bucket's psum is
    its own op, and the resolved values match the serial sync's exactly."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2]))
    shapes = [(3, 5), (7,), (2, 2, 4)]
    gs = [rng.standard_normal((2,) + s).astype(np.float32) for s in shapes]

    def make_params(vals):
        params = []
        for v in vals:
            p = Tensor(jnp.zeros(v.shape), _internal=True)
            p.stop_gradient = False
            p.grad = Tensor(v, _internal=True)
            params.append(p)
        return params

    def body(*rank_grads):
        vals = [g.reshape(s) for g, s in zip(rank_grads, shapes)]
        serial = make_params(vals)
        grad_comm.GradCommunicator(
            grad_comm.GradCommConfig("bf16")).sync(serial, world=2)
        asyncp = make_params(vals)
        comm = OverlappedGradCommunicator(grad_comm.GradCommConfig("bf16"))
        futs = comm.sync_async(asyncp, world=2)
        for f in futs:
            assert isinstance(f, BucketFuture) and f.done()
            f.scatter()   # write back per bucket, future by future
        return (tuple(p.grad._value for p in serial)
                + tuple(p.grad._value for p in asyncp))

    outs = mesh_mod.compat_shard_map(
        body, m, P("data"), tuple([P()] * (2 * len(shapes))))(*gs)
    ser, got = outs[:len(shapes)], outs[len(shapes):]
    for r, g in zip(ser, got):
        assert np.array_equal(np.asarray(r), np.asarray(g))


# -------------------------------------------------- fused flat-buffer step
@pytest.mark.parametrize("opt_cls", [optim.SGD, optim.Adam, optim.AdamW])
def test_fused_flat_update_exact_vs_per_param(opt_cls):
    def build():
        net = _mlp()
        return net, opt_cls(learning_rate=0.05,
                            parameters=net.parameters())

    net1, opt1 = build()
    for _ in range(4):
        F.mse_loss(net1(paddle.to_tensor(X)),
                   paddle.to_tensor(Y)).backward()
        opt1.step()
        opt1.clear_grad()

    net2, opt2 = build()
    params2 = [p for p in net2.parameters() if not p.stop_gradient]
    fused = FusedFlatUpdater(opt2, params2)
    for _ in range(4):
        F.mse_loss(net2(paddle.to_tensor(X)),
                   paddle.to_tensor(Y)).backward()
        fused.step()   # one kernel per bucket, no per-param unflatten
        opt2.clear_grad()

    for a, b in zip(net1.parameters(), net2.parameters()):
        assert np.array_equal(np.asarray(a._value), np.asarray(b._value)), \
            opt_cls.__name__


def test_fused_update_consumes_futures_without_grad_scatter(monkeypatch):
    """The overlap x fused composition: sync_async futures feed the flat
    update directly — the reduced buffer never unflattens into .grad."""
    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    net1, net2 = _mlp(), _mlp()
    opt1 = optim.Adam(learning_rate=0.05, parameters=net1.parameters())
    opt2 = optim.Adam(learning_rate=0.05, parameters=net2.parameters())
    p1 = [p for p in net1.parameters() if not p.stop_gradient]
    p2 = [p for p in net2.parameters() if not p.stop_gradient]
    comm1 = grad_comm.GradCommunicator(_cfg("fp32"))
    comm2 = OverlappedGradCommunicator(_cfg("fp32"))
    fused = FusedFlatUpdater(opt2, p2, communicator=comm2)
    for _ in range(3):
        F.mse_loss(net1(paddle.to_tensor(X)),
                   paddle.to_tensor(Y)).backward()
        F.mse_loss(net2(paddle.to_tensor(X)),
                   paddle.to_tensor(Y)).backward()
        comm1.sync(p1, world=2)
        opt1.step()
        opt1.clear_grad()
        futs = comm2.sync_async(p2, world=2)
        fused.step(futures=futs)
        opt2.clear_grad()
    for a, b in zip(p1, p2):
        assert np.array_equal(np.asarray(a._value), np.asarray(b._value))


def test_fused_rejects_nonelementwise_and_clip():
    net = _mlp()
    params = list(net.parameters())
    with pytest.raises(ValueError, match="cannot be fused"):
        FusedFlatUpdater(optim.Lamb(learning_rate=0.01, parameters=params),
                         params)
    with pytest.raises(ValueError, match="grad_clip"):
        FusedFlatUpdater(
            optim.SGD(learning_rate=0.01, parameters=params,
                      grad_clip=nn.ClipGradByGlobalNorm(1.0)), params)


def test_fused_sharded_update_matches_full(monkeypatch):
    """ZeRO stage-2 form: each rank updates only its owned shard of every
    flat bucket, shards all_gather back — and the result equals the full
    fused update exactly (the update rule is elementwise)."""
    # reference: full fused update
    net_ref = _mlp()
    opt_ref = optim.Adam(learning_rate=0.05,
                         parameters=net_ref.parameters())
    p_ref = [p for p in net_ref.parameters() if not p.stop_gradient]
    fused_ref = FusedFlatUpdater(opt_ref, p_ref)
    grads = [rng.standard_normal(p.shape).astype(np.float32) * 1e-2
             for p in p_ref]
    for p, g in zip(p_ref, grads):
        p.grad = Tensor(g)
    fused_ref.step()
    expected = {b.index: np.concatenate(
        [np.asarray(p_ref[pi]._value).reshape(-1)
         for pi in b.param_indices]) for b in fused_ref.buckets}

    world = 2
    for rank in range(world):
        net = _mlp()
        opt = optim.Adam(learning_rate=0.05, parameters=net.parameters())
        params = [p for p in net.parameters() if not p.stop_gradient]
        fused = FusedFlatUpdater(opt, params)
        for p, g in zip(params, grads):
            p.grad = Tensor(g)
        captured = {}

        def fake_all_gather(tl, t, group=None, **kw):
            # emulate the 2-rank gather: this rank's updated shard plus
            # the agreed full result for the peer's half
            i = len(captured)
            b = fused.buckets[i]
            captured[b.index] = np.asarray(t._value)
            pad = (-b.size) % world
            full = np.concatenate(
                [expected[b.index],
                 np.zeros(pad, expected[b.index].dtype)])
            return Tensor(full, _internal=True)

        monkeypatch.setattr(coll, "all_gather", fake_all_gather)
        fused.step_sharded(rank=rank, world=world)
        # the shard this rank computed IS the corresponding slice of the
        # full fused update, bit for bit
        for b in fused.buckets:
            pad = (-b.size) % world
            chunk = (b.size + pad) // world
            full = np.concatenate(
                [expected[b.index], np.zeros(pad, np.float32)])
            want = full[rank * chunk:(rank + 1) * chunk]
            assert np.array_equal(captured[b.index], want), \
                (rank, b.index)


def test_fused_slot_roundtrip_through_optimizer():
    net = _mlp()
    opt = optim.Adam(learning_rate=0.05, parameters=net.parameters())
    params = [p for p in net.parameters() if not p.stop_gradient]
    fused = FusedFlatUpdater(opt, params)
    F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y)).backward()
    fused.step()
    fused.sync_slots_to_optimizer()
    sd = opt.state_dict()
    assert any(k.endswith(".moment1") for k in sd)
    # re-import yields identical flat slots
    fused2 = FusedFlatUpdater(opt, params)
    fused2.load_slots_from_optimizer()
    for bi, slots in fused._slots.items():
        for k, v in slots.items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(fused2._slots[bi][k]))


# ------------------------------------------------------------------ wiring
def test_strategy_overlap_knob_selects_overlapped_communicator(monkeypatch):
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    net = nn.Linear(4, 2)
    st = fleet.DistributedStrategy()
    st.grad_comm = True
    st.grad_comm_configs = {"codec": "fp32", "overlap": True}
    dp = dist.DataParallel(net, strategy=st)
    comm = dp._grad_communicator()
    assert type(comm) is OverlappedGradCommunicator
    assert comm.config.overlap is True
    # forward arms the hook; backward launches; apply = flush
    from paddle_tpu.framework import autograd as ag

    loss = dp(paddle.to_tensor(rng.rand(8, 4).astype(np.float32))).sum()
    assert ag._grad_ready_hook is not None
    loss.backward()
    dp.apply_collective_grads()
    assert ag._grad_ready_hook is None
    assert comm.stats["overlapped"] is True
    assert comm.stats["buckets_launched_early"] == comm.stats["n_buckets"]
    # default stays serial
    st2 = fleet.DistributedStrategy()
    st2.grad_comm = True
    dp2 = dist.DataParallel(net, strategy=st2)
    assert type(dp2._grad_communicator()) is grad_comm.GradCommunicator


def test_sharding_stage2_overlap_uses_reduce_scatter(monkeypatch):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 8}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 2, "sharding_degree": 8}
    strategy.grad_comm = True
    strategy.grad_comm_configs = {"codec": "bf16", "overlap": True}
    fleet.init(is_collective=True, strategy=strategy)
    net = _mlp(seed=5)
    wrapped = fleet.distributed_model(net)
    assert type(wrapped._grad_comm) is OverlappedGradCommunicator

    rs_calls, ag_calls = [], []
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    monkeypatch.setattr(
        coll, "reduce_scatter",
        lambda t, tensor_list=None, op=None, group=None, **kw:
        rs_calls.append(str(t._value.dtype)) or t)
    monkeypatch.setattr(
        coll, "all_gather",
        lambda tl, t, group=None, **kw: ag_calls.append(1) or t)
    # forward arms, backward launches per completed bucket, apply flushes
    loss = wrapped(paddle.to_tensor(X)).sum()
    loss.backward()
    wrapped.apply_collective_grads()
    st = wrapped._grad_comm.stats
    assert st["overlapped"] is True
    assert len(rs_calls) == len(ag_calls) == st["n_buckets"]
    assert all(d == "bfloat16" for d in rs_calls)


def test_group_sharded_overlap_and_fused_knobs():
    from paddle_tpu.distributed.sharding import group_sharded_parallel

    mesh_mod.set_mesh(mesh_mod.build_mesh({"sharding": 8}))
    net = nn.Linear(16, 8)
    opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
    model, opt, _ = group_sharded_parallel(net, opt, "os_g",
                                           overlap_comm=True,
                                           fuse_update=True)
    assert type(model._grad_comm) is OverlappedGradCommunicator
    assert isinstance(model._fused_update, FusedFlatUpdater)


def test_hapi_fit_syncs_through_wrapper(monkeypatch):
    """Model.fit's eager path calls apply_collective_grads between
    backward and the optimizer (serial here: world emulated at 2), and the
    non-update micro-batches of gradient accumulation disarm overlap."""
    from paddle_tpu.hapi import Model

    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    synced = []
    real_sync = grad_comm.GradCommunicator.sync
    monkeypatch.setattr(
        grad_comm.GradCommunicator, "sync",
        lambda self, params, world=None, **kw:
        synced.append(world) or real_sync(self, params, world=1))
    net = dist.DataParallel(_mlp())
    model = Model(net)
    model.prepare(optimizer=optim.SGD(learning_rate=0.1,
                                      parameters=net.parameters()),
                  loss=F.mse_loss, jit_compile=False)
    data = [(X[i], Y[i]) for i in range(16)]
    model.fit(data, batch_size=4, shuffle=False, epochs=1, verbose=0)
    assert len(synced) == 4             # one sync per update step
    assert all(w == 2 for w in synced)
    # accumulation: 2 micro-batches per update -> half the syncs
    synced.clear()
    model.fit(data, batch_size=4, shuffle=False, epochs=1, verbose=0,
              accumulate_grad_batches=2)
    assert len(synced) == 2


# --------------------------------------------------- cost model + tooling
def test_comm_cost_overlap_terms():
    from paddle_tpu.cost_model import comm_cost

    gb = 350e6
    serial = comm_cost(gb, world=8, codec="bf16")
    assert serial["exposed_time_s"] == serial["time_s"]
    assert serial["overlap_efficiency"] == 0.0
    # a long backward hides everything but the last bucket
    ov = comm_cost(gb, world=8, codec="bf16", overlap=True, backward_s=1.0)
    assert ov["time_s"] == serial["time_s"]          # total work unchanged
    assert ov["exposed_time_s"] == pytest.approx(
        ov["time_s"] / ov["collectives"])            # last bucket exposed
    assert ov["exposed_time_s"] < serial["exposed_time_s"]
    assert 0.0 < ov["overlap_efficiency"] < 1.0
    # no backward window -> nothing hidden
    none = comm_cost(gb, world=8, codec="bf16", overlap=True, backward_s=0)
    assert none["exposed_time_s"] == none["time_s"]
    # a short window hides exactly that much
    short = comm_cost(gb, world=8, codec="bf16", overlap=True,
                      backward_s=serial["time_s"] / 10)
    assert short["hidden_time_s"] == pytest.approx(serial["time_s"] / 10)


def test_overlap_report_and_bench_artifact():
    """tools/overlap_bench.py measures a real hook/lane cycle, and the
    committed artifact records the exposed-comm win per codec (style:
    test_grad_comm_bench_tool_and_artifact)."""
    net = _mlp()
    rep = overlap_report([p for p in net.parameters()],
                         _cfg("bf16"), world=2, compute_s=0.05)
    assert rep["n_buckets"] >= 3
    assert rep["buckets_launched_early"] == rep["n_buckets"]
    assert 0.0 <= rep["overlap_efficiency"] <= 1.0
    # with a 50ms backward window and ~ms of comm, most comm hides
    assert rep["overlap_efficiency"] > 0.5, rep

    d = json.load(open(os.path.join(REPO, "artifacts",
                                    "overlap_bench.json")))
    assert d["model"] == "gpt-test"
    for codec, row in d["codecs"].items():
        assert row["overlapped_exposed_comm_ms"] \
            < row["serial_exposed_comm_ms"], codec
        assert row["overlap_efficiency"] > 0.5
        assert row["buckets_launched_early"] == row["n_buckets"]


def test_overlap_efficiency_gauge_exported(monkeypatch):
    from paddle_tpu.observability import get_registry

    monkeypatch.setattr(coll, "all_reduce", _two_rank_all_reduce())
    net = _mlp()
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(_cfg("fp32", True))
    comm.prepare(params, world=2)
    F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y)).backward()
    comm.sync(params, world=2)
    snap = get_registry().snapshot()
    assert snap["grad_comm_overlap_efficiency"] == pytest.approx(
        comm.stats["overlap_efficiency"], abs=1e-6)
    assert snap["grad_comm_overlapped_syncs_total"] >= 1
    assert snap["grad_comm_buckets_launched_early_total"] >= \
        comm.stats["buckets_launched_early"]

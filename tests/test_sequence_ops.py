"""Sequence op family (nn/functional/sequence.py).

Reference: fluid/operators/sequence_ops/ over LoD; here the carrier is
(padded [B, T, ...], lengths [B]).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _x():
    # B=3, T=4, d=2; lengths 2, 4, 1
    rs = np.random.RandomState(0)
    x = rs.randn(3, 4, 2).astype("float32")
    lens = np.array([2, 4, 1], np.int64)
    return paddle.to_tensor(x), paddle.to_tensor(lens), x, lens


def test_sequence_mask():
    m = F.sequence_mask(paddle.to_tensor(np.array([1, 3])), maxlen=4)
    np.testing.assert_array_equal(m.numpy(),
                                  [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_pad_unpad_roundtrip():
    flat = paddle.to_tensor(np.arange(14, dtype=np.float32).reshape(7, 2))
    lens = paddle.to_tensor(np.array([3, 4], np.int64))
    padded, out_lens = F.sequence_pad(flat, 0.0, lens)
    assert tuple(padded.shape) == (2, 4, 2)
    np.testing.assert_allclose(padded.numpy()[0, 3], 0.0)  # padding
    back = F.sequence_unpad(padded, out_lens)
    np.testing.assert_allclose(back.numpy(), flat.numpy())


def test_sequence_reverse_respects_lengths():
    x, lens, xn, ln = _x()
    r = F.sequence_reverse(x, lens).numpy()
    np.testing.assert_allclose(r[0, :2], xn[0, [1, 0]])
    np.testing.assert_allclose(r[0, 2:], xn[0, 2:])  # padding untouched
    np.testing.assert_allclose(r[1], xn[1, ::-1])
    np.testing.assert_allclose(r[2, 0], xn[2, 0])


@pytest.mark.parametrize("ptype,ref", [
    ("sum", lambda xn, l: xn[:l].sum(0)),
    ("average", lambda xn, l: xn[:l].mean(0)),
    ("max", lambda xn, l: xn[:l].max(0)),
    ("last", lambda xn, l: xn[l - 1]),
    ("first", lambda xn, l: xn[0]),
])
def test_sequence_pool(ptype, ref):
    x, lens, xn, ln = _x()
    out = F.sequence_pool(x, ptype, lens).numpy()
    for b in range(3):
        np.testing.assert_allclose(out[b], ref(xn[b], ln[b]), rtol=1e-6)


def test_sequence_softmax_masks_padding():
    x, lens, xn, ln = _x()
    s = F.sequence_softmax(x, lens).numpy()
    for b in range(3):
        np.testing.assert_allclose(s[b, :ln[b]].sum(0), 1.0, rtol=1e-5)
        np.testing.assert_allclose(s[b, ln[b]:], 0.0)


def test_sequence_expand_and_concat():
    x = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
    e = F.sequence_expand(x, np.array([2, 3]))
    np.testing.assert_allclose(e.numpy()[:, 0], [1, 1, 2, 2, 2])
    a = paddle.to_tensor(np.ones((2, 2, 1), np.float32))
    b = paddle.to_tensor(np.zeros((2, 3, 1), np.float32))
    c = F.sequence_concat([a, b])
    assert tuple(c.shape) == (2, 5, 1)


def test_sequence_slice():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(2, 6))
    out = F.sequence_slice(x, np.array([1, 2]), np.array([2, 3])).numpy()
    np.testing.assert_allclose(out[0], [1, 2, 0])
    np.testing.assert_allclose(out[1], [8, 9, 10])


def test_static_nn_exposure():
    import paddle_tpu.static as static

    assert hasattr(static.nn, "sequence_pool")
    assert hasattr(static.nn, "sequence_pad")


def test_grad_through_sequence_pool():
    x, lens, xn, ln = _x()
    x.stop_gradient = False
    F.sequence_pool(x, "sum", lens).sum().backward()
    g = x.grad.numpy()
    for b in range(3):
        np.testing.assert_allclose(g[b, :ln[b]], 1.0)
        np.testing.assert_allclose(g[b, ln[b]:], 0.0)

"""SPMD pipeline schedule (distributed/pipeline.py).

Verifies the VERDICT r1 'real pipeline' bar: pp_degree=2 matches pp_degree=1
losses, micro-batches genuinely rotate (collective-permute in the compiled
HLO), and gradients flow through the transposed pipeline.

Reference capability matched: fleet/meta_parallel/pipeline_parallel.py 1F1B
train_batch + pp_utils/p2p_communication.py stage hand-off.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import pipeline_spmd
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)


@pytest.fixture
def pipe_mesh():
    prev = mesh_mod.get_mesh()
    mesh = mesh_mod.build_mesh({"pipe": 2, "data": 2},
                               devices=jax.devices()[:4])
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod.set_mesh(prev)


def test_pipeline_spmd_matches_sequential(pipe_mesh):
    """A 2-stage stack of elementwise-linear stages == sequential apply."""
    rs = np.random.RandomState(0)
    # stacked per-stage params: leading dim 2 (stages), sharded over pipe
    w = jnp.asarray(rs.randn(2, 8).astype(np.float32))
    b = jnp.asarray(rs.randn(2, 8).astype(np.float32))
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))

    def stage(params_local, mb):
        wl, bl = params_local  # [1, 8] each (one stage's slice)
        return jnp.tanh(mb * wl[0] + bl[0])

    out = jax.jit(lambda w, b, x: pipeline_spmd(
        stage, (w, b), x, mesh=pipe_mesh,
        param_specs=[P("pipe"), P("pipe")], microbatches=4))(w, b, x)

    expect = x
    for i in range(2):
        expect = jnp.tanh(expect * w[i] + b[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-6, atol=1e-6)


def test_pipeline_grads_match_sequential(pipe_mesh):
    rs = np.random.RandomState(1)
    w = jnp.asarray(rs.randn(2, 8).astype(np.float32))
    b = jnp.asarray(rs.randn(2, 8).astype(np.float32))
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))

    def stage(params_local, mb):
        wl, bl = params_local
        return jnp.tanh(mb * wl[0] + bl[0])

    def loss_pipe(w, b, x):
        return jnp.sum(pipeline_spmd(
            stage, (w, b), x, mesh=pipe_mesh,
            param_specs=[P("pipe"), P("pipe")], microbatches=4) ** 2)

    def loss_seq(w, b, x):
        y = x
        for i in range(2):
            y = jnp.tanh(y * w[i] + b[i])
        return jnp.sum(y ** 2)

    g1 = jax.jit(jax.grad(loss_pipe, (0, 1)))(w, b, x)
    g2 = jax.grad(loss_seq, (0, 1))(w, b, x)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=1e-5, atol=1e-5)


def test_hlo_contains_collective_permute(pipe_mesh):
    """Micro-batches must rotate between stages — the compiled program has to
    carry a collective-permute (the ppermute hand-off)."""
    rs = np.random.RandomState(2)
    w = jnp.asarray(rs.randn(2, 8).astype(np.float32))
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))

    def stage(params_local, mb):
        return mb * params_local[0][0]

    fn = jax.jit(lambda w, x: pipeline_spmd(
        stage, (w,), x, mesh=pipe_mesh, param_specs=[P("pipe")]))
    hlo = fn.lower(w, x).compile().as_text()
    assert "collective-permute" in hlo


def _gpt_losses(topology, steps=3, mode="scan", microbatches=0):
    prev = mesh_mod.get_mesh()
    if topology:
        total = int(np.prod(list(topology.values())))
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            topology, devices=jax.devices()[:total]))
    else:
        mesh_mod.set_mesh(None)
    try:
        cfg = gpt_presets("gpt-test", mode=mode,
                          pp_microbatches=microbatches)
        model = GPTForCausalLM(cfg, seed=0)
        crit = GPTPretrainingCriterion()
        optim = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (8, 16)), dtype="int64")
        labels = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (8, 16)), dtype="int64")
        return [float(step(inputs=(ids,), labels=(labels,)))
                for _ in range(steps)]
    finally:
        mesh_mod.set_mesh(prev)


def test_gpt_pp2_matches_pp1():
    """The VERDICT bar: pp_degree=2 losses == pp_degree=1 losses."""
    base = _gpt_losses(None, mode="loop")
    pp2 = _gpt_losses({"pipe": 2}, mode="scan")
    np.testing.assert_allclose(pp2, base, rtol=2e-4)
    # losses must actually descend
    assert pp2[-1] < pp2[0]


def test_gpt_pp2_more_microbatches():
    base = _gpt_losses(None, mode="loop")
    pp2m4 = _gpt_losses({"pipe": 2}, mode="scan", microbatches=4)
    np.testing.assert_allclose(pp2m4, base, rtol=2e-4)


def test_gpt_pp_times_tp():
    """pipe=2 x model=2 — the manual-Megatron composition inside the
    pipeline manual region."""
    base = _gpt_losses(None, mode="loop")
    hybrid = _gpt_losses({"pipe": 2, "model": 2}, mode="scan")
    np.testing.assert_allclose(hybrid, base, rtol=2e-4)

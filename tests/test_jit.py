"""jit / TrainStep / amp tests — eager-vs-compiled parity is the core contract
(reference analog: unittests/dygraph_to_static eager-vs-to_static comparisons)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
from paddle_tpu.jit import StaticFunction, TrainStep

rng = np.random.RandomState(5)


def make_data(n=64):
    X = rng.randn(n, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int64)
    return X, Y


class TestStaticFunction:
    def test_forward_parity(self):
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        X, _ = make_data()
        eager = net(paddle.to_tensor(X)).numpy()
        sf = StaticFunction(net)
        net.eval()
        jitted = sf(paddle.to_tensor(X)).numpy()
        np.testing.assert_allclose(eager, jitted, rtol=1e-5, atol=1e-6)

    def test_shape_cache_recompile(self):
        net = nn.Linear(4, 2)
        sf = StaticFunction(net)
        net.eval()
        a = sf(paddle.to_tensor(rng.rand(3, 4).astype(np.float32)))
        b = sf(paddle.to_tensor(rng.rand(7, 4).astype(np.float32)))
        assert a.shape == [3, 2] and b.shape == [7, 2]
        base_keys = [k for k in sf._cache if k[0] != "gradjit"]
        assert len(base_keys) == 2

    def test_grad_through_static(self):
        net = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 1))
        X, _ = make_data(16)
        sf = StaticFunction(net)
        out = sf(paddle.to_tensor(X))
        out.sum().backward()
        # compare against eager grads
        eager_net = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 1))
        eager_net.set_state_dict(net.state_dict())
        out2 = eager_net(paddle.to_tensor(X))
        out2.sum().backward()
        for p1, p2 in zip(net.parameters(), eager_net.parameters()):
            np.testing.assert_allclose(p1.grad.numpy(), p2.grad.numpy(), rtol=1e-4,
                                       atol=1e-6)

    def test_batchnorm_buffers_thread_through_jit(self):
        net = nn.Sequential(nn.Linear(8, 4), nn.BatchNorm1D(4))
        sf = StaticFunction(net)
        X, _ = make_data(32)
        before = net[1]._mean.numpy().copy()
        net.train()
        sf(paddle.to_tensor(X))
        assert not np.allclose(net[1]._mean.numpy(), before)

    def test_dropout_rng_varies_under_jit(self):
        net = nn.Dropout(0.5)
        sf = StaticFunction(net)
        x = paddle.ones([1000])
        a = sf(x).numpy()
        b = sf(x).numpy()
        assert not np.array_equal(a, b)  # fresh key per call, same compiled fn
        assert len(sf._cache) == 1


class TestTrainStep:
    def test_matches_eager_training(self):
        paddle.seed(0)
        X, Y = make_data(128)

        def build():
            paddle.seed(42)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2))
            opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
            return net, opt

        net1, opt1 = build()
        step = TrainStep(net1, lambda o, y: F.cross_entropy(o, y), opt1)
        jit_losses = [float(step(paddle.to_tensor(X), paddle.to_tensor(Y)).numpy())
                      for _ in range(10)]

        net2, opt2 = build()
        eager_losses = []
        for _ in range(10):
            loss = F.cross_entropy(net2(paddle.to_tensor(X)), paddle.to_tensor(Y))
            eager_losses.append(float(loss.numpy()))
            loss.backward()
            opt2.step()
            opt2.clear_grad()
        np.testing.assert_allclose(jit_losses, eager_losses, rtol=1e-4, atol=1e-5)
        for p1, p2 in zip(net1.parameters(), net2.parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-3, atol=1e-5)

    def test_frozen_params_not_updated(self):
        net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 2))
        net[0].weight.stop_gradient = True
        frozen0 = net[0].weight.numpy().copy()
        opt = optim.SGD(0.1, parameters=net.parameters())
        step = TrainStep(net, lambda o, y: F.cross_entropy(o, y), opt)
        X, Y = make_data(32)
        step(paddle.to_tensor(X), paddle.to_tensor(Y))
        np.testing.assert_array_equal(net[0].weight.numpy(), frozen0)
        assert not np.allclose(net[1].weight.numpy(), frozen0[:, :2] if False else net[1].weight.numpy() * 0)

    def test_grad_clip_in_step(self):
        net = nn.Linear(8, 2)
        opt = optim.SGD(1.0, parameters=net.parameters(),
                        grad_clip=nn.ClipGradByGlobalNorm(1e-6))
        step = TrainStep(net, lambda o, y: F.mse_loss(o, y), opt)
        w0 = net.weight.numpy().copy()
        X = rng.rand(16, 8).astype(np.float32)
        step(paddle.to_tensor(X), paddle.to_tensor(rng.rand(16, 2).astype(np.float32)))
        assert np.abs(net.weight.numpy() - w0).max() < 1e-4

    def test_lr_schedule_traced_not_baked(self):
        sched = optim.lr.StepDecay(0.5, step_size=1, gamma=0.1)
        net = nn.Linear(2, 1, bias_attr=False)
        opt = optim.SGD(sched, parameters=net.parameters())
        step = TrainStep(net, lambda o, y: F.mse_loss(o, y), opt)
        X = np.ones((4, 2), np.float32)
        Y = np.zeros((4, 1), np.float32)
        w0 = net.weight.numpy().copy()
        step(paddle.to_tensor(X), paddle.to_tensor(Y))
        d1 = np.abs(net.weight.numpy() - w0).max()
        sched.step()  # lr 0.5 -> 0.05; same compiled fn must honor it
        w1 = net.weight.numpy().copy()
        step(paddle.to_tensor(X), paddle.to_tensor(Y))
        d2 = np.abs(net.weight.numpy() - w1).max()
        assert len(step._cache) == 1
        assert d2 < d1 * 0.5


class TestAmp:
    def test_o1_white_black(self):
        with paddle.amp.auto_cast(level="O1"):
            y = paddle.matmul(paddle.rand([4, 8]), paddle.rand([8, 4]))
            assert str(y.dtype) == "bfloat16"
            s = paddle.sum(y)
            assert s.dtype == np.float32
        y2 = paddle.matmul(paddle.rand([4, 8]), paddle.rand([8, 4]))
        assert y2.dtype == np.float32

    def test_o2_casts_most(self):
        with paddle.amp.auto_cast(level="O2"):
            a = paddle.rand([4]) + paddle.rand([4])
            assert str(a.dtype) == "bfloat16"

    def test_custom_lists(self):
        with paddle.amp.auto_cast(custom_black_list={"matmul"}):
            y = paddle.matmul(paddle.rand([2, 2]), paddle.rand([2, 2]))
            assert y.dtype == np.float32

    def test_grad_scaler_happy_path(self):
        net = nn.Linear(4, 2)
        opt = optim.SGD(0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        w0 = net.weight.numpy().copy()
        loss = net(paddle.to_tensor(rng.rand(8, 4).astype(np.float32))).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        assert not np.allclose(net.weight.numpy(), w0)
        # gradient was unscaled before apply: step size bounded
        assert np.abs(net.weight.numpy() - w0).max() < 10.0

    def test_grad_scaler_skips_on_inf(self):
        net = nn.Linear(2, 2)
        opt = optim.SGD(0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        w0 = net.weight.numpy().copy()
        net.weight.grad = paddle.to_tensor(np.full((2, 2), np.inf, np.float32))
        scaler.step(opt)
        np.testing.assert_array_equal(net.weight.numpy(), w0)
        assert scaler._scale == 4.0

    def test_decorate_o2(self):
        import jax.numpy as jnp

        net = nn.Linear(4, 4)
        net = paddle.amp.decorate(net, level="O2")
        assert net.weight.dtype == jnp.bfloat16


@pytest.mark.requires_jax_export
def test_jit_load_returns_translated_layer(tmp_path):
    """jit.save with input_spec → jit.load returns a CALLABLE TranslatedLayer
    (reference: dygraph/io.py TranslatedLayer)."""
    import paddle_tpu.static as static

    net = nn.Sequential(nn.Linear(4, 3))
    net.eval()
    x = np.random.RandomState(0).randn(2, 4).astype("float32")
    expect = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "tl")
    paddle.jit.save(net, prefix,
                    input_spec=[static.InputSpec([None, 4], "float32", "x")])
    loaded = paddle.jit.load(prefix)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5, atol=1e-6)
    # shape-polymorphic: different batch size works
    x8 = np.random.RandomState(1).randn(8, 4).astype("float32")
    assert loaded(paddle.to_tensor(x8)).shape[0] == 8
    with pytest.raises(RuntimeError):
        loaded.train()


def test_to_static_training_matches_eager_and_caches_vjp():
    """VERDICT r1 weak #5: the @to_static grad path must not re-trace the
    vjp per call — fwd and vjp are jitted once per shape key — and the
    training trajectory must equal eager's from identical init."""
    import paddle_tpu.jit as jit
    import paddle_tpu.optimizer as opt

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(6, 16)
            self.b = nn.Linear(16, 2)

        def forward(self, x):
            return self.b(F.relu(self.a(x)))

    x = paddle.to_tensor(rng.rand(8, 6).astype(np.float32))
    y = paddle.to_tensor(rng.rand(8, 2).astype(np.float32))

    paddle.seed(3)
    ne = Net()
    oe = opt.SGD(learning_rate=0.1, parameters=ne.parameters())
    paddle.seed(3)
    ns = jit.to_static(Net())
    os_ = opt.SGD(learning_rate=0.1, parameters=ns.parameters())

    le, ls = [], []
    for _ in range(8):
        l = ((ne(x) - y) ** 2).mean()
        l.backward(); oe.step(); oe.clear_grad(); le.append(float(l))
        l2 = ((ns(x) - y) ** 2).mean()
        l2.backward(); os_.step(); os_.clear_grad(); ls.append(float(l2))
    np.testing.assert_allclose(le, ls, rtol=1e-4)
    assert ls[-1] < ls[0]
    # exactly one gradjit cache entry for the single shape key
    sf = ns.forward
    gkeys = [k for k in sf._cache if k[0] == "gradjit"]
    assert len(gkeys) == 1, gkeys


def test_to_static_grad_respects_amp_autocast():
    """Fast grad path must apply the same AMP input casting call_op does."""
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l = nn.Linear(4, 4)

        def forward(self, x):
            return self.l(x)

    net = jit.to_static(Net())
    x = paddle.to_tensor(rng.rand(2, 4).astype(np.float32),
                         stop_gradient=False)
    with paddle.amp.auto_cast(level="O2"):
        out = net(x)
    # O2: compute in bf16
    assert "bfloat16" in str(out.dtype) or "float16" in str(out.dtype), \
        out.dtype
    out.astype("float32").sum().backward()
    assert x.grad is not None


def test_to_static_input_gradients_flow_to_caller_tensor():
    """Input grads must land on the USER'S tensor, not a fresh wrapper
    (the old path silently dropped dL/dx)."""
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l = nn.Linear(3, 1)

        def forward(self, x):
            return self.l(x)

    net = jit.to_static(Net())
    x = paddle.to_tensor(rng.rand(4, 3).astype(np.float32),
                         stop_gradient=False)
    out = net(x)
    out.sum().backward()
    assert x.grad is not None
    w = list(net.parameters())[0]
    np.testing.assert_allclose(
        x.grad.numpy(), np.tile(w.numpy().sum(-1), (4, 1)), rtol=1e-5)


def test_to_static_scalar_args_grad_path():
    """Non-Tensor scalar args must work through the cached grad path."""
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l = nn.Linear(3, 3)

        def forward(self, x, scale=1.0):
            return self.l(x) * scale

    net = jit.to_static(Net())
    x = paddle.to_tensor(rng.rand(2, 3).astype(np.float32))
    a = net(x, 0.5)
    b = net(x, 2.0)
    np.testing.assert_allclose(a.numpy() * 4.0, b.numpy(), rtol=1e-5)
    a.sum().backward()  # grad path with the scalar arg


def test_to_static_amp_toggle_not_stale():
    """Turning auto_cast on/off between same-shape calls must not reuse a
    trace compiled under the other AMP mode."""
    import paddle_tpu as paddle
    import paddle_tpu.jit as jit

    net = jit.to_static(nn.Linear(4, 4))
    x = paddle.to_tensor(rng.rand(2, 4).astype(np.float32))
    out_fp32 = net(x)
    assert "float32" in str(out_fp32.dtype)
    with paddle.amp.auto_cast(level="O2"):
        out_amp = net(x)
    assert "bfloat16" in str(out_amp.dtype) or "float16" in str(out_amp.dtype)
    out_fp32_again = net(x)
    assert "float32" in str(out_fp32_again.dtype)


def test_trainstep_optimizer_state_roundtrip(tmp_path):
    """Compiled-path optimizer state must survive checkpoint/resume:
    TrainStep slots mirror into optimizer.state_dict(), and a restored
    optimizer's moments seed a fresh TrainStep — resumed trajectory equals
    uninterrupted training (the reference's save/load-of-optimizer flow)."""
    import paddle_tpu.optimizer as opt

    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(8, 4).astype("f4"))
    y = paddle.to_tensor(rs.randn(8, 4).astype("f4"))

    def build():
        paddle.seed(0)
        net = paddle.nn.Linear(4, 4)
        optim = opt.Adam(learning_rate=0.05, parameters=net.parameters())
        step = TrainStep(net, lambda o, t: ((o - t) ** 2).mean(), optim)
        return net, optim, step

    # uninterrupted: 6 steps
    net, optim, step = build()
    ref = [float(step((x,), (y,))) for _ in range(6)]

    # interrupted at 3: save model + optimizer, rebuild, restore, continue
    net, optim, step = build()
    first = [float(step((x,), (y,))) for _ in range(3)]
    sd_opt = optim.state_dict()
    assert any(k.endswith("moment1") for k in sd_opt)  # slots mirrored out
    paddle.save(net.state_dict(), str(tmp_path / "m.pdparams"))
    paddle.save(sd_opt, str(tmp_path / "o.pdopt"))

    net2, optim2, step2 = build()
    net2.set_state_dict(paddle.load(str(tmp_path / "m.pdparams")))
    optim2.set_state_dict(paddle.load(str(tmp_path / "o.pdopt")))
    resumed = [float(step2((x,), (y,))) for _ in range(3)]

    np.testing.assert_allclose(first + resumed, ref, rtol=1e-5, atol=1e-7)


def test_interleaved_compiled_and_eager_steps():
    """Compiled/eager interleaving must be crash-free AND state-coherent
    (last-writer arbitration): the mixed sequence's params, AND the
    checkpointed moments at every point, match an all-eager oracle —
    neither path may clobber or ignore the other's newer state."""
    import paddle_tpu.optimizer as opt

    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(8, 4).astype("f4"))
    y = paddle.to_tensor(rs.randn(8, 4).astype("f4"))

    def build():
        paddle.seed(0)
        net = paddle.nn.Linear(4, 4)
        optim = opt.Adam(learning_rate=0.05,
                         parameters=net.parameters())
        return net, optim

    def eager_step(net, optim):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()

    # oracle: 4 eager steps
    net_o, opt_o = build()
    for _ in range(4):
        eager_step(net_o, opt_o)
    sd_oracle = opt_o.state_dict()

    # mixed: compiled, eager, eager, compiled
    net, optim = build()
    step = TrainStep(net, lambda o, t: ((o - t) ** 2).mean(), optim)
    step((x,), (y,))
    sd1 = optim.state_dict()
    eager_step(net, optim)
    eager_step(net, optim)
    sd3 = optim.state_dict()  # must be the EAGER moments, not stale
    step((x,), (y,))          # must consume the eager moments
    sd4 = optim.state_dict()

    np.testing.assert_allclose(net.weight.numpy(), net_o.weight.numpy(),
                               rtol=1e-5, atol=1e-6)
    # the two builds auto-name their params differently (global
    # unique_name counter), so compare the first moment1 slot BY POSITION
    key_o = [k for k in sd_oracle if k.endswith("moment1")][0]
    key = [k for k in sd4 if k.endswith("moment1")][0]
    np.testing.assert_allclose(sd4[key], sd_oracle[key_o],
                               rtol=1e-5, atol=1e-6)
    # the mid-run snapshot reflects the eager writes (no clobber)
    assert not np.allclose(sd3[key], sd1[key])


def test_auto_checkpoint_resumes_compiled_optimizer_state(tmp_path):
    """TrainEpochRange with {model, optimizer} state around a COMPILED
    TrainStep: resume reproduces the uninterrupted trajectory exactly —
    the optimizer entry now carries the compiled-path moments."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
        TrainEpochRange,
    )

    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(8, 4).astype("f4"))
    y = paddle.to_tensor(rs.randn(8, 4).astype("f4"))

    def build():
        paddle.seed(0)
        net = paddle.nn.Linear(4, 4)
        optim = opt.Adam(learning_rate=0.05,
                         parameters=net.parameters())
        step = TrainStep(net, lambda o, t: ((o - t) ** 2).mean(), optim)
        return net, optim, step

    def run(save_dir, crash_after=None):
        net, optim, step = build()
        r = TrainEpochRange(5, name="opt_resume", save_dir=save_dir,
                            state={"model": net, "optimizer": optim})
        losses = []
        for epoch in r:
            losses.append(float(step((x,), (y,))))
            if crash_after is not None and epoch == crash_after:
                # crash mid-epoch: this epoch's post-yield checkpoint never
                # lands, so resume must REPLAY it from the epoch-0 state
                return losses, r
        return losses, r

    ref, _ = run(str(tmp_path / "a"))                 # uninterrupted
    first, _ = run(str(tmp_path / "b"), crash_after=1)
    resumed, r2 = run(str(tmp_path / "b"))
    assert r2.start_epoch == 1 and r2.restored_from
    # epoch 1 replays identically (restored params AND moments), then the
    # trajectory continues exactly as the uninterrupted run
    np.testing.assert_allclose(resumed, ref[1:], rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(first, ref[:2], rtol=1e-5, atol=1e-7)

"""global_scatter/global_gather — real AllToAll over the mesh.

Reference: operators/collective/global_scatter_op.cc / global_gather_op.cc
(ragged NCCL alltoall); the TPU path is a shard_map AllToAll with
device-uniform counts (ragged routing is MoELayer's fixed-capacity job).
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import global_gather, global_scatter
from paddle_tpu.distributed import mesh as mesh_mod

W, E, C, D = 4, 2, 3, 2


@pytest.fixture
def ep_mesh():
    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh_mod.build_mesh({"data": W},
                                          devices=jax.devices()[:W]))
    yield
    mesh_mod.set_mesh(prev)


def _tagged_x():
    rows = []
    for rank in range(W):
        for dest in range(W * E):
            for s in range(C):
                rows.append([rank * 1000 + dest * 10 + s] * D)
    return paddle.to_tensor(np.asarray(rows, np.float32))


def test_scatter_routes_rows_to_expert_owners(ep_mesh):
    x = _tagged_x()
    lc = np.full(W * E, C, np.int64)
    o = global_scatter(x, lc, lc).numpy()
    for r in range(W):
        blk = o[r * W * E * C:(r + 1) * W * E * C].reshape(E, W, C, D)
        for e in range(E):
            for s in range(W):
                expect = s * 1000 + (r * E + e) * 10 + np.arange(C)
                np.testing.assert_allclose(blk[e, s, :, 0], expect)


def test_gather_is_exact_inverse(ep_mesh):
    x = _tagged_x()
    lc = np.full(W * E, C, np.int64)
    back = global_gather(global_scatter(x, lc, lc), lc, lc)
    np.testing.assert_allclose(back.numpy(), x.numpy())


def test_ragged_counts_raise(ep_mesh):
    x = _tagged_x()
    lc = np.full(W * E, C, np.int64)
    lc[0] = C + 1
    with pytest.raises(NotImplementedError, match="uniform"):
        global_scatter(x, lc, lc)


def test_world_one_identity():
    prev = mesh_mod.get_mesh()
    mesh_mod.set_mesh(None)
    try:
        x = paddle.to_tensor(np.ones((6, 2), np.float32))
        lc = np.array([3, 3], np.int64)
        out = global_scatter(x, lc, lc)
        np.testing.assert_allclose(out.numpy(), x.numpy())
    finally:
        mesh_mod.set_mesh(prev)

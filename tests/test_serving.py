"""Serving runtime (ISSUE 14): paged quantized KV cache + continuous
batching + multi-replica eviction.

Contracts pinned here:
- KV block pool: fp32 codec bit-identical, int8/fp8 blockwise round-trip
  inside the codec error bound, append read-back == gather (the engine's
  incremental mirror IS the at-rest cache), free-list reuse, OOM typing,
  int8 at-rest bytes <= ~1/4 of fp32, flag-on (pallas seam) parity.
- Decode model: teacher-forced prefill+decode logits == the full forward
  (the training model's math, incrementally).
- Engine: paged generation == dense-cache reference generation exactly
  (fp32), no head-of-line blocking, blocks returned on completion,
  admission rejects at queue depth, int8 KV parity bound end to end.
- Replica set: hang/crash/corrupt replicas are evicted with their
  in-flight requests drained and re-dispatched — ZERO accepted requests
  lost (the acceptance-criteria chaos phase), zombie threads fenced.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddle_tpu.models import GPTForCausalLM, gpt_presets
from paddle_tpu.serving import (
    GPTDecodeModel, KVBlockPool, KVCacheOOM, ReplicaSet, RequestQueue,
    ServeRequest, ServingEngine, bucket_pow2,
)
from paddle_tpu.serving.scheduler import _m_queue_depth, _m_requests


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    """Serving is mesh-independent, but the parity tests run the
    TRAINING model's forward, whose sharding constraints reject a
    leftover ambient mesh (e.g. data=8 vs batch 2) from earlier suites
    — the shared conftest fixture clears and restores it."""


def _mini_cfg(**over):
    kw = dict(hidden_size=32, num_heads=2, num_layers=2, vocab_size=64,
              max_position_embeddings=64)
    kw.update(over)
    return gpt_presets("gpt-test", **kw)


@pytest.fixture(scope="module")
def dm():
    return GPTDecodeModel(GPTForCausalLM(_mini_cfg(), seed=0))


def _pool(dm, codec="fp32", n_blocks=32, block_tokens=8):
    return KVBlockPool(n_blocks=n_blocks, block_tokens=block_tokens,
                       elems_per_token=dm.elems_per_token, codec=codec)


def _drive(engine, max_steps=200):
    """Step an engine until idle (queue drained, batch empty)."""
    for _ in range(max_steps):
        worked = engine.step()
        if not worked and not engine.running and not engine.queue.depth:
            return
    raise AssertionError("engine did not drain")


def _reqs(rs, n, prompt_len=5, max_new=4, vocab=64, **kw):
    return [ServeRequest(prompt_ids=rs.randint(0, vocab, (prompt_len,)),
                         max_new_tokens=max_new, **kw) for _ in range(n)]


# ---------------------------------------------------------------------------
# KV block pool + codecs
# ---------------------------------------------------------------------------

class TestKVBlockPool:
    def test_fp32_round_trip_bit_identical(self):
        pool = KVBlockPool(8, 4, 16, codec="fp32")
        rs = np.random.RandomState(0)
        kv = rs.randn(10, 16).astype(np.float32)
        t = pool.alloc_table(10)
        back = pool.append(t, kv)
        np.testing.assert_array_equal(back, kv)
        np.testing.assert_array_equal(pool.gather(t), kv)

    @pytest.mark.parametrize("codec", ["int8_block", "fp8_block"])
    def test_quantized_round_trip_error_bound(self, codec):
        pool = KVBlockPool(8, 4, 256, codec=codec)
        rs = np.random.RandomState(1)
        kv = (rs.randn(11, 256) * 3).astype(np.float32)
        t = pool.alloc_table(11)
        back = pool.append(t, kv)
        got = pool.gather(t)
        # append read-back IS the at-rest value
        np.testing.assert_array_equal(back, got)
        # per-scale-block error bound: int8 is a uniform grid (half a
        # step = absmax/127/2); fp8 e4m3 is a float format whose error is
        # RELATIVE to each value (3 mantissa bits -> half-ulp = |v|/16),
        # plus the shared-scale grid for subnormal-small values
        qmax = 127.0 if codec == "int8_block" else 448.0
        qb = pool.quant_block
        flat_in = kv.reshape(-1, qb)
        flat_out = got.reshape(-1, qb)
        step = np.abs(flat_in).max(axis=1, keepdims=True) / qmax
        if codec == "int8_block":
            tol = 0.5 * step + 1e-7
        else:
            tol = np.abs(flat_in) / 8.0 + step + 1e-7
        assert (np.abs(flat_in - flat_out) <= tol).all()

    def test_incremental_append_equals_gather(self):
        """Token-by-token appends (the decode path) must read back
        bit-identically to a fresh gather — quantize-once alignment."""
        pool = KVBlockPool(8, 4, 128, codec="int8_block")
        rs = np.random.RandomState(2)
        t = pool.alloc_table(9)
        rows = []
        for _ in range(9):
            row = rs.randn(1, 128).astype(np.float32)
            rows.append(pool.append(t, row))
        mirror = np.concatenate(rows)
        np.testing.assert_array_equal(mirror, pool.gather(t))

    def test_free_list_reuse_and_oom(self):
        pool = KVBlockPool(4, 4, 8, codec="fp32")
        t1 = pool.alloc_table(16)          # all 4 blocks
        assert pool.free_blocks == 0
        with pytest.raises(KVCacheOOM):
            pool.alloc_table(1)
        pool.free_table(t1)
        assert pool.free_blocks == 4
        t2 = pool.alloc_table(5)           # 2 blocks
        assert pool.free_blocks == 2 and len(t2.block_ids) == 2
        with pytest.raises(KVCacheOOM):
            pool.append(t2, np.zeros((9, 8), np.float32))  # > reservation

    def test_int8_bytes_le_quarter_of_fp32(self):
        pool = KVBlockPool(8, 16, 256, codec="int8_block")
        t = pool.alloc_table(40)
        pool.append(t, np.ones((40, 256), np.float32))
        ratio = pool.bytes_in_use() / pool.fp32_equiv_bytes()
        assert ratio <= 0.28, ratio   # 1/4 payload + 4/quant_block scales
        fp = KVBlockPool(8, 16, 256, codec="fp32")
        tf = fp.alloc_table(40)
        assert fp.block_bytes() * len(tf.block_ids) == fp.fp32_equiv_bytes()

    def test_quant_block_alignment_enforced(self):
        with pytest.raises(ValueError, match="must divide"):
            KVBlockPool(4, 4, 96, codec="int8_block", quant_block=64)

    def test_kernel_autotune_flag_path_identical(self):
        """The codec rides grad_comm._block_kernel_ops: with
        FLAGS_kernel_autotune on (CPU target -> jnp pair retained) the
        at-rest bits must be identical to the flag-off path."""
        from paddle_tpu.framework import flags

        rs = np.random.RandomState(3)
        kv = rs.randn(7, 128).astype(np.float32)
        pool_off = KVBlockPool(8, 4, 128, codec="int8_block")
        t_off = pool_off.alloc_table(7)
        pool_off.append(t_off, kv)
        flags.set_flags({"FLAGS_kernel_autotune": True})
        try:
            pool_on = KVBlockPool(8, 4, 128, codec="int8_block")
            t_on = pool_on.alloc_table(7)
            pool_on.append(t_on, kv)
            np.testing.assert_array_equal(pool_on._payload, pool_off._payload)
            np.testing.assert_array_equal(pool_on._scales, pool_off._scales)
            np.testing.assert_array_equal(pool_on.gather(t_on),
                                          pool_off.gather(t_off))
        finally:
            flags.set_flags({"FLAGS_kernel_autotune": False})

    def test_pallas_codec_kernels_match_jnp_pair(self):
        """The pallas codec kernels themselves (interpret mode on CPU)
        must produce the exact payload/decode the pool stores — the TPU
        flag-on path is bit-for-bit the tested one."""
        from paddle_tpu.distributed import grad_comm
        from paddle_tpu.ops.pallas import codec as pcodec

        rs = np.random.RandomState(4)
        flat = rs.randn(512).astype(np.float32)
        qb = 128
        absmax = grad_comm.block_absmax(flat, qb)
        scales = grad_comm.block_scales(absmax, "int8_block")
        q_ref = grad_comm.block_encode(flat, scales, qb, "int8_block")
        q_ker = pcodec.block_encode(flat, scales, qb, "int8_block")
        np.testing.assert_array_equal(np.asarray(q_ref), np.asarray(q_ker))
        d_ref = grad_comm.block_decode(q_ref, scales, 1, np.float32, 512)
        d_ker = pcodec.block_decode(q_ref, scales, 1, np.float32, 512)
        np.testing.assert_array_equal(np.asarray(d_ref), np.asarray(d_ker))


# ---------------------------------------------------------------------------
# decode-model adapter
# ---------------------------------------------------------------------------

class TestDecodeModel:
    def test_bucket_pow2(self):
        assert bucket_pow2(1) == 1
        assert bucket_pow2(3) == 4
        assert bucket_pow2(9, minimum=16) == 16
        assert bucket_pow2(900, minimum=16, maximum=64) == 64

    def test_prefill_matches_full_forward(self, dm):
        import paddle_tpu as paddle

        model = GPTForCausalLM(_mini_cfg(), seed=0)
        rs = np.random.RandomState(0)
        ids = rs.randint(0, 64, (2, 9)).astype(np.int64)
        ref = model(paddle.to_tensor(ids)).numpy()
        got = dm.forced_logits(ids)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_teacher_forced_decode_parity(self, dm):
        """Incremental prefill+decode logits == full-forward logits at
        every position (fp32 dense cache)."""
        rs = np.random.RandomState(1)
        seq = rs.randint(0, 64, (10,)).astype(np.int32)
        ref = dm.forced_logits(seq[None])[0]            # [s, V]
        last, kvs = dm.prefill([seq[:4]])
        np.testing.assert_allclose(last[0], ref[3], atol=1e-5)
        past = np.zeros((1, 16, dm.elems_per_token), np.float32)
        past[0, :4] = kvs[0]
        n = 4
        for t in range(4, 10):
            lg, kv = dm.decode(np.array([seq[t]]), np.array([n]), past,
                               np.array([n]))
            np.testing.assert_allclose(lg[0], ref[t], atol=1e-5)
            past[0, n] = kv[0]
            n += 1

    def test_prefill_batch_padding_inert(self, dm):
        """Ragged prompts prefilled together == prefilled alone (padding
        rows/positions must not leak into real rows)."""
        rs = np.random.RandomState(2)
        a, b_ = rs.randint(0, 64, (9,)), rs.randint(0, 64, (3,))
        last2, kv2 = dm.prefill([a, b_])
        la, kva = dm.prefill([a])
        lb, kvb = dm.prefill([b_])
        np.testing.assert_allclose(last2[0], la[0], atol=1e-5)
        np.testing.assert_allclose(last2[1], lb[0], atol=1e-5)
        np.testing.assert_allclose(kv2[0], kva[0], atol=1e-5)
        np.testing.assert_allclose(kv2[1], kvb[0], atol=1e-5)

    def test_prompt_bounds(self, dm):
        with pytest.raises(ValueError, match="empty"):
            dm.prefill([np.zeros((0,), np.int32)])
        with pytest.raises(ValueError, match="max_context"):
            dm.prefill([np.zeros((65,), np.int32)])

    def test_int8_kv_logits_parity_bound(self, dm):
        """Decode against an int8-at-rest cache stays within the codec
        error bound of the fp32-cache logits (the 'pinned output parity'
        of the acceptance criteria)."""
        rs = np.random.RandomState(3)
        seq = rs.randint(0, 64, (12,)).astype(np.int32)
        _, kvs = dm.prefill([seq])
        kv = kvs[0]
        pool = _pool(dm, codec="int8_block")
        t = pool.alloc_table(12)
        kv_q = pool.append(t, kv)
        S = 16
        past = np.zeros((1, S, dm.elems_per_token), np.float32)
        past_q = past.copy()
        past[0, :12], past_q[0, :12] = kv, kv_q
        lg, _ = dm.decode(np.array([5]), np.array([12]), past,
                          np.array([12]))
        lg_q, _ = dm.decode(np.array([5]), np.array([12]), past_q,
                            np.array([12]))
        # logits drift bounded; loose bound, tight enough to catch a
        # broken codec (which lands O(1) off) while allowing ~1% KV error
        assert np.abs(lg - lg_q).max() < 0.15


# ---------------------------------------------------------------------------
# continuous-batching engine
# ---------------------------------------------------------------------------

class TestServingEngine:
    def _engine(self, dm, codec="fp32", **kw):
        q = RequestQueue(max_depth=kw.pop("queue_depth", 64))
        pool = _pool(dm, codec=codec,
                     n_blocks=kw.pop("n_blocks", 32))
        return ServingEngine(dm, pool, q, max_batch=kw.pop("max_batch", 4),
                             **kw)

    def _reference_greedy(self, dm, prompt, max_new):
        last, kvs = dm.prefill([prompt])
        toks = [int(np.argmax(last[0]))]
        cap = len(prompt) + max_new
        S = bucket_pow2(cap, minimum=16)
        past = np.zeros((1, S, dm.elems_per_token), np.float32)
        past[0, :len(prompt)] = kvs[0]
        n = len(prompt)
        while len(toks) < max_new:
            lg, kv = dm.decode(np.array([toks[-1]]), np.array([n]), past,
                               np.array([n]))
            past[0, n] = kv[0]
            n += 1
            toks.append(int(np.argmax(lg[0])))
        return toks

    def test_paged_generation_matches_dense_reference(self, dm):
        """fp32 paged engine == dense-cache greedy reference, exactly,
        for a batch of ragged requests served concurrently."""
        eng = self._engine(dm)
        rs = np.random.RandomState(0)
        reqs = [ServeRequest(prompt_ids=rs.randint(0, 64, (3 + i,)),
                             max_new_tokens=3 + i) for i in range(4)]
        for r in reqs:
            assert eng.queue.submit(r)
        _drive(eng)
        for r in reqs:
            assert r.outcome == "completed"
            assert r.generated == self._reference_greedy(
                dm, r.prompt_ids, r.max_new_tokens), r.request_id

    def test_no_head_of_line_blocking(self, dm):
        """A short request admitted behind a long one finishes first —
        the decode batch is re-formed every step."""
        eng = self._engine(dm, max_batch=2)
        rs = np.random.RandomState(1)
        long = ServeRequest(prompt_ids=rs.randint(0, 64, (4,)),
                            max_new_tokens=24)
        short = ServeRequest(prompt_ids=rs.randint(0, 64, (4,)),
                             max_new_tokens=2)
        eng.queue.submit(long)
        eng.queue.submit(short)
        order = []
        for _ in range(60):
            eng.step()
            for r in (short, long):
                if r.outcome == "completed" and r.request_id not in order:
                    order.append(r.request_id)
            if len(order) == 2:
                break
        assert order == [short.request_id, long.request_id]

    def test_blocks_freed_on_completion_and_batch_reforms(self, dm):
        eng = self._engine(dm, max_batch=2, n_blocks=8)
        rs = np.random.RandomState(2)
        reqs = _reqs(rs, 5, prompt_len=4, max_new=3)
        for r in reqs:
            eng.queue.submit(r)
        _drive(eng)
        assert all(r.outcome == "completed" for r in reqs)
        assert eng.pool.blocks_in_use == 0
        assert eng.pool.free_blocks == 8
        assert eng.completed == 5

    def test_admission_rejects_at_depth(self, dm):
        before = _m_requests.labels(outcome="rejected").get()
        q = RequestQueue(max_depth=2)
        rs = np.random.RandomState(3)
        rr = _reqs(rs, 3)
        assert q.submit(rr[0]) and q.submit(rr[1])
        assert not q.submit(rr[2])
        assert _m_requests.labels(outcome="rejected").get() == before + 1
        assert _m_queue_depth.get() == 2

    def test_oversized_request_fails_cleanly(self, dm):
        eng = self._engine(dm)
        r = ServeRequest(prompt_ids=np.zeros((40,), np.int64),
                         max_new_tokens=60)   # budget 99 > max_context 64
        eng.queue.submit(r)
        _drive(eng)
        assert r.outcome == "failed" and "context" in r.error

    def test_put_back_when_pool_full_then_served(self, dm):
        """Admission defers (front put-back, not drop) while the pool
        has no room, and serves the request once blocks free up."""
        eng = self._engine(dm, n_blocks=4, max_batch=4)
        rs = np.random.RandomState(4)
        r1, r2 = _reqs(rs, 2, prompt_len=8, max_new=17)  # 3 blocks each
        eng.queue.submit(r1)
        eng.queue.submit(r2)
        _drive(eng)
        assert r1.outcome == "completed" and r2.outcome == "completed"

    def test_int8_engine_serves_with_quantized_pool(self, dm):
        eng = self._engine(dm, codec="int8_block")
        rs = np.random.RandomState(5)
        reqs = _reqs(rs, 3, prompt_len=6, max_new=4)
        for r in reqs:
            eng.queue.submit(r)
        _drive(eng)
        assert all(r.outcome == "completed" for r in reqs)
        assert all(len(r.generated) == 4 for r in reqs)

    def test_mirror_equals_pool_gather_mid_flight(self, dm):
        """The engine's incremental fp32 mirror must be bit-identical to
        a fresh dequantizing gather of the paged cache at every step —
        attention consumes exactly the at-rest bits."""
        eng = self._engine(dm, codec="int8_block", max_batch=2)
        rs = np.random.RandomState(6)
        for r in _reqs(rs, 2, prompt_len=5, max_new=8):
            eng.queue.submit(r)
        for _ in range(12):
            eng.step()
            for s in eng.running:
                np.testing.assert_array_equal(
                    s.mirror[:s.n_past], eng.pool.gather(s.table))
        _drive(eng)


# ---------------------------------------------------------------------------
# replica set: dispatch, chaos, eviction (the acceptance chaos phase)
# ---------------------------------------------------------------------------

class TestReplicaSet:
    def _submit_n(self, rset, rs, n, max_new=5):
        ids = []
        for r in _reqs(rs, n, prompt_len=5, max_new=max_new):
            assert rset.submit(r)
            ids.append(r.request_id)
        return ids

    def test_two_replicas_complete_everything(self, dm):
        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=4)
        rs = np.random.RandomState(0)
        with rset:
            ids = self._submit_n(rset, rs, 8)
            res = rset.wait(ids, timeout=60)
        assert len(res) == 8
        assert all(r.outcome == "completed" for r in res.values())
        # outputs equal the single-engine reference (shared zero-copy
        # weights; per-replica state must not leak into results)
        for r in res.values():
            q = RequestQueue(8)
            ref_eng = ServingEngine(dm, _pool(dm), q, max_batch=1)
            ref = ServeRequest(prompt_ids=r.prompt_ids,
                               max_new_tokens=r.max_new_tokens)
            q.submit(ref)
            _drive(ref_eng)
            assert r.generated == ref.generated

    def test_hang_eviction_loses_zero_requests(self, dm):
        """CHAOS: replica 0 hangs mid-run holding live sequences; the
        watchdog evicts it, its requests drain + re-dispatch, and every
        accepted request still completes."""
        gate = threading.Event()
        hung = threading.Event()

        def hang_hook(eng):
            if eng.running and not gate.is_set():
                hung.set()
                gate.wait(30)   # "stuck inside a step"

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, watchdog_timeout=0.3,
                          pre_step_hooks={0: hang_hook})
        rs = np.random.RandomState(1)
        try:
            with rset:
                ids = self._submit_n(rset, rs, 10, max_new=6)
                assert hung.wait(20), "replica 0 never picked up work"
                res = rset.wait(ids, timeout=60)
                assert len(res) == 10, \
                    f"lost requests: {set(ids) - set(res)}"
                assert all(r.outcome == "completed" for r in res.values())
                deadline = time.monotonic() + 10
                while not rset.evictions and time.monotonic() < deadline:
                    time.sleep(0.02)
        finally:
            gate.set()      # release the zombie thread
        assert [e["reason"] for e in rset.evictions] == ["hang"]
        assert rset.evictions[0]["drained"] >= 1
        assert not rset.engines[0].alive and rset.engines[1].alive
        # drained requests were re-run from scratch on the survivor
        redone = [r for r in res.values() if r.attempts > 0]
        assert len(redone) >= 1
        assert all(len(r.generated) == 6 for r in res.values())

    def test_crash_eviction_loses_zero_requests(self, dm):
        """CHAOS: a replica whose step RAISES is evicted and drained."""
        state = {"armed": True}

        def crash_hook(eng):
            if eng.running and state["armed"]:
                state["armed"] = False
                raise RuntimeError("injected replica crash")

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, pre_step_hooks={0: crash_hook})
        rs = np.random.RandomState(2)
        with rset:
            ids = self._submit_n(rset, rs, 8)
            res = rset.wait(ids, timeout=60)
        assert len(res) == 8
        assert all(r.outcome == "completed" for r in res.values())
        assert [e["reason"] for e in rset.evictions] == ["error"]

    def test_corrupt_replica_evicted_by_guard(self, dm):
        """CHAOS: a replica serving from corrupted weights diverges from
        the boot-time ReplicaGuard digest and is evicted."""
        import jax.numpy as jnp

        bad = GPTDecodeModel.__new__(GPTDecodeModel)
        bad.__dict__.update(dm.__dict__)
        bad.params = dict(dm.params)
        w = np.array(bad.params["fc1_w"])
        w[0, 0, 0] += 1.0   # SDC: one flipped weight
        bad.params["fc1_w"] = jnp.asarray(w)
        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, guard_every=1, models=[bad, dm])
        rs = np.random.RandomState(3)
        with rset:
            ids = self._submit_n(rset, rs, 6)
            res = rset.wait(ids, timeout=60)
        assert len(res) == 6
        assert all(r.outcome == "completed" for r in res.values())
        assert [e["reason"] for e in rset.evictions] == ["corrupt"]
        assert not rset.engines[0].alive

    def test_serving_exposition_section(self, dm):
        from paddle_tpu.observability.exposition import TelemetryServer

        rset = ReplicaSet(dm, n_replicas=1, n_blocks=16, block_tokens=8,
                          max_batch=2)
        rs = np.random.RandomState(4)
        with rset, TelemetryServer(port=0) as srv:
            ids = self._submit_n(rset, rs, 3)
            rset.wait(ids, timeout=60)
            with urllib.request.urlopen(srv.url + "/serving",
                                        timeout=5) as resp:
                doc = json.loads(resp.read())
        assert doc["alive_replicas"] == 1
        assert doc["replicas"][0]["name"] == "replica-0"
        assert doc["replicas"][0]["kv"]["codec"] == "fp32"
        assert doc["latency_ms"]["count"] >= 3
        assert doc["latency_ms"]["p99"] is not None
        # unregistered after stop: the route 404s again
        with TelemetryServer(port=0) as srv2:
            req = urllib.request.Request(srv2.url + "/serving")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(req, timeout=5)

    def test_outcome_accounting(self, dm):
        done0 = _m_requests.labels(outcome="completed").get()
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=16, block_tokens=8,
                          max_batch=2)
        rs = np.random.RandomState(5)
        with rset:
            ids = self._submit_n(rset, rs, 4)
            res = rset.wait(ids, timeout=60)
        assert len(res) == 4
        assert _m_requests.labels(outcome="completed").get() == done0 + 4

    def test_flags_defaults_wired(self, dm):
        from paddle_tpu.framework.flags import get_flags

        f = get_flags(["FLAGS_serving_block_tokens",
                       "FLAGS_serving_max_batch",
                       "FLAGS_serving_queue_depth",
                       "FLAGS_serving_kv_codec",
                       "FLAGS_serving_watchdog_s"])
        assert f["FLAGS_serving_kv_codec"] == "fp32"
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=4)
        assert rset.queue.max_depth == f["FLAGS_serving_queue_depth"]
        assert rset.engines[0].max_batch == f["FLAGS_serving_max_batch"]
        assert rset.engines[0].pool.block_tokens == \
            f["FLAGS_serving_block_tokens"]
        assert rset.codec == "fp32"


# ---------------------------------------------------------------------------
# request-scoped tracing (ISSUE 18)
# ---------------------------------------------------------------------------

class TestRequestTracing:
    def _submit_n(self, rset, rs, n, max_new=5):
        ids = []
        for r in _reqs(rs, n, prompt_len=5, max_new=max_new):
            assert rset.submit(r)
            ids.append(r.request_id)
        return ids

    def test_traces_section_lifecycle(self, dm):
        """/traces (index) and /traces/<id> serve the trace store while
        the ReplicaSet runs; unknown ids 404; after stop the whole route
        404s again (satellite 3)."""
        from paddle_tpu.observability.exposition import TelemetryServer

        rset = ReplicaSet(dm, n_replicas=1, n_blocks=16, block_tokens=8,
                          max_batch=2)
        rs = np.random.RandomState(6)
        with rset, TelemetryServer(port=0) as srv:
            ids = self._submit_n(rset, rs, 3)
            res = rset.wait(ids, timeout=60)
            assert all(r.trace is not None for r in res.values())
            with urllib.request.urlopen(srv.url + "/traces",
                                        timeout=5) as resp:
                idx = json.loads(resp.read())
            listed = {t["trace_id"]: t for t in idx["traces"]}
            r0 = res[ids[0]]
            assert r0.trace.trace_id in listed
            assert listed[r0.trace.trace_id]["request_id"] == ids[0]
            with urllib.request.urlopen(
                    srv.url + "/traces/" + r0.trace.trace_id,
                    timeout=5) as resp:
                doc = json.loads(resp.read())
            names = [s["name"] for s in doc["spans"]]
            assert names[0] == "queue_wait" and names[-1] == "retire"
            assert "prefill" in names and "decode_step" in names
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(srv.url + "/traces/t0-nope",
                                       timeout=5)
            assert e.value.code == 404
        # unregistered after stop: the route 404s again
        with TelemetryServer(port=0) as srv2:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(srv2.url + "/traces", timeout=5)
            assert e.value.code == 404

    def test_chaos_trace_names_every_hop(self, dm):
        """Acceptance (ISSUE 18): replica hangs mid-decode -> watchdog
        eviction -> requeue-at-head -> completion on the survivor yields
        ONE trace whose spans name every hop, retrievable over /traces/
        <id> starting from an exemplar on the latency histogram."""
        from paddle_tpu.observability.exposition import TelemetryServer
        from paddle_tpu.observability.tracing import get_tracer
        from paddle_tpu.serving.engine import _m_latency

        gate = threading.Event()
        hung = threading.Event()

        def hang_hook(eng):
            if eng.running and not gate.is_set():
                hung.set()
                gate.wait(30)

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, watchdog_timeout=0.3,
                          pre_step_hooks={0: hang_hook})
        rs = np.random.RandomState(7)
        try:
            with rset, TelemetryServer(port=0) as srv:
                ids = self._submit_n(rset, rs, 6, max_new=6)
                assert hung.wait(20), "replica 0 never picked up work"
                res = rset.wait(ids, timeout=60)
                assert len(res) == 6
                assert all(r.outcome == "completed"
                           for r in res.values())
                redone = [r for r in res.values() if r.attempts > 0]
                assert redone, "no request survived an eviction"
                tid = redone[0].trace.trace_id
                with urllib.request.urlopen(srv.url + "/traces/" + tid,
                                            timeout=5) as resp:
                    doc = json.loads(resp.read())
        finally:
            gate.set()
        names = [s["name"] for s in doc["spans"]]
        # every hop of the journey, in causal order: admitted, started on
        # the doomed replica, evicted, requeued at head, re-admitted and
        # finished on the survivor
        for hop in ("queue_wait", "prefill", "eviction", "requeue_front",
                    "retire"):
            assert hop in names, f"missing hop {hop!r} in {names}"
        assert names.count("queue_wait") == 2      # two admissions
        assert names.index("eviction") < names.index("requeue_front") \
            < names.index("retire")
        retire = [s for s in doc["spans"] if s["name"] == "retire"][-1]
        assert retire["fields"]["outcome"] == "completed"
        assert retire["fields"]["attempt"] >= 1
        evicted = [s for s in doc["spans"] if s["name"] == "eviction"]
        assert evicted[0]["fields"]["reason"] == "hang"
        assert evicted[0]["fields"]["replica"] == "replica-0"
        # the trace is reachable FROM the telemetry: some latency-bucket
        # exemplar resolves to a trace that names the eviction hop
        exemplars = (_m_latency.get().get("exemplars") or {}).values()
        store = get_tracer().store
        traced = [store.get(e["trace_id"]) for e in exemplars]
        assert any(t and any(s["name"] == "eviction" for s in t["spans"])
                   for t in traced), \
            "no exemplar led to a trace naming the eviction"


# ---------------------------------------------------------------------------
# bench plumbing
# ---------------------------------------------------------------------------

class TestServeBenchGate:
    def test_gate_serve_metrics(self):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        assert bg.GATES["serve_tokens_per_s"][1] == "higher"
        assert bg.GATES["serve_p99_ms"][1] == "lower"
        base = {"value": 100.0, "device_kind": "cpu", "fallback": "cpu",
                "serve_tokens_per_s": 500.0, "serve_p99_ms": 40.0}
        good = dict(base, serve_tokens_per_s=520.0, serve_p99_ms=38.0)
        bad = dict(base, serve_tokens_per_s=200.0, serve_p99_ms=200.0)
        old = {"value": 100.0, "device_kind": "cpu", "fallback": "cpu"}
        traj = [("r1", base)]
        rows, compared, regressed = bg.gate(good, traj, 0.20)
        verdicts = {r["metric"]: r["verdict"] for r in rows}
        assert verdicts["serve_tokens_per_s"] == "OK"
        assert verdicts["serve_p99_ms"] == "OK"
        rows, compared, regressed = bg.gate(bad, traj, 0.20)
        verdicts = {r["metric"]: r["verdict"] for r in rows}
        assert verdicts["serve_tokens_per_s"] == "REGRESSED"
        assert verdicts["serve_p99_ms"] == "REGRESSED"
        # records predating the serving runtime SKIP, never fail
        rows, compared, regressed = bg.gate(old, traj, 0.20)
        verdicts = {r["metric"]: r["verdict"] for r in rows}
        assert verdicts["serve_tokens_per_s"] == "SKIP"
        assert verdicts["serve_p99_ms"] == "SKIP"


class TestServeBenchArtifact:
    """The committed artifacts/serve_bench.json must carry the ISSUE 14
    acceptance claims (regenerate with `python tools/serve_bench.py`)."""

    @pytest.fixture(scope="class")
    def rec(self):
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "artifacts",
                            "serve_bench.json")
        with open(path) as f:
            return json.load(f)

    def test_continuous_beats_saturated_baseline(self, rec):
        base = rec["sequential_baseline"]["tokens_per_s"]
        sat = [p for p in rec["continuous"]
               if p["qps_over_baseline_capacity"] >= 1.0]
        assert sat, "sweep must include the saturation point"
        assert max(p["tokens_per_s"] for p in sat) > base
        assert rec["speedup_at_saturation"] > 1.0
        assert rec["serve_tokens_per_s"] >= max(
            p["tokens_per_s"] for p in sat)

    def test_per_qps_point_reporting(self, rec):
        for p in rec["continuous"]:
            for k in ("qps", "tokens_per_s", "p50_ms", "p99_ms",
                      "mean_queue_depth", "max_queue_depth", "accepted",
                      "rejected"):
                assert k in p, k
        assert rec["serve_p99_ms"] > 0

    def test_int8_kv_quarter_bytes_at_parity(self, rec):
        kv = rec["kv_cache"]
        assert kv["bytes_ratio"] <= 0.28
        assert kv["int8_block_peak_bytes"] * 4 <= \
            kv["fp32_peak_bytes"] * 1.12
        assert kv["token_match_fraction"] >= 0.95

    def test_chaos_phase_zero_lost(self, rec):
        chaos = rec["chaos"]
        assert chaos["lost"] == 0
        assert chaos["ok"] is True
        assert any(e["reason"] == "hang" for e in chaos["evictions"])
        assert chaos["completed"] == chaos["accepted"]


@pytest.mark.slow
class TestServeBenchLive:
    def test_quick_bench_in_process(self, tmp_path):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "serve_bench_live", os.path.join(
                os.path.dirname(__file__), "..", "tools",
                "serve_bench.py"))
        sb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sb)
        rec = sb.run_serve_bench(quick=True)
        assert rec["speedup_at_saturation"] > 1.0
        assert rec["kv_cache"]["bytes_ratio"] <= 0.28
        assert rec["chaos"]["lost"] == 0 and rec["chaos"]["ok"]


class TestFleetScaling:
    """Policy-driven replica scaling (ISSUE 17): the drain + re-admit
    path loses zero requests, sync pump() drives a set deterministically,
    and the compile-aware watchdog grace keeps a slow-compiling replica
    alive where a stalled serving replica is evicted."""

    def test_caller_queue_is_shared_even_when_empty(self, dm):
        """Regression: RequestQueue defines __len__, so an EMPTY queue is
        FALSY — `queue or RequestQueue(...)` silently replaced the
        caller's queue and every externally-submitted request vanished.
        The fleet harness submits through exactly this shape."""
        q = RequestQueue(max_depth=8)
        assert len(q) == 0 and not q      # the trap: empty == falsy
        rset = ReplicaSet(dm, n_replicas=2, queue=q, n_blocks=32,
                          block_tokens=8, max_batch=2)
        assert rset.queue is q
        assert all(e.queue is q for e in rset.engines)
        req = ServeRequest(prompt_ids=np.array([1, 2, 3]),
                           max_new_tokens=2)
        q.submit(req)
        rset.pump(ticks=8)
        assert req.outcome == "completed"

    def test_engine_state_boot_compiling_serving(self, dm):
        q = RequestQueue(8)
        eng = ServingEngine(dm, _pool(dm), q, max_batch=2)
        assert eng.state == "boot"
        # idle ticks before the first request must NOT leave "compiling"
        # — the first real admission is what triggers the jit compile,
        # and the watchdog grace has to still be covering it then
        eng.step()
        assert eng.state == "compiling"
        seen = {}

        def spy(e):
            seen["during"] = e.state

        eng.pre_step = spy
        q.submit(ServeRequest(prompt_ids=np.array([1, 2, 3]),
                              max_new_tokens=2))
        eng.step()                  # first REAL step: admits + compiles
        assert seen["during"] == "compiling"
        assert eng.state == "serving"
        assert eng.stats()["state"] == "serving"

    def test_compile_guard_covers_first_shape_bucket(self, dm):
        """A model call on a never-executed shape bucket runs under
        state="compiling" (the first call per bucket may XLA-compile for
        ~seconds; a watchdog sized for a decode tick would read that as
        a hang and evict the survivor). A repeat bucket stays covered by
        whatever state the step is in."""
        q = RequestQueue(8)
        eng = ServingEngine(dm, _pool(dm), q, max_batch=2)
        eng._warm = True
        eng.state = "serving"
        with eng._compile_guard("decode", 2, 16):
            assert eng.state == "compiling"
        assert ("decode", 2, 16) in eng._seen_buckets
        # second encounter: no state flip, the bucket is warm
        eng.state = "serving"
        with eng._compile_guard("decode", 2, 16):
            assert eng.state == "serving"
        # a failed first call does NOT mark the bucket — the retry must
        # still run under grace
        try:
            with eng._compile_guard("extend", 4, 32, 3):
                raise RuntimeError("interrupted compile")
        except RuntimeError:
            pass
        assert ("extend", 4, 32, 3) not in eng._seen_buckets
        # a served request leaves its real buckets behind
        q.submit(ServeRequest(prompt_ids=np.array([1, 2, 3]),
                              max_new_tokens=2))
        _drive(eng)
        assert any(k[0] == "prefill" for k in eng._seen_buckets)
        assert any(k[0] == "decode" for k in eng._seen_buckets)

    def test_drain_recovers_mid_admission_intake(self, dm):
        """Requests popped from the queue but not yet landed in
        ``running`` (mid-prefill) must be visible to drain() — a
        scale-down racing _admit() on a HEALTHY replica would otherwise
        silently lose the batch being built."""
        q = RequestQueue(8)
        eng = ServingEngine(dm, _pool(dm), q, max_batch=2)
        r = ServeRequest(prompt_ids=np.array([4, 5, 6]), max_new_tokens=2)
        eng._intake.append(r)       # as _admit() holds it mid-prefill
        drained = eng.drain()
        assert not eng.alive
        assert [d.request_id for d in drained] == [r.request_id]
        assert drained[0].attempts == r.attempts + 1
        assert eng._intake == []
        # the worker's release is told the reincarnated copy is now
        # authoritative (so it won't also finish/requeue the original)
        assert eng._intake_discard(r) is False

    def test_intake_discard_is_identity_based(self, dm):
        """dataclass == on ServeRequest trips numpy's ambiguous-truth
        error (prompt_ids is an array); _intake_discard must match by
        identity, releasing exactly the object it was handed."""
        q = RequestQueue(8)
        eng = ServingEngine(dm, _pool(dm), q, max_batch=2)
        a = ServeRequest(prompt_ids=np.array([1, 2, 3]), max_new_tokens=2)
        b = ServeRequest(prompt_ids=np.array([9, 8, 7]), max_new_tokens=2)
        eng._intake.extend([a, b])
        assert eng._intake_discard(b) is True
        assert eng._intake == [a]
        assert eng._intake_discard(b) is False
        assert eng._intake == [a]

    def test_sync_scale_down_drains_and_readmits(self, dm):
        """The controller's serve_to_train path: retire a BUSY replica
        mid-flight; its running requests re-enter at the queue head and
        every accepted request still completes. Zero lost."""
        q = RequestQueue(max_depth=16)
        rset = ReplicaSet(dm, n_replicas=2, queue=q, n_blocks=32,
                          block_tokens=8, max_batch=2)
        rs = np.random.RandomState(7)
        reqs = _reqs(rs, 6, max_new=4)
        for r in reqs:
            assert rset.submit(r)
        rset.pump(ticks=2)          # both replicas pick up work
        assert rset.engines[1].running, "replica 1 never got in-flight work"
        ev = rset.scale_down(reason="fleet_policy")
        assert ev is not None and ev["direction"] == "down"
        assert ev["reason"] == "fleet_policy" and ev["drained"] >= 1
        assert rset.alive_replicas == 1
        rset.pump(ticks=60)         # the survivor absorbs the re-admits
        # drained requests finish as REINCARNATED objects (same
        # request_id, attempts+1) — judge by the result table, the same
        # identity the fleet ledger counts
        assert len(rset.results) == 6
        assert {r.request_id for r in reqs} == set(rset.results)
        assert all(r.outcome == "completed" for r in rset.results.values())
        assert any(r.attempts > 0 for r in rset.results.values())
        assert rset.stats()["scale_events"] == [ev]

    def test_sync_scale_up_adds_serving_capacity(self, dm):
        rset = ReplicaSet(dm, n_replicas=1, n_blocks=32, block_tokens=8,
                          max_batch=2)
        idx = rset.scale_up(reason="fleet_policy")
        assert idx == 1 and rset.alive_replicas == 2
        assert rset.scale_events[-1]["direction"] == "up"
        rs = np.random.RandomState(8)
        reqs = _reqs(rs, 4, max_new=3)
        for r in reqs:
            assert rset.submit(r)
        rset.pump(ticks=40)
        assert all(r.outcome == "completed" for r in reqs)
        # both engines did real work — the new replica is not a stub
        assert all(e.steps > 0 for e in rset.engines)

    def test_slow_compile_survives_watchdog_grace(self, dm):
        """Satellite 1: a replica stuck in its first (compiling) step for
        longer than watchdog_timeout is NOT evicted while compile_grace
        covers it, and serves normally once warm."""
        def slow_compile(eng):
            if eng.steps == 0:
                time.sleep(0.9)     # 3x the watchdog timeout

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, watchdog_timeout=0.3,
                          compile_grace=30.0,
                          pre_step_hooks={0: slow_compile})
        rs = np.random.RandomState(9)
        with rset:
            ids = [r.request_id for r in _reqs(rs, 6, max_new=3)
                   if rset.submit(r) or True]
            res = rset.wait(ids, timeout=60)
        assert len(res) == 6
        assert rset.evictions == []
        assert all(e.alive for e in rset.engines)

    def test_stall_without_grace_is_still_evicted(self, dm):
        """Control for the grace test: the same stall AFTER the first
        step (state == serving) fires the watchdog — compile grace must
        not blind it to real hangs."""
        gate = threading.Event()

        def hang_warm(eng):
            if eng.running and not gate.is_set():
                gate.wait(20)       # stuck while state == "serving"

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, watchdog_timeout=0.3,
                          compile_grace=30.0,
                          pre_step_hooks={0: hang_warm})
        rs = np.random.RandomState(10)
        try:
            with rset:
                ids = [r.request_id for r in _reqs(rs, 8, max_new=4)
                       if rset.submit(r) or True]
                res = rset.wait(ids, timeout=60)
                assert len(res) == 8
                deadline = time.monotonic() + 10
                while not rset.evictions and time.monotonic() < deadline:
                    time.sleep(0.02)
        finally:
            gate.set()
        assert [e["reason"] for e in rset.evictions] == ["hang"]
        assert not rset.engines[0].alive and rset.engines[1].alive

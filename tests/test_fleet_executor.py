"""Fleet executor actor runtime (distributed/fleet_executor.py).

Reference: paddle/fluid/distributed/fleet_executor/ — Carrier/Interceptor/
MessageBus task-graph orchestration for multi-stage inference.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet_executor import FleetExecutor, TaskNode


def test_three_stage_pipeline_order_and_results():
    exe = FleetExecutor([
        TaskNode(0, fn=lambda x: x + 1, downstream=[1]),
        TaskNode(1, fn=lambda x: x * 2, downstream=[2]),
        TaskNode(2, fn=lambda x: x - 3),
    ])
    outs = exe.run([1, 2, 3, 4])
    assert sorted(outs) == [(v + 1) * 2 - 3 for v in [1, 2, 3, 4]]
    exe.shutdown()


def test_stages_overlap_in_time():
    """Real concurrency: with 2 slow stages, total < serial sum."""
    def slow(tag):
        def fn(x):
            time.sleep(0.05)
            return x
        return fn

    exe = FleetExecutor([
        TaskNode(0, fn=slow("a"), downstream=[1]),
        TaskNode(1, fn=slow("b")),
    ])
    t0 = time.perf_counter()
    exe.run(list(range(8)))
    dt = time.perf_counter() - t0
    exe.shutdown()
    # serial = 8 * 2 * 0.05 = 0.8s; pipelined ≈ 0.05 * 9 = 0.45
    assert dt < 0.7, dt


def test_fanout_graph():
    """One source feeding two sinks (branching task graph)."""
    exe = FleetExecutor([
        TaskNode(0, fn=lambda x: x * 10, downstream=[1, 2]),
        TaskNode(1, fn=lambda x: x + 1),
        TaskNode(2, fn=lambda x: x + 2),
    ])
    outs = exe.run([1, 2], timeout=30)
    assert len(outs) == 2  # run() waits for len(microbatches) results
    assert set(outs) <= {11, 12, 21, 22}
    exe.shutdown()


def test_stage_error_propagates():
    def boom(x):
        raise RuntimeError("stage exploded")

    exe = FleetExecutor([
        TaskNode(0, fn=boom, downstream=[1]),
        TaskNode(1, fn=lambda x: x),
    ])
    with pytest.raises((RuntimeError, Exception)):
        exe.run([1], timeout=5)


def test_with_compiled_predictor_stage():
    """The intended composition: host pre/post stages around a jitted
    program."""
    import jax
    import jax.numpy as jnp

    predict = jax.jit(lambda v: jnp.tanh(v).sum())
    exe = FleetExecutor([
        TaskNode(0, fn=lambda x: np.asarray(x, np.float32) / 10.0,
                 downstream=[1]),
        TaskNode(1, fn=lambda v: float(predict(v))),
    ])
    outs = exe.run([np.ones(4), np.zeros(4)])
    assert sorted(round(o, 4) for o in outs) == sorted(
        [round(float(np.tanh(0.1) * 4), 4), 0.0])
    exe.shutdown()


@pytest.mark.requires_jax_export
def test_dist_model_sharded_inference_matches_single_device(tmp_path):
    """DistModel (reference dist_model.cc): artifact load + batch sharded
    over the mesh produces the same logits as plain single-device run."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.static as static
    import paddle_tpu.distributed.mesh as mesh_mod
    from paddle_tpu.distributed.fleet_executor import (
        DistModel, DistModelConfig,
    )

    rs = np.random.RandomState(0)
    prefix = str(tmp_path / "distm")
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", shape=[None, 8], dtype="float32")
        h = static.nn.fc(x, 16, activation="relu")
        out = static.nn.fc(h, 4)
    exe = static.Executor()
    exe.run(startup)
    static.save_inference_model(prefix, [x], [out], exe, program=main)

    feed = rs.rand(16, 8).astype("float32")
    (ref,) = exe.run(main, feed={"x": feed}, fetch_list=[out])

    try:
        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
        cfg = DistModelConfig(model_prefix=prefix)
        dm = DistModel(cfg)
        assert dm.init()
        (got,) = dm.run([feed])
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5,
                                   atol=1e-6)
        # the fed batch really was sharded over the 8 devices
        assert dm._batch_sharding.mesh.size == 8
    finally:
        mesh_mod._current[0] = None


@pytest.mark.requires_jax_export
def test_dist_model_mesh_set_after_init(tmp_path):
    """A mesh installed AFTER init() must be honored at run() (the
    sharding decision follows the current mesh, not a stale snapshot)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.static as static
    import paddle_tpu.distributed.mesh as mesh_mod
    from paddle_tpu.distributed.fleet_executor import (
        DistModel, DistModelConfig,
    )

    rs = np.random.RandomState(1)
    prefix = str(tmp_path / "dm2")
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", shape=[None, 4], dtype="float32")
        out = static.nn.fc(x, 2)
    exe = static.Executor()
    exe.run(startup)
    static.save_inference_model(prefix, [x], [out], exe, program=main)
    feed = rs.rand(8, 4).astype("float32")
    (ref,) = exe.run(main, feed={"x": feed}, fetch_list=[out])

    dm = DistModel(DistModelConfig(model_prefix=prefix))
    dm.init()  # no mesh yet
    try:
        (got0,) = dm.run([feed])  # meshless run works
        np.testing.assert_allclose(got0, np.asarray(ref), rtol=1e-5)
        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
        (got,) = dm.run([feed])  # mesh appeared afterwards: no crash
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5)
        assert dm._batch_sharding is not None
    finally:
        mesh_mod._current[0] = None


def test_backpressure_bounds_inflight_work():
    """Credit gating must propagate hop-by-hop (reference
    compute_interceptor.cc ready = input AND output-buffer space): a fast
    middle stage may run at most its downstream credit ahead of a slow
    sink, not absorb the whole feed into memory."""
    import threading

    processed_mid = []
    first_sink = threading.Event()
    mid_at_first_sink = []

    def mid(x):
        processed_mid.append(x)
        return x

    def sink(x):
        if not first_sink.is_set():
            time.sleep(0.3)
            mid_at_first_sink.append(len(processed_mid))
            first_sink.set()
        return x

    exe = FleetExecutor([
        TaskNode(0, downstream=[1], max_run_times=1),
        TaskNode(1, fn=mid, downstream=[2], max_run_times=1),
        TaskNode(2, fn=sink, max_run_times=1),
    ])
    outs = exe.run(list(range(8)))
    exe.shutdown()
    assert len(outs) == 8
    assert mid_at_first_sink[0] <= 2, mid_at_first_sink


def test_many_microbatches_fanout_stress():
    """200 micro-batches through a diamond graph (source -> 2 branches ->
    join): credit flow must neither deadlock nor drop/duplicate work."""
    import numpy as np

    joined = []

    exe = FleetExecutor([
        TaskNode(0, fn=lambda x: x, downstream=[1, 2], max_run_times=3),
        TaskNode(1, fn=lambda x: x * 2, downstream=[3], max_run_times=2),
        TaskNode(2, fn=lambda x: x * 3, downstream=[3], max_run_times=1),
        TaskNode(3, fn=lambda x: joined.append(int(x)) or x,
                 max_run_times=2),
    ])
    outs = exe.run(list(range(200)), timeout=60)
    exe.shutdown()
    # join sees each micro-batch TWICE (once per branch)
    assert len(outs) == 200 and len(joined) == 400
    got = sorted(joined)
    want = sorted([i * 2 for i in range(200)] + [i * 3 for i in range(200)])
    assert got == want

"""Eager per-op dispatch regression guard (VERDICT r4 #7, SURVEY §7
hard-part 1).

artifacts/eager_dispatch.json carries the measured numbers (TPU record
from the on-chip sprint; CPU record from tools/eager_dispatch.py). This
guard re-measures the CPU-PJRT hit path in-suite. The signal is the
miss/hit RATIO over the min of several repetitions, not an absolute
wall-clock bound: a loaded CI host inflates both paths together, while
the regression this guard exists for — a cache-key bug recompiling per
call, a new per-op host hop — collapses the ratio toward 1. (The old
`hit_us < 450` absolute bound flaked whenever the suite shared a box.)
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_eager_hit_dispatch_stays_bounded():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from eager_dispatch import measure

    from paddle_tpu.framework.autograd import clear_op_cache

    recs = []
    for _ in range(3):
        # a repeat run would otherwise find the previous run's entries and
        # measure cache HITS on the miss path, collapsing the ratio
        clear_op_cache()
        recs.append(measure(n_hit=150, n_miss=2))
    # min over repetitions: the least-interfered-with measurement of each
    # path is the honest one on a shared host
    hit_us = min(r["hit_us"] for r in recs)
    miss_us = min(r["miss_us"] for r in recs)
    # the miss path must actually be a compile (orders slower than a
    # cache hit), or the hit measurement is not exercising the cache
    assert miss_us > 10 * hit_us, (hit_us, miss_us, recs)


def test_eager_dispatch_artifact_is_current():
    """The committed artifact must exist, carry both labeled records, and
    keep the TPU record marked as on-chip."""
    path = os.path.join(REPO, "artifacts", "eager_dispatch.json")
    d = json.load(open(path))
    assert "cpu" in d and d["cpu"]["on_tpu"] is False
    assert d["cpu"]["hit_us"] > 0 and d["cpu"]["miss_us"] > d["cpu"]["hit_us"]
    assert "tpu" in d and d["tpu"]["on_tpu"] is True
    assert d["tpu"]["hit_us"] > 0

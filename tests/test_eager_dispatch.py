"""Eager per-op dispatch regression guard (VERDICT r4 #7, SURVEY §7
hard-part 1).

artifacts/eager_dispatch.json carries the measured numbers (TPU record
from the on-chip sprint; CPU record from tools/eager_dispatch.py). This
guard re-measures the CPU-PJRT hit path in-suite: the bound is
deliberately loose (10x the ~45us measured) so only an order-of-
magnitude dispatch regression — a new per-op host hop, a cache-key bug
recompiling per call — trips it, not scheduler jitter.
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_eager_hit_dispatch_stays_bounded():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from eager_dispatch import measure

    rec = measure(n_hit=150, n_miss=2)
    assert rec["hit_us"] < 450, rec  # 10x the measured ~45us CPU hit
    # the miss path must actually be a compile (orders slower), or the
    # "hit" measurement is not exercising the cache at all
    assert rec["miss_us"] > 10 * rec["hit_us"], rec


def test_eager_dispatch_artifact_is_current():
    """The committed artifact must exist, carry both labeled records, and
    keep the TPU record marked as on-chip."""
    path = os.path.join(REPO, "artifacts", "eager_dispatch.json")
    d = json.load(open(path))
    assert "cpu" in d and d["cpu"]["on_tpu"] is False
    assert d["cpu"]["hit_us"] > 0 and d["cpu"]["miss_us"] > d["cpu"]["hit_us"]
    assert "tpu" in d and d["tpu"]["on_tpu"] is True
    assert d["tpu"]["hit_us"] > 0

"""Multi-host runtime: real two-process rendezvous + cross-process collective.

The reference proves its comm backend with two-rank local processes
(test_collective_base.py pattern, SURVEY.md §4). Here two spawned Python
processes each run init_parallel_env (-> jax.distributed.initialize,
the PJRT coordination-service rendezvous that replaces
gen_comm_id_helper.cc:343), form one global 8-device CPU view, and a jitted
reduction over a mesh spanning both processes must see both processes' data.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    rank = int(sys.argv[1])
    port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = "2"
    os.environ["PADDLE_MASTER"] = "127.0.0.1:" + port
    sys.path.insert(0, {repo!r})
    import paddle_tpu.distributed as dist
    env = dist.init_parallel_env()
    assert dist.is_initialized()
    assert env.rank == rank and env.world_size == 2

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    mesh = Mesh(np.array(jax.devices()), ("data",))
    local = np.full((4, 2), rank + 1, np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local)
    total = jax.jit(lambda a: jnp.sum(a),
                    out_shardings=NamedSharding(mesh, P()))(garr)
    # rank0 rows of 1s + rank1 rows of 2s: 4*2*1 + 4*2*2 = 24
    assert float(total) == 24.0, float(total)
    print("RANK_OK", rank)
""").format(repo=REPO)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.timeout(180)
@pytest.mark.requires_cpu_multiprocess
def test_two_process_rendezvous_and_collective(tmp_path):
    port = str(_free_port())
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    # strip the single-chip TPU-tunnel shim; the worker forces CPU anyway
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    procs = [subprocess.Popen([sys.executable, str(script), str(r), port],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
        assert f"RANK_OK {r}" in out

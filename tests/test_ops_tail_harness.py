"""OpTest-harness coverage for the ops added this round: forward vs NumPy
semantics + analytic grads vs central finite differences (the reference's
OpTest.check_output/check_grad contract, unittests/op_test.py:280)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_forward, check_grad

rs = np.random.RandomState(7)


def A(*shape):
    return rs.rand(*shape).astype("float32") + 0.1


def test_add_n_forward_grad():
    check_forward(lambda a, b, c: paddle.add_n([a, b, c]),
                  lambda a, b, c: a + b + c, [A(3, 4), A(3, 4), A(3, 4)])
    check_grad(lambda a, b: paddle.add_n([a, b]), [A(2, 3), A(2, 3)])


def test_diagonal_forward_grad():
    check_forward(paddle.diagonal, np.diagonal, [A(4, 5)])
    check_forward(lambda x: paddle.diagonal(x, offset=1),
                  lambda x: np.diagonal(x, offset=1), [A(4, 5)])
    check_grad(paddle.diagonal, [A(4, 4)])


def test_multiplex_forward_grad():
    idx = np.array([[1], [0], [1]], np.int32)

    def np_ref(a, b):
        stacked = np.stack([a, b])
        return stacked[idx[:, 0], np.arange(3)]

    check_forward(lambda a, b: paddle.multiplex([a, b], paddle.to_tensor(idx)),
                  np_ref, [A(3, 4), A(3, 4)])
    check_grad(lambda a, b: paddle.multiplex([a, b], paddle.to_tensor(idx)),
               [A(3, 4), A(3, 4)])


def test_affine_channel_forward_grad():
    def np_ref(x, s, b):
        return x * s[None, :, None, None] + b[None, :, None, None]

    check_forward(F.affine_channel, np_ref, [A(2, 3, 4, 4), A(3), A(3)])
    check_grad(F.affine_channel, [A(2, 3, 4, 4), A(3), A(3)])


def test_partial_ops_grad():
    check_grad(lambda a, b: paddle.partial_concat([a, b], 1, 2),
               [A(3, 5), A(3, 5)])
    check_grad(lambda a, b: paddle.partial_sum([a, b], 0, 3),
               [A(3, 5), A(3, 5)])


def test_pad_constant_like_grad():
    big = np.zeros((5, 6), "float32")
    check_forward(
        lambda y: paddle.pad_constant_like(paddle.to_tensor(big), y, 0.0),
        lambda y: np.pad(y, [(0, 2), (0, 2)]), [A(3, 4)])
    check_grad(
        lambda y: paddle.pad_constant_like(paddle.to_tensor(big), y, 0.0),
        [A(3, 4)])


def test_fill_diagonal_grad():
    check_grad(lambda x: paddle.fill_diagonal(x, 0.0), [A(4, 4)])


def test_diag_embed_grad():
    check_forward(F.diag_embed,
                  lambda x: np.stack([np.diag(r) for r in x]), [A(3, 4)])
    check_grad(F.diag_embed, [A(3, 4)])


def test_max_unpool1d_grad():
    x = A(2, 2, 8)

    def op(xx):
        p, idx = F.max_pool1d(xx, 2, return_mask=True)
        return F.max_unpool1d(p, idx, 2)

    check_grad(op, [x])


def test_rank_loss_grad():
    lbl = np.ones((4, 1), "float32")
    check_grad(lambda l, r: F.rank_loss(paddle.to_tensor(lbl), l, r),
               [A(4, 1), A(4, 1)])


def test_bpr_loss_grad():
    lbl = rs.randint(0, 4, (5, 1)).astype("int64")
    check_grad(lambda x: F.bpr_loss(x, paddle.to_tensor(lbl)), [A(5, 4)])


def test_npair_dice_grads():
    lbl = rs.randint(0, 3, (4,)).astype("int64")
    check_grad(lambda a, p: F.npair_loss(a, p, paddle.to_tensor(lbl)),
               [A(4, 6), A(4, 6)])
    lab = rs.randint(0, 4, (2, 5, 1)).astype("int64")
    check_grad(lambda x: F.dice_loss(x, paddle.to_tensor(lab)),
               [A(2, 5, 4)])


def test_hsigmoid_grad():
    lbl = rs.randint(0, 8, (4,)).astype("int64")
    check_grad(
        lambda x, w: F.hsigmoid_loss(x, paddle.to_tensor(lbl), 8, w),
        [A(4, 6), A(7, 6)])


def test_margin_cross_entropy_grad():
    lbl = rs.randint(0, 6, (4,)).astype("int64")
    check_grad(
        lambda lg: F.margin_cross_entropy(
            lg * 0.9, paddle.to_tensor(lbl), margin1=1.0, margin2=0.1,
            margin3=0.0, scale=4.0),
        [A(4, 6)], rtol=1e-2, atol=1e-3)


def test_sequence_tail_grads():
    import paddle_tpu.static.nn as snn

    check_grad(lambda x: snn.sequence_reshape(x, 4), [A(6, 8)])
    idx = np.array([[0, 2], [1, 3]], np.int64)
    upd_shape = (2, 2)
    check_grad(
        lambda x, u: snn.sequence_scatter(x, paddle.to_tensor(idx), u),
        [A(2, 6), A(*upd_shape)])


def test_im2sequence_matches_manual_patches():
    x = A(2, 3, 5, 5)
    out = F.im2sequence(paddle.to_tensor(x), filter_size=2, stride=1)
    # manual: 4x4 positions per image, rows ordered (n, oh, ow)
    assert out.shape == [2 * 16, 3 * 4]
    manual = np.stack([
        x[n, :, i:i + 2, j:j + 2].reshape(-1)
        for n in range(2) for i in range(4) for j in range(4)])
    np.testing.assert_allclose(out.numpy(), manual, rtol=1e-6)
    check_grad(lambda v: F.im2sequence(v, 2, 2), [A(1, 2, 4, 4)])


def test_conv_shift_semantics_and_grad():
    x = A(2, 6)
    y = A(2, 3)
    out = F.conv_shift(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
    # manual circular correlation with offset (M-1)/2 = 1
    manual = np.zeros((2, 6), np.float32)
    for b in range(2):
        for i in range(6):
            for j in range(3):
                manual[b, i] += x[b, (i + j - 1) % 6] * y[b, j]
    np.testing.assert_allclose(out, manual, rtol=1e-5)
    check_grad(F.conv_shift, [A(2, 6), A(2, 3)])


def test_fsp_matrix_and_grad():
    a, b = A(2, 3, 4, 4), A(2, 5, 4, 4)
    out = F.fsp_matrix(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    manual = np.einsum("bchw,bdhw->bcd", a, b) / 16
    np.testing.assert_allclose(out, manual, rtol=1e-5)
    check_grad(F.fsp_matrix, [a, b])


def test_batch_fc_and_grad():
    inp, w, bias = A(3, 4, 5), A(3, 5, 6), A(3, 6)
    out = F.batch_fc(paddle.to_tensor(inp), paddle.to_tensor(w),
                     paddle.to_tensor(bias)).numpy()
    manual = np.einsum("sbi,sio->sbo", inp, w) + bias[:, None, :]
    np.testing.assert_allclose(out, manual, rtol=1e-5)
    check_grad(F.batch_fc, [inp, w, bias])


def test_correlation_zero_displacement_is_patchmean_dot():
    """At displacement (0,0), kernel 1: out = mean_c(x1*x2) (reference
    normalization: / (k^2 * C) with the kernel sum)."""
    x1, x2 = A(1, 4, 6, 6), A(1, 4, 6, 6)
    out = F.correlation(paddle.to_tensor(x1), paddle.to_tensor(x2),
                        pad_size=0, kernel_size=1, max_displacement=0,
                        stride1=1, stride2=1)
    assert out.shape == [1, 1, 6, 6]
    np.testing.assert_allclose(out.numpy()[0, 0],
                               (x1 * x2).mean(1)[0], rtol=1e-5)
    check_grad(lambda a, b: F.correlation(a, b, 0, 1, 0, 1, 1),
               [A(1, 2, 4, 4), A(1, 2, 4, 4)])


def test_correlation_displacement_grid():
    x1, x2 = A(1, 2, 8, 8), A(1, 2, 8, 8)
    out = F.correlation(paddle.to_tensor(x1), paddle.to_tensor(x2),
                        pad_size=2, kernel_size=1, max_displacement=2,
                        stride1=1, stride2=2)
    # D = 2*(2//2)+1 = 3 -> 9 displacement channels
    assert out.shape[1] == 9
    # center channel (index 4) == zero displacement correlation
    center = out.numpy()[0, 4]
    ref = F.correlation(paddle.to_tensor(x1), paddle.to_tensor(x2),
                        pad_size=2, kernel_size=1, max_displacement=0,
                        stride1=1, stride2=1).numpy()[0, 0]
    # out_h differs (border), compare the overlapping interior
    h = min(center.shape[0], ref.shape[0])
    off1 = (center.shape[0] - h) // 2
    off2 = (ref.shape[0] - h) // 2
    np.testing.assert_allclose(
        center[off1:off1 + h, off1:off1 + h],
        ref[off2:off2 + h, off2:off2 + h], rtol=1e-4)


def test_filter_by_instag():
    rows = np.arange(12, dtype=np.float32).reshape(4, 3)
    tags = np.array([[1], [2], [1], [3]], np.int64)
    out, w, idx = F.filter_by_instag(paddle.to_tensor(rows),
                                     paddle.to_tensor(tags),
                                     paddle.to_tensor(np.array([1], np.int64)))
    np.testing.assert_array_equal(idx.numpy(), [0, 2])
    np.testing.assert_allclose(out.numpy(), rows[[0, 2]])
    np.testing.assert_allclose(w.numpy(), np.ones((2, 1)))
    # empty match -> sentinel row
    out2, w2, idx2 = F.filter_by_instag(
        paddle.to_tensor(rows), paddle.to_tensor(tags),
        paddle.to_tensor(np.array([9], np.int64)), out_val_if_empty=-1)
    assert out2.shape == [1, 3] and float(out2.numpy().max()) == -1.0
    assert w2.numpy().sum() == 0.0 and idx2.shape == [0]


def test_filter_by_instag_gradient_and_lod():
    import pytest as _pytest

    rows = A(4, 3)
    tags = np.array([[1], [2], [1], [3]], np.int64)
    x = paddle.to_tensor(rows, stop_gradient=False)
    out, w, idx = F.filter_by_instag(
        x, paddle.to_tensor(tags), paddle.to_tensor(np.array([1], np.int64)))
    out.sum().backward()
    g = x.grad.numpy()
    np.testing.assert_allclose(g[[0, 2]], 1.0)  # kept rows get grads
    np.testing.assert_allclose(g[[1, 3]], 0.0)  # dropped rows get zero
    # LoD form: instance 0 spans 2 rows, instance 1 spans 1
    rows3 = A(3, 2)
    t2 = np.array([[5], [7]], np.int64)
    out2, _, idx2 = F.filter_by_instag(
        paddle.to_tensor(rows3), paddle.to_tensor(t2),
        paddle.to_tensor(np.array([5], np.int64)), ins_lod=[2, 1])
    np.testing.assert_array_equal(idx2.numpy(), [0, 1])
    with _pytest.raises(ValueError):
        F.filter_by_instag(paddle.to_tensor(rows3), paddle.to_tensor(t2),
                           paddle.to_tensor(np.array([5], np.int64)))


def test_prroi_pool_exact_vs_dense_integration():
    """PrRoI = exact integral of the bilinear surface: compare against
    brute-force numerical integration on a fine grid."""
    import paddle_tpu.vision.ops as vo

    feat = A(1, 2, 8, 8)
    boxes = np.array([[1.2, 0.7, 6.3, 5.9]], np.float32)
    bn = np.array([1], np.int32)
    out = vo.prroi_pool(paddle.to_tensor(feat), paddle.to_tensor(boxes),
                        paddle.to_tensor(bn), output_size=2).numpy()
    assert out.shape == (1, 2, 2, 2)

    # dense oracle: bilinear interp sampled on a fine sub-grid per bin
    def bilinear(f, y, x):
        H, W = f.shape
        y0, x0 = int(np.floor(y)), int(np.floor(x))
        vals = 0.0
        for dy in (0, 1):
            for dx in (0, 1):
                yi, xi = y0 + dy, x0 + dx
                w = max(0.0, 1 - abs(y - yi)) * max(0.0, 1 - abs(x - xi))
                if 0 <= yi < H and 0 <= xi < W and w > 0:
                    vals += f[yi, xi] * w
        return vals

    x1, y1, x2, y2 = boxes[0]
    bw, bh = (x2 - x1) / 2, (y2 - y1) / 2
    K = 60
    for c in range(2):
        for i in range(2):
            for j in range(2):
                ys = y1 + (i + (np.arange(K) + 0.5) / K) * bh
                xs = x1 + (j + (np.arange(K) + 0.5) / K) * bw
                acc = np.mean([bilinear(feat[0, c], yy, xx)
                               for yy in ys for xx in xs])
                np.testing.assert_allclose(out[0, c, i, j], acc,
                                           rtol=2e-3, atol=2e-3)


def test_prroi_pool_grads_flow_to_features_and_boxes():
    import paddle_tpu.vision.ops as vo

    feat = paddle.to_tensor(A(1, 2, 6, 6), stop_gradient=False)
    boxes = paddle.to_tensor(np.array([[1.0, 1.0, 5.0, 5.0]], np.float32),
                             stop_gradient=False)
    bn = paddle.to_tensor(np.array([1], np.int32))
    out = vo.prroi_pool(feat, boxes, bn, output_size=2)
    out.sum().backward()
    assert feat.grad is not None and np.isfinite(feat.grad.numpy()).all()
    # PrRoI's hallmark: gradients w.r.t. the BOX COORDINATES exist
    assert boxes.grad is not None
    assert np.abs(boxes.grad.numpy()).sum() > 0


def test_lstmp_projection_cell_and_rnn():
    import paddle_tpu.nn as nn

    cell = nn.LSTMCell(6, 8, proj_size=3)
    x = paddle.to_tensor(A(4, 6))
    h, (h2, c) = cell(x)
    assert h.shape == [4, 3] and c.shape == [4, 8]  # projected h, full c
    # runs under the RNN wrapper over time
    rnn = nn.RNN(nn.LSTMCell(6, 8, proj_size=3))
    seq = paddle.to_tensor(A(2, 5, 6))
    out, (hf, cf) = rnn(seq)
    assert out.shape == [2, 5, 3] and cf.shape == [2, 8]
    # gradients flow through the projection
    x2 = paddle.to_tensor(A(4, 6), stop_gradient=False)
    h3, _ = cell(x2)
    h3.sum().backward()
    assert x2.grad is not None


def test_inplace_abn_matches_bn_plus_act():
    mean = paddle.to_tensor(np.zeros(3, np.float32))
    var = paddle.to_tensor(np.ones(3, np.float32))
    w = paddle.to_tensor(np.full(3, 2.0, np.float32))
    b = paddle.to_tensor(np.full(3, 0.5, np.float32))
    x = paddle.to_tensor(rs.randn(2, 3, 4, 4).astype("float32"))
    out = F.inplace_abn(x, mean, var, weight=w, bias=b,
                        activation="leaky_relu", alpha=0.1)
    import paddle_tpu.nn.functional as FF
    ref = FF.leaky_relu(FF.batch_norm(x, mean, var, weight=w, bias=b),
                        negative_slope=0.1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)


def test_resnet_unit_composition():
    from paddle_tpu.incubate.nn import ResNetUnit

    unit = ResNetUnit(num_channels_x=4, num_filters=8, filter_size=3,
                      stride=2, has_shortcut=True, num_channels_z=4,
                      stride_z=2)
    x = paddle.to_tensor(A(2, 4, 8, 8))
    out = unit(x, x)
    assert out.shape == [2, 8, 4, 4]
    assert float(out.numpy().min()) >= 0.0  # relu applied
    # plain unit with residual add
    unit2 = ResNetUnit(num_channels_x=4, num_filters=4, filter_size=3,
                       fuse_add=True)
    z = paddle.to_tensor(A(2, 4, 8, 8))
    out2 = unit2(paddle.to_tensor(A(2, 4, 8, 8)), z)
    assert out2.shape == [2, 4, 8, 8]


def test_resnet_unit_validation():
    import pytest as _pytest

    from paddle_tpu.incubate.nn import ResNetUnit

    with _pytest.raises(ValueError):
        ResNetUnit(num_channels_x=4, num_filters=4, filter_size=3,
                   act="leaky_relu")
    unit = ResNetUnit(num_channels_x=4, num_filters=4, filter_size=3,
                      fuse_add=True)
    with _pytest.raises(ValueError):
        unit(paddle.to_tensor(A(1, 4, 4, 4)))  # fuse_add needs z


def test_bilateral_slice_matches_reference_taps():
    """Cross-check against a direct NumPy port of the reference CUDA
    kernel's tap loop (clamped trilinear, centers at i+0.5)."""
    N, C, H, W = 1, 2, 6, 6
    gd, gh, gw, n_out = 4, 3, 3, 2
    has_offset = True
    stride = C + 1
    x = A(N, C, H, W)
    guide = rs.rand(N, H, W).astype("float32")
    grid = A(N, n_out * stride, gd, gh, gw)

    out = F.bilateral_slice(paddle.to_tensor(x), paddle.to_tensor(guide),
                            paddle.to_tensor(grid), has_offset=True).numpy()

    def ref_px(b, oc, y, xw):
        gx = (xw + 0.5) * gw / W
        gy = (y + 0.5) * gh / H
        gz = guide[b, y, xw] * gd
        val = 0.0
        for ic in range(stride):
            cs = 0.0
            for xx in range(int(np.floor(gx - 0.5)), int(np.floor(gx - 0.5)) + 2):
                x_ = min(max(xx, 0), gw - 1)
                wx = max(1.0 - abs(xx + 0.5 - gx), 0.0)
                for yy in range(int(np.floor(gy - 0.5)), int(np.floor(gy - 0.5)) + 2):
                    y_ = min(max(yy, 0), gh - 1)
                    wy = max(1.0 - abs(yy + 0.5 - gy), 0.0)
                    for zz in range(int(np.floor(gz - 0.5)), int(np.floor(gz - 0.5)) + 2):
                        z_ = min(max(zz, 0), gd - 1)
                        wz = max(1.0 - abs(zz + 0.5 - gz), 0.0)
                        cs += grid[b, oc * stride + ic, z_, y_, x_] * wx * wy * wz
            if ic < C:
                val += cs * x[b, ic, y, xw]
            else:
                val += cs
        return val

    for oc in range(n_out):
        for y in range(0, H, 2):
            for xw in range(0, W, 3):
                np.testing.assert_allclose(
                    out[0, oc, y, xw], ref_px(0, oc, y, xw),
                    rtol=2e-4, atol=2e-4)
    check_grad(lambda a, g: F.bilateral_slice(
        a, paddle.to_tensor(guide), g, has_offset=True),
        [x, grid])


def test_bilateral_slice_guide_gradient_and_validation():
    import pytest as _pytest

    x = A(1, 2, 4, 4)
    grid = A(1, 2 * 3, 3, 2, 2)
    guide = paddle.to_tensor(rs.rand(1, 4, 4).astype("float32"),
                             stop_gradient=False)
    out = F.bilateral_slice(paddle.to_tensor(x), guide,
                            paddle.to_tensor(grid), has_offset=True)
    out.sum().backward()
    # guide grads flow through the z coordinate (tent derivative)
    assert guide.grad is not None
    assert float(np.abs(guide.grad.numpy()).sum()) > 0
    with _pytest.raises(ValueError):
        # C=2, has_offset=True -> stride 3; 10 % 3 != 0
        F.bilateral_slice(paddle.to_tensor(x), guide,
                          paddle.to_tensor(A(1, 10, 3, 2, 2)),
                          has_offset=True)


def test_tree_conv_matches_reference_port():
    """Direct NumPy port of the reference tree2col loops as oracle."""
    # tree: 1 -> (2, 3); 2 -> (4)
    edges = np.array([[[1, 2], [1, 3], [2, 4], [0, 0]]], np.int64)
    N, F_, out_size, nf = 5, 3, 2, 2  # node 5 exists but is isolated
    feats = A(1, N, F_)
    w = A(F_, 3, out_size, nf)
    out = F.tree_conv(paddle.to_tensor(feats), paddle.to_tensor(edges),
                      paddle.to_tensor(w), max_depth=2).numpy()
    assert out.shape == (1, N, out_size * nf)

    # oracle: construct_patch per root at max_depth=2
    tr = {1: [2, 3], 2: [4]}
    md = 2.0

    def patch_of(root):
        patch = [(root, 1, 1, 0)]
        if root in tr:
            ch = tr[root]
            for i, v in enumerate(ch):
                patch.append((v, i + 1, len(ch), 1))
        return patch

    for root in (1, 2, 3, 4):
        acc = np.zeros((F_, 3), np.float32)
        for (v, idx, pclen, depth) in patch_of(root):
            eta_t = (md - depth) / md
            tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            acc[:, 0] += eta_l * feats[0, v - 1]
            acc[:, 1] += eta_r * feats[0, v - 1]
            acc[:, 2] += eta_t * feats[0, v - 1]
        ref = np.einsum("fk,fkon->on", acc, w).reshape(-1)
        np.testing.assert_allclose(out[0, root - 1], ref, rtol=1e-5)

    check_grad(
        lambda nv, ww: F.tree_conv(nv, paddle.to_tensor(edges), ww,
                                   max_depth=2),
        [feats, w])


def test_tree_conv_padding_rows_and_interleaved_zeros():
    # (u,0) padding rows must be skipped, not wrap to the last column
    edges = np.array([[[1, 2], [3, 0], [1, 3], [0, 0]]], np.int64)
    feats = A(1, 4, 2)
    w = A(2, 3, 1, 1)
    out = F.tree_conv(paddle.to_tensor(feats), paddle.to_tensor(edges),
                      paddle.to_tensor(w), max_depth=2).numpy()
    # edge (1,3) AFTER the (3,0) padding row still counts
    edges2 = np.array([[[1, 2], [1, 3], [0, 0], [0, 0]]], np.int64)
    out2 = F.tree_conv(paddle.to_tensor(feats), paddle.to_tensor(edges2),
                       paddle.to_tensor(w), max_depth=2).numpy()
    np.testing.assert_allclose(out[0, 0], out2[0, 0], rtol=1e-6)


def test_var_conv_2d_per_sample_shapes_and_grads():
    import paddle_tpu.static.nn as snn
    from paddle_tpu.framework.lod import LoDTensor

    imgs = [rs.randn(2, 5, 7).astype("float32"),
            rs.randn(2, 3, 4).astype("float32")]
    flat = np.concatenate([im.reshape(-1) for im in imgs])
    xl = LoDTensor(flat.reshape(-1, 1), [[imgs[0].size, imgs[1].size]])
    w = paddle.to_tensor((rs.randn(3, 2 * 3 * 3) * 0.2).astype("float32"),
                         stop_gradient=False)
    outs = snn.var_conv_2d(xl, [5, 3], [7, 4], input_channel=2,
                           output_channel=3, filter_size=3, stride=2, w=w)
    # SAME-style: (H-1)//s+1
    assert tuple(outs[0].shape) == (3, 3, 4)
    assert tuple(outs[1].shape) == (3, 2, 2)
    # reference-faithful oracle: centered im2col (pad_low = k//2, windows
    # at y*s — var_conv_2d_op.cc), NOT the same call as the implementation
    wt_np = w.numpy().reshape(3, 2, 3, 3)

    def ref_conv(im, sh=2, sw=2, kh=3, kw=3):
        C, H, W = im.shape
        oh, ow = (H - 1) // sh + 1, (W - 1) // sw + 1
        out = np.zeros((3, oh, ow), np.float32)
        for oc in range(3):
            for y in range(oh):
                for x_ in range(ow):
                    acc = 0.0
                    for c in range(C):
                        for ky in range(kh):
                            for kx in range(kw):
                                iy = y * sh + ky - kh // 2
                                ix = x_ * sw + kx - kw // 2
                                if 0 <= iy < H and 0 <= ix < W:
                                    acc += im[c, iy, ix] * wt_np[oc, c, ky, kx]
                    out[oc, y, x_] = acc
        return out

    for im, out in zip(imgs, outs):
        np.testing.assert_allclose(out.numpy(), ref_conv(im), rtol=1e-4,
                                   atol=1e-5)
    # shared filter receives gradients from all samples
    (outs[0].sum() + outs[1].sum()).backward()
    assert w.grad is not None and np.isfinite(w.grad.numpy()).all()
    # mismatched row/col raises
    import pytest as _pytest
    with _pytest.raises(ValueError):
        snn.var_conv_2d(xl, [5], [7, 4], 2, 3, 3)


def test_rank_attention_matches_reference_port():
    """Oracle: direct python port of expand_input/expand_param +
    per-instance matmul (rank_attention.cu.h)."""
    N, d, mr, out = 5, 4, 3, 2
    x = A(N, d)
    param = A(mr * mr * d, out)
    ro = np.zeros((N, 1 + 2 * mr), np.int32)
    rs2 = np.random.RandomState(3)
    for i in range(N):
        ro[i, 0] = rs2.randint(0, mr + 1)  # 0 => invalid instance
        for k in range(mr):
            ro[i, 2 * k + 1] = rs2.randint(0, mr + 1)
            ro[i, 2 * k + 2] = rs2.randint(0, N)
    got = F.rank_attention(paddle.to_tensor(x), paddle.to_tensor(ro),
                           paddle.to_tensor(param), max_rank=mr).numpy()

    ref = np.zeros((N, out), np.float32)
    for i in range(N):
        lower = ro[i, 0] - 1
        for k in range(mr):
            faster = ro[i, 2 * k + 1] - 1
            if lower < 0 or faster < 0:
                continue
            idx = ro[i, 2 * k + 2]
            start = lower * mr + faster
            W = param[start * d:(start + 1) * d]   # [d, out]
            ref[i] += x[idx] @ W
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    check_grad(
        lambda xx, pp: F.rank_attention(xx, paddle.to_tensor(ro), pp,
                                        max_rank=mr),
        [x, param])

"""Sequence-parallel ring attention tests (net-new capability; SURVEY.md §5
records its absence in the reference)."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.ring_attention import ring_attention_val
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)


@pytest.fixture(autouse=True)
def clean_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def qkv(seq=32, batch=2, heads=4, dim=8, seed=0):
    import jax.numpy as jnp

    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(batch, seq, heads, dim).astype("float32"))
    return mk(), mk(), mk()


class TestRingAttentionVal:
    def test_matches_full_causal(self):
        import jax

        q, k, v = qkv()
        ref = ring_attention_val(q, k, v)  # no mesh → plain path
        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 2, "sep": 4}))
        out = jax.jit(lambda a, b, c: ring_attention_val(a, b, c))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_noncausal(self):
        import jax
        import jax.numpy as jnp

        q, k, v = qkv()
        ref = ring_attention_val(q, k, v, causal=False)
        mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 8}))
        out = jax.jit(
            lambda a, b, c: ring_attention_val(a, b, c, causal=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match(self):
        import jax

        q, k, v = qkv()
        loss = lambda a, b, c: ring_attention_val(a, b, c).sum()
        ref_g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 4, "model": 2}))
        out_g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        for r, o in zip(ref_g, out_g):
            np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                                       rtol=2e-3, atol=2e-4)


class TestGPTSequenceParallel:
    def test_gpt_sp_training(self):
        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 2, "sep": 2, "model": 2}))
        cfg = gpt_presets("gpt-test", use_ring_attention=True,
                          sequence_parallel=True)
        m = GPTForCausalLM(cfg, seed=5)
        crit = GPTPretrainingCriterion()
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda lg, lb: crit(lg, lb), o)
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 256, (4, 32)), dtype="int64")
        labels = paddle.to_tensor(rs.randint(0, 256, (4, 32)), dtype="int64")
        losses = [float(step(inputs=(ids,), labels=(labels,)))
                  for _ in range(3)]
        assert losses[-1] < losses[0]

    def test_gpt_sp_matches_single(self):
        cfg = gpt_presets("gpt-test")
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 256, (4, 32)), dtype="int64")
        labels = paddle.to_tensor(rs.randint(0, 256, (4, 32)), dtype="int64")
        crit = GPTPretrainingCriterion()

        single = float(crit(GPTForCausalLM(cfg, seed=9)(ids), labels))
        mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 8}))
        cfg_sp = gpt_presets("gpt-test", use_ring_attention=True)
        m = GPTForCausalLM(cfg_sp, seed=9)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda lg, lb: crit(lg, lb), o)
        sp_loss = float(step(inputs=(ids,), labels=(labels,)))
        np.testing.assert_allclose(single, sp_loss, rtol=2e-3)


def test_flash_ring_forward_matches_einsum_ring_interpret():
    """The flash-chunk ring forward (TPU path, exercised here in pallas
    interpret mode) must match the einsum ring exactly (VERDICT r1 item 3:
    flash extended to the ring inner block)."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import PartitionSpec as P

    import importlib
    ra = importlib.import_module("paddle_tpu.distributed.ring_attention")
    from paddle_tpu.distributed import mesh as mesh_mod

    prev = mesh_mod.get_mesh()
    mesh = mesh_mod.build_mesh({"sep": 4}, devices=jax.devices()[:4])
    mesh_mod.set_mesh(mesh)
    try:
        rs = np.random.RandomState(0)
        b, s, h, d = 2, 64, 2, 16  # s_loc = 16 per device, blk=16
        q = jnp.asarray(rs.randn(b, s, h, d).astype("f4"))
        k = jnp.asarray(rs.randn(b, s, h, d).astype("f4"))
        v = jnp.asarray(rs.randn(b, s, h, d).astype("f4"))
        spec = P(None, "sep", None, None)

        def run(fn):
            body = mesh_mod.compat_shard_map(
                partial(fn, axis="sep", sp=4, causal=True), mesh,
                (spec, spec, spec), spec)
            return np.asarray(body(q, k, v))

        flash = run(lambda a, b_, c, axis, sp, causal: ra._ring_flash_forward(
            a, b_, c, axis, sp, causal))
        einsum = run(lambda a, b_, c, axis, sp, causal: ra._ring_einsum(
            a, b_, c, axis, sp, causal))
        np.testing.assert_allclose(flash, einsum, rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.set_mesh(prev)
